"""Flight recorder: bounded ring of recent obs records + repro bundles.

A diverged IPM cohort, a rescued-then-still-stuck cell, a device
failure, or an uncertified leaf in a 12k-region build used to leave
nothing behind but a counter -- the failure could not be reproduced or
triaged after the run.  The FlightRecorder turns each such anomaly into
a *versioned, compressed repro bundle* on disk: the exact solver inputs
(canonical QP matrices, query points, warm-start iterates, schedule and
precision flags, cell geometry) plus the last few hundred obs records
leading up to the event.  ``scripts/replay_solve.py`` re-runs a bundle
standalone -- no checkpoint, no problem registry, no build state -- and
must reproduce the original converged/diverged mask bit-for-bit,
turning any field failure into a unit-test-sized repro.

Wiring: ``cfg.obs_recorder`` makes the frontier engine build one and
point ``oracle.recorder`` at it; the obs sink's ``tap`` feeds the ring.
Capture sites (each dumps at most ``max_bundles`` bundles per run):

- ``oracle/oracle.py``: point/pair cells that end *feasible but
  unconverged* after the full pipeline (two-phase cohort + rescue) --
  the diverged-straggler class -- and simplex rows that return -inf
  (no usable bound: the joint solve stalled);
- ``partition/frontier.py``: device-failure batches (after the CPU
  fallback resolves them, so the bundle carries the observed masks)
  and depth-capped *uncertified leaves* (cell geometry + vertex data
  via ``partition.certify.cell_snapshot``);
- ``oracle/ipm.py`` contributes ``solve_mask``, the standalone replay
  kernel the bundle's ``--kernel-only`` diagnostic path uses.

Bundle format (``repro_<trigger>_<seq>.npz``, np.savez_compressed):
one ``__meta__`` JSON string (bundle_version, trigger, kind, oracle
schedule/precision, anomaly indices, the obs-record ring) plus flat
numpy arrays -- ``can_*`` canonical matrices, ``thetas``/``delta_idx``
(or ``bary_Ms`` / ``cell_verts``), optional ``warm_*`` donor iterates,
and the observed ``obs_conv``/``obs_feas``/``obs_V`` masks replay
compares against.  Format documented in docs/observability.md.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import Optional

import numpy as np

from explicit_hybrid_mpc_tpu.obs.sink import json_default

#: Bumped on any incompatible change to the bundle layout or the meta
#: fields replay_solve.py depends on.
BUNDLE_VERSION = 1

#: Canonical-matrix fields stored in every solver bundle (mirrors
#: problems.base.CanonicalMPQP minus nothing: replay rebuilds the exact
#: DeviceProblem from these).
CANONICAL_FIELDS = ("H", "f", "F", "G", "w", "S", "Y", "pvec", "cconst",
                    "u_map", "u_theta", "u_const", "deltas")


def canonical_arrays(can) -> dict:
    """CanonicalMPQP -> the bundle's ``can_*`` array dict."""
    return {f"can_{k}": np.asarray(getattr(can, k))
            for k in CANONICAL_FIELDS}


def oracle_meta(oracle) -> dict:
    """The solver-configuration fields replay needs to reconstruct an
    Oracle with bit-identical semantics (same contract as
    Oracle.cpu_twin, which the device-failure fallback already relies
    on for bit-compatibility)."""
    return {
        # Class name rides for triage: bundles from subclassed kernels
        # (PrunedOracle, SOCOracle) replay through the PLAIN Oracle --
        # decision-identical by those classes' own exactness contracts,
        # but not necessarily bitwise, and the report should say why.
        "oracle_class": type(oracle).__name__,
        "n_iter": oracle.n_iter + oracle.n_f32,
        "precision": oracle.precision,
        "n_f32": (oracle.n_f32 if oracle.precision == "mixed" else None),
        "point_schedule": (list(oracle.point_schedule)
                           if oracle.point_schedule else None),
        "rescue_iter": oracle.rescue_iter,
        "two_phase": oracle.two_phase,
        "phase1_iters": oracle.phase1_iters,
        "warm_start": oracle.warm_start,
        "stage2_phase1_first": bool(oracle.stage2_phase1_first),
        # Resolved per-class schedules, so the --kernel-only replay
        # path can drive ipm.solve_mask without re-deriving the split.
        "point_n_f32": oracle.point_n_f32,
        "point_n_iter": oracle.point_n_iter,
        "simplex_n_f32": oracle.n_f32,
        "simplex_n_iter": oracle.n_iter,
        # Resolved IPM dispatch tier (oracle/pallas_ipm.py): replay
        # rebuilds the oracle on the same tier; pre-tier bundles
        # default to the XLA reference path.
        "ipm_kernel": getattr(oracle, "ipm_kernel", "xla"),
    }


class FlightRecorder:
    """Ring buffer of recent obs records + bundle writer (see module
    docstring).  Thread-safe: the ring is fed from the sink's tap (any
    emitting thread) and dumps may race between the build loop and a
    serving thread."""

    def __init__(self, out_dir: str, capacity: int = 256,
                 max_bundles: int = 16, ring_in_bundle: int = 64,
                 obs=None):
        """out_dir: bundle directory (created lazily on first dump).
        capacity: obs records kept in the ring.  max_bundles: hard cap
        on bundles written per recorder lifetime -- an anomaly storm
        must not fill the disk; overflow is counted, not written.
        obs: optional obs.Obs handle; each dump emits a
        ``recorder.bundle`` event and bumps the ``recorder.bundles``
        counter through it."""
        self.out_dir = out_dir
        self.max_bundles = max_bundles
        self.ring_in_bundle = ring_in_bundle
        self.obs = obs
        self.ring: collections.deque[dict] = collections.deque(
            maxlen=capacity)
        self.bundles: list[str] = []
        self.n_dropped = 0
        self._lock = threading.Lock()
        self._seq = 0

    # -- ring (sink tap) ---------------------------------------------------

    def note(self, rec: dict) -> None:
        """Sink-tap callback: remember one obs record.  Locked: dump()
        snapshots the ring from another thread, and iterating a deque
        while an appender mutates it raises -- which would silently
        lose the one repro bundle the anomaly produced."""
        with self._lock:
            self.ring.append(rec)

    # -- bundles -----------------------------------------------------------

    def dump(self, trigger: str, arrays: dict, meta: dict) -> Optional[str]:
        """Write one repro bundle; returns its path, or None when the
        max_bundles cap already hit (the overflow is counted so the
        run's stats still say how many anomalies occurred)."""
        with self._lock:
            if len(self.bundles) >= self.max_bundles:
                self.n_dropped += 1
                return None
            self._seq += 1
            seq = self._seq
            ring = list(self.ring)[-self.ring_in_bundle:]
        os.makedirs(self.out_dir, exist_ok=True)
        path = os.path.join(self.out_dir,
                            f"repro_{trigger}_{seq:03d}.npz")
        full_meta = {"bundle_version": BUNDLE_VERSION,
                     "trigger": trigger,
                     "created_unix": time.time(),
                     **meta,
                     "ring": ring}
        # Meta rides as a 0-d unicode array: np.load needs no pickle.
        np.savez_compressed(
            path,
            __meta__=np.array(json.dumps(full_meta, default=json_default)),
            **{k: np.asarray(v) for k, v in arrays.items()})
        with self._lock:
            self.bundles.append(path)
        o = self.obs
        if o is not None and o.enabled:
            o.counter("recorder.bundles").inc()
            # bundle_kind, not kind: `kind` is the record envelope's
            # own discriminator and must not be shadowed by a field.
            o.event("recorder.bundle", path=path, trigger=trigger,
                    bundle_kind=meta.get("kind"))
        return path


def load_bundle(path: str) -> tuple[dict, dict]:
    """(meta dict, arrays dict) from a bundle written by
    FlightRecorder.dump.  Shared by scripts/replay_solve.py and the
    tests; raises on a bundle_version this reader does not know."""
    with np.load(path, allow_pickle=False) as z:
        meta = json.loads(str(z["__meta__"][()]))
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
    ver = meta.get("bundle_version")
    if ver != BUNDLE_VERSION:
        raise ValueError(f"bundle {path} has version {ver!r}; this "
                         f"reader understands {BUNDLE_VERSION}")
    return meta, arrays


def rebuild_canonical(arrays: dict):
    """Reconstruct the CanonicalMPQP a bundle's ``can_*`` arrays came
    from (the standalone half of replay: no problem registry, no
    constructor args -- the matrices ARE the problem)."""
    from explicit_hybrid_mpc_tpu.problems.base import CanonicalMPQP

    return CanonicalMPQP(**{k: np.asarray(arrays[f"can_{k}"])
                            for k in CANONICAL_FIELDS})


class BundleProblem:
    """Minimal problem shim wrapping a rebuilt CanonicalMPQP -- exactly
    the surface Oracle.__init__ reads (canonical + the optional
    stage2_hint), so replay never needs the original problem class."""

    def __init__(self, canonical, stage2_hint: str | None = None):
        self.canonical = canonical
        if stage2_hint is not None:
            self.stage2_hint = stage2_hint
        self.n_theta = canonical.n_theta
        self.n_u = canonical.n_u
