"""Host-level observability: competing-CPU-load sampling.

ContentionMonitor lived inside bench.py through PR 1 (r4 weak #1: a
competing campaign on the one-core host halved the driver-visible
benchmark and nothing recorded it).  It is host observability, so with
the obs subsystem it moved here: its readings now fold into the shared
gauge registry (``host.competing_cpu_frac_mean`` / ``_max`` /
``host.contended``) next to the build/oracle/serving metrics, and the
/proc readers are injectable so the guest-jiffies accounting is
testable without a live procfs.  bench.py and parallel.mesh re-export
the class for existing callers.
"""

from __future__ import annotations

import os
import threading
import time

import numpy as np


class ContentionMonitor:
    """Background sampler of how much CPU OTHER processes burned while
    a measurement ran.

    Samples /proc/stat total busy jiffies against /proc/self/stat own
    (+reaped children) jiffies; the difference over elapsed capacity is
    the competing share.  summary() feeds the load fields of the bench
    JSON and, when built with a MetricsRegistry, sets the host.* gauges;
    a mean share above `threshold` marks the capture CONTENDED in its
    own metric line (bench.py main)."""

    def __init__(self, interval_s: float = 2.0, threshold: float = 0.05,
                 metrics=None, stat_path: str = "/proc/stat",
                 self_stat_path: str = "/proc/self/stat", reader=None):
        """metrics: optional obs.MetricsRegistry the summary folds its
        gauges into.  stat_path/self_stat_path: procfs locations,
        overridable with fixture files (tests).  reader: full override
        of the jiffies sampler -- a callable returning (total busy
        jiffies, own jiffies) or None; tests drive the sampling loop
        with scripted sequences through it."""
        self.interval_s = interval_s
        self.threshold = threshold
        self.metrics = metrics
        self._stat_path = stat_path
        self._self_stat_path = self_stat_path
        self._reader = reader if reader is not None else self._jiffies
        self._stop = threading.Event()
        self._samples: list[float] = []
        self._thread: threading.Thread | None = None
        self._load_start = None

    @staticmethod
    def _busy_jiffies(vals: list[int]) -> int:
        """Total busy jiffies from the /proc/stat cpu-line fields
        (user nice system idle iowait irq softirq steal guest
        guest_nice).  idle + iowait are not busy; guest + guest_nice
        are ALREADY counted inside user/nice (kernel accounting), so
        they must come off too or VM hosts running guests double-count
        and overstate the competing-CPU share (ADVICE r5, fixed PR 1)."""
        busy = sum(vals) - vals[3] - (vals[4] if len(vals) > 4 else 0)
        busy -= (vals[8] if len(vals) > 8 else 0)   # guest
        busy -= (vals[9] if len(vals) > 9 else 0)   # guest_nice
        return busy

    def _jiffies(self) -> tuple[int, int] | None:
        try:
            with open(self._stat_path) as f:
                vals = [int(x) for x in f.readline().split()[1:]]
            busy = ContentionMonitor._busy_jiffies(vals)
            with open(self._self_stat_path) as f:
                st = f.read().rsplit(")", 1)[1].split()
            own = sum(int(x) for x in st[11:15])  # utime stime cu cs
            return busy, own
        except (OSError, IndexError, ValueError):
            return None  # non-procfs host: monitor degrades to loadavg

    @staticmethod
    def _competing_frac(prev: tuple[int, int], cur: tuple[int, int],
                        capacity_jiffies: float) -> float:
        """Competing-CPU share over one interval: (total busy delta -
        own delta) / capacity, clamped to [0, 1]."""
        other = (cur[0] - prev[0]) - (cur[1] - prev[1])
        return min(1.0, max(0.0, other / capacity_jiffies))

    def _run(self) -> None:
        hz = os.sysconf("SC_CLK_TCK")
        ncpu = os.cpu_count() or 1
        prev, prev_t = self._reader(), time.time()
        while not self._stop.wait(self.interval_s):
            cur, now = self._reader(), time.time()
            if prev is not None and cur is not None:
                cap = (now - prev_t) * hz * ncpu
                if cap > 0:
                    self._samples.append(
                        self._competing_frac(prev, cur, cap))
            prev, prev_t = cur, now

    def start(self) -> "ContentionMonitor":
        try:
            self._load_start = os.getloadavg()
        except OSError:
            pass
        if self._reader() is not None:
            self._thread = threading.Thread(target=self._run, daemon=True)
            self._thread.start()
        return self

    def summary(self) -> dict:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.interval_s)
        out = {"cpu_count": os.cpu_count()}
        try:
            out["loadavg_end"] = [round(x, 2) for x in os.getloadavg()]
        except OSError:
            pass
        if self._load_start is not None:
            out["loadavg_start"] = [round(x, 2) for x in self._load_start]
        if self._samples:
            mean = float(np.mean(self._samples))
            out.update(
                competing_cpu_frac_mean=round(mean, 3),
                competing_cpu_frac_max=round(max(self._samples), 3),
                contended=mean > self.threshold)
        if self.metrics is not None:
            m = self.metrics
            m.gauge("host.cpu_count").set(os.cpu_count() or 1)
            if self._samples:
                m.gauge("host.competing_cpu_frac_mean").set(
                    out["competing_cpu_frac_mean"])
                m.gauge("host.competing_cpu_frac_max").set(
                    out["competing_cpu_frac_max"])
                m.gauge("host.contended").set(float(out["contended"]))
        return out
