"""Typed metrics registry: counters, gauges, log-bucket histograms.

Prometheus-shaped but in-process and dependency-free: the frontier
build, the oracle stack, and sharded serving all record into one
registry; `snapshot()` returns a plain JSON-ready dict and `emit()`
writes it to the JSONL sink as a single ``kind="metrics"`` record.
Histograms use FIXED log-spaced bucket boundaries -- never derived
from the data -- so two snapshots (or two runs, or a run and the last
BENCH_*.json) are always bucket-compatible and scripts/obs_report.py
can diff them without re-binning.
"""

from __future__ import annotations

import bisect
import math
import threading
from typing import Optional, Sequence

# 5 buckets per decade, 100 ns .. 100 s: spans one IPM iteration
# through a whole checkpointed frontier step.  Fixed by construction
# (see module docstring).
DEFAULT_LATENCY_BOUNDS: tuple[float, ...] = tuple(
    10.0 ** (e / 5.0) for e in range(-35, 11))


class Counter:
    """Monotonic counter.  inc() is guarded by the registry-wide GIL
    contract: single bytecode-level += per call, incremented from one
    producer thread per name in practice."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Last-write-wins scalar (frontier size, device_frac, shard
    imbalance, competing-CPU share)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class Histogram:
    """Fixed-bound histogram with len(bounds)+1 cells:
    counts[i] counts observations v with bounds[i-1] < v <= bounds[i]
    (counts[0]: v <= bounds[0]; counts[-1]: v > bounds[-1]).

    observe(value, n=k) records k observations of the same value in one
    call -- the batched-oracle pattern: one device program solves n QPs
    in wall seconds w, so per-QP latency w/n is observed with weight n
    and the histogram's quantiles stay per-solve figures."""

    __slots__ = ("bounds", "counts", "count", "sum", "min", "max",
                 "_lock")

    def __init__(self, bounds: Optional[Sequence[float]] = None):
        self.bounds = tuple(float(b) for b in
                            (bounds if bounds is not None
                             else DEFAULT_LATENCY_BOUNDS))
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    def observe(self, value: float, n: int = 1) -> None:
        v = float(value)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.counts[i] += n
            self.count += n
            self.sum += v * n
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v

    def snapshot(self) -> dict:
        with self._lock:
            return {"bounds": list(self.bounds),
                    "counts": list(self.counts),
                    "count": self.count, "sum": self.sum,
                    "min": (self.min if self.count else None),
                    "max": (self.max if self.count else None)}


def quantile(hist: dict, q: float) -> Optional[float]:
    """q-quantile estimate from a Histogram.snapshot() dict.

    Log-linear interpolation inside the landing bucket (the bounds are
    log-spaced, so this is linear in the exponent); the recorded exact
    min/max clamp the open-ended tail buckets.  Works on dicts so
    scripts/obs_report.py can compute quantiles from a parsed JSONL
    snapshot without reconstructing Histogram objects."""
    count = hist["count"]
    if not count:
        return None
    bounds, counts = hist["bounds"], hist["counts"]
    target = q * count
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        lo_cum = cum
        cum += c
        if cum >= target:
            lo = bounds[i - 1] if i > 0 else hist["min"]
            hi = bounds[i] if i < len(bounds) else hist["max"]
            lo = max(lo, hist["min"])
            hi = max(lo, min(hi, hist["max"]))
            frac = (target - lo_cum) / c
            if lo <= 0.0 or hi <= 0.0:
                return float(lo + frac * (hi - lo))
            return float(lo * (hi / lo) ** frac)
    return float(hist["max"])


def histogram_row(h: dict, quantiles: Sequence[float] = (0.5, 0.99)
                  ) -> dict:
    """Condense one Histogram.snapshot() dict to count/mean/min/max +
    quantile fields (p50, p99, ...).  The ONE reduction behind both
    MetricsRegistry.summary() (the bench `metrics` block) and
    scripts/obs_report.py's rendered rows -- two copies would let the
    bench block and the report rows drift apart and diff_bench compare
    mismatched semantics."""
    row = {"count": h["count"],
           "mean": (h["sum"] / h["count"]) if h["count"] else None,
           "min": h["min"], "max": h["max"]}
    for q in quantiles:
        row[f"p{round(q * 100):d}"] = quantile(h, q)
    return row


class MetricsRegistry:
    """Name -> metric map with get-or-create accessors.

    Creation is lock-guarded; the returned metric objects are cached by
    the instrumentation sites, so the hot path touches only the metric
    itself."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._hists: dict[str, Histogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            return c

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            return g

    def histogram(self, name: str,
                  bounds: Optional[Sequence[float]] = None) -> Histogram:
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(bounds)
            return h

    def snapshot(self) -> dict:
        """Full plain-dict state: counters/gauges by name, histograms
        as Histogram.snapshot() dicts.  JSON-ready."""
        with self._lock:
            counters = dict(sorted(self._counters.items()))
            gauges = dict(sorted(self._gauges.items()))
            hists = dict(sorted(self._hists.items()))
        return {"counters": {k: c.value for k, c in counters.items()},
                "gauges": {k: g.value for k, g in gauges.items()},
                "histograms": {k: h.snapshot() for k, h in hists.items()}}

    def summary(self, quantiles: Sequence[float] = (0.5, 0.99)) -> dict:
        """Condensed snapshot for artifact JSON (the bench.py `metrics`
        block): counters + gauges verbatim, histograms reduced to
        count/mean/min/max plus the requested quantiles."""
        snap = self.snapshot()
        return {"counters": snap["counters"], "gauges": snap["gauges"],
                "histograms": {k: histogram_row(h, quantiles)
                               for k, h in snap["histograms"].items()}}

    def emit(self, sink) -> dict:
        """One kind="metrics" record holding the full snapshot.
        Returns the emitted record -- the ONE producer of the
        'metrics'/'snapshot' record shape; consumers that also feed a
        HealthMonitor (frontier step, long_build checkpoints) reuse
        the dict instead of re-inlining the shape."""
        return sink.emit("metrics", "snapshot", **self.snapshot())
