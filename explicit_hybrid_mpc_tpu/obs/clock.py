"""Stream identity + clock anchoring for fleet telemetry.

Every obs stream's ``t`` column is MONOTONIC seconds since its sink's
epoch (``time.perf_counter`` based -- immune to NTP steps, meaningless
across processes).  One process's ``t=3.2`` and another's ``t=3.2``
can be minutes apart in real time, so N per-process streams cannot be
merged on ``t`` alone.  The identity record every schema-v2 sink
stamps (obs/sink.py) therefore carries a **clock anchor**: the wall
clock (``time.time``) and the stream's own ``t``, captured at the same
instant.  ``to_wall(identity, t)`` maps any record's stream time onto
the shared wall axis, which is what ``obs/fleet.py`` sorts merged
fleet views by.

Caveat the reader must keep in mind: wall clocks across HOSTS agree
only as well as NTP does (typically ms, occasionally worse).  Within
one host -- the supervised-restart chain, co-host replicas -- the
anchor is exact to the two back-to-back clock reads.

``run_id`` identifies one logical run ACROSS processes: a fleet
launcher (scripts/supervise_build.py, a pod driver) exports
``EHM_RUN_ID`` so every child stamps the same id; a standalone process
mints its own.  The id also lands in bench rows (bench.py) so a
BENCH_HISTORY entry is joinable back to its obs streams.
"""

from __future__ import annotations

import os
import socket
import sys
import time
import uuid

#: Env var a fleet launcher exports so all its processes share one
#: run id (scripts/supervise_build.py sets it for the restart chain).
RUN_ID_ENV = "EHM_RUN_ID"

_run_id: str | None = None


def run_id() -> str:
    """This process's run id: ``EHM_RUN_ID`` when a launcher set it,
    else a fresh 12-hex id minted once per process."""
    global _run_id
    if _run_id is None:
        _run_id = os.environ.get(RUN_ID_ENV) or uuid.uuid4().hex[:12]
    return _run_id


def new_run_id() -> str:
    """A fresh id for a launcher to export as ``EHM_RUN_ID``."""
    return uuid.uuid4().hex[:12]


def _safe_process_coords() -> dict:
    """process_index / process_count WITHOUT initializing any backend.

    A sink may be constructed before jax ever touches a device (or in
    a process that never imports jax at all); calling
    ``jax.process_index()`` here would trigger backend discovery -- on
    a host with a dead TPU tunnel that can hang stream creation.  So
    this reads only state that already exists: the jax.distributed
    global state when jax is ALREADY imported and initialized, else
    the launcher-provided env vars, else the single-process default.
    Drivers that are past backend init use the full
    ``parallel.distributed.process_coords()`` instead.
    """
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            from jax._src import distributed as _jdist

            st = _jdist.global_state
            if getattr(st, "process_id", None) is not None:
                return {"process_index": int(st.process_id),
                        "process_count": int(st.num_processes or 1)}
        except Exception:  # tpulint: disable=silent-except -- best-effort identity probe
            pass
    try:
        return {"process_index": int(os.environ.get("JAX_PROCESS_ID", 0)),
                "process_count": int(os.environ.get("JAX_NUM_PROCESSES",
                                                    1))}
    except ValueError:
        return {"process_index": 0, "process_count": 1}


def identity() -> dict:
    """The stream-identity fields the v2 sink stamps into its leading
    ``meta``/``stream`` record (docs/observability.md "Fleet
    telemetry").  The emitting sink adds its own ``t``; the
    (``t``, ``wall_time``) pair is the stream's clock anchor."""
    coords = _safe_process_coords()
    return {"run_id": run_id(),
            "host": socket.gethostname(),
            "pid": os.getpid(),
            "wall_time": time.time(),
            **coords}


def wall_offset(identity_rec: dict) -> float | None:
    """Stream-t -> wall-clock offset from an identity record, i.e.
    ``wall = offset + t`` for every record of that stream.  None when
    the record carries no anchor (schema-v1 legacy streams)."""
    w = identity_rec.get("wall_time") if identity_rec else None
    t = identity_rec.get("t") if identity_rec else None
    if isinstance(w, (int, float)) and isinstance(t, (int, float)):
        return float(w) - float(t)
    return None


def to_wall(identity_rec: dict, t: float) -> float | None:
    """Absolute wall time of a record with stream time `t`, or None
    for anchor-less legacy streams."""
    off = wall_offset(identity_rec)
    return None if off is None else off + float(t)
