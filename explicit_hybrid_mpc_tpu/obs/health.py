"""Streaming build-health watchdog: rolling SLO rules over obs records.

A multi-hour (or multi-day, at the million-leaf north star) build can
go *sick* long before it goes down: regions/sec stalls while the
frontier churns, a divergence storm sends every cohort cell into phase
2, tree warm-starts stop being accepted, one serving shard carries 10x
the load, or a competing campaign steals the host's only core.  Each
of those is visible in the obs stream (PR 2/3 signals) but nothing
*watched* it -- a sick build burned its TPU allocation to the end.

``HealthMonitor`` evaluates a rule set over the stream incrementally:
feed it records (``feed``), poll it for wall-clock stall
(``check_stall``), and it returns/emits structured ``health.*`` events
with a severity; ``worst`` aggregates into the exit-status contract
drivers act on (``scripts/obs_watch.py`` tails a live file;
``scripts/long_build.py`` feeds its own checkpoint snapshots and
checkpoint-and-halts on critical).

Rule schema (all values floats; 0 disables a threshold rule):

=========================  =============================================
``stall_s``                no new record for this many wall seconds ->
                           ``health.stall`` (critical)
``window_steps``           build.step window for the rolling rates
``min_regions_per_s``      rolling throughput floor ->
                           ``health.throughput_low`` (warn)
``max_rescue_frac``        rescue / point solve delta between metric
                           snapshots -> ``health.rescue_storm`` (critical)
``max_phase2_survivor_frac``  two-phase survivors gauge (divergence
                           storm proxy) -> ``health.divergence_storm``
                           (critical)
``min_warmstart_accept``   accept-rate collapse (after
                           ``min_solves_for_rates`` point solves) ->
                           ``health.warmstart_collapse`` (warn)
``max_shard_imbalance``    serve.shard_imbalance gauge ->
                           ``health.shard_imbalance`` (warn)
``max_competing_cpu_frac`` host contention gauge ->
                           ``health.host_contended`` (warn)
``max_device_failures``    device_failure records tolerated before
                           ``health.device_failures`` (warn)
``serve_p99_us``           serving p99 latency ceiling in microseconds
                           (per-controller serve.ctl.<name>.p99_us
                           gauges, serve/scheduler.py; legacy bare
                           serve.p99_us also evaluated) ->
                           ``health.serve_p99_us`` (warn); 0 = off
                           (the budget is deployment-specific)
``fallback_frac``          rolling degraded-mode fraction
                           (serve.ctl.<name>.fallback_frac gauges) ->
                           ``health.fallback_frac`` (warn) -- the
                           serving SLO from docs/serving.md
``max_queue_frac``         queue-dominated tail: queue phase's share
                           of request wall over the rolling window
                           (serve.ctl.<name>.queue_frac gauges,
                           obs/reqtrace.py; volume-gated like p99) ->
                           ``health.serve_queue`` (warn) -- the
                           "scale replicas, not kernels" signal; 0 =
                           off (the acceptable share is
                           deployment-specific)
``max_subopt``             measured serving suboptimality ceiling
                           (serve.ctl.<name>.subopt_p99 gauges from
                           the demand hub's online oracle re-solves,
                           obs/demand.py; volume-gated on the
                           ``.subopt_samples`` counter vs
                           ``min_subopt_samples``, its OWN gate -- the
                           sample budget is a tiny fraction of request
                           volume) -> ``health.subopt`` (warn); 0 =
                           off.  Set it to the build's eps budget:
                           the paper's certificate as a measured SLO.
                           The hub also emits its own in-stream
                           ``health.subopt`` events, which any monitor
                           ADOPTS; this rule is the external-tailer
                           (obs_watch) complement reading the gauge
``min_subopt_samples``     sample-volume floor for ``max_subopt``
                           (three lucky re-solves must not alarm a
                           fresh deploy)
``min_rebuild_reuse``      warm-rebuild reuse_frac floor
                           (rebuild.reuse_frac gauge, volume-gated on
                           ``min_rebuild_leaves`` prior leaves -- its
                           OWN gate, in leaves, not the solve-count
                           knob) -> ``health.rebuild_reuse_collapse``
                           (warn): a near-zero reuse on a large prior
                           tree signals a silently-drifted problem
                           hash -- the rebuild is paying cold-build
                           cost while reporting warm; 0 = off
``min_rebuild_leaves``     prior-leaf volume floor for the rule above
                           (a tiny prior legitimately invalidates
                           wholesale)
``max_staleness_s``        continuous-rebuild staleness ceiling in
                           wall seconds (lifecycle.staleness_p99_s
                           gauge, lifecycle/service.py; volume-gated
                           on the lifecycle.rebuilds counter) ->
                           ``health.staleness`` (warn); 0 = off (the
                           budget is deployment-specific, like
                           ``serve_p99_us``).  The daemon also emits
                           its own per-generation ``health.staleness``
                           SLA-miss events, which any monitor ADOPTS;
                           this rule is the external-tailer
                           (obs_watch) complement reading the rolling
                           gauge
``max_quarantine_frac``    quarantined cells (build.quarantined_cells,
                           faults/policy.py poison-cell quarantine) as
                           a fraction of all solved point+simplex
                           cells, volume-gated on
                           ``min_solves_for_rates`` ->
                           ``health.quarantine`` (critical): the
                           build is surviving by GIVING UP on cells
                           at scale -- solver infrastructure is
                           broken, not one poison cell
``slo_burn_fast``          error-budget burn-rate ceiling on the FAST
                           window pair (obs/slo.py ``slo.<spec>.
                           burn_fast`` gauges, published as the MIN
                           across the pair's 5m/1h windows) ->
                           ``health.slo_burn`` (critical): the budget
                           is burning fast enough to exhaust a 3-day
                           allowance in hours; 0 = off
``slo_burn_slow``          same over the SLOW 6h/3d pair
                           (``burn_slow`` gauges) ->
                           ``health.slo_burn`` (warn): a sustained
                           on-or-over-budget burn -- ticket, don't
                           page; 0 = off
``max_shard_straggle_frac``  FLEET rule (obs/fleet.py FleetMonitor;
                           scripts/obs_watch.py --fleet): concurrent
                           shards' regions/s spread, 1 - slowest /
                           fastest -> ``health.shard_straggle`` (warn)
                           -- faster shards idle on the straggler's
                           work every step.  Single-stream monitors
                           never evaluate it.
``fleet_stall``            FLEET rule: EVERY shard's stream silent
                           for this many wall seconds ->
                           ``health.fleet_stall`` (critical); a single
                           silent shard still fires the per-stream
                           ``stall_s`` rule with the shard named
``min_solves_for_rates``   rate rules stay silent below this volume
``metrics_every_steps``    engine-side feed cadence (frontier.py)
=========================  =============================================

Overrides travel as ``(name, value)`` pairs (``cfg.health_rules``, the
``--health-rule`` CLI flag, ``LONG_HEALTH_RULES``); unknown names raise
-- a typo'd rule silently never firing is the failure mode this module
exists to prevent.

Besides its own rules, the monitor ADOPTS ``health.*`` event records
already in the flow it is fed -- the frontier's runtime recompile
sentinel emits ``health.recompile`` (analysis/recompile_guard.py,
docs/static_analysis.md), and a tailed stream may carry another
monitor's findings -- folding their severity into ``worst`` so
obs_watch's exit code and long_build's halt decision see them.
"""

from __future__ import annotations

import collections
from typing import Iterable, Optional

DEFAULT_RULES: dict[str, float] = {
    "stall_s": 300.0,
    "window_steps": 50.0,
    "min_regions_per_s": 0.0,
    "max_rescue_frac": 0.25,
    "max_phase2_survivor_frac": 0.95,
    "min_warmstart_accept": 0.02,
    "max_shard_imbalance": 8.0,
    "max_competing_cpu_frac": 0.25,
    "max_device_failures": 3.0,
    "serve_p99_us": 0.0,
    "fallback_frac": 0.25,
    "max_queue_frac": 0.0,
    "max_subopt": 0.0,
    "min_subopt_samples": 20.0,
    "min_rebuild_reuse": 0.2,
    "min_rebuild_leaves": 500.0,
    "max_staleness_s": 0.0,
    "max_quarantine_frac": 0.02,
    # SLO burn-rate ceilings (obs/slo.py): the tracker publishes each
    # pair's burn gauge as the MIN across its two windows, so one
    # gauge compare here IS the both-windows alert condition.  The
    # tracker also emits its own rising-edge health.slo_burn events,
    # which any monitor ADOPTS; these rules are the external-tailer
    # (obs_watch) complement re-deriving the verdict from gauges.
    "slo_burn_fast": 14.4,
    "slo_burn_slow": 1.0,
    # Fleet-level rules (obs/fleet.py FleetMonitor; single-stream
    # monitors carry but never evaluate them, so one validated rule
    # vocabulary covers obs_watch with and without --fleet).
    "max_shard_straggle_frac": 0.5,
    "fleet_stall": 300.0,
    "min_solves_for_rates": 2000.0,
    "metrics_every_steps": 100.0,
}

_SEVERITY = {"ok": 0, "warn": 1, "critical": 2}

#: {rule: (severity-or-'config', one-line doc)} -- the discovery
#: catalog behind ``obs_watch --list-rules`` (mirroring tpulint's
#: --list-rules).  'config' marks knobs that gate/shape other rules
#: rather than firing themselves.  Kept next to DEFAULT_RULES so a new
#: rule without a catalog row fails the covering test, not discovery.
RULE_DOCS: dict[str, tuple[str, str]] = {
    "stall_s": ("critical", "no new obs record for this many wall "
                            "seconds (health.stall)"),
    "window_steps": ("config", "build.step window behind the rolling "
                               "throughput rate"),
    "min_regions_per_s": ("warn", "rolling regions/s floor "
                                  "(health.throughput_low); 0 = off"),
    "max_rescue_frac": ("critical", "rescue share of point solves per "
                                    "snapshot delta "
                                    "(health.rescue_storm)"),
    "max_phase2_survivor_frac": ("critical", "two-phase survivor gauge "
                                             "ceiling "
                                             "(health.divergence_storm)"),
    "min_warmstart_accept": ("warn", "tree warm-start accept-rate "
                                     "floor "
                                     "(health.warmstart_collapse)"),
    "max_shard_imbalance": ("warn", "serving shard max/mean load "
                                    "ceiling (health.shard_imbalance)"),
    "max_competing_cpu_frac": ("warn", "competing host CPU share "
                                       "ceiling "
                                       "(health.host_contended)"),
    "max_device_failures": ("warn", "device failures tolerated before "
                                    "health.device_failures"),
    "serve_p99_us": ("warn", "per-controller rolling p99 ceiling in "
                             "us (health.serve_p99_us); 0 = off"),
    "fallback_frac": ("warn", "per-controller degraded-serve fraction "
                              "ceiling (health.fallback_frac)"),
    "max_queue_frac": ("warn", "queue share of request wall ceiling "
                               "(health.serve_queue); 0 = off"),
    "max_subopt": ("warn", "measured serving subopt p99 ceiling vs "
                           "the eps certificate (health.subopt); "
                           "0 = off"),
    "min_subopt_samples": ("config", "sample-volume floor for "
                                     "max_subopt"),
    "min_rebuild_reuse": ("warn", "warm-rebuild reuse_frac floor "
                                  "(health.rebuild_reuse_collapse); "
                                  "0 = off"),
    "min_rebuild_leaves": ("config", "prior-leaf volume floor for "
                                     "min_rebuild_reuse"),
    "max_staleness_s": ("warn", "lifecycle staleness p99 ceiling in "
                                "wall seconds (health.staleness); "
                                "0 = off"),
    "max_quarantine_frac": ("critical", "quarantined share of all "
                                        "solved cells "
                                        "(health.quarantine)"),
    "slo_burn_fast": ("critical", "error-budget burn multiplier "
                                  "ceiling, fast 5m/1h pair "
                                  "(health.slo_burn); 0 = off"),
    "slo_burn_slow": ("warn", "error-budget burn multiplier ceiling, "
                              "slow 6h/3d pair (health.slo_burn); "
                              "0 = off"),
    "max_shard_straggle_frac": ("warn", "fleet regions/s spread "
                                        "ceiling "
                                        "(health.shard_straggle)"),
    "fleet_stall": ("critical", "every fleet shard silent for this "
                                "many seconds (health.fleet_stall)"),
    "min_solves_for_rates": ("config", "volume floor shared by the "
                                       "rate rules"),
    "metrics_every_steps": ("config", "engine-side monitor feed "
                                      "cadence in steps"),
}


def rules_from_pairs(pairs: Iterable[tuple[str, float]] | dict
                     ) -> dict[str, float]:
    """DEFAULT_RULES overridden by (name, value) pairs / a dict; raises
    on unknown rule names (see module docstring)."""
    out = dict(DEFAULT_RULES)
    items = pairs.items() if isinstance(pairs, dict) else pairs
    for k, v in items:
        if k not in DEFAULT_RULES:
            raise ValueError(
                f"unknown health rule {k!r} (known: "
                f"{', '.join(sorted(DEFAULT_RULES))})")
        out[k] = float(v)
    return out


class HealthMonitor:
    """Incremental rule evaluator (see module docstring).

    Events are plain dicts ``{"name": "health.<rule>", "severity":
    "warn"|"critical", "value": ..., "threshold": ..., "msg": ...}``;
    when built with a sink they are ALSO emitted into the stream as
    ``kind="event"`` records, so a health verdict is part of the run's
    own record.  Each rule emits at most one event per `refire_after`
    fed records (storms emit periodic reminders, not thousands of
    duplicates); `worst` still updates on every suppressed trigger."""

    def __init__(self, rules: Optional[dict] = None, sink=None,
                 refire_after: int = 50):
        self.rules = dict(DEFAULT_RULES)
        if rules:
            self.rules.update(rules_from_pairs(rules))
        self.sink = sink
        self.events: list[dict] = []
        self.worst = "ok"
        self._refire_after = refire_after
        self._cooldown: dict[str, int] = {}
        w = max(2, int(self.rules["window_steps"]))
        self._steps: collections.deque[tuple[float, float]] = \
            collections.deque(maxlen=w)
        self._prev_counters: dict[str, float] = {}
        self._n_device_failures = 0
        self.n_records = 0
        self.last_t: Optional[float] = None  # stream time of last record

    # -- event plumbing ----------------------------------------------------

    def _fire(self, rule: str, severity: str, value, threshold,
              msg: str, key: Optional[str] = None) -> Optional[dict]:
        """`key` widens the cooldown identity beyond the rule name
        (per-controller serving rules: one breaching controller's
        cooldown must not silence another's first event)."""
        key = key or rule
        if _SEVERITY[severity] > _SEVERITY[self.worst]:
            self.worst = severity
        if self._cooldown.get(key, 0) > 0:
            # Still cooling down: severity updated, no event.  The
            # cooldown is NOT refreshed here -- a persistent condition
            # must re-notify once per refire_after records, not fall
            # silent for the rest of the episode.
            return None
        self._cooldown[key] = self._refire_after
        ev = {"name": f"health.{rule}", "severity": severity,
              "value": value, "threshold": threshold, "msg": msg}
        self.events.append(ev)
        if self.sink is not None:
            self.sink.emit("event", ev["name"],
                           **{k: v for k, v in ev.items() if k != "name"})
        return ev

    def _tick_cooldowns(self) -> None:
        for k in list(self._cooldown):
            if self._cooldown[k] > 0:
                self._cooldown[k] -= 1

    # -- feeding -----------------------------------------------------------

    def feed(self, rec: dict) -> list[dict]:
        """Evaluate one obs record; returns newly fired events."""
        n0 = len(self.events)
        self.n_records += 1
        t = rec.get("t")
        if isinstance(t, (int, float)):
            self.last_t = float(t)
        kind, name = rec.get("kind"), rec.get("name")
        self._tick_cooldowns()
        if kind == "event" and name == "build.step":
            self._feed_step(rec)
        elif kind == "metrics":
            self._feed_metrics(rec)
        elif kind == "event" and isinstance(name, str) \
                and name.startswith("health.") \
                and rec.get("severity") in _SEVERITY:
            # A health verdict ALREADY IN the record flow -- the
            # frontier's recompile sentinel (health.recompile), or a
            # prior monitor's events when tailing a stream: adopt it.
            # Without this fold, an external tailer (obs_watch) would
            # read a stream full of in-build findings and still exit 0,
            # and the in-build monitor would never see guard events.
            sev = rec["severity"]
            if _SEVERITY[sev] > _SEVERITY[self.worst]:
                self.worst = sev
            self.events.append({
                "name": name, "severity": sev,
                "value": rec.get("value"),
                "threshold": rec.get("threshold"),
                "msg": rec.get("msg", "(external health event)")})
        elif kind == "event" and (name == "build.device_failure"
                                  or (name == "runlog"
                                      and "device_failure" in rec)):
            self._n_device_failures += 1
            lim = self.rules["max_device_failures"]
            if self._n_device_failures > lim:
                self._fire("device_failures", "warn",
                           self._n_device_failures, lim,
                           f"{self._n_device_failures} device failures "
                           f"(> {lim:.0f}); batches run on the CPU twin")
        return self.events[n0:]

    def _feed_step(self, rec: dict) -> None:
        t = rec.get("t")
        regions = rec.get("regions")
        if not isinstance(t, (int, float)) \
                or not isinstance(regions, (int, float)):
            return
        self._steps.append((float(t), float(regions)))
        floor = self.rules["min_regions_per_s"]
        if floor <= 0 or len(self._steps) < self._steps.maxlen:
            return
        (t0, r0), (t1, r1) = self._steps[0], self._steps[-1]
        if t1 <= t0:
            return
        rps = (r1 - r0) / (t1 - t0)
        if rps < floor:
            self._fire("throughput_low", "warn", round(rps, 3), floor,
                       f"rolling throughput {rps:.2f} regions/s over the "
                       f"last {len(self._steps)} steps (< {floor:g})")

    def _feed_metrics(self, rec: dict) -> None:
        counters = rec.get("counters", {}) or {}
        gauges = rec.get("gauges", {}) or {}
        min_n = self.rules["min_solves_for_rates"]
        points = counters.get("oracle.point_solves", 0)

        # Rescue storm: rescue share of point solves since the last
        # EVALUATED snapshot (snapshots are cumulative; the delta is
        # the window).  The baseline rolls forward only once a window
        # reached min_n -- resetting it on every snapshot would let a
        # low-volume snapshot cadence keep each window under the
        # threshold forever and the rule would silently never fire.
        d_res = (counters.get("oracle.rescue_solves", 0)
                 - self._prev_counters.get("oracle.rescue_solves", 0))
        d_pt = points - self._prev_counters.get("oracle.point_solves", 0)
        lim = self.rules["max_rescue_frac"]
        if lim > 0:
            if d_pt >= min_n:
                frac = d_res / d_pt
                if frac > lim:
                    self._fire(
                        "rescue_storm", "critical", round(frac, 4), lim,
                        f"rescue pass re-solved {100 * frac:.1f}% of "
                        f"the last {d_pt} point QPs (> {100 * lim:.0f}%)"
                        ": the configured schedule is missing broadly")
                self._prev_counters = dict(counters)
        else:
            self._prev_counters = dict(counters)

        lim = self.rules["max_phase2_survivor_frac"]
        surv = gauges.get("oracle.phase2_survivor_frac")
        if lim > 0 and surv is not None and points >= min_n \
                and surv > lim:
            self._fire("divergence_storm", "critical", round(surv, 4),
                       lim,
                       f"{100 * surv:.1f}% of two-phase cells survive "
                       f"phase 1 unconverged (> {100 * lim:.0f}%): the "
                       "cohort split is buying nothing / solves diverge")

        lim = self.rules["min_warmstart_accept"]
        acc = gauges.get("oracle.warmstart_accept_rate")
        # Gated on the attempts gauge, not the rate alone: an oracle
        # with warm_start off reports rate 0.0 forever, which is not a
        # collapse -- nothing was ever offered to the merit gate.
        attempts = gauges.get("oracle.warm_attempts", 0)
        if lim > 0 and acc is not None and attempts >= min_n \
                and acc < lim:
            self._fire("warmstart_collapse", "warn", round(acc, 4),
                       lim,
                       f"tree warm-start accept rate {acc:.3f} over "
                       f"{attempts:.0f} attempts (< {lim:g}): donors "
                       "rejected by the merit gate; every midpoint "
                       "starts cold")

        lim = self.rules["max_shard_imbalance"]
        imb = gauges.get("serve.shard_imbalance")
        if lim > 0 and imb is not None and imb > lim:
            self._fire("shard_imbalance", "warn", round(imb, 3), lim,
                       f"serving shard imbalance {imb:.2f}x max/mean "
                       f"(> {lim:g}): re-shard or deepen the cut")

        # Serving SLO rules, evaluated PER CONTROLLER over the
        # namespaced serve.ctl.<name>.* gauges (serve/scheduler.py):
        # several schedulers share one obs handle, and a healthy
        # controller's gauge must not mask a breaching one.  The
        # un-namespaced serve.* names from older streams still
        # evaluate.  Each controller is volume-gated on ITS request
        # counter like the build-side rate rules -- a three-request
        # smoke run must not trip a p99 alarm.
        prefixes = {"serve"}
        for key in gauges:
            if key.startswith("serve.ctl.") and (
                    key.endswith(".p99_us")
                    or key.endswith(".fallback_frac")
                    or key.endswith(".queue_frac")
                    or key.endswith(".subopt_p99")):
                prefixes.add(key.rsplit(".", 1)[0])
        for pre in sorted(prefixes):
            ctl = pre[len("serve.ctl."):] if pre != "serve" else ""
            tag = f" [controller {ctl!r}]" if ctl else ""
            n_req = counters.get(f"{pre}.requests", 0)
            lim = self.rules["serve_p99_us"]
            p99 = gauges.get(f"{pre}.p99_us")
            if lim > 0 and p99 is not None and n_req >= min_n \
                    and p99 > lim:
                self._fire("serve_p99_us", "warn", round(p99, 1), lim,
                           f"serving p99 {p99:.0f} us over the rolling "
                           f"window{tag} (> {lim:g} us): deadline "
                           "budget or shard placement needs retuning",
                           key=f"serve_p99_us:{ctl}")

            lim = self.rules["fallback_frac"]
            fb = gauges.get(f"{pre}.fallback_frac")
            if lim > 0 and fb is not None and n_req >= min_n \
                    and fb > lim:
                self._fire("fallback_frac", "warn", round(fb, 4), lim,
                           f"{100 * fb:.1f}% of recent queries served "
                           f"degraded{tag} (> {100 * lim:.0f}%): "
                           "traffic has left the certified box or the "
                           "tree has holes -- rebuild or widen the "
                           "partition", key=f"fallback_frac:{ctl}")

            # Queue-dominated tail (obs/reqtrace.py queue_frac: the
            # queue phase's share of request wall over the rolling
            # window).  When the tail is queueing, kernel and shard
            # tuning cannot move it -- the fix is capacity ("scale
            # replicas, not kernels").  Same volume gate as p99.
            lim = self.rules["max_queue_frac"]
            qf = gauges.get(f"{pre}.queue_frac")
            if lim > 0 and qf is not None and n_req >= min_n \
                    and qf > lim:
                self._fire("serve_queue", "warn", round(qf, 4), lim,
                           f"{100 * qf:.1f}% of request wall spent "
                           f"queued{tag} (> {100 * lim:.0f}%): the "
                           "tail is queue-dominated -- scale replicas "
                           "or raise max_batch, kernel tuning will "
                           "not move it", key=f"serve_queue:{ctl}")

            # Measured suboptimality SLO (obs/demand.py online
            # re-solves).  Gated on ITS OWN sample counter, not
            # n_req: the sampler re-solves a tiny deterministic
            # fraction of traffic, so min_solves_for_rates in
            # REQUESTS would keep the rule silent long after the
            # subopt estimate is statistically sound.
            lim = self.rules["max_subopt"]
            sp = gauges.get(f"{pre}.subopt_p99")
            n_sub = counters.get(f"{pre}.subopt_samples", 0)
            if lim > 0 and sp is not None \
                    and n_sub >= self.rules["min_subopt_samples"] \
                    and sp > lim:
                self._fire("subopt", "warn", round(sp, 6), lim,
                           f"measured serving suboptimality p99 "
                           f"{sp:.4g} over {n_sub:.0f} sampled "
                           f"re-solves{tag} (> {lim:g}): served "
                           "answers exceed the eps certificate -- "
                           "check artifact provenance / trigger a "
                           "rebuild", key=f"subopt:{ctl}")

        # SLO burn rate (obs/slo.py): the tracker publishes
        # slo.<spec>.burn_fast / .burn_slow as the MIN across each
        # pair's two windows, so a single gauge compare IS the
        # both-windows multi-burn-rate condition.  No volume gate:
        # burn is 0.0 by construction until a window holds units.
        for key, rule, sev in (("burn_fast", "slo_burn_fast",
                                "critical"),
                               ("burn_slow", "slo_burn_slow", "warn")):
            lim = self.rules[rule]
            if lim <= 0:
                continue
            suffix = f".{key}"
            for gname, v in gauges.items():
                if not (gname.startswith("slo.")
                        and gname.endswith(suffix)):
                    continue
                if v is None or v <= lim:
                    continue
                spec = gname[len("slo."):-len(suffix)]
                pair = "fast" if key == "burn_fast" else "slow"
                self._fire(
                    "slo_burn", sev, round(v, 3), lim,
                    f"slo {spec!r} burning {v:.1f}x its budget rate "
                    f"on both {pair}-pair windows (> {lim:g}x): see "
                    "the budget-exhaustion runbook in "
                    "docs/observability.md",
                    key=f"{rule}:{spec}")

        # Warm-rebuild reuse collapse: a near-zero reuse fraction on a
        # LARGE prior tree means the revision invalidated (almost)
        # everything -- most often a silently-drifted problem hash
        # (wrong prior artifact, unnoticed model change), i.e. the
        # rebuild pays cold-build cost while the operator believes it
        # is warm.  Volume-gated on its OWN leaf-count floor
        # (min_rebuild_leaves) -- the min_solves_for_rates knob is in
        # SOLVES and would silently disable this rule for mid-size
        # trees (and retune it whenever the solve knob moves).
        lim = self.rules["min_rebuild_reuse"]
        reuse = gauges.get("rebuild.reuse_frac")
        n_leaves = (counters.get("rebuild.leaves_reused", 0)
                    + counters.get("rebuild.leaves_invalidated", 0))
        if lim > 0 and reuse is not None \
                and n_leaves >= self.rules["min_rebuild_leaves"] \
                and reuse < lim:
            self._fire("rebuild_reuse_collapse", "warn", round(reuse, 4),
                       lim,
                       f"warm rebuild reused {100 * reuse:.1f}% of "
                       f"{n_leaves:.0f} prior leaves (< {100 * lim:.0f}"
                       "%): the revision invalidated nearly everything "
                       "-- check the prior artifact's provenance stamp "
                       "(a drifted problem hash makes every "
                       "certificate fail)")

        # Continuous-rebuild staleness (lifecycle/service.py): the
        # rolling p99 of revision-observed -> new-controller-live.
        # Volume-gated on at least one completed rebuild (the gauge
        # is meaningless before the first generation lands).
        lim = self.rules["max_staleness_s"]
        stale = gauges.get("lifecycle.staleness_p99_s")
        if lim > 0 and stale is not None \
                and counters.get("lifecycle.rebuilds", 0) >= 1 \
                and stale > lim:
            self._fire("staleness", "warn", round(stale, 3), lim,
                       f"rebuild staleness p99 {stale:.1f}s "
                       f"(> {lim:g}s): revisions are going live "
                       "slower than the SLA -- the daemon is falling "
                       "behind plant drift")

        # Quarantine storm (faults/policy.py): poison-cell quarantine
        # exists so ONE unrecoverable batch cannot kill a campaign --
        # but a meaningful FRACTION of all cells being given up on
        # means the solver infrastructure itself is broken (dead
        # device AND broken CPU twin, systematic timeout), and the
        # "surviving" build is quietly producing an
        # uncertified-riddled tree.  Critical: checkpoint-and-halt
        # beats burning the allocation.
        lim = self.rules["max_quarantine_frac"]
        q = counters.get("build.quarantined_cells", 0)
        denom = q + points + counters.get("oracle.simplex_solves", 0)
        if lim > 0 and q > 0 and denom >= min_n:
            frac = q / denom
            if frac > lim:
                self._fire(
                    "quarantine", "critical", round(frac, 4), lim,
                    f"{q} cells quarantined ({100 * frac:.1f}% of "
                    f"{denom} solved cells, > {100 * lim:.0f}%): "
                    "recovery is failing at scale -- check the "
                    "fallback oracle and the device, not the cells")

        lim = self.rules["max_competing_cpu_frac"]
        host = gauges.get("host.competing_cpu_frac_mean")
        if lim > 0 and host is not None and host > lim:
            self._fire("host_contended", "warn", round(host, 3), lim,
                       f"competing processes used {100 * host:.0f}% of "
                       f"host CPU (> {100 * lim:.0f}%): measurements "
                       "and the build itself are degraded")

    # -- wall-clock stall --------------------------------------------------

    def check_stall(self, idle_s: float) -> list[dict]:
        """Wall-based stall check, driven by the tailer: `idle_s` is
        how long the stream has produced NOTHING (no file growth).  A
        frozen stream means the build is hung (device wedge, deadlock)
        or dead without its atexit flush -- either way, critical."""
        lim = self.rules["stall_s"]
        if lim <= 0 or idle_s < lim:
            return []
        ev = self._fire("stall", "critical", round(idle_s, 1), lim,
                        f"no obs records for {idle_s:.0f}s "
                        f"(> {lim:.0f}s): build frozen or dead")
        return [ev] if ev else []

    # -- verdict -----------------------------------------------------------

    @property
    def exit_code(self) -> int:
        """0 healthy, 1 warn-level findings, 2 critical (the contract
        scripts/obs_watch.py and long_build's halt decision share)."""
        return _SEVERITY[self.worst]

    def summary(self) -> dict:
        return {"worst": self.worst, "exit_code": self.exit_code,
                "n_records": self.n_records,
                "n_events": len(self.events),
                "events": list(self.events)}
