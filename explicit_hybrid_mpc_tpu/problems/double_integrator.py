"""Config 1: double-integrator explicit MPC (2-state, 1-input, N=5, box
constraints) -- BASELINE.md row 1.  Pure mp-QP (single commutation): the
minimum end-to-end slice of SURVEY.md section 8 exercises every layer except
delta-enumeration on this problem.
"""

from __future__ import annotations

import functools

import numpy as np

from explicit_hybrid_mpc_tpu.problems import base
from explicit_hybrid_mpc_tpu.problems.registry import register


@register
class DoubleIntegrator(base.HybridMPC):
    name = "double_integrator"

    # x_max default keeps the whole Theta box inside the N-step feasible
    # set (|pos| grows at most theta_box * (1 + N*dt) from any corner), so
    # the partition terminates without depth-capped boundary cells.
    def __init__(self, N: int = 5, dt: float = 0.25,
                 theta_box: float = 3.0, u_max: float = 1.0,
                 x_max: float = 10.0):
        self.N = N
        self.dt = dt
        self.u_max = u_max
        self.x_max = x_max
        self.theta_lb = -theta_box * np.ones(2)
        self.theta_ub = theta_box * np.ones(2)
        self.n_u = 1
        self.Qc = np.diag([1.0, 0.1])
        self.Rc = np.array([[0.1]])

    @functools.cache
    def _plant(self):
        Ac = np.array([[0.0, 1.0], [0.0, 0.0]])
        Bc = np.array([[0.0], [1.0]])
        return base.zoh(Ac, Bc, self.dt)

    def plant_step(self, x, u):
        A, B = self._plant()
        return A @ x + B @ u

    def build_canonical(self) -> base.CanonicalMPQP:
        A, B = self._plant()
        N = self.N
        Q = np.diag([1.0, 0.1])
        R = np.array([[0.1]])
        # Discrete LQR terminal weight for stability-flavoured cost.
        P = _dare(A, B, Q, R)
        Cx, cx = base.box_rows(-self.x_max * np.ones(2), self.x_max * np.ones(2))
        Cu, cu = base.box_rows(np.array([-self.u_max]), np.array([self.u_max]))
        sl = base.condense(
            A_seq=[A] * N, B_seq=[B] * N, e_seq=[np.zeros(2)] * N,
            Q=Q, R=R, P=P, E=np.eye(2), x_nom=np.zeros(2), n_u=1,
            state_con=[(Cx, cx)] * N, input_con=[(Cu, cu)] * N,
        )
        return base.stack_slices([sl], deltas=np.zeros((1, 0), dtype=np.int64))


def _dare(A, B, Q, R):
    import scipy.linalg

    return np.asarray(scipy.linalg.solve_discrete_are(A, B, Q, R))
