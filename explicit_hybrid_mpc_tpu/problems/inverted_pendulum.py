"""Config 3: inverted-pendulum hybrid MPC (2 PWA modes, mp-MIQP) --
BASELINE.md row 3, and the north-star headline benchmark.

Plant: torque-controlled inverted pendulum linearized about upright, with
an elastic wall at angle 0 on the positive side:

    mode 0 (free,    th <= 0):  thdd = a*th            + u
    mode 1 (contact, th >= 0):  thdd = (a - ks)*th     + u

The PWA vector field is continuous at the mode boundary (the wall force
ks*th vanishes at th = 0), so the optimal value function is continuous and
the eps-suboptimal partition is well posed.

Hybrid encoding: the commutation delta in {0,1}^N is the mode *sequence*
over the horizon.  For fixed delta, the dynamics are the time-varying
linear sequence A_{delta_k} and mode *membership* becomes linear state
constraints (step k's mode constrains x_k: th_k <= 0 for mode 0,
-th_k <= 0 for mode 1).  Enumerating all 2^N sequences turns the MIQP into
a batch of 2^N mp-QPs solved by one vmapped kernel -- the TPU-native
replacement for branch-and-bound (SURVEY.md section 8 layer 2; the
reference solves the same problem with Gurobi's B&B through cvxpy
[M-high], citation UNVERIFIED -- reference mount empty).
"""

from __future__ import annotations

import itertools

import numpy as np

from explicit_hybrid_mpc_tpu.problems import base
from explicit_hybrid_mpc_tpu.problems.registry import register


@register
class InvertedPendulum(base.HybridMPC):
    name = "inverted_pendulum"

    def __init__(self, N: int = 5, dt: float = 0.1, a: float = 2.0,
                 ks: float = 10.0, theta_box=(0.4, 1.0), u_max: float = 8.0,
                 th_max: float = 1.2, w_max: float = 4.0):
        """a: unstable pole strength g/l; ks: wall spring stiffness
        (ks > a so contact is restoring); theta_box: half-widths of the
        partitioned (th, thdot) set; u_max sized so the whole box is
        N-step recoverable (keeps infeasible leaves at the margins)."""
        if ks <= a:
            raise ValueError("need ks > a for a restoring wall")
        self.N = N
        self.dt = dt
        self.a = a
        self.ks = ks
        self.u_max = u_max
        self.th_max = th_max
        self.w_max = w_max
        self.theta_lb = -np.asarray(theta_box, dtype=np.float64)
        self.theta_ub = np.asarray(theta_box, dtype=np.float64)
        self.n_u = 1
        # The step-0 mode membership flips across the wall th = 0, a fixed
        # hyperplane in theta: root cells must align with it (see
        # geometry.box_triangulation).
        self.root_splits = {0: (0.0,)}
        self.Qc = np.diag([4.0, 0.4])
        self.Rc = np.array([[0.2]])

    def plant_step(self, x, u):
        """PWA plant: mode by the wall side of the CURRENT angle, matching
        the prediction model's Euler discretization (build_canonical)."""
        a_eff = self.a if x[0] <= 0.0 else self.a - self.ks
        A = np.eye(2) + self.dt * np.array([[0.0, 1.0], [a_eff, 0.0]])
        B = np.array([[0.5 * self.dt ** 2], [self.dt]])
        return A @ x + B @ u

    def build_canonical(self) -> base.CanonicalMPQP:
        B_c = np.array([[0.0], [1.0]])
        A_free = np.array([[0.0, 1.0], [self.a, 0.0]])
        A_wall = np.array([[0.0, 1.0], [self.a - self.ks, 0.0]])
        # Forward Euler in A, NOT ZOH: per-mode ZOH lets the chosen mode
        # act over the whole interval even after the trajectory crosses
        # the wall, making the discrete PWA map (and hence V*) jump at
        # th = 0.  Euler is affine in the continuous-time field, which the
        # two modes share at the boundary, so the discrete map stays
        # continuous.  B is the double-integrator second-order hold
        # [dt^2/2, dt], IDENTICAL for both modes (mode-independent B
        # preserves continuity): actuating the angle at second order gives
        # every later-step mode-membership hyperplane a control band of
        # half-width (dt^2/2) u_max, so simplices near those lines certify
        # at finite depth instead of refining forever.
        dt = self.dt
        Bd = np.array([[0.5 * dt * dt], [dt]])
        AB = [(np.eye(2) + dt * A_free, Bd),
              (np.eye(2) + dt * A_wall, Bd)]

        N = self.N
        Q = np.diag([4.0, 0.4])
        R = np.array([[0.2]])
        P = _dare(AB[0][0], AB[0][1], Q, R)  # free-mode terminal weight
        x_lb = np.array([-self.th_max, -self.w_max])
        Cbox, cbox = base.box_rows(x_lb, -x_lb)
        Cu, cu = base.box_rows(np.array([-self.u_max]),
                               np.array([self.u_max]))
        # Mode-membership half-space on the angle: mode 0 needs th <= 0,
        # mode 1 needs -th <= 0.
        mode_row = {0: (np.array([[1.0, 0.0]]), np.zeros(1)),
                    1: (np.array([[-1.0, 0.0]]), np.zeros(1))}

        slices = []
        deltas = list(itertools.product((0, 1), repeat=N))
        for delta in deltas:
            A_seq = [AB[m][0] for m in delta]
            B_seq = [AB[m][1] for m in delta]
            # state_con[k] constrains x_{k+1}: box everywhere, plus the
            # membership row of the mode ACTIVE AT step k+1 (x_N, beyond
            # the last mode decision, gets the box only).
            state_con = []
            for k in range(N):
                if k + 1 < N:
                    Cm, cm = mode_row[delta[k + 1]]
                    state_con.append((np.vstack([Cbox, Cm]),
                                      np.concatenate([cbox, cm])))
                else:
                    state_con.append((Cbox, cbox))
            # Step 0's mode constrains x_0 = theta directly.
            Cm0, cm0 = mode_row[delta[0]]
            slices.append(base.condense(
                A_seq=A_seq, B_seq=B_seq, e_seq=[np.zeros(2)] * N,
                Q=Q, R=R, P=P, E=np.eye(2), x_nom=np.zeros(2), n_u=1,
                state_con=state_con, input_con=[(Cu, cu)] * N,
                theta_con=(Cm0, cm0)))
        return base.stack_slices(
            slices, deltas=np.asarray(deltas, dtype=np.int64))


def _dare(A, B, Q, R):
    import scipy.linalg

    return np.asarray(scipy.linalg.solve_discrete_are(A, B, Q, R))
