"""Config 4: satellite reaction-wheel desaturation (6-state, mixed-integer
thruster selection) -- BASELINE.md row 4.

Plant: rigid spacecraft with a spin bias about +z, three reaction wheels
(continuous torques) and three axis-aligned thruster pairs.  State
x = (omega, h): body angular-rate error (3) + wheel momentum (3).
Linearized Euler dynamics about (omega_bar = n e_z, h = 0):

    omega_dot = J^-1 [ (skew(J omega_bar) - skew(omega_bar) J) omega
                       - skew(omega_bar) h  - u_w + T(delta) m ]
    h_dot     = u_w

Wheels torque the body and absorb momentum (they conserve TOTAL angular
momentum J omega + h, so wheels alone cannot desaturate -- the physical
reason thrusters, and hence the integer structure, exist).  Each thruster
pair i has a MINIMUM IMPULSE BOUND: per MPC cycle it is either off, or
fires with |torque| in [u_min, u_max].  The commutation is the per-axis
firing decision delta in {-1, 0, +1}^3 held over the horizon -- 27
commutations, each a convex mp-QP (the reference models the same
min-impulse satellite family with per-thruster binaries solved by Gurobi
B&B; SURVEY.md section 3 "Problem library" [M-med], citation UNVERIFIED --
reference mount empty).

Convexification per commutation: the decision channel m_i >= 0 is the
thrust MAGNITUDE; the firing sign is folded into the input matrix column
and the u_selector, so "fire negative" stays a convex box [u_min, u_max]
on m_i.  Off thrusters get a zeroed input column plus m_i in [0, u_max]:
with R positive definite the optimizer parks m_i at exactly 0, avoiding
empty-interior equality rows that would degrade the IPM.

`axes=1` gives the scalar (omega, h) single-wheel variant (3 commutations,
2-D parameter set) used by fast partition tests; `axes=3` is the full
6-state benchmark.
"""

from __future__ import annotations

import functools
import itertools

import numpy as np

from explicit_hybrid_mpc_tpu.problems import base
from explicit_hybrid_mpc_tpu.problems.registry import register


def _skew(v: np.ndarray) -> np.ndarray:
    return np.array([[0.0, -v[2], v[1]],
                     [v[2], 0.0, -v[0]],
                     [-v[1], v[0], 0.0]])


@register
class Satellite(base.HybridMPC):
    name = "satellite"
    # Row pruning (oracle/prune.py) measured on the 6-D 25%-box config:
    # warm 1.72x at the IDENTICAL 7,744-region tree (96 -> <=14 kept
    # rows per commutation, verified fallbacks) -- the A/B is
    # artifacts/sat_prune_ab_cpu.json; CPU benchmark drivers pick the
    # pruned oracle up via this hint.
    prune_hint = True

    def __init__(self, N: int = 4, dt: float = 2.0, axes: int = 3,
                 J=(5.0, 6.0, 7.0), spin: float = 0.05,
                 u_w_max: float = 0.2, u_min: float = 0.2,
                 u_max: float = 0.5, omega_box: float = 0.12,
                 h_box: float = 1.2, omega_max: float = 0.3,
                 h_max: float = 2.5):
        """spin: rate bias n about +z giving gyroscopic coupling; u_min:
        the min-impulse torque bound (the hybrid structure; u_min > 0);
        omega_box/h_box: half-widths of the partitioned parameter set;
        omega_max/h_max: the (looser) state constraint box."""
        if axes not in (1, 3):
            raise ValueError("axes must be 1 or 3")
        if not 0.0 < u_min < u_max:
            raise ValueError("need 0 < u_min < u_max")
        self.N = N
        self.dt = dt
        self.axes = axes
        self.J = np.asarray(J, dtype=np.float64)[:axes]
        self.spin = spin
        self.u_w_max = u_w_max
        self.u_min = u_min
        self.u_max = u_max
        self.omega_max = omega_max
        self.h_max = h_max
        self.theta_lb = -np.concatenate([np.full(axes, omega_box),
                                         np.full(axes, h_box)])
        self.theta_ub = -self.theta_lb
        self.n_u = 2 * axes   # applied (u_w, signed thruster torque)
        self.root_splits = None
        self.Qc = np.diag(np.concatenate([np.full(axes, 50.0),
                                          np.full(axes, 2.0)]))
        self.Rc = np.diag(np.concatenate([np.full(axes, 1.0),
                                          np.full(axes, 4.0)]))

    @functools.cache
    def _plant(self):
        A_c, B_w_c, B_t_c = self._continuous()
        return base.zoh(A_c, np.hstack([B_w_c, B_t_c]), self.dt)

    def plant_step(self, x, u):
        """u = (wheel torques, SIGNED thruster torques) -- the applied
        input the online controller emits (u_selector folds the firing
        sign into the magnitude channel)."""
        A, B = self._plant()
        return A @ x + B @ u

    def _continuous(self):
        """(A_c, B_w_c, B_t_unit_c): drift, wheel columns, unit-thrust
        columns (sign applied per commutation)."""
        a = self.axes
        Jinv = np.diag(1.0 / self.J)
        if a == 3:
            wbar = np.array([0.0, 0.0, self.spin])
            A_ww = Jinv @ (_skew(np.diag(self.J) @ wbar)
                           - _skew(wbar) @ np.diag(self.J))
            A_wh = Jinv @ (-_skew(wbar))
        else:
            A_ww = np.zeros((1, 1))
            A_wh = np.zeros((1, 1))
        A = np.block([[A_ww, A_wh],
                      [np.zeros((a, a)), np.zeros((a, a))]])
        B_w = np.vstack([-Jinv, np.eye(a)])
        B_t = np.vstack([Jinv, np.zeros((a, a))])
        return A, B_w, B_t

    def build_canonical(self) -> base.CanonicalMPQP:
        a = self.axes
        N = self.N
        A_c, B_w_c, B_t_c = self._continuous()

        Q = np.diag(np.concatenate([np.full(a, 50.0), np.full(a, 2.0)]))
        R = np.diag(np.concatenate([np.full(a, 1.0), np.full(a, 4.0)]))

        # Common terminal weight so V_delta are comparable across
        # commutations (certificate requirement): DARE with ALL actuators
        # at positive sign -- wheels alone leave total momentum
        # uncontrollable and the DARE has no stabilizing solution.
        A_full, B_full = base.zoh(A_c, np.hstack([B_w_c, B_t_c]), self.dt)
        import scipy.linalg
        P = np.asarray(scipy.linalg.solve_discrete_are(A_full, B_full, Q, R))

        x_ub = np.concatenate([np.full(a, self.omega_max),
                               np.full(a, self.h_max)])
        Cx, cx = base.box_rows(-x_ub, x_ub)

        slices, deltas = [], list(itertools.product((-1, 0, 1), repeat=a))
        for delta in deltas:
            s = np.asarray(delta, dtype=np.float64)
            # Signs folded into the thruster columns; off columns zeroed.
            Ad, Bd = base.zoh(A_c, np.hstack([B_w_c, B_t_c @ np.diag(s)]),
                              self.dt)
            # Magnitude boxes: on-axis [u_min, u_max], off-axis [0, u_max].
            m_lb = np.where(s != 0.0, self.u_min, 0.0)
            Cu, cu = base.box_rows(
                np.concatenate([np.full(a, -self.u_w_max), m_lb]),
                np.concatenate([np.full(a, self.u_w_max),
                                np.full(a, self.u_max)]))
            sel = np.diag(np.concatenate([np.ones(a), s]))
            slices.append(base.condense(
                A_seq=[Ad] * N, B_seq=[Bd] * N,
                e_seq=[np.zeros(2 * a)] * N,
                Q=Q, R=R, P=P, E=np.eye(2 * a), x_nom=np.zeros(2 * a),
                n_u=2 * a, state_con=[(Cx, cx)] * N,
                input_con=[(Cu, cu)] * N, u_selector=sel))
        return base.stack_slices(
            slices, deltas=np.asarray(deltas, dtype=np.int64))
