"""Config 5: 6-DOF quadrotor obstacle avoidance (12-state, 8 integer mode
vars, N=10) -- BASELINE.md row 5.

Plant: hover-linearized quadrotor, x = (p, v, att, omega) in R^12 with
p position, v velocity, att = (roll, pitch, yaw) small angles, omega body
rates; u = (thrust delta, 3 torques).  Near hover:

    p_dot = v,   v_dot = g (pitch, -roll, 0) + (0, 0, dT/m),
    att_dot = omega,   omega_dot = J^-1 tau.

Hybrid structure: two axis-aligned box obstacles in the (x, y) plane.  The
mixed-integer encoding assigns each obstacle a one-hot choice of WHICH
FACE the whole predicted trajectory stays clear of (left/right/front/
back) -- 2 obstacles x 4 one-hot binaries = the config's 8 integer mode
vars, 16 valid assignments.  For a fixed assignment the avoidance rows are
linear in the state, so each commutation is a convex mp-QP; the 16-way
enumeration replaces the big-M branch-and-bound the reference's Gurobi
oracle would run (SURVEY.md section 8 layer 2; reference encoding
UNVERIFIED -- mount empty).

The avoidance rows are SOFT (quadratic-penalty slacks, base.soften):
hard rows would put the feasible parameter set's boundary on a
dynamics-dependent surface slightly off the obstacle faces, and simplices
straddling that surface can never certify (they subdivide to the depth
cap).  With the penalty, every commutation is feasible everywhere, V* is
continuous on all of Theta, and the mode structure (which side to pass)
lives in the cost, where the eps-certificate can decide it.

Side-choice-per-horizon is a restriction of per-step big-M (a trajectory
may not switch faces mid-horizon); it upper-bounds the big-M optimal cost
while preserving feasibility for the maneuvers the benchmark exercises,
and it is what keeps the commutation set enumerable (SURVEY.md section 8:
enumeration requires finite, small Delta).

The partitioned parameter is the initial (px, py, vx, vy) slice, theta in
R^4, embedded into x0 by E (altitude/attitude start at hover nominal):
partitioning all 12 states is neither useful (attitude transients are
fast) nor tractable for a simplicial partition (the Kuhn triangulation of
a 12-box has 12! roots).
"""

from __future__ import annotations

import functools

import numpy as np

from explicit_hybrid_mpc_tpu.problems import base
from explicit_hybrid_mpc_tpu.problems.registry import register

# Face selections per obstacle: (normal sign, axis).  "left of" = stay at
# x <= cx - w  <=>  +x row; encoded one-hot over 4 faces.
_FACES = ((-1, 0), (+1, 0), (-1, 1), (+1, 1))


@register
class Quadrotor(base.HybridMPC):
    name = "quadrotor"
    # Every commutation is feasible everywhere (the avoidance rows are
    # softened), so stage-2's hybrid phase1-first default would run a
    # 360-row joint phase-1 per pair that never excludes anything;
    # min-first lets the elastic minimum's own t=0 witness prove
    # feasibility and reserves phase-1 for the (empty) remainder.
    stage2_hint = "min_first"
    # The mixed schedule's f32 phase collapses on this problem (60% of
    # point solves unconverged after the short f64 polish, r4 A/B in
    # artifacts/quad_prune_ab_cpu.json): CPU benchmark drivers should
    # run full f64 (4x faster end-to-end); TPU keeps mixed (emulated
    # f64 changes the tradeoff -- to be re-measured on-chip).
    cpu_precision_hint = "f64"
    # Row-heavy config (nc=360): benchmark drivers should use the
    # pruned oracle on CPU (measured 2.87x at the identical tree).
    prune_hint = True

    def __init__(self, N: int = 10, dt: float = 0.25, mass: float = 1.0,
                 g: float = 9.81, J=(0.01, 0.01, 0.02),
                 obstacles=(((1.5, 0.0), 0.6), ((-1.5, 0.0), 0.6)),
                 pos_box: float = 4.0, vel_box: float = 2.0,
                 pos_max: float = 5.0, vel_max: float = 3.0,
                 tilt_max: float = 0.35, rate_max: float = 2.0,
                 dT_max: float = 5.0, tau_max: float = 0.15,
                 param: str = "pv", obs_rho: float = 200.0):
        """obstacles: ((cx, cy), half_width) axis-aligned squares the
        trajectory keeps one face clear of; pos_box/vel_box: half-widths
        of the partitioned (px, py, vx, vy) set; *_max: state/input
        constraint boxes (looser than the parameter box); param: which
        initial-condition slice is the partitioned parameter -- "pv" =
        (px, py, vx, vy) (the benchmark), "p" = (px, py) only (2-D,
        for fast tests/figures)."""
        if param not in ("pv", "p"):
            raise ValueError("param must be 'pv' or 'p'")
        self.param = param
        self.obs_rho = obs_rho
        self.N = N
        self.dt = dt
        self.mass = mass
        self.g = g
        self.J = np.asarray(J, dtype=np.float64)
        self.obstacles = tuple(((float(c[0]), float(c[1])), float(w))
                               for c, w in obstacles)
        self.pos_max = pos_max
        self.vel_max = vel_max
        self.tilt_max = tilt_max
        self.rate_max = rate_max
        self.dT_max = dT_max
        self.tau_max = tau_max
        if param == "pv":
            self.theta_lb = -np.array([pos_box, pos_box, vel_box, vel_box])
        else:
            self.theta_lb = -np.array([pos_box, pos_box])
        self.theta_ub = -self.theta_lb
        self.n_u = 4
        self.Qc = np.diag([4.0, 4.0, 4.0, 1.0, 1.0, 1.0,
                           2.0, 2.0, 2.0, 0.5, 0.5, 0.5])
        self.Rc = np.diag([0.1, 0.5, 0.5, 0.5])
        # Obstacle faces are fixed hyperplanes in (px, py); align root
        # cells so near-edge simplices certify at finite depth.
        xs, ys = set(), set()
        for (cx, cy), w in self.obstacles:
            for val, box, acc in ((cx, pos_box, xs), (cy, pos_box, ys)):
                for edge in (val - w, val + w):
                    if -box < edge < box:
                        acc.add(round(edge, 12))
        self.root_splits = {}
        if xs:
            self.root_splits[0] = tuple(sorted(xs))
        if ys:
            self.root_splits[1] = tuple(sorted(ys))

    def plant_step(self, x, u):
        Ad, Bd = self._discrete()
        return Ad @ x + Bd @ u

    def theta_of_state(self, x):
        """Project the 12-state onto the partitioned slice.  The explicit
        law is exact on the slice and an approximation off it (attitude
        transients are treated as disturbances by the closed loop)."""
        idx = [0, 1, 3, 4] if self.param == "pv" else [0, 1]
        return np.asarray(x, dtype=np.float64)[idx]

    def state_of_theta(self, theta):
        x = np.zeros(12)
        x[0], x[1] = theta[0], theta[1]
        if self.param == "pv":
            x[3], x[4] = theta[2], theta[3]
        return x

    @functools.cache
    def _discrete(self):
        g, m = self.g, self.mass
        A = np.zeros((12, 12))
        A[0:3, 3:6] = np.eye(3)            # p_dot = v
        A[3, 7] = g                         # vx_dot =  g * pitch
        A[4, 6] = -g                        # vy_dot = -g * roll
        A[6:9, 9:12] = np.eye(3)           # att_dot = omega
        B = np.zeros((12, 4))
        B[5, 0] = 1.0 / m                  # vz_dot = dT/m
        B[9:12, 1:4] = np.diag(1.0 / self.J)
        return base.zoh(A, B, self.dt)

    def build_canonical(self) -> base.CanonicalMPQP:
        N = self.N
        Ad, Bd = self._discrete()
        E = np.zeros((12, self.n_theta))
        E[0, 0] = E[1, 1] = 1.0
        if self.param == "pv":
            E[3, 2] = E[4, 3] = 1.0

        Q = np.diag([4.0, 4.0, 4.0, 1.0, 1.0, 1.0,
                     2.0, 2.0, 2.0, 0.5, 0.5, 0.5])
        R = np.diag([0.1, 0.5, 0.5, 0.5])
        import scipy.linalg
        P = np.asarray(scipy.linalg.solve_discrete_are(Ad, Bd, Q, R))

        # State rows: position, velocity, tilt, rates (yaw box too).
        Cx_rows, cx_rows = [], []
        for idx, lim in ((range(0, 3), self.pos_max),
                         (range(3, 6), self.vel_max),
                         (range(6, 9), self.tilt_max),
                         (range(9, 12), self.rate_max)):
            for i in idx:
                e = np.zeros(12)
                e[i] = 1.0
                Cx_rows += [e, -e]
                cx_rows += [lim, lim]
        Cx = np.stack(Cx_rows)
        cx = np.asarray(cx_rows, dtype=np.float64)
        Cu, cu = base.box_rows(
            np.array([-self.dT_max] + [-self.tau_max] * 3),
            np.array([self.dT_max] + [self.tau_max] * 3))

        # Prestabilizing LQR gain: condensing the (unstable) 12-state
        # linearization open-loop over N=10 grows H entries with powers
        # of A (cond(H) ~ 3e8 -- stalls fixed-iteration IPMs and makes
        # the f32 phase of the mixed schedule useless); condensing the
        # closed loop u = Kx + v keeps H near the weight scale.  Exact
        # substitution: same value function and applied inputs
        # (tests/test_problems.py equivalence test).  K_pre is derived
        # from the SAME DARE solution P used as the terminal cost above
        # -- that pairing is load-bearing: it is the completion-of-
        # squares identity that makes the condensed Hessian essentially
        # diagonal (scaled cond ~1.0, docs/perf.md).
        K_pre = -np.linalg.solve(R + Bd.T @ P @ Bd, Bd.T @ P @ Ad)

        slices, deltas = [], []
        for f0 in range(4):
            for f1 in range(4):
                rows, offs = [], []
                for (face, ((cxy), w)) in zip(
                        (f0, f1), self.obstacles):
                    sgn, ax = _FACES[face]
                    # stay clear of face: sgn * p_ax >= sgn * c_ax + w
                    # <=>  -sgn * p_ax <= -(sgn * c_ax + w)
                    row = np.zeros(12)
                    row[ax] = -sgn
                    rows.append(row)
                    offs.append(-(sgn * cxy[ax] + w))
                C_obs = np.stack(rows)
                c_obs = np.asarray(offs, dtype=np.float64)
                Call = np.vstack([Cx, C_obs])
                call = np.concatenate([cx, c_obs])
                sl = base.condense(
                    A_seq=[Ad] * N, B_seq=[Bd] * N,
                    e_seq=[np.zeros(12)] * N,
                    Q=Q, R=R, P=P, E=E, x_nom=np.zeros(12), n_u=4,
                    state_con=[(Call, call)] * N,
                    input_con=[(Cu, cu)] * N, K_prestab=K_pre)
                # Obstacle rows are the trailing 2 rows of each step's
                # 26-row state block.  Hard avoidance makes the feasible
                # set's boundary a dynamics-dependent surface slightly off
                # the obstacle faces -- simplices straddling it never
                # certify; the quadratic penalty (exact enough at rho for
                # the benchmark's clearances) keeps V* continuous on all
                # of Theta (see base.soften).
                nrow = Call.shape[0]
                obs_rows = np.concatenate(
                    [k * nrow + np.arange(Cx.shape[0], nrow)
                     for k in range(N)])
                slices.append(base.soften(sl, obs_rows, rho=self.obs_rho))
                # Report as the 8-bit one-hot integer encoding.
                bits = np.zeros(8, dtype=np.int64)
                bits[f0] = 1
                bits[4 + f1] = 1
                deltas.append(bits)
        return base.stack_slices(slices, deltas=np.stack(deltas))
