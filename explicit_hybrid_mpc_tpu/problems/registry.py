"""Benchmark problem registry (the reference dispatches examples by CLI name;
SURVEY.md section 3 "CLI / entry", [M-med])."""

from __future__ import annotations

import importlib

_REGISTRY: dict[str, type] = {}

_MODULES = ("double_integrator", "mass_spring", "inverted_pendulum",
            "satellite", "satellite_soc", "quadrotor")


def register(cls):
    _REGISTRY[cls.name] = cls
    return cls


def _load_all() -> None:
    for mod in _MODULES:
        full = f"explicit_hybrid_mpc_tpu.problems.{mod}"
        # Skip not-yet-implemented modules, but surface real import errors
        # from modules that do exist.
        if importlib.util.find_spec(full) is not None:
            importlib.import_module(full)


def make(name: str, **kwargs):
    _load_all()
    if name not in _REGISTRY:
        raise KeyError(f"unknown problem {name!r}; have {sorted(_REGISTRY)}")
    return _REGISTRY[name](**kwargs)


def names() -> list[str]:
    _load_all()
    return sorted(_REGISTRY)
