"""Hybrid MPC problem canonicalization.

The reference builds cvxpy programs once per process and re-solves them with
new parameter values in the hot loop (SURVEY.md section 4.4, [M-med]).  The
TPU-native analogue canonicalizes ONCE on the host to dense matrices; the
device kernel then consumes only parameter vectors.  Concretely, every
problem is reduced to a *family of multiparametric QPs indexed by the integer
commutation delta*:

    V_delta(theta) = min_z  1/2 z'H z + (f + F theta)'z
                            + 1/2 theta'Y theta + p'theta + c
                     s.t.   G z <= w + S theta

with one matrix slice per delta, stacked along axis 0 so a single vmapped
interior-point kernel solves (points x commutations) in one shot
(BASELINE.json north-star: enumeration over the finite commutation set
replaces Gurobi's branch-and-bound -- sound because every benchmark's delta
set is finite and enumerable, SURVEY.md section 8 layer 2).

The MICP value function is V*(theta) = min_delta V_delta(theta); its
eps-suboptimal PWA approximation is what the partitioner builds.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass(frozen=True)
class CanonicalMPQP:
    """Stacked per-commutation mp-QP data (all float64 numpy, host-resident).

    Shapes: n_delta commutations, nz decision vars, nc constraint rows
    (padded to a common count across commutations with vacuous rows
    0'z <= 1), n_theta parameters.
    """

    H: np.ndarray      # (nd, nz, nz) PD Hessian
    f: np.ndarray      # (nd, nz)
    F: np.ndarray      # (nd, nz, n_theta)
    G: np.ndarray      # (nd, nc, nz)
    w: np.ndarray      # (nd, nc)
    S: np.ndarray      # (nd, nc, n_theta)
    Y: np.ndarray      # (nd, n_theta, n_theta) theta-quadratic cost term
    pvec: np.ndarray   # (nd, n_theta)  theta-linear cost term
    cconst: np.ndarray  # (nd,) constant cost term
    # First applied control move: u0 = u_map[d] @ z + u_theta[d] @ theta
    # + u_const[d].  The affine theta part is nonzero only under
    # prestabilized condensing (z holds v, u = K x + v).
    u_map: np.ndarray  # (nd, n_u, nz)
    u_theta: np.ndarray  # (nd, n_u, n_theta)
    u_const: np.ndarray  # (nd, n_u)
    deltas: np.ndarray  # (nd, m) integer encodings, for reporting/tie-breaks

    @property
    def n_delta(self) -> int:
        return self.H.shape[0]

    @property
    def nz(self) -> int:
        return self.H.shape[1]

    @property
    def nc(self) -> int:
        return self.G.shape[1]

    @property
    def n_theta(self) -> int:
        return self.F.shape[2]

    @property
    def n_u(self) -> int:
        return self.u_map.shape[1]

    def value(self, d: int, theta: np.ndarray, z: np.ndarray) -> float:
        """Objective of commutation d at (theta, z) -- for tests/checks."""
        th = np.asarray(theta, dtype=np.float64)
        return float(
            0.5 * z @ self.H[d] @ z + (self.f[d] + self.F[d] @ th) @ z
            + 0.5 * th @ self.Y[d] @ th + self.pvec[d] @ th + self.cconst[d]
        )


@dataclasses.dataclass(frozen=True)
class CondensedSlice:
    """One commutation's canonical matrices before stacking/padding."""

    H: np.ndarray
    f: np.ndarray
    F: np.ndarray
    G: np.ndarray
    w: np.ndarray
    S: np.ndarray
    Y: np.ndarray
    pvec: np.ndarray
    cconst: float
    u_map: np.ndarray
    # Affine-in-theta part of u0 (prestabilized condensing; zero otherwise).
    u_theta: np.ndarray | None = None
    u_const: np.ndarray | None = None


def condense(
    A_seq: Sequence[np.ndarray],
    B_seq: Sequence[np.ndarray],
    e_seq: Sequence[np.ndarray],
    Q: np.ndarray,
    R: np.ndarray,
    P: np.ndarray,
    E: np.ndarray,
    x_nom: np.ndarray,
    n_u: int,
    state_con: Optional[Sequence[tuple[np.ndarray, np.ndarray]]] = None,
    input_con: Optional[Sequence[tuple[np.ndarray, np.ndarray]]] = None,
    theta_con: Optional[tuple[np.ndarray, np.ndarray]] = None,
    u_selector: Optional[np.ndarray] = None,
    K_prestab: Optional[np.ndarray] = None,
) -> CondensedSlice:
    """Condense one fixed-commutation linear MPC into an mp-QP slice.

    Dynamics (commutation-dependent, time-varying):
        x_{k+1} = A_k x_k + B_k u_k + e_k,   k = 0..N-1,
        x_0 = x_nom + E theta                 (theta embeds into the state).
    Cost: sum_k 1/2 x_k'Q x_k + 1/2 u_k'R u_k  (k=0..N-1)  + 1/2 x_N'P x_N.
    Constraints:
        state_con[k] = (Cx, cx): Cx x_{k+1} <= cx  for step k (on x_1..x_N),
        input_con[k] = (Cu, cu): Cu u_k <= cu,
        theta_con = (Ct, ct):    Ct theta <= ct  (pure parameter rows, e.g.
                                 mode-region membership of x_0).
    The decision vector is z = [u_0; ...; u_{N-1}].  u_selector (n_u x n_u,
    default identity) maps z's first block to the physically applied input
    (e.g. zeroing thrusters that this commutation switches off).

    Returns the slice of V_delta(theta) = min_z 1/2 z'Hz + (f+F theta)'z
    + theta-terms s.t. Gz <= w + S theta, with the theta-only cost terms kept
    so that value functions are comparable ACROSS commutations (required by
    the eps-suboptimality certificates, SURVEY.md section 8 "certificate
    math").

    K_prestab: optional (m, n_x) feedback gain for CLOSED-LOOP condensing
    (u_k = K x_k + v_k; the decision vector becomes v).  An EXACT variable
    substitution -- same value function, same applied inputs -- whose
    point is conditioning: condensing an unstable plant open-loop grows
    H entries with powers of A (quadrotor: cond(H) ~ 3e8), while the
    prestabilized A + BK keeps H near the weight scale so the f32-bulk
    mixed IPM schedule stays usable on TPU.  Constraint row ORDER matches
    the open-loop path exactly (soften() indexes rows by position).
    """
    if K_prestab is not None:
        return _condense_prestab(
            A_seq, B_seq, e_seq, Q, R, P, E, x_nom, n_u,
            np.asarray(K_prestab, dtype=np.float64),
            state_con, input_con, theta_con, u_selector)
    N = len(A_seq)
    n_x = A_seq[0].shape[0]
    m = B_seq[0].shape[1]
    nz = N * m
    E = np.asarray(E, dtype=np.float64)
    n_theta = E.shape[1]
    x_nom = np.asarray(x_nom, dtype=np.float64)

    # Prediction matrices: X = Phi x0 + Gam z + phi with X = [x_1..x_N].
    Phi = np.zeros((N * n_x, n_x))
    Gam = np.zeros((N * n_x, nz))
    phi = np.zeros(N * n_x)
    for k in range(N):
        rows = slice(k * n_x, (k + 1) * n_x)
        if k == 0:
            Phi[rows] = A_seq[0]
            phi[rows] = e_seq[0]
        else:
            prev = slice((k - 1) * n_x, k * n_x)
            Phi[rows] = A_seq[k] @ Phi[prev]
            phi[rows] = A_seq[k] @ phi[prev] + e_seq[k]
            Gam[rows] = A_seq[k] @ Gam[prev]
        Gam[rows, k * m:(k + 1) * m] = B_seq[k]

    # Block cost weights over X and z.
    Qbar = np.zeros((N * n_x, N * n_x))
    for k in range(N - 1):
        Qbar[k * n_x:(k + 1) * n_x, k * n_x:(k + 1) * n_x] = Q
    Qbar[(N - 1) * n_x:, (N - 1) * n_x:] = P
    Rbar = np.kron(np.eye(N), R)

    H = Gam.T @ Qbar @ Gam + Rbar
    H = 0.5 * (H + H.T)

    # Linear-in-z term: (Phi x0 + phi)'Qbar Gam z with x0 = x_nom + E theta.
    F = Gam.T @ Qbar @ Phi @ E                      # (nz, n_theta)
    f = Gam.T @ Qbar @ (Phi @ x_nom + phi)

    # theta-only cost: 1/2 (Phi x0 + phi)'Qbar(Phi x0 + phi) + 1/2 x0'Q x0.
    Q0 = Phi.T @ Qbar @ Phi + Q
    Y = E.T @ Q0 @ E
    Y = 0.5 * (Y + Y.T)
    g0 = Phi.T @ Qbar @ phi
    pvec = E.T @ (Q0 @ x_nom + g0)
    cconst = float(0.5 * x_nom @ Q0 @ x_nom + x_nom @ g0
                   + 0.5 * phi @ Qbar @ phi)

    # Constraints.
    G_rows, w_rows, S_rows = [], [], []
    if state_con is not None:
        for k, con in enumerate(state_con):
            if con is None:
                continue
            Cx, cx = con
            rows = slice(k * n_x, (k + 1) * n_x)
            G_rows.append(Cx @ Gam[rows])
            w_rows.append(cx - Cx @ (Phi[rows] @ x_nom + phi[rows]))
            S_rows.append(-Cx @ Phi[rows] @ E)
    if input_con is not None:
        for k, con in enumerate(input_con):
            if con is None:
                continue
            Cu, cu = con
            Gk = np.zeros((Cu.shape[0], nz))
            Gk[:, k * m:(k + 1) * m] = Cu
            G_rows.append(Gk)
            w_rows.append(np.asarray(cu, dtype=np.float64))
            S_rows.append(np.zeros((Cu.shape[0], n_theta)))
    if theta_con is not None:
        Ct, ct = theta_con
        G_rows.append(np.zeros((Ct.shape[0], nz)))
        w_rows.append(np.asarray(ct, dtype=np.float64))
        S_rows.append(-np.asarray(Ct, dtype=np.float64))

    G = np.vstack(G_rows) if G_rows else np.zeros((0, nz))
    w = np.concatenate(w_rows) if w_rows else np.zeros(0)
    S = np.vstack(S_rows) if S_rows else np.zeros((0, n_theta))

    sel = np.eye(n_u, m) if u_selector is None else np.asarray(u_selector)
    if sel.shape != (n_u, m):
        raise ValueError(f"u_selector must be ({n_u}, {m}), got {sel.shape}")
    u_map = np.zeros((n_u, nz))
    u_map[:, :m] = sel
    return CondensedSlice(H=H, f=f, F=F, G=G, w=w, S=S, Y=Y, pvec=pvec,
                          cconst=cconst, u_map=u_map)


def _condense_prestab(A_seq, B_seq, e_seq, Q, R, P, E, x_nom, n_u, K,
                      state_con, input_con, theta_con,
                      u_selector) -> CondensedSlice:
    """Closed-loop condensing: substitute u_k = K x_k + v_k and condense
    in v.  Derivation (stage cost with the substitution):

        1/2 x'Qx + 1/2 u'Ru = 1/2 x'(Q + K'RK)x + x'K'R v + 1/2 v'Rv

    so with X0 = [x_0..x_{N-1}] (affine in (x0, v) through the CLOSED-
    LOOP prediction matrices) the objective is quadratic in v with a
    cross term X0' blkdiag(K'R) v; x_N carries the terminal P.  Exactness
    is tested against the open-loop path (tests/test_problems.py)."""
    N = len(A_seq)
    n_x = A_seq[0].shape[0]
    m = B_seq[0].shape[1]
    nz = N * m
    E = np.asarray(E, dtype=np.float64)
    n_theta = E.shape[1]
    x_nom = np.asarray(x_nom, dtype=np.float64)

    Acl = [np.asarray(A_seq[k]) + np.asarray(B_seq[k]) @ K
           for k in range(N)]
    # Closed-loop prediction: X = Phi x0 + Gam v + phi, X = [x_1..x_N].
    Phi = np.zeros((N * n_x, n_x))
    Gam = np.zeros((N * n_x, nz))
    phi = np.zeros(N * n_x)
    for k in range(N):
        rows = slice(k * n_x, (k + 1) * n_x)
        if k == 0:
            Phi[rows] = Acl[0]
            phi[rows] = e_seq[0]
        else:
            prev = slice((k - 1) * n_x, k * n_x)
            Phi[rows] = Acl[k] @ Phi[prev]
            phi[rows] = Acl[k] @ phi[prev] + e_seq[k]
            Gam[rows] = Acl[k] @ Gam[prev]
        Gam[rows, k * m:(k + 1) * m] = B_seq[k]

    # X0 = [x_0..x_{N-1}] map (x_0 is affine in theta, not part of X).
    Phi0 = np.vstack([np.eye(n_x), Phi[:(N - 1) * n_x]])
    Gam0 = np.vstack([np.zeros((n_x, nz)), Gam[:(N - 1) * n_x]])
    phi0 = np.concatenate([np.zeros(n_x), phi[:(N - 1) * n_x]])
    PhiN = Phi[(N - 1) * n_x:]
    GamN = Gam[(N - 1) * n_x:]
    phiN = phi[(N - 1) * n_x:]

    Qk = Q + K.T @ R @ K
    Qt = np.kron(np.eye(N), Qk)
    Cross = np.kron(np.eye(N), K.T @ R)      # (N n_x, N m)
    Rbar = np.kron(np.eye(N), R)

    H = (Gam0.T @ Qt @ Gam0 + Gam0.T @ Cross + Cross.T @ Gam0 + Rbar
         + GamN.T @ P @ GamN)
    H = 0.5 * (H + H.T)
    Fx0 = Gam0.T @ Qt @ Phi0 + Cross.T @ Phi0 + GamN.T @ P @ PhiN
    F = Fx0 @ E
    f = (Fx0 @ x_nom + Gam0.T @ Qt @ phi0 + Cross.T @ phi0
         + GamN.T @ P @ phiN)

    Q0 = Phi0.T @ Qt @ Phi0 + PhiN.T @ P @ PhiN
    g0 = Phi0.T @ Qt @ phi0 + PhiN.T @ P @ phiN
    Y = E.T @ Q0 @ E
    Y = 0.5 * (Y + Y.T)
    pvec = E.T @ (Q0 @ x_nom + g0)
    cconst = float(0.5 * x_nom @ Q0 @ x_nom + x_nom @ g0
                   + 0.5 * phi0 @ Qt @ phi0 + 0.5 * phiN @ P @ phiN)

    # Constraints -- SAME row order as the open-loop path.
    G_rows, w_rows, S_rows = [], [], []
    if state_con is not None:
        for k, con in enumerate(state_con):
            if con is None:
                continue
            Cx, cx = con
            rows = slice(k * n_x, (k + 1) * n_x)
            G_rows.append(Cx @ Gam[rows])
            w_rows.append(cx - Cx @ (Phi[rows] @ x_nom + phi[rows]))
            S_rows.append(-Cx @ Phi[rows] @ E)
    if input_con is not None:
        for k, con in enumerate(input_con):
            if con is None:
                continue
            Cu, cu = con
            # u_k = K x_k + v_k with x_k affine in (x0, v).
            if k == 0:
                xk_Phi, xk_Gam, xk_phi = (np.eye(n_x),
                                          np.zeros((n_x, nz)),
                                          np.zeros(n_x))
            else:
                rs = slice((k - 1) * n_x, k * n_x)
                xk_Phi, xk_Gam, xk_phi = Phi[rs], Gam[rs], phi[rs]
            CuK = Cu @ K
            Gk = CuK @ xk_Gam
            Gk[:, k * m:(k + 1) * m] += Cu
            G_rows.append(Gk)
            w_rows.append(np.asarray(cu, dtype=np.float64)
                          - CuK @ (xk_Phi @ x_nom + xk_phi))
            S_rows.append(-CuK @ xk_Phi @ E)
    if theta_con is not None:
        Ct, ct = theta_con
        G_rows.append(np.zeros((Ct.shape[0], nz)))
        w_rows.append(np.asarray(ct, dtype=np.float64))
        S_rows.append(-np.asarray(Ct, dtype=np.float64))

    G = np.vstack(G_rows) if G_rows else np.zeros((0, nz))
    w = np.concatenate(w_rows) if w_rows else np.zeros(0)
    S = np.vstack(S_rows) if S_rows else np.zeros((0, n_theta))

    sel = np.eye(n_u, m) if u_selector is None else np.asarray(u_selector)
    if sel.shape != (n_u, m):
        raise ValueError(f"u_selector must be ({n_u}, {m}), got {sel.shape}")
    u_map = np.zeros((n_u, nz))
    u_map[:, :m] = sel
    selK = sel @ K
    return CondensedSlice(H=H, f=f, F=F, G=G, w=w, S=S, Y=Y, pvec=pvec,
                          cconst=cconst, u_map=u_map,
                          u_theta=selK @ E, u_const=selK @ x_nom)


def soften(sl: CondensedSlice, rows: np.ndarray,
           rho: float = 1e3) -> CondensedSlice:
    """Soften the given constraint rows with quadratic-penalty slacks.

    Each row i in `rows` becomes  G_i z - s_i <= w_i + S_i theta,  s_i >= 0,
    with rho/2 * s_i^2 added to the cost.  Use on constraints whose hard
    version would make the feasible parameter set's boundary cut through
    Theta along a dynamics-dependent (curved) surface: simplices straddling
    such a surface can never certify and subdivide to the depth cap,
    whereas the softened V_delta is finite and continuous on ALL of Theta
    and the eps-certificate closes at finite depth.
    """
    rows = np.asarray(rows, dtype=np.int64)
    nz = sl.H.shape[0]
    ns = len(rows)
    nt = sl.F.shape[1]
    nc = sl.G.shape[0]
    H = np.block([[sl.H, np.zeros((nz, ns))],
                  [np.zeros((ns, nz)), rho * np.eye(ns)]])
    f = np.concatenate([sl.f, np.zeros(ns)])
    F = np.vstack([sl.F, np.zeros((ns, nt))])
    sel = np.zeros((nc, ns))
    sel[rows, np.arange(ns)] = 1.0
    G = np.block([[sl.G, -sel],
                  [np.zeros((ns, nz)), -np.eye(ns)]])
    w = np.concatenate([sl.w, np.zeros(ns)])
    S = np.vstack([sl.S, np.zeros((ns, nt))])
    u_map = np.hstack([sl.u_map, np.zeros((sl.u_map.shape[0], ns))])
    return CondensedSlice(H=H, f=f, F=F, G=G, w=w, S=S, Y=sl.Y,
                          pvec=sl.pvec, cconst=sl.cconst, u_map=u_map,
                          u_theta=sl.u_theta, u_const=sl.u_const)


def stack_slices(slices: Sequence[CondensedSlice],
                 deltas: np.ndarray) -> CanonicalMPQP:
    """Stack per-commutation slices, padding constraint rows to a common
    count with vacuous rows 0'z <= 1 (static shapes for vmap over delta).

    At least one row is always kept: the IPM kernel's reductions over the
    constraint axis require nc >= 1, and a vacuous row solves the
    unconstrained problem exactly."""
    nc = max(1, max(s.G.shape[0] for s in slices))
    nz = slices[0].H.shape[0]
    n_theta = slices[0].F.shape[1]

    def pad(s: CondensedSlice):
        k = nc - s.G.shape[0]
        G = np.vstack([s.G, np.zeros((k, nz))])
        w = np.concatenate([s.w, np.ones(k)])
        S = np.vstack([s.S, np.zeros((k, n_theta))])
        return G, w, S

    padded = [pad(s) for s in slices]
    n_u = slices[0].u_map.shape[0]
    return CanonicalMPQP(
        H=np.stack([s.H for s in slices]),
        f=np.stack([s.f for s in slices]),
        F=np.stack([s.F for s in slices]),
        G=np.stack([g for g, _, _ in padded]),
        w=np.stack([w for _, w, _ in padded]),
        S=np.stack([s for _, _, s in padded]),
        Y=np.stack([s.Y for s in slices]),
        pvec=np.stack([s.pvec for s in slices]),
        cconst=np.array([s.cconst for s in slices]),
        u_map=np.stack([s.u_map for s in slices]),
        u_theta=np.stack([s.u_theta if s.u_theta is not None
                          else np.zeros((n_u, n_theta)) for s in slices]),
        u_const=np.stack([s.u_const if s.u_const is not None
                          else np.zeros(n_u) for s in slices]),
        deltas=np.asarray(deltas),
    )


def box_rows(lb: np.ndarray, ub: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(C, c) with C v <= c encoding lb <= v <= ub."""
    n = len(lb)
    C = np.vstack([np.eye(n), -np.eye(n)])
    c = np.concatenate([ub, -np.asarray(lb, dtype=np.float64)])
    return C, c


def zoh(Ac: np.ndarray, Bc: np.ndarray, dt: float) -> tuple[np.ndarray, np.ndarray]:
    """Zero-order-hold discretization (the reference discretizes its plants
    with ZOH; SURVEY.md section 3 "Problem library", [M-med])."""
    import scipy.linalg

    n, m = Bc.shape
    M = np.zeros((n + m, n + m))
    M[:n, :n] = Ac
    M[:n, n:] = Bc
    eM = scipy.linalg.expm(M * dt)
    return eM[:n, :n], eM[:n, n:]


class HybridMPC:
    """Base class for benchmark problems (the reference's `MPC` base class
    role, SURVEY.md section 3 "Problem library" -- UNVERIFIED naming).

    Subclasses define the parameter box (the partitioned set Theta), the
    commutation enumeration, and build_canonical().
    """

    name: str = "base"
    theta_lb: np.ndarray
    theta_ub: np.ndarray
    n_u: int
    # Axis-aligned hyperplanes (axis -> coordinate values) that the ROOT
    # triangulation must align with: any fixed theta-hyperplane across
    # which commutation feasibility flips (e.g. PWA mode membership of
    # x_0) must land on root cell faces or cells straddling it can never
    # certify (see geometry.box_triangulation).  None = no splits;
    # subclasses ASSIGN a fresh dict (a mutable class-level default would
    # be shared across every problem).
    root_splits = None

    @property
    def n_theta(self) -> int:
        return int(self.theta_lb.size)

    # Stage-cost weights for closed-loop evaluation (sim/), set by
    # subclasses alongside their canonical cost.  Shapes (n_x, n_x) and
    # (n_u, n_u) in APPLIED-input coordinates.
    Qc: np.ndarray
    Rc: np.ndarray

    def plant_step(self, x: np.ndarray, u: np.ndarray) -> np.ndarray:
        """True plant update x+ = f(x, u) with the APPLIED input u (what
        the online controller emits).  Used by the closed-loop simulator
        (SURVEY.md section 4.3); the default raises so prediction-only
        problems fail loudly."""
        raise NotImplementedError(f"{self.name} defines no plant")

    def theta_of_state(self, x: np.ndarray) -> np.ndarray:
        """Partition parameter for plant state x (identity when the
        parameter IS the state; slice problems override)."""
        return np.asarray(x, dtype=np.float64)

    def state_of_theta(self, theta: np.ndarray) -> np.ndarray:
        """Initial plant state for parameter theta (identity default)."""
        return np.asarray(theta, dtype=np.float64)

    def stage_cost(self, x: np.ndarray, u: np.ndarray) -> float:
        return float(0.5 * x @ self.Qc @ x + 0.5 * u @ self.Rc @ u)

    @functools.cached_property
    def canonical(self) -> CanonicalMPQP:
        can = self.build_canonical()
        for d in range(can.n_delta):
            eig = np.linalg.eigvalsh(can.H[d])
            if eig.min() <= 0:
                raise ValueError(
                    f"{self.name}: H[{d}] not PD (min eig {eig.min():.3e}); "
                    "add input regularization R > 0")
        return can

    def build_canonical(self) -> CanonicalMPQP:
        raise NotImplementedError
