"""Satellite desaturation with a TRUE second-order-cone wheel envelope.

The reference's problem class is mixed-integer QP/SOCP (SURVEY.md
section 1 [P]); every driver benchmark is QP-representable, so this
config exists to exercise the cone path end to end (round-3 verdict
item 9): the three-axis satellite (problems/satellite.py) with the
box constraint on the transverse wheel torques replaced by the physical
circular envelope of a two-axis gimballed wheel assembly:

    || (u_w,x(k), u_w,y(k)) ||_2 <= r      for every horizon step k

-- one 3-dim second-order cone per step, identical across commutations
(the thruster integer structure is untouched).  The box rows from the
base class remain (ball subset box: redundant but sound); the cone is
what binds on diagonal-torque maneuvers.

Scope: point MICP queries, online fixed-commutation solves, and
closed-loop simulation run through oracle.soc_point.SOCPointOracle; the
partition certificates stay QP-only -- the recorded scoping decision and
what lifting it would take are in docs/socp_scope.md.
"""

from __future__ import annotations

import numpy as np

from explicit_hybrid_mpc_tpu.problems.registry import register
from explicit_hybrid_mpc_tpu.problems.satellite import Satellite


@register
class SatelliteSOC(Satellite):
    name = "satellite_soc"

    def __init__(self, soc_radius: float | None = None, **kw):
        kw.setdefault("axes", 3)
        if kw["axes"] != 3:
            raise ValueError("satellite_soc needs axes=3 (the cone "
                             "couples the two transverse wheel channels)")
        super().__init__(**kw)
        # Default: the cone circumscribes nothing new (radius = box
        # half-width) -- it strictly tightens the corners of the
        # (u_w,x, u_w,y) box, which is where it binds.
        self.soc_radius = float(soc_radius if soc_radius is not None
                                else self.u_w_max)
        if self.soc_radius <= 0:
            raise ValueError("soc_radius must be > 0")

    def soc_cones(self) -> tuple[np.ndarray, np.ndarray]:
        """(Ac, bc) with Ac (K, 3, nz), bc (K, 3), K = N cones: per step
        k, s = bc_k - Ac_k z = (r, u_w,x(k), u_w,y(k)) in SOC_3.

        Identical for every commutation: the wheel channels occupy the
        same z slots in each delta slice (satellite.build_canonical
        orders z as N blocks of (u_w (3), m (3)))."""
        N, nz = self.N, self.canonical.nz
        n_u = 6  # per-step input block: 3 wheel torques + 3 magnitudes
        Ac = np.zeros((N, 3, nz))
        bc = np.zeros((N, 3))
        for k in range(N):
            bc[k, 0] = self.soc_radius
            Ac[k, 1, k * n_u + 0] = -1.0   # s1 = u_w,x(k)
            Ac[k, 2, k * n_u + 1] = -1.0   # s2 = u_w,y(k)
        return Ac, bc
