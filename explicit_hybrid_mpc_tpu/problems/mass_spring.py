"""Config 2: constrained LQR mp-QP on a 4-state mass-spring chain, N=10 --
BASELINE.md row 2.  Two masses coupled by springs, one force input on the
first mass; tight input bounds make the constrained region structure
non-trivial.  Pure mp-QP (single commutation).
"""

from __future__ import annotations

import functools

import numpy as np

from explicit_hybrid_mpc_tpu.problems import base
from explicit_hybrid_mpc_tpu.problems.registry import register


@register
class MassSpring(base.HybridMPC):
    name = "mass_spring"

    def __init__(self, N: int = 10, dt: float = 0.2, theta_box: float = 2.0,
                 u_max: float = 0.5, x_max: float = 4.0):
        self.N = N
        self.dt = dt
        self.u_max = u_max
        self.x_max = x_max
        self.theta_lb = -theta_box * np.ones(4)
        self.theta_ub = theta_box * np.ones(4)
        self.n_u = 1
        self.Qc = np.diag([1.0, 0.1, 1.0, 0.1])
        self.Rc = np.array([[0.5]])

    @staticmethod
    def _continuous():
        # Two unit masses, springs k=1 wall-m1-m2, light damping.
        k, c = 1.0, 0.1
        Ac = np.array([
            [0.0, 1.0, 0.0, 0.0],
            [-2 * k, -c, k, 0.0],
            [0.0, 0.0, 0.0, 1.0],
            [k, 0.0, -k, -c],
        ])
        Bc = np.array([[0.0], [1.0], [0.0], [0.0]])
        return Ac, Bc

    @functools.cache
    def _plant(self):
        return base.zoh(*self._continuous(), self.dt)

    def plant_step(self, x, u):
        A, B = self._plant()
        return A @ x + B @ u

    def build_canonical(self) -> base.CanonicalMPQP:
        Ac, Bc = self._continuous()
        A, B = base.zoh(Ac, Bc, self.dt)
        N = self.N
        Q = np.diag([1.0, 0.1, 1.0, 0.1])
        R = np.array([[0.5]])
        import scipy.linalg

        P = np.asarray(scipy.linalg.solve_discrete_are(A, B, Q, R))
        Cx, cx = base.box_rows(-self.x_max * np.ones(4), self.x_max * np.ones(4))
        Cu, cu = base.box_rows(np.array([-self.u_max]), np.array([self.u_max]))
        sl = base.condense(
            A_seq=[A] * N, B_seq=[B] * N, e_seq=[np.zeros(4)] * N,
            Q=Q, R=R, P=P, E=np.eye(4), x_nom=np.zeros(4), n_u=1,
            state_con=[(Cx, cx)] * N, input_con=[(Cu, cu)] * N,
        )
        return base.stack_slices([sl], deltas=np.zeros((1, 0), dtype=np.int64))
