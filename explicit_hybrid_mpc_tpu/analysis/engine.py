"""The tpulint rule engine: AST visitors, findings, pragmas, baseline.

Design notes:

- **Jit-region index.**  Most rules only fire INSIDE code that jax will
  trace (a host sync in plain host code is just numpy).  The engine
  computes, once per file, the set of function nodes reachable from a
  jit entry point: functions decorated with ``@jax.jit`` / ``@vmap`` /
  ``@shard_map`` (including through ``functools.partial``), lambdas and
  named functions passed to those wrappers or into body positions of
  ``lax.fori_loop`` / ``scan`` / ``while_loop`` / ``cond``, plus a
  same-module transitive closure over simple-name calls (a helper
  called from a jitted lambda is traced too).  The closure is
  name-based and module-local -- deliberately: cross-module dataflow
  would need real type inference, and the kernels this repo cares
  about (oracle/ipm.py, online/) keep their traced helpers in-module.
- **Pragmas.**  ``# tpulint: disable=<rule>[,<rule>...]`` trailing a
  code line suppresses those rules on that line; the same pragma on a
  comment-only line suppresses them for the whole file.  Anything
  after the rule list (``-- reason``) is the human justification the
  review policy requires.  ``disable=all`` suppresses every rule.
  ``# tpulint: x32-module`` on a comment-only line tags the file as an
  f32 kernel module for the dtype-discipline rule.
- **Baseline.**  ``TPULINT_BASELINE.json`` holds a multiset of
  (file, rule, stripped-source-line) keys: legacy findings matched by
  CONTENT, not line number, so unrelated edits do not resurrect them,
  while genuinely new findings always gate.  ``scripts/tpulint.py
  --update-baseline`` rewrites it from the current findings.

The module is pure ``ast`` + stdlib (no jax/numpy): see the package
docstring.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from collections import Counter
from typing import Iterable, Iterator, Optional

BASELINE_VERSION = 1

SEVERITIES = ("error", "warn")

#: wrappers whose FIRST argument (or decorated function) is traced.
#: pallas_call: a Pallas kernel body is traced (then Mosaic-lowered or
#: interpret-executed) exactly like a jitted function, so host-sync /
#: dtype / obs-in-hot-loop rules must cover kernel bodies too
#: (oracle/pallas_ipm.py, online/pallas_eval.py).
_JIT_WRAPPERS = {"jit", "vmap", "pmap", "shard_map", "named_call",
                 "pallas_call"}
#: control-flow combinators -> indices of their traced function args.
_BODY_WRAPPERS = {
    "fori_loop": (2,),
    "while_loop": (0, 1),
    "scan": (0,),
    "cond": (1, 2),
    "switch": (),          # branches arrive as a list; handled inline
    "map": (0,),
    "associative_scan": (0,),
}

_PRAGMA = re.compile(r"#\s*tpulint:\s*(disable|x32-module)\b\s*(?:=\s*(.*))?")


def _pragma_rules(raw: Optional[str]) -> set[str]:
    """Rule ids from a pragma value, tolerating a trailing freeform
    justification after each id (``disable=silent-except -- why``)."""
    out = set()
    for tok in (raw or "").split(","):
        tok = tok.strip()
        if tok:
            out.add(tok.split()[0])
    return out


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to file:line:col.

    ``code`` is the stripped source line -- the content-addressed key
    the baseline matches on (line numbers churn; code lines rarely do
    without the finding itself changing)."""

    rule: str
    severity: str
    path: str
    line: int
    col: int
    msg: str
    code: str

    @property
    def key(self) -> tuple[str, str, str]:
        return (self.path, self.rule, self.code)

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"[{self.rule}] {self.severity}: {self.msg}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class Rule:
    """Base class for lint rules.

    Subclasses set ``name`` / ``severity`` / ``doc`` and implement
    ``check(ctx)`` yielding findings; ``finding(ctx, node, msg)`` fills
    in the location + source-line plumbing."""

    name: str = "abstract"
    severity: str = "warn"
    doc: str = ""

    def check(self, ctx: "ModuleContext") -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: "ModuleContext", node: ast.AST, msg: str,
                severity: Optional[str] = None) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        code = (ctx.lines[line - 1].strip()
                if 0 < line <= len(ctx.lines) else "")
        return Finding(rule=self.name, severity=severity or self.severity,
                       path=ctx.rel, line=line, col=col, msg=msg,
                       code=code)


def _attr_chain(node: ast.AST) -> list[str]:
    """['jax', 'lax', 'fori_loop'] for jax.lax.fori_loop; [] when the
    expression is not a plain name/attribute chain."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return parts[::-1]
    return []


def _call_name(node: ast.AST) -> str:
    """Last segment of a call target's name chain ('' if unnameable)."""
    chain = _attr_chain(node)
    return chain[-1] if chain else ""


def _unwrap_partial(node: ast.AST) -> ast.AST:
    """functools.partial(f, ...) -> f (jit(partial(fn, ...)) traces fn)."""
    if isinstance(node, ast.Call) and _call_name(node.func) == "partial" \
            and node.args:
        return node.args[0]
    return node


class _JitIndex:
    """The per-module set of function nodes jax will trace (see module
    docstring for what is and is not covered)."""

    def __init__(self, tree: ast.Module):
        self.marked: set[ast.AST] = set()
        # Every def in the module by simple name (scope-insensitive on
        # purpose: marking one extra same-named helper costs a lint
        # false positive at worst, missing one hides a real host sync).
        self._defs: dict[str, list[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._defs.setdefault(node.name, []).append(node)
        self._seed(tree)
        self._close(tree)

    def _mark_expr(self, node: ast.AST) -> None:
        node = _unwrap_partial(node)
        if isinstance(node, ast.Lambda):
            self.marked.add(node)
        elif isinstance(node, ast.Name):
            for d in self._defs.get(node.id, ()):
                self.marked.add(d)

    def _seed(self, tree: ast.Module) -> None:
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    target = dec.func if isinstance(dec, ast.Call) else dec
                    target = _unwrap_partial(target) if isinstance(
                        dec, ast.Call) else target
                    name = _call_name(target)
                    if name in _JIT_WRAPPERS:
                        self.marked.add(node)
                    elif name == "partial" and isinstance(dec, ast.Call) \
                            and dec.args \
                            and _call_name(dec.args[0]) in _JIT_WRAPPERS:
                        # @functools.partial(jax.jit, static_argnums=..)
                        self.marked.add(node)
            elif isinstance(node, ast.Call):
                name = _call_name(node.func)
                if name in _JIT_WRAPPERS and node.args:
                    self._mark_expr(node.args[0])
                elif name in _BODY_WRAPPERS:
                    for i in _BODY_WRAPPERS[name]:
                        if i < len(node.args):
                            self._mark_expr(node.args[i])
                    if name == "switch" and len(node.args) > 1 and \
                            isinstance(node.args[1], (ast.List, ast.Tuple)):
                        for el in node.args[1].elts:
                            self._mark_expr(el)

    def _close(self, tree: ast.Module) -> None:
        """Fixpoint: helpers CALLED by simple name from a marked
        function are traced too."""
        changed = True
        while changed:
            changed = False
            for fn in list(self.marked):
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call) \
                            and isinstance(node.func, ast.Name):
                        for d in self._defs.get(node.func.id, ()):
                            if d not in self.marked:
                                self.marked.add(d)
                                changed = True


class ModuleContext:
    """Everything rules need about one file, computed once: AST, parent
    links, the jit-region index, pragma tables, and source lines."""

    def __init__(self, path: str, source: str, rel: Optional[str] = None):
        self.path = path
        self.rel = rel or path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.parents: dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self.parents[child] = parent
        self._jit = _JitIndex(self.tree)
        self.jit_funcs = self._jit.marked
        self.line_disables: dict[int, set[str]] = {}
        self.file_disables: set[str] = set()
        self.x32_module = False
        self._scan_pragmas()

    def _scan_pragmas(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _PRAGMA.search(line)
            if not m:
                continue
            comment_only = line.strip().startswith("#")
            if m.group(1) == "x32-module":
                if comment_only:
                    self.x32_module = True
                continue
            rules = _pragma_rules(m.group(2))
            if comment_only:
                self.file_disables |= rules
            else:
                self.line_disables.setdefault(i, set()).update(rules)

    # -- queries rules use -------------------------------------------------

    def in_jit(self, node: ast.AST) -> bool:
        """True when any enclosing function scope (including `node`
        itself) is jit-traced."""
        cur: Optional[ast.AST] = node
        while cur is not None:
            if cur in self.jit_funcs:
                return True
            cur = self.parents.get(cur)
        return False

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return cur
            cur = self.parents.get(cur)
        return None

    def suppressed(self, f: Finding) -> bool:
        if {"all", f.rule} & self.file_disables:
            return True
        line_rules = self.line_disables.get(f.line, set())
        return bool({"all", f.rule} & line_rules)


# -- linting entry points --------------------------------------------------

def _default_rules() -> list[Rule]:
    from explicit_hybrid_mpc_tpu.analysis.rules import all_rules

    return all_rules()


def lint_source(source: str, path: str, rules: Iterable[Rule] | None = None,
                rel: Optional[str] = None) -> list[Finding]:
    """Lint one source string; a syntax error becomes a single
    ``parse-error`` finding rather than an exception (the gate must
    report a broken file, not crash on it)."""
    rules = list(rules) if rules is not None else _default_rules()
    try:
        ctx = ModuleContext(path, source, rel=rel)
    except SyntaxError as e:
        return [Finding(rule="parse-error", severity="error",
                        path=rel or path, line=e.lineno or 1,
                        col=e.offset or 0, msg=f"cannot parse: {e.msg}",
                        code="")]
    out: list[Finding] = []
    for rule in rules:
        for f in rule.check(ctx):
            if not ctx.suppressed(f):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_paths(paths: Iterable[str], rules: Iterable[Rule] | None = None,
               root: Optional[str] = None) -> list[Finding]:
    """Lint files and/or directory trees (``*.py``); finding paths are
    recorded relative to ``root`` (default: cwd) so baseline keys stay
    stable across checkouts."""
    rules = list(rules) if rules is not None else _default_rules()
    root = os.path.abspath(root or os.getcwd())
    files: list[str] = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if not d.startswith((".", "__pycache")))
                files.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames)
                             if f.endswith(".py"))
        else:
            files.append(p)
    out: list[Finding] = []
    for fp in files:
        with open(fp, encoding="utf-8") as fh:
            src = fh.read()
        rel = os.path.relpath(os.path.abspath(fp), root)
        out.extend(lint_source(src, fp, rules, rel=rel))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


# -- baseline --------------------------------------------------------------

def baseline_payload(findings: Iterable[Finding]) -> dict:
    """The serializable baseline: a sorted multiset of finding keys."""
    counts = Counter(f.key for f in findings)
    rows = [{"file": k[0], "rule": k[1], "code": k[2], "count": n}
            for k, n in sorted(counts.items())]
    return {"version": BASELINE_VERSION, "tool": "tpulint",
            "findings": rows}


def load_baseline(path: str) -> Counter:
    """Baseline file -> Counter of (file, rule, code) keys.  A missing
    file is an empty baseline (everything gates)."""
    if not os.path.exists(path):
        return Counter()
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}; this "
            f"engine writes v{BASELINE_VERSION} -- regenerate it with "
            "scripts/tpulint.py --update-baseline")
    out: Counter = Counter()
    for row in data.get("findings", []):
        out[(row["file"], row["rule"], row["code"])] += int(
            row.get("count", 1))
    return out


def split_baselined(findings: Iterable[Finding], baseline: Counter
                    ) -> tuple[list[Finding], list[Finding]]:
    """(new, baselined): each baseline entry absolves at most `count`
    matching findings -- a key's N+1'th occurrence is NEW and gates."""
    budget = Counter(baseline)
    new: list[Finding] = []
    old: list[Finding] = []
    for f in findings:
        if budget[f.key] > 0:
            budget[f.key] -= 1
            old.append(f)
        else:
            new.append(f)
    return new, old
