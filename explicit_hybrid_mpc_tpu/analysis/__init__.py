"""tpulint: static + runtime analysis of TPU-hostile code patterns.

The failure modes that silently destroy TPU throughput -- hidden host
syncs inside jitted regions, shape/dtype churn that triggers
recompilation, observability emission in traced code -- were only
detected AFTER the fact, via the bench gate (scripts/bench_gate.py) or
the ``oracle.compiled_shapes`` gauge.  This subsystem catches them at
lint time and at run time:

- ``engine``  -- the AST rule engine: visitor framework, findings with
  file:line + rule id + severity, per-line and per-file
  ``# tpulint: disable=<rule>`` pragmas, JSON + human output, and a
  checked-in ``TPULINT_BASELINE.json`` so legacy findings do not block
  the gate while NEW ones do (scripts/tpulint.py is the CLI).
- ``rules``   -- the initial rule pack: host-sync-in-jit,
  recompile-hazard, dtype-discipline, obs-in-hot-loop, silent-except
  (catalog: docs/static_analysis.md).
- ``recompile_guard`` -- the runtime complement: a context manager
  snapshotting the oracle's compiled-shape ledger (and/or jitted
  functions' cache sizes) around a build phase, raising or emitting a
  ``health.recompile`` event on unexpected lowerings
  (cfg.recompile_guard / --recompile-guard wires it into the frontier's
  steady-state wave loop).

No module in this package imports jax or numpy at module scope: the
engine is pure-``ast`` and the guard probes duck-typed objects
(``compiled_shapes`` ledgers, jitted ``_cache_size``), so lint cost is
parse-only and the guard adds no imports to the hot loop.
"""

from explicit_hybrid_mpc_tpu.analysis.engine import (  # noqa: F401
    BASELINE_VERSION, Finding, Rule, baseline_payload, lint_paths,
    lint_source, load_baseline, split_baselined)
from explicit_hybrid_mpc_tpu.analysis.recompile_guard import (  # noqa: F401
    RecompileError, RecompileGuard)
