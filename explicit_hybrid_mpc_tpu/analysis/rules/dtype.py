"""dtype-discipline: width-ambiguous and f64-leaking dtypes.

Two sub-checks:

1. **builtin-dtype cast** (everywhere): ``x.astype(float)`` /
   ``dtype=float`` (and ``int``) resolve through Python's builtins,
   whose array width depends on the platform and the
   ``jax_enable_x64`` flag -- the same line means f64 on this repo's
   host path and f32 inside an x32 context.  Name the width:
   ``np.float64``, ``jnp.float32``, or the source array's ``.dtype``.
   (``bool`` is exempt: one width, idiomatic numpy.)
2. **f64 in x32 modules** (files tagged ``# tpulint: x32-module``):
   ``np.float64`` / ``jnp.float64`` / ``dtype='float64'`` literals in a
   module declared to hold f32 kernel code.  One f64 constant folded
   into an otherwise-f32 TPU kernel upcasts the whole expression chain
   into emulated-f64 territory (~10x per op) -- exactly the leak the
   mixed-precision schedule exists to avoid.  This repo's modules are
   f64-first by policy (IPMs need it), so no file is tagged today; the
   tag is the opt-in for future x32 kernel modules (and the fixture
   tests exercise it).
"""

from __future__ import annotations

import ast
from typing import Iterator

from explicit_hybrid_mpc_tpu.analysis.engine import (Finding, ModuleContext,
                                                     Rule, _attr_chain)

# float/int only: their array width depends on the platform and the
# x64 flag.  `bool` is deliberately NOT here -- np.bool_ has exactly
# one width, so dtype=bool is idiomatic numpy, not a hazard.
_BUILTIN_DTYPES = {"float", "int"}
_F64_NAMES = {"float64", "double"}


class DtypeDiscipline(Rule):
    name = "dtype-discipline"
    severity = "warn"
    doc = ("builtin-dtype casts (astype(float), dtype=int) whose width "
           "depends on platform/x64 flag; f64 literals in x32-tagged "
           "kernel modules")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                yield from self._check_call(ctx, node)
            if ctx.x32_module and isinstance(node, ast.Attribute) \
                    and node.attr in _F64_NAMES:
                chain = _attr_chain(node)
                yield self.finding(
                    ctx, node,
                    f"{'.'.join(chain) or node.attr} in an x32-tagged "
                    "kernel module: one f64 constant upcasts the traced "
                    "expression chain into emulated f64 on TPU")

    def _check_call(self, ctx: ModuleContext, node: ast.Call
                    ) -> Iterator[Finding]:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr == "astype" \
                and node.args:
            a = node.args[0]
            if isinstance(a, ast.Name) and a.id in _BUILTIN_DTYPES:
                yield self.finding(
                    ctx, node,
                    f".astype({a.id}) resolves through the Python "
                    "builtin: width depends on platform and the x64 "
                    "flag; name it (np.float64 / jnp.float32 / "
                    "other.dtype)")
        for kw in node.keywords:
            if kw.arg == "dtype":
                v = kw.value
                if isinstance(v, ast.Name) and v.id in _BUILTIN_DTYPES:
                    yield self.finding(
                        ctx, v,
                        f"dtype={v.id} resolves through the Python "
                        "builtin: width depends on platform and the x64 "
                        "flag; name it explicitly")
                elif ctx.x32_module and isinstance(v, ast.Constant) \
                        and isinstance(v.value, str) \
                        and v.value in _F64_NAMES:
                    yield self.finding(
                        ctx, v,
                        f"dtype='{v.value}' in an x32-tagged kernel "
                        "module leaks emulated f64 into the kernel")
