"""host-sync-in-jit: host round-trips inside jit-traced code.

``float(x)`` / ``int(x)`` / ``bool(x)`` / ``np.asarray(x)`` /
``x.item()`` on a traced value forces a device->host transfer AND a
synchronization barrier at every trace -- one stray cast in a vmapped
kernel serializes the whole dispatch wave (the exact pathology the
frontier's async dispatch/prefetch pipeline exists to avoid).  Branching
on a traced value (``if jnp.any(mask):``) is the same sync wearing
control-flow clothes, plus a ConcretizationTypeError under jit.

The rule fires only inside the jit-region index (engine docstring):
host code is free to call numpy all it likes.
"""

from __future__ import annotations

import ast
from typing import Iterator

from explicit_hybrid_mpc_tpu.analysis.engine import (Finding, ModuleContext,
                                                     Rule, _attr_chain)

#: builtins that concretize a traced value.
_HOST_CASTS = {"float", "int", "bool"}
#: numpy entry points that force a transfer when fed a tracer.
_NP_SYNC = {"asarray", "array", "copy", "ascontiguousarray"}
_NP_ROOTS = {"np", "numpy", "onp"}
#: methods that block on / concretize device values.
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
#: jnp/jax reductions whose value a branch test would concretize.
_ARRAY_ROOTS = {"jnp", "jax", "lax"}


class HostSyncInJit(Rule):
    name = "host-sync-in-jit"
    severity = "error"
    doc = ("host transfer/synchronization inside jit-traced code "
           "(float()/int()/bool()/np.asarray()/.item()/branch on a "
           "traced value)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and ctx.in_jit(node):
                yield from self._check_call(ctx, node)
            elif isinstance(node, (ast.If, ast.While)) and ctx.in_jit(node):
                yield from self._check_branch(ctx, node)

    def _check_call(self, ctx: ModuleContext, node: ast.Call
                    ) -> Iterator[Finding]:
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _HOST_CASTS and node.args:
            yield self.finding(
                ctx, node,
                f"{fn.id}() in a jit-traced region concretizes its "
                "argument (device sync per trace); keep it an array or "
                "hoist the cast to host code")
        elif isinstance(fn, ast.Attribute):
            chain = _attr_chain(fn)
            if fn.attr in _SYNC_METHODS:
                yield self.finding(
                    ctx, node,
                    f".{fn.attr}() in a jit-traced region blocks on the "
                    "device; return the array and read it after the wave")
            elif (fn.attr in _NP_SYNC and chain
                  and chain[0] in _NP_ROOTS):
                yield self.finding(
                    ctx, node,
                    f"{'.'.join(chain)}() in a jit-traced region forces "
                    "a device->host transfer; use jnp (traced) or move "
                    "the conversion outside the jitted function")

    def _check_branch(self, ctx: ModuleContext, node: ast.AST
                      ) -> Iterator[Finding]:
        test = node.test  # type: ignore[attr-defined]
        for sub in ast.walk(test):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute):
                chain = _attr_chain(sub.func)
                if chain and chain[0] in _ARRAY_ROOTS:
                    kw = "if" if isinstance(node, ast.If) else "while"
                    yield self.finding(
                        ctx, node,
                        f"`{kw}` on a traced value "
                        f"({'.'.join(chain)}(...)) in a jit region: "
                        "concretizes per trace (or raises under jit); "
                        "use jnp.where / lax.cond / a host-side mask "
                        "read after the wave")
                    return
