"""silent-except: broad exception handlers that swallow silently.

Around device dispatch, a bare ``except: pass`` eats the whole failure
taxonomy at once -- XlaRuntimeError (dead tunnel, OOM), programming
errors, KeyboardInterrupt under ``BaseException`` -- and the build
"succeeds" with a hole where a batch of solves should be.  The repo's
sanctioned patterns are narrow typed handlers that LOG and re-route
(frontier._oracle_call's CPU fallback) or diagnostics guards explicitly
annotated as must-never-break-the-build; the latter carry a tpulint
pragma with the justification inline, which doubles as reviewer
documentation.
"""

from __future__ import annotations

import ast
from typing import Iterator

from explicit_hybrid_mpc_tpu.analysis.engine import (Finding, ModuleContext,
                                                     Rule, _call_name)

_BROAD = {"Exception", "BaseException"}


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare except
        return True
    if isinstance(t, (ast.Name, ast.Attribute)):
        return _call_name(t) in _BROAD
    if isinstance(t, ast.Tuple):
        return any(_call_name(e) in _BROAD for e in t.elts)
    return False


def _is_trivial(body: list[ast.stmt]) -> bool:
    """pass / ... / continue only: nothing logged, nothing re-raised."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                     ast.Constant):
            continue  # docstring or `...`
        return False
    return True


class SilentExcept(Rule):
    name = "silent-except"
    severity = "warn"
    doc = ("broad except handler (bare / Exception / BaseException) "
           "whose body swallows silently: device failures vanish into "
           "a hole in the build")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and _is_broad(node) \
                    and _is_trivial(node.body):
                yield self.finding(
                    ctx, node,
                    "broad exception handler silently swallows -- device "
                    "failures (and Ctrl-C under BaseException) vanish; "
                    "narrow the type, log the error, or pragma it with a "
                    "justification if it guards diagnostics that must "
                    "never break the build")
