"""recompile-hazard: patterns that multiply XLA compilations.

Three sub-checks, each a pattern that turns "compile once, dispatch
thousands of times" into "compile per call":

1. **jit-in-function**: ``jax.jit(...)`` called inside a plain function
   body builds a FRESH compiled callable (and jit cache) per call --
   every invocation retraces and recompiles.  Constructors
   (``__init__`` and friends) are exempt: building a program family
   once per object is the repo's standard pattern (oracle.Oracle);
   ``functools.cache``/``lru_cache``-decorated enclosing functions are
   exempt too (the closure IS the cache).
2. **loop-varying closure**: a jit-wrapped lambda closing over a local
   that an enclosing loop rebinds -- each rebinding is a new hashable
   constant baked into the trace, so the jit cache grows with the loop
   instead of hitting.
3. **non-pow-2 bucket literal**: padding/bucket sizes feeding the
   batched solver paths (``qp_solve`` / ``solve_pairs_full`` and the
   dispatch plumbing around them) must be powers of two -- that is the
   repo-wide invariant bounding the compiled-shape set
   (Oracle.max_points_per_call, sharded._bucket).  An int literal
   bucket/pad/cap that is not a power of two silently mints a new
   compiled shape per distinct batch size.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from explicit_hybrid_mpc_tpu.analysis.engine import (Finding, ModuleContext,
                                                     Rule, _attr_chain,
                                                     _call_name)

_CTOR_NAMES = {"__init__", "__new__", "__post_init__", "__init_subclass__"}
_CACHE_DECOS = {"cache", "lru_cache", "cached_property"}
_BUCKET_NAME = re.compile(r"(bucket|pad|batch|chunk|cap)s?$", re.IGNORECASE)


def _pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


class RecompileHazard(Rule):
    name = "recompile-hazard"
    severity = "warn"
    doc = ("jit-in-function (fresh compile per call), loop-varying "
           "closures baked into traces, non-pow-2 bucket literals "
           "feeding the batched solver paths")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                name = _call_name(node.func)
                if name == "jit":
                    yield from self._check_jit_site(ctx, node)
                if name in ("jit", "vmap", "shard_map") and node.args \
                        and isinstance(node.args[0], ast.Lambda):
                    yield from self._check_closure(ctx, node.args[0])
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                yield from self._check_bucket_assign(ctx, node)
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.keyword) and node.arg \
                    and _BUCKET_NAME.search(node.arg):
                v = node.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int) \
                        and not isinstance(v.value, bool) \
                        and v.value > 2 and not _pow2(v.value):
                    yield self.finding(
                        ctx, v,
                        f"non-power-of-two literal {v.value} for "
                        f"'{node.arg}': padding buckets must be powers "
                        "of two to bound the compiled-shape set")

    # -- 1. jit built inside a per-call function ---------------------------

    def _check_jit_site(self, ctx: ModuleContext, node: ast.Call
                        ) -> Iterator[Finding]:
        fn = ctx.enclosing_function(node)
        if fn is None or isinstance(fn, ast.Lambda):
            return
        if fn.name in _CTOR_NAMES:
            return
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if _call_name(target) in _CACHE_DECOS:
                return
        yield self.finding(
            ctx, node,
            f"jax.jit(...) inside `{fn.name}` builds a fresh compiled "
            "callable (and empty jit cache) per call -- every invocation "
            "recompiles; hoist to module/constructor scope or "
            "functools.cache the builder")

    # -- 2. loop-varying closures ------------------------------------------

    def _check_closure(self, ctx: ModuleContext, lam: ast.Lambda
                       ) -> Iterator[Finding]:
        params = {a.arg for a in (lam.args.args + lam.args.kwonlyargs
                                  + lam.args.posonlyargs)}
        if lam.args.vararg:
            params.add(lam.args.vararg.arg)
        free = {n.id for n in ast.walk(lam.body)
                if isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)} - params
        fn = ctx.enclosing_function(lam)
        if fn is None or not free:
            return
        for node in ast.walk(fn):
            targets: list[ast.AST] = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AugAssign):
                targets = [node.target]
            elif isinstance(node, ast.For):
                targets = [node.target]
            for t in targets:
                for nm in ast.walk(t):
                    if isinstance(nm, ast.Name) and nm.id in free \
                            and self._in_loop(ctx, node, stop=fn):
                        yield self.finding(
                            ctx, lam,
                            f"jitted lambda closes over `{nm.id}`, which "
                            "an enclosing loop rebinds: each value is a "
                            "new trace constant, so the jit cache grows "
                            "with the loop; pass it as an argument "
                            "instead")
                        return

    @staticmethod
    def _in_loop(ctx: ModuleContext, node: ast.AST, stop: ast.AST) -> bool:
        cur = ctx.parents.get(node)
        while cur is not None and cur is not stop:
            if isinstance(cur, (ast.For, ast.While)):
                return True
            cur = ctx.parents.get(cur)
        return isinstance(node, ast.For)

    # -- 3. non-pow-2 bucket literals --------------------------------------

    def _check_bucket_assign(self, ctx: ModuleContext, node: ast.AST
                             ) -> Iterator[Finding]:
        if isinstance(node, ast.Assign):
            targets, value = node.targets, node.value
        else:  # AnnAssign
            targets = [node.target]  # type: ignore[attr-defined]
            value = node.value  # type: ignore[attr-defined]
        if not (isinstance(value, ast.Constant)
                and isinstance(value.value, int)
                and not isinstance(value.value, bool)
                and value.value > 2 and not _pow2(value.value)):
            return
        for t in targets:
            chain = _attr_chain(t)
            if chain and _BUCKET_NAME.search(chain[-1]):
                yield self.finding(
                    ctx, node,
                    f"non-power-of-two literal {value.value} assigned to "
                    f"'{chain[-1]}': padding buckets must be powers of "
                    "two to bound the compiled-shape set")
                return
