"""obs-in-hot-loop: observability emission inside jit-traced code.

The obs subsystem's contract (docs/observability.md) is host-side:
sinks take plain dicts, metric objects mutate Python state under a
lock.  Called from inside a jit trace they either concretize tracers
(a host sync per trace) or -- worse -- run once at TRACE time and then
silently never again, so the counter undercounts by exactly the cache
hit rate.  Emission belongs in host code around the wave
(frontier.step's post-consume block) or behind
``jax.debug.callback`` / ``io_callback`` when it truly must originate
inside traced code.
"""

from __future__ import annotations

import ast
from typing import Iterator

from explicit_hybrid_mpc_tpu.analysis.engine import (Finding, ModuleContext,
                                                     Rule, _attr_chain)

#: method names that are unambiguously obs emission.
_EMIT_METHODS = {"emit", "event", "observe", "span", "flush_metrics", "inc"}
#: object-chain segments that mark the receiver as an obs handle.
_OBS_SEGMENTS = {"obs", "metrics", "sink", "recorder", "tracer"}
#: roots whose methods share names with the above but are array math
#: (jnp.log, math.log, ...): never obs receivers.
_ARRAY_ROOTS = {"np", "numpy", "jnp", "jax", "lax", "math", "scipy"}


class ObsInHotLoop(Rule):
    name = "obs-in-hot-loop"
    severity = "error"
    doc = ("sink/metric emission inside jit-traced code -- runs at "
           "trace time (undercounts) or syncs per trace; use host "
           "callbacks or post-wave snapshots")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and ctx.in_jit(node)):
                continue
            chain = _attr_chain(node.func)
            if chain and chain[0] in _ARRAY_ROOTS:
                continue
            receiver = chain[:-1] if chain else []
            if node.func.attr in _EMIT_METHODS \
                    or any(seg in _OBS_SEGMENTS for seg in receiver):
                yield self.finding(
                    ctx, node,
                    f"{'.'.join(chain) or node.func.attr}(...) inside "
                    "jit-traced code: emission runs at trace time (then "
                    "never again on cache hits) or forces a host sync; "
                    "emit from host code after the wave")
