"""The tpulint rule pack (catalog + rationale: docs/static_analysis.md).

Each module holds one rule class; ``all_rules()`` is the registry the
engine and the CLI share.  Adding a rule = adding a module here and
listing it below -- the CLI's ``--list-rules`` / ``--rules`` and the
tier-1 package-clean test pick it up automatically.
"""

from __future__ import annotations

from explicit_hybrid_mpc_tpu.analysis.engine import Rule
from explicit_hybrid_mpc_tpu.analysis.rules.dtype import DtypeDiscipline
from explicit_hybrid_mpc_tpu.analysis.rules.host_sync import HostSyncInJit
from explicit_hybrid_mpc_tpu.analysis.rules.obs_hot import ObsInHotLoop
from explicit_hybrid_mpc_tpu.analysis.rules.recompile import RecompileHazard
from explicit_hybrid_mpc_tpu.analysis.rules.silent_except import SilentExcept

_RULE_CLASSES = (HostSyncInJit, RecompileHazard, DtypeDiscipline,
                 ObsInHotLoop, SilentExcept)


def all_rules() -> list[Rule]:
    return [cls() for cls in _RULE_CLASSES]


def rules_by_name() -> dict[str, Rule]:
    return {r.name: r for r in all_rules()}
