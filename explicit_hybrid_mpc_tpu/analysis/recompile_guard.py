"""Runtime recompile sentinel: fail loudly when a "steady" phase lowers
new device programs.

The static rules (analysis/rules/) catch recompile HAZARDS; this guard
catches recompile FACTS.  The oracle already keeps an exact ledger of
every (program family, padded rows) shape it dispatched
(``Oracle.compiled_shapes`` -- the gauge behind the warm-shapes ==
run-shapes bench invariant), and every ``jax.jit``-wrapped callable
exposes its compiled-variant count via ``_cache_size()``.  The guard
snapshots either (or both) at ``arm()`` and, at ``check()`` / context
exit, treats ANY growth as a finding:

- ``action='warn'``: emit a ``health.recompile`` event (severity warn)
  into the obs stream -- the PR-4 watchdog surface: the in-build
  HealthMonitor folds it into its verdict, scripts/obs_watch.py exits
  nonzero on it, scripts/obs_report.py renders it as a warning -- then
  RE-ARM, so a churning phase reports each new shape once, not every
  step.
- ``action='raise'``: raise ``RecompileError`` (the test/CI mode; the
  frontier's ``cfg.recompile_guard='raise'`` aborts the build).

Wired into the frontier's steady-state wave loop by
``cfg.recompile_guard`` / ``--recompile-guard`` (the engine arms after
a warmup of full-size batches -- ramp-up and drain-down legitimately
mint new pow-2 buckets; a FULL batch re-lowering mid-campaign is the
bug).  Standalone use around any phase::

    with RecompileGuard(watch=[jitted_fn], action="raise"):
        jitted_fn(x)          # same shapes: fine
        jitted_fn(x_bigger)   # new lowering: RecompileError at exit

No jax import: probes are duck-typed (``compiled_shapes`` set,
``_cache_size()`` method), so the guard is constructible in tests and
host tooling without touching the accelerator stack.
"""

from __future__ import annotations

from typing import Iterable, Optional, Sequence

_ACTIONS = ("warn", "raise")


class RecompileError(RuntimeError):
    """A guarded phase lowered new device programs."""


class RecompileGuard:
    """Snapshot/compare compiled-program ledgers around a build phase.

    Parameters:
        oracle: object with a ``compiled_shapes`` set attribute
            (oracle.Oracle; anything duck-typed works).
        watch: jitted callables probed via ``_cache_size()``.
        obs: Obs handle for the ``health.recompile`` event (NOOP-safe;
            when None or disabled the event dict is still RETURNED so
            callers can feed an in-process HealthMonitor).
        action: 'warn' (emit + return the event) or 'raise'.
        label: phase name stamped into events/errors.
    """

    def __init__(self, oracle=None, watch: Sequence = (),
                 obs=None, action: str = "warn",
                 label: str = "steady_state"):
        if action not in _ACTIONS:
            raise ValueError(f"unknown action {action!r} "
                             f"(expected one of {_ACTIONS})")
        if oracle is None and not watch:
            raise ValueError("RecompileGuard needs an oracle (with a "
                             "compiled_shapes ledger) and/or watch= "
                             "jitted callables")
        if oracle is not None and not hasattr(oracle, "compiled_shapes"):
            raise ValueError("oracle has no compiled_shapes ledger; "
                             "pass watch= jitted callables instead")
        self._watch = list(watch)
        for fn in self._watch:
            if not callable(getattr(fn, "_cache_size", None)):
                raise ValueError(
                    f"watch target {fn!r} has no _cache_size(); is it "
                    "a jax.jit-wrapped callable?")
        self.oracle = oracle
        self.obs = obs
        self.action = action
        self.label = label
        self.n_violations = 0
        self._shapes0: Optional[frozenset] = None
        self._cache0: Optional[list[int]] = None
        self.arm()

    # -- snapshot / compare ------------------------------------------------

    def arm(self) -> None:
        """(Re)take the baseline snapshot; growth is measured from the
        most recent arm."""
        if self.oracle is not None:
            self._shapes0 = frozenset(self.oracle.compiled_shapes)
        self._cache0 = [int(fn._cache_size()) for fn in self._watch]

    def new_shapes(self) -> list[tuple]:
        """Oracle ledger entries added since arm() (sorted)."""
        if self.oracle is None:
            return []
        return sorted(set(self.oracle.compiled_shapes) - self._shapes0)

    def cache_growth(self) -> int:
        """Total jit-cache entries added across watch targets."""
        return sum(int(fn._cache_size()) - c0
                   for fn, c0 in zip(self._watch, self._cache0))

    def check(self, **fields) -> Optional[dict]:
        """Compare against the armed snapshot.  On growth: emit the
        ``health.recompile`` event (when obs is live), re-arm, and
        return the event dict -- or raise under action='raise'.
        Returns None when nothing new lowered.  Extra ``fields`` ride
        along in the event (the frontier stamps the step number)."""
        shapes = self.new_shapes()
        growth = self.cache_growth()
        if not shapes and growth <= 0:
            return None
        self.n_violations += 1
        parts = []
        if shapes:
            parts.append(f"{len(shapes)} new oracle shape(s): "
                         + ", ".join(f"{fam}[{rows}]"
                                     for fam, rows in shapes[:8])
                         + ("..." if len(shapes) > 8 else ""))
        if growth > 0:
            parts.append(f"{growth} new jit-cache entr"
                         f"{'y' if growth == 1 else 'ies'} on watched "
                         "callables")
        msg = (f"unexpected recompilation in phase '{self.label}': "
               + "; ".join(parts))
        ev = {"kind": "event", "name": "health.recompile",
              "severity": "warn", "label": self.label,
              "value": len(shapes) + max(growth, 0),
              "shapes": [list(s) for s in shapes[:8]],
              "msg": msg, **fields}
        if self.obs is not None and getattr(self.obs, "enabled", False):
            emitted = self.obs.event(
                "health.recompile",
                **{k: v for k, v in ev.items()
                   if k not in ("kind", "name")})
            if emitted is not None:
                ev = emitted
        self.arm()  # report increments once, not once per step
        if self.action == "raise":
            raise RecompileError(msg)
        return ev

    # -- context manager ---------------------------------------------------

    def __enter__(self) -> "RecompileGuard":
        self.arm()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        # Never mask an in-flight exception with the guard's own.
        if exc_type is None:
            self.check()
