"""CLI entry point for the offline partition build.

Mirrors the reference's argparse surface -- example name, eps_a/eps_r,
algorithm variant, parallelism degree (SURVEY.md section 2 L8 and
section 3 "CLI / entry" [M-med]; exact flags UNVERIFIED, reference mount
empty) -- with the MPI process count replaced by the TPU-native knobs
(backend, mesh devices, device batch size).

    python -m explicit_hybrid_mpc_tpu.main -e inverted_pendulum -a 1e-2 \
        --backend tpu --batch 512 -o build/pend

Outputs under --output PREFIX: PREFIX.tree.pkl (the simplex tree),
PREFIX.stats.json (build statistics), PREFIX.log.jsonl (per-step metrics),
and with --simulate, PREFIX.sim.json (closed-loop comparison).

A second surface, ``python -m explicit_hybrid_mpc_tpu.main serve``,
deploys exported artifacts behind the online serving runtime
(serve/cli.py, docs/serving.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="explicit_hybrid_mpc_tpu",
        description="TPU-native approximate explicit hybrid MPC: "
                    "offline partition build")
    p.add_argument("-e", "--example", required=True,
                   help="benchmark problem name (see --list)")
    p.add_argument("-a", "--eps-a", type=float, default=None,
                   help="absolute suboptimality tolerance eps_a "
                        "(default 1e-2 when neither -a nor -r is given)")
    p.add_argument("-r", "--eps-r", type=float, default=None,
                   help="relative suboptimality tolerance eps_r")
    p.add_argument("--algorithm", choices=("suboptimal", "feasible"),
                   default="suboptimal",
                   help="fully-explicit eps-suboptimal partition vs "
                        "semi-explicit feasibility-only variant")
    p.add_argument("--backend", choices=("tpu", "cpu", "serial"),
                   default="tpu")
    p.add_argument("--precision", choices=("f64", "mixed"), default="f64",
                   help="IPM iteration precision: pure float64 vs "
                        "f32-bulk + f64-polish (TPU-fast, same tolerance)")
    p.add_argument("--batch", type=int, default=256,
                   help="frontier simplices per device step")
    p.add_argument("--mesh", type=int, default=None, metavar="D",
                   help="shard the solve batch over D local devices")
    p.add_argument("--max-depth", type=int, default=40)
    p.add_argument("--boundary-depth", type=int, default=None,
                   metavar="D", help="close mixed-feasibility simplices "
                   "at depth >= D as semi-explicit boundary leaves "
                   "(online fixed-delta QP) instead of splitting to "
                   "--max-depth; closes the feasible-set boundary shell")
    p.add_argument("--prune-rows", action="store_true",
                   help="prune never-active constraint rows with "
                   "KKT-verified per-solve fallback (row-heavy configs)")
    p.add_argument("--no-two-phase", action="store_true",
                   help="disable the two-phase early-exit IPM cohort "
                   "(run the full fixed schedule on every QP)")
    p.add_argument("--phase1-iters", type=int, default=None, metavar="N",
                   help="f64 iterations in the cohort's first phase "
                   "(default: 2/5 of each class's f64 schedule)")
    p.add_argument("--phase1-iters-point", type=int, default=None,
                   metavar="N",
                   help="per-class override of --phase1-iters for the "
                   "POINT-class programs only")
    p.add_argument("--phase1-iters-simplex", type=int, default=None,
                   metavar="N",
                   help="per-class override of --phase1-iters for the "
                   "joint elastic-simplex programs only")
    p.add_argument("--no-warm-start", action="store_true",
                   help="disable tree warm-starts (cold-start every "
                   "child-vertex QP)")
    p.add_argument("--ipm-kernel", choices=("auto", "pallas", "xla"),
                   default="auto",
                   help="IPM dispatch tier (oracle/pallas_ipm.py): "
                   "'auto' probes the backend (TPU -> fused Pallas "
                   "VMEM micro-kernel, CPU -> XLA reference); "
                   "'pallas'/'xla' force a tier (pallas runs in "
                   "interpret mode off-TPU)")
    p.add_argument("--pipeline-depth", type=int, default=None,
                   metavar="N",
                   help="frontier batches planned + dispatched ahead of "
                   "the committing step (default 2; 0 = strictly "
                   "synchronous; the produced tree is bit-identical at "
                   "any depth)")
    p.add_argument("--no-speculate", action="store_true",
                   help="disable speculative child dispatch (midpoint "
                   "solves of predicted splits issued before the "
                   "certificate verdict)")
    p.add_argument("--dedup-window", type=int, default=None, metavar="K",
                   help="max in-flight vertices tracked for cross-batch "
                   "solve dedup (default 8192)")
    p.add_argument("--shard-frontier", action="store_true",
                   help="pod-scale sharded frontier (partition/"
                        "shard.py): each jax.distributed process "
                        "builds its own round-robin share of the root "
                        "simplices on its local devices, with cross-"
                        "host vertex dedup through the asynchronous "
                        "exchange under --shard-dir; the merged tree "
                        "is node-for-node identical to the single-"
                        "process build (launch with JAX_COORDINATOR_"
                        "ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID, "
                        "e.g. via scripts/shard_launch.py)")
    p.add_argument("--shard-dir", metavar="DIR", default=None,
                   help="exchange/result directory shared by every "
                        "shard (default PREFIX.shard)")
    p.add_argument("--shard-timeout", type=float, default=None,
                   metavar="S",
                   help="remote-cell wait budget before a shard "
                        "re-solves locally (default 300)")
    p.add_argument("--async-certify", action="store_true",
                   help="background waiter resolves in-flight "
                        "lookahead programs while the host certifies "
                        "(partition/pipeline.py): trees bit-identical, "
                        "serialized cp_wait share shrinks")
    p.add_argument("--rebuild-from", "--from", dest="rebuild_from",
                   metavar="PRIOR", default=None,
                   help="incremental warm rebuild (partition/rebuild.py"
                        "): transfer PRIOR (.tree.pkl or .ckpt.pkl), "
                        "bulk re-certify its leaves against THIS "
                        "problem/eps, and subdivide only what the "
                        "revision invalidated (the `rebuild` "
                        "subcommand implies this flag)")
    p.add_argument("--strict-provenance", action="store_true",
                   help="refuse rebuild priors without a provenance "
                        "stamp (legacy artifacts otherwise shim with a "
                        "stats note)")
    p.add_argument("--artifacts-out", metavar="DIR", default=None,
                   help="additionally export the built tree as a "
                        "provenance-stamped serving artifact directory "
                        "(serve/registry.save_artifacts layout; deploy "
                        "with `main serve --artifacts DIR`)")
    p.add_argument("--max-steps", type=int, default=10_000)
    p.add_argument("--checkpoint-every", type=int, default=0, metavar="K",
                   help="snapshot frontier+tree every K steps")
    p.add_argument("--resume", metavar="CKPT",
                   help="resume a build from a checkpoint file")
    p.add_argument("-o", "--output", default="partition",
                   help="output file prefix")
    p.add_argument("--simulate", type=int, default=0, metavar="T",
                   help="after the build, run a T-step closed-loop "
                        "explicit-vs-implicit comparison")
    p.add_argument("--problem-arg", action="append", default=[],
                   metavar="K=V", help="problem constructor overrides, "
                   "e.g. --problem-arg N=5 --problem-arg axes=1")
    p.add_argument("--profile", metavar="DIR", default=None,
                   help="write a jax.profiler trace of the first "
                        "--profile-steps frontier steps to DIR")
    p.add_argument("--profile-steps", type=int, default=5)
    p.add_argument("--obs", choices=("off", "jsonl", "full"),
                   default="off",
                   help="observability mode (obs subsystem): 'jsonl' "
                        "streams spans/metrics to PREFIX.obs.jsonl; "
                        "'full' additionally annotates host spans into "
                        "any active jax.profiler trace "
                        "(scripts/obs_report.py renders the stream)")
    p.add_argument("--obs-path", metavar="FILE", default=None,
                   help="override the obs stream path "
                        "(default PREFIX.obs.jsonl)")
    p.add_argument("--obs-per-process", action="store_true",
                   help="suffix the obs stream with .p<index>-<pid> "
                        "(fleet telemetry): N processes sharing one "
                        "prefix -- supervised restarts, multi-process "
                        "builds -- write N streams instead of "
                        "interleaving one file; merge with "
                        "obs_report/obs_watch --fleet")
    p.add_argument("--auto-profile", action="store_true",
                   help="health-triggered bounded device profiling "
                        "(obs/profiling.py): the first CRITICAL "
                        "in-build health verdict opens a jax.profiler "
                        "capture of --profile-steps steps and drops a "
                        "summarized auto_profile JSON bundle (needs "
                        "--obs and --health-rule)")
    p.add_argument("--recorder", action="store_true",
                   help="flight recorder: dump versioned compressed "
                        "repro bundles on solver anomalies (diverged "
                        "cells, simplex stalls, device failures, "
                        "uncertified leaves); replay them with "
                        "scripts/replay_solve.py")
    p.add_argument("--recorder-dir", metavar="DIR", default=None,
                   help="bundle directory (default PREFIX.repro/)")
    p.add_argument("--recompile-guard", choices=("warn", "raise"),
                   default=None,
                   help="runtime recompile sentinel "
                        "(analysis/recompile_guard.py): after the "
                        "steady-state warmup, any NEW compiled oracle "
                        "shape on a full-size frontier step emits a "
                        "health.recompile event (warn) or aborts the "
                        "build (raise)")
    p.add_argument("--solve-timeout", type=float, default=None,
                   metavar="S",
                   help="watchdog timeout per oracle attempt "
                        "(faults/policy.py): a wedged solve raises "
                        "SolveTimeout and takes the device-failure "
                        "recovery path (bounded retries, then "
                        "poison-cell quarantine) instead of hanging "
                        "the build")
    p.add_argument("--fault-plan", metavar="PLAN.json", default=None,
                   help="deterministic fault-injection plan "
                        "(faults/plan.py; chaos testing only -- "
                        "scripts/chaos_suite.py drives this)")
    p.add_argument("--health-rule", action="append", default=[],
                   metavar="NAME=VALUE",
                   help="override a streaming health rule (repeatable; "
                        "see obs.health.DEFAULT_RULES).  Any override "
                        "activates the in-build watchdog: health.* "
                        "events land in the obs stream (needs --obs)")
    p.add_argument("--list", action="store_true",
                   help="list registered problems and exit")
    return p


def _parse_health_rules(pairs: list[str]) -> tuple:
    """NAME=VALUE pairs -> cfg.health_rules tuple, with CLI-friendly
    errors.  Name/value validation is delegated to the ONE validator
    (obs.health.rules_from_pairs) so the known-rule list can never go
    stale here."""
    if not pairs:
        return ()
    from explicit_hybrid_mpc_tpu.obs.health import rules_from_pairs

    out = []
    for kv in pairs:
        if "=" not in kv:
            raise SystemExit(f"--health-rule needs NAME=VALUE, got {kv!r}")
        k, v = kv.split("=", 1)
        try:
            rules_from_pairs([(k, float(v))])
        except ValueError as e:
            raise SystemExit(f"--health-rule: {e}")
        out.append((k, float(v)))
    return tuple(out)


def _parse_problem_args(pairs: list[str]) -> dict:
    out = {}
    for kv in pairs:
        if "=" not in kv:
            raise SystemExit(f"--problem-arg needs K=V, got {kv!r}")
        k, v = kv.split("=", 1)
        try:
            out[k] = json.loads(v)
        except json.JSONDecodeError:
            out[k] = v
    return out


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "serve":
        # The online serving runtime is a subcommand, dispatched before
        # the build parser (whose -e/--example is required): the two
        # surfaces share nothing but the package.  docs/serving.md.
        from explicit_hybrid_mpc_tpu.serve.cli import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "serve-rebuild":
        # The continuous rebuild daemon (lifecycle/; docs/lifecycle.md)
        # dispatches the same way: its flags are service-scoped, not
        # the build parser's.
        from explicit_hybrid_mpc_tpu.lifecycle.cli import (
            serve_rebuild_main)

        return serve_rebuild_main(argv[1:])
    # `rebuild` is sugar over the build surface: same parser, --from
    # required (docs/perf.md "Incremental warm rebuild").
    rebuild_cmd = bool(argv) and argv[0] == "rebuild"
    if rebuild_cmd:
        argv = argv[1:]
    args = build_parser().parse_args(argv)
    if rebuild_cmd and not args.rebuild_from:
        raise SystemExit("rebuild: --from PRIOR (a .tree.pkl or "
                         ".ckpt.pkl) is required")
    if args.rebuild_from and args.resume:
        raise SystemExit("--rebuild-from and --resume are exclusive: "
                         "resume continues ONE build mid-flight, "
                         "rebuild starts a NEW build from a prior "
                         "tree's certificates")

    from explicit_hybrid_mpc_tpu.problems.registry import make, names
    if args.list:
        print("\n".join(names()))
        return 0

    # Sharded runs: the per-process suffix (checkpoints, logs) comes
    # from the launcher's env (scripts/shard_launch.py and any pod
    # launcher MUST export JAX_PROCESS_ID alongside the coordinator
    # vars) so it is known BEFORE any jax import, and each process
    # resumes its OWN shard checkpoint.  A degraded single-shard
    # --shard-frontier run (no coordinator env) saves UNSUFFIXED
    # checkpoints -- the suffix applies only when the suffixed
    # generation actually exists, so both shapes resume.
    shard_pidx = int(os.environ.get("JAX_PROCESS_ID", "0") or 0) \
        if args.shard_frontier else 0
    if args.shard_frontier and args.resume:
        cand = f"{args.resume}.p{shard_pidx}"
        if os.path.exists(cand) or os.path.exists(cand + ".prev"):
            args.resume = cand

    snapshot = None
    if args.resume:
        # Loaded here, before the platform-pin decision: on --resume the
        # EFFECTIVE backend is the snapshot's, not args.backend, and a
        # resumed cpu/serial build must still get the pin below (else a
        # dead TPU tunnel hangs a pure-CPU run).  Unpickling touches no
        # device; the dict is reused by the resume block further down.
        # load_checkpoint verifies the content checksum and falls back
        # to the .prev generation on a torn/corrupt file -- the
        # supervised-restart path (scripts/supervise_build.py) resumes
        # through exactly this loader.
        from explicit_hybrid_mpc_tpu.partition.frontier import (
            load_checkpoint)

        snapshot = load_checkpoint(args.resume)

    effective_backend = snapshot["cfg"].backend if snapshot else args.backend
    if effective_backend in ("cpu", "serial"):
        # Pin the platform BEFORE the first device query: with the TPU
        # plugin registered, jax.devices("cpu") still initializes every
        # backend, and a dead TPU tunnel then hangs a pure-CPU run.
        # (Env JAX_PLATFORMS alone is overridden by the plugin's own
        # config.update -- see .claude/skills/verify/SKILL.md gotchas.)
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.shard_frontier:
        # Multi-process rendezvous BEFORE any device query (a sharded
        # launch without coordinator env degrades to a single-shard
        # run, which is behavior-identical to the plain build).
        from explicit_hybrid_mpc_tpu.parallel import distributed

        distributed.init_distributed()

    from explicit_hybrid_mpc_tpu.config import PartitionConfig
    from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                            make_oracle)
    from explicit_hybrid_mpc_tpu.utils.logging import RunLog

    if args.health_rule and args.obs == "off":
        # The in-build watchdog lives on the obs stream; configuring
        # rules that can never fire is the exact silent failure the
        # rule-name validation exists to prevent.
        raise SystemExit("--health-rule requires --obs jsonl|full "
                         "(the watchdog evaluates the obs stream)")
    problem_args = _parse_problem_args(args.problem_arg)
    prefix = args.output
    os.makedirs(os.path.dirname(prefix) or ".", exist_ok=True)
    eps_a = args.eps_a if args.eps_a is not None else (
        1e-2 if args.eps_r is None else 0.0)
    cfg = PartitionConfig(
        problem=args.example,
        problem_args=tuple(sorted(problem_args.items())), eps_a=eps_a,
        eps_r=args.eps_r if args.eps_r is not None else 0.0,
        algorithm=args.algorithm, backend=args.backend,
        batch_simplices=args.batch, max_depth=args.max_depth,
        semi_explicit_boundary_depth=args.boundary_depth,
        prune_rows=args.prune_rows,
        ipm_two_phase=not args.no_two_phase,
        ipm_phase1_iters=args.phase1_iters,
        ipm_phase1_iters_point=args.phase1_iters_point,
        ipm_phase1_iters_simplex=args.phase1_iters_simplex,
        warm_start_tree=not args.no_warm_start,
        ipm_kernel=args.ipm_kernel,
        **({"pipeline_depth": args.pipeline_depth}
           if args.pipeline_depth is not None else {}),
        speculate=not args.no_speculate,
        **({"dedup_window": args.dedup_window}
           if args.dedup_window is not None else {}),
        max_steps=args.max_steps,
        checkpoint_every=args.checkpoint_every,
        checkpoint_path=(f"{prefix}.ckpt.pkl"
                         if args.checkpoint_every else None),
        # Per-process log stream under sharding (the engine suffixes
        # the checkpoint itself): two shards appending one JSONL file
        # would interleave torn lines.
        log_path=(f"{prefix}.log.jsonl.p{shard_pidx}"
                  if args.shard_frontier else f"{prefix}.log.jsonl"),
        precision=args.precision,
        profile_path=args.profile, profile_steps=args.profile_steps,
        obs=args.obs,
        obs_path=(args.obs_path or f"{prefix}.obs.jsonl"
                  if args.obs != "off" else None),
        # Sharded builds force per-process obs streams: N shards
        # sharing one configured path would interleave one file.
        obs_per_process=(args.obs_per_process
                         or (args.shard_frontier and args.obs != "off")),
        auto_profile=args.auto_profile,
        # --recorder-dir implies --recorder: naming a bundle directory
        # and silently recording nothing would be the worst reading.
        obs_recorder=args.recorder or bool(args.recorder_dir),
        recorder_dir=(args.recorder_dir or f"{prefix}.repro"
                      if args.recorder or args.recorder_dir else None),
        health_rules=_parse_health_rules(args.health_rule),
        recompile_guard=args.recompile_guard or "off",
        solve_timeout_s=args.solve_timeout,
        fault_plan=args.fault_plan,
        rebuild_from=args.rebuild_from,
        rebuild_strict_provenance=args.strict_provenance,
        shard_frontier=args.shard_frontier,
        shard_dir=(args.shard_dir or f"{prefix}.shard"
                   if args.shard_frontier else args.shard_dir),
        **({"shard_timeout_s": args.shard_timeout}
           if args.shard_timeout is not None else {}),
        async_certify=args.async_certify)

    if snapshot is not None:
        # SOLVER flags (precision/backend/eps/batch...) come from the
        # snapshot: silently mixing CLI values into a half-built partition
        # would change solver behaviour mid-build with no record.  RUN-
        # BUDGET and OUTPUT flags (max_steps; log/checkpoint/profile paths)
        # stay with THIS run: the usual reason to resume is precisely to
        # EXTEND a budget-truncated build, and a resumed build must not
        # append to the old run's log or overwrite its checkpoint.
        # FrontierEngine.resume reuses the dict (the snapshot holds the
        # whole tree + cache).
        import dataclasses

        snap_cfg = snapshot["cfg"]
        if not hasattr(snap_cfg, "problem_args"):
            # Snapshot predates the problem_args field: trust this run's
            # --problem-arg values (the old behaviour), recorded going
            # forward.  object.__setattr__ is the frozen-dataclass patch.
            object.__setattr__(snap_cfg, "problem_args",
                               cfg.problem_args)
        # (Pre-boundary-closure snapshots need no back-fill: the new
        # semi_explicit_boundary_depth field has a plain class-level
        # default, so attribute lookup on old pickles already yields
        # None -- the feature stays off for resumed old builds.)
        # Two-phase/warm-start knobs DO need a back-fill, and a
        # conservative one: their class-level defaults are True (the
        # new path), but a resumed pre-knob build must keep its
        # original single-phase cold-start solver semantics mid-build
        # (resumed-equals-straight parity) -- the class default would
        # silently switch conv patterns at the resume point.
        for fld, legacy in (("ipm_two_phase", False),
                            ("ipm_phase1_iters", None),
                            ("ipm_phase1_iters_point", None),
                            ("ipm_phase1_iters_simplex", None),
                            ("warm_start_tree", False),
                            # Pre-tier snapshots keep the XLA path
                            # mid-build (resumed-equals-straight).
                            ("ipm_kernel", "xla")):
            if fld not in snap_cfg.__dict__:
                object.__setattr__(snap_cfg, fld, legacy)
        for fld in ("problem", "problem_args", "eps_a", "eps_r",
                    "algorithm", "backend", "precision",
                    "ipm_point_schedule", "ipm_rescue_iters",
                    "ipm_two_phase", "ipm_phase1_iters",
                    "ipm_phase1_iters_point", "ipm_phase1_iters_simplex",
                    "warm_start_tree", "ipm_kernel",
                    "batch_simplices", "max_depth",
                    "semi_explicit_boundary_depth", "prune_rows"):
            cli_v = getattr(cfg, fld)
            # default: pre-problem_args snapshots lack the field
            snap_v = getattr(snap_cfg, fld, cli_v)
            if cli_v != snap_v:
                print(f"resume: using snapshot {fld}={snap_v!r} "
                      f"(CLI value {cli_v!r} ignored)", file=sys.stderr)
        # Obs knobs stay with THIS run (output-class flags, like the
        # log/profile paths; snapshots predating the knobs resolve
        # through the dataclass's class-level defaults).
        # Pipeline knobs are run-scoped like the obs flags: pipelining,
        # speculation, and dedup are bit-invisible to the produced tree
        # (partition/pipeline.py), so resuming with different lookahead
        # settings changes only throughput, never results.
        cfg = dataclasses.replace(
            snap_cfg, log_path=cfg.log_path,
            prefetch_solves=cfg.prefetch_solves,
            pipeline_depth=cfg.pipeline_depth,
            speculate=cfg.speculate,
            dedup_window=cfg.dedup_window,
            max_steps=cfg.max_steps,
            checkpoint_every=cfg.checkpoint_every,
            checkpoint_path=cfg.checkpoint_path,
            profile_path=cfg.profile_path,
            profile_steps=cfg.profile_steps,
            obs=cfg.obs, obs_path=cfg.obs_path,
            obs_per_process=cfg.obs_per_process,
            auto_profile=cfg.auto_profile,
            # Diagnostics knobs are output-class too: recording repro
            # bundles or watching health changes nothing about the
            # solve, so THIS run's flags win over the snapshot's.
            obs_recorder=cfg.obs_recorder,
            recorder_dir=cfg.recorder_dir,
            health_rules=cfg.health_rules,
            recompile_guard=cfg.recompile_guard,
            # Recovery/chaos knobs are run-scoped like the diagnostics
            # flags: retries, timeouts, and injection change when work
            # runs and where it falls back, never a solved value.
            solve_timeout_s=cfg.solve_timeout_s,
            oracle_retry_attempts=cfg.oracle_retry_attempts,
            oracle_retry_backoff_s=cfg.oracle_retry_backoff_s,
            device_failure_cap=cfg.device_failure_cap,
            fault_plan=cfg.fault_plan,
            # Sharding/async-certify are run-scoped like the pipeline
            # knobs: they change where work runs and when waits block,
            # never a solved value -- a sharded resume passes
            # --shard-frontier again (same launcher env => same shard
            # coordinates and per-process checkpoint suffix).
            shard_frontier=cfg.shard_frontier,
            shard_dir=cfg.shard_dir,
            shard_timeout_s=cfg.shard_timeout_s,
            async_certify=cfg.async_certify)

    # Built from the FINAL cfg: on resume that is the snapshot's problem +
    # constructor args, so matrix shapes always match the restored cache.
    problem = make(cfg.problem, **dict(getattr(cfg, "problem_args", ())))

    mesh = None
    if args.mesh:
        from explicit_hybrid_mpc_tpu.parallel import make_mesh
        mesh = make_mesh((args.mesh, 1))
    # Solver schedule knobs come from the FINAL cfg too: resuming with a
    # different schedule than the snapshot's would silently change conv
    # patterns mid-build (resumed-equals-straight parity).  make_oracle
    # is the ONE oracle-choice path (shared with build_partition);
    # strict surfaces the prune-rows/backend conflict as a CLI error.
    try:
        oracle = make_oracle(problem, cfg, mesh=mesh, strict=True)
    except ValueError as e:
        raise SystemExit(str(e))
    log = RunLog(cfg.log_path, echo=True)
    if args.resume:
        eng = FrontierEngine.resume(snapshot, problem, oracle, log, cfg=cfg)
        res = eng.run()
    elif cfg.rebuild_from:
        from explicit_hybrid_mpc_tpu.partition.provenance import (
            ProvenanceMismatch)
        from explicit_hybrid_mpc_tpu.partition.rebuild import (
            RebuildError, warm_rebuild)

        try:
            res = warm_rebuild(
                problem, cfg, cfg.rebuild_from, oracle=oracle, log=log,
                strict_provenance=cfg.rebuild_strict_provenance)
        except (RebuildError, ProvenanceMismatch) as e:
            raise SystemExit(f"rebuild: {e}")
    else:
        eng = FrontierEngine(problem, oracle, cfg, log)
        res = eng.run()

    if args.shard_frontier:
        # Every shard holds the identical merged result; only the
        # owner writes the shared outputs (the per-shard trees/stats
        # live under --shard-dir regardless).
        from explicit_hybrid_mpc_tpu.parallel import distributed

        if not distributed.is_frontier_owner():
            return 0
    res.tree.save(f"{prefix}.tree.pkl")
    with open(f"{prefix}.stats.json", "w") as f:
        json.dump(res.stats, f, indent=2)
    print(json.dumps(res.stats), file=sys.stderr)
    if args.artifacts_out:
        from explicit_hybrid_mpc_tpu.serve.registry import save_artifacts

        save_artifacts(res.tree, res.roots, args.artifacts_out)
        print(f"serving artifacts written to {args.artifacts_out}",
              file=sys.stderr)

    if args.simulate:
        import numpy as np

        from explicit_hybrid_mpc_tpu.online import export
        from explicit_hybrid_mpc_tpu.sim import simulator

        table = export.export_leaves(res.tree)
        theta0 = 0.8 * problem.theta_ub
        # Feasibility-only partitions deploy semi-explicitly: the leaf
        # fixes delta and a small convex QP runs online (SURVEY.md 4.2).
        # Hybrid builds (--boundary-depth) carry semi-explicit BOUNDARY
        # leaves whose interpolated payloads are fallbacks only -- the
        # mask routes exactly those through the online fixed-delta QP.
        semi_mask = export.semi_explicit_mask(res.tree, table)
        cmp = simulator.compare(problem, table, oracle, theta0,
                                T=args.simulate,
                                semi_explicit=cfg.algorithm == "feasible",
                                semi_mask=semi_mask)
        sim_stats = {
            "theta0": np.asarray(theta0).tolist(),
            "explicit_cost": cmp.explicit.total_cost,
            "implicit_cost": cmp.implicit.total_cost,
            "cost_ratio": cmp.cost_ratio,
            "explicit_us_per_step": cmp.explicit.mean_eval_us,
            "implicit_us_per_step": cmp.implicit.mean_eval_us,
            "online_speedup": cmp.speedup,
            # Full trajectories so post.figures.plot_closed_loop can
            # render the paper-style comparison from the artifact alone.
            "trajectories": {
                label: {"states": np.asarray(r.states).tolist(),
                        "inputs": np.asarray(r.inputs).tolist()}
                for label, r in (("explicit", cmp.explicit),
                                 ("implicit", cmp.implicit))},
        }
        with open(f"{prefix}.sim.json", "w") as f:
            json.dump(sim_stats, f, indent=2)
        # stderr keeps the compact summary; the trajectory arrays live
        # only in the artifact (at T=1000 they are hundreds of KB).
        print(json.dumps({k: v for k, v in sim_stats.items()
                          if k != "trajectories"}), file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
