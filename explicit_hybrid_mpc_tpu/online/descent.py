"""O(depth) device tree-descent point location.

The brute-force locate (online/evaluator.py, online/pallas_eval.py)
touches every leaf per query -- O(L) HBM traffic, the right trade at
10^3-10^4 leaves where one fused contraction wins.  The reference's online
stage is an O(depth) tree descent (SURVEY.md section 4.2 [P]); this module
is its device-native counterpart for LARGE partitions: the tree's internal
nodes export as flat split-hyperplane arrays and the descent runs as a
fixed-trip-count `fori_loop` of gathers, one hyperplane sign test per
level.  scripts/online_crossover.py measures the brute-vs-descent
crossover; see artifacts/online_crossover.json.

Geometry: a longest-edge bisection's two children are separated by the
hyperplane through the shared face = {edge midpoint} u {the p-1 unsplit
vertices}.  Sign convention: h(x) = w.x - c <= 0 on the LEFT child (the
child that kept vertex i of the split edge (i, j), left = V with V[j]
replaced by the midpoint -- partition/geometry.bisect).

Root location is a brute-force min-barycentric argmax over the ROOTS only
(at most p! per sub-box, tiny next to the leaf count).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from explicit_hybrid_mpc_tpu.online.evaluator import (DeviceLeafTable,
                                                      EvalResult)
from explicit_hybrid_mpc_tpu.online.export import LeafTable
from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.tree import NO_CHILD, Tree


class DescentTable(NamedTuple):
    """Flat device arrays for the descent locate."""

    root_bary: jax.Array  # (R, p+1, p+1) root barycentric matrices
    root_node: jax.Array  # (R,) i32 tree node id per root
    children: jax.Array   # (Nn, 2) i32, NO_CHILD at leaves
    normal: jax.Array     # (Nn, p) split hyperplane normal (internal nodes)
    offset: jax.Array     # (Nn,) split hyperplane offset
    leaf_row: jax.Array   # (Nn,) i32 row into the LeafTable; -1 elsewhere
    max_depth: int


def _split_hyperplane(V: np.ndarray, i: int, j: int
                      ) -> tuple[np.ndarray, float]:
    """Hyperplane through the shared child face of the (i, j) bisection,
    oriented so h(V[i]) < 0 (left child side)."""
    p = V.shape[1]
    mid = 0.5 * (V[i] + V[j])
    others = np.delete(V, (i, j), axis=0)          # (p-1, p)
    if others.shape[0] == 0:                        # p == 1: point split
        w = np.ones(1)
    else:
        # Normal = nullspace direction of the face's spanning vectors.
        _, _, vt = np.linalg.svd(others - mid)
        w = vt[-1]
    c = float(w @ mid)
    if float(w @ V[i]) > c:
        w, c = -w, -c
    n = np.linalg.norm(w)
    return w / n, c / n


def export_descent(tree: Tree, roots: list[int],
                   table: LeafTable) -> DescentTable:
    """Flatten a built tree into descent arrays (host, then staged)."""
    Nn = len(tree)
    p = tree.p
    children = np.asarray(tree.children, dtype=np.int32)
    normal = np.zeros((Nn, p))
    offset = np.zeros(Nn)
    for n in range(Nn):
        if children[n, 0] == NO_CHILD:
            continue
        i, j = tree.split_edge[n]
        normal[n], offset[n] = _split_hyperplane(tree.vertices[n], i, j)
    leaf_row = np.full(Nn, -1, dtype=np.int32)
    leaf_row[table.node_id] = np.arange(table.n_leaves, dtype=np.int32)
    root_bary = np.stack([geometry.barycentric_matrix(tree.vertices[r])
                          for r in roots])
    return DescentTable(
        root_bary=jnp.asarray(root_bary),
        root_node=jnp.asarray(np.asarray(roots, dtype=np.int32)),
        children=jnp.asarray(children),
        normal=jnp.asarray(normal),
        offset=jnp.asarray(offset),
        leaf_row=jnp.asarray(leaf_row),
        max_depth=int(tree.max_depth()))


@functools.partial(jax.jit, static_argnames=())
def locate_descent(table: DescentTable, thetas: jax.Array
                   ) -> tuple[jax.Array, jax.Array]:
    """Leaf-table row per query (i32 (B,)), plus the tree node id.

    Row is -1 when the descent lands on a non-converged (infeasible /
    hole) leaf.  Queries outside every root descend from the
    best-matching root (callers read the evaluator's `inside` flag).
    """
    B = thetas.shape[0]
    th1 = jnp.concatenate(
        [thetas, jnp.ones((B, 1), thetas.dtype)], axis=1)
    lam = jnp.einsum("rij,bj->bri", table.root_bary, th1)
    best_root = jnp.argmax(jnp.min(lam, axis=-1), axis=-1)      # (B,)
    node = table.root_node[best_root].astype(jnp.int32)

    def body(_, node):
        ch = table.children[node]                               # (B, 2)
        h = (jnp.einsum("bp,bp->b", table.normal[node], thetas)
             - table.offset[node])
        nxt = jnp.where(h <= 0, ch[:, 0], ch[:, 1])
        return jnp.where(ch[:, 0] == NO_CHILD, node, nxt)

    node = jax.lax.fori_loop(0, table.max_depth, body, node)
    return table.leaf_row[node], node


def evaluate_descent(table: DescentTable, dev: DeviceLeafTable,
                     thetas: jax.Array, tol: float = 1e-9) -> EvalResult:
    """Descent-located, barycentric-interpolated PWA evaluation -- same
    contract as online.evaluator.evaluate, O(depth) instead of O(L)."""
    row, _node = locate_descent(table, thetas)
    B = thetas.shape[0]
    safe = jnp.maximum(row, 0)
    th1 = jnp.concatenate(
        [thetas, jnp.ones((B, 1), dev.bary_M.dtype)], axis=1)
    lam = jnp.einsum("bij,bj->bi", dev.bary_M[safe], th1)
    u = jnp.einsum("bi,bin->bn", lam, dev.U[safe])
    cost = jnp.einsum("bi,bi->b", lam, dev.V[safe])
    inside = (row >= 0) & (jnp.min(lam, axis=-1) >= -tol)
    return EvalResult(u=u, cost=cost, leaf=safe, inside=inside)
