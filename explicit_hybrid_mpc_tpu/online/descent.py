"""O(depth) device tree-descent point location.

The brute-force locate (online/evaluator.py, online/pallas_eval.py)
touches every leaf per query -- O(L) HBM traffic, the right trade at
10^3-10^4 leaves where one fused contraction wins.  The reference's online
stage is an O(depth) tree descent (SURVEY.md section 4.2 [P]); this module
is its device-native counterpart for LARGE partitions: the tree's internal
nodes export as flat split-hyperplane arrays and the descent runs as a
fixed-trip-count `fori_loop` of gathers, one hyperplane sign test per
level.  scripts/online_crossover.py measures the brute-vs-descent
crossover; see artifacts/online_crossover.json.

Geometry: a longest-edge bisection's two children are separated by the
hyperplane through the shared face = {edge midpoint} u {the p-1 unsplit
vertices}.  Sign convention: h(x) = w.x - c <= 0 on the LEFT child (the
child that kept vertex i of the split edge (i, j), left = V with V[j]
replaced by the midpoint -- partition/geometry.bisect).

Root location is a brute-force min-barycentric argmax over the ROOTS only
(at most p! per sub-box, tiny next to the leaf count).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.online.evaluator import (DeviceLeafTable,
                                                      EvalResult)
from explicit_hybrid_mpc_tpu.online.export import LeafTable
from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.tree import NO_CHILD, Tree


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass(frozen=True)
class DescentTable:
    """Flat device arrays for the descent locate.

    max_depth is pytree AUX DATA, not a leaf: it is the fori_loop trip
    count, so it must reach jit as a static Python int (a traced leaf
    would lower the loop as a dynamic while_loop and key the jit cache on
    an array -- round-2 advisor item)."""

    root_bary: jax.Array  # (R, p+1, p+1) root barycentric matrices
    root_node: jax.Array  # (R,) i32 tree node id per root
    children: jax.Array   # (Nn, 2) i32, NO_CHILD at leaves
    normal: jax.Array     # (Nn, p) split hyperplane normal (internal nodes)
    offset: jax.Array     # (Nn,) split hyperplane offset
    leaf_row: jax.Array   # (Nn,) i32 row into the LeafTable; -1 elsewhere
    max_depth: int        # static: trip count of the descent loop

    def tree_flatten(self):
        return ((self.root_bary, self.root_node, self.children,
                 self.normal, self.offset, self.leaf_row), self.max_depth)

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves, max_depth=aux)


def _split_hyperplane(V: np.ndarray, i: int, j: int
                      ) -> tuple[np.ndarray, float]:
    """Hyperplane through the shared child face of the (i, j) bisection,
    oriented so h(V[i]) < 0 (left child side)."""
    p = V.shape[1]
    mid = 0.5 * (V[i] + V[j])
    others = np.delete(V, (i, j), axis=0)          # (p-1, p)
    if others.shape[0] == 0:                        # p == 1: point split
        w = np.ones(1)
    else:
        # Normal = nullspace direction of the face's spanning vectors.
        _, _, vt = np.linalg.svd(others - mid)
        w = vt[-1]
    c = float(w @ mid)
    if float(w @ V[i]) > c:
        w, c = -w, -c
    n = np.linalg.norm(w)
    return w / n, c / n


def export_descent(tree: Tree, roots: list[int], table: LeafTable,
                   force_batched: bool = False,
                   stage: bool = True,
                   obs: "obs_lib.Obs | None" = None) -> DescentTable:
    """Flatten a built tree into descent arrays (host, then staged).

    Trees built with split-time hyperplanes (partition.tree.Tree.split,
    the default) already hold every internal node's normal/offset in
    columnar storage, so this is pure array slicing -- descent-table
    availability costs O(copy), not a 1129 s post-hoc SVD pass at the
    9.8M-leaf satellite scale.  Trees that predate the columns (legacy
    pickles, split_hyperplanes=False builds) fall back to ONE batched
    SVD over all internal nodes (geometry.split_hyperplanes -- a
    per-node Python loop would be minutes-scale even at 10^5 leaves,
    round-2 verdict weak item 8); `force_batched=True` forces that path
    for the split-time-vs-batched parity cross-check.
    `_split_hyperplane` stays as the scalar reference the tests check
    the batch against."""
    o = obs if obs is not None else obs_lib.default()
    with o.span("export.descent", nodes=len(tree),
                leaves=int(table.n_leaves)) as sp:
        Nn = len(tree)
        p = tree.p
        children = np.asarray(tree.children, dtype=np.int32)
        use_stored = tree.split_hyperplanes_available() and not force_batched
        sp["stored_hyperplanes"] = bool(use_stored)
        if use_stored:
            normal = np.array(tree.split_normals, dtype=np.float64)
            offset = np.array(tree.split_offsets, dtype=np.float64)
        else:
            normal = np.zeros((Nn, p))
            offset = np.zeros(Nn)
            internal = np.flatnonzero(children[:, 0] != NO_CHILD)
            if internal.size:
                w, c = geometry.split_hyperplanes(
                    np.asarray(tree.vertices[internal]),
                    np.asarray(tree.split_edge[internal], dtype=np.int64))
                normal[internal] = w
                offset[internal] = c
        leaf_row = np.full(Nn, -1, dtype=np.int32)
        leaf_row[table.node_id] = np.arange(table.n_leaves, dtype=np.int32)
        root_bary = geometry.barycentric_matrices(
            tree.vertices[np.asarray(roots, dtype=np.int64)])
        # stage=False keeps host numpy arrays: the sharded serving path
        # (online/sharded.py) slices per-shard tables out of them and
        # stages each slice on ITS OWN device -- staging the full table
        # on the default device first would defeat the point.
        lift = jnp.asarray if stage else np.asarray
        return DescentTable(
            root_bary=lift(root_bary),
            root_node=lift(np.asarray(roots, dtype=np.int32)),
            children=lift(children),
            normal=lift(normal),
            offset=lift(offset),
            leaf_row=lift(leaf_row),
            max_depth=int(tree.max_depth()))


@functools.partial(jax.jit, static_argnames=())
def descend_from(table: DescentTable, thetas: jax.Array,
                 node: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Descend from per-query start nodes (i32 (B,)): the fori_loop of
    hyperplane sign tests, factored out of locate_descent so the
    sharded serving path (online/sharded.py) can route root selection
    on the host and start each query at its shard-local root."""
    node = node.astype(jnp.int32)

    def body(_, node):
        ch = table.children[node]                               # (B, 2)
        h = (jnp.einsum("bp,bp->b", table.normal[node], thetas)
             - table.offset[node])
        nxt = jnp.where(h <= 0, ch[:, 0], ch[:, 1])
        return jnp.where(ch[:, 0] == NO_CHILD, node, nxt)

    node = jax.lax.fori_loop(0, table.max_depth, body, node)
    return table.leaf_row[node], node


@functools.partial(jax.jit, static_argnames=())
def locate_descent(table: DescentTable, thetas: jax.Array
                   ) -> tuple[jax.Array, jax.Array]:
    """Leaf-table row per query (i32 (B,)), plus the tree node id.

    Row is -1 when the descent lands on a non-converged (infeasible /
    hole) leaf.  Queries outside every root descend from the
    best-matching root (callers read the evaluator's `inside` flag).
    """
    B = thetas.shape[0]
    th1 = jnp.concatenate(
        [thetas, jnp.ones((B, 1), thetas.dtype)], axis=1)
    lam = jnp.einsum("rij,bj->bri", table.root_bary, th1)
    best_root = jnp.argmax(jnp.min(lam, axis=-1), axis=-1)      # (B,)
    node = table.root_node[best_root].astype(jnp.int32)
    return descend_from(table, thetas, node)


@functools.partial(jax.jit, static_argnames=())
def evaluate_rows(dev: DeviceLeafTable, thetas: jax.Array, row: jax.Array,
                  tol: float = 1e-9) -> EvalResult:
    """Barycentric-interpolated PWA evaluation at already-located leaf
    rows (-1 = no converged leaf; flagged outside)."""
    B = thetas.shape[0]
    safe = jnp.maximum(row, 0)
    th1 = jnp.concatenate(
        [thetas, jnp.ones((B, 1), dev.bary_M.dtype)], axis=1)
    lam = jnp.einsum("bij,bj->bi", dev.bary_M[safe], th1)
    u = jnp.einsum("bi,bin->bn", lam, dev.U[safe])
    cost = jnp.einsum("bi,bi->b", lam, dev.V[safe])
    inside = (row >= 0) & (jnp.min(lam, axis=-1) >= -tol)
    return EvalResult(u=u, cost=cost, leaf=safe, inside=inside)


def evaluate_descent(table: DescentTable, dev: DeviceLeafTable,
                     thetas: jax.Array, tol: float = 1e-9) -> EvalResult:
    """Descent-located, barycentric-interpolated PWA evaluation -- same
    contract as online.evaluator.evaluate, O(depth) instead of O(L)."""
    row, _node = locate_descent(table, thetas)
    return evaluate_rows(dev, thetas, row, tol)


def save_descent(table: DescentTable, path: str) -> None:
    """Persist descent arrays as one .npz: with save_leaf_table /
    load_leaf_table (online.export) the deployed online stage loads
    flat arrays only -- never the multi-GB pickled Tree.  Written
    atomically (utils/atomic.py tmp+rename, np.savez streaming into
    the tmp handle -- no in-RAM staging): a crash mid-save leaves the
    previous complete file, never a torn npz a later deploy would
    choke on."""
    from explicit_hybrid_mpc_tpu.utils import atomic

    with atomic.atomic_file(path) as f:
        np.savez(f,
                 root_bary=np.asarray(table.root_bary),
                 root_node=np.asarray(table.root_node),
                 children=np.asarray(table.children),
                 normal=np.asarray(table.normal),
                 offset=np.asarray(table.offset),
                 leaf_row=np.asarray(table.leaf_row),
                 max_depth=np.asarray(table.max_depth, dtype=np.int64))


def load_descent(path: str) -> DescentTable:
    with np.load(path) as z:
        return DescentTable(
            root_bary=jnp.asarray(z["root_bary"]),
            root_node=jnp.asarray(z["root_node"]),
            children=jnp.asarray(z["children"]),
            normal=jnp.asarray(z["normal"]),
            offset=jnp.asarray(z["offset"]),
            leaf_row=jnp.asarray(z["leaf_row"]),
            max_depth=int(z["max_depth"]))
