"""Online PWA controller evaluation (pure-JAX reference implementation).

u(theta): locate the leaf simplex containing theta, take barycentric
weights lambda, return u = sum_i lambda_i u_i -- the reference's online
algorithm (SURVEY.md section 4.2, [P]), executed as one fixed-shape device
program over the exported leaf table.

Point location here is blocked brute force: compute lambda for EVERY leaf
and select the leaf with the least-negative minimum barycentric coordinate
(inside <=> min_i lambda_i >= 0).  On TPU this is a batched matmul over
leaves -- bandwidth-bound, microseconds for 10^4-10^5 leaves, and exactly
parallel; the O(depth) tree descent the reference uses is a host-side
alternative (partition.tree.Tree.locate).  online/pallas_eval.py provides
the hand-tiled kernel version of the same contraction.

A query outside every simplex (or in an uncertified hole) returns the
best-matching leaf anyway; callers needing strict domain checks read the
returned `inside` flag.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.online.export import LeafTable


class EvalResult(NamedTuple):
    u: jax.Array        # (B, n_u)
    cost: jax.Array     # (B,) interpolated vertex cost (certified upper bd)
    leaf: jax.Array     # (B,) leaf row index
    inside: jax.Array   # (B,) bool: min barycentric coord >= -tol


class DeviceLeafTable(NamedTuple):
    bary_M: jax.Array
    U: jax.Array
    V: jax.Array


def stage(table: LeafTable,
          obs: "obs_lib.Obs | None" = None) -> DeviceLeafTable:
    """Host leaf table -> device arrays.  The staging span makes the
    one-time host->device transfer cost visible at large L (a multi-GB
    table's device_put is seconds, easily mistaken for serving cost)."""
    o = obs if obs is not None else obs_lib.default()
    with o.span("serve.stage_leaves", leaves=int(table.n_leaves)):
        return DeviceLeafTable(bary_M=jnp.asarray(table.bary_M),
                               U=jnp.asarray(table.U),
                               V=jnp.asarray(table.V))


@functools.partial(jax.jit, static_argnames=())
def evaluate(dev: DeviceLeafTable, thetas: jax.Array,
             tol: float = 1e-9) -> EvalResult:
    """Batched PWA evaluation: thetas (B, p) -> EvalResult."""
    B, p = thetas.shape
    th1 = jnp.concatenate([thetas, jnp.ones((B, 1), thetas.dtype)], axis=1)
    # lam[b, l, i] = bary_M[l, i, :] . th1[b]  -- one big contraction.
    lam = jnp.einsum("lij,bj->bli", dev.bary_M, th1)
    score = jnp.min(lam, axis=-1)             # (B, L) containment margin
    leaf = jnp.argmax(score, axis=-1)         # best (first on ties)
    lam_best = jnp.take_along_axis(
        lam, leaf[:, None, None], axis=1)[:, 0, :]          # (B, p+1)
    U_best = dev.U[leaf]                      # (B, p+1, n_u)
    V_best = dev.V[leaf]                      # (B, p+1)
    u = jnp.einsum("bi,bin->bn", lam_best, U_best)
    cost = jnp.einsum("bi,bi->b", lam_best, V_best)
    inside = jnp.max(score, axis=-1) >= -tol
    return EvalResult(u=u, cost=cost, leaf=leaf, inside=inside)


def evaluate_np(table: LeafTable, theta: np.ndarray) -> np.ndarray:
    """Single-point numpy evaluation (host reference for tests)."""
    th1 = np.concatenate([theta, [1.0]])
    lam = table.bary_M @ th1                  # (L, p+1)
    leaf = int(np.argmax(lam.min(axis=1)))
    return table.U[leaf].T @ lam[leaf]
