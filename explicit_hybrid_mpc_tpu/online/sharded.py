"""Sharded descent serving: split the leaf/descent tables across devices.

The flat descent path (online/descent.py) keeps ONE table of all nodes
and all leaves; its us/query degrades with leaf count (0.863 us at 12k
leaves -> 62.7 us at 9.8M, commit 0ff2285) because every level of the
fori_loop gathers from arrays far larger than any cache, and the whole
multi-GB table must fit one device.  This module restores near-flat
us/query at large L by sharding:

- The tree is CUT a few levels below the roots; each cut node's subtree
  (its descent arrays AND its slice of the leaf table, both compacted to
  shard-local ids) becomes part of one of ``n_shards`` shards, balanced
  by leaf count (greedy largest-first).  Shards are placed round-robin
  over devices (parallel.mesh.serving_placement) -- a shard's working
  set is O(L / n_shards), so tables that cannot fit one device simply
  shard wider.
- A query is first ROUTED to its cut node: the root pick (an analytic
  geometry.kuhn_root_locator when the root layout allows -- O(p^2) per
  query -- else the brute min-barycentric scan as a small device
  program, identical formula and first-max tie-break as the flat
  locate), then ``cut_depth`` hyperplane sign tests over a routing
  table holding only the above-cut nodes.  At the satellite full box's
  720 roots the brute scan alone costs ~21 us/query (inside the flat
  path's program too!) -- the analytic router is what makes serving
  us/query nearly independent of both R and L.
- Queries are then BATCHED PER SHARD (padded to power-of-two buckets so
  the compiled-shape set stays bounded) and dispatched to each shard's
  device via the shared descend_from / evaluate_rows programs; jax async
  dispatch runs the shards concurrently and results scatter back into
  query order.

Same value contract as descent.evaluate_descent: interpolated u/cost
equal (leaf ids may differ on shared facets, as everywhere else in the
online stack); `leaf` is the GLOBAL leaf-table row.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from explicit_hybrid_mpc_tpu import config as config_mod
from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.online import descent as descent_mod
from explicit_hybrid_mpc_tpu.online.descent import DescentTable
from explicit_hybrid_mpc_tpu.online.evaluator import (DeviceLeafTable,
                                                      EvalResult)
from explicit_hybrid_mpc_tpu.online.export import LeafTable
from explicit_hybrid_mpc_tpu.parallel.mesh import serving_placement
from explicit_hybrid_mpc_tpu.partition.tree import NO_CHILD

_MIN_BUCKET = 8

# Largest padding bucket a single evaluate/locate call may mint.  A
# query batch beyond this is SPLIT into max-bucket chunks instead of
# silently compiling a fresh (and likely never-reused) device shape --
# the serving-side counterpart of the build's RecompileGuard.  The
# split is observable: a health.oversized_batch event (warn severity,
# adopted by obs.health.HealthMonitor) plus the serve.oversized_batches
# counter.  The value lives in config.py so ServeConfig's deploy-time
# validation compares against the same number.
_DEFAULT_MAX_BUCKET = config_mod.DEFAULT_MAX_BUCKET

# Batch-size histogram bounds: power-of-two edges matching the padding
# buckets, so the distribution reads directly as compiled-shape usage.
_BATCH_BOUNDS = tuple(float(1 << k) for k in range(21))


@jax.jit
def _serve_shard(dt: DescentTable, leaves: DeviceLeafTable,
                 thetas: jax.Array, node0: jax.Array, tol: float
                 ) -> tuple[jax.Array, EvalResult]:
    """Descend + interpolate as ONE program per shard: halves the
    per-shard dispatch count, which at tens of shards is the dominant
    serving overhead."""
    row, _node = descent_mod.descend_from(dt, thetas, node0)
    return row, descent_mod.evaluate_rows(leaves, thetas, row, tol)


def _bucket(n: int) -> int:
    """Power-of-two padding >= n: bounds the per-shard compiled-shape
    set to log2(max batch) programs."""
    return max(_MIN_BUCKET, 1 << max(0, (n - 1).bit_length()))


def _find_cut(children: np.ndarray, root_node: np.ndarray,
              target: int) -> tuple[np.ndarray, int]:
    """Descend level-by-level from the roots until the frontier holds at
    least `target` nodes (leaves stay put); returns (cut node ids,
    cut_depth).  The frontier after k steps is exactly the set of nodes
    a k-step routing descent can land on."""
    cur = root_node.astype(np.int64)
    k = 0
    while cur.size < target:
        ch = children[cur]
        leaf = ch[:, 0] == NO_CHILD
        if leaf.all():
            break
        cur = np.concatenate([cur[leaf], ch[~leaf].reshape(-1)])
        k += 1
    return cur, k


def _subtree_owners(children: np.ndarray, cut: np.ndarray) -> np.ndarray:
    """(Nn,) index into `cut` of the owning cut node (-1 above the cut),
    by breadth-first owner propagation."""
    owner = np.full(children.shape[0], -1, dtype=np.int64)
    owner[cut] = np.arange(cut.size)
    frontier = cut
    while frontier.size:
        ch = children[frontier]
        live = ch[:, 0] != NO_CHILD
        kids = ch[live].reshape(-1)
        owner[kids] = np.repeat(owner[frontier[live]], 2)
        frontier = kids
    return owner


def _balance(counts: np.ndarray, n_shards: int) -> np.ndarray:
    """Greedy largest-first bin packing: (n_cut,) shard per cut node."""
    shard = np.zeros(counts.size, dtype=np.int64)
    load = np.zeros(n_shards, dtype=np.int64)
    for c in np.argsort(counts, kind="stable")[::-1]:
        s = int(np.argmin(load))
        shard[c] = s
        load[s] += counts[c]
    return shard


class ShardedDescent:
    """Descent/leaf tables sharded across devices, queries batched per
    shard.  Build with `shard_descent` (from a host DescentTable +
    LeafTable -- a fresh export or load_descent/load_leaf_table
    artifacts; the pickled Tree is never needed)."""

    def __init__(self, dt: DescentTable, table: LeafTable,
                 n_shards: Optional[int] = None,
                 devices: Optional[Sequence[jax.Device]] = None,
                 granularity: int = 8, router=None,
                 obs: "obs_lib.Obs | None" = None,
                 max_bucket: Optional[int] = None):
        devices = list(devices if devices is not None else jax.devices())
        self.max_bucket = int(max_bucket if max_bucket is not None
                              else _DEFAULT_MAX_BUCKET)
        if self.max_bucket < _MIN_BUCKET \
                or not config_mod.is_pow2(self.max_bucket):
            raise ValueError(f"max_bucket must be a power of two >= "
                             f"{_MIN_BUCKET}, got {self.max_bucket}")
        # Extra fields merged into every serve.eval heartbeat event:
        # the request scheduler (serve/scheduler.py) writes its
        # queue_depth / batch_fill_frac here -- and, with request
        # tracing on (obs/reqtrace.py), the rolling queue_frac -- so
        # stream consumers (scripts/obs_watch.py) can alarm on serving
        # stalls and queue-dominated tails, not just build stalls.
        self.heartbeat: dict = {}
        # Serving observability (obs subsystem): per-shard query-latency
        # histograms, batch sizes, routing counters, imbalance gauge.
        # NOOP by default -- the hot path pays one boolean test per
        # batch when disabled.
        self._obs = obs if obs is not None else obs_lib.NOOP
        # Optional analytic root locator (geometry.kuhn_root_locator):
        # callable(thetas (B, p)) -> (B,) GLOBAL root index.  Replaces
        # the O(R)-per-query brute margin scan; the caller owns the
        # claim that it matches this tree's root layout.
        self._router = router
        if n_shards is None:
            n_shards = len(devices)
        children = np.asarray(dt.children)
        normal = np.asarray(dt.normal, dtype=np.float64)
        offset = np.asarray(dt.offset, dtype=np.float64)
        leaf_row = np.asarray(dt.leaf_row)
        root_node = np.asarray(dt.root_node, dtype=np.int64)
        self.root_bary = np.asarray(dt.root_bary, dtype=np.float64)
        self.max_depth = int(dt.max_depth)
        self.n_shards = n_shards
        # Cut a few levels down: ~granularity cut nodes per shard gives
        # the greedy packer enough pieces to balance leaf counts.
        cut, self.cut_depth = _find_cut(children, root_node,
                                        granularity * n_shards)
        owner = _subtree_owners(children, cut)
        node_ids = np.asarray(table.node_id, dtype=np.int64)
        counts = np.bincount(owner[node_ids], minlength=cut.size)
        cut_shard = _balance(counts, n_shards)

        # Routing table: the above-cut nodes plus the cut itself, with
        # children remapped to routing-local ids (NO_CHILD at the cut, so
        # the host descent parks there).
        above = np.flatnonzero(owner == -1)
        rnodes = np.concatenate([above, cut])
        rmap = np.full(children.shape[0], -1, dtype=np.int64)
        rmap[rnodes] = np.arange(rnodes.size)
        rch = children[rnodes].astype(np.int64)
        rch[rch != NO_CHILD] = rmap[rch[rch != NO_CHILD]]
        rch[np.isin(rnodes, cut)] = NO_CHILD
        self._r_shard = np.full(rnodes.size, -1, dtype=np.int64)
        self._r_shard[rmap[cut]] = cut_shard
        self._r_start = np.full(rnodes.size, -1, dtype=np.int64)
        self._r_root = rmap[root_node]
        # The routing table IS a DescentTable over the above-cut nodes
        # (leaf_row unused, max_depth = cut_depth): routing runs through
        # the SAME locate_descent / descend_from programs as the shards,
        # so the root-pick tie-break and descent sign convention cannot
        # drift between routing and shard-local descent.  It must be a
        # device program: the (B, R, p+1) root margin scan through
        # numpy ufuncs cost ~40 us/query by itself at the satellite
        # full box's 720 roots.
        r_dev = devices[0]
        self._rt = DescentTable(
            root_bary=jax.device_put(self.root_bary, r_dev),
            root_node=jax.device_put(self._r_root.astype(np.int32),
                                     r_dev),
            children=jax.device_put(rch.astype(np.int32), r_dev),
            normal=jax.device_put(normal[rnodes], r_dev),
            offset=jax.device_put(offset[rnodes], r_dev),
            leaf_row=jax.device_put(
                np.full(rnodes.size, -1, dtype=np.int32), r_dev),
            max_depth=self.cut_depth)

        # Per-shard compacted tables, each staged on its own device.
        placement = serving_placement(n_shards, devices)
        self.devices = placement
        node_shard = np.where(owner >= 0, cut_shard[owner], -1)
        row_shard = node_shard[node_ids]
        self._shards = []
        for s in range(n_shards):
            nodes_s = np.flatnonzero(node_shard == s)
            rows_s = np.flatnonzero(row_shard == s)
            if nodes_s.size == 0:
                self._shards.append(None)
                continue
            new_id = np.full(children.shape[0], -1, dtype=np.int64)
            new_id[nodes_s] = np.arange(nodes_s.size)
            ch_s = children[nodes_s].astype(np.int64)
            ch_s[ch_s != NO_CHILD] = new_id[ch_s[ch_s != NO_CHILD]]
            rowmap = np.full(table.n_leaves, -1, dtype=np.int64)
            rowmap[rows_s] = np.arange(rows_s.size)
            lr_s = leaf_row[nodes_s].astype(np.int64)
            lr_s = np.where(lr_s >= 0, rowmap[lr_s], -1)
            cut_s = cut[cut_shard == s]
            self._r_start[rmap[cut_s]] = new_id[cut_s]
            dev = placement[s]
            dt_s = DescentTable(
                # Root fields are routing-only and routing happens on the
                # host; per-shard descent starts at explicit nodes.
                root_bary=jax.device_put(
                    np.zeros((1,) + self.root_bary.shape[1:]), dev),
                root_node=jax.device_put(np.zeros(1, np.int32), dev),
                children=jax.device_put(ch_s.astype(np.int32), dev),
                normal=jax.device_put(normal[nodes_s], dev),
                offset=jax.device_put(offset[nodes_s], dev),
                leaf_row=jax.device_put(lr_s.astype(np.int32), dev),
                max_depth=self.max_depth)
            if rows_s.size:
                dev_table = DeviceLeafTable(
                    bary_M=jax.device_put(
                        np.asarray(table.bary_M[rows_s]), dev),
                    U=jax.device_put(np.asarray(table.U[rows_s]), dev),
                    V=jax.device_put(np.asarray(table.V[rows_s]), dev))
            else:
                # A shard can cover only payload-free subtrees (fully
                # infeasible region): keep one zero row so the
                # evaluate_rows gather at safe=max(row, 0)=0 stays in
                # bounds (row itself is -1 there, flagged outside).
                m, n_u = table.bary_M.shape[1], table.U.shape[2]
                dev_table = DeviceLeafTable(
                    bary_M=jax.device_put(np.zeros((1, m, m)), dev),
                    U=jax.device_put(np.zeros((1, m, n_u)), dev),
                    V=jax.device_put(np.zeros((1, m)), dev))
            self._shards.append({
                "sid": s, "dt": dt_s, "leaves": dev_table, "device": dev,
                "rows_global": rows_s, "nodes_global": nodes_s})
        # Metric objects are resolved ONCE here (registry lookups are
        # lock-guarded and the serving loop is the us/query hot path);
        # None when disabled, so the hot path pays one truthiness test.
        self._ms = None
        if self._obs.enabled:
            sizes = self.shard_sizes()
            mean = sum(sizes) / max(1, len(sizes))
            m = self._obs.metrics
            m.gauge("serve.shards").set(self.n_shards)
            m.gauge("serve.leaves").set(float(sum(sizes)))
            m.gauge("serve.cut_depth").set(self.cut_depth)
            # Greedy-packing quality: max/mean leaf load (1.0 = perfect).
            m.gauge("serve.shard_imbalance").set(
                max(sizes) / mean if mean else 0.0)
            self._obs.event("serve.sharded", shards=self.n_shards,
                            cut_depth=self.cut_depth, sizes=sizes)
            self._ms = {
                "shard_hist": {
                    sh["sid"]: m.histogram(
                        f"serve.shard{sh['sid']:02d}.query_s")
                    for sh in self._shards if sh is not None},
                "batch": m.histogram("serve.shard_batch",
                                     bounds=_BATCH_BOUNDS),
                "route_s": m.histogram("serve.route_s"),
                # Analytic-vs-brute root pick: the O(R) brute scan is
                # the large-R serving bottleneck (docs/perf.md), so the
                # routing mode must be visible per query count.
                "route_q": m.counter("serve.route_analytic_queries"
                                     if self._router is not None
                                     else "serve.route_brute_queries"),
                "queries": m.counter("serve.queries"),
                "query_s": m.histogram("serve.query_s"),
                "locate_q": m.counter("serve.locate_queries"),
                "oversized": m.counter("serve.oversized_batches"),
            }

    # -- host routing ------------------------------------------------------

    def _route(self, thetas: np.ndarray) -> np.ndarray:
        """(B,) routing-local cut node per query: root pick (analytic
        router when given, else the routing table's locate_descent --
        identical formula/tie-break to the flat locate) + cut_depth
        hyperplane sign tests, all via the shared descent programs.
        Queries are padded to a power-of-two bucket so the compiled
        route-program set stays bounded."""
        B = thetas.shape[0]
        ms = self._ms
        t0 = time.perf_counter() if ms else 0.0
        pad = _bucket(B)
        if pad != B:
            thetas = np.concatenate(
                [thetas, np.zeros((pad - B, thetas.shape[1]))])
        if self._router is not None:
            ridx = np.asarray(self._router(thetas), dtype=np.int64)
            node = self._r_root[ridx]
            if self.cut_depth:
                _row, node = descent_mod.descend_from(
                    self._rt, jnp.asarray(thetas),
                    jnp.asarray(node.astype(np.int32)))
                node = np.asarray(node)
        else:
            _row, node = descent_mod.locate_descent(
                self._rt, jnp.asarray(thetas))
            node = np.asarray(node)
        if ms:
            ms["route_q"].inc(B)
            ms["route_s"].observe(time.perf_counter() - t0)
        return node[:B].astype(np.int64)

    # -- serving -----------------------------------------------------------

    def _dispatch(self, thetas: np.ndarray, program) -> list[tuple]:
        """Route, then batch per shard (power-of-two padding, shard-
        device staging) and dispatch `program(shard, queries, start)`
        on each; returns [(query idx, shard, outputs), ...].  All
        shards dispatch before any result is read (jax async dispatch
        runs them concurrently) -- the one scaffolding both evaluate
        and locate run through."""
        rnode = self._route(thetas)
        shard = self._r_shard[rnode]
        ms = self._ms
        pending = []
        for s in range(self.n_shards):
            idx = np.flatnonzero(shard == s)
            if idx.size == 0:
                continue
            sh = self._shards[s]
            if ms:
                ms["batch"].observe(idx.size)
            pad = _bucket(idx.size)
            qs = np.zeros((pad, thetas.shape[1]))
            qs[:idx.size] = thetas[idx]
            n0 = np.zeros(pad, dtype=np.int32)
            n0[:idx.size] = self._r_start[rnode[idx]]
            dev = sh["device"]
            pending.append((idx, sh, program(
                sh, jax.device_put(qs, dev), jax.device_put(n0, dev))))
        return pending

    @staticmethod
    def _global_rows(sh: dict, local: np.ndarray) -> np.ndarray:
        """Shard-local leaf rows -> global table rows (-1 preserved;
        payload-free shards have no rows to map)."""
        glob = (sh["rows_global"][np.maximum(local, 0)]
                if sh["rows_global"].size
                else np.full(local.size, -1))
        return np.where(local >= 0, glob, -1)

    def _note_oversized(self, B: int, n_chunks: int) -> None:
        """A batch beyond the largest padding bucket: record the split
        as a health.* event (warn severity -- HealthMonitor ADOPTS
        these, so obs_watch and the in-build watchdog both see it) --
        the old behavior silently minted a fresh compiled shape per
        distinct oversized size."""
        if self._ms:
            self._ms["oversized"].inc()
        self._obs.event(
            "health.oversized_batch", severity="warn", value=B,
            threshold=self.max_bucket,
            msg=(f"query batch of {B} exceeds the largest padding "
                 f"bucket {self.max_bucket}; split into {n_chunks} "
                 "max-bucket chunks instead of compiling a new shape"))

    def evaluate(self, thetas: np.ndarray, tol: float = 1e-9
                 ) -> EvalResult:
        """Batched PWA evaluation, same contract as
        descent.evaluate_descent; `leaf` is the global leaf-table row.
        Accepts/returns host numpy (the serving boundary).  Batches
        beyond `max_bucket` are split into max-bucket chunks (see
        _note_oversized) -- results are identical (every field is
        computed row-independently), only the dispatch granularity
        changes."""
        thetas = np.asarray(thetas, dtype=np.float64)
        B = thetas.shape[0]
        if B > self.max_bucket:
            step = self.max_bucket
            self._note_oversized(B, -(-B // step))
            parts = [self._evaluate_bounded(thetas[lo:lo + step], tol)
                     for lo in range(0, B, step)]
            return EvalResult(
                u=np.concatenate([p.u for p in parts]),
                cost=np.concatenate([p.cost for p in parts]),
                leaf=np.concatenate([p.leaf for p in parts]),
                inside=np.concatenate([p.inside for p in parts]))
        return self._evaluate_bounded(thetas, tol)

    def _evaluate_bounded(self, thetas: np.ndarray, tol: float
                          ) -> EvalResult:
        B = thetas.shape[0]
        ms = self._ms
        t0 = time.perf_counter() if ms else 0.0
        pending = self._dispatch(
            thetas, lambda sh, qs, n0: _serve_shard(
                sh["dt"], sh["leaves"], qs, n0, tol))
        n_u = (int(pending[0][2][1].u.shape[1]) if pending
               else self._shards_n_u())
        u = np.zeros((B, n_u))
        cost = np.zeros(B)
        leaf = np.full(B, -1, dtype=np.int64)
        inside = np.zeros(B, dtype=bool)
        for idx, sh, (row, res) in pending:
            n = idx.size
            # Per-shard histogram = THIS shard's own blocking consume
            # segment per query (its program wait + transfer; the first
            # shard consumed absorbs the async-overlapped compute).
            # Charging whole-batch elapsed here would book routing and
            # every earlier shard's transfer onto lightly-loaded shards
            # as phantom per-query latency; the end-to-end amortized
            # figure lives in serve.query_s below.
            seg0 = time.perf_counter() if ms else 0.0
            u[idx] = np.asarray(res.u)[:n]
            cost[idx] = np.asarray(res.cost)[:n]
            inside[idx] = np.asarray(res.inside)[:n]
            leaf[idx] = self._global_rows(
                sh, np.asarray(row)[:n].astype(np.int64))
            if ms:
                ms["shard_hist"][sh["sid"]].observe(
                    (time.perf_counter() - seg0) / n, n=n)
        if ms:
            ms["queries"].inc(B)
            wall = time.perf_counter() - t0
            ms["query_s"].observe(wall / max(B, 1), n=B)
            # One streaming event per evaluate() batch (never per
            # query): gives live-stream consumers -- the health
            # watchdog's shard-imbalance rule, scripts/obs_watch.py --
            # a serving heartbeat between metrics snapshots.
            self._obs.event("serve.eval", batch=B,
                            wall_s=round(wall, 6),
                            us_per_query=round(wall / max(B, 1) * 1e6,
                                               3),
                            **self.heartbeat)
        return EvalResult(u=u, cost=cost, leaf=leaf, inside=inside)

    def _shards_n_u(self) -> int:
        for sh in self._shards:
            if sh is not None:
                return int(sh["leaves"].U.shape[2])
        return 1

    def locate(self, thetas: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(global leaf-table row, global tree node id) per query; -1
        row where the descent lands on a payload-free leaf.  Oversized
        batches split like evaluate()."""
        thetas = np.asarray(thetas, dtype=np.float64)
        B = thetas.shape[0]
        if B > self.max_bucket:
            step = self.max_bucket
            self._note_oversized(B, -(-B // step))
            parts = [self._locate_bounded(thetas[lo:lo + step])
                     for lo in range(0, B, step)]
            return (np.concatenate([r for r, _n in parts]),
                    np.concatenate([n for _r, n in parts]))
        return self._locate_bounded(thetas)

    def _locate_bounded(self, thetas: np.ndarray
                        ) -> tuple[np.ndarray, np.ndarray]:
        B = thetas.shape[0]
        if self._ms:
            self._ms["locate_q"].inc(B)
        pending = self._dispatch(
            thetas, lambda sh, qs, n0: descent_mod.descend_from(
                sh["dt"], qs, n0))
        rows = np.full(B, -1, dtype=np.int64)
        nodes = np.full(B, -1, dtype=np.int64)
        for idx, sh, (row, node) in pending:
            n = idx.size
            rows[idx] = self._global_rows(
                sh, np.asarray(row)[:n].astype(np.int64))
            nodes[idx] = sh["nodes_global"][
                np.asarray(node)[:n].astype(np.int64)]
        return rows, nodes

    def shard_sizes(self) -> list[int]:
        """Leaf count per shard (0 for empty shards) -- balance metric."""
        return [0 if s is None else int(s["rows_global"].size)
                for s in self._shards]

    @property
    def n_leaves(self) -> int:
        """Total leaf-table rows across shards -- the global leaf-row
        space ``EvalResult.leaf`` indexes (the demand hub records it as
        the top-decile denominator hint, obs/demand.py)."""
        return sum(self.shard_sizes())


def shard_descent(dt: DescentTable, table: LeafTable,
                  n_shards: Optional[int] = None,
                  devices: Optional[Sequence[jax.Device]] = None,
                  granularity: int = 8, router=None,
                  obs: "obs_lib.Obs | None" = None,
                  max_bucket: Optional[int] = None) -> ShardedDescent:
    """Build the sharded server from host-side descent + leaf tables.

    `dt` should be a host export (descent.export_descent(..., stage=
    False)) or descent.load_descent output; `table` an export_leaves /
    load_leaf_table result (memmap-backed tables stream shard slices
    straight from disk -- peak RSS is the largest shard, not L).
    `router` (optional): analytic global-root locator, e.g.
    geometry.kuhn_root_locator(problem.theta_lb, problem.theta_ub,
    problem.root_splits) for engine-built trees -- replaces the
    O(R)-per-query brute root scan."""
    return ShardedDescent(dt, table, n_shards=n_shards, devices=devices,
                          granularity=granularity, router=router, obs=obs,
                          max_bucket=max_bucket)
