"""Export a built Tree's converged leaves to flat device arrays.

The reference deploys its controller by descending the pickled tree in
Python (SURVEY.md section 4.2); the TPU-native online stage instead consumes
a flat table of leaves -- per leaf the barycentric matrix (lambda =
bary_M @ [theta;1]) and the vertex input matrix -- so point location +
affine evaluation is one fixed-shape device program (BASELINE.json
north-star: "a Pallas point-in-simplex + affine-eval kernel").

Two export shapes share one chunked core (`_fill_chunks`):

- `export_leaves(tree)` materializes the table in RAM (small/medium
  partitions, tests, the benchmark's flagship tree);
- `write_leaf_table(tree, dir)` streams the SAME chunks into
  memory-mapped ``.npy`` files, so exporting a multi-million-leaf tree
  next to its live 45 GB in-RAM form costs O(chunk) additional RSS, not
  a second O(L) copy (the 9.8M-leaf satellite export peaked at 94.8 GB
  host RSS with the in-RAM path -- commit 0ff2285).  `load_leaf_table`
  maps the files back (optionally copy-free) so the online stage never
  needs the pickled tree at all.

Chunk boundaries do not change a single bit of the output: every field
is computed row-independently (batched inverses per chunk, columnar
fancy indexing), which tests/test_online.py pins against the in-RAM
export.
"""

from __future__ import annotations

import json
import os
from typing import NamedTuple

import numpy as np

from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.tree import Tree

# Streaming chunk: 2^18 leaves x (bary_M + U + V) is ~20-80 MB transient
# for the benchmark problems -- large enough that the per-chunk batched
# inverse amortizes, small enough that export RSS stays flat.
DEFAULT_CHUNK = 1 << 18

_LEAF_FIELDS = ("bary_M", "U", "V", "delta", "node_id")


class LeafTable(NamedTuple):
    """Flat leaf arrays (numpy; jnp.asarray to stage on device).

    bary_M:   (L, p+1, p+1) -- lambda(theta) = bary_M @ [theta; 1]
    U:        (L, p+1, n_u) -- vertex first-move inputs
    V:        (L, p+1)      -- vertex costs (for cost readout)
    delta:    (L,)          -- commutation index per leaf
    node_id:  (L,)          -- tree node of each row (for cross-checks)

    Arrays may be np.memmap views of an on-disk table (load_leaf_table);
    the contract is identical either way.
    """

    bary_M: np.ndarray
    U: np.ndarray
    V: np.ndarray
    delta: np.ndarray
    node_id: np.ndarray

    @property
    def n_leaves(self) -> int:
        return self.bary_M.shape[0]


def _fill_chunks(tree: Tree, ids: np.ndarray, out: LeafTable,
                 chunk: int) -> None:
    """Stream leaf payloads + barycentric inverses into preallocated
    (possibly memory-mapped) arrays, `chunk` leaves at a time.  The only
    live transients are one chunk's payload slices and its batched
    inverse -- O(chunk), independent of L."""
    for lo in range(0, ids.size, chunk):
        sl = slice(lo, lo + chunk)
        ids_c = ids[sl]
        delta, U, V = tree.leaf_payloads(ids_c)
        out.bary_M[sl] = geometry.barycentric_matrices(
            tree.vertices[ids_c])
        out.U[sl] = U
        out.V[sl] = V
        out.delta[sl] = delta.astype(np.int32)
        out.node_id[sl] = ids_c.astype(np.int32)


def _leaf_ids(tree: Tree) -> np.ndarray:
    ids = tree.converged_leaf_ids()
    if ids.size == 0:
        raise ValueError("tree has no converged leaves")
    return ids


def _field_shapes(tree: Tree, L: int) -> dict[str, tuple]:
    m = tree.p + 1
    return {"bary_M": (L, m, m), "U": (L, m, tree.n_u), "V": (L, m),
            "delta": (L,), "node_id": (L,)}


def _field_dtype(name: str):
    return np.int32 if name in ("delta", "node_id") else np.float64


def _write_meta(dir_path: str, n_leaves: int, p: int, n_u: int,
                provenance: dict | None,
                checksums: dict | None = None) -> None:
    """The table's ``meta.json``, including the build-provenance stamp
    (partition/provenance.py) when one is known.  A stamp-less write is
    legal (synthetic trees, tests) -- loaders then treat the table as
    legacy/unstamped.

    Written ATOMICALLY and LAST (utils/atomic.py): meta.json is the
    artifact directory's commit marker -- the field ``.npy`` files
    stream in place (a memmap cannot), so a crash mid-export leaves
    either the previous complete meta (describing the previous arrays'
    shapes, which the loader's structural check then flags) or no new
    meta at all, never a torn one.  ``checksums`` (field -> sha256
    hex) rides along so load_leaf_table(verify_checksum=True) can
    detect at-rest corruption of the arrays themselves."""
    meta = {"n_leaves": int(n_leaves), "p": int(p), "n_u": int(n_u)}
    if provenance is not None:
        meta["provenance"] = provenance
    if checksums:
        meta["checksums"] = checksums
    from explicit_hybrid_mpc_tpu.utils import atomic

    atomic.atomic_write_json(os.path.join(dir_path, "meta.json"), meta)


def _read_meta(dir_path: str) -> dict | None:
    try:
        with open(os.path.join(dir_path, "meta.json")) as f:
            return json.load(f)
    except OSError:
        return None  # legacy layout without meta.json
    except json.JSONDecodeError as e:
        from explicit_hybrid_mpc_tpu.utils import atomic

        raise atomic.CorruptArtifact(
            f"{dir_path}/meta.json: unreadable ({e}) -- the artifact "
            "commit marker is torn; re-export the table or restore a "
            "previous generation") from e


def _field_checksums(dir_path: str) -> dict:
    """sha256 per field file, read back post-flush (sequential, page
    cache warm from the write; O(chunk) memory)."""
    from explicit_hybrid_mpc_tpu.utils import atomic

    return {k: atomic.file_sha256(os.path.join(dir_path, f"{k}.npy"))
            for k in _LEAF_FIELDS}


def load_table_provenance(dir_path: str) -> dict | None:
    """The provenance stamp of an exported table directory, or None for
    legacy/stamp-less tables (missing meta.json included -- the arrays
    alone are still a loadable table).  A PRESENT-but-torn meta.json
    raises CorruptArtifact (_read_meta): treating a corrupt commit
    marker as merely 'legacy' would wave a damaged artifact through
    the provenance guard."""
    meta = _read_meta(dir_path)
    return None if meta is None else meta.get("provenance")


def export_leaves(tree: Tree, chunk: int = DEFAULT_CHUNK) -> LeafTable:
    """In-RAM export, chunk-streamed into one preallocated table.  (The
    per-leaf python loop this replaced built 3L small arrays in lists
    and OOM'd the 9.8M-leaf satellite full-box export next to the live
    tree; the later one-shot vectorized form still materialized the
    full [V^T; 1] stack -- the chunked core bounds every transient.)"""
    ids = _leaf_ids(tree)
    shapes = _field_shapes(tree, ids.size)
    out = LeafTable(**{k: np.empty(shapes[k], dtype=_field_dtype(k))
                       for k in _LEAF_FIELDS})
    _fill_chunks(tree, ids, out, chunk)
    return out


def commit_leaf_table(dir_path: str, n_leaves: int, p: int, n_u: int,
                      provenance: dict | None = None,
                      checksum: bool = True) -> None:
    """Write the artifact directory's COMMIT MARKER (meta.json,
    atomic, with optional per-field sha256s) and fire the
    artifact.written injection site.  Split out of write_leaf_table so
    a multi-file artifact (save_artifacts: leaf table + descent.npz)
    can land EVERY file before the marker commits -- a crash between
    the table and the descent write must leave a directory the loader
    rejects as uncommitted, never a 'valid' table pointing at a
    missing or stale descent."""
    _write_meta(dir_path, n_leaves, p, n_u, provenance,
                checksums=_field_checksums(dir_path) if checksum
                else None)
    # At-rest-corruption injection site (faults/plan.py): `corrupt`
    # kinds mangle the largest field so the loader's rejection path is
    # exercised end to end.
    from explicit_hybrid_mpc_tpu.faults import injector as faults_inj

    faults_inj.fire("artifact.written", label=dir_path,
                    path=os.path.join(dir_path, "bary_M.npy"))


def invalidate_meta(dir_path: str) -> None:
    """Remove the commit marker before re-exporting INTO an existing
    artifact directory: the field files are rewritten in place (a
    memmap cannot write elsewhere), and a crash mid-rewrite must not
    leave the OLD meta.json 'committing' a half-new table.  (The
    resulting marker-less directory loads as legacy -- the documented
    weak spot for pre-meta layouts -- but never as a falsely-committed
    one.)"""
    try:
        os.unlink(os.path.join(dir_path, "meta.json"))
    except FileNotFoundError:
        pass


def write_leaf_table(tree: Tree, dir_path: str,
                     chunk: int = DEFAULT_CHUNK,
                     provenance: dict | None = None,
                     checksum: bool = True,
                     commit: bool = True) -> LeafTable:
    """Stream the leaf table into memory-mapped ``<dir>/<field>.npy``
    files; peak additional RSS is O(chunk), so a built tree can be
    exported next to itself without doubling host memory.  Returns the
    memmap-backed table (flushed; reopen with load_leaf_table for a
    clean read-only mapping).  ``provenance`` defaults to the tree's
    own build stamp and lands in ``meta.json``.  ``checksum=False``
    skips the per-field sha256 pass (a full re-read; turn it off for
    cluster-scale exports where the structural check suffices).
    ``commit=False`` defers the meta.json commit marker -- callers
    adding MORE files to the artifact (registry.save_artifacts)
    commit once everything is on disk (commit_leaf_table)."""
    ids = _leaf_ids(tree)
    os.makedirs(dir_path, exist_ok=True)
    invalidate_meta(dir_path)
    shapes = _field_shapes(tree, ids.size)
    out = LeafTable(**{
        k: np.lib.format.open_memmap(
            os.path.join(dir_path, f"{k}.npy"), mode="w+",
            dtype=_field_dtype(k), shape=shapes[k])
        for k in _LEAF_FIELDS})
    _fill_chunks(tree, ids, out, chunk)
    for a in out:
        a.flush()
    if provenance is None:
        provenance = getattr(tree, "provenance", None)
    if commit:
        commit_leaf_table(dir_path, ids.size, tree.p, tree.n_u,
                          provenance, checksum=checksum)
    return out


def save_leaf_table(table: LeafTable, dir_path: str,
                    provenance: dict | None = None,
                    checksum: bool = True) -> None:
    """Persist an already-materialized table (same layout as
    write_leaf_table; prefer that for large trees -- it never holds the
    full table in RAM)."""
    os.makedirs(dir_path, exist_ok=True)
    for k in _LEAF_FIELDS:
        np.save(os.path.join(dir_path, f"{k}.npy"), getattr(table, k))
    _write_meta(dir_path, table.n_leaves, table.bary_M.shape[1] - 1,
                table.U.shape[2], provenance,
                checksums=_field_checksums(dir_path) if checksum
                else None)


def load_leaf_table(dir_path: str, mmap: bool = True,
                    expect_provenance: dict | None = None,
                    strict: bool = False,
                    verify_checksum: bool = False) -> LeafTable:
    """Load an exported table; ``mmap=True`` maps the files read-only
    (pages fault in on demand -- the online stage working set, not L,
    bounds RSS), ``mmap=False`` reads full copies.

    Integrity (docs/robustness.md): an unreadable field file or a
    row-count mismatch against ``meta.json`` (the commit marker a torn
    export leaves stale or absent) raises ``CorruptArtifact`` with a
    clear message instead of shipping truncated tables into serving;
    ``verify_checksum=True`` additionally re-hashes every field
    against the recorded sha256s (a full read -- deploy-time
    paranoia, not the request path).  Legacy meta-less layouts load
    as before.

    ``expect_provenance``: the build stamp the caller believes this
    table carries (partition/provenance.build_stamp).  A mismatch warns
    by default and raises ``ProvenanceMismatch`` under ``strict`` --
    the guard against deploying/reusing a table against a revised
    problem.  Legacy stamp-less tables warn and load."""
    from explicit_hybrid_mpc_tpu.utils import atomic

    meta = _read_meta(dir_path)
    if expect_provenance is not None:
        from explicit_hybrid_mpc_tpu.partition import provenance as prov

        prov.check_stamp((meta or {}).get("provenance"),
                         expect_provenance, where=dir_path,
                         strict=strict)
    if verify_checksum:
        sums = (meta or {}).get("checksums")
        if not sums:
            raise atomic.CorruptArtifact(
                f"{dir_path}: verify_checksum requested but meta.json "
                "records no checksums (legacy export or "
                "checksum=False write)")
        for k, want in sums.items():
            got = atomic.file_sha256(os.path.join(dir_path, f"{k}.npy"))
            if got != want:
                raise atomic.CorruptArtifact(
                    f"{dir_path}/{k}.npy: sha256 mismatch (recorded "
                    f"{want[:12]}.., found {got[:12]}..) -- the field "
                    "file was corrupted after export; re-export or "
                    "restore")
    mode = "r" if mmap else None
    arrs = []
    for k in _LEAF_FIELDS:
        p = os.path.join(dir_path, f"{k}.npy")
        try:
            arrs.append(np.load(p, mmap_mode=mode))
        except (OSError, ValueError, EOFError) as e:
            raise atomic.CorruptArtifact(
                f"{p}: unreadable leaf-table field ({e}) -- the "
                "artifact is truncated or torn; re-export the table "
                "or restore a previous generation") from e
    table = LeafTable(*arrs)
    if meta is not None and "n_leaves" in meta:
        for k, a in zip(_LEAF_FIELDS, table):
            if a.shape[0] != meta["n_leaves"]:
                raise atomic.CorruptArtifact(
                    f"{dir_path}/{k}.npy holds {a.shape[0]} rows but "
                    f"meta.json committed {meta['n_leaves']}: the "
                    "export was torn mid-write; re-export or restore")
    return table


def semi_explicit_mask(tree: Tree, table: LeafTable) -> np.ndarray:
    """(L,) bool: which table rows are semi-explicit boundary leaves.

    Those rows' interpolated laws are fallbacks only; the deployed
    controller must route them through the online fixed-delta QP
    (sim.SemiExplicitController(semi_mask=...)).  Kept out of LeafTable
    itself so pure eps-certified partitions pay nothing.  Reads the
    flags column directly (a per-leaf python loop here would undo the
    vectorized export at cluster scale -- main.py calls this right
    after export_leaves)."""
    return tree.semi_explicit_flags(table.node_id)
