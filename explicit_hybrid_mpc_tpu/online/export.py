"""Export a built Tree's converged leaves to flat device arrays.

The reference deploys its controller by descending the pickled tree in
Python (SURVEY.md section 4.2); the TPU-native online stage instead consumes
a flat table of leaves -- per leaf the barycentric matrix (lambda =
bary_M @ [theta;1]) and the vertex input matrix -- so point location +
affine evaluation is one fixed-shape device program (BASELINE.json
north-star: "a Pallas point-in-simplex + affine-eval kernel").
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.tree import Tree


class LeafTable(NamedTuple):
    """Flat leaf arrays (numpy; jnp.asarray to stage on device).

    bary_M:   (L, p+1, p+1) -- lambda(theta) = bary_M @ [theta; 1]
    U:        (L, p+1, n_u) -- vertex first-move inputs
    V:        (L, p+1)      -- vertex costs (for cost readout)
    delta:    (L,)          -- commutation index per leaf
    node_id:  (L,)          -- tree node of each row (for cross-checks)
    """

    bary_M: np.ndarray
    U: np.ndarray
    V: np.ndarray
    delta: np.ndarray
    node_id: np.ndarray

    @property
    def n_leaves(self) -> int:
        return self.bary_M.shape[0]


def export_leaves(tree: Tree) -> LeafTable:
    """Fully vectorized over the columnar tree: batched barycentric
    inverses + payload fancy-indexing.  The per-leaf python loop this
    replaces built 3L small arrays in lists and OOM'd the 9.8M-leaf
    satellite full-box export next to the live tree."""
    ids = tree.converged_leaves()
    if not ids:
        raise ValueError("tree has no converged leaves")
    ids = np.asarray(ids, dtype=np.int64)
    delta, U, V = tree.leaf_payloads(ids)
    return LeafTable(
        bary_M=geometry.barycentric_matrices(tree.vertices[ids]),
        U=U, V=V, delta=delta.astype(np.int32),
        node_id=ids.astype(np.int32))


def semi_explicit_mask(tree: Tree, table: LeafTable) -> np.ndarray:
    """(L,) bool: which table rows are semi-explicit boundary leaves.

    Those rows' interpolated laws are fallbacks only; the deployed
    controller must route them through the online fixed-delta QP
    (sim.SemiExplicitController(semi_mask=...)).  Kept out of LeafTable
    itself so pure eps-certified partitions pay nothing.  Reads the
    flags column directly (a per-leaf python loop here would undo the
    vectorized export at cluster scale -- main.py calls this right
    after export_leaves)."""
    return tree.semi_explicit_flags(table.node_id)
