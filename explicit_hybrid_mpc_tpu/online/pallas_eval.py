"""Hand-tiled Pallas TPU kernel for online PWA point location.

The online controller is `locate the leaf simplex containing theta, then
barycentrically interpolate the vertex inputs` (SURVEY.md section 4.2 [P];
BASELINE.json north-star: "a Pallas point-in-simplex + affine-eval kernel").
The pure-JAX reference (online/evaluator.py) materializes the full
(queries x leaves) barycentric tensor in HBM; for 10^5-leaf partitions that
tensor, not the arithmetic, is the cost.  This kernel streams leaf tiles
through VMEM instead and keeps only a running (best score, best leaf) per
query -- the flash-attention trick applied to point location:

  grid = (query tiles, leaf tiles), leaf axis innermost;
  per step: score[b, l] = min_i  th1[b] . bary[i, :, l]   (PV small matmuls
            on the MXU, min on the VPU);
            running argmax update in VMEM scratch;
  at the last leaf tile: write (best score, best leaf index).

HBM traffic is exactly one pass over the leaf table per 128-query tile, and
nothing of size (B x L) is ever materialized.  The affine evaluation itself
(a (p+1)-point interpolation on the located leaf) is a cheap gather done in
plain JAX at f64 -- point location is where the work is.

Point location runs in f32: TPU has no native f64, and containment scores
only *select* a leaf (ties at shared faces are resolved either way to the
same interpolated law on conforming meshes).  The interpolation then uses
the f64 tables.  Tests cross-check against the f64 pure-JAX evaluator.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

try:  # jax >= 0.5 top-level export
    _enable_x64 = jax.enable_x64
except AttributeError:  # older jax: the experimental home
    from jax.experimental import enable_x64 as _enable_x64

from explicit_hybrid_mpc_tpu.online.evaluator import EvalResult
from explicit_hybrid_mpc_tpu.online.export import LeafTable

# Leaf-tile width: lane dimension of the score tile.
_TL = 128
# Query-tile height.
_TB = 128
# Sentinel magnitudes for padded vertices (+BIG: never the min) and padded
# leaves (-BIG: never the argmax).  Well inside f32 range so arithmetic
# with real scores stays finite.
_BIG = 1e30


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


class PallasLeafTable(NamedTuple):
    """Leaf table staged for the locate kernel.

    bary_T: (PV, K, Lpad) f32 -- bary_T[i, :, l] is vertex i's barycentric
            row of leaf l, zero-padded in K; padded vertices/leaves carry
            +/-_BIG at the homogeneous column so min/argmax ignore them.
    """

    bary_T: jax.Array
    n_leaves: int
    p: int

    @property
    def n_pad_leaves(self) -> int:
        return self.bary_T.shape[2]


def stage_pallas(table: LeafTable) -> PallasLeafTable:
    """Host-side pack: LeafTable -> padded f32 transposed layout."""
    L, pp1, _ = table.bary_M.shape
    p = pp1 - 1
    PV = max(8, 1 << (pp1 - 1).bit_length())    # padded vertex count
    K = 8 * _cdiv(pp1, 8)                        # padded contraction dim
    Lpad = _TL * _cdiv(L, _TL)
    bary = np.zeros((PV, K, Lpad), dtype=np.float32)
    # Real data: bary[i, j, l] = bary_M[l, i, j].
    bary[:pp1, :pp1, :L] = np.ascontiguousarray(
        table.bary_M.transpose(1, 2, 0), dtype=np.float32)
    # Padded vertices of real leaves: lam = +BIG (the homogeneous entry of
    # th1 is 1, so a row [0..0, BIG, 0..] at column p yields BIG).
    bary[pp1:, p, :L] = _BIG
    # Padded leaves: every vertex lam = -BIG => score -BIG, never selected.
    bary[:, p, L:] = -_BIG
    return PallasLeafTable(bary_T=jnp.asarray(bary), n_leaves=L, p=p)


def _locate_kernel(th_ref, bary_ref, val_ref, idx_ref, best_val, best_idx):
    """One (query tile, leaf tile) step of the streaming argmax."""
    lt = pl.program_id(1)

    @pl.when(lt == 0)
    def _():
        best_val[:] = jnp.full_like(best_val, -jnp.inf)
        best_idx[:] = jnp.zeros_like(best_idx)

    th = th_ref[:]                                   # (TB, K)
    PV = bary_ref.shape[0]
    score = jnp.full((th.shape[0], _TL), _BIG, dtype=jnp.float32)
    for i in range(PV):                              # PV is static & small
        # HIGHEST: true-f32 MXU passes -- default f32 matmul goes through
        # bf16 and costs ~3 decimal digits of containment margin.
        lam_i = jnp.dot(th, bary_ref[i],
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)  # (TB, TL)
        score = jnp.minimum(score, lam_i)

    # First-match argmax within the tile (matches jnp.argmax tie-break).
    iota = jax.lax.broadcasted_iota(jnp.int32, score.shape, 1) + lt * _TL
    tile_max = jnp.max(score, axis=1, keepdims=True)          # (TB, 1)
    tile_idx = jnp.min(jnp.where(score == tile_max, iota, jnp.int32(2**30)),
                       axis=1, keepdims=True)
    # Strict > keeps the earliest tile on cross-tile ties.  Running best is
    # lane-replicated: explicit broadcast, stores don't broadcast.
    shape = best_val.shape
    better = jnp.broadcast_to(tile_max > best_val[:, 0:1], shape)
    best_val[:] = jnp.where(better, jnp.broadcast_to(tile_max, shape),
                            best_val[:])
    best_idx[:] = jnp.where(better, jnp.broadcast_to(tile_idx, shape),
                            best_idx[:])

    @pl.when(lt == pl.num_programs(1) - 1)
    def _():
        val_ref[:] = best_val[:]
        idx_ref[:] = best_idx[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def locate(ptable: PallasLeafTable, thetas: jax.Array,
           interpret: bool = False) -> tuple[jax.Array, jax.Array]:
    """Best-containing leaf per query: (leaf_idx (B,) i32, score (B,) f32).

    score >= -tol  <=>  theta is inside leaf_idx's simplex.
    """
    B, p = thetas.shape
    PV, K, Lpad = ptable.bary_T.shape
    Bpad = _TB * _cdiv(B, _TB)
    th1 = jnp.zeros((Bpad, K), dtype=jnp.float32)
    th1 = th1.at[:B, :p].set(thetas.astype(jnp.float32))
    th1 = th1.at[:B, p].set(1.0)
    # Padded queries stay all-zero: their scores are garbage, sliced off.

    grid = (Bpad // _TB, Lpad // _TL)
    # x64 is enabled globally (the IPM needs it) but Mosaic has no i64:
    # trace the kernel with x64 off so index-map and iota constants lower
    # as i32.  Everything here is f32/i32 by construction.
    with _enable_x64(False):
        val, idx = _locate_call(grid, PV, K, th1, ptable.bary_T, interpret)
    return idx[:B, 0], val[:B, 0]


def _locate_call(grid, PV, K, th1, bary_T, interpret):
    Bpad = th1.shape[0]
    return pl.pallas_call(
        _locate_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TB, K), lambda b, lt: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((PV, K, _TL), lambda b, lt: (0, 0, lt),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_TB, 128), lambda b, lt: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TB, 128), lambda b, lt: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bpad, 128), jnp.float32),
            jax.ShapeDtypeStruct((Bpad, 128), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_TB, 128), jnp.float32),
            pltpu.VMEM((_TB, 128), jnp.int32),
        ],
        interpret=interpret,
    )(th1, bary_T)


def evaluate(ptable: PallasLeafTable, dev_table, thetas: jax.Array,
             tol: float = 1e-4, interpret: bool = False) -> EvalResult:
    """Pallas-located, f64-interpolated PWA evaluation.

    dev_table: online.evaluator.DeviceLeafTable (the f64 arrays) -- the
    located leaf's barycentric matrix and vertex data are gathered from it
    so the control law itself is computed at full precision.
    """
    leaf, score = locate(ptable, thetas, interpret=interpret)
    B = thetas.shape[0]
    th1 = jnp.concatenate(
        [thetas, jnp.ones((B, 1), dev_table.bary_M.dtype)], axis=1)
    M_best = dev_table.bary_M[leaf]                  # (B, p+1, p+1)
    lam = jnp.einsum("bij,bj->bi", M_best, th1)
    u = jnp.einsum("bi,bin->bn", lam, dev_table.U[leaf])
    cost = jnp.einsum("bi,bi->b", lam, dev_table.V[leaf])
    inside = score >= -tol
    return EvalResult(u=u, cost=cost, leaf=leaf, inside=inside)
