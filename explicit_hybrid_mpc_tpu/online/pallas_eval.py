"""Hand-tiled Pallas TPU kernel for online PWA point location.

The online controller is `locate the leaf simplex containing theta, then
barycentrically interpolate the vertex inputs` (SURVEY.md section 4.2 [P];
BASELINE.json north-star: "a Pallas point-in-simplex + affine-eval kernel").
The pure-JAX reference (online/evaluator.py) materializes the full
(queries x leaves) barycentric tensor in HBM; for 10^5-leaf partitions that
tensor, not the arithmetic, is the cost.  This kernel streams leaf tiles
through VMEM instead and keeps only a running (best score, best leaf) per
query -- the flash-attention trick applied to point location:

  grid = (query tiles, leaf tiles), leaf axis innermost;
  per step: score[b, l] = min_i  th1[b] . bary[i, :, l]   (PV small matmuls
            on the MXU, min on the VPU);
            running argmax update in VMEM scratch;
  at the last leaf tile: write (best score, best leaf index).

HBM traffic is exactly one pass over the leaf table per 128-query tile, and
nothing of size (B x L) is ever materialized.  The affine evaluation itself
(a (p+1)-point interpolation on the located leaf) is a cheap gather done in
plain JAX at f64 -- point location is where the work is.

Point location runs in f32: TPU has no native f64, and containment scores
only *select* a leaf (ties at shared faces are resolved either way to the
same interpolated law on conforming meshes).  The interpolation then uses
the f64 tables.  Tests cross-check against the f64 pure-JAX evaluator.

PR 16 adds the FUSED serving kernel (`arena_eval_fused`): point location
+ barycentric affine evaluation + certified-box fallback clamp in ONE
``pallas_call``, so a serving request never round-trips to the host
between locate and eval.  It consumes ARENA-layout buffers (serve/
arena.py: many controllers' leaf tables packed column-wise into shared
padded f32 buffers) with per-row column extents, so one launch serves a
MIXED-TENANT micro-batch: each row's argmax is masked to its own
controller's columns.  Evaluation stays f32 in-kernel (the f64 gather
path in evaluator.py remains the reference/parity path; values agree to
f32 interpolation accuracy, leaf ids exactly on tie-free queries --
tests/test_pallas_fused.py documents the f32-locate tie caveat).
"""
# tpulint: x32-module

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

try:  # jax >= 0.5 top-level export
    _enable_x64 = jax.enable_x64
except AttributeError:  # older jax: the experimental home
    from jax.experimental import enable_x64 as _enable_x64

from explicit_hybrid_mpc_tpu.online.evaluator import EvalResult
from explicit_hybrid_mpc_tpu.online.export import LeafTable

# Leaf-tile width: lane dimension of the score tile.
_TL = 128
# Query-tile height.
_TB = 128
# Sentinel magnitudes for padded vertices (+BIG: never the min) and padded
# leaves (-BIG: never the argmax).  Well inside f32 range so arithmetic
# with real scores stays finite.
_BIG = 1e30


def _cdiv(a: int, b: int) -> int:
    return -(-a // b)


class PallasLeafTable(NamedTuple):
    """Leaf table staged for the locate kernel.

    bary_T: (PV, K, Lpad) f32 -- bary_T[i, :, l] is vertex i's barycentric
            row of leaf l, zero-padded in K; padded vertices/leaves carry
            +/-_BIG at the homogeneous column so min/argmax ignore them.
    """

    bary_T: jax.Array
    n_leaves: int
    p: int

    @property
    def n_pad_leaves(self) -> int:
        return self.bary_T.shape[2]


def stage_pallas(table: LeafTable) -> PallasLeafTable:
    """Host-side pack: LeafTable -> padded f32 transposed layout."""
    L, pp1, _ = table.bary_M.shape
    p = pp1 - 1
    PV = max(8, 1 << (pp1 - 1).bit_length())    # padded vertex count
    K = 8 * _cdiv(pp1, 8)                        # padded contraction dim
    Lpad = _TL * _cdiv(L, _TL)
    bary = np.zeros((PV, K, Lpad), dtype=np.float32)
    # Real data: bary[i, j, l] = bary_M[l, i, j].
    bary[:pp1, :pp1, :L] = np.ascontiguousarray(
        table.bary_M.transpose(1, 2, 0), dtype=np.float32)
    # Padded vertices of real leaves: lam = +BIG (the homogeneous entry of
    # th1 is 1, so a row [0..0, BIG, 0..] at column p yields BIG).
    bary[pp1:, p, :L] = _BIG
    # Padded leaves: every vertex lam = -BIG => score -BIG, never selected.
    bary[:, p, L:] = -_BIG
    return PallasLeafTable(bary_T=jnp.asarray(bary), n_leaves=L, p=p)


def _locate_kernel(th_ref, bary_ref, val_ref, idx_ref, best_val, best_idx):
    """One (query tile, leaf tile) step of the streaming argmax."""
    lt = pl.program_id(1)

    @pl.when(lt == 0)
    def _():
        best_val[:] = jnp.full_like(best_val, -jnp.inf)
        best_idx[:] = jnp.zeros_like(best_idx)

    th = th_ref[:]                                   # (TB, K)
    PV = bary_ref.shape[0]
    score = jnp.full((th.shape[0], _TL), _BIG, dtype=jnp.float32)
    for i in range(PV):                              # PV is static & small
        # HIGHEST: true-f32 MXU passes -- default f32 matmul goes through
        # bf16 and costs ~3 decimal digits of containment margin.
        lam_i = jnp.dot(th, bary_ref[i],
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)  # (TB, TL)
        score = jnp.minimum(score, lam_i)

    # First-match argmax within the tile (matches jnp.argmax tie-break).
    iota = jax.lax.broadcasted_iota(jnp.int32, score.shape, 1) + lt * _TL
    tile_max = jnp.max(score, axis=1, keepdims=True)          # (TB, 1)
    tile_idx = jnp.min(jnp.where(score == tile_max, iota, jnp.int32(2**30)),
                       axis=1, keepdims=True)
    # Strict > keeps the earliest tile on cross-tile ties.  Running best is
    # lane-replicated: explicit broadcast, stores don't broadcast.
    shape = best_val.shape
    better = jnp.broadcast_to(tile_max > best_val[:, 0:1], shape)
    best_val[:] = jnp.where(better, jnp.broadcast_to(tile_max, shape),
                            best_val[:])
    best_idx[:] = jnp.where(better, jnp.broadcast_to(tile_idx, shape),
                            best_idx[:])

    @pl.when(lt == pl.num_programs(1) - 1)
    def _():
        val_ref[:] = best_val[:]
        idx_ref[:] = best_idx[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def locate(ptable: PallasLeafTable, thetas: jax.Array,
           interpret: bool = False) -> tuple[jax.Array, jax.Array]:
    """Best-containing leaf per query: (leaf_idx (B,) i32, score (B,) f32).

    score >= -tol  <=>  theta is inside leaf_idx's simplex.
    """
    B, p = thetas.shape
    PV, K, Lpad = ptable.bary_T.shape
    Bpad = _TB * _cdiv(B, _TB)
    th1 = jnp.zeros((Bpad, K), dtype=jnp.float32)
    th1 = th1.at[:B, :p].set(thetas.astype(jnp.float32))
    th1 = th1.at[:B, p].set(1.0)
    # Padded queries stay all-zero: their scores are garbage, sliced off.

    grid = (Bpad // _TB, Lpad // _TL)
    # x64 is enabled globally (the IPM needs it) but Mosaic has no i64:
    # trace the kernel with x64 off so index-map and iota constants lower
    # as i32.  Everything here is f32/i32 by construction.
    with _enable_x64(False):
        val, idx = _locate_call(grid, PV, K, th1, ptable.bary_T, interpret)
    return idx[:B, 0], val[:B, 0]


def _locate_call(grid, PV, K, th1, bary_T, interpret):
    Bpad = th1.shape[0]
    return pl.pallas_call(
        _locate_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((_TB, K), lambda b, lt: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((PV, K, _TL), lambda b, lt: (0, 0, lt),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((_TB, 128), lambda b, lt: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((_TB, 128), lambda b, lt: (b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bpad, 128), jnp.float32),
            jax.ShapeDtypeStruct((Bpad, 128), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_TB, 128), jnp.float32),
            pltpu.VMEM((_TB, 128), jnp.int32),
        ],
        interpret=interpret,
    )(th1, bary_T)


def evaluate(ptable: PallasLeafTable, dev_table, thetas: jax.Array,
             tol: float = 1e-4, interpret: bool = False) -> EvalResult:
    """Pallas-located, f64-interpolated PWA evaluation.

    dev_table: online.evaluator.DeviceLeafTable (the f64 arrays) -- the
    located leaf's barycentric matrix and vertex data are gathered from it
    so the control law itself is computed at full precision.
    """
    leaf, score = locate(ptable, thetas, interpret=interpret)
    B = thetas.shape[0]
    th1 = jnp.concatenate(
        [thetas, jnp.ones((B, 1), dev_table.bary_M.dtype)], axis=1)
    M_best = dev_table.bary_M[leaf]                  # (B, p+1, p+1)
    lam = jnp.einsum("bij,bj->bi", M_best, th1)
    u = jnp.einsum("bi,bin->bn", lam, dev_table.U[leaf])
    cost = jnp.einsum("bi,bi->b", lam, dev_table.V[leaf])
    inside = score >= -tol
    return EvalResult(u=u, cost=cost, leaf=leaf, inside=inside)


# ---------------------------------------------------------------------------
# Fused serving kernel: clamp -> locate -> evaluate in one pallas_call over
# arena-layout buffers (serve/arena.py).  One launch serves a mixed-tenant
# micro-batch: per-row column extents mask the argmax to each row's own
# controller.
# ---------------------------------------------------------------------------

# Padded control-input width of the arena U buffer (lane dimension).
_NU = 128


def pack_columns(table: LeafTable, n_cols: int, PV: int, K: int,
                 nu: int = _NU) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pack a LeafTable into ``n_cols`` arena columns (host-side, f32).

    Returns (bary (PV, K, n_cols), U (PV, n_cols, nu), V (PV, n_cols)).
    Padded vertices carry +_BIG at the homogeneous column (never the min)
    with zero U/V rows (exact-zero contribution to the one-hot gather);
    pad columns past the table's leaves carry -_BIG (never the argmax).
    The f64 -> f32 cast is elementwise, so packing rows gathered from an
    existing arena extent is bitwise identical to packing from the f64
    table -- the property lifecycle delta-apply into the arena relies on.
    """
    L, pp1, _ = table.bary_M.shape
    p = pp1 - 1
    if L > n_cols:
        raise ValueError(f"table has {L} leaves > {n_cols} columns")
    if table.U.shape[2] > nu:
        raise ValueError(f"n_u={table.U.shape[2]} exceeds arena lane pad {nu}")
    bary = np.zeros((PV, K, n_cols), dtype=np.float32)
    bary[:pp1, :pp1, :L] = np.ascontiguousarray(
        table.bary_M.transpose(1, 2, 0), dtype=np.float32)
    bary[pp1:, p, :L] = _BIG
    bary[:, p, L:] = -_BIG
    U = np.zeros((PV, n_cols, nu), dtype=np.float32)
    U[:pp1, :L, :table.U.shape[2]] = np.ascontiguousarray(
        table.U.transpose(1, 0, 2), dtype=np.float32)
    V = np.zeros((PV, n_cols), dtype=np.float32)
    V[:pp1, :L] = np.ascontiguousarray(table.V.T, dtype=np.float32)
    return bary, U, V


def _fused_kernel(th_ref, lb_ref, ub_ref, ext_ref, bary_ref, u_ref, v_ref,
                  val_ref, idx_ref, u_out_ref, cost_ref, clamp_ref,
                  best_val, best_idx, best_u, best_cost):
    """One (query tile, leaf tile) step: clamp, score, running argmax,
    and the candidate one-hot evaluation -- all in VMEM.

    ext_ref lanes 0/1 hold each row's [start, end) column extent (relative
    to the streamed buffers); columns outside it are masked to -_BIG so a
    row never selects another tenant's leaf.  The one-hot gather
    ``sum_i (onehot * lam_i) @ U_i`` adds exact zeros off the selected
    column, so it is bitwise a gather of the f32 arena rows.
    """
    lt = pl.program_id(1)
    th = th_ref[:]                                    # (TB, K) homogeneous
    thc = jnp.clip(th, lb_ref[:], ub_ref[:])          # certified-box clamp

    @pl.when(lt == 0)
    def _():
        best_val[:] = jnp.full_like(best_val, -jnp.inf)
        best_idx[:] = jnp.zeros_like(best_idx)
        best_u[:] = jnp.zeros_like(best_u)
        best_cost[:] = jnp.zeros_like(best_cost)
        moved = jnp.any(th != thc, axis=1, keepdims=True)      # (TB, 1)
        clamp_ref[:] = jnp.broadcast_to(
            moved, clamp_ref.shape).astype(jnp.int32)

    PV = bary_ref.shape[0]
    score = jnp.full((th.shape[0], _TL), _BIG, dtype=jnp.float32)
    lams = []
    for i in range(PV):                               # PV is static & small
        lam_i = jnp.dot(thc, bary_ref[i],
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)   # (TB, TL)
        lams.append(lam_i)
        score = jnp.minimum(score, lam_i)

    # Mask columns outside the row's controller extent.  Constants are
    # explicitly f32/i32: in interpret mode the kernel body is traced at
    # pallas_call lowering time, OUTSIDE the caller's enable_x64(False)
    # window, so a bare python float would lower as f64.
    col = jax.lax.broadcasted_iota(jnp.int32, score.shape, 1) + lt * _TL
    live = (col >= ext_ref[:, 0:1]) & (col < ext_ref[:, 1:2])
    score = jnp.where(live, score, jnp.float32(-_BIG))

    # First-match argmax within the tile.  An all-masked tile yields
    # tile_max == -_BIG and tile_idx == the tile's first column; the
    # candidate only survives until any live tile beats it (real scores
    # are >> -_BIG), and rows with an empty extent are host-discarded.
    iota = jax.lax.broadcasted_iota(jnp.int32, score.shape, 1)
    tile_max = jnp.max(score, axis=1, keepdims=True)           # (TB, 1)
    in_tile = jnp.min(jnp.where(score == tile_max, iota, jnp.int32(2**30)),
                      axis=1, keepdims=True)
    tile_idx = jnp.where(in_tile == jnp.int32(2**30), jnp.int32(0),
                         in_tile) + lt * _TL

    # Candidate evaluation at the tile's winning column: one-hot weights
    # turn the gather into PV small MXU matmuls.
    onehot = (iota == (tile_idx - lt * _TL)).astype(jnp.float32)  # (TB, TL)
    u_cand = jnp.zeros((th.shape[0], u_ref.shape[2]), dtype=jnp.float32)
    cost_cand = jnp.zeros((th.shape[0], 1), dtype=jnp.float32)
    for i in range(PV):
        w_i = onehot * lams[i]                        # (TB, TL)
        u_cand = u_cand + jnp.dot(w_i, u_ref[i],
                                  preferred_element_type=jnp.float32,
                                  precision=jax.lax.Precision.HIGHEST)
        cost_cand = cost_cand + jnp.sum(w_i * v_ref[i][None, :],
                                        axis=1, keepdims=True)

    # Strict > keeps the earliest tile on cross-tile ties.
    shape = best_val.shape
    better1 = tile_max > best_val[:, 0:1]                      # (TB, 1)
    better = jnp.broadcast_to(better1, shape)
    best_val[:] = jnp.where(better, jnp.broadcast_to(tile_max, shape),
                            best_val[:])
    best_idx[:] = jnp.where(better, jnp.broadcast_to(tile_idx, shape),
                            best_idx[:])
    best_u[:] = jnp.where(jnp.broadcast_to(better1, best_u.shape),
                          u_cand, best_u[:])
    best_cost[:] = jnp.where(better, jnp.broadcast_to(cost_cand, shape),
                             best_cost[:])

    @pl.when(lt == pl.num_programs(1) - 1)
    def _():
        val_ref[:] = best_val[:]
        idx_ref[:] = best_idx[:]
        u_out_ref[:] = best_u[:]
        cost_ref[:] = best_cost[:]


@functools.partial(jax.jit, static_argnames=("interpret",))
def arena_eval_fused(bary, u_buf, v_buf, th1, lb1, ub1, ext,
                     interpret: bool = False):
    """Fused clamp+locate+eval over arena-layout buffers.

    bary (PV, K, C) / u_buf (PV, C, NU) / v_buf (PV, C): f32 arena slices,
    C a multiple of _TL.  th1/lb1/ub1 (Bpad, K): f32 homogeneous queries
    and per-row clamp boxes (column p is 1.0 in all three so the clamp is
    the identity there; K-pad columns are 0).  ext (Bpad, 2) i32: per-row
    [start, end) column extent relative to the buffers.

    Returns (val (Bpad,) f32, col (Bpad,) i32, u (Bpad, NU) f32,
    cost (Bpad,) f32, clamped (Bpad,) bool).
    """
    Bpad, K = th1.shape
    PV, _, C = bary.shape
    ext128 = jnp.zeros((Bpad, 128), dtype=jnp.int32)
    ext128 = ext128.at[:, 0:2].set(ext.astype(jnp.int32))
    grid = (Bpad // _TB, C // _TL)
    with _enable_x64(False):
        val, idx, u, cost, clamp = _fused_call(
            grid, PV, K, th1, lb1, ub1, ext128, bary, u_buf, v_buf,
            interpret)
    return val[:, 0], idx[:, 0], u, cost[:, 0], clamp[:, 0] != 0


def _fused_call(grid, PV, K, th1, lb1, ub1, ext128, bary, u_buf, v_buf,
                interpret):
    Bpad = th1.shape[0]
    NU = u_buf.shape[2]
    row_spec = pl.BlockSpec((_TB, K), lambda b, lt: (b, 0),
                            memory_space=pltpu.VMEM)
    out_spec = pl.BlockSpec((_TB, 128), lambda b, lt: (b, 0),
                            memory_space=pltpu.VMEM)
    return pl.pallas_call(
        _fused_kernel,
        grid=grid,
        in_specs=[
            row_spec,                                          # th1
            row_spec,                                          # lb1
            row_spec,                                          # ub1
            pl.BlockSpec((_TB, 128), lambda b, lt: (b, 0),
                         memory_space=pltpu.VMEM),             # ext
            pl.BlockSpec((PV, K, _TL), lambda b, lt: (0, 0, lt),
                         memory_space=pltpu.VMEM),             # bary
            pl.BlockSpec((PV, _TL, NU), lambda b, lt: (0, lt, 0),
                         memory_space=pltpu.VMEM),             # U
            pl.BlockSpec((PV, _TL), lambda b, lt: (0, lt),
                         memory_space=pltpu.VMEM),             # V
        ],
        out_specs=[
            out_spec,                                          # val
            out_spec,                                          # idx
            pl.BlockSpec((_TB, NU), lambda b, lt: (b, 0),
                         memory_space=pltpu.VMEM),             # u
            out_spec,                                          # cost
            out_spec,                                          # clamped
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Bpad, 128), jnp.float32),
            jax.ShapeDtypeStruct((Bpad, 128), jnp.int32),
            jax.ShapeDtypeStruct((Bpad, NU), jnp.float32),
            jax.ShapeDtypeStruct((Bpad, 128), jnp.float32),
            jax.ShapeDtypeStruct((Bpad, 128), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((_TB, 128), jnp.float32),
            pltpu.VMEM((_TB, 128), jnp.int32),
            pltpu.VMEM((_TB, NU), jnp.float32),
            pltpu.VMEM((_TB, 128), jnp.float32),
        ],
        interpret=interpret,
    )(th1, lb1, ub1, ext128, bary, u_buf, v_buf)


@jax.jit
def arena_eval_xla(baryT, u_buf, v_buf, q, ext):
    """Plain-XLA twin of `arena_eval_fused`: identical semantics over the
    SAME f32 arena buffers (clamp, extent masking, first-match argmax,
    one-hot-equivalent gather), no Pallas.  This is the CPU serving path:
    interpret-mode Pallas re-simulates the grid per launch and is far too
    slow for a latency bench, while this path jit-compiles to the same
    f32 arithmetic.  Same returns as `arena_eval_fused`.

    Unlike the Pallas path the caller passes FULL resident buffers with
    ABSOLUTE extents: slicing a column window out first would copy the
    (PV, C, NU) payload buffer every launch, and the only per-launch
    O(C) work left -- the location sgemm -- is cheap next to that copy.

    `baryT` is the arena's LOCATION-LAYOUT twin of the kernel-layout
    bary buffer: shape (K, pp1, C) with pp1 = p + 1 live vertex rows
    only, maintained at publish time (serve/arena.py).  Rows pp1..PV of
    the kernel buffer are lane padding (+BIG scores that never win the
    min, exactly-zero payloads that add nothing to the interpolation),
    so dropping them costs nothing semantically and at PV=8, p=2 is
    2.7x less location work per launch; keeping the transpose resident
    saves a further O(C) copy every call.  The Pallas kernel keeps the
    full-PV (PV, K, C) layout -- its tiles are already lane-shaped.

    `q` stacks the per-row query planes [th1; lb1; ub1] into ONE
    (3, B, K) f32 array so the caller pays a single host->device
    transfer per launch instead of three (transfer DISPATCH, not
    bytes, is what shows up at micro-batch sizes).
    """
    with _enable_x64(False):
        th1, lb1, ub1 = q[0], q[1], q[2]
        B, K = th1.shape
        _, pp1, C = baryT.shape
        thc = jnp.clip(th1, lb1, ub1)
        clamped = jnp.any(th1 != thc, axis=1)
        # lam[b, i*C + c] = thc[b] . bary[i, :, c] as ONE sgemm (the
        # einsum form lowers to a batched dot that bypasses BLAS).
        lam = jnp.dot(thc, baryT.reshape(K, pp1 * C),
                      preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST
                      ).reshape(B, pp1, C)
        score = jnp.min(lam, axis=1)                           # (B, C)
        col = jnp.arange(C, dtype=jnp.int32)
        live = (col[None, :] >= ext[:, 0:1]) & (col[None, :] < ext[:, 1:2])
        score = jnp.where(live, score, jnp.float32(-_BIG))
        best = jnp.argmax(score, axis=1).astype(jnp.int32)     # first match
        val = jnp.take_along_axis(score, best[:, None], axis=1)[:, 0]
        lam_best = jnp.take_along_axis(
            lam, best[:, None, None], axis=2)[:, :, 0]         # (B, pp1)
        u_best = jnp.swapaxes(u_buf[:pp1, best, :], 0, 1)      # (B, pp1, NU)
        u = jnp.einsum("bi,bin->bn", lam_best, u_best,
                       preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)
        cost = jnp.sum(lam_best * v_buf[:pp1, best].T, axis=1)
        return val, best, u, cost, clamped
