"""Simplex geometry for the partition engine.

Host-side (numpy) counterparts of the reference's geometry helpers
(SURVEY.md section 3 "Geometry & misc tools", [M-med], UNVERIFIED --
reference mount empty): triangulation of the parameter box into root
simplices, barycentric coordinates, longest-edge bisection, volumes.

Everything here is deterministic (lexicographic tie-breaks) because region-
count parity between the serial-CPU and TPU oracle paths requires identical
subdivision decisions (BASELINE.json north-star).

A simplex in R^p is stored as a vertex matrix ``V`` of shape (p+1, p).
"""

from __future__ import annotations

import itertools
import math

import numpy as np


def kuhn_triangulation(lb: np.ndarray, ub: np.ndarray) -> np.ndarray:
    """Triangulate the box [lb, ub] into p! simplices (Kuhn/Freudenthal).

    Each permutation ``pi`` of (0..p-1) yields the simplex with vertices
    ``v_0 = lb``, ``v_{k+1} = v_k + (ub-lb)[pi[k]] * e_{pi[k]}``.  The union
    covers the box exactly, interiors are disjoint, and the construction is
    deterministic -- unlike Delaunay of the 2^p corners, it needs no Qhull
    and is stable in any dimension.  Returns (p!, p+1, p).

    The reference Delaunay-triangulates the parameter box into root
    simplices (SURVEY.md section 1 step 1, [P]); Kuhn gives the same cover
    with a reproducible simplex set.
    """
    lb = np.asarray(lb, dtype=np.float64)
    ub = np.asarray(ub, dtype=np.float64)
    if lb.shape != ub.shape or lb.ndim != 1:
        raise ValueError("lb/ub must be 1-D with equal shapes")
    if not np.all(ub > lb):
        raise ValueError("need ub > lb elementwise")
    p = lb.size
    if p > 7:
        raise ValueError(
            f"Kuhn triangulation of a {p}-D box has {math.factorial(p)} "
            "root simplices; partition over a lower-dimensional parameter "
            "subspace instead (see problems.base.ParameterMap)"
        )
    edges = ub - lb
    sims = []
    for pi in itertools.permutations(range(p)):
        verts = np.empty((p + 1, p), dtype=np.float64)
        verts[0] = lb
        for k, axis in enumerate(pi):
            verts[k + 1] = verts[k]
            verts[k + 1, axis] += edges[axis]
        sims.append(verts)
    return np.stack(sims)


def box_triangulation(lb: np.ndarray, ub: np.ndarray,
                      splits: dict | None = None) -> np.ndarray:
    """Kuhn-triangulate the box after pre-splitting along axis planes.

    ``splits`` maps axis index -> iterable of coordinate values; the box is
    cut into sub-boxes at each value strictly inside the range, and every
    sub-box is Kuhn-triangulated.  Returns (n_simplices, p+1, p).

    Why pre-split: a problem whose commutation feasibility changes across a
    fixed hyperplane in theta (e.g. PWA mode membership of the initial
    state) can never certify a simplex STRADDLING that plane -- no single
    commutation is feasible at vertices on both sides -- and longest-edge
    bisection midpoints approach but need not ever hit the plane, so the
    subdivision would refine forever.  Aligning root cell faces with the
    plane makes every descendant stay in one closed halfspace.
    """
    lb = np.asarray(lb, dtype=np.float64)
    ub = np.asarray(ub, dtype=np.float64)
    boxes = [(lb, ub)]
    for axis, values in sorted((splits or {}).items()):
        new = []
        for lo, hi in boxes:
            cuts = [v for v in sorted(set(values))
                    if lo[axis] < v < hi[axis]]
            edges = [lo[axis]] + cuts + [hi[axis]]
            for a, b in zip(edges[:-1], edges[1:]):
                nlo, nhi = lo.copy(), hi.copy()
                nlo[axis], nhi[axis] = a, b
                new.append((nlo, nhi))
        boxes = new
    return np.concatenate([kuhn_triangulation(lo, hi) for lo, hi in boxes])


def _perm_rank(order: np.ndarray) -> np.ndarray:
    """(B, p) permutation rows -> lexicographic rank (Lehmer code),
    matching the order itertools.permutations(range(p)) yields."""
    B, p = order.shape
    rank = np.zeros(B, dtype=np.int64)
    for i in range(p):
        smaller = (order[:, i + 1:] < order[:, i:i + 1]).sum(axis=1)
        rank += smaller * math.factorial(p - 1 - i)
    return rank


def kuhn_root_locator(lb: np.ndarray, ub: np.ndarray,
                      splits: dict | None = None):
    """O(p^2)-per-query analytic root location for box_triangulation
    partitions: returns ``locate(thetas (B, p)) -> (B,) root index``
    into the triangulation's simplex order.

    The brute root pick (min-barycentric argmax over ALL roots) is a
    (B, R, p+1, p+1) contraction -- at the satellite full box's 720
    roots it costs more than the whole tree descent it routes for.  A
    Kuhn simplex needs no scan: x lies in the sub-box found by
    per-axis bisection of the split planes, and within it in the
    permutation simplex given by sorting the normalized coordinates
    DESCENDING (v_{k+1} = v_k + edge[pi[k]] e_{pi[k]}, so axes added
    earlier carry larger normalized coordinates).  Stable descending
    argsort reproduces the brute pick's first-max tie-break on shared
    faces WITHIN a sub-box (the lexicographically smallest containing
    permutation).  Queries EXACTLY ON a split plane land in the lower
    sub-box (its t=1 face); the brute scan's pick there is decided by
    last-ulp noise in the barycentric inverses (the true margins tie
    at 0), so the two may name different roots -- both contain the
    query, and interpolated values agree by facet continuity, the same
    caveat as shared facets everywhere in the online stack.  Queries
    OUTSIDE the box clamp to the nearest sub-box, which may differ
    from the brute pick's best-margin root -- callers read the
    evaluator's `inside` flag either way, exactly as with the scan.

    Only valid for trees whose roots came from box_triangulation(lb,
    ub, splits) with THESE arguments, in its simplex order.
    """
    lb = np.asarray(lb, dtype=np.float64)
    ub = np.asarray(ub, dtype=np.float64)
    p = lb.size
    fact = math.factorial(p)
    # Interior cut values per split axis, in box_triangulation's
    # (sorted-axis, ascending-interval) nesting order.
    axes = []
    for axis, values in sorted((splits or {}).items()):
        cuts = np.asarray([v for v in sorted(set(values))
                           if lb[axis] < v < ub[axis]], dtype=np.float64)
        if cuts.size:
            axes.append((axis, cuts))

    def locate(thetas: np.ndarray) -> np.ndarray:
        thetas = np.asarray(thetas, dtype=np.float64)
        B = thetas.shape[0]
        box_idx = np.zeros(B, dtype=np.int64)
        lo = np.broadcast_to(lb, thetas.shape).copy()
        hi = np.broadcast_to(ub, thetas.shape).copy()
        for axis, cuts in axes:
            # side="left": a query EXACTLY ON a cut plane lands in the
            # LOWER sub-box (on its t=1 face), which is the first
            # containing root in triangulation order -- the same root
            # the brute argmax's first-max tie-break picks.
            k = np.searchsorted(cuts, thetas[:, axis], side="left")
            box_idx = box_idx * (cuts.size + 1) + k
            edges = np.concatenate([[lb[axis]], cuts, [ub[axis]]])
            lo[:, axis] = edges[k]
            hi[:, axis] = edges[k + 1]
        t = (thetas - lo) / (hi - lo)
        order = np.argsort(-t, axis=1, kind="stable")
        return box_idx * fact + _perm_rank(order)

    return locate


def barycentric_matrix(V: np.ndarray) -> np.ndarray:
    """Matrix M with lambda = M @ [theta; 1] the barycentric coordinates.

    V is (p+1, p).  Solves [V^T; 1^T] lambda = [theta; 1]; M is the inverse
    of that (p+1)x(p+1) system, precomputed per leaf for the online
    evaluator (SURVEY.md section 4.2).
    """
    p = V.shape[1]
    A = np.vstack([V.T, np.ones((1, p + 1))])
    return np.linalg.inv(A)


def barycentric_matrices(Vs: np.ndarray,
                         chunk: int = 1 << 20) -> np.ndarray:
    """Batched barycentric_matrix: (L, p+1, p) -> (L, p+1, p+1).

    One batched inverse per chunk instead of a per-leaf python loop --
    the loop (plus its L small-array intermediates) is what blew the
    online export past host RAM at the 9.8M-leaf satellite full-box
    ledger.  Chunking bounds the transient [V^T; 1] stack."""
    Vs = np.asarray(Vs, dtype=np.float64)
    L, m, p = Vs.shape
    out = np.empty((L, m, m), dtype=np.float64)
    for lo in range(0, L, chunk):
        Vc = Vs[lo:lo + chunk]
        A = np.concatenate(
            [Vc.transpose(0, 2, 1),
             np.ones((Vc.shape[0], 1, m), dtype=np.float64)], axis=1)
        out[lo:lo + chunk] = np.linalg.inv(A)
    return out


def barycentric(V: np.ndarray, theta: np.ndarray) -> np.ndarray:
    """Barycentric coordinates of theta w.r.t. simplex V ((p+1,p))."""
    M = barycentric_matrix(V)
    return M @ np.concatenate([theta, [1.0]])


def contains(V: np.ndarray, theta: np.ndarray, tol: float = 1e-9) -> bool:
    """Point-in-simplex test via barycentric nonnegativity."""
    lam = barycentric(V, theta)
    return bool(np.all(lam >= -tol))


def simplex_volume(V: np.ndarray) -> float:
    """Volume of the simplex with vertex matrix V ((p+1, p))."""
    p = V.shape[1]
    D = V[1:] - V[0]
    return float(abs(np.linalg.det(D)) / math.factorial(p))


def longest_edge(V: np.ndarray) -> tuple[int, int]:
    """Indices (i, j), i < j, of the longest edge; lexicographic tie-break.

    The subdivision step bisects this edge (SURVEY.md section 1 step 2c,
    [P]/[NS]: "longest-edge bisection").  Tie-break must be deterministic
    for backend-parity of the produced tree.
    """
    n = V.shape[0]
    # One vectorized pass for the pairwise squared lengths (the python
    # np.dot double loop was ~150 ms/step at cluster-scale batch sizes);
    # the selection loop below runs on plain floats and keeps the exact
    # sequential tie-break semantics.
    D = V[:, None, :] - V[None, :, :]
    d2 = np.einsum("ijk,ijk->ij", D, D)
    best = (-1.0, 0, 1)
    for i in range(n):
        row = d2[i]
        for j in range(i + 1, n):
            d = row[j]
            # Strict > with a RELATIVE margin keeps the lexicographically
            # first pair on ties at ANY scale: squared edge lengths shrink
            # ~4x per bisection level, so an absolute epsilon would turn
            # every comparison at depth >~ 20 into a "tie" and silently
            # replace longest-edge (Rivara shape regularity) with
            # lexicographic-first selection.
            if d > best[0] * (1.0 + 1e-12):
                best = (d, i, j)
    return best[1], best[2]


def bisect(V: np.ndarray) -> tuple[np.ndarray, np.ndarray, int, int, np.ndarray]:
    """Longest-edge bisection: split V into two children.

    Returns (child_left, child_right, i, j, midpoint) where the split edge
    is (i, j) and each child replaces one endpoint with the midpoint.  The
    children cover V exactly with disjoint interiors; repeated longest-edge
    bisection keeps simplices shape-regular (Rivara).
    """
    i, j = longest_edge(V)
    mid = 0.5 * (V[i] + V[j])
    left = V.copy()
    left[j] = mid
    right = V.copy()
    right[i] = mid
    return left, right, i, j, mid


def split_hyperplanes(Vs: np.ndarray, ij: np.ndarray
                      ) -> tuple[np.ndarray, np.ndarray]:
    """Batched split-face hyperplanes of longest-edge bisections.

    Vs (N, p+1, p) parent vertex matrices, ij (N, 2) split edges.
    Returns (w (N, p), c (N,)) with ||w||=1, oriented so w.x - c <= 0 on
    the LEFT child (the child that keeps vertex i of edge (i, j)): the
    hyperplane passes through the shared child face = {edge midpoint} u
    {the p-1 unsplit vertices}, and its normal is the nullspace direction
    of that face's spanning vectors.

    This is THE hyperplane definition of the descent locate
    (online/descent.py).  Tree.split calls it with N=1 at split time and
    export_descent with N=all-internal-nodes as the fallback; per-row
    results are bit-identical between the two (np.linalg.svd and the
    einsum row reductions operate per matrix/row), which is what the
    split-time-vs-batched parity tests pin."""
    Vs = np.asarray(Vs, dtype=np.float64)
    ij = np.asarray(ij, dtype=np.int64)
    N, m, p = Vs.shape
    ar = np.arange(N)
    mid = 0.5 * (Vs[ar, ij[:, 0]] + Vs[ar, ij[:, 1]])          # (N, p)
    if p == 1:
        w = np.ones((N, 1))
    else:
        # Rows of each simplex not on the split edge, in stable order:
        # the face spanning set whose nullspace is the split normal.
        idx = np.arange(p + 1)
        keep = ((idx[None, :] != ij[:, :1])
                & (idx[None, :] != ij[:, 1:2]))                # (N, p+1)
        rows = np.argsort(~keep, axis=1, kind="stable")[:, :p - 1]
        others = np.take_along_axis(Vs, rows[:, :, None], axis=1)
        _, _, vt = np.linalg.svd(others - mid[:, None, :])
        w = vt[:, -1, :]                                       # (N, p)
    c = np.einsum("np,np->n", w, mid)
    flip = np.einsum("np,np->n", w, Vs[ar, ij[:, 0]]) > c
    w[flip] *= -1.0
    c[flip] *= -1.0
    nrm = np.linalg.norm(w, axis=1)
    return w / nrm[:, None], c / nrm


def vertex_key(v: np.ndarray, decimals: int = 9) -> bytes:
    """Hashable key for a vertex, for the solve cache.

    Bisection midpoints are shared by siblings and by neighbouring
    simplices; caching per-vertex oracle solutions reproduces the
    reference's work complexity (SURVEY.md section 8 layer 3, "vertex-solve
    caching").  Rounding makes keys stable under the exact-midpoint
    arithmetic used here (midpoints are computed identically everywhere).
    """
    return np.round(np.asarray(v, dtype=np.float64), decimals).tobytes()


def vertex_keys(V: np.ndarray, decimals: int = 9) -> list[bytes]:
    """Per-row vertex_key for an (m, p) vertex matrix.

    Byte-identical to [vertex_key(v) for v in V] (np.round is
    elementwise), in ONE rounding pass: per-vertex rounding was the
    single largest host cost in cluster-scale step profiles (~350 k
    np.round calls per dozen steps at the 800 k-region satellite)."""
    R = np.round(np.asarray(V, dtype=np.float64), decimals)
    return [R[i].tobytes() for i in range(R.shape[0])]
