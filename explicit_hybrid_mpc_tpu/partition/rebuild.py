"""Incremental warm rebuild: certificate-reuse tree transfer across
problem revisions.

The offline stage pays the full simplex-subdivision cost -- per-vertex
oracle grids, stage-2 joint QPs, eps-certification -- for every
controller it ships, yet in the production setting (ROADMAP: trees
rebuilt continuously as plant models, horizons, or eps targets are
revised and hot-swapped into ``serve/``) successive problems are
overwhelmingly similar.  The cheapest compute is *not solving at all*:
transfer the previous tree, re-certify its leaves in bulk against the
revised oracle, and subdivide only what the revision invalidated.

``warm_rebuild(problem, cfg, prior)`` runs three phases:

1. **Transfer**: the prior tree (a ``Tree``/tree pickle, or a build
   checkpoint -- whose ``VertexCache`` rows additionally donate warm
   starts) is copied bit-identically and re-stamped with the new build
   provenance (partition/provenance.py).  Priors whose root
   triangulation or problem shapes cannot transfer raise
   ``RebuildError`` (a cold build is required); a provenance diff of
   everything else is recorded in the stats.
2. **Re-certification sweep**: the prior build's stage-2 fact ledger
   (Tree.excl_events: whole-simplex Farkas exclusions + finite simplex
   lower bounds, recorded at the node that proved them) is re-VERIFIED
   against the new oracle and the survivors inherited down the tree --
   the sweep's stand-in for cold-build bound inheritance
   (_verify_excl_events).  Every prior leaf's vertices are then solved
   in pow-2 buckets through the engine's own MASKED planner
   (re-verified ancestor exclusions skip their point cells; vertices
   with a prior-checkpoint donor row go through the warm pair path,
   started from the cached prior duals/slacks exactly like the
   in-build tree warm-starts), and each leaf's STORED certificate is
   re-checked: eps-certified leaves via the stored-delta stage-1/
   stage-2 bounds (certify.recertify_stored_stage1/_stage2; loose
   ledger bounds retry exactly on the leaf, the frontier's round A/B),
   infeasible leaves via the re-verified emptiness certificates plus
   leaf-exact checks.  A pass keeps the leaf UNTOUCHED -- payloads are
   never rewritten, which is both the perf point and what makes an
   unchanged-problem rebuild bit-identical.
3. **Frontier re-entry**: invalidated leaves drop their payload
   (Tree.clear_leaf), seed their sweep-learned stage-2 facts into the
   bound-inheritance map, and re-enter the ordinary ``BuildPipeline``
   frontier, which runs exactly as a cold build from there
   (speculation/dedup/two-phase/Pallas tiers inherited).

Contract (tests/test_rebuild.py): an UNCHANGED problem rebuilds
node-for-node bit-identical with ZERO subdivision solves (the sweep is
the only oracle traffic); any revision produces a tree whose every
kept or newly-built leaf carries the same certificates a cold build
would establish -- reuse is a perf tier, never a correctness
relaxation.  One caveat at scale, the same last-ulp pow-2-bucket class
the build pipeline documents: the sweep's batch shapes differ from the
original build's, so a KNIFE-EDGE certificate (a Farkas cert or gap
within float noise of its threshold) can flip and invalidate a handful
of leaves even on an unchanged problem -- those leaves re-certify
soundly through the frontier (measured: 15 of 12,033 flagship pendulum
leaves; 0 on the tier-1 acceptance config, which is exactly
bit-identical).  A kept leaf's certificate is re-proved from fresh oracle
data under the NEW problem/eps (the sweep's pass is exactly the cold
build's certificate mathematics, docs/certificates.md); the only
structural difference to a cold build is that the transferred tree may
be FINER than necessary (a certificate that now holds higher up is not
coarsened), which is sound by refinement.

Leaves that carried no eps-certificate (depth-cap best-effort,
semi-explicit boundary leaves) are conservatively invalidated and
re-opened -- they re-close through the frontier's own rules.

Publish: ``publish_rebuild`` exports the result as a provenance-
stamped serving artifact directory and (optionally) hot-swaps it into
a ``serve.ControllerRegistry`` as a new version under the same
controller name (two-epoch handoff, docs/serving.md).
"""

from __future__ import annotations

import os
import time

import numpy as np

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition import certify, geometry
from explicit_hybrid_mpc_tpu.partition import provenance as prov
from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                        PartitionResult,
                                                        _donor_warm,
                                                        make_oracle)
from explicit_hybrid_mpc_tpu.partition.tree import NO_CHILD, Tree
from explicit_hybrid_mpc_tpu.utils.logging import RunLog


class RebuildError(ValueError):
    """The prior artifact cannot transfer to the revised problem at all
    (root triangulation / shape mismatch): a cold build is required."""


class RebuildResult(PartitionResult):
    """PartitionResult whose stats additionally carry the rebuild_*
    reuse/invalidation accounting (see warm_rebuild)."""


def _load_prior(prior) -> tuple[Tree, dict, str]:
    """(prior tree, prior VertexCache rows or {}, source kind).

    Accepts a PartitionResult (the immediately-preceding build/rebuild,
    chained in memory by the continuous-rebuild daemon -- no disk
    round-trip per generation), a Tree instance, a tree pickle path
    (main.py's PREFIX.tree.pkl), a build-checkpoint path
    (PREFIX.ckpt.pkl -- its cache rows become warm-start donors), or an
    already-loaded checkpoint dict.  (A serve-registry version is NOT
    accepted: it carries only the flat leaf table, no tree structure --
    keep the PartitionResult next to what you publish.)"""
    if isinstance(prior, PartitionResult):
        return prior.tree, {}, "result"
    if isinstance(prior, Tree):
        return prior, {}, "tree"
    if isinstance(prior, dict):
        return prior["tree"], prior.get("cache", {}) or {}, "checkpoint"
    if not isinstance(prior, (str, os.PathLike)):
        raise RebuildError(f"unsupported prior type {type(prior)!r}")
    if os.path.isdir(prior):
        # Serving artifact dirs hold only the flat leaf table -- no
        # internal structure to transfer.  A tree pickle next to the
        # artifacts makes them rebuild-capable.
        cand = os.path.join(prior, "tree.pkl")
        if os.path.exists(cand):
            return Tree.load(cand), {}, "artifact"
        raise RebuildError(
            f"{prior} is an artifact directory without a tree.pkl: "
            "flat leaf tables carry no tree structure to transfer -- "
            "pass the build's .tree.pkl or .ckpt.pkl instead")
    from explicit_hybrid_mpc_tpu.utils import atomic

    try:
        obj, _checked = atomic.read_checked_pickle(prior)
    except atomic.CorruptArtifact as e:
        raise RebuildError(
            f"prior {prior} failed its integrity check ({e}): a "
            "truncated/corrupt prior transfers garbage structure -- "
            "restore a previous generation (.prev for checkpoints) or "
            "run a cold build") from e
    if isinstance(obj, Tree):
        return obj, {}, "tree"
    if isinstance(obj, dict) and "tree" in obj:
        return obj["tree"], obj.get("cache", {}) or {}, "checkpoint"
    raise RebuildError(f"{prior} contains neither a Tree nor a build "
                       "checkpoint")


def _donor_rows(prior_cache: dict) -> dict:
    """Prior-checkpoint cache rows usable as warm-start donors: the
    10-slot layout with live duals (shimmed exactly like
    FrontierEngine.resume shims restored rows)."""
    donors: dict[bytes, tuple] = {}
    for k, row in prior_cache.items():
        if len(row) >= 10 and row[8] is not None:
            donors[k] = row
    return donors


def _check_transferable(prior_tree: Tree, problem) -> None:
    """Raise RebuildError when the prior's geometry cannot host the
    revised problem: parameter/input dims and the root triangulation
    must match bit-exactly (children are midpoint functions of roots,
    so a root drift poisons every vertex)."""
    if prior_tree.p != problem.n_theta or prior_tree.n_u != problem.n_u:
        raise RebuildError(
            f"prior tree has (p={prior_tree.p}, n_u={prior_tree.n_u}) "
            f"but the revised problem has (p={problem.n_theta}, "
            f"n_u={problem.n_u}): nothing transfers, run a cold build")
    roots_V = geometry.box_triangulation(
        problem.theta_lb, problem.theta_ub,
        getattr(problem, "root_splits", None))
    prior_roots = prior_tree.roots()
    if len(prior_roots) != len(roots_V) or not all(
            np.array_equal(prior_tree.vertices[r], V)
            for r, V in zip(prior_roots, roots_V)):
        raise RebuildError(
            "prior tree's root triangulation differs from the revised "
            "problem's box (theta bounds or root_splits changed): "
            "vertex geometry does not transfer, run a cold build")


def _verify_excl_events(eng: FrontierEngine, tree: Tree, nd: int
                        ) -> tuple[list, list, int, int]:
    """Re-verify the prior build's Farkas EXCLUSION events against the
    NEW oracle and push the survivors down the tree; index the FINITE
    bound events for lazy re-solving.

    Returns (excl_of, finfact_of, n_events, n_excl_ok):

    - ``excl_of[node]``: accumulated {delta: +inf} exclusions inherited
      from re-verified ancestor emptiness certificates (shared dict
      refs -- one object per distinct set);
    - ``finfact_of[node]``: {delta: fact_node} pointing at the DEEPEST
      ancestor (or the node itself) where the prior build solved a
      finite simplex lower bound for that commutation -- the sweep
      re-solves the bound AT THAT NODE on demand, one joint QP shared
      by every descendant leaf (the cold build's inheritance shape,
      re-proved fresh).

    This is the sweep's answer to cold-build bound inheritance: each
    event is ONE certificate covering every descendant leaf, re-proved
    under the revised problem (reuse is never trusted, only
    re-targeted).  Without the ledger (legacy priors) every pending
    (leaf, commutation) pays its own joint QP -- correct, but the
    dominant sweep cost on hybrid problems."""
    # Last-wins dedup at first-occurrence position: a chained rebuild's
    # frontier appends FRESH facts after the transferred prior ledger,
    # and the freshest fact for a (node, delta) is the one to re-verify;
    # keeping the first occurrence's position preserves the exact list
    # (hence tree bit-identity) when there are no duplicates.
    seen: dict[tuple[int, int], float] = {}
    for a, d, v in tree.excl_events:
        key = (int(a), int(d))
        if 0 <= key[1] < nd and 0 <= key[0] < len(tree):
            seen[key] = float(v)
    inf_events = [k for k, v in seen.items() if v == np.inf]
    verified: dict[int, set] = {}
    n_ok = 0
    if inf_events:
        # Batched barycentric inverses: a python-loop
        # barycentric_matrix per event is ~seconds of pure host
        # overhead at flagship ledger sizes (~20k events).
        nodes_a = np.array([a for a, _ in inf_events], dtype=np.int64)
        Ms = geometry.barycentric_matrices(tree.vertices[nodes_a])
        ds = np.array([d for _, d in inf_events], dtype=np.int64)
        _t, _f, cert = eng._oracle_call("simplex_feasibility", Ms, ds)
        for (a, d), ok in zip(inf_events, cert):
            if ok:
                n_ok += 1
                verified.setdefault(a, set()).add(d)
    fin_at: dict[int, list[int]] = {}
    for (a, d), v in seen.items():
        if np.isfinite(v):
            fin_at.setdefault(a, []).append(d)
    # Push down: children inherit the parent's accumulated maps (node
    # ids ascend parent-before-child by construction); nodes adding
    # nothing SHARE the parent's dict -- O(distinct sets) memory.
    # Deeper finite facts override shallower ones (tighter bounds,
    # exactly like frontier inheritance).
    parent = tree.parent
    empty_b: dict[int, float] = {}
    empty_f: dict[int, int] = {}
    excl_of: list = [None] * len(tree)
    finfact_of: list = [None] * len(tree)
    for i in range(len(tree)):
        pi = int(parent[i])
        base_b = excl_of[pi] if pi >= 0 else empty_b
        base_f = finfact_of[pi] if pi >= 0 else empty_f
        mine_b = verified.get(i)
        if mine_b:
            base_b = dict(base_b)
            for d in mine_b:
                base_b[d] = np.inf
        mine_f = fin_at.get(i)
        if mine_f:
            base_f = dict(base_f)
            for d in mine_f:
                base_f[d] = i
        excl_of[i] = base_b
        finfact_of[i] = base_f
    # The REBUILT tree's ledger: deduped facts minus exclusion events
    # that failed re-verification (a dead event would otherwise be
    # re-checked -- and fail -- on every future rebuild, and the
    # ledger would grow monotonically across chained rebuilds).
    surviving = [(a, d, v) for (a, d), v in seen.items()
                 if np.isfinite(v) or d in verified.get(a, ())]
    return excl_of, finfact_of, len(seen), n_ok, surviving


def _inject_prior_donors(plan: dict, donors: dict) -> None:
    """Override a plan's pair-path warm starts with SAME-VERTEX donor
    rows from a prior checkpoint's VertexCache: the prior solution of
    the exact vertex being re-solved is a strictly better IPM start
    than the sibling-vertex donor _plan_missing picked (and the merit
    gate still protects against a stale one).  Mutates the plan's
    pair_warm arrays in place; wire order (z, s, lam, has) matches
    _PlanBuilder / Oracle.dispatch_pairs."""
    if not donors or plan.get("pair_warm") is None:
        return
    zw, sw, lw, hw = plan["pair_warm"]
    for k, ds, lo in plan["pair_slices"]:
        drow = donors.get(k)
        if drow is None:
            continue
        z2, l2, s2, h2 = _donor_warm(drow, ds)
        sl = slice(lo, lo + ds.size)
        zw[sl], sw[sl], lw[sl], hw[sl] = z2, s2, l2, h2


def _dispatch_sweep(eng: FrontierEngine, plan: dict):
    """Run a sweep plan's device programs (grid + warm pairs) through
    the engine's device-failure-fallback oracle path."""
    sol = pair_out = None
    if plan["grid_arr"] is not None:
        sol = eng._oracle_call("solve_vertices", plan["grid_arr"])
    if plan["pair_t"] is not None:
        pair_out = eng._oracle_call("solve_pairs_full", plan["pair_t"],
                                    plan["pair_d"], plan["pair_warm"])
    return sol, pair_out


def _capture_recert(eng: FrontierEngine, node: int, sd, delta_idx: int,
                    gap: float, vmin: np.ndarray | None) -> None:
    """Repro bundle for an INVALIDATED stored-delta re-certification
    (recorder on): cell geometry + certification snapshot + the stage-2
    bounds the verdict consumed, replayable standalone by
    scripts/replay_solve.py (kind='recert')."""
    from explicit_hybrid_mpc_tpu.obs import recorder as rec_lib

    nd = eng.oracle.can.n_delta
    arrays = {**rec_lib.canonical_arrays(eng.oracle.can),
              **certify.cell_snapshot(sd)}
    arrays["recert_vmin"] = (np.full(nd, np.nan) if vmin is None
                             else np.asarray(vmin, dtype=np.float64))
    eng.recorder.dump(
        "recert_invalidated", arrays,
        {"kind": "recert",
         "oracle": rec_lib.oracle_meta(eng.oracle),
         "backend": eng.oracle.backend,
         "node": int(node), "delta_idx": int(delta_idx),
         "gap": float(gap) if np.isfinite(gap) else None,
         "eps_a": eng.cfg.eps_a, "eps_r": eng.cfg.eps_r})


def warm_rebuild(problem, cfg: PartitionConfig, prior,
                 oracle: Oracle | None = None,
                 obs: "obs_lib.Obs | None" = None,
                 log: RunLog | None = None,
                 strict_provenance: bool = False,
                 priority: dict | None = None) -> RebuildResult:
    """Rebuild a fully eps-certified tree for (problem, cfg) by
    transferring `prior` (see module docstring).

    strict_provenance: refuse priors that carry NO provenance stamp
    (legacy artifacts cannot be validated against the revision; the
    default shims them with a stats note and proceeds -- the sweep
    itself re-proves every kept certificate either way).

    priority: optional {tree node id: weight} demand hint
    (obs/demand.py ``priority_from_snapshot`` maps a serving traffic
    snapshot's leaf rows to node ids).  Invalidated leaves re-enter
    the frontier hottest-first instead of in node order, so under an
    interrupted or wall-bounded rebuild the leaves live traffic
    actually visits are re-certified before cold corners.  It is an
    ORDERING hint only: the same leaves are processed either way, so
    a rebuild that re-certifies every invalidated leaf WITHOUT
    splitting yields a bit-identical tree (node numbering only
    diverges when splits allocate fresh node ids in a different
    order; the tier-1 priority smoke pins the no-split case).  Nodes
    missing from the map sort as weight 0 in node order -- a stale
    snapshot degrades to the default ordering, never to an error.

    Returns a RebuildResult whose stats extend the ordinary build
    stats with::

        rebuild_leaves_total / _recertified / _reused / _invalidated
        rebuild_reuse_frac          kept / total prior leaves
        recert_solves               oracle solves issued by the sweep
        subdivision_solves          oracle solves issued by the frontier
        sweep_wall_s / rebuild_wall_s
        provenance_changed          field-level prior-vs-new stamp diff
        rebuild_priority_hint       nodes matched by the demand hint
        rebuild_priority_order      first frontier entries (hint runs)
    """
    t0 = time.perf_counter()
    # Fault-injection site (faults/injector.py): scripted failures at
    # the rebuild boundary -- the sweep inherits the engine's full
    # bounded-recovery policy for everything downstream.
    from explicit_hybrid_mpc_tpu.faults import injector as faults_inj

    faults_inj.fire("rebuild.sweep")
    prior_tree, prior_cache, src = _load_prior(prior)
    prior_stamp = getattr(prior_tree, "provenance", None)
    if strict_provenance and prior_stamp is None:
        raise prov.ProvenanceMismatch(
            "prior artifact carries no provenance stamp and "
            "strict_provenance is set: cannot validate what problem/"
            "config it was built for (re-export it from a stamped "
            "build, or drop --strict-provenance to shim)")
    if oracle is None:
        oracle = make_oracle(problem, cfg)
    _check_transferable(prior_tree, problem)
    new_stamp = prov.build_stamp(problem, cfg)
    stamp_diffs = prov.diff_stamps(prior_stamp, new_stamp)

    # Bit-identical structure transfer: columnar copy (Tree.clone; a
    # prior loaded from disk was already normalized + vertex-rederived
    # by __setstate__, and an in-memory prior is columnar by
    # construction -- the old pickle.dumps round-trip serialized
    # O(tree) bytes per generation in the rebuild daemon's hot loop
    # for a copy the columns give directly).
    new_tree: Tree = prior_tree.clone()
    new_tree.provenance = new_stamp

    eng = FrontierEngine.resume(
        {"tree": new_tree, "roots": new_tree.roots(), "frontier": [],
         "cache": {}, "steps": 0, "n_uncertified": 0,
         "n_semi_explicit": 0, "n_unique_solves": 0, "cfg": cfg},
        problem, oracle, log=log, cfg=cfg, obs=obs)
    nd = oracle.can.n_delta
    tree = eng.tree
    childless = np.nonzero(tree.children[:, 0] == NO_CHILD)[0]
    data_ids = tree.converged_leaf_ids()
    deltas = tree.leaf_payloads(data_ids)[0] if data_ids.size else \
        np.zeros(0, dtype=np.int32)
    cert_mask = tree.certified_flags(data_ids)
    # Re-certifiable: eps-certified leaves with a transferable delta,
    # plus closed-infeasible leaves (childless, no payload).  Best-
    # effort/semi-explicit leaves carried no certificate to transfer:
    # conservatively invalidated (they re-close through the frontier's
    # own depth rules).
    recert_ok = cert_mask & (deltas < nd)
    certified = set(int(i) for i in data_ids[recert_ok])
    stored_delta = {int(i): int(d)
                    for i, d in zip(data_ids[recert_ok],
                                    deltas[recert_ok])}
    infeasible = set(int(i) for i in childless) \
        - set(int(i) for i in data_ids)
    pre_invalid = [int(i) for i in data_ids[~recert_ok]]
    sweep_nodes = sorted(certified | infeasible)
    n_total = len(sweep_nodes) + len(pre_invalid)

    # Retain every prior leaf up front: shared vertices between a kept
    # leaf (released after its verdict) and a later batch's leaf must
    # not be evicted mid-sweep, and every node that enters the frontier
    # must hold its refcounts like step()-split children do.
    for node in sweep_nodes:
        eng._retain(node)
    for node in pre_invalid:
        eng._retain(node)

    donors = _donor_rows(prior_cache)
    feasible_variant = getattr(cfg, "algorithm", "suboptimal") == "feasible"
    use_inh = getattr(cfg, "inherit_bounds", True)
    n_reused = n_invalid = 0
    invalid_nodes: list[int] = []

    def invalidate(node: int, facts: dict | None = None) -> None:
        nonlocal n_invalid
        n_invalid += 1
        tree.clear_leaf(node)
        if facts and use_inh:
            # MERGE on top of the ancestor-exclusion seeds (below):
            # both are inherited facts for the frontier phase.
            eng._inherit.setdefault(node, {}).update(facts)
        invalid_nodes.append(node)

    def keep(node: int) -> None:
        nonlocal n_reused
        n_reused += 1
        # Kept leaves never reach a frontier commit: drop their
        # ancestor-exclusion seeds and cache refcounts here.
        eng._inherit.pop(node, None)
        eng._release(node)

    for node in pre_invalid:
        invalidate(node)

    # Sweep chunk size: leaves are verdict-independent, so the sweep
    # batches far wider than the frontier's step size -- the oracle
    # still pads/chunks device programs at its own pow-2 caps (no new
    # compiled shapes), and fewer chunks means fewer host passes
    # (gather/plan/certify fixed costs).  The floor is 1024 leaves
    # REGARDLESS of cfg.batch_simplices (chunk memory is a few MB of
    # vertex rows; a larger batch_simplices widens chunks further).
    batch = max(1024, cfg.batch_simplices)
    # Re-verify the prior build's Farkas exclusion ledger ONCE and
    # inherit the survivors down the tree (see _verify_excl_events):
    # the per-node exclusion dicts seed the engine's inheritance map
    # chunk by chunk, so the ORDINARY masked planner skips the excluded
    # point cells exactly like a cold build, and the stage-2 keep-check
    # reads their +inf bounds for free.  Exclusions are eps-independent
    # feasibility geometry, so an eps-only revision re-verifies the
    # whole ledger.
    with eng.obs.span("rebuild.verify_exclusions"):
        (excl_of, finfact_of, n_excl_events, n_excl_ok,
         surviving_events) = _verify_excl_events(eng, tree, nd)
    # The new tree carries the PRUNED ledger (dead exclusions dropped,
    # duplicates collapsed); the frontier phase appends its fresh facts
    # on top, so chained rebuilds stay bounded.
    tree.excl_events = surviving_events
    if use_inh:
        # Pre-invalidated leaves (best-effort/semi-explicit) re-opened
        # above inherit the re-verified exclusions too -- their
        # re-subdivision then masks point cells like any cold child.
        for node in invalid_nodes:
            excl = excl_of[node]
            if excl:
                eng._inherit.setdefault(node, {}).update(excl)
    # Finite-bound facts re-solve LAZILY at their recorded node, once,
    # shared by every descendant leaf that demands them.
    fact_memo: dict[tuple[int, int], float] = {}
    bary_memo: dict[int, np.ndarray] = {}

    def _bary(node: int) -> np.ndarray:
        M = bary_memo.get(node)
        if M is None:
            M = geometry.barycentric_matrix(tree.vertices[node])
            bary_memo[node] = M
        return M

    with eng.obs.span("rebuild.sweep"):
        for lo in range(0, len(sweep_nodes), batch):
            chunk = sweep_nodes[lo:lo + batch]
            if use_inh:
                for n in chunk:
                    excl = excl_of[n]
                    if excl:
                        eng._inherit.setdefault(n, {}).update(excl)
            plan = eng._plan_missing(chunk)
            if plan is not None:
                _inject_prior_donors(plan, donors)
                sol, pair_out = _dispatch_sweep(eng, plan)
                eng._merge_plan_results(plan, sol, pair_out)
            sds, _ = eng._gather_batch(chunk)
            pending: dict[int, certify.CertificateResult] = {}
            farkas_pend: list[int] = []
            for node in chunk:
                sd = sds[node]
                if node in infeasible:
                    if certify.recertify_infeasible(sd) == "split":
                        invalidate(node)
                    else:
                        farkas_pend.append(node)
                    continue
                d = stored_delta[node]
                if feasible_variant:
                    # Feasibility-only partitions: the stored law's
                    # certificate IS vertex convergence + convexity.
                    if bool(np.all(sd.conv[:, d])):
                        keep(node)
                    else:
                        invalidate(node)
                    continue
                res = certify.recertify_stored_stage1(
                    sd, d, cfg.eps_a, cfg.eps_r)
                if res.status == "certified":
                    keep(node)
                elif res.status == "split":
                    if eng.recorder is not None:
                        try:  # diagnostics must never break the sweep
                            _capture_recert(eng, node, sd, d, res.gap,
                                            None)
                        except Exception:  # tpulint: disable=silent-except -- diag
                            pass
                    invalidate(node)
                else:  # pending: stage-2 bounds needed
                    pending[node] = res

            # -- Farkas re-proof for closed-infeasible leaves ----------
            # The re-verified ledger already covers most commutations;
            # only the ones with no surviving ancestor certificate need
            # a leaf-exact emptiness check.
            if farkas_pend:
                rows_b = []
                unproved: dict[int, list[int]] = {}
                for nn in farkas_pend:
                    excl = excl_of[nn]
                    miss = [d for d in range(nd) if d not in excl]
                    unproved[nn] = miss
                    rows_b.extend((nn, d) for d in miss)
                exact: dict[tuple[int, int], bool] = {}
                if rows_b:
                    Ms = np.stack([_bary(n2) for n2, _ in rows_b])
                    ds = np.array([d for _, d in rows_b],
                                  dtype=np.int64)
                    _t, _f, cert = eng._oracle_call(
                        "simplex_feasibility", Ms, ds)
                    for key, ok in zip(rows_b, cert):
                        exact[key] = bool(ok)
                for nn in farkas_pend:
                    if all(exact[(nn, d)] for d in unproved[nn]):
                        # Still certified empty on all of R: the closed
                        # infeasible leaf stands untouched.
                        keep(nn)
                    else:
                        facts = {d: np.inf for d in unproved[nn]
                                 if exact[(nn, d)]}
                        invalidate(nn, facts)

            # -- stage-2 bounds for stored-delta keeps -----------------
            # Three tiers per pending commutation: ledger exclusions
            # carry +inf for free; commutations with a finite ledger
            # fact re-solve the bound AT THE RECORDED NODE (memoized --
            # one joint QP shared by every descendant leaf) and try the
            # certificate with that valid-but-possibly-loose bound (the
            # frontier's round A); only commutations with no fact, or
            # whose loose bound failed the keep, pay an EXACT leaf
            # solve (round B).
            if pending:
                fact_rows: list[tuple[int, int]] = []
                seen_rows: set[tuple[int, int]] = set()
                rows_l: list[tuple[int, int]] = []
                for nn, res in pending.items():
                    excl = excl_of[nn]
                    fin = finfact_of[nn]
                    for dp in res.pending_deltas:
                        dp = int(dp)
                        if dp in excl:
                            continue
                        fn = fin.get(dp)
                        if fn is None:
                            rows_l.append((nn, dp))  # no fact: exact
                        else:
                            key = (fn, dp)
                            if key not in fact_memo \
                                    and key not in seen_rows:
                                seen_rows.add(key)
                                fact_rows.append(key)
                if fact_rows:
                    Ms = np.stack([_bary(a) for a, _ in fact_rows])
                    ds = np.array([d for _, d in fact_rows],
                                  dtype=np.int64)
                    Vmin, _f = eng._oracle_call("solve_simplex_min",
                                                Ms, ds)
                    for key, v in zip(fact_rows, Vmin):
                        fact_memo[key] = float(v)
                vm_exact: dict[tuple[int, int], float] = {}
                if rows_l:
                    Ms = np.stack([_bary(n2) for n2, _ in rows_l])
                    ds = np.array([d for _, d in rows_l],
                                  dtype=np.int64)
                    Vmin, _f = eng._oracle_call("solve_simplex_min",
                                                Ms, ds)
                    for key, v in zip(rows_l, Vmin):
                        vm_exact[key] = float(v)

                def _leaf_vm(nn: int, res) -> tuple[dict, list[int]]:
                    """(per-delta bounds, loose deltas): exclusion /
                    exact bounds are final; fact-node bounds are loose
                    unless the fact node IS the leaf."""
                    excl = excl_of[nn]
                    fin = finfact_of[nn]
                    vm: dict[int, float] = {}
                    loose: list[int] = []
                    for dp in res.pending_deltas:
                        dp = int(dp)
                        if dp in excl:
                            vm[dp] = np.inf
                        elif (nn, dp) in vm_exact:
                            vm[dp] = vm_exact[(nn, dp)]
                        else:
                            fn = fin[dp]
                            vm[dp] = fact_memo[(fn, dp)]
                            if fn != nn and vm[dp] != np.inf:
                                loose.append(dp)
                    return vm, loose

                round_b: list[tuple[int, int]] = []
                loose_of: dict[int, list[int]] = {}
                vm_of: dict[int, dict[int, float]] = {}
                for nn, res in pending.items():
                    d = stored_delta[nn]
                    gaps = res._stage1_gap[0]
                    vm, loose = _leaf_vm(nn, res)
                    vm_of[nn] = vm
                    u_max = float(np.max(sds[nn].V[:, d]))
                    ok, _g = certify.recertify_stored_stage2(
                        gaps, u_max, sds[nn].Vstar, vm, cfg.eps_a,
                        cfg.eps_r)
                    if ok:
                        keep(nn)
                        pending[nn] = None
                        continue
                    if loose:
                        loose_of[nn] = loose
                        round_b.extend((nn, dp) for dp in loose)
                if round_b:
                    Ms = np.stack([_bary(n2) for n2, _ in round_b])
                    ds = np.array([d for _, d in round_b],
                                  dtype=np.int64)
                    Vmin, _f = eng._oracle_call("solve_simplex_min",
                                                Ms, ds)
                    for (nn, dp), v in zip(round_b, Vmin):
                        vm_of[nn][dp] = float(v)
                for nn, res in pending.items():
                    if res is None:
                        continue  # kept in round A
                    vm = vm_of[nn]
                    d = stored_delta[nn]
                    gaps = res._stage1_gap[0]
                    u_max = float(np.max(sds[nn].V[:, d]))
                    kept = False
                    if nn in loose_of:
                        kept, _g = certify.recertify_stored_stage2(
                            gaps, u_max, sds[nn].Vstar, vm, cfg.eps_a,
                            cfg.eps_r)
                    if kept:
                        keep(nn)
                        continue
                    # Invalidated: seed the frontier's inheritance map
                    # with what the sweep proved -- ledger exclusions,
                    # re-proved fact bounds (valid ancestor bounds for
                    # this node and its children), and exact leaf
                    # bounds are inherited facts exactly like step()'s
                    # fresh results (-inf stalls are never stored,
                    # matching step()).
                    if eng.recorder is not None:
                        try:  # diagnostics must never break the sweep
                            vmin_vec = np.full(nd, np.nan)
                            for dp, v in vm.items():
                                vmin_vec[dp] = v
                            _capture_recert(eng, nn, sds[nn], d,
                                            np.inf, vmin_vec)
                        except Exception:  # tpulint: disable=silent-except -- diag
                            pass
                    invalidate(nn, {dp: v for dp, v in vm.items()
                                    if v != -np.inf})

    sweep_s = time.perf_counter() - t0
    recert_solves = oracle.n_solves
    n_recert = len(sweep_nodes)
    reuse_frac = n_reused / max(1, n_total)
    o = eng.obs
    if o.enabled:
        m = o.metrics
        m.counter("rebuild.leaves_recertified").inc(n_recert)
        m.counter("rebuild.leaves_reused").inc(n_reused)
        m.counter("rebuild.leaves_invalidated").inc(n_invalid)
        m.counter("rebuild.recert_solves").inc(recert_solves)
        m.gauge("rebuild.reuse_frac").set(reuse_frac)
        rec = o.event("rebuild.sweep", prior_source=src,
                      leaves_total=n_total, recertified=n_recert,
                      reused=n_reused, invalidated=n_invalid,
                      reuse_frac=round(reuse_frac, 4),
                      recert_solves=recert_solves,
                      sweep_s=round(sweep_s, 3),
                      provenance_changed=stamp_diffs)
        if eng._health is not None:
            # The reuse-collapse rule reads the metrics snapshot; feed
            # one now so an unchanged rebuild (zero frontier steps --
            # the engine's periodic feed never runs) still gets a
            # verdict.
            eng._health.feed(rec)
            snap = o.flush_metrics()
            if snap is not None:
                eng._health.feed(snap)
    eng.log.emit(rebuild_sweep=True, leaves_total=n_total,
                 reused=n_reused, invalidated=n_invalid,
                 reuse_frac=round(reuse_frac, 4),
                 recert_solves=recert_solves,
                 sweep_s=round(sweep_s, 3))

    # Invalidated leaves re-enter the frontier IN NODE ORDER (the
    # deterministic order a resumed build would see them) unless a
    # demand priority hint reorders them hottest-first (docstring);
    # then the ordinary pipelined build runs to completion.
    n_hinted = 0
    if priority:
        pr = {int(k): float(v) for k, v in priority.items()}
        entry = sorted(invalid_nodes,
                       key=lambda n2: (-pr.get(int(n2), 0.0), int(n2)))
        n_hinted = sum(1 for n2 in invalid_nodes
                       if pr.get(int(n2), 0.0) > 0)
    else:
        entry = sorted(invalid_nodes)
    for node in entry:
        eng.frontier.append(node)
    res = eng.run()

    wall = time.perf_counter() - t0
    stats = dict(res.stats)
    stats.update(
        rebuild_prior_source=src,
        rebuild_prior_regions=int(prior_tree.n_regions()),
        rebuild_leaves_total=n_total,
        rebuild_leaves_recertified=n_recert,
        rebuild_leaves_reused=n_reused,
        rebuild_leaves_invalidated=n_invalid,
        rebuild_reuse_frac=round(reuse_frac, 4),
        recert_solves=recert_solves,
        subdivision_solves=oracle.n_solves - recert_solves,
        sweep_wall_s=round(sweep_s, 3),
        rebuild_wall_s=round(wall, 3),
        regions_per_s=res.tree.n_regions() / max(wall, 1e-9),
        provenance_changed=stamp_diffs,
        warm_donor_vertices=len(donors),
        # Prior Farkas exclusion ledger economy: events carried by the
        # prior tree vs events whose certificates re-verified under the
        # revised problem (each survivor covers every descendant leaf's
        # pending commutation for free).
        rebuild_excl_events=n_excl_events,
        rebuild_excl_reverified=n_excl_ok,
        # Demand-hint consumption (docstring): how many invalidated
        # leaves the hint actually ranked, and the order the first of
        # them entered the frontier -- the priority smoke asserts hot
        # nodes lead it.
        rebuild_priority_hint=n_hinted,
        rebuild_priority_order=[int(n2) for n2 in entry[:16]],
    )
    return RebuildResult(res.tree, res.roots, stats)


def publish_rebuild(result: PartitionResult, dir_path: str,
                    registry=None, name: str = "default",
                    version: str | None = None,
                    **load_kwargs) -> str:
    """Export `result` as a provenance-stamped serving artifact
    directory and, when a ``serve.ControllerRegistry`` is given,
    publish it as a new version under `name` (atomic two-epoch hot
    swap -- in-flight batches drain on the old tree, docs/serving.md).
    Returns the version string (default: derived from the build
    stamp's problem hash + eps, so successive rebuilds of the same
    revision publish under the same version name)."""
    from explicit_hybrid_mpc_tpu.serve import registry as reg_mod

    stamp = getattr(result.tree, "provenance", None)
    if version is None:
        if stamp is not None:
            version = (f"rebuild-{stamp['problem_hash'][:8]}"
                       f"-eps{stamp['eps_a']:g}")
        else:
            version = f"rebuild-r{result.tree.n_regions()}"
    reg_mod.save_artifacts(result.tree, result.roots, dir_path,
                           provenance=stamp)
    if registry is not None:
        registry.load_artifacts(name, version, dir_path,
                                expect_provenance=stamp, **load_kwargs)
    return version
