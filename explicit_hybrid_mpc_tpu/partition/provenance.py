"""Build provenance stamps for trees, checkpoints, and serving artifacts.

A built tree is only meaningful RELATIVE to the problem and solver
configuration that produced it: deploying a tree against a revised plant
model, or warm-rebuilding from a tree whose eps targets drifted, silently
serves/reuses certificates that no longer mean what the consumer thinks
they mean.  Every writer therefore stamps its artifact with a provenance
dict -- the canonical-problem content hash, the eps targets, the solver
schedule knobs that change solve RESULTS (not just speed), and the
code/schema versions -- and every loader can compare a found stamp
against the problem/config it is about to use:

- ``Tree.provenance`` rides the tree pickle (and therefore every
  checkpoint, which pickles the tree);
- ``online/export.write_leaf_table``/``save_leaf_table`` put the stamp
  into the table's ``meta.json``; ``load_leaf_table`` checks it;
- ``serve/registry.save_artifacts``/``load_artifacts`` stamp/check the
  serving artifact directory (a deploy against the wrong problem is the
  serving-side failure this catches);
- ``partition/rebuild.py`` reads the prior stamp to report exactly WHAT
  changed between revisions (the invalidation telemetry), and rejects
  priors whose geometry cannot transfer at all.

Mismatch policy: loaders WARN by default (``ProvenanceWarning``) and
raise ``ProvenanceMismatch`` under ``strict=True``; artifacts written
before stamping existed ("legacy") load with a one-line unstamped
warning, never an error.  docs/perf.md "Incremental warm rebuild"
documents the format.
"""

from __future__ import annotations

import hashlib
import warnings

import numpy as np

#: Version of the stamp schema itself (bump on incompatible layout
#: changes; readers tolerate unknown EXTRA keys at the same version).
PROVENANCE_VERSION = 1

#: CanonicalMPQP fields folded into the problem hash, in fixed order.
#: This is the COMPLETE canonical problem: two problems hash equal iff
#: every matrix the oracle consumes is bit-equal.
_CANONICAL_FIELDS = ("H", "f", "F", "G", "w", "S", "Y", "pvec", "cconst",
                     "u_map", "u_theta", "u_const", "deltas")

#: Solver config knobs that change solve RESULTS (iterate trajectories,
#: convergence patterns) rather than just wall time.  Pipeline/obs/
#: output knobs are deliberately absent: they are bit-invisible to the
#: produced tree and must not invalidate reuse.
_SOLVER_FIELDS = ("backend", "precision", "ipm_point_schedule",
                  "ipm_rescue_iters", "ipm_two_phase", "ipm_phase1_iters",
                  "ipm_phase1_iters_point", "ipm_phase1_iters_simplex",
                  "warm_start_tree", "ipm_kernel")


class ProvenanceWarning(UserWarning):
    """Loader found a missing or mismatched provenance stamp."""


class ProvenanceMismatch(ValueError):
    """Strict-mode loader rejection: the artifact's stamp does not
    match the expected problem/config."""


def problem_hash(problem) -> str:
    """Content hash of a problem's canonical mp-QP family + box.

    Hashes every canonical matrix (shape, dtype, raw bytes) plus the
    certified parameter box and root splits, so any revision the oracle
    or the geometry could observe changes the hash; solver knobs do NOT
    enter (they live in the stamp's ``solver`` block)."""
    can = getattr(problem, "canonical", problem)
    h = hashlib.sha256()
    for name in _CANONICAL_FIELDS:
        a = np.ascontiguousarray(getattr(can, name))
        h.update(name.encode())
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    for name in ("theta_lb", "theta_ub"):
        v = getattr(problem, name, None)
        if v is not None:
            h.update(name.encode())
            h.update(np.ascontiguousarray(v, dtype=np.float64).tobytes())
    rs = getattr(problem, "root_splits", None)
    if rs is not None:
        h.update(repr(rs).encode())
    return h.hexdigest()[:16]


def build_stamp(problem, cfg) -> dict:
    """The provenance stamp for a build of `problem` under `cfg`.

    getattr-safe over cfg so configs unpickled from before a knob
    existed stamp with that knob absent rather than crashing."""
    from explicit_hybrid_mpc_tpu import __version__
    from explicit_hybrid_mpc_tpu.obs.sink import SCHEMA_VERSION

    solver = {k: getattr(cfg, k, None) for k in _SOLVER_FIELDS}
    # Tuples survive JSON round-trips as lists; normalize at write time
    # so a stamp read back from meta.json compares equal to a fresh one.
    if solver.get("ipm_point_schedule") is not None:
        solver["ipm_point_schedule"] = list(solver["ipm_point_schedule"])
    return {
        "provenance_version": PROVENANCE_VERSION,
        "problem": getattr(cfg, "problem", None),
        "problem_args": [list(kv) for kv in
                         getattr(cfg, "problem_args", ()) or ()],
        "problem_hash": problem_hash(problem),
        "eps_a": float(getattr(cfg, "eps_a", 0.0)),
        "eps_r": float(getattr(cfg, "eps_r", 0.0)),
        "algorithm": getattr(cfg, "algorithm", "suboptimal"),
        "solver": solver,
        "code_version": __version__,
        "obs_schema_version": SCHEMA_VERSION,
        "tree_schema": "columnar-v2",
    }


#: Stamp keys whose drift means the CERTIFICATES no longer transfer
#: as-is (the warm-rebuild invalidation axes); compared first and
#: reported by name.
_CERT_KEYS = ("problem_hash", "eps_a", "eps_r", "algorithm")


def diff_stamps(found: dict | None, expected: dict | None) -> list[str]:
    """Human-readable field-level differences between two stamps.

    Empty list = stamps agree on every certificate-relevant key and
    every solver knob BOTH sides recorded.  A missing stamp on either
    side reports as a single 'unstamped' line."""
    if found is None or expected is None:
        which = "artifact" if found is None else "expected reference"
        return [f"{which} carries no provenance stamp (legacy, "
                "pre-stamp writer)"]
    diffs = []
    for k in _CERT_KEYS:
        if found.get(k) != expected.get(k):
            diffs.append(f"{k}: {found.get(k)!r} != {expected.get(k)!r}")
    fs, es = found.get("solver") or {}, expected.get("solver") or {}
    for k in sorted(set(fs) & set(es)):
        if fs[k] != es[k]:
            diffs.append(f"solver.{k}: {fs[k]!r} != {es[k]!r}")
    return diffs


def check_stamp(found: dict | None, expected: dict | None, where: str,
                strict: bool = False) -> list[str]:
    """Compare an artifact's stamp against the expected one; returns
    the differences.  Warn-by-default (``ProvenanceWarning``), raise
    ``ProvenanceMismatch`` under strict -- EXCEPT for a legacy
    unstamped artifact, which warns even under strict only when an
    expectation exists (there is nothing to compare; rejecting every
    pre-stamp file would brick all existing deploys)."""
    if expected is None:
        return []
    diffs = diff_stamps(found, expected)
    if not diffs:
        return diffs
    msg = (f"provenance mismatch in {where}: " + "; ".join(diffs)
           + " -- the artifact was built for a different problem/"
           "config (docs/perf.md, 'Incremental warm rebuild')")
    if strict and found is not None:
        raise ProvenanceMismatch(msg)
    warnings.warn(msg, ProvenanceWarning, stacklevel=3)
    return diffs
