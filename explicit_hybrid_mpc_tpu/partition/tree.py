"""Host-side simplex tree.

Counterpart of the reference's binary simplex tree (``Tree``/``NodeData``,
SURVEY.md section 3 [M-high]; citation UNVERIFIED -- reference mount empty):
node = vertex matrix + commutation + vertex inputs/costs; grows by
longest-edge bisection; serializes to disk.

Flat-array storage instead of linked Python objects: nodes live in growable
numpy arrays so that (a) serialization is trivial and fast, (b) exporting
leaves for the on-device online evaluator (online/export.py) is a slice, not
a traversal, and (c) memory stays compact for >10^5-region partitions.
"""

from __future__ import annotations

import dataclasses
import pickle
from typing import Optional

import numpy as np

NO_CHILD = -1


@dataclasses.dataclass
class LeafData:
    """Payload of a converged leaf.

    delta_idx indexes the problem's commutation enumeration (-1 for pure
    mp-QP problems with a single implicit commutation).  vertex_inputs is
    (p+1, n_u): the first control move at each vertex; the online law is
    their barycentric interpolation (SURVEY.md section 4.2).  vertex_costs
    is (p+1,): the fixed-commutation optimal cost at each vertex.
    """

    delta_idx: int
    vertex_inputs: np.ndarray
    vertex_costs: np.ndarray
    # Full primal sequences at the vertices (p+1, nz): their barycentric
    # interpolation is the certified feasible, eps-suboptimal input sequence.
    vertex_z: np.ndarray | None = None
    # False for depth-cap best-effort leaves: the law is the best
    # available candidate but carries NO eps-certificate.  Consumers must
    # read it via getattr(ld, "certified", True) -- pre-field pickles
    # restore without the attribute.
    certified: bool = True
    # True for semi-explicit BOUNDARY leaves: the commutation converged at
    # only part of the cell's vertices (the hybrid feasible set's boundary
    # crosses it), so the online path must solve the fixed-delta QP at the
    # query point (sim.SemiExplicitController) instead of trusting the
    # interpolated law; feasibility is then established per query by the
    # QP itself.  Read via getattr(ld, "semi_explicit", False).
    semi_explicit: bool = False


class Tree:
    """Binary simplex tree over the parameter set Theta.

    Roots are the Kuhn triangulation of the Theta box; every internal node
    has exactly two children from longest-edge bisection.
    """

    def __init__(self, p: int, n_u: int):
        self.p = p
        self.n_u = n_u
        self.vertices: list[np.ndarray] = []  # per node: (p+1, p)
        self.parent: list[int] = []
        self.children: list[tuple[int, int]] = []  # (NO_CHILD, NO_CHILD) = leaf
        self.depth: list[int] = []
        # Split metadata (for tree-descent online eval): which edge (i, j)
        # of this node's simplex was bisected.
        self.split_edge: list[tuple[int, int]] = []
        self.leaf_data: list[Optional[LeafData]] = []

    # -- construction ------------------------------------------------------

    def add_root(self, V: np.ndarray) -> int:
        return self._add(V, parent=-1, depth=0)

    def roots(self) -> list:
        """Ids of the root simplices (parent == -1), in insertion order.
        Lets a tree loaded from pickle feed the APIs that take the build
        result's root list (online.descent.export_descent,
        post.analysis.partition_report)."""
        return [i for i, pa in enumerate(self.parent) if pa == -1]

    def _add(self, V: np.ndarray, parent: int, depth: int) -> int:
        assert V.shape == (self.p + 1, self.p)
        self.vertices.append(np.asarray(V, dtype=np.float64))
        self.parent.append(parent)
        self.children.append((NO_CHILD, NO_CHILD))
        self.depth.append(depth)
        self.split_edge.append((-1, -1))
        self.leaf_data.append(None)
        return len(self.vertices) - 1

    def split(self, node: int, left_V: np.ndarray, right_V: np.ndarray,
              edge: tuple[int, int]) -> tuple[int, int]:
        """Attach the two bisection children of `node`."""
        assert self.children[node] == (NO_CHILD, NO_CHILD)
        d = self.depth[node] + 1
        li = self._add(left_V, node, d)
        ri = self._add(right_V, node, d)
        self.children[node] = (li, ri)
        self.split_edge[node] = edge
        return li, ri

    def set_leaf(self, node: int, data: LeafData) -> None:
        assert self.children[node] == (NO_CHILD, NO_CHILD)
        self.leaf_data[node] = data

    # -- queries -----------------------------------------------------------

    def __len__(self) -> int:
        return len(self.vertices)

    def is_leaf(self, node: int) -> bool:
        return self.children[node] == (NO_CHILD, NO_CHILD)

    def leaves(self) -> list[int]:
        return [i for i in range(len(self)) if self.is_leaf(i)]

    def converged_leaves(self) -> list[int]:
        return [i for i in self.leaves() if self.leaf_data[i] is not None]

    def n_regions(self) -> int:
        return len(self.converged_leaves())

    def max_depth(self) -> int:
        return max(self.depth) if self.depth else 0

    def locate(self, theta: np.ndarray, roots: list[int],
               tol: float = 1e-9) -> int:
        """Tree descent: leaf whose simplex contains theta (-1 if outside).

        The reference's online point location (SURVEY.md section 4.2 [P]):
        pick the containing root, then at each internal node descend into
        the child containing theta.  O(depth) barycentric tests.
        """
        from explicit_hybrid_mpc_tpu.partition import geometry

        node = -1
        for r in roots:
            if geometry.contains(self.vertices[r], theta, tol):
                node = r
                break
        if node < 0:
            return -1
        while not self.is_leaf(node):
            li, ri = self.children[node]
            if geometry.contains(self.vertices[li], theta, tol):
                node = li
            else:
                node = ri
        return node

    # -- serialization -----------------------------------------------------

    def save(self, path: str) -> None:
        """Pickle to disk (the reference pickles its tree; SURVEY.md
        section 3 [M-high], UNVERIFIED)."""
        with open(path, "wb") as f:
            pickle.dump(self, f, protocol=pickle.HIGHEST_PROTOCOL)

    @staticmethod
    def load(path: str) -> "Tree":
        with open(path, "rb") as f:
            tree = pickle.load(f)
        if not isinstance(tree, Tree):
            raise TypeError(f"{path} does not contain a Tree")
        return tree
