"""Host-side simplex tree.

Counterpart of the reference's binary simplex tree (``Tree``/``NodeData``,
SURVEY.md section 3 [M-high]; citation UNVERIFIED -- reference mount empty):
node = vertex matrix + commutation + vertex inputs/costs; grows by
longest-edge bisection; serializes to disk.

COLUMNAR storage: every node attribute lives in one preallocated,
capacity-doubling numpy array; leaf payloads live in a RAGGED slot store
(most nodes are internal or infeasible and carry none); the optional
per-leaf primal matrices live in a second ragged store, so

(a) memory is a few hundred B/node instead of the per-node-Python-object
    design's ~15 KB (round-4 judge measurement: 44.8 GB RSS at ~800 k
    satellite regions -- the benchmark boxes would OOM),
(b) n_regions()/max_depth() are O(1) counters instead of O(N) scans
    (both ran EVERY STEP in the engine's log line and long_build's loop:
    the bulk of the 84% host-side step time at cluster scale),
(c) checkpoint/serialize is a handful of big array dumps, not millions
    of object pickles (round-4: 316 s per checkpoint at 633 k regions);
    vertex matrices are NOT serialized at all -- children are exact
    midpoint functions of their parents, so __setstate__ re-derives
    them level-by-level from the roots (bit-identical to
    geometry.bisect, which uses the same 0.5*(v_i+v_j) arithmetic),
(d) exporting leaves for the on-device online evaluator is array
    slicing, not traversal.

Old checkpoints/tree pickles (list-of-objects layout) load transparently:
``__setstate__`` detects the legacy layout and converts.
"""

from __future__ import annotations

import dataclasses
import pickle
from typing import Optional

import numpy as np

from explicit_hybrid_mpc_tpu.partition import geometry

NO_CHILD = -1

# leaf_flags bits
_F_DATA = 1        # leaf payload present (converged / best-effort leaf)
_F_CERTIFIED = 2   # eps-certificate holds (off for best-effort leaves)
_F_SEMI = 4        # semi-explicit boundary leaf (online fixed-delta QP)


@dataclasses.dataclass
class LeafData:
    """Payload of a converged leaf.

    delta_idx indexes the problem's commutation enumeration (-1 for pure
    mp-QP problems with a single implicit commutation).  vertex_inputs is
    (p+1, n_u): the first control move at each vertex; the online law is
    their barycentric interpolation (SURVEY.md section 4.2).  vertex_costs
    is (p+1,): the fixed-commutation optimal cost at each vertex.
    """

    delta_idx: int
    vertex_inputs: np.ndarray
    vertex_costs: np.ndarray
    # Full primal sequences at the vertices (p+1, nz): their barycentric
    # interpolation is the certified feasible, eps-suboptimal input
    # sequence.  Optional -- cfg.store_vertex_z=False drops it at cluster
    # scale (it feeds offline soundness sampling, not the deployed law).
    vertex_z: np.ndarray | None = None
    # False for depth-cap best-effort leaves: the law is the best
    # available candidate but carries NO eps-certificate.  Consumers must
    # read it via getattr(ld, "certified", True) -- pre-field pickles
    # restore without the attribute.
    certified: bool = True
    # True for semi-explicit BOUNDARY leaves: the commutation converged at
    # only part of the cell's vertices (the hybrid feasible set's boundary
    # crosses it), so the online path must solve the fixed-delta QP at the
    # query point (sim.SemiExplicitController) instead of trusting the
    # interpolated law; feasibility is then established per query by the
    # QP itself.  Read via getattr(ld, "semi_explicit", False).
    semi_explicit: bool = False


class _LeafDataView:
    """Read view over the leaf-payload columns, indexable like the old
    ``list[LeafData | None]`` (``tree.leaf_data[i]``).  Materializes a
    LeafData on access; the arrays inside are views into the columns."""

    def __init__(self, tree: "Tree"):
        self._t = tree

    def __getitem__(self, i: int) -> Optional[LeafData]:
        t = self._t
        i = int(i)
        if not 0 <= i < t._n:
            raise IndexError(i)
        flags = t._leaf_flags[i]
        if not flags & _F_DATA:
            return None
        s = t._leaf_slot[i]
        zi = t._pl_zidx[s]
        return LeafData(
            delta_idx=int(t._pl_delta[s]),
            vertex_inputs=t._pl_inputs[s],
            vertex_costs=t._pl_costs[s],
            vertex_z=t._z_store[zi] if zi >= 0 else None,
            certified=bool(flags & _F_CERTIFIED),
            semi_explicit=bool(flags & _F_SEMI))

    def __len__(self) -> int:
        return self._t._n


class Tree:
    """Binary simplex tree over the parameter set Theta.

    Roots are the Kuhn triangulation of the Theta box; every internal node
    has exactly two children from longest-edge bisection.
    """

    _INIT_CAP = 1024

    def __init__(self, p: int, n_u: int, split_hyperplanes: bool = True):
        self.p = p
        self.n_u = n_u
        # Build provenance stamp (partition/provenance.py): set by the
        # frontier engine at build start, carried through every pickle/
        # checkpoint so loaders and the warm-rebuild engine can tell
        # WHAT problem/config produced this tree.  None on trees built
        # outside the engine (synthetic, tests) and on legacy pickles.
        self.provenance: Optional[dict] = None
        # Farkas exclusion event log: (node, delta) pairs where the
        # build certified a commutation INFEASIBLE on the node's whole
        # simplex (frontier stage-2 / infeasible-candidate passes).
        # The warm rebuild re-verifies exactly these certificates
        # against the revised oracle and inherits the survivors down
        # the tree -- re-DISCOVERING them would cost a joint QP per
        # (leaf, pending commutation), the dominant sweep cost on
        # hybrid problems (partition/rebuild.py).  ~8 bytes/event.
        self.excl_events: list = []
        self._n = 0
        # Split-time descent hyperplanes: each split() computes its
        # split-face normal/offset inline (one (p-1, p) nullspace solve,
        # microseconds next to the oracle solves that caused the split),
        # so the descent table is available at build end without the
        # post-hoc batched-SVD pass over every internal node (1129 s at
        # the 9.8M-leaf satellite export).  False (and trees loaded from
        # pickles that predate the columns) fall back to that batched
        # pass in online.descent.export_descent.
        self._split_normals_live = bool(split_hyperplanes)
        self._alloc(self._INIT_CAP)
        self._alloc_payload(self._INIT_CAP)
        self._n_slots = 0
        # Ragged side store for the optional (p+1, nz) per-leaf primal
        # matrices (nz is unknown until the first payload arrives).
        self._z_store: np.ndarray | None = None
        self._z_n = 0
        # O(1) stats counters (n_regions()/max_depth() run every frontier
        # step in logs and driver loops -- scans would be O(N) each).
        self._n_regions = 0
        self._max_depth = 0

    def _alloc(self, cap: int) -> None:
        p = self.p
        self._vertices = np.empty((cap, p + 1, p), dtype=np.float64)
        self._parent = np.full(cap, -1, dtype=np.int32)
        self._children = np.full((cap, 2), NO_CHILD, dtype=np.int32)
        self._depth = np.zeros(cap, dtype=np.int32)
        self._split_edge = np.full((cap, 2), -1, dtype=np.int8)
        self._leaf_flags = np.zeros(cap, dtype=np.uint8)
        self._leaf_slot = np.full(cap, -1, dtype=np.int32)
        # Split hyperplane per INTERNAL node (zeros at leaves / when
        # _split_normals_live is False).
        self._normal = np.zeros((cap, p), dtype=np.float64)
        self._offset = np.zeros(cap, dtype=np.float64)

    def _alloc_payload(self, cap: int) -> None:
        self._pl_delta = np.zeros(cap, dtype=np.int32)
        self._pl_inputs = np.zeros((cap, self.p + 1, self.n_u),
                                   dtype=np.float64)
        self._pl_costs = np.zeros((cap, self.p + 1), dtype=np.float64)
        self._pl_zidx = np.full(cap, -1, dtype=np.int32)

    @staticmethod
    def _up(a: np.ndarray, n: int, new_cap: int) -> np.ndarray:
        out = np.empty((new_cap,) + a.shape[1:], dtype=a.dtype)
        out[:n] = a[:n]
        return out

    def _grow(self, need: int) -> None:
        cap = self._vertices.shape[0]
        if need <= cap:
            return
        new_cap, n = max(need, 2 * cap), self._n
        self._vertices = self._up(self._vertices, n, new_cap)
        for name in ("_parent", "_children", "_depth", "_split_edge",
                     "_leaf_flags", "_leaf_slot", "_normal", "_offset"):
            old = getattr(self, name)
            new = self._up(old, n, new_cap)
            new[n:] = (-1 if name in ("_parent", "_leaf_slot") else
                       NO_CHILD if name == "_children" else
                       -1 if name == "_split_edge" else 0)
            setattr(self, name, new)

    def _grow_payload(self, need: int) -> None:
        cap = self._pl_delta.shape[0]
        if need <= cap:
            return
        new_cap, n = max(need, 2 * cap), self._n_slots
        self._pl_delta = self._up(self._pl_delta, n, new_cap)
        self._pl_inputs = self._up(self._pl_inputs, n, new_cap)
        self._pl_costs = self._up(self._pl_costs, n, new_cap)
        new_z = self._up(self._pl_zidx, n, new_cap)
        new_z[n:] = -1
        self._pl_zidx = new_z

    # -- column access (read-only views, trimmed to the live length) ------

    @property
    def vertices(self) -> np.ndarray:
        return self._vertices[:self._n]

    @property
    def parent(self) -> np.ndarray:
        return self._parent[:self._n]

    @property
    def children(self) -> np.ndarray:
        return self._children[:self._n]

    @property
    def depth(self) -> np.ndarray:
        return self._depth[:self._n]

    @property
    def split_edge(self) -> np.ndarray:
        return self._split_edge[:self._n]

    @property
    def split_normals(self) -> np.ndarray:
        """(n, p) split hyperplane normals (unit, zeros at leaves)."""
        return self._normal[:self._n]

    @property
    def split_offsets(self) -> np.ndarray:
        """(n,) split hyperplane offsets (h(x) = w.x - c)."""
        return self._offset[:self._n]

    def split_hyperplanes_available(self) -> bool:
        """True when every internal node carries its split-time
        hyperplane, so online.descent.export_descent can slice the
        columns instead of re-deriving all normals with the batched
        post-hoc SVD pass (minutes-scale at multi-million-leaf trees)."""
        return self._split_normals_live

    @property
    def leaf_data(self) -> _LeafDataView:
        return _LeafDataView(self)

    # -- construction ------------------------------------------------------

    def add_root(self, V: np.ndarray) -> int:
        return self._add(V, parent=-1, depth=0)

    def roots(self) -> list:
        """Ids of the root simplices (parent == -1), in insertion order.
        Lets a tree loaded from pickle feed the APIs that take the build
        result's root list (online.descent.export_descent,
        post.analysis.partition_report)."""
        return np.nonzero(self._parent[:self._n] == -1)[0].tolist()

    def _add(self, V: np.ndarray, parent: int, depth: int) -> int:
        assert V.shape == (self.p + 1, self.p)
        i = self._n
        self._grow(i + 1)
        self._vertices[i] = V
        self._parent[i] = parent
        self._depth[i] = depth
        if depth > self._max_depth:
            self._max_depth = depth
        self._n = i + 1
        return i

    def split(self, node: int, left_V: np.ndarray, right_V: np.ndarray,
              edge: tuple[int, int]) -> tuple[int, int]:
        """Attach the two bisection children of `node`.

        Children MUST be the longest-edge bisection of `node` (the left
        child replaces v_j by the edge midpoint, the right child v_i, as
        geometry.bisect produces): serialization re-derives every vertex
        matrix from the roots under exactly that relation
        (__getstate__/_rederive_vertices), so arbitrary child geometry
        would silently corrupt on save/load.  The midpoint rows are
        checked here, and the remaining rows are checked to be inherited
        unchanged from the parent (a caller with correct midpoints but
        perturbed inherited rows would otherwise be accepted and
        silently corrupt on save/load -- ADVICE r5)."""
        assert self._children[node, 0] == NO_CHILD
        i, j = edge
        pv = self._vertices[node]
        mid = 0.5 * (pv[i] + pv[j])
        if not (np.array_equal(left_V[j], mid)
                and np.array_equal(right_V[i], mid)):
            raise ValueError("split children are not the midpoint "
                             "bisection of the parent along `edge`")
        if not (np.array_equal(np.delete(left_V, j, axis=0),
                               np.delete(pv, j, axis=0))
                and np.array_equal(np.delete(right_V, i, axis=0),
                                   np.delete(pv, i, axis=0))):
            raise ValueError("split children do not inherit the parent's "
                             "non-split vertex rows unchanged")
        d = int(self._depth[node]) + 1
        li = self._add(left_V, node, d)
        ri = self._add(right_V, node, d)
        self._children[node, 0] = li
        self._children[node, 1] = ri
        self._split_edge[node] = edge
        if self._split_normals_live:
            # Split-time descent hyperplane: the bisection has the face
            # vertices in hand right here, so the normal is one small
            # nullspace solve now instead of a post-hoc batched SVD over
            # every internal node at export time.  N=1 call of the SAME
            # batched routine export_descent falls back to -> bit-
            # identical DescentTable arrays (tests pin this).
            w, c = geometry.split_hyperplanes(
                pv[None], np.asarray([[i, j]], dtype=np.int64))
            self._normal[node] = w[0]
            self._offset[node] = c[0]
        return li, ri

    def set_leaf(self, node: int, data: LeafData) -> None:
        assert self._children[node, 0] == NO_CHILD
        s = self._leaf_slot[node]
        if s < 0:
            s = self._n_slots
            self._grow_payload(s + 1)
            self._leaf_slot[node] = s
            self._n_slots = s + 1
            self._n_regions += 1
        self._pl_delta[s] = data.delta_idx
        self._pl_inputs[s] = data.vertex_inputs
        self._pl_costs[s] = data.vertex_costs
        flags = _F_DATA
        if data.certified:
            flags |= _F_CERTIFIED
        if data.semi_explicit:
            flags |= _F_SEMI
        self._leaf_flags[node] = flags
        if data.vertex_z is None:
            # Re-setting a leaf without z must not expose a previous
            # payload's stale primal matrix (the row, if any, is
            # abandoned in the store -- double-sets are rare).
            self._pl_zidx[s] = -1
        else:
            z = np.asarray(data.vertex_z, dtype=np.float64)
            if self._pl_zidx[s] >= 0:
                self._z_store[self._pl_zidx[s]] = z  # reuse on re-set
                return
            if self._z_store is None:
                self._z_store = np.empty(
                    (self._INIT_CAP,) + z.shape, dtype=np.float64)
            elif self._z_n >= self._z_store.shape[0]:
                self._z_store = self._up(self._z_store, self._z_n,
                                         2 * self._z_store.shape[0])
            self._z_store[self._z_n] = z
            self._pl_zidx[s] = self._z_n
            self._z_n += 1

    def clear_leaf(self, node: int) -> None:
        """Drop a leaf's payload and flags (warm-rebuild invalidation:
        the node re-enters the frontier as an OPEN simplex).  The
        abandoned payload slot stays in the ragged store -- re-opened
        leaves are a small minority of a rebuild, and slot compaction
        would re-index every other leaf for nothing."""
        assert self._children[node, 0] == NO_CHILD
        if self._leaf_flags[node] & _F_DATA:
            self._n_regions -= 1
        self._leaf_flags[node] = 0
        self._leaf_slot[node] = -1

    def leaf_payloads(self, ids: np.ndarray
                      ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(delta (L,), vertex_inputs (L, p+1, n_u), vertex_costs
        (L, p+1)) for payload-carrying leaf ids, by columnar fancy
        indexing -- the per-leaf LeafData materialization loop was the
        online export's memory blow-up at cluster scale."""
        ids = np.asarray(ids, dtype=np.int64)
        slots = self._leaf_slot[ids]
        if ids.size and slots.min() < 0:
            raise ValueError("leaf_payloads: id without payload")
        # Fancy indexing returns fresh arrays -- no aliasing of tree
        # storage in any of the three.
        return (self._pl_delta[slots],
                self._pl_inputs[slots],
                self._pl_costs[slots])

    def certified_flags(self, ids: np.ndarray) -> np.ndarray:
        """(L,) bool: eps-certified flag per node id, from the flags
        column (the warm-rebuild sweep classifies the whole leaf set
        this way; a per-leaf LeafData loop would be O(L) python
        objects)."""
        ids = np.asarray(ids, dtype=np.int64)
        return (self._leaf_flags[ids] & _F_CERTIFIED) != 0

    def semi_explicit_flags(self, ids: np.ndarray) -> np.ndarray:
        """(L,) bool: semi-explicit boundary flag per node id, from the
        flags column (the per-leaf LeafData loop this replaces ran right
        after every export at cluster scale)."""
        ids = np.asarray(ids, dtype=np.int64)
        return (self._leaf_flags[ids] & _F_SEMI) != 0

    # -- queries -----------------------------------------------------------

    def __len__(self) -> int:
        return self._n

    def is_leaf(self, node: int) -> bool:
        return bool(self._children[node, 0] == NO_CHILD)

    def leaves(self) -> list[int]:
        n = self._n
        return np.nonzero(self._children[:n, 0] == NO_CHILD)[0].tolist()

    def converged_leaves(self) -> list[int]:
        return self.converged_leaf_ids().tolist()

    def converged_leaf_ids(self) -> np.ndarray:
        """(L,) int64 payload-carrying leaf ids, ascending.  Array form
        of converged_leaves(): the python-int list costs ~30 B/leaf in
        object overhead, which at the 9.8M-leaf satellite export is
        ~300 MB of pure boxing -- the streaming export slices this."""
        n = self._n
        mask = ((self._children[:n, 0] == NO_CHILD)
                & (self._leaf_flags[:n] & _F_DATA != 0))
        return np.nonzero(mask)[0].astype(np.int64)

    def n_regions(self) -> int:
        return self._n_regions

    def max_depth(self) -> int:
        return self._max_depth

    def locate(self, theta: np.ndarray, roots: list[int],
               tol: float = 1e-9) -> int:
        """Tree descent: leaf whose simplex contains theta (-1 if outside).

        The reference's online point location (SURVEY.md section 4.2 [P]):
        pick the containing root, then at each internal node descend into
        the child containing theta.  O(depth) barycentric tests.
        """
        node = -1
        for r in roots:
            if geometry.contains(self._vertices[r], theta, tol):
                node = r
                break
        if node < 0:
            return -1
        while not self.is_leaf(node):
            li, ri = self._children[node]
            if geometry.contains(self._vertices[li], theta, tol):
                node = int(li)
            else:
                node = int(ri)
        return node

    def clone(self) -> "Tree":
        """Bit-identical deep copy WITHOUT a pickle round-trip.

        ``warm_rebuild`` transfers a prior tree by copying it; for
        priors loaded from disk the pickle round-trip doubles as layout
        normalization, but an IN-MEMORY prior (the continuous-rebuild
        daemon chains each generation's PartitionResult straight into
        the next ``warm_rebuild``) is already columnar, and serializing
        O(tree) bytes per revision just to copy arrays was the
        daemon hot loop's dominant fixed cost.  Columns are copied
        directly -- including the vertex matrices, which a pickle
        round-trip would re-DERIVE from the roots to the same bits."""
        t = Tree.__new__(Tree)
        t.p, t.n_u = self.p, self.n_u
        t.provenance = (None if self.provenance is None
                        else dict(self.provenance))
        t.excl_events = list(self.excl_events)
        n, ns = self._n, self._n_slots
        t._n = n
        t._split_normals_live = self._split_normals_live
        t._alloc(max(self._INIT_CAP, n))
        for name in ("_vertices", "_parent", "_children", "_depth",
                     "_split_edge", "_leaf_flags", "_leaf_slot",
                     "_normal", "_offset"):
            getattr(t, name)[:n] = getattr(self, name)[:n]
        t._n_slots = ns
        t._alloc_payload(max(self._INIT_CAP, ns))
        t._pl_delta[:ns] = self._pl_delta[:ns]
        t._pl_inputs[:ns] = self._pl_inputs[:ns]
        t._pl_costs[:ns] = self._pl_costs[:ns]
        t._pl_zidx[:ns] = self._pl_zidx[:ns]
        t._z_store = (None if self._z_store is None
                      else np.array(self._z_store[:self._z_n]))
        t._z_n = self._z_n
        t._n_regions = self._n_regions
        t._max_depth = self._max_depth
        return t

    # -- serialization -----------------------------------------------------

    def __getstate__(self) -> dict:
        n, ns = self._n, self._n_slots
        roots = np.nonzero(self._parent[:n] == -1)[0]
        return {
            "format": "columnar-v2", "p": self.p, "n_u": self.n_u,
            "n": n,
            # Vertex matrices are re-derived on load (children are exact
            # midpoint functions of parents): they are the largest node
            # column (~1 GB per 3M satellite nodes) and pure redundancy
            # on disk.
            "root_vertices": self._vertices[roots],
            "parent": self._parent[:n],
            "children": self._children[:n],
            "depth": self._depth[:n],
            "split_edge": self._split_edge[:n],
            "leaf_flags": self._leaf_flags[:n],
            "leaf_slot": self._leaf_slot[:n],
            # Split hyperplanes ARE serialized (unlike the vertex
            # matrices): re-deriving them on load would re-pay the
            # batched-SVD export pass this column exists to amortize
            # away, and a resumed campaign would then export slowly.
            "normal": self._normal[:n] if self._split_normals_live
            else None,
            "offset": self._offset[:n] if self._split_normals_live
            else None,
            "pl_delta": self._pl_delta[:ns],
            "pl_inputs": self._pl_inputs[:ns],
            "pl_costs": self._pl_costs[:ns],
            "pl_zidx": self._pl_zidx[:ns],
            "z_store": (None if self._z_store is None
                        else self._z_store[:self._z_n]),
            "n_regions": self._n_regions,
            "max_depth": self._max_depth,
            "provenance": self.provenance,
            "excl_events": (np.asarray(self.excl_events,
                                       dtype=np.float64)
                            if self.excl_events else None),
        }

    def __setstate__(self, state: dict) -> None:
        if state.get("format") != "columnar-v2":
            self._set_legacy_state(state)
            return
        self.p, self.n_u = state["p"], state["n_u"]
        n = state["n"]
        self._n = n
        self._alloc(max(self._INIT_CAP, n))
        for dst, key in ((self._parent, "parent"),
                         (self._children, "children"),
                         (self._depth, "depth"),
                         (self._split_edge, "split_edge"),
                         (self._leaf_flags, "leaf_flags"),
                         (self._leaf_slot, "leaf_slot")):
            dst[:n] = state[key]
        nm = state.get("normal")
        self._split_normals_live = nm is not None
        if nm is not None:
            self._normal[:n] = nm
            self._offset[:n] = state["offset"]
        ns = state["pl_delta"].shape[0]
        self._n_slots = ns
        self._alloc_payload(max(self._INIT_CAP, ns))
        self._pl_delta[:ns] = state["pl_delta"]
        self._pl_inputs[:ns] = state["pl_inputs"]
        self._pl_costs[:ns] = state["pl_costs"]
        self._pl_zidx[:ns] = state["pl_zidx"]
        zs = state["z_store"]
        if zs is None:
            self._z_store, self._z_n = None, 0
        else:
            self._z_store = np.ascontiguousarray(zs)
            self._z_n = zs.shape[0]
        self._n_regions = state["n_regions"]
        self._max_depth = state["max_depth"]
        # Pre-stamp columnar pickles lack the key: legacy = None.
        self.provenance = state.get("provenance")
        ev = state.get("excl_events")
        if ev is None:
            self.excl_events = []
        elif ev.shape[1] == 2:
            # Transitional (node, delta) int layout: exclusion-only.
            self.excl_events = [(int(a), int(d), np.inf)
                                for a, d in ev]
        else:
            self.excl_events = [(int(a), int(d), float(v))
                                for a, d, v in ev]
        self._rederive_vertices(state["root_vertices"])

    def _rederive_vertices(self, root_vertices: np.ndarray) -> None:
        """Rebuild every node's vertex matrix from the roots, level by
        level: a child equals its parent with one endpoint of the split
        edge replaced by the midpoint -- the same 0.5*(v_i+v_j) float64
        arithmetic as geometry.bisect, so the result is bit-identical to
        what was in memory when the tree was saved."""
        n = self._n
        V = self._vertices
        parent = self._parent[:n]
        depth = self._depth[:n]
        roots = np.nonzero(parent == -1)[0]
        V[roots] = root_vertices
        for d in range(1, self._max_depth + 1):
            ids = np.nonzero(depth == d)[0]
            if ids.size == 0:
                continue
            pa = parent[ids].astype(np.int64)
            ij = self._split_edge[pa]
            i = ij[:, 0].astype(np.int64)
            j = ij[:, 1].astype(np.int64)
            mid = 0.5 * (V[pa, i] + V[pa, j])
            V[ids] = V[pa]
            left = self._children[pa, 0] == ids
            li = np.nonzero(left)[0]
            ri = np.nonzero(~left)[0]
            V[ids[li], j[li]] = mid[li]
            V[ids[ri], i[ri]] = mid[ri]

    def _set_legacy_state(self, state: dict) -> None:
        """Convert a pre-columnar pickle (python lists of per-node arrays
        / tuples / LeafData objects -- every round-1..4 checkpoint and
        .tree.pkl artifact) into the columnar layout."""
        if "format" in state:
            raise ValueError(
                f"unsupported Tree pickle format {state['format']!r}")
        self.p, self.n_u = state["p"], state["n_u"]
        self.provenance = None  # pre-stamp layout: legacy
        self.excl_events = []
        # Pre-column pickles carry no split hyperplanes; export falls
        # back to the batched post-hoc SVD pass.
        self._split_normals_live = False
        verts = state["vertices"]
        n = len(verts)
        self._n = n
        self._alloc(max(self._INIT_CAP, n))
        self._alloc_payload(self._INIT_CAP)
        self._n_slots = 0
        if n:
            self._vertices[:n] = np.asarray(verts)
            self._parent[:n] = np.asarray(state["parent"], dtype=np.int32)
            self._children[:n] = np.asarray(state["children"],
                                            dtype=np.int32)
            self._depth[:n] = np.asarray(state["depth"], dtype=np.int32)
            self._split_edge[:n] = np.asarray(state["split_edge"],
                                              dtype=np.int8)
        self._z_store, self._z_n = None, 0
        self._n_regions = 0
        self._max_depth = int(np.max(self._depth[:n])) if n else 0
        leaf = state["leaf_data"]
        for i, ld in enumerate(leaf):
            if ld is None:
                continue
            # Old dataclass instances restore attribute-wise; pre-field
            # pickles lack certified/semi_explicit (defaults True/False).
            self.set_leaf(i, LeafData(
                delta_idx=ld.delta_idx,
                vertex_inputs=ld.vertex_inputs,
                vertex_costs=ld.vertex_costs,
                vertex_z=getattr(ld, "vertex_z", None),
                certified=getattr(ld, "certified", True),
                semi_explicit=getattr(ld, "semi_explicit", False)))

    def save(self, path: str) -> None:
        """Atomic checksummed pickle (utils/atomic.py): tmp + fsync +
        rename with a content-checksum trailer, so a crash mid-save
        never tears the tree a later rebuild/deploy trusts.  (The
        reference pickles its tree in place; SURVEY.md section 3
        [M-high], UNVERIFIED.)"""
        from explicit_hybrid_mpc_tpu.utils import atomic

        atomic.atomic_pickle(path, self)

    @staticmethod
    def load(path: str) -> "Tree":
        """Load with integrity verification: a checksummed pickle is
        verified (CorruptArtifact on mismatch/truncation, with a clear
        message); legacy trailer-less pickles load as before."""
        from explicit_hybrid_mpc_tpu.utils import atomic

        tree, _checked = atomic.read_checked_pickle(path)
        if not isinstance(tree, Tree):
            raise TypeError(f"{path} does not contain a Tree")
        return tree
