"""Bounded asynchronous build pipeline: depth-N in-flight scheduling,
cross-batch vertex dedup, and speculative child dispatch.

The frontier engine's old overlap was a SINGLE prefetch slot: one step's
point solves could be dispatched while the previous step's host work ran,
and everything else (stage-2 joint programs, certify/bisect, tree
commits) serialized against the device.  This module generalizes it into
a bounded pipeline (cfg.pipeline_depth): up to N frontier batches are
planned and dispatched ahead of the committing step, so plan(k+2) and
dispatch(k+2) run while wait(k+1) resolves and commit(k) writes the tree.

Correctness model -- the produced tree is NODE-FOR-NODE BIT-IDENTICAL
to the synchronous (pipeline_depth=0) build: same region count, same
node vertex matrices (bitwise -- bisection arithmetic is exact), same
leaf commutation choices and certification statuses.  (Leaf payload
FLOATS may differ in the final ulp when a cell's solve was served from
a program padded to a different pow-2 bucket -- a different XLA
executable; converged lanes are bitwise lane-independent WITHIN a
bucket, measured, but not across bucket sizes.  The legacy prefetch's
duplicate-and-overwrite merges and the CPU bench's warm-start donors
carry exactly the same caveat; certificates sit eps away from these
ulps.)  The scheduling invariants:

- Claims are full-size frontier prefixes only; the frontier deque pops
  at the front (commits) and appends at the back (children), so a
  claimed batch always equals the batch the synchronous loop would pop.
- Fill-time plans are TENTATIVE: they may be computed against a cache
  state older than the one the synchronous build would plan against.
  Every step therefore re-plans AUTHORITATIVELY at commit time, when the
  cache state is exactly the synchronous build's, and serves each
  missing (vertex, delta) cell from the in-flight window only when the
  dispatched program's route matches the authoritative plan's route --
  same program family (dense grid vs sparse pair) and the same
  warm-start donor row (identity, or bitwise-equal donor cells).  The
  per-cell IPM programs are batch-composition independent within a
  program family, so a route-matched cell is the cell the synchronous
  build would have solved (to the ulp caveat above); mismatched cells
  are re-solved synchronously from the authoritative plan.  Cache rows
  are then written through the same merge code, in commit order.
- Speculative results live in the same window and obey the same route
  match; a mis-speculation is dropped before it can ever reach a cache
  row.

Dedup: duplicate (vertex, delta) requests across the whole in-flight
window -- sibling bisection midpoints, the batch-boundary overlaps the
old prefetch re-solved ("a midpoint shared across the batch boundary can
be solved twice") -- coalesce into one dispatched program fanned back
out to every requester through the window, shrinking point_solves.

Speculation (cfg.speculate): when a frontier cell's inherited
certificate gap is INFINITE (the mixed-feasibility boundary
population, the only one whose re-split is predictable; see
speculate() for the measurement), the cell's own children's shared new
vertex (its longest-edge bisection midpoint) is dispatched at consume
time, BEFORE the cell's certificate verdict lands and only while the
device is not already the bottleneck (SPEC_DEVICE_FRAC_MAX).  The
device then solves next-generation vertices while the host certifies
this one; hits are served through the window when the children are
claimed, and misses (the cell certified or closed instead of
splitting) are dropped at commit and tallied as spec_waste.
"""

from __future__ import annotations

import collections
import itertools
import queue
import threading
import time
import types

import numpy as np

#: Speculation is an idle-device filler: it only pays when the device
#: would otherwise sit out the host's certify/commit work.  When the
#: rolling device-busy fraction of recent steps exceeds this bound the
#: device is already the bottleneck and speculative batches would only
#: deepen its queue (measured on the tier-1 CPU bench, device_frac
#: ~0.94: unconditional speculation wasted 16% of point-solve work and
#: cost ~8% wall), so dispatch is skipped.  Tests raise the bound to
#: force speculation on CPU.
SPEC_DEVICE_FRAC_MAX = 0.6


class _Program:
    """One dispatched oracle program batch (grid or pairs): handle,
    fallback args, resolved output, and speculation accounting in
    point-QP cells."""

    __slots__ = ("kind", "handle", "args", "out", "spec", "n_cells",
                 "n_used", "live_refs", "retired", "lock", "queued")

    def __init__(self, kind: str, handle, args: tuple, spec: bool,
                 n_cells: int):
        self.kind = kind
        self.handle = handle
        self.args = args
        self.out = None
        self.spec = spec
        self.n_cells = n_cells
        self.n_used = 0
        self.live_refs = 0
        self.retired = False
        # Serializes resolution between the committing thread and the
        # async-certify background waiter (cfg.async_certify); a
        # plain Lock is ~100 ns uncontended, noise next to a dispatch.
        self.lock = threading.Lock()
        self.queued = False


class _Src:
    """A (program, row) reference serving one window cell or one full
    grid row; `donor` is the warm-start donor row the program's warm
    arrays were sliced from (None = cold), `owner` the speculating
    parent node (None for real plan programs)."""

    __slots__ = ("prog", "idx", "donor", "owner")

    def __init__(self, prog: _Program, idx: int, donor, owner):
        self.prog = prog
        self.idx = idx
        self.donor = donor
        self.owner = owner


class _Entry:
    """Window entry for one vertex key: dense-grid sources (cover every
    commutation, cold) and per-delta pair sources."""

    __slots__ = ("grid", "cells")

    def __init__(self):
        self.grid: list[_Src] = []
        self.cells: dict[int, list[_Src]] = {}


class BuildPipeline:
    """Scheduler + dedup window + speculation for one FrontierEngine.

    The engine drives it per step: fill() claims and dispatches ahead,
    pop_claim() consumes the head claim, serve() resolves the
    authoritative plan from the window (sync-solving route mismatches),
    speculate() dispatches predicted grandchildren, on_commit() settles
    speculation, cancel() drops every in-flight handle (checkpoints,
    end of run)."""

    #: Class attribute so tests (and subclasses) can force speculation
    #: on a host whose "device" is never idle.
    SPEC_DEVICE_FRAC_MAX = SPEC_DEVICE_FRAC_MAX

    def __init__(self, eng):
        self.eng = eng
        cfg = eng.cfg
        self.depth = (int(getattr(cfg, "pipeline_depth", 2))
                      if getattr(cfg, "prefetch_solves", True) else 0)
        # eps_r-only builds never speculate (the infinite-gap split
        # predictor was only validated on eps_a builds; config.py
        # documents the limitation), and neither do mesh-sharded
        # oracles: the speculation gate reads
        # the TIMING-dependent device_frac EMA, and under multi-process
        # SPMD a dispatch decision that differs across processes would
        # desynchronize the collective mesh programs.
        self.spec_on = (bool(getattr(cfg, "speculate", True))
                        and self.depth >= 1
                        and getattr(cfg, "eps_a", 0.0) > 0
                        and getattr(eng.oracle, "mesh", None) is None
                        # Sharded frontiers never speculate: a
                        # mis-speculated midpoint on a shard boundary
                        # would post exchange requests the owner then
                        # solves for a child that never materializes --
                        # wasted remote work AND a broken summed-
                        # point_solves parity bar.
                        and getattr(eng, "_shard", None) is None)
        self.window_cap = int(getattr(cfg, "dedup_window", 8192))
        # (batch node tuple, planned?) -- planned is False when the
        # full dedup window refused the tentative plan at fill time.
        self._claims: collections.deque[
            tuple[tuple[int, ...], bool]] = collections.deque()
        self._win: dict[bytes, _Entry] = {}
        self._spec_keys: dict[int, list[bytes]] = {}
        self._child_gap: dict[int, float] = {}
        self.n_pipelined_steps = 0
        self.dedup_saved = 0
        self.spec_hits = 0
        self.spec_waste = 0
        self.spec_dropped_unwaited = 0
        self._fill_sum = 0.0
        self._fill_steps = 0
        # Asynchronous host-certify (cfg.async_certify): a background
        # waiter resolves in-flight NON-speculative programs while the
        # engine certifies, so the next step's serve() finds them
        # memoized and the serialized cp_wait share shrinks.
        # Speculative programs are excluded on purpose: the oracle
        # counts solves at WAIT time, and pre-waiting a speculation
        # that gets dropped would count device work the synchronous
        # build never counts.  Mesh oracles are excluded like the
        # speculation gate: collective programs must resolve in the
        # engine thread's deterministic order on every process.
        self.async_on = (bool(getattr(cfg, "async_certify", False))
                         and self.depth >= 1
                         and getattr(eng.oracle, "mesh", None) is None)
        self.overlap_wait_s = 0.0
        self.n_overlap_resolved = 0
        self._bg_thread: threading.Thread | None = None
        self._bg_q: "queue.Queue[_Program | None]" | None = None
        # Wall seconds of the most recent fill() call -- the
        # "pipeline fill" segment of the engine's per-step critical-
        # path breakdown (frontier.step; measured here so lookahead
        # planning + dispatch cost is attributed by its owner).
        self.last_fill_wall = 0.0

    # -- stats -------------------------------------------------------------

    @property
    def in_flight(self) -> int:
        return len(self._claims)

    @property
    def planned_in_flight(self) -> int:
        """Claims whose batch was tentatively planned + dispatched at
        fill time (a full dedup window admits claims unplanned; those
        re-solve synchronously and do not count as occupancy)."""
        return sum(1 for _, p in self._claims if p)

    def fill_frac(self) -> float:
        """Mean pipeline occupancy: PLANNED in-flight claims / depth,
        averaged over steps (1.0 = the lookahead stayed full and the
        window never refused a plan)."""
        return self._fill_sum / self._fill_steps if self._fill_steps \
            else 0.0

    def spec_hit_rate(self) -> float:
        """Fraction of settled speculative cells that were consumed."""
        tot = self.spec_hits + self.spec_waste
        return self.spec_hits / tot if tot else 0.0

    def spec_waste_frac(self, n_point_solves: int) -> float:
        """Wasted speculative cells over all point-QP cells the device
        actually ran (waited solves + speculative programs dropped
        before their wait -- those never reach the oracle counters)."""
        denom = n_point_solves + self.spec_dropped_unwaited
        return self.spec_waste / denom if denom else 0.0

    # -- fill / claim ------------------------------------------------------

    def fill(self) -> None:
        """Claim + tentatively plan + dispatch future batches until the
        lookahead holds `depth` claims or the unclaimed frontier cannot
        fill a whole batch.  Only full-size batches are claimed: a
        partial batch's membership depends on in-flight verdicts, while
        a full prefix of the deque is exactly what the synchronous loop
        would pop (children append at the back)."""
        if self.depth == 0:
            self.last_fill_wall = 0.0
            return
        t_fill = time.perf_counter()
        eng = self.eng
        B = eng.cfg.batch_simplices
        while len(self._claims) < self.depth:
            off = sum(len(c) for c, _ in self._claims)
            if len(eng.frontier) - off < B:
                break
            nodes = list(itertools.islice(eng.frontier, off, off + B))
            # Bounded window (cfg.dedup_window): when full, claim the
            # batch WITHOUT dispatching -- refusing admission keeps the
            # head claim's in-flight results (evicting oldest-first
            # would drop exactly the rows the next serve() consumes);
            # the skipped batch just re-solves synchronously at its
            # commit.  A single plan may overshoot the cap (soft
            # bound).
            planned = len(self._win) < self.window_cap
            if planned:
                plan = eng._plan_missing(nodes, window=self)
                if plan is not None:
                    self.admit_plan(plan)
            self._claims.append((tuple(nodes), planned))
        # Occupancy counts PLANNED claims only: a claim refused by the
        # full window re-solves synchronously at its commit, and
        # reporting it as fill would hide exactly the degradation the
        # pipeline_fill_frac bench gate exists to catch.
        self._fill_sum += self.planned_in_flight / self.depth
        self._fill_steps += 1
        self.last_fill_wall = time.perf_counter() - t_fill

    def pop_claim(self, nodes: list[int]) -> bool:
        """Consume the head claim if it matches this step's batch.  A
        mismatch is structurally unreachable (claims are full-batch
        frontier prefixes); if it ever happens the whole lookahead is
        cancelled so the build degrades to synchronous, never to a
        wrong tree."""
        if not self._claims:
            return False
        batch, planned = self._claims[0]
        if batch == tuple(nodes):
            self._claims.popleft()
            if planned:
                self.n_pipelined_steps += 1
            return True
        self.cancel()
        return False

    # -- fill-time coverage (consulted by _plan_missing(window=...)) -------

    def covers_grid(self, k: bytes) -> bool:
        """True when an in-flight dense-grid program already covers this
        vertex; tallies the dedup save for non-speculative coverage
        (speculative coverage settles at serve/commit time)."""
        e = self._win.get(k)
        if e is None or not e.grid:
            return False
        if any(not s.prog.spec for s in e.grid):
            self.dedup_saved += int(self.eng.oracle.can.n_delta)
        return True

    def cover_masks(self, k: bytes, donor, nd: int):
        """(real, spec) boolean delta masks of in-flight coverage whose
        route is compatible with a pair-path request carrying `donor`
        (None = cold).  None when the vertex has no window entry.
        Grid sources never cover pair needs -- the two program families
        are not bitwise interchangeable per cell (see _match_cell)."""
        e = self._win.get(k)
        if e is None:
            return None
        real = np.zeros(nd, dtype=bool)
        spec = np.zeros(nd, dtype=bool)
        for d, lst in e.cells.items():
            for s in lst:
                if s.donor is donor:
                    (spec if s.prog.spec else real)[d] = True
        return real, spec

    # -- program admission / dispatch --------------------------------------

    def has_entry(self, k: bytes) -> bool:
        return k in self._win

    def _entry(self, k: bytes) -> _Entry:
        e = self._win.get(k)
        if e is None:
            e = self._win[k] = _Entry()
        return e

    def admit_plan(self, plan: dict,
                   owners: dict[bytes, int] | None = None) -> None:
        """Dispatch a (tentative or speculative) plan's device programs
        and register their rows in the window."""
        spec = owners is not None
        nd = int(self.eng.oracle.can.n_delta)
        if plan["grid_arr"] is not None:
            h = self._dispatch("grid", plan["grid_arr"], None, None)
            prog = _Program("grid", h, (plan["grid_arr"],), spec,
                            plan["grid_arr"].shape[0] * nd)
            for i, k in enumerate(plan["grid_keys"]):
                self._entry(k).grid.append(
                    _Src(prog, i, None, owners.get(k) if spec else None))
                prog.live_refs += 1
        if plan["pair_slices"]:
            h = self._dispatch("pairs", plan["pair_t"], plan["pair_d"],
                               plan["pair_warm"])
            prog = _Program(
                "pairs", h,
                (plan["pair_t"], plan["pair_d"], plan["pair_warm"]),
                spec, plan["pair_t"].shape[0])
            for (k, ds, lo), dnr in zip(plan["pair_slices"],
                                        plan["pair_donors"]):
                e = self._entry(k)
                own = owners.get(k) if spec else None
                for pos, d in enumerate(ds):
                    e.cells.setdefault(int(d), []).append(
                        _Src(prog, lo + pos, dnr, own))
                    prog.live_refs += 1

    def _timed(self, span: str, fn):
        """Run a dispatch/wait thunk under its obs span and charge its
        wall time to eng._oracle_s — the ONE device-time accounting
        point, since _oracle_s drives device_frac and the speculation
        idle-device gate (SPEC_DEVICE_FRAC_MAX)."""
        eng = self.eng
        t0 = time.perf_counter()
        try:
            with eng.obs.span(span):
                return fn()
        finally:
            eng._oracle_s += time.perf_counter() - t0

    def _dispatch(self, kind: str, a, b, warm):
        """Non-blocking oracle dispatch; a dispatch-time device error is
        recorded in the handle and rerouted to the CPU fallback at
        resolve time (same contract as the old prefetch path).  A
        DEGRADED engine (device-failure cap tripped,
        frontier._note_device_failure) mints a ("degraded", kind)
        handle instead of touching the dead device at all: the wait
        routes straight to the CPU twin with no per-batch re-failure."""
        eng = self.eng
        if eng._degraded:
            return ("degraded", kind)

        def go():
            if kind == "grid":
                return eng.oracle.dispatch_vertices(a)
            if warm is not None:
                return eng.oracle.dispatch_pairs(a, b, warm=warm)
            return eng.oracle.dispatch_pairs(a, b)

        try:
            return self._timed("build.dispatch", go)
        except (RuntimeError, OSError) as e:
            return ("failed", e)

    def _wait_pairs(self, handle, args: tuple):
        """Pair-handle wait normalized to the 7-tuple wire format.
        Legacy oracles (and subclasses with their own handle kinds --
        PrunedOracle's 'pruned-chunks') must resolve through wait_pairs,
        not wait_pairs_full."""
        eng = self.eng
        if getattr(eng.oracle, "_point_full_out", False):
            return eng._wait_or_fallback("pairs_full", handle, args)
        out5 = eng._wait_or_fallback("pairs", handle,
                                     (args[0], args[1]))
        return (*out5, None, None)

    def _resolve(self, prog: _Program, background: bool = False):
        """Block on a program's handle (device failures retry on the
        CPU fallback, bit-compatible); memoized.  Thread-safe under
        the per-program lock: the committing thread and the async-
        certify waiter may race to the same program, and exactly one
        performs the wait.  Background resolution charges
        ``overlap_wait_s`` instead of the engine's ``_oracle_s`` (the
        overlap is the point: that wall no longer serializes a
        step)."""
        if prog.out is not None:
            return prog.out
        eng = self.eng
        # eng._oracle_lock (an RLock) serializes this wait against
        # BOTH the waiter thread and the engine's own synchronous
        # oracle calls: the oracle's wait paths mutate shared counters
        # (n_point_solves += K, the iteration ledger, obs batching)
        # and the device-failure/degrade machinery, none of which are
        # thread-safe -- per-program locks alone would let two
        # DIFFERENT programs' waits interleave those read-modify-write
        # updates and silently lose increments the bit-exact parity
        # gates depend on.
        with eng._oracle_lock, prog.lock:
            if prog.out is not None:
                return prog.out
            if prog.kind == "grid":
                span = "build.wait_vertices"

                def fn():
                    return eng._wait_or_fallback(
                        "vertices", prog.handle, prog.args)
            else:
                span = "build.wait_pairs"

                def fn():
                    return self._wait_pairs(prog.handle, prog.args)
            if background:
                # No obs span off-thread (the tracer's span stack is
                # thread-local; a background span would orphan).
                t0 = time.perf_counter()
                prog.out = fn()
                self.overlap_wait_s += time.perf_counter() - t0
                self.n_overlap_resolved += 1
            else:
                prog.out = self._timed(span, fn)
            prog.handle = None
        return prog.out

    # -- asynchronous host-certify (cfg.async_certify) ---------------------

    def _ensure_waiter(self) -> None:
        if self._bg_thread is not None:
            return
        self._bg_q = queue.Queue()

        def loop():
            while True:
                prog = self._bg_q.get()
                try:
                    if prog is None:
                        return
                    try:
                        self._resolve(prog, background=True)
                    except Exception:  # tpulint: disable=silent-except -- overlap is best-effort; the foreground wait re-raises
                        pass
                finally:
                    self._bg_q.task_done()

        self._bg_thread = threading.Thread(
            target=loop, daemon=True, name="ehm-async-certify")
        self._bg_thread.start()

    def prewait(self) -> None:
        """Queue every unresolved, non-speculative in-flight program
        for background resolution -- called by the engine right before
        its certify/commit block, so the device waits of steps k+1..
        overlap the host wall of step k.  A no-op unless
        cfg.async_certify armed the waiter."""
        if not self.async_on:
            return
        self._ensure_waiter()
        seen: set[int] = set()
        for e in self._win.values():
            for src in itertools.chain(
                    e.grid, *e.cells.values()):
                prog = src.prog
                if (prog.spec or prog.queued or prog.out is not None
                        or id(prog) in seen):
                    continue
                seen.add(id(prog))
                prog.queued = True
                self._bg_q.put(prog)

    def quiesce(self) -> None:
        """Stop the background waiter at a safe point: PENDING queue
        entries are dropped UN-resolved (their programs were never
        waited, so -- like the sync build's dropped in-flight handles
        -- the oracle never counts them; resolving them here would
        count device work whose cells cancel() is about to discard),
        then the one program the waiter may currently be resolving is
        allowed to finish (a snapshot must never race a half-resolved
        wait; that single program's wait-time counting is the at-most-
        one-program drift async certify can add at a cancel
        boundary)."""
        if self._bg_q is None:
            return
        while True:
            try:
                prog = self._bg_q.get_nowait()
            except queue.Empty:
                break
            if prog is not None:
                prog.queued = False
            self._bg_q.task_done()
        self._bg_q.join()

    def resolve_vertex(self, k: bytes, nd: int) -> dict | None:
        """Resolve this vertex's in-flight NON-speculative coverage
        into (nd,)-shaped row parts: {"mask","V","conv","grad","u0",
        "z"} -- the sharded frontier's request server uses it so a
        peer's request for a cell this shard already has ON THE DEVICE
        waits the existing program instead of re-solving (counting is
        unaffected: wait-time counters fire once per program, and the
        claim's own serve() later reads the memoized result).  None
        when nothing in flight covers the vertex."""
        e = self._win.get(k)
        if e is None:
            return None
        can = self.eng.oracle.can
        for src in e.grid:
            if src.prog.spec:
                continue
            sol = self._resolve(src.prog)
            i = src.idx
            return {"mask": np.ones(nd, dtype=bool), "V": sol.V[i],
                    "conv": sol.conv[i], "grad": sol.grad[i],
                    "u0": sol.u0[i], "z": sol.z[i]}
        res = None
        for d, lst in e.cells.items():
            for src in lst:
                if src.prog.spec:
                    continue
                out = self._resolve(src.prog)
                if res is None:
                    res = {"mask": np.zeros(nd, dtype=bool),
                           "V": np.full(nd, np.inf),
                           "conv": np.zeros(nd, dtype=bool),
                           "grad": np.zeros((nd, can.n_theta)),
                           "u0": np.zeros((nd, can.n_u)),
                           "z": np.zeros((nd, can.nz))}
                res["mask"][d] = True
                res["V"][d] = out[0][src.idx]
                res["conv"][d] = out[1][src.idx]
                res["grad"][d] = out[2][src.idx]
                res["u0"][d] = out[3][src.idx]
                res["z"][d] = out[4][src.idx]
                break
        return res

    # -- authoritative serve -----------------------------------------------

    def serve(self, plan: dict):
        """Resolve an AUTHORITATIVE plan's results: every route-matched
        cell comes from the window (one solve fanned out to every
        requester); the residual is solved synchronously with the
        authoritative warm data.  Returns (grid_sol, pair_out) shaped
        exactly like the oracle's own wait outputs, so the engine's
        merge code cannot tell the difference.

        Residual programs for BOTH parts dispatch before either part
        blocks (same overlap the legacy plan path had: the pair batch
        queues on the device behind the grid batch instead of waiting
        for its transfer)."""
        eng = self.eng
        can = eng.oracle.can
        nd = int(can.n_delta)
        gprep = pprep = None
        if plan["grid_arr"] is not None:
            gprep = self._prep_grid(plan)
        if plan["pair_slices"]:
            pprep = self._prep_pairs(plan)
        grid_sol = self._finish_grid(plan, can, nd, *gprep) \
            if gprep is not None else None
        pair_out = self._finish_pairs(plan, can, *pprep) \
            if pprep is not None else None
        # Window copies of the deltas THIS plan merges are redundant
        # from here on (later requesters hit the cache row), so they
        # retire now.  Other claims' in-flight cells for OTHER deltas
        # of the same vertex stay: the cache row being written does not
        # cover them, and dropping them would force their claims to
        # re-solve work the device already ran.
        for k in plan["grid_keys"]:
            self._pop_entry(k)
        for k, ds, _lo in plan["pair_slices"] or ():
            self._drop_cells(k, ds)
        return grid_sol, pair_out

    def _prep_grid(self, plan: dict):
        """Window lookup + residual dispatch (non-blocking) for the
        grid part: (srcs, miss, handle)."""
        srcs = []
        for k in plan["grid_keys"]:
            e = self._win.get(k)
            srcs.append(e.grid[0] if e is not None and e.grid else None)
        miss = [i for i, s in enumerate(srcs) if s is None]
        h = None
        if miss:
            arr = (plan["grid_arr"] if len(miss) == len(srcs)
                   else plan["grid_arr"][np.asarray(miss,
                                                    dtype=np.int64)])
            h = self._dispatch("grid", arr, None, None)
        return srcs, miss, h

    def _finish_grid(self, plan: dict, can, nd: int, srcs, miss, h):
        eng = self.eng
        keys = plan["grid_keys"]
        if len(miss) == len(srcs):
            # Nothing in flight (synchronous tail / depth 0): wait the
            # whole dispatched grid directly -- the legacy path.
            return self._timed(
                "build.wait_vertices",
                lambda: eng._wait_or_fallback(
                    "vertices", h, (plan["grid_arr"],)))
        P = len(keys)
        nt, nu, nz, nc = can.n_theta, can.n_u, can.nz, can.nc
        have_lam = bool(getattr(eng.oracle, "_point_full_out", False))
        V = np.empty((P, nd))
        conv = np.empty((P, nd), dtype=bool)
        grad = np.empty((P, nd, nt))
        u0 = np.empty((P, nd, nu))
        z = np.empty((P, nd, nz))
        Vs = np.empty(P)
        dstar = np.empty(P, dtype=np.int64)
        lam = np.empty((P, nd, nc)) if have_lam else None
        s = np.empty((P, nd, nc)) if have_lam else None
        by_prog: dict[int, tuple[_Program, list[int]]] = {}
        for i, src in enumerate(srcs):
            if src is not None:
                by_prog.setdefault(id(src.prog),
                                   (src.prog, []))[1].append(i)
        for prog, idxs in by_prog.values():
            sol = self._resolve(prog)
            ii = np.asarray(idxs, dtype=np.int64)
            jj = np.asarray([srcs[i].idx for i in idxs], dtype=np.int64)
            V[ii], conv[ii], grad[ii] = sol.V[jj], sol.conv[jj], \
                sol.grad[jj]
            u0[ii], z[ii] = sol.u0[jj], sol.z[jj]
            Vs[ii], dstar[ii] = sol.Vstar[jj], sol.dstar[jj]
            if have_lam:
                lam[ii], s[ii] = sol.lam[jj], sol.s[jj]
            prog.n_used += len(idxs) * nd
            if prog.spec:
                self.spec_hits += len(idxs) * nd
        if miss:
            mi = np.asarray(miss, dtype=np.int64)
            arr = plan["grid_arr"][mi]
            sol = self._timed(
                "build.wait_vertices",
                lambda: eng._wait_or_fallback("vertices", h, (arr,)))
            V[mi], conv[mi], grad[mi] = sol.V, sol.conv, sol.grad
            u0[mi], z[mi] = sol.u0, sol.z
            Vs[mi], dstar[mi] = sol.Vstar, sol.dstar
            if have_lam:
                lam[mi], s[mi] = sol.lam, sol.s
        return types.SimpleNamespace(V=V, conv=conv, grad=grad, u0=u0,
                                     z=z, Vstar=Vs, dstar=dstar, lam=lam,
                                     s=s)

    @staticmethod
    def _donor_equal(r1, r2, d: int) -> bool:
        """Bitwise equality of the donor cells a warm start actually
        reads (a widened cache row replaces the tuple, so identity
        misses rows whose delta-d slices never changed).  equal_nan:
        rescued cells carry NaN dual slots by design, and two rows
        identical up to those NaNs produce the identical warm tuple
        (the isfinite-gated `has` mask is False on both sides)."""
        if r1 is None or r2 is None or r1[8] is None or r2[8] is None:
            return False
        return (bool(r1[1][d]) == bool(r2[1][d])
                and bool(np.array_equal(r1[4][d], r2[4][d],
                                        equal_nan=True))
                and bool(np.array_equal(r1[8][d], r2[8][d],
                                        equal_nan=True))
                and bool(np.array_equal(r1[9][d], r2[9][d],
                                        equal_nan=True)))

    def _match_cell(self, e: _Entry, d: int, donor):
        """Route-matched window source for one pair cell, or None.
        Pair sources must carry the SAME donor row (identity, or
        bitwise-equal donor cells).  A dense-grid source is NEVER
        served to a pair-route need: the grid and pair program families
        compile to different XLA executables whose per-cell results can
        differ in the last ulp (measured: ~1e-16 drift on pendulum leaf
        payloads), and the bit-identity contract is family-exact, not
        just decision-exact."""
        for src in e.cells.get(d, ()):
            if src.donor is donor or self._donor_equal(src.donor, donor,
                                                       d):
                return src
        return None

    def _prep_pairs(self, plan: dict):
        """Window lookup + residual dispatch (non-blocking) for the
        pair part: (srcs, miss, handle)."""
        K = plan["pair_t"].shape[0]
        warm = plan["pair_warm"]
        srcs: list = [None] * K
        for (k, ds, lo), dnr in zip(plan["pair_slices"],
                                    plan["pair_donors"]):
            e = self._win.get(k)
            if e is None:
                continue
            for pos, d in enumerate(ds):
                srcs[lo + pos] = self._match_cell(e, int(d), dnr)
        miss = [i for i, s in enumerate(srcs) if s is None]
        h = None
        if miss:
            if len(miss) == K:
                h = self._dispatch("pairs", plan["pair_t"],
                                   plan["pair_d"], warm)
            else:
                mi = np.asarray(miss, dtype=np.int64)
                wa = (tuple(w[mi] for w in warm)
                      if warm is not None else None)
                h = self._dispatch("pairs", plan["pair_t"][mi],
                                   plan["pair_d"][mi], wa)
        return srcs, miss, h

    def _finish_pairs(self, plan: dict, can, srcs, miss, h):
        eng = self.eng
        K = plan["pair_t"].shape[0]
        warm = plan["pair_warm"]
        nt, nu, nz, nc = can.n_theta, can.n_u, can.nz, can.nc
        if len(miss) == K:
            # Nothing in flight: wait the whole dispatched batch
            # directly -- the legacy path.
            return self._timed(
                "build.wait_pairs",
                lambda: self._wait_pairs(
                    h, (plan["pair_t"], plan["pair_d"], warm)))
        have_lam = bool(getattr(eng.oracle, "_point_full_out", False))
        V = np.empty(K)
        conv = np.empty(K, dtype=bool)
        grad = np.empty((K, nt))
        u0 = np.empty((K, nu))
        z = np.empty((K, nz))
        lam = np.empty((K, nc)) if have_lam else None
        s = np.empty((K, nc)) if have_lam else None
        by_prog: dict[int, tuple[_Program, list[int]]] = {}
        for flat, src in enumerate(srcs):
            if src is not None:
                by_prog.setdefault(id(src.prog),
                                   (src.prog, []))[1].append(flat)
        for prog, idxs in by_prog.values():
            # Always a pair-family program (_match_cell is family-
            # exact), so `out` is the 7-tuple wire format.
            out = self._resolve(prog)
            ii = np.asarray(idxs, dtype=np.int64)
            jj = np.asarray([srcs[i].idx for i in idxs], dtype=np.int64)
            V[ii], conv[ii] = out[0][jj], out[1][jj]
            grad[ii], u0[ii], z[ii] = out[2][jj], out[3][jj], out[4][jj]
            if have_lam:
                lam[ii], s[ii] = out[5][jj], out[6][jj]
            prog.n_used += len(idxs)
            if prog.spec:
                self.spec_hits += len(idxs)
        if miss:
            mi = np.asarray(miss, dtype=np.int64)
            ta, da = plan["pair_t"][mi], plan["pair_d"][mi]
            wa = (tuple(w[mi] for w in warm)
                  if warm is not None else None)
            out = self._timed(
                "build.wait_pairs",
                lambda: self._wait_pairs(h, (ta, da, wa)))
            V[mi], conv[mi], grad[mi] = out[0], out[1], out[2]
            u0[mi], z[mi] = out[3], out[4]
            if have_lam and out[5] is not None:
                lam[mi], s[mi] = out[5], out[6]
        return V, conv, grad, u0, z, lam, s

    # -- speculation -------------------------------------------------------

    def note_children(self, li: int, ri: int, gap: float) -> None:
        """Record the split gap of a fresh split as the children's
        split-prediction hint (read once when their batch consumes)."""
        if self.spec_on:
            self._child_gap[li] = gap
            self._child_gap[ri] = gap

    def speculate(self, nodes: list[int]) -> None:
        """Dispatch the bisection-midpoint programs of every batch cell
        the gap heuristic predicts will split -- called after the
        batch's own rows landed in the cache (donor rows final) and
        BEFORE its certificates run, so the device chews on the next
        generation while the host certifies this one."""
        hints = {n: self._child_gap.pop(n, None) for n in nodes}
        if not self.spec_on:
            return
        eng = self.eng
        # Idle-device gate: when recent steps were device-bound the
        # speculative batch would only queue behind real work (see
        # SPEC_DEVICE_FRAC_MAX).  The hints above are still consumed --
        # they are one-shot either way.
        if eng.device_frac_ema > self.SPEC_DEVICE_FRAC_MAX:
            return
        if len(self._win) >= self.window_cap:
            return  # bounded window: see fill()
        # The only population whose split is predictable BEFORE its
        # certificate is the cells whose inherited gap is INFINITE --
        # i.e. whose parent split on mixed vertex feasibility or an
        # inconclusive infeasibility check: the hybrid feasible set's
        # boundary crosses the parent, so (almost) every child
        # straddles it and must split again.  Measured on the pendulum
        # (eps_a 0.05 and 0.02): children of gap=inf splits re-split at
        # 100%, while children of FINITE-gap splits re-split at ~0.49
        # independent of gap magnitude (bisection localizes the error
        # into one child, so the parent's scalar gap carries ~1 bit) --
        # a finite-gap threshold, however tuned, would waste nearly
        # one solve per hit, so no such knob exists.
        sb = eng.cfg.semi_explicit_boundary_depth
        cands = [n for n in nodes
                 if hints[n] is not None and hints[n] == np.inf
                 and eng.tree.depth[n] < eng.cfg.max_depth
                 # A predicted-mixed cell at the semi-explicit closure
                 # depth closes as a boundary leaf instead of splitting.
                 and (sb is None or eng.tree.depth[n] < sb)]
        if not cands:
            return
        planned = eng._plan_spec_children(cands, window=self)
        if planned is None:
            return
        plan, owners = planned
        self.admit_plan(plan, owners=owners)
        for k, n in owners.items():
            self._spec_keys.setdefault(n, []).append(k)

    def on_commit(self, n: int, split: bool) -> None:
        """Settle node n's speculation: a split leaves the staged
        midpoint rows for the children to consume; anything else drops
        them before they can reach a cache row (waste)."""
        keys = self._spec_keys.pop(n, None)
        if keys is None or split:
            return
        for k in keys:
            e = self._win.get(k)
            if e is None:
                continue
            kept = []
            for src in e.grid:
                if src.owner == n:
                    self._drop_ref(src.prog)
                else:
                    kept.append(src)
            e.grid = kept
            for d in list(e.cells):
                lst = []
                for src in e.cells[d]:
                    if src.owner == n:
                        self._drop_ref(src.prog)
                    else:
                        lst.append(src)
                if lst:
                    e.cells[d] = lst
                else:
                    del e.cells[d]
            if not e.grid and not e.cells:
                self._win.pop(k, None)

    # -- retirement / cancel ----------------------------------------------

    def _drop_ref(self, prog: _Program) -> bool:
        prog.live_refs -= 1
        if prog.live_refs <= 0 and not prog.retired:
            prog.retired = True
            if prog.spec:
                unused = max(0, prog.n_cells - prog.n_used)
                self.spec_waste += unused
                if prog.out is None:
                    # Dropped before anyone waited: the device ran the
                    # work but it never reached the oracle's solve
                    # counters -- tracked so spec_waste_frac's
                    # denominator stays "cells the device actually ran".
                    self.spec_dropped_unwaited += unused
        return True

    def _pop_entry(self, k: bytes) -> None:
        e = self._win.pop(k, None)
        if e is None:
            return
        for src in e.grid:
            self._drop_ref(src.prog)
        for lst in e.cells.values():
            for src in lst:
                self._drop_ref(src.prog)

    def _drop_cells(self, k: bytes, ds) -> None:
        """Retire one vertex's window sources for the deltas a served
        plan just merged, plus any dense-grid sources (the cache row
        now exists, and grid coverage is only ever consulted for
        row-less vertices, so they are dead weight).  Pair sources for
        other deltas stay to serve the claims that dispatched them."""
        e = self._win.get(k)
        if e is None:
            return
        for src in e.grid:
            self._drop_ref(src.prog)
        e.grid = []
        for d in map(int, ds):
            lst = e.cells.pop(d, None)
            if lst:
                for src in lst:
                    self._drop_ref(src.prog)
        if not e.cells:
            self._win.pop(k, None)

    def cancel(self) -> None:
        """Drop every in-flight claim, window row, and handle.  Called
        before a checkpoint serializes (so a resume can never
        re-dispatch or double-commit in-flight work) and at the end of
        a run.  Dispatched-but-unwaited programs were never counted by
        the oracle, so solve statistics stay exact.  (Under
        cfg.async_certify, quiesce() drops the waiter's PENDING work
        un-resolved for the same reason; only a program mid-resolve at
        this instant is waited-and-counted -- an at-most-one-program
        stats drift per cancel, never a tree change.)"""
        self.quiesce()
        if self._bg_thread is not None:
            # Shut the waiter down for real: a daemon thread parked in
            # get() would otherwise pin the whole engine (tree, cache,
            # oracle) through its closure for the life of the process
            # -- one leaked build per async-certify run in long-lived
            # hosts.  prewait() restarts a fresh one on demand.
            self._bg_q.put(None)
            self._bg_q.join()
            self._bg_thread.join(timeout=5.0)
            self._bg_thread = None
            self._bg_q = None
        for k in list(self._win):
            self._pop_entry(k)
        self._claims.clear()
        self._spec_keys.clear()
