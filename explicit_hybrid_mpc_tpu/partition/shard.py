"""Pod-scale sharded frontier: per-process shard contexts, cross-host
vertex dedup, and shard-tree merging (ROADMAP item 1).

The lockstep multi-process build (parallel/distributed.py) replays the
IDENTICAL host frontier on every process: N hosts pay N copies of the
plan/certify/commit wall and the only parallelism is inside the sharded
device programs.  This module shards the FRONTIER itself: each process
owns a subset of the root simplices (round-robin over the canonical
Kuhn-triangulation order) and runs the ordinary pipelined engine over
its own subtrees, with its oracle on its own local devices -- no
per-step collectives, no replicated host work.

Cross-host vertex dedup: bisection midpoints on the face shared by two
shards' root regions are needed by both.  A deterministic OWNERSHIP
HASH over the vertex cache key assigns every (vertex, delta) cell to
exactly one shard (all commutations of a vertex are co-owned, so the
owner can serve a full-enumeration need through the same dense-grid
program family the single-process build uses -- the (vertex, delta)
cell remains the dedup/transfer unit: requests and publications carry
per-delta masks).  A shard needing a remotely-owned cell posts an
asynchronous REQUEST into the shared exchange directory and keeps
pipelining; the owner answers requests between its own steps, solving
on-behalf cells it never needed itself, and PUBLISHES result rows that
any shard can consume.  Two shards can therefore never solve the same
(vertex, delta) program: summed ``oracle.point_solves`` across shards
equals the single-process build's count exactly.

The exchange is plain files under one shared directory (request
journals + atomically-renamed result batches + done markers) and --
critically -- it is ASYNCHRONOUS: no step of any shard ever blocks on
a collective; a shard blocks only when its own batch's certificates
need a remote cell that has not landed yet, and even then it keeps
serving its peers while it waits (deadlock-free by construction).
Filesystem requirements: a local FS / tmpfs (the CI harness) or a
POSIX-COHERENT shared mount where one client's appends/renames become
visible to others without a close (most NFS servers with attribute
caching tuned down qualify; an object-store fuse mount that uploads
only on close does NOT -- its visibility latency turns every
cross-shard cell into a shard_timeout_s stall followed by a loud
local fallback, sound but slow and duplicate-counting).

Tree contract: the merged tree is node-for-node identical to the
single-process build -- vertices bitwise (bisection arithmetic is
exact), same leaf sets, same certification statuses and commutation
choices -- compared canonically (by vertex-matrix bytes; the merged
insertion ORDER is per-shard-subtree, not breadth-first interleaved).
Leaf payload floats carry the documented last-ulp pow-2-bucket caveat
(a remote cell is solved inside the owner's batch composition), and
warm-start donor drift on shared cells is absorbed by the eps margin
exactly like the CPU-twin fallback's -- 0 flips measured on the DI
acceptance config (tests/test_shard.py, scripts/fleet_smoke.py
--sharded).
"""

from __future__ import annotations

import hashlib
import json
import os
import time
import warnings
import zipfile

import numpy as np

from explicit_hybrid_mpc_tpu.partition.tree import NO_CHILD, Tree
from explicit_hybrid_mpc_tpu.utils import atomic


def shard_owner(key: bytes, n_shards: int) -> int:
    """Deterministic owner shard of a vertex cache key.

    Stable across processes, runs, and platforms (blake2b over the
    exact key bytes -- no PYTHONHASHSEED dependence), and independent
    of which shard asks: every (vertex, delta) cell is assigned to
    exactly one shard for ANY process count, because all delta cells
    of a vertex share the vertex's owner.  Per-vertex (not per-cell)
    granularity is deliberate: a full-enumeration need then stays one
    dense-grid program on one owner instead of splintering into
    per-delta pair programs across shards -- the same program family
    the single-process build dispatches (the bit-parity route-match
    argument in partition/pipeline.py is family-exact)."""
    if n_shards <= 1:
        return 0
    h = hashlib.blake2b(key, digest_size=8).digest()
    return int.from_bytes(h, "little") % n_shards


def owned_root_indices(n_roots: int, shard: int, n_shards: int) -> list:
    """Root indices owned by `shard`: round-robin over the canonical
    root order (deterministic; every root owned by exactly one
    shard)."""
    return [r for r in range(n_roots) if r % n_shards == shard]


# -- cross-host exchange ----------------------------------------------------


class ShardExchange:
    """Asynchronous file-based cell exchange under one shared directory.

    Layout (all writers atomic-rename or append-only, so readers never
    see a torn record as anything but a retriable tail):

    - ``req.p<i>.jsonl``   -- shard i's request journal (append-only
      JSON lines ``{"k": hex-key, "t": [exact theta], "d": [deltas]}``;
      JSON floats round-trip exactly in python, so the owner solves at
      the requester's EXACT coordinates, not the rounded cache key).
    - ``pub.p<i>.<seq>.npz`` -- result batches published by shard i
      (tmp + rename; per-row delta masks, merged idempotently by every
      consumer).
    - ``done.p<i>.json``   -- shard i's frontier-drained marker,
      written AFTER its tree file (the TREE's commit marker; the
      stats file intentionally lands LATER, after the all-shards
      drain barrier, so on-behalf solves served while draining are in
      it -- consumers wait for stats.p<i>.json itself, as finalize's
      second barrier does).
    - ``tree.p<i>.pkl`` / ``stats.p<i>.json`` -- shard results the
      merge consumes.
    """

    def __init__(self, directory: str, shard: int, n_shards: int):
        self.dir = directory
        self.shard = int(shard)
        self.n_shards = int(n_shards)
        os.makedirs(directory, exist_ok=True)
        # key -> {"mask","V","conv","grad","u0","z","lam","s"} merged
        # over every publication seen (and everything this shard
        # published itself).
        self.rows: dict[bytes, dict] = {}
        self._req_path = os.path.join(directory, f"req.p{shard}.jsonl")
        self._req_f = None
        self._req_off: dict[str, int] = {}
        self._pub_seq = 0
        self._seen_pubs: set[str] = set()
        # Per-peer next expected publication sequence + torn-read
        # retries (see _new_pub_paths/poll).
        self._peer_seq: dict[int, int] = {}
        self._retry_pubs: list[str] = []
        # key -> delta mask already requested (request once per cell).
        self._req_mask: dict[bytes, np.ndarray] = {}
        # key -> delta mask already published (publish once per cell).
        self._pub_mask: dict[bytes, np.ndarray] = {}
        # Crash/resume recovery: reload THIS shard's own prior
        # publications (each file is an atomic whole) so a restarted
        # owner (a) continues the sequence instead of overwriting
        # files peers already consumed -- their dedup is by basename
        # and their sequence cursors are already past it, so the
        # overwrite would silently orphan every later answer -- and
        # (b) serves re-read requests from the recovered store instead
        # of re-solving cells it already published (the zero-duplicate
        # bar).  Peer publications re-ingest from zero via poll();
        # merging is idempotent.
        self._recover_own_publications()

    def _recover_own_publications(self) -> None:
        seq = 0
        while True:
            path = os.path.join(self.dir,
                                f"pub.p{self.shard}.{seq:06d}.npz")
            if not os.path.exists(path):
                break
            try:
                with np.load(path) as zf:
                    keys = zf["keys"]
                    lam = zf["lam"] if "lam" in zf.files else None
                    s = zf["s"] if "s" in zf.files else None
                    for i in range(keys.shape[0]):
                        key = keys[i].tobytes()
                        self.merge_row(
                            key, zf["mask"][i], zf["V"][i],
                            zf["conv"][i], zf["grad"][i], zf["u0"][i],
                            zf["z"][i],
                            lam[i] if lam is not None else None,
                            s[i] if s is not None else None)
                        self._pub_mask[key] = \
                            self.rows[key]["mask"].copy()
            except (OSError, ValueError, KeyError,
                    zipfile.BadZipFile):
                pass  # a torn own file: sequence past it regardless
            self._seen_pubs.add(os.path.basename(path))
            seq += 1
        self._pub_seq = seq

    # -- paths -------------------------------------------------------------

    def tree_path(self, shard: int | None = None) -> str:
        s = self.shard if shard is None else shard
        return os.path.join(self.dir, f"tree.p{s}.pkl")

    def stats_path(self, shard: int | None = None) -> str:
        s = self.shard if shard is None else shard
        return os.path.join(self.dir, f"stats.p{s}.json")

    def done_path(self, shard: int | None = None) -> str:
        s = self.shard if shard is None else shard
        return os.path.join(self.dir, f"done.p{s}.json")

    def hb_path(self, shard: int | None = None) -> str:
        s = self.shard if shard is None else shard
        return os.path.join(self.dir, f"hb.p{s}")

    #: Seconds between heartbeat-file touches (liveness for the drain
    #: barrier -- an interior-crunching shard may generate no exchange
    #: traffic for hours).
    HB_EVERY_S = 5.0

    def heartbeat(self) -> None:
        """Touch this shard's liveness marker, throttled."""
        now = time.monotonic()
        if now - getattr(self, "_hb_last", 0.0) < self.HB_EVERY_S:
            return
        self._hb_last = now
        try:
            with open(self.hb_path(), "a") as f:
                f.write(".")  # append: size growth is visible even on
                # mounts that cache utime-only changes
        except OSError:
            pass  # liveness is best-effort; the deadline still bounds

    def peer_heartbeats(self) -> tuple:
        """Fingerprint of every peer's liveness marker (sizes +
        mtimes); any change means some peer is alive and making
        progress."""
        out = []
        for s in range(self.n_shards):
            if s == self.shard:
                continue
            try:
                st = os.stat(self.hb_path(s))
                out.append((s, st.st_size, st.st_mtime))
            except OSError:
                out.append((s, -1, -1.0))
        return tuple(out)

    # -- requests ----------------------------------------------------------

    def request(self, key: bytes, theta: np.ndarray,
                need: np.ndarray) -> int:
        """Post an asynchronous request for the deltas of `key` in mask
        `need` not yet requested; returns how many new cells were
        posted.  Append + flush (no fsync: same-host readers see the
        page cache; durability is not required -- a crashed requester
        re-requests on resume)."""
        prev = self._req_mask.get(key)
        new = need if prev is None else (need & ~prev)
        if not new.any():
            return 0
        if self._req_f is None:
            self._req_f = open(self._req_path, "a")
        rec = {"k": key.hex(), "t": np.asarray(theta).tolist(),
               "d": np.nonzero(new)[0].tolist()}
        self._req_f.write(json.dumps(rec) + "\n")
        self._req_f.flush()
        self._req_mask[key] = new if prev is None else (prev | new)
        return int(new.sum())

    def read_requests(self, nd: int) -> list[tuple[bytes, np.ndarray,
                                                   np.ndarray]]:
        """New request records from every PEER journal since the last
        read, merged per key: [(key, theta, delta mask)].  A torn tail
        line (a peer mid-write) is left unconsumed for the next poll."""
        merged: dict[bytes, tuple[np.ndarray, np.ndarray]] = {}
        for s in range(self.n_shards):
            if s == self.shard:
                continue
            path = os.path.join(self.dir, f"req.p{s}.jsonl")
            try:
                size = os.path.getsize(path)
            except OSError:
                continue
            off = self._req_off.get(path, 0)
            if size <= off:
                continue
            with open(path, "rb") as f:
                f.seek(off)
                buf = f.read(size - off)
            end = buf.rfind(b"\n")
            if end < 0:
                continue  # only a torn tail so far
            self._req_off[path] = off + end + 1
            for ln in buf[:end].split(b"\n"):
                if not ln.strip():
                    continue
                try:
                    rec = json.loads(ln)
                except json.JSONDecodeError:
                    continue  # torn mid-journal line: skip, peers retry
                key = bytes.fromhex(rec["k"])
                theta = np.asarray(rec["t"], dtype=np.float64)
                mask = np.zeros(nd, dtype=bool)
                mask[np.asarray(rec["d"], dtype=np.int64)] = True
                if key in merged:
                    merged[key] = (merged[key][0], merged[key][1] | mask)
                else:
                    merged[key] = (theta, mask)
        return [(k, t, m) for k, (t, m) in merged.items()]

    # -- publications ------------------------------------------------------

    def merge_row(self, key: bytes, mask: np.ndarray, V, conv, grad,
                  u0, z, lam=None, s=None) -> None:
        """Merge per-delta cells (valid where `mask`) into the in-memory
        store row for `key` (idempotent; later merges overwrite the
        same cells with the same values)."""
        row = self.rows.get(key)
        if row is None:
            nd = mask.shape[0]
            row = self.rows[key] = {
                "mask": np.zeros(nd, dtype=bool),
                "V": np.full(nd, np.inf),
                "conv": np.zeros(nd, dtype=bool),
                "grad": np.zeros((nd,) + np.shape(grad)[1:]),
                "u0": np.zeros((nd,) + np.shape(u0)[1:]),
                "z": np.zeros((nd,) + np.shape(z)[1:]),
                "lam": (np.zeros((nd,) + np.shape(lam)[1:])
                        if lam is not None else None),
                "s": (np.zeros((nd,) + np.shape(s)[1:])
                      if s is not None else None),
            }
        ds = np.nonzero(mask)[0]
        row["mask"][ds] = True
        row["V"][ds] = np.asarray(V)[ds]
        row["conv"][ds] = np.asarray(conv)[ds]
        row["grad"][ds] = np.asarray(grad)[ds]
        row["u0"][ds] = np.asarray(u0)[ds]
        row["z"][ds] = np.asarray(z)[ds]
        if lam is not None and row["lam"] is not None:
            row["lam"][ds] = np.asarray(lam)[ds]
            row["s"][ds] = np.asarray(s)[ds]

    def publish(self, items: list[tuple[bytes, np.ndarray]]) -> int:
        """Publish store rows for `items` = [(key, requested mask)]:
        each row ships its full currently-available mask (consumers
        merge idempotently), but a cell already published is never
        re-shipped -- `_pub_mask` keeps publications append-only in
        coverage.  Returns rows actually written."""
        out_keys, out_rows = [], []
        for key, req in items:
            row = self.rows.get(key)
            if row is None:
                continue
            prev = self._pub_mask.get(key)
            fresh = (req & row["mask"]) if prev is None else \
                (req & row["mask"] & ~prev)
            if not fresh.any():
                continue
            self._pub_mask[key] = row["mask"].copy()
            out_keys.append(np.frombuffer(key, dtype=np.uint8))
            out_rows.append(row)
        if not out_keys:
            return 0
        arrs = {
            "keys": np.stack(out_keys),
            "mask": np.stack([r["mask"] for r in out_rows]),
            "V": np.stack([r["V"] for r in out_rows]),
            "conv": np.stack([r["conv"] for r in out_rows]),
            "grad": np.stack([r["grad"] for r in out_rows]),
            "u0": np.stack([r["u0"] for r in out_rows]),
            "z": np.stack([r["z"] for r in out_rows]),
        }
        if out_rows[0]["lam"] is not None:
            arrs["lam"] = np.stack([r["lam"] for r in out_rows])
            arrs["s"] = np.stack([r["s"] for r in out_rows])
        path = os.path.join(
            self.dir, f"pub.p{self.shard}.{self._pub_seq:06d}.npz")
        self._pub_seq += 1
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrs)
            f.flush()
        os.replace(tmp, path)  # readers only ever see whole files
        self._seen_pubs.add(os.path.basename(path))
        return len(out_rows)

    def _new_pub_paths(self) -> list[str]:
        """Unconsumed publication files, probed by each peer's next
        expected sequence number -- O(n_shards + new files) per call
        instead of an O(all files) directory glob, which matters
        because poll() runs every POLL_S inside a blocked collect()
        (and on the NFS/GCS-fuse mounts the exchange targets, a
        directory listing is a server round-trip)."""
        out: list[str] = []
        for s in range(self.n_shards):
            if s == self.shard:
                continue
            seq = self._peer_seq.get(s, 0)
            while True:
                path = os.path.join(self.dir,
                                    f"pub.p{s}.{seq:06d}.npz")
                if not os.path.exists(path):
                    break
                out.append(path)
                seq += 1
            self._peer_seq[s] = seq
        return out

    def poll(self) -> int:
        """Load publications from peers not yet consumed; returns rows
        merged into the store."""
        n = 0
        retry = []
        for path in self._new_pub_paths() + self._retry_pubs:
            base = os.path.basename(path)
            if base in self._seen_pubs:
                continue
            try:
                with np.load(path) as zf:
                    keys = zf["keys"]
                    lam = zf["lam"] if "lam" in zf.files else None
                    s = zf["s"] if "s" in zf.files else None
                    for i in range(keys.shape[0]):
                        self.merge_row(
                            keys[i].tobytes(), zf["mask"][i], zf["V"][i],
                            zf["conv"][i], zf["grad"][i], zf["u0"][i],
                            zf["z"][i],
                            lam[i] if lam is not None else None,
                            s[i] if s is not None else None)
                        n += 1
            except (OSError, ValueError, KeyError,
                    zipfile.BadZipFile):
                # A reader racing the writer's rename never sees a torn
                # file on POSIX, but a remote/fuse mount may: retry on
                # the next poll (the sequence probe has already moved
                # past it, so it rides the explicit retry list).
                retry.append(path)
                continue
            self._seen_pubs.add(base)
        self._retry_pubs = retry
        return n

    def close(self) -> None:
        if self._req_f is not None:
            self._req_f.close()
            self._req_f = None


# -- engine-facing context --------------------------------------------------


class ShardContext:
    """Bridges one FrontierEngine to the exchange: root ownership,
    remote-cell routing during planning, blocking collection at
    certify time, and the on-behalf request server.

    Built by the engine when ``cfg.shard_frontier`` resolves to an
    active multi-shard run; ``from_config`` returns None otherwise, so
    the single-process path carries a literal None-check and nothing
    else."""

    #: Poll interval while blocked on a remote cell (seconds).
    POLL_S = 0.001

    def __init__(self, eng, shard: int, n_shards: int, directory: str,
                 timeout_s: float = 300.0):
        self.eng = eng
        self.shard = int(shard)
        self.n_shards = int(n_shards)
        self.timeout_s = float(timeout_s)
        self._claim_dir(directory)
        self.ex = ShardExchange(directory, shard, n_shards)
        self.remote_cells = 0     # cells consumed from peers
        self.served_cells = 0     # on-behalf cells solved for peers
        self.fallback_cells = 0   # remote cells solved locally (timeout)

    def _claim_dir(self, directory: str) -> None:
        """Bind the exchange directory to THIS build's identity.

        Exchange state survives crashes on purpose (publication
        recovery, request journals), so a REUSED directory from a
        DIFFERENT problem/eps/shard-count would serve rows solved for
        another program -- keyed only by rounded theta coordinates,
        they would merge silently and corrupt certificates.  The first
        shard writes a manifest (problem content hash + eps + shard
        count); every shard verifies it and refuses a mismatch with a
        clear message.  A same-build restart matches and proceeds."""
        from explicit_hybrid_mpc_tpu.obs import clock as obs_clock
        from explicit_hybrid_mpc_tpu.partition import provenance as prov

        eng = self.eng
        ident = {"problem_hash": prov.problem_hash(eng.problem),
                 "eps_a": float(getattr(eng.cfg, "eps_a", 0.0)),
                 "eps_r": float(getattr(eng.cfg, "eps_r", 0.0)),
                 "n_shards": self.n_shards}
        manifest = dict(ident, run_id=obs_clock.run_id())
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, "manifest.json")
        if os.path.exists(path):
            try:
                with open(path) as f:
                    prior = json.load(f)
            except (OSError, json.JSONDecodeError):
                prior = None  # torn: a concurrent first write; retry
            if prior is not None:
                if {k: prior.get(k) for k in ident} != ident:
                    raise ValueError(
                        f"shard_dir {directory} belongs to a "
                        f"different build ({prior} != {manifest}); "
                        "use a fresh --shard-dir per build")
                if prior.get("run_id") != manifest["run_id"]:
                    # Same build identity, different run id.  NEVER
                    # delete anything: shards of one launch share an
                    # id only when a launcher exports EHM_RUN_ID, so a
                    # mismatch may simply be a platform-spawned peer of
                    # THIS run -- deleting 'stale' files here would
                    # destroy a live peer's journals (open handles
                    # write to unlinked inodes; sequence cursors point
                    # past deleted files).  The state is same-problem
                    # and deterministic, so reusing it is SOUND; it
                    # can only pre-solve cells, which shows up as a
                    # lower summed solve count.  Warn so the exact
                    # count-parity bar is knowingly waived on reused
                    # dirs (the gates always use fresh ones).
                    warnings.warn(
                        f"shard_dir {directory} carries exchange "
                        f"state from run {prior.get('run_id')!r} "
                        f"(this run: {manifest['run_id']!r}); reusing "
                        "it as a same-build cache -- summed solve "
                        "counts may undershoot the single-process "
                        "build's; use a fresh --shard-dir for parity "
                        "measurements", RuntimeWarning, stacklevel=2)
                return
        atomic.atomic_write_json(path, manifest)

    @classmethod
    def from_config(cls, eng, cfg) -> "ShardContext | None":
        if not getattr(cfg, "shard_frontier", False):
            return None
        shard = getattr(cfg, "shard_index", None)
        count = getattr(cfg, "shard_count", None)
        if shard is None or count is None:
            import jax

            if shard is None:
                shard = jax.process_index()
            if count is None:
                count = jax.process_count()
        if count <= 1:
            return None  # single shard: behavior-identical plain build
        directory = getattr(cfg, "shard_dir", None)
        if not directory:
            raise ValueError(
                "cfg.shard_frontier needs cfg.shard_dir (a directory "
                "shared by every shard -- the CLI derives "
                "<output>.shard)")
        return cls(eng, shard, count, directory,
                   timeout_s=getattr(cfg, "shard_timeout_s", 300.0))

    # -- ownership ---------------------------------------------------------

    def owned_roots(self, roots: list[int]) -> list[int]:
        return [roots[i] for i in owned_root_indices(
            len(roots), self.shard, self.n_shards)]

    def is_remote(self, key: bytes) -> bool:
        return shard_owner(key, self.n_shards) != self.shard

    # -- consuming remote results ------------------------------------------

    def take(self, key: bytes, need: np.ndarray) -> bool:
        """Merge store coverage of `key` cells in `need` into the
        engine cache (through the engine's ONE row-writing path);
        returns True when anything was merged."""
        row = self.ex.rows.get(key)
        if row is None:
            return False
        avail = need & row["mask"]
        if not avail.any():
            return False
        ds = np.nonzero(avail)[0]
        self._merge_cells(key, ds, row)
        self.remote_cells += len(ds)
        return True

    def _merge_cells(self, key: bytes, ds: np.ndarray, row: dict) -> None:
        """Write store cells into the engine cache via
        eng._merge_plan_results (the shared merge keeps Vstar/dstar
        reduction and row-widening semantics identical to a local
        solve)."""
        plan = {"grid_arr": None, "grid_keys": [],
                "pair_slices": [(key, ds, 0)], "pair_donors": [None],
                "n_skips": 0, "n_new": 0}
        # Exchange rows are DONOR-STERILE on purpose (no duals ever
        # cross the exchange): publication ARRIVAL timing is
        # nondeterministic, and a remote row that could become a
        # warm-start donor would make _pick_donor's choice -- and
        # therefore the pipeline's serve-time route match -- depend on
        # cross-host timing, turning the exact summed-point_solves
        # parity into a race.  Cells near a shard boundary simply
        # start cold; the merit gate makes that a pure iteration-count
        # effect.
        pair_out = (row["V"][ds], row["conv"][ds], row["grad"][ds],
                    row["u0"][ds], row["z"][ds], None, None)
        self.eng._merge_plan_results(plan, None, pair_out)

    def request(self, key: bytes, theta: np.ndarray,
                need: np.ndarray) -> None:
        self.ex.request(key, theta, need)

    # -- eviction stash -----------------------------------------------------

    def _boundary_roots(self):
        """Vertex matrices of the root simplices OTHER shards own
        (lazy: the engine's tree exists by the first eviction)."""
        if not hasattr(self, "_nonowned_roots"):
            eng = self.eng
            own = set(owned_root_indices(len(eng.roots), self.shard,
                                         self.n_shards))
            self._nonowned_roots = [
                np.array(eng.tree.vertices[r])
                for i, r in enumerate(eng.roots) if i not in own]
        return self._nonowned_roots

    def note_evict(self, key: bytes, vertex: np.ndarray,
                   row: tuple) -> None:
        """Called by the engine right before evicting a cache row: an
        OWNED vertex on the shard boundary (contained in a root
        simplex another shard owns) is stashed into the exchange store
        first, so a peer's request arriving AFTER the owner's own
        nodes closed is served from the stash instead of re-solved --
        the eviction race would otherwise double-solve the cell and
        break the exact summed-point_solves bar (timing-dependent).
        Interior vertices are skipped: no peer subtree can ever touch
        them, so the stash stays O(shard boundary), not O(subtree)."""
        from explicit_hybrid_mpc_tpu.partition import geometry

        if shard_owner(key, self.n_shards) != self.shard:
            return
        srow = self.ex.rows.get(key)
        have = srow["mask"] if srow is not None else None
        mask = row[7]
        if have is not None and not (mask & ~have).any():
            return
        if not any(geometry.contains(V, vertex, 1e-9)
                   for V in self._boundary_roots()):
            return
        self.ex.merge_row(key, mask, row[0], row[1], row[2], row[3],
                          row[4])

    def collect(self, remote: list[tuple[bytes, np.ndarray,
                                         np.ndarray]]) -> None:
        """Block until every remote cell in `remote` = [(key, theta,
        delta index array)] is in the engine cache, serving peer
        requests the whole time (deadlock freedom: two shards blocked
        on each other both keep answering).  After cfg.shard_timeout_s
        the stragglers are solved LOCALLY -- liveness wins over the
        zero-duplicate guarantee, loudly (obs event + counter; the
        acceptance configs never hit it)."""
        eng = self.eng
        nd = eng.oracle.can.n_delta
        pending = {k: (t, ds) for k, t, ds in remote}
        t0 = time.monotonic()

        def _missing(k: bytes, ds: np.ndarray) -> np.ndarray:
            need = np.zeros(nd, dtype=bool)
            need[ds] = True
            crow = eng.cache.get_key(k)
            if crow is not None:
                need &= ~crow[7]
            return need

        sleep_s = self.POLL_S
        while pending:
            self.ex.heartbeat()
            self.ex.poll()
            progressed = False
            for k in list(pending):
                _t, ds = pending[k]
                need = _missing(k, ds)
                if need.any():
                    if self.take(k, need):
                        progressed = True
                    need = _missing(k, ds)
                if not need.any():
                    del pending[k]
            if not pending:
                break
            self.serve_requests()
            if time.monotonic() - t0 > self.timeout_s:
                self._fallback(pending)
                break
            time.sleep(sleep_s)
            # Adaptive backoff: stay snappy while results stream in,
            # ramp toward 100 ms while nothing arrives -- a blocked
            # shard at a fixed 1 ms issues ~1000x n_shards stat-class
            # filesystem operations per second against the shared
            # mount, for no latency benefit.
            sleep_s = self.POLL_S if progressed \
                else min(sleep_s * 1.5, 0.1)

    def _fallback(self, pending: dict) -> None:
        """Timeout path: solve the still-missing remote cells locally
        (duplicate work, sound results) so a dead peer cannot hang the
        build."""
        eng = self.eng
        slices, donors, T, D = [], [], [], []
        off = 0
        for k, (theta, ds) in pending.items():
            crow = eng.cache.get_key(k)
            rem = np.asarray([d for d in ds
                              if crow is None or not crow[7][d]],
                             dtype=np.int64)
            if rem.size == 0:
                continue
            slices.append((k, rem, off))
            donors.append(None)
            T.extend([theta] * rem.size)
            D.extend(rem.tolist())
            off += rem.size
        if not D:
            return
        out = eng._oracle_call("solve_pairs_full", np.stack(T),
                               np.asarray(D, dtype=np.int64), None)
        plan = {"grid_arr": None, "grid_keys": [],
                "pair_slices": slices, "pair_donors": donors,
                "n_skips": 0, "n_new": 0}
        eng._merge_plan_results(plan, None, out)
        self.fallback_cells += off
        eng.log.emit(shard_fallback=True, cells=off,
                     timeout_s=self.timeout_s)
        eng.obs.event("shard.request_timeout", cells=off,
                      timeout_s=self.timeout_s)
        if eng.obs.enabled:
            eng.obs.metrics.counter("shard.fallback_cells").inc(off)

    # -- serving peers ------------------------------------------------------

    def tick(self) -> None:
        """Per-step exchange maintenance: ingest publications, answer
        requests, assert liveness.  Bounded work; called at every step
        start and inside every blocking wait."""
        self.ex.heartbeat()
        self.ex.poll()
        self.serve_requests()

    def serve_requests(self) -> None:
        """Answer peer requests for cells this shard owns: serve
        already-solved cells from the engine cache / store, solve the
        rest on-behalf (dense grid family for full-enumeration cold
        needs, sparse pairs otherwise -- the same routing the
        requester's own single-process build would use), publish."""
        eng = self.eng
        nd = eng.oracle.can.n_delta
        reqs = self.ex.read_requests(nd)
        if not reqs:
            return
        todo_grid: list[tuple[bytes, np.ndarray]] = []
        todo_pairs: list[tuple[bytes, np.ndarray, np.ndarray]] = []
        publish: list[tuple[bytes, np.ndarray]] = []
        for key, theta, mask in reqs:
            if shard_owner(key, self.n_shards) != self.shard:
                continue  # misrouted/stale: not mine to answer
            publish.append((key, mask))
            srow = self.ex.rows.get(key)
            have = srow["mask"].copy() if srow is not None \
                else np.zeros(nd, dtype=bool)
            crow = eng.cache.get_key(key)
            if crow is not None:
                # Mirror locally-solved cells into the store so they
                # can be published (and never re-solved).  Duals stay
                # behind -- see _merge_cells (donor-sterile exchange).
                own = crow[7] & ~have
                if own.any():
                    self.ex.merge_row(key, own, crow[0], crow[1],
                                      crow[2], crow[3], crow[4])
                    have |= own
            new = mask & ~have
            if new.any():
                # A requested cell already IN FLIGHT on this shard's
                # device (a tentative lookahead dispatched it for our
                # own future claim) resolves from the window instead
                # of re-solving: the program's wait-time counting
                # fires once either way, and re-dispatching the cell
                # would be exactly the cross-shard duplicate the
                # ownership hash exists to prevent.
                win = eng._pipe.resolve_vertex(key, nd)
                if win is not None:
                    hit = new & win["mask"]
                    if hit.any():
                        self.ex.merge_row(key, hit, win["V"],
                                          win["conv"], win["grad"],
                                          win["u0"], win["z"])
                        have |= hit
                        new = mask & ~have
            if not new.any():
                continue
            if new.all() and not have.any():
                todo_grid.append((key, theta))
            else:
                # was_new: this solve mints the vertex's first row
                # anywhere on this shard -- the owner counts it toward
                # unique_vertex_solves so the summed figure matches the
                # single-process build's.
                todo_pairs.append((key, theta, np.nonzero(new)[0],
                                   not have.any()))
        n_solved = 0
        if todo_grid:
            arr = np.stack([t for _, t in todo_grid])
            sol = eng._oracle_call("solve_vertices", arr)
            full = np.ones(nd, dtype=bool)
            for i, (key, _t) in enumerate(todo_grid):
                # No duals into the store (donor-sterile exchange --
                # see _merge_cells).
                self.ex.merge_row(
                    key, full, sol.V[i], sol.conv[i], sol.grad[i],
                    sol.u0[i], sol.z[i])
            n_solved += len(todo_grid) * nd
            eng.n_unique_solves += len(todo_grid)
        if todo_pairs:
            T = np.repeat(np.stack([t for _, t, _, _ in todo_pairs]),
                          [ds.size for _, _, ds, _ in todo_pairs],
                          axis=0)
            D = np.concatenate([ds for _, _, ds, _ in todo_pairs])
            V, conv, grad, u0, z, _lam, _s = eng._oracle_call(
                "solve_pairs_full", T, D.astype(np.int64), None)
            off = 0
            for key, _t, ds, was_new in todo_pairs:
                sl = slice(off, off + ds.size)
                m = np.zeros(nd, dtype=bool)
                m[ds] = True
                self.ex.merge_row(
                    key, m, _scatter(V[sl], ds, nd, np.inf),
                    _scatter(conv[sl], ds, nd, False),
                    _scatter(grad[sl], ds, nd, 0.0),
                    _scatter(u0[sl], ds, nd, 0.0),
                    _scatter(z[sl], ds, nd, 0.0))
                off += ds.size
                if was_new:
                    eng.n_unique_solves += 1
            n_solved += off
        if n_solved:
            self.served_cells += n_solved
            if eng.obs.enabled:
                eng.obs.metrics.counter("shard.served_cells").inc(
                    n_solved)
        self.ex.publish(publish)

    # -- finalize / merge ---------------------------------------------------

    def stats_extras(self) -> dict:
        return {"shard": self.shard, "n_shards": self.n_shards,
                "shard_remote_cells": self.remote_cells,
                "shard_served_cells": self.served_cells,
                "shard_fallback_cells": self.fallback_cells}

    def finalize(self, eng, wall: float):
        """End-of-build shard protocol: write this shard's tree/stats,
        post the done marker, keep serving requests until EVERY shard
        is done, then merge the shard trees into the global result
        (every process merges identically, so callers see the same
        PartitionResult on all shards -- the lockstep build's
        contract).  Raises after cfg.shard_timeout_s (scaled by shard
        count) if a peer never finishes."""
        from explicit_hybrid_mpc_tpu.partition.frontier import (
            PartitionResult)

        eng.tree.save(self.ex.tree_path())
        atomic.atomic_write_json(self.ex.done_path(),
                                 {"shard": self.shard, "wall_s": wall})
        state = {"deadline": time.monotonic() + self.timeout_s,
                 "hb": self.ex.peer_heartbeats()}

        def _await(path_of, what: str) -> None:
            # The timeout bounds SILENCE, not total wall: a straggler
            # shard legitimately runs long past its peers on an
            # imbalanced root split, and killing a multi-hour build
            # because one shard finished early would be worse than
            # the crash it guards against.  Any peer heartbeat
            # advance pushes the deadline out; only a peer silent for
            # a full shard_timeout_s is declared dead.
            sleep_s = ShardContext.POLL_S
            while True:
                missing = [s for s in range(self.n_shards)
                           if not os.path.exists(path_of(s))]
                if not missing:
                    return
                self.tick()
                hb = self.ex.peer_heartbeats()
                if hb != state["hb"]:
                    state["hb"] = hb
                    state["deadline"] = time.monotonic() + self.timeout_s
                if time.monotonic() > state["deadline"]:
                    raise RuntimeError(
                        f"sharded build: shard(s) {missing} never "
                        f"posted a {what} under {self.ex.dir} and no "
                        f"peer heartbeat advanced for "
                        f"{self.timeout_s:.0f}s (crashed peer?)")
                time.sleep(sleep_s)
                sleep_s = min(sleep_s * 1.5, 0.1)  # back off while idle

        # Drain: keep answering peer requests until EVERY shard's
        # frontier is done.  Only then are this shard's counters final
        # (on-behalf solves served while draining must land in the
        # stats file -- the summed-point_solves parity bar), so the
        # stats write happens AFTER the drain barrier.
        _await(self.ex.done_path, "done marker")
        my_stats = eng.stats_dict(wall)
        my_stats.update(self.stats_extras())
        atomic.atomic_write_json(self.ex.stats_path(), my_stats,
                                 default=_json_default)
        _await(self.ex.stats_path, "stats file")
        trees = [Tree.load(self.ex.tree_path(s))
                 for s in range(self.n_shards)]
        stats_list = []
        for s in range(self.n_shards):
            with open(self.ex.stats_path(s)) as f:
                stats_list.append(json.load(f))
        merged = merge_shard_trees(
            trees, lambda r: r % self.n_shards)
        stats = merge_shard_stats(stats_list, merged, wall)
        self.ex.close()
        return PartitionResult(merged, merged.roots(), stats)


def _json_default(o):
    """Numpy scalars in a stats dict -> plain JSON numbers."""
    if isinstance(o, (np.integer,)):
        return int(o)
    if isinstance(o, (np.floating,)):
        return float(o)
    if isinstance(o, (np.bool_,)):
        return bool(o)
    raise TypeError(f"not JSON-serializable: {type(o)}")


def _scatter(vals: np.ndarray, ds: np.ndarray, nd: int, fill):
    """(K, ...) per-cell values -> (nd, ...) row with `fill`
    elsewhere."""
    vals = np.asarray(vals)
    out = np.full((nd,) + vals.shape[1:], fill, dtype=vals.dtype)
    out[ds] = vals
    return out


# -- merging ----------------------------------------------------------------


def merge_shard_trees(trees: list[Tree], owner_of) -> Tree:
    """Merge per-shard trees (each holding ALL roots but expanding only
    its owned ones) into one global tree.

    Node order: roots first (ids 0..R-1, identical in every shard tree
    by construction), then each shard's non-root block in shard order
    -- deterministic, so every process merges bit-identically.  The
    merged order differs from the single-process build's breadth-first
    interleaving; compare with ``compare_trees_canonical``."""
    base = trees[0]
    R = len(base.roots())
    for s, t in enumerate(trees[1:], start=1):
        if len(t.roots()) != R or not np.array_equal(
                t.vertices[:R], base.vertices[:R]):
            raise ValueError(f"shard {s} tree roots diverge from "
                             "shard 0's -- not the same build")
    out = Tree(p=base.p, n_u=base.n_u,
               split_hyperplanes=all(t._split_normals_live
                                     for t in trees))
    counts = [len(t) - R for t in trees]
    offs, off = [], 0
    for c in counts:
        offs.append(off)
        off += c
    total = R + off
    out._grow(total)
    out._n = total

    def remap(ids: np.ndarray, s: int) -> np.ndarray:
        ids = np.asarray(ids, dtype=np.int64)
        return np.where(ids == NO_CHILD, NO_CHILD,
                        np.where(ids < R, ids, ids + offs[s]))

    # Root rows come from each root's OWNER (the only shard that
    # expanded it); payloads are set through set_leaf below.
    for r in range(R):
        s = owner_of(r)
        t = trees[s]
        out._vertices[r] = t._vertices[r]
        out._parent[r] = -1
        out._depth[r] = 0
        out._children[r] = remap(t._children[r], s)
        out._split_edge[r] = t._split_edge[r]
        if out._split_normals_live:
            out._normal[r] = t._normal[r]
            out._offset[r] = t._offset[r]
    for s, t in enumerate(trees):
        n_s = len(t)
        if n_s <= R:
            continue
        sl = slice(R + offs[s], R + offs[s] + (n_s - R))
        out._vertices[sl] = t._vertices[R:n_s]
        out._parent[sl] = remap(t._parent[R:n_s], s)
        out._children[sl] = np.stack(
            [remap(t._children[R:n_s, 0], s),
             remap(t._children[R:n_s, 1], s)], axis=1)
        out._depth[sl] = t._depth[R:n_s]
        out._split_edge[sl] = t._split_edge[R:n_s]
        if out._split_normals_live:
            out._normal[sl] = t._normal[R:n_s]
            out._offset[sl] = t._offset[R:n_s]
    out._max_depth = max(int(t.max_depth()) for t in trees)
    # Leaf payloads through the one mutation path (keeps slot/flag/
    # region bookkeeping consistent).
    for s, t in enumerate(trees):
        flags = t._leaf_flags[:len(t)]
        for j in np.nonzero(flags & 1)[0]:
            if j < R and owner_of(int(j)) != s:
                continue  # a root leaf belongs to its owner's tree
            nid = int(j) if j < R else int(j) + offs[s]
            out.set_leaf(nid, t.leaf_data[int(j)])
    # Stage-2 certificate ledger for the warm rebuild: remap node ids,
    # concatenate in shard order (each event lives inside its shard's
    # owned subtree, so there are no duplicates).
    ev = []
    for s, t in enumerate(trees):
        for n, d, v in t.excl_events:
            nid = n if n < R else n + offs[s]
            ev.append((int(nid), int(d), float(v)))
    out.excl_events = ev
    out.provenance = base.provenance
    return out


def merge_shard_stats(stats_list: list[dict], merged: Tree,
                      wall: float) -> dict:
    """Global stats for a sharded build: additive counters sum,
    structural figures come from the merged tree, and the per-shard
    rows ride along for the bench/scaling report."""
    SUM = ("steps", "oracle_solves", "point_solves", "simplex_solves",
           "rescue_solves", "inherited_skips", "uncertified",
           "semi_explicit", "frontier_left", "unique_vertex_solves",
           "masked_point_skips", "prefetched_steps", "pipelined_steps",
           "dedup_saved", "spec_hits", "spec_waste", "device_failures",
           "quarantined_cells", "shard_remote_cells",
           "shard_served_cells", "shard_fallback_cells")
    stats: dict = {k: sum(int(st.get(k) or 0) for st in stats_list)
                   for k in SUM}
    # High-water marks are per-cache figures, not additive work: the
    # global reading that keeps the key's single-cache meaning is the
    # worst shard's peak.
    stats["cache_peak_vertices"] = max(
        (int(st.get("cache_peak_vertices") or 0)
         for st in stats_list), default=0)
    stats["regions"] = merged.n_regions()
    stats["tree_nodes"] = len(merged)
    stats["max_depth"] = merged.max_depth()
    stats["truncated"] = any(st.get("truncated") for st in stats_list)
    stats["device_degraded"] = any(st.get("device_degraded")
                                   for st in stats_list)
    stats["wall_s"] = wall
    stats["regions_per_s"] = merged.n_regions() / max(wall, 1e-9)
    stats["sharded"] = True
    stats["n_shards"] = len(stats_list)
    stats["per_shard"] = [
        {k: st.get(k) for k in
         ("shard", "regions", "steps", "wall_s", "regions_per_s",
          "point_solves", "simplex_solves", "shard_remote_cells",
          "shard_served_cells", "shard_fallback_cells",
          "quarantined_cells", "device_degraded",
          "cp_fill_frac", "cp_plan_frac", "cp_wait_frac",
          "cp_certify_frac", "cp_other_frac", "cp_overlap_s")}
        for st in stats_list]
    return stats


# -- canonical comparison ---------------------------------------------------


def compare_trees_canonical(a: Tree, b: Tree,
                            payloads: bool = False) -> list[str]:
    """Node-for-node divergence list ([] = identical) under the
    canonical node identity: a node IS its exact vertex-matrix bytes
    (bisection arithmetic is exact, so equal geometry implies equal
    bytes).  Insertion-order independent -- the sharded merge orders
    nodes per-subtree while the single-process build interleaves
    breadth-first.  Compares: node sets (vertices bitwise), split
    structure, leaf sets, certification statuses and commutation
    choices, region counts, depths; leaf payload floats only under
    ``payloads=True`` (the sharded parity bar excludes them -- a
    remote cell solved in the owner's batch composition carries the
    documented last-ulp pow-2-bucket caveat)."""
    diffs: list[str] = []
    if len(a) != len(b):
        return [f"node count {len(a)} != {len(b)}"]

    def index(t: Tree) -> dict[bytes, int]:
        out: dict[bytes, int] = {}
        for i in range(len(t)):
            k = t.vertices[i].tobytes()
            if k in out:
                raise ValueError("duplicate vertex matrix in tree -- "
                                 "canonical comparison undefined")
            out[k] = i
        return out

    ia, ib = index(a), index(b)
    only_a = set(ia) - set(ib)
    if only_a:
        return [f"{len(only_a)} node(s) have no geometric counterpart"]
    fa, fb = a._leaf_flags, b._leaf_flags
    for k, na in ia.items():
        nb = ib[k]
        leaf_a, leaf_b = a.is_leaf(na), b.is_leaf(nb)
        if leaf_a != leaf_b:
            diffs.append(f"node depth {int(a.depth[na])}: "
                         f"leaf({leaf_a}) vs leaf({leaf_b})")
            continue
        if int(fa[na]) != int(fb[nb]):
            diffs.append(f"leaf flags {int(fa[na])} != {int(fb[nb])} "
                         f"at depth {int(a.depth[na])}")
            continue
        da, db = a.leaf_data[na], b.leaf_data[nb]
        if da is None:
            continue
        if da.delta_idx != db.delta_idx:
            diffs.append(f"leaf commutation {da.delta_idx} != "
                         f"{db.delta_idx} at depth {int(a.depth[na])}")
        elif payloads and not (
                np.array_equal(da.vertex_inputs, db.vertex_inputs)
                and np.array_equal(da.vertex_costs, db.vertex_costs)):
            diffs.append("leaf payload floats differ at depth "
                         f"{int(a.depth[na])}")
        if len(diffs) >= 10:
            diffs.append("... (further diffs suppressed)")
            return diffs
    if a.n_regions() != b.n_regions():
        diffs.append(f"regions {a.n_regions()} != {b.n_regions()}")
    if a.max_depth() != b.max_depth():
        diffs.append(f"max depth {a.max_depth()} != {b.max_depth()}")
    return diffs
