"""Breadth-first frontier partition engine.

This is the TPU-native inversion of the reference's distributed runtime
(SURVEY.md sections 3-4, [M-high]): where the reference runs an MPI task
farm (scheduler rank + workers recursing depth-first with one serial Gurobi
solve at a time), here the open leaves form a HOST-SIDE FRONTIER and each
step issues ONE batched device program covering every unsolved vertex of
every frontier simplex (BASELINE.json north-star: "the simplex-tree
subdivision loop becomes a breadth-first frontier").

Per step:
  1. pop up to cfg.batch_simplices open simplices;
  2. dedupe their vertices against the solve cache (bisection shares
     vertices between siblings/neighbours -- caching preserves the
     reference's work complexity);
  3. one vmapped oracle call for all new vertices x all commutations;
  4. host-side certificates (cheap numpy, certify.py); commutations with no
     converged vertex trigger a second batched device call (exact simplex
     minima / infeasibility exclusion);
  5. converged leaves stream into the Tree; bisected children re-enter the
     frontier.

Termination: frontier empty (all leaves certified / infeasible / depth-
capped).  The frontier + cache + tree snapshot to disk every
cfg.checkpoint_every steps and any run can resume (SURVEY.md section 6.4).

Steps are scheduled by the bounded asynchronous build pipeline
(partition/pipeline.py): up to cfg.pipeline_depth future batches are
planned and dispatched while earlier steps wait/certify/commit, with
cross-batch vertex dedup and speculative child dispatch -- node-for-node
identical trees at any depth, enforced by an authoritative commit-time
re-plan.
"""

from __future__ import annotations

import collections
import itertools
import os
import pickle
import threading
import time
import warnings

import numpy as np

from explicit_hybrid_mpc_tpu import faults as faults_lib
from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.utils import atomic
from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition import certify, geometry
from explicit_hybrid_mpc_tpu.partition.pipeline import BuildPipeline
from explicit_hybrid_mpc_tpu.partition.tree import LeafData, Tree
from explicit_hybrid_mpc_tpu.utils.logging import RunLog


def _donor_warm(drow, ds: np.ndarray):
    """Warm-start slices (z, lam, s, has) for pair cells `ds` from donor
    row `drow` -- shared by the real planner and the speculative child
    planner so their bit-for-bit warm data can never drift.

    Centrality floor (Mehrotra-style shifted warm start): a converged
    donor sits ON the boundary (active s_i, inactive lam_i ~ 1e-9), and
    an IPM started there crawls -- the merit gate cannot see centrality,
    only residuals.  Flooring slacks/duals at 1e-2 re-centers the start
    while keeping the donor's primal point; measured: restores warm
    convergence rates to >= cold everywhere (two-phase continuations are
    NOT floored -- they must resume the exact iterate).  Only converged
    donor cells with live duals are offered (rescued cells carry NaN
    donor slots -- the rescue program returns no duals; diverged
    iterates are junk the gate would reject anyway)."""
    return (drow[4][ds],
            np.maximum(drow[8][ds], 1e-2),
            np.maximum(drow[9][ds], 1e-2),
            np.asarray(drow[1][ds], dtype=bool)
            & np.isfinite(drow[8][ds, 0]))


class _PlanBuilder:
    """Accumulates a solve plan's dense-grid and sparse-pair cells and
    stacks them ONCE into the plan dict -- shared by the authoritative
    planner (_plan_missing) and the speculative child planner
    (_plan_spec_children) so the two can never drift: the pipeline's
    serve-time route match assumes both assemble cells, warm slices,
    and batch layout bit-identically.  (Stacking once also matters for
    host cost: dispatch re-stacking per-element python lists was the
    largest host cost of pure-splitting phases, ~6k np.asarray calls
    per step via np.stack.)"""

    def __init__(self, can, use_warm: bool):
        self._can = can
        self._use_warm = use_warm
        self.grid_keys: list[bytes] = []
        self._grid_pts: list[np.ndarray] = []
        self._pair_verts: list[np.ndarray] = []
        self._pair_ds: list[np.ndarray] = []
        # z / s / lam / has, the wire order of Oracle.dispatch_pairs'
        # warm tuple.
        self._warm: tuple[list, list, list, list] = ([], [], [], [])
        # (key, delta indices, offset into the pair batch)
        self.pair_slices: list[tuple[bytes, np.ndarray, int]] = []
        # Donor ROW OBJECTS aligned with pair_slices: the pipeline's
        # route match compares the authoritative donor against the one
        # an in-flight program was dispatched with (a widened cache row
        # replaces the tuple, so identity is an exact staleness check).
        self.pair_donors: list[tuple | None] = []
        self._n_pair = 0

    @property
    def empty(self) -> bool:
        return not self._grid_pts and not self.pair_slices

    @property
    def n_grid(self) -> int:
        return len(self._grid_pts)

    def add_grid(self, k: bytes, v: np.ndarray) -> None:
        self._grid_pts.append(v)
        self.grid_keys.append(k)

    def add_pair(self, k: bytes, ds: np.ndarray, v: np.ndarray,
                 drow) -> None:
        self.pair_slices.append((k, ds, self._n_pair))
        self.pair_donors.append(drow)
        self._pair_verts.append(v)
        self._pair_ds.append(ds)
        if self._use_warm:
            if drow is not None:
                zw, lw, sw, hw = _donor_warm(drow, ds)
            else:
                zw = np.zeros((ds.size, self._can.nz))
                lw = np.zeros((ds.size, self._can.nc))
                sw = np.zeros((ds.size, self._can.nc))
                hw = np.zeros(ds.size, dtype=bool)
            self._warm[0].append(zw)
            self._warm[1].append(sw)
            self._warm[2].append(lw)
            self._warm[3].append(hw)
        self._n_pair += ds.size

    def finish(self, n_skips: int, n_new: int) -> dict:
        grid_arr = np.stack(self._grid_pts) if self._grid_pts else None
        pair_warm = None
        if self.pair_slices:
            counts = np.asarray([d.size for d in self._pair_ds])
            pair_t = np.repeat(np.stack(self._pair_verts), counts,
                               axis=0)
            pair_d = np.concatenate(self._pair_ds).astype(np.int64)
            if self._use_warm:
                pair_warm = tuple(np.concatenate(w) for w in self._warm)
        else:
            pair_t = pair_d = None
        return {"grid_arr": grid_arr, "grid_keys": self.grid_keys,
                "pair_t": pair_t, "pair_d": pair_d,
                "pair_warm": pair_warm,
                "pair_slices": self.pair_slices,
                "pair_donors": self.pair_donors,
                "n_skips": n_skips, "n_new": n_new}


class VertexCache:
    """vertex -> oracle solution row, keyed by rounded coordinates.

    Row layout: (V, conv, grad, u0, z, Vstar, dstar, solved-delta mask,
    lam, s); lam/s are the per-commutation duals/slacks warm-capable
    oracles return (the tree warm-start donor data) and None otherwise.

    Memory accounting: one row holds the full (nd, ...) per-commutation
    block -- dominated by z at nd x nz and lam/s at nd x nc float64 --
    so an unbounded cache at 10^5 vertices is GBs.  The engine therefore
    EVICTS rows once no open simplex references the vertex (see
    FrontierEngine._release); `peak_vertices`/`peak_bytes` record the
    high-water mark for the build-stats memory figure."""

    def __init__(self):
        self._d: dict[bytes, tuple] = {}
        self._row_bytes = 0
        self.peak_vertices = 0
        self.peak_bytes = 0

    def __contains__(self, v: np.ndarray) -> bool:
        return geometry.vertex_key(v) in self._d

    def get(self, v: np.ndarray) -> tuple:
        return self._d[geometry.vertex_key(v)]

    def get_key(self, k: bytes) -> tuple | None:
        return self._d.get(k)

    def put(self, v: np.ndarray, row: tuple) -> None:
        self.put_key(geometry.vertex_key(v), row)

    def put_key(self, k: bytes, row: tuple) -> None:
        if not self._row_bytes:
            self._row_bytes = sum(
                a.nbytes if isinstance(a, np.ndarray) else 8 for a in row)
        self._d[k] = row
        if len(self._d) > self.peak_vertices:
            self.peak_vertices = len(self._d)
            self.peak_bytes = self.peak_vertices * self._row_bytes

    def evict_key(self, key: bytes) -> None:
        self._d.pop(key, None)

    def __len__(self) -> int:
        return len(self._d)


class PartitionResult:
    def __init__(self, tree: Tree, roots: list[int], stats: dict):
        self.tree = tree
        self.roots = roots
        self.stats = stats


class FrontierEngine:
    def __init__(self, problem, oracle: Oracle, cfg: PartitionConfig,
                 log: RunLog | None = None,
                 obs: "obs_lib.Obs | None" = None):
        self.problem = problem
        self.oracle = oracle
        self.cfg = cfg
        self.log = log or RunLog(cfg.log_path, echo=False)
        # Unified tracing/metrics (obs subsystem): caller-provided handle
        # wins; otherwise built from cfg.obs / cfg.obs_path (NOOP when
        # off).  The oracle's metrics (solve-time histograms, IPM
        # iteration counters) are routed into the SAME registry unless
        # the caller already wired the oracle to its own handle.
        self.obs = obs if obs is not None else obs_lib.from_config(cfg)
        self._owns_obs = obs is None
        if (self.obs.enabled and getattr(oracle, "obs", None) is not None
                and not oracle.obs.enabled):
            oracle.obs = self.obs
        self._obs_t0 = time.perf_counter()
        self._prev_solves = oracle.n_solves
        self._obs_regions0 = 0
        self._init_diagnostics()
        p = problem.n_theta
        self.tree = Tree(p=p, n_u=problem.n_u,
                         split_hyperplanes=getattr(
                             cfg, "split_hyperplanes", True))
        # Build-provenance stamp (partition/provenance.py): rides the
        # tree through every pickle/checkpoint/export so loaders and
        # the warm-rebuild engine can detect problem/config drift.
        from explicit_hybrid_mpc_tpu.partition import provenance as prov

        self.tree.provenance = prov.build_stamp(problem, cfg)
        self.roots = [self.tree.add_root(V) for V in
                      geometry.box_triangulation(
                          problem.theta_lb, problem.theta_ub,
                          getattr(problem, "root_splits", None))]
        # Pod-scale sharded frontier (partition/shard.py): when active,
        # THIS process's frontier holds only its round-robin share of
        # the roots; cross-shard vertex dedup goes through the
        # asynchronous exchange (requests posted at plan time, results
        # collected before certify).  None on single-process runs --
        # every hook below is a None-test.
        self._shard = None
        if getattr(cfg, "shard_frontier", False):
            from explicit_hybrid_mpc_tpu.partition.shard import (
                ShardContext)

            self._shard = ShardContext.from_config(self, cfg)
        self.frontier: collections.deque[int] = collections.deque(
            self.roots if self._shard is None
            else self._shard.owned_roots(self.roots))
        self.cache = VertexCache()
        self.steps = 0
        self.n_uncertified = 0
        self.n_semi_explicit = 0
        self.n_unique_solves = 0
        self.n_device_failures = 0
        self.n_point_skips = 0
        # Interned all-True active-delta mask (shared by every full cache
        # row; never mutated -- partial masks are fresh copies).
        self._full_mask = np.ones(oracle.can.n_delta, dtype=bool)
        self._fb_oracle: Oracle | None = None
        self._oracle_s = 0.0
        # Serializes every oracle query/wait against the async-certify
        # background waiter (partition/pipeline.py _resolve): oracle
        # counters and the failure/degrade machinery are not
        # thread-safe.  Reentrant -- _resolve holds it across
        # _wait_or_fallback.  Uncontended cost is nanoseconds, so the
        # async_certify=False path is unaffected.
        self._oracle_lock = threading.RLock()
        # vertex key -> number of OPEN simplices (frontier + in-flight)
        # referencing it.  Every future simplex is a child of an open one,
        # so its vertices are open-simplex vertices or new bisection
        # midpoints: a vertex with refcount 0 can never be queried again
        # and its cache row is evicted (bounded-memory build; a rare
        # hanging-node midpoint that resurrects an evicted vertex just
        # re-solves -- the cache is a cache, correctness is unaffected).
        self._refcount: collections.Counter[bytes] = collections.Counter()
        self._node_keys = {}
        # Rolling device-busy fraction of recent steps (EMA of
        # device_frac): the pipeline's speculation gate reads it --
        # speculative batches are idle-device fillers and are skipped
        # while the device is already the bottleneck.
        self.device_frac_ema = 0.0
        for n in self.frontier:
            self._retain(n)
        # node -> {delta: lower bound on min_R V_delta} inherited from
        # ancestors.  +inf = Farkas-certified infeasible on an ancestor
        # simplex -- exact for every descendant (child subset of ancestor),
        # so the (node, delta) stage-2 solve is skipped forever.  A finite
        # value is the ancestor's certified simplex lower bound: a valid (but
        # possibly loose) lower bound on any child; it is used to attempt
        # certification for free, and re-solved on the child's own simplex
        # only when the loose-bound certificate fails (round B below).
        # CERTIFIED decisions then match an inheritance-free build; the
        # builds are NOT tree-identical, because an inherited +inf is
        # strictly more accurate than re-running the child's phase-1 (a
        # stalled child solve demotes an exactly-known infeasible simplex
        # to 'split'), so the uninherited build may subdivide infeasible
        # space slightly further (tests/test_partition.py asserts the
        # guaranteed direction + identical certified volume).
        # BENCH_r02 measured 82% of all solves in stage-2 joint QPs,
        # mostly re-proving the same delta' infeasible down entire
        # subtrees; this inheritance removes that re-proving.
        self._inherit: dict[int, dict[int, float]] = {}
        self.n_inherited_skips = 0
        # Bounded asynchronous build pipeline (partition/pipeline.py):
        # depth-N in-flight batch scheduling, cross-batch vertex dedup,
        # speculative child dispatch.  cfg.prefetch_solves=False is the
        # legacy kill switch (depth 0 = strictly synchronous).
        self._pipe = BuildPipeline(self)

    # -- diagnostics: flight recorder + in-stream health monitor -----------

    def _init_diagnostics(self) -> None:
        """Build the flight recorder (cfg.obs_recorder) and the
        in-stream health monitor (cfg.health_rules + obs enabled) --
        shared by __init__ and resume().  Both are None by default, and
        every hook below is guarded on that None, so the obs='off' fast
        path gains no per-step work."""
        # Bounded-recovery policy + fault-injection hookup
        # (faults/policy.py, faults/injector.py; docs/robustness.md).
        # install_from_config is a no-op returning None unless
        # cfg.fault_plan / EHM_FAULT_PLAN name a plan (or a test's
        # activate() block already installed one).
        self._policy = faults_lib.RetryPolicy.from_config(self.cfg)
        self._injector = faults_lib.install_from_config(self.cfg,
                                                        obs=self.obs)
        # Poison-cell quarantine ledger + permanent-CPU-degrade flag
        # (session-local, like n_device_failures).
        self.n_quarantined_cells = 0
        self._degraded = False
        # Per-step critical-path ledger (fleet telemetry, ISSUE 13):
        # cumulative wall seconds per step segment -- pipeline fill,
        # authoritative host planning, device wait/dispatch, host
        # certify+commit, residual -- plus checkpoint wall (outside
        # the step loop).  Per-step figures ride the build.step event
        # (cp_*_s fields, fractions of step_s summing to 1 by
        # construction); cumulative fractions ride the build.cp_*_frac
        # gauges, stats_dict, and the bench row.
        self._cp = {"fill": 0.0, "plan": 0.0, "wait": 0.0,
                    "certify": 0.0, "other": 0.0, "checkpoint": 0.0}
        self._cp_step_s = 0.0  # cumulative step wall (fraction denom)
        # Wall time the previous step ended (None before the first):
        # the in-build stall probe measures the gap at the next step's
        # start, so a wedged solve that eventually recovers (injected
        # hang, device wedge) still registers as a stall with the
        # health monitor -- the auto-profile trigger.  Updated after
        # checkpoints too (a slow checkpoint is not a stall).
        self._last_step_end: float | None = None
        self.recorder = None
        # recorder_dir implies obs_recorder at EVERY entry point (the
        # CLI applies the same rule): naming a bundle directory while
        # silently recording nothing would be the worst reading.
        if getattr(self.cfg, "obs_recorder", False) \
                or getattr(self.cfg, "recorder_dir", None):
            from explicit_hybrid_mpc_tpu.obs.recorder import FlightRecorder

            out_dir = (getattr(self.cfg, "recorder_dir", None)
                       or os.path.join("artifacts", "repro"))
            self.recorder = FlightRecorder(out_dir, obs=self.obs)
            # The sink tap feeds the recorder's ring so every bundle
            # carries the obs records leading up to the anomaly.
            if (self.obs.enabled and self.obs.sink is not None
                    and self.obs.sink.tap is None):
                self.obs.sink.tap = self.recorder.note
            if getattr(self.oracle, "recorder", None) is None:
                self.oracle.recorder = self.recorder
        self._health = None
        rules = getattr(self.cfg, "health_rules", ())
        if self.obs.enabled and rules:
            from explicit_hybrid_mpc_tpu.obs.health import (
                HealthMonitor, rules_from_pairs)

            self._health = HealthMonitor(rules_from_pairs(rules),
                                         sink=self.obs.sink)
        # Health-triggered bounded device profiling (cfg.auto_profile;
        # obs/profiling.py): armed here, triggered by the first
        # critical health verdict (_poll_auto_profile) or an external
        # driver (trigger_auto_profile -- long_build's halt path).
        # Mutually exclusive with a manual cfg.profile_path trace: jax
        # allows one active trace, and the manual capture IS the
        # evidence the auto-capture exists to produce.
        self._auto_prof = None
        if getattr(self.cfg, "auto_profile", False) \
                and not self.cfg.profile_path:
            from explicit_hybrid_mpc_tpu.obs.profiling import AutoProfiler

            out_dir = (getattr(self.cfg, "recorder_dir", None)
                       or (os.path.dirname(
                           getattr(self.cfg, "obs_path", None) or "")
                           or "artifacts"))
            self._auto_prof = AutoProfiler(
                out_dir, steps=self.cfg.profile_steps)
        self._auto_prof_seen_events = 0
        # Runtime recompile sentinel (cfg.recompile_guard): armed after
        # the first _GUARD_WARMUP_FULL_STEPS full-size batches, checked
        # on every later full-size step -- see _guard_step.
        self._rc_guard = None
        self._rc_steady_steps = 0
        mode = getattr(self.cfg, "recompile_guard", "off")
        if mode and mode != "off":
            from explicit_hybrid_mpc_tpu.analysis.recompile_guard import (
                RecompileGuard)

            self._rc_guard = RecompileGuard(oracle=self.oracle,
                                            obs=self.obs, action=mode,
                                            label="frontier_steady_state")

    # Full-size steps before the recompile sentinel arms.  The first
    # few full batches legitimately compile the steady-state program
    # set (grid bucket, pair buckets, the stage-2 simplex buckets whose
    # row counts still vary pow-2-wise early on); by this many full
    # waves the ledger has plateaued on every measured config, so
    # growth PAST it is the recompile bug the guard exists to catch.
    _GUARD_WARMUP_FULL_STEPS = 8

    def _guard_step(self, batch: int) -> None:
        """Per-step recompile sentinel hook (no-op unless
        cfg.recompile_guard is on and the step ran a FULL batch --
        ramp-up/drain-down batches mint new pow-2 buckets by design).
        Under 'warn' the violation event also feeds the in-build
        HealthMonitor so the campaign verdict reflects it; under
        'raise' RecompileError propagates and aborts the build."""
        if batch < self.cfg.batch_simplices:
            # Partial waves are exempt BY DESIGN -- but a full-size
            # step's check measures growth since the last arm(), so an
            # armed guard must re-arm here or a backlog dip's
            # legitimately-minted small bucket would be attributed to
            # the NEXT full-size step (a false positive that would
            # abort a healthy build under 'raise').  A full step's own
            # mints are still caught: its check runs at the end of the
            # same step, before any partial-step re-arm.
            if self._rc_steady_steps >= self._GUARD_WARMUP_FULL_STEPS:
                self._rc_guard.arm()
            return
        self._rc_steady_steps += 1
        if self._rc_steady_steps < self._GUARD_WARMUP_FULL_STEPS:
            return
        if self._rc_steady_steps == self._GUARD_WARMUP_FULL_STEPS:
            self._rc_guard.arm()
            return
        ev = self._rc_guard.check(step=self.steps)
        if ev is not None and self._health is not None:
            self._health.feed(ev)

    def _health_device_failure(self, e: BaseException) -> None:
        """Record a device failure where every health consumer can see
        it.  The RunLog record goes to cfg.log_path's SEPARATE stream,
        which neither the in-build monitor nor an external obs_watch
        tail reads -- without this hook the max_device_failures rule
        silently never fires, the exact failure mode the rule
        validation exists to prevent.  Emits a build.device_failure
        event into the obs stream (obs_watch's input) AND feeds the
        in-build monitor directly (obs may be off)."""
        rec = self.obs.event("build.device_failure",
                             error=repr(e)[:200])
        if self._health is not None:
            self._health.feed(rec or {"kind": "event",
                                      "name": "build.device_failure"})

    def _capture_uncertified(self, node: int, sd, res) -> None:
        """Repro bundle for a depth-capped UNcertified leaf: the cell
        geometry plus every vertex fact the certificate read
        (certify.cell_snapshot) and the canonical problem, so
        scripts/replay_solve.py can re-solve the vertices and re-run
        stage 1 standalone."""
        from explicit_hybrid_mpc_tpu.obs import recorder as rec_lib

        self.recorder.dump(
            "uncertified_leaf",
            {**rec_lib.canonical_arrays(self.oracle.can),
             **certify.cell_snapshot(sd)},
            {"kind": "cell",
             "oracle": rec_lib.oracle_meta(self.oracle),
             "backend": self.oracle.backend,
             "node": int(node), "depth": int(self.tree.depth[node]),
             "gap": float(res.gap),
             "eps_a": self.cfg.eps_a, "eps_r": self.cfg.eps_r})

    # Device-failure bundles keep the whole failed batch (the INPUT is
    # the repro), but bounded: beyond this many rows the bundle is a
    # disk hazard, not a repro.
    _MAX_FAILURE_ROWS = 4096

    def _capture_device_failure(self, kind: str, args: tuple, out,
                                err: str) -> None:
        """Bundle a device-failed batch AFTER its CPU-fallback re-solve
        (so the observed masks ride along): the exact batch that broke
        the device, replayable on any host."""
        from explicit_hybrid_mpc_tpu.obs import recorder as rec_lib

        cap = self._MAX_FAILURE_ROWS
        arrays = dict(rec_lib.canonical_arrays(self.oracle.can))
        meta = {"oracle": rec_lib.oracle_meta(self.oracle),
                "backend": self.oracle.backend, "error": err[:500]}
        if kind == "vertices":
            sol = out
            arrays.update(thetas=np.asarray(args[0])[:cap],
                          obs_conv=np.asarray(sol.conv, dtype=bool)[:cap],
                          obs_feas=np.asarray(sol.feas, dtype=bool)[:cap],
                          obs_V=np.asarray(sol.V, dtype=np.float64)[:cap])
            meta["kind"] = "vertices"
        else:  # pairs / pairs_full
            arrays.update(thetas=np.asarray(args[0])[:cap],
                          delta_idx=np.asarray(args[1],
                                               dtype=np.int64)[:cap],
                          obs_V=np.asarray(out[0], dtype=np.float64)[:cap],
                          obs_conv=np.asarray(out[1], dtype=bool)[:cap])
            if kind == "pairs_full" and args[2] is not None:
                zw, sw, lw, hw = args[2]
                arrays.update(warm_z=np.asarray(zw)[:cap],
                              warm_s=np.asarray(sw)[:cap],
                              warm_lam=np.asarray(lw)[:cap],
                              warm_has=np.asarray(hw, dtype=bool)[:cap])
            meta["kind"] = "pairs"
        self.recorder.dump("device_failure", arrays, meta)

    def _capture_oracle_failure(self, method: str, args: tuple, out,
                                err: str) -> None:
        """Device-failure bundle for the synchronous stage-2 calls
        (_oracle_call): simplex-batch inputs + the fallback's observed
        outputs."""
        from explicit_hybrid_mpc_tpu.obs import recorder as rec_lib

        cap = self._MAX_FAILURE_ROWS
        arrays = dict(rec_lib.canonical_arrays(self.oracle.can))
        arrays.update(bary_Ms=np.asarray(args[0])[:cap],
                      delta_idx=np.asarray(args[1], dtype=np.int64)[:cap])
        meta = {"oracle": rec_lib.oracle_meta(self.oracle),
                "backend": self.oracle.backend, "error": err[:500]}
        if method == "solve_simplex_min":
            arrays.update(obs_vmin=np.asarray(out[0],
                                              dtype=np.float64)[:cap],
                          obs_feas_sw=np.asarray(out[1],
                                                 dtype=bool)[:cap])
            meta["kind"] = "simplex"
        elif method == "simplex_feasibility":
            arrays.update(obs_t=np.asarray(out[0],
                                           dtype=np.float64)[:cap],
                          obs_feas_sw=np.asarray(out[1],
                                                 dtype=bool)[:cap],
                          obs_infeas=np.asarray(out[2],
                                                dtype=bool)[:cap])
            meta["kind"] = "simplex_feas"
        else:
            return
        self.recorder.dump("device_failure", arrays, meta)

    # -- device-failure fallback (SURVEY.md section 6.3) -------------------

    def _fallback_oracle(self) -> Oracle:
        """Lazily built CPU twin of the main oracle: same kernel, same
        precision schedule, CPU devices -- results are bit-compatible, so
        retrying a failed device batch on it preserves build parity.
        Built by the oracle's own cpu_twin so subclassed kernels
        (SOCOracle) fall back to THEMSELVES, not the plain QP kernel."""
        if self._fb_oracle is None:
            self._fb_oracle = self.oracle.cpu_twin(self.problem)
            # Injection-site role tag: "dead device" fault plans match
            # the primary's dispatches, not the recovery twin's.
            self._fb_oracle._fault_role = "fallback"
        return self._fb_oracle

    def _oracle_call(self, method: str, *args):
        """Issue an oracle query under the bounded-recovery policy
        (faults/policy.py): on a device failure (dead TPU tunnel, OOM,
        interconnect error) or a solve timeout, retry the SAME batch on
        the host-CPU fallback oracle with exponential backoff instead
        of aborting the whole build (round-1 postmortem: one backend
        outage voided the benchmark capture); if every attempt fails
        the batch's cells are QUARANTINED (_quarantine) and the build
        continues.  Once the device-failure cap trips, the engine is
        DEGRADED and queries route straight to the CPU twin -- a dead
        accelerator costs the fail-then-fallback tax once, not
        per-batch.  Events are logged; solve counts fold into the main
        oracle's statistics."""
        t0 = time.perf_counter()
        try:
            self._oracle_lock.acquire()
            if not self._degraded:
                try:
                    # The span doubles as a device-trace annotation
                    # under obs='full', anchoring each synchronous
                    # oracle query on the host track of a jax.profiler
                    # capture.  The fault hook sits INSIDE the timed
                    # callable so an injected hang is seen by the
                    # watchdog exactly like a wedged real solve.
                    with self.obs.span("oracle." + method):
                        def _go():
                            faults_lib.fire("oracle.call", label=method)
                            return getattr(self.oracle, method)(*args)

                        return faults_lib.call_with_timeout(
                            _go, self._policy.solve_timeout_s)
                except (RuntimeError, OSError) as e:
                    # XlaRuntimeError (dead tunnel, device OOM,
                    # interconnect faults) subclasses RuntimeError;
                    # socket/tunnel drops raise OSError; SolveTimeout is
                    # a RuntimeError by design.  Deterministic
                    # programming errors (TypeError/ValueError/shape
                    # bugs) propagate instead of being retried on the
                    # fallback, where they would resurface as a second
                    # failure mislabeled 'device_failure' (round-2
                    # advisor item).
                    self._note_device_failure(method, e)
                    err: BaseException | None = e
            else:
                err = None
            return self._recover(method, args, err)
        finally:
            self._oracle_lock.release()
            self._oracle_s += time.perf_counter() - t0

    def _keys(self, node: int) -> list[bytes]:
        """Cache keys of `node`'s vertices, memoized for the node's open
        lifetime (each open node's keys are read by _retain, planning,
        the batch gather, and _release -- recomputing the rounding+
        tobytes per use dominated host time at cluster scale)."""
        ks = self._node_keys.get(node)
        if ks is None:
            ks = geometry.vertex_keys(self.tree.vertices[node])
            self._node_keys[node] = ks
        return ks

    def _retain(self, node: int) -> None:
        for k in self._keys(node):
            self._refcount[k] += 1

    def _release(self, node: int) -> None:
        keys = self._keys(node)
        verts = self.tree.vertices[node] if self._shard is not None \
            else None
        for vi, k in enumerate(keys):
            c = self._refcount[k] - 1
            if c <= 0:
                del self._refcount[k]
                if verts is not None:
                    # Sharded frontier: stash owned boundary rows into
                    # the exchange store before they vanish (a late
                    # peer request must never re-solve an owned cell;
                    # partition/shard.py note_evict).
                    row = self.cache.get_key(k)
                    if row is not None:
                        self._shard.note_evict(k, verts[vi], row)
                self.cache.evict_key(k)
            else:
                self._refcount[k] = c
        self._node_keys.pop(node, None)

    # -- vertex solves -----------------------------------------------------

    def _use_mask(self) -> bool:
        """Whether planning may skip ancestor-Farkas-excluded point QPs
        (cfg.mask_point_solves; mesh oracles keep the dense grid)."""
        return (self.oracle.can.n_delta > 1 and self.oracle.mesh is None
                and getattr(self.cfg, "mask_point_solves", True)
                and getattr(self.cfg, "inherit_bounds", True))

    def _use_warm(self) -> bool:
        """Whether planning attaches tree warm-start donors
        (cfg.warm_start_tree on a warm-capable oracle)."""
        return (getattr(self.oracle, "warm_start", False)
                and getattr(self.cfg, "warm_start_tree", True))

    def _active_delta_mask(self, n: int, use_mask: bool) -> np.ndarray:
        """Active-commutation mask of node n: all minus the inherited
        Farkas +inf exclusions.  Returns self._full_mask ITSELF when
        nothing is excluded -- planning relies on the identity to merge
        per-key needs cheaply."""
        full = self._full_mask
        if use_mask and n in self._inherit:
            excl = [d for d, b in self._inherit[n].items()
                    if b == np.inf]
            if excl:
                act = full.copy()
                act[excl] = False
                return act
        return full

    def _pick_donor(self, keys) -> tuple | None:
        """First cached vertex among `keys` that carries duals.  The ONE
        donor pick shared by the authoritative planner and the
        speculative child planner: if the two ever diverged, every
        speculative program would carry a donor the claiming plan never
        picks and pipeline._match_cell would route-miss all of it."""
        for k in keys:
            r = self.cache.get_key(k)
            if r is not None and len(r) > 8 and r[8] is not None:
                return r
        return None

    def _plan_missing(self, nodes: list[int],
                      window: "BuildPipeline | None" = None) -> dict | None:
        """Decide every (vertex, commutation) cell the certificates of
        `nodes` can read but the cache does not hold.

        Masked path (cfg.mask_point_solves): a commutation Farkas-excluded
        on an ancestor simplex is infeasible at every point of the child
        (child subset of ancestor), so its point QP at any child vertex is
        known-infeasible without solving.  Each node contributes an
        active-delta set (all minus its inherited +inf exclusions); a
        vertex shared by several nodes needs the UNION.  Vertices needing
        every commutation go through the dense solve_vertices grid (warm
        buckets, mesh-shardable); partially-needed vertices go through the
        sparse solve_pairs path, and cached rows widen in place when a
        later node needs commutations an earlier requester excluded.
        Fabricated cells (V=+inf, conv=False) encode exactly what the
        skipped solve would have returned for an infeasible QP, so the
        build is tree-identical to the unmasked one.

        Tree warm-starts (cfg.warm_start_tree, warm-capable oracles):
        a missing vertex of a simplex is (almost always) the bisection
        midpoint of an edge whose endpoints the cache still holds --
        their converged (z, lam, s) rows are natural IPM starts for the
        midpoint's QPs.  The plan picks the first cached vertex of the
        requesting node as DONOR and routes the solve through the warm-
        capable pair path (per-delta donor slices, `has` set only where
        the donor cell converged).  Correctness never depends on the
        donor: the kernel's merit gate falls back to the cold start.

        Returns a plan dict, or None if the cache already holds
        everything.  Planning only reads state that is stable between
        frontier steps (cache rows, inherited exclusions of OPEN
        nodes), which is what makes lookahead planning during step k
        valid for steps k+1..k+depth.

        window: the BuildPipeline for TENTATIVE fill-time plans --
        (vertex, delta) cells an in-flight program already covers with
        a route-compatible solve are skipped (cross-batch dedup; real
        coverage tallies window.dedup_saved).  The AUTHORITATIVE
        commit-time plan passes None: it is computed against exactly
        the cache state the synchronous build would see and defines the
        bit-exact results; the pipeline serves it from the window only
        under a per-cell route match (pipeline.serve)."""
        nd = self.oracle.can.n_delta
        full = self._full_mask
        use_mask = self._use_mask()
        use_warm = self._use_warm()
        need: dict[bytes, np.ndarray] = {}
        vert: dict[bytes, np.ndarray] = {}
        donor: dict[bytes, tuple] = {}
        for n in nodes:
            act = self._active_delta_mask(n, use_mask)
            keys = self._keys(n)
            for k, v in zip(keys, self.tree.vertices[n]):
                cur = need.get(k)
                if cur is None:
                    need[k] = act
                    vert[k] = v
                elif cur is not full and act is not cur:
                    need[k] = full if act is full else (cur | act)
            if use_warm:
                # First cached vertex of this node that carries duals:
                # deterministic (node order x key order), so builds stay
                # reproducible run-to-run.
                drow = self._pick_donor(keys)
                if drow is not None:
                    for k2 in keys:
                        if k2 not in donor:
                            donor[k2] = drow
        pb = _PlanBuilder(self.oracle.can, use_warm)
        n_skips = n_new = 0
        # Remote cells (sharded frontier): (key, theta, delta indices)
        # a peer shard owns -- requested asynchronously here, collected
        # by the step before certify (window=None plans only).
        remote: list[tuple[bytes, np.ndarray, np.ndarray]] = []
        shard = self._shard
        for k, m in need.items():
            row = self.cache.get_key(k)
            if shard is not None:
                # Consume any exchange coverage first (a cell a peer
                # published -- or this shard solved on a peer's behalf
                # -- must never be re-solved), then route what is still
                # missing of a remotely-owned vertex through the
                # exchange instead of the local plan.
                miss0 = m if row is None else (m & ~row[7])
                if miss0.any() and shard.take(k, miss0):
                    row = self.cache.get_key(k)
                    miss0 = m & ~row[7]
                if miss0.any() and shard.is_remote(k):
                    shard.request(k, vert[k], miss0)
                    if window is None:
                        remote.append((k, vert[k], np.where(miss0)[0]))
                    continue
            drow = donor.get(k) if use_warm else None
            if row is None:
                if m.all():
                    # Full-need vertices stay on the dense grid program
                    # (donor or not): rerouting them through the pair
                    # path measurably slowed the build (per-cell H[d]
                    # gathers vs the grid's shared-delta vmap), while
                    # warm starts matter most in the masked deep tail
                    # whose cells already travel the pair path below.
                    if window is not None and window.covers_grid(k):
                        continue  # in-flight grid program covers it
                    pb.add_grid(k, vert[k])
                    continue
                missing_d = m
                n_skips += int(nd - m.sum())
            else:
                missing_d = m & ~row[7]
                if not missing_d.any():
                    continue
            if window is not None:
                cov = window.cover_masks(k, drow, nd)
                if cov is not None:
                    real, spec = cov
                    saved = missing_d & real
                    if saved.any():
                        window.dedup_saved += int(saved.sum())
                    missing_d = missing_d & ~(real | spec)
                if not missing_d.any():
                    continue
            ds = np.where(missing_d)[0]
            if row is None:
                # Widenings of an existing row are top-ups of a vertex
                # already counted -- n_unique_solves stays a count of
                # distinct vertices ever solved, same meaning as the
                # unmasked build's.
                n_new += 1
            pb.add_pair(k, ds, vert[k], drow)
        if pb.empty and not remote:
            return None
        plan = pb.finish(n_skips, n_new + pb.n_grid)
        if remote:
            plan["remote"] = remote
        return plan

    def _shard_prefetch(self) -> None:
        """Post exchange requests for every remotely-owned missing
        cell visible in the frontier's head (bounded; see step()).
        Store-covered cells are skipped -- they will be consumed at
        plan time without a request."""
        sh = self._shard
        use_mask = self._use_mask()
        limit = 4 * self.cfg.batch_simplices
        for n in itertools.islice(self.frontier, 0, limit):
            act = self._active_delta_mask(n, use_mask)
            for k, v in zip(self._keys(n), self.tree.vertices[n]):
                if not sh.is_remote(k):
                    continue
                row = self.cache.get_key(k)
                miss = act if row is None else (act & ~row[7])
                if not miss.any():
                    continue
                srow = sh.ex.rows.get(k)
                if srow is not None:
                    miss = miss & ~srow["mask"]
                if miss.any():
                    sh.request(k, v, miss)

    def _plan_spec_children(self, nodes: list[int],
                            window: "BuildPipeline"
                            ) -> tuple[dict, dict] | None:
        """Speculative plan for the bisection midpoints of `nodes`'
        predicted children (pipeline.speculate).

        Each node's longest-edge bisection is deterministic, so the
        children's shared new vertex -- and the exact plan the children's
        own claim would produce for it -- is computable before the
        node's verdict: active-delta mask from the node's inherited
        exclusions (the children inherit a superset of them), route by
        the same grid-vs-pair rule as _plan_missing, and the warm donor
        by the same first-cached-with-duals scan over the LEFT child's
        key order (the left child is appended to the frontier first, so
        it is the first requester whose donor pick sticks).  A route or
        donor that drifts by commit time is caught by the pipeline's
        serve-time match and re-solved -- speculation can only waste
        device work, never change a cache row.

        Returns (plan dict shaped like _plan_missing's, {key: owner
        node}), or None when nothing is worth dispatching."""
        use_mask = self._use_mask()
        use_warm = self._use_warm()
        pb = _PlanBuilder(self.oracle.can, use_warm)
        owners: dict[bytes, int] = {}
        for n in nodes:
            left, _right, _i, _j, mid = geometry.bisect(
                self.tree.vertices[n])
            k = geometry.vertex_key(mid)
            if k in owners or window.has_entry(k):
                continue  # already in flight (dedup)
            row = self.cache.get_key(k)
            act = self._active_delta_mask(n, use_mask)
            missing = act if row is None else (act & ~row[7])
            if not missing.any():
                continue
            owners[k] = n
            if row is None and missing.all():
                pb.add_grid(k, mid)
                continue
            ds = np.where(missing)[0]
            drow = None
            if use_warm:
                # The children's donor pick, replayed ahead of time:
                # every left-child vertex except the midpoint itself is
                # already cached (the node's own batch just consumed),
                # so the scan sees what the claiming plan will see.
                drow = self._pick_donor(geometry.vertex_keys(left))
            pb.add_pair(k, ds, mid, drow)
        if pb.empty:
            return None
        return pb.finish(0, 0), owners

    def _merge_plan_results(self, plan: dict, sol, pair_out) -> None:
        """Write an authoritative plan's resolved results into the
        cache.  `sol` / `pair_out` are shaped exactly like the oracle's
        wait_vertices / wait_pairs_full outputs whether they came from
        a direct wait, the pipeline window, or a mix (pipeline.serve):
        this is the ONE row-writing path, so pipelined and synchronous
        builds cannot diverge here."""
        nd = self.oracle.can.n_delta
        full = self._full_mask
        self.n_unique_solves += plan["n_new"]
        self.n_point_skips += plan["n_skips"]
        nc = self.oracle.can.nc
        if plan["grid_arr"] is not None:
            have_duals = sol.lam is not None
            for i, k in enumerate(plan["grid_keys"]):
                self.cache.put_key(
                    k, (sol.V[i], sol.conv[i], sol.grad[i], sol.u0[i],
                        sol.z[i], sol.Vstar[i], sol.dstar[i], full,
                        sol.lam[i] if have_duals else None,
                        sol.s[i] if have_duals else None))
        if plan["pair_slices"]:
            V, conv, grad, u0, z, lam_p, s_p = pair_out
            nt, nu, nz = (self.problem.n_theta, self.problem.n_u,
                          self.oracle.can.nz)
            have_duals = lam_p is not None
            for k, ds, lo in plan["pair_slices"]:
                row = self.cache.get_key(k)
                if row is None:
                    Vr = np.full(nd, np.inf)
                    convr = np.zeros(nd, dtype=bool)
                    gradr = np.zeros((nd, nt))
                    u0r = np.zeros((nd, nu))
                    zr = np.zeros((nd, nz))
                    maskr = np.zeros(nd, dtype=bool)
                    lamr = np.zeros((nd, nc)) if have_duals else None
                    sr = np.zeros((nd, nc)) if have_duals else None
                else:
                    Vr, convr, gradr = (row[0].copy(), row[1].copy(),
                                        row[2].copy())
                    u0r, zr = row[3].copy(), row[4].copy()
                    maskr = row[7].copy()
                    lamr = sr = None
                    if have_duals:
                        lamr = (row[8].copy() if row[8] is not None
                                else np.zeros((nd, nc)))
                        sr = (row[9].copy() if row[9] is not None
                              else np.zeros((nd, nc)))
                sl = slice(lo, lo + ds.size)
                Vr[ds], convr[ds], gradr[ds] = V[sl], conv[sl], grad[sl]
                u0r[ds], zr[ds] = u0[sl], z[sl]
                if have_duals:
                    lamr[ds] = lam_p[sl]
                    sr[ds] = s_p[sl]
                maskr[ds] = True
                # Same reduction as oracle.reduce_deltas (first
                # minimum): skipped cells are +inf/unconverged, so the
                # subset argmin equals the full-grid argmin.
                Vval = np.where(convr, Vr, np.inf)
                j = int(np.argmin(Vval))
                Vs = Vval[j]
                self.cache.put_key(k, (Vr, convr, gradr, u0r, zr, Vs,
                                       np.int64(j if np.isfinite(Vs)
                                                else -1),
                                       full if maskr.all() else maskr,
                                       lamr, sr))

    def _wait_or_fallback(self, kind: str, handle, args: tuple):
        """Resolve one dispatched part; on device failure (or solve
        timeout) re-solve the same batch on the CPU fallback oracle
        under the bounded-recovery policy (_recover: backoff retries,
        then quarantine).  ("degraded", ...) handles -- minted by the
        pipeline once the device-failure cap tripped -- skip the
        device wait AND the failure bookkeeping: the degraded engine
        routes straight to the twin without re-failing per batch.

        Takes eng._oracle_lock (reentrant -- pipeline._resolve already
        holds it): wait-time counter updates and the recovery
        machinery must never interleave with the async-certify
        waiter's."""
        with self._oracle_lock:
            return self._wait_or_fallback_locked(kind, handle, args)

    def _wait_or_fallback_locked(self, kind: str, handle, args: tuple):
        if not (isinstance(handle, tuple) and handle
                and handle[0] == "degraded"):
            try:
                if isinstance(handle, tuple) and len(handle) == 2 \
                        and handle[0] == "failed":
                    raise handle[1]

                def _go():
                    faults_lib.fire("oracle.wait", label=kind)
                    if kind == "vertices":
                        return self.oracle.wait_vertices(handle)
                    if kind == "pairs_full":
                        return self.oracle.wait_pairs_full(handle)
                    return self.oracle.wait_pairs(handle)

                return faults_lib.call_with_timeout(
                    _go, self._policy.solve_timeout_s)
            except (RuntimeError, OSError) as e:
                self._note_device_failure(f"dispatch_{kind}", e)
                err: BaseException | None = e
        else:
            err = None
        return self._recover(kind, args, err)

    # _recover kind -> quarantine-synthesis kind (oracle method names
    # normalize to the wait-kind vocabulary of synthesize_failure).
    _SYNTH_KIND = {"solve_vertices": "vertices",
                   "solve_pairs": "pairs",
                   "solve_pairs_full": "pairs_full"}

    def _fb_call(self, fb: Oracle, kind: str, args: tuple):
        """One fallback attempt on the CPU twin.  The twin mirrors
        two_phase/warm_start (cpu_twin), so a pairs_full re-solve
        consumes the same warm donors and returns the same extended
        tuple -- results stay bit-compatible with the device's."""
        if kind == "vertices":
            return fb.solve_vertices(*args)
        if kind == "pairs_full":
            return fb.solve_pairs_full(args[0], args[1], warm=args[2])
        if kind == "pairs":
            return fb.solve_pairs(*args)
        return getattr(fb, kind)(*args)

    def _recover(self, kind: str, args: tuple,
                 err: BaseException | None):
        """Bounded CPU-twin retries with exponential backoff; poison-
        cell quarantine on exhaustion.  `err` is the device-side
        failure that routed us here (None on the degraded fast path --
        no failure to capture, the device is simply out of rotation).

        Every additive stat (solve counts, iteration ledger, cohort/
        warm-start counters) folds into the main oracle so the
        exact-accounting figures survive partial device fallback."""
        pol = self._policy
        last = err
        for attempt in range(pol.max_attempts):
            if attempt:
                time.sleep(pol.backoff(attempt - 1))
            fb = self._fallback_oracle()
            before = fb.stat_snapshot()
            try:
                def _go():
                    faults_lib.fire("oracle.fallback", label=kind)
                    return self._fb_call(fb, kind, args)

                out = faults_lib.call_with_timeout(
                    _go, pol.fallback_timeout())
            except (RuntimeError, OSError) as e:
                last = e
                continue
            self.oracle.fold_stats(fb, before)
            if err is not None and self.recorder is not None:
                try:  # diagnostics must never break the fallback path
                    if kind in ("vertices", "pairs", "pairs_full"):
                        self._capture_device_failure(kind, args, out,
                                                     repr(err))
                    else:
                        self._capture_oracle_failure(kind, args, out,
                                                     repr(err))
                except Exception:  # tpulint: disable=silent-except -- diag
                    pass
            return out
        return self._quarantine(kind, args, last)

    def _note_device_failure(self, query: str, e: BaseException) -> None:
        """Shared device-failure bookkeeping: counters, log, health
        feed -- and the permanent-CPU degrade once the cap trips
        (cfg.device_failure_cap): from then on _oracle_call routes
        straight to the twin and the pipeline mints ("degraded", ...)
        handles instead of dispatching to the dead device, so a lost
        accelerator costs the fail-then-fallback tax ONCE instead of
        on every remaining batch (the old _wait_or_fallback retried
        the device forever)."""
        self.n_device_failures += 1
        self.log.emit(device_failure=repr(e)[:500], query=query,
                      retry_backend="cpu")
        self._health_device_failure(e)
        if not self._degraded \
                and self.n_device_failures >= self._policy.device_failure_cap:
            self._degraded = True
            self.log.emit(device_degraded=True,
                          failures=self.n_device_failures)
            rec = self.obs.event(
                "faults.device_degraded",
                failures=self.n_device_failures,
                cap=self._policy.device_failure_cap,
                msg="device failure cap reached: all further oracle "
                    "work routes to the CPU twin")
            if self._health is not None:
                self._health.feed(rec or {
                    "kind": "event", "name": "faults.device_degraded"})

    def _quarantine(self, kind: str, args: tuple,
                    err: BaseException | None):
        """Every recovery attempt failed: synthesize the conservative
        no-information result for the batch (faults/policy.py -- +inf
        unconverged points, -inf no-bound simplex rows, no Farkas
        certificates), record the poison cells, and let the build
        continue.  Sound by construction: synthesized values can only
        cause extra subdivision or uncertified leaves, never a wrong
        certificate."""
        out, n_cells = faults_lib.synthesize_failure(
            self._SYNTH_KIND.get(kind, kind), args, self.oracle)
        self.n_quarantined_cells += n_cells
        self.log.emit(quarantine=kind, cells=n_cells,
                      error=repr(err)[:300] if err else None)
        rec = self.obs.event("faults.quarantine", query=kind,
                             cells=n_cells,
                             error=repr(err)[:200] if err else None)
        if self.obs.enabled:
            self.obs.metrics.counter(
                "build.quarantined_cells").inc(n_cells)
        if self._health is not None:
            self._health.feed(rec or {"kind": "event",
                                      "name": "faults.quarantine"})
        if self.recorder is not None:
            try:  # diagnostics must never break the quarantine path
                self._capture_quarantine(kind, args, err)
            except Exception:  # tpulint: disable=silent-except -- diag
                pass
        return out

    def _capture_quarantine(self, kind: str, args: tuple,
                            err: BaseException | None) -> None:
        """Repro bundle for a quarantined batch: the exact inputs every
        recovery attempt failed on (scripts/replay_solve.py re-solves
        them standalone -- the poison-cell triage entry point)."""
        from explicit_hybrid_mpc_tpu.obs import recorder as rec_lib

        cap = self._MAX_FAILURE_ROWS
        arrays = dict(rec_lib.canonical_arrays(self.oracle.can))
        a0 = np.asarray(args[0])[:cap]
        if kind in ("vertices", "pairs", "pairs_full"):
            arrays["thetas"] = a0
        else:
            arrays["bary_Ms"] = a0
        if len(args) > 1 and args[1] is not None:
            arrays["delta_idx"] = np.asarray(args[1],
                                             dtype=np.int64)[:cap]
        self.recorder.dump(
            "quarantine", arrays,
            {"kind": "quarantine", "query": kind,
             "oracle": rec_lib.oracle_meta(self.oracle),
             "backend": self.oracle.backend,
             "error": repr(err)[:500] if err else None})

    def _gather_batch(self, nodes: list[int]) -> tuple[dict, tuple]:
        """Vertex data for the whole batch: ONE cache lookup per unique
        vertex and one stack per result field, with per-node
        SimplexVertexData as views into the batch tensors.  (The per-node
        7-row stacks and duplicate per-(node, vertex) lookups of the old
        scalar path were, with vertex_key, the top host costs in the
        cluster-scale step profile.)

        Returns (sds, (verts, V, conv, grad, u0, z, Vstar, dstar)); the
        tensors have leading dims (B, p+1, ...) and feed
        certify_stage1_batch directly, so the batch is stacked once, not
        twice."""
        rows: list[tuple] = []
        idx_of: dict[bytes, int] = {}
        m = self.tree.p + 1
        node_ix = np.empty((len(nodes), m), dtype=np.int64)
        for bi, n in enumerate(nodes):
            for vi, k in enumerate(self._keys(n)):
                j = idx_of.get(k)
                if j is None:
                    row = self.cache.get_key(k)
                    if row is None:
                        raise KeyError(f"vertex row missing for node {n}")
                    j = len(rows)
                    idx_of[k] = j
                    rows.append(row)
                node_ix[bi, vi] = j
        verts = self.tree.vertices[np.asarray(nodes, dtype=np.int64)]
        V = np.stack([r[0] for r in rows])[node_ix]
        conv = np.stack([r[1] for r in rows])[node_ix]
        grad = np.stack([r[2] for r in rows])[node_ix]
        u0 = np.stack([r[3] for r in rows])[node_ix]
        z = np.stack([r[4] for r in rows])[node_ix]
        Vstar = np.asarray([r[5] for r in rows])[node_ix]
        dstar = np.asarray([r[6] for r in rows])[node_ix]
        sds = {n: certify.SimplexVertexData(
                   verts=verts[bi], V=V[bi], conv=conv[bi], grad=grad[bi],
                   u0=u0[bi], z=z[bi], Vstar=Vstar[bi], dstar=dstar[bi])
               for bi, n in enumerate(nodes)}
        return sds, (verts, V, conv, grad, u0, z, Vstar, dstar)

    # -- one frontier step -------------------------------------------------

    def step(self) -> None:
        # Crash-at-step injection site (chaos testing; a None-test
        # when no plan is installed).
        faults_lib.fire("build.step", label=str(self.steps))
        t_step = time.perf_counter()
        self._oracle_s = 0.0
        if self._shard is not None:
            # Exchange maintenance: ingest peer publications, answer
            # peer requests (on-behalf solves charge _oracle_s through
            # _oracle_call like any other device work).
            self._shard.tick()
            # Request-ahead for the whole VISIBLE frontier, not just
            # pipeline claims: small shard frontiers rarely fill a
            # full-size lookahead batch, and a request first posted at
            # commit time costs a full cross-shard round-trip stall
            # per step.  Count-safe: an open node's need mask is fixed
            # at its split (inherit entries never change until it
            # commits), the request memo dedupes, and the owner solves
            # each cell once regardless of when it was asked.
            self._shard_prefetch()
        B = min(len(self.frontier), self.cfg.batch_simplices)
        nodes = [self.frontier.popleft() for _ in range(B)]
        pipe = self._pipe
        # Was this batch planned + dispatched during an earlier step?
        # (Claims are full-batch frontier prefixes, so a head claim is
        # always exactly this batch; the device worked through its point
        # solves while the host certified previous steps.)
        pipe.pop_claim(nodes)
        # Refill the lookahead BEFORE blocking on this batch: up to
        # cfg.pipeline_depth future batches are tentatively planned and
        # dispatched, so stage-2 solves queue behind them on the device
        # and the device never idles during host-side certification.
        with self.obs.span("build.pipeline_fill"):
            pipe.fill()
        # Critical-path segment boundaries (ISSUE 13): fill wall is
        # measured by the pipeline itself; everything the oracle
        # charges to _oracle_s from here on is device wait/dispatch.
        t_fill_end = time.perf_counter()
        oracle_s_fill = self._oracle_s
        # Authoritative plan, computed against exactly the cache state
        # the synchronous build would see at this step; the pipeline
        # serves route-matched cells from the in-flight window (one
        # coalesced solve fanned out to every requester) and re-solves
        # the rest synchronously, then the shared merge writes the
        # rows -- node-for-node identical to the synchronous build
        # (partition/pipeline.py, correctness model).
        plan = self._plan_missing(nodes)
        t_plan_end = time.perf_counter()
        if plan is not None:
            sol, pair_out = pipe.serve(plan)
            self._merge_plan_results(plan, sol, pair_out)
            rem = plan.get("remote")
            if rem:
                # Block for remotely-owned cells (sharded frontier).
                # The full collect wall is cross-shard wait: charge it
                # to _oracle_s exactly once (collect's own on-behalf
                # solves already charged their share through
                # _oracle_call, so take the max, not the sum).
                t_rem = time.perf_counter()
                o0 = self._oracle_s
                self._shard.collect(rem)
                inner = self._oracle_s - o0
                self._oracle_s = o0 + max(
                    time.perf_counter() - t_rem, inner)
        # Speculative child dispatch: cells the inherited-gap heuristic
        # predicts will split get their children's shared midpoint
        # dispatched NOW, before this batch's certificates run.
        pipe.speculate(nodes)
        # Asynchronous host-certify (cfg.async_certify): hand the
        # in-flight lookahead programs to the background waiter so
        # their device waits overlap the certify/commit host wall
        # below instead of serializing into the next step's wait.
        pipe.prewait()

        results: dict[int, certify.CertificateResult] = {}
        stage2: list[tuple[int, int]] = []  # (node, delta')
        infeas_candidates: list[int] = []
        use_inh = getattr(self.cfg, "inherit_bounds", True)
        bary_memo: dict[int, np.ndarray] = {}

        def _bary(n: int) -> np.ndarray:
            if n not in bary_memo:
                bary_memo[n] = geometry.barycentric_matrix(
                    self.tree.vertices[n])
            return bary_memo[n]

        # Exact per-delta facts established THIS step (Farkas +inf
        # exclusions, certified simplex lower bounds) -- inherited by children when
        # the node splits.
        fresh: dict[int, dict[int, float]] = collections.defaultdict(dict)
        sds, (bverts, bV, bconv, bgrad, _bu0, _bz, bVstar, bdstar) = \
            self._gather_batch(nodes)
        if self.cfg.algorithm == "feasible":
            for n in nodes:
                results[n] = certify.certify_feasible(sds[n])
        else:
            # Batched stage-1 certification: one vectorized pass over the
            # whole batch (decision-identical to the scalar path; the
            # per-node tangent einsums dominated host time), fed the batch
            # tensors the gather already built.
            res_list = certify.certify_stage1_batch(
                bverts, bV, bconv, bgrad, bVstar, bdstar,
                self.cfg.eps_a, self.cfg.eps_r)
            for n, res in zip(nodes, res_list):
                if res.status == "certified":
                    # The batch pass leaves the leaf payload to us (it
                    # would otherwise haul the (B, m, nd, nz) z tensor
                    # through every call).
                    sd = sds[n]
                    d = res.delta_idx
                    res.vertex_inputs = sd.u0[:, d, :]
                    res.vertex_z = sd.z[:, d, :]
                results[n] = res
        for n in nodes:
            res = results[n]
            if res.status == "pending":
                stage2.extend((n, int(d)) for d in res.pending_deltas)
            elif res.status == "infeasible":
                infeas_candidates.append(n)

        if infeas_candidates:
            # All vertices infeasible does NOT imply the simplex is (the
            # hybrid feasible set is a union over commutations, not
            # convex): require positive phase-1 evidence that EVERY
            # commutation is infeasible on the whole simplex; otherwise
            # split to hunt for the interior feasible pocket.  Commutations
            # already Farkas-certified infeasible on an ANCESTOR simplex
            # (child subset of ancestor) are exact and skipped -- note this
            # decision is STRICTLY more accurate than re-solving (a child
            # phase-1 that stalls would demote an exactly-known infeasible
            # to 'split'), so an inheritance-free build can in principle
            # split where this one closes an infeasible leaf.
            nd = self.oracle.can.n_delta
            empty_on_R = {n: True for n in infeas_candidates}
            reqs = []
            for n in infeas_candidates:
                inh = self._inherit.get(n, {}) if use_inh else {}
                for d in range(nd):
                    if inh.get(d) == np.inf:
                        self.n_inherited_skips += 1
                    else:
                        reqs.append((n, d))
            if reqs:
                Ms = np.stack([_bary(n) for n, _ in reqs])
                ds = np.array([d for _, d in reqs], dtype=np.int64)
                _t, _feas, infeas_cert = self._oracle_call(
                    "simplex_feasibility", Ms, ds)
                for (n, d), ok in zip(reqs, infeas_cert):
                    empty_on_R[n] &= bool(ok)
                    if ok:
                        fresh[n][d] = np.inf
            for n in infeas_candidates:
                if not empty_on_R[n]:
                    results[n] = certify.CertificateResult(status="split")
                # else keep 'infeasible': certified empty on R

        if stage2:
            # Round A: solve only (node, delta') pairs with NO inherited
            # bound.  +inf entries are exact ancestor Farkas exclusions;
            # finite entries are ancestor simplex lower bounds -- valid lower
            # bounds on any child, tried for free first.
            solve_list: list[tuple[int, int]] = []
            vm_map: dict[int, dict[int, float]] = collections.defaultdict(dict)
            loose: dict[int, list[int]] = collections.defaultdict(list)
            for n, d in stage2:
                inh = self._inherit.get(n, {}) if use_inh else {}
                b = inh.get(d)
                if b is None or b == -np.inf:
                    solve_list.append((n, d))
                elif b == np.inf:
                    vm_map[n][d] = np.inf
                    self.n_inherited_skips += 1
                else:
                    vm_map[n][d] = b
                    loose[n].append(d)
                    self.n_inherited_skips += 1
            if solve_list:
                Ms = np.stack([_bary(n) for n, _ in solve_list])
                ds = np.array([d for _, d in solve_list], dtype=np.int64)
                Vmin, _feas = self._oracle_call("solve_simplex_min", Ms, ds)
                for (n, d), vm in zip(solve_list, Vmin):
                    vm_map[n][d] = float(vm)
                    fresh[n][d] = float(vm)
            # Certify with what we have.  A PASS with loose bounds is sound
            # (looser lower bound => larger gap; exact build would also
            # pass -- though possibly selecting a different certifying
            # candidate delta, so leaf delta_idx is NOT guaranteed
            # bit-identical, only the certify/split decision).  A FAIL that
            # used loose bounds is inconclusive: round B re-solves exactly
            # so the split/certify decision matches an inheritance-free
            # build (region/structure parity).
            roundB: list[tuple[int, int]] = []
            for n in sorted(vm_map):
                res2 = certify.certify_suboptimal_stage2(
                    sds[n], results[n], vm_map[n], self.cfg.eps_a,
                    self.cfg.eps_r)
                if res2.status == "certified" or not loose[n]:
                    results[n] = res2
                else:
                    roundB.extend((n, d) for d in loose[n])
            if roundB:
                Ms = np.stack([_bary(n) for n, _ in roundB])
                ds = np.array([d for _, d in roundB], dtype=np.int64)
                Vmin, _feas = self._oracle_call("solve_simplex_min", Ms, ds)
                for (n, d), vm in zip(roundB, Vmin):
                    vm_map[n][d] = float(vm)
                    fresh[n][d] = float(vm)
                    self.n_inherited_skips -= 1  # loose bound did not stick
                for n in sorted({nn for nn, _ in roundB}):
                    results[n] = certify.certify_suboptimal_stage2(
                        sds[n], results[n], vm_map[n], self.cfg.eps_a,
                        self.cfg.eps_r)

        # Log fresh stage-2 facts into the tree's event ledger
        # (tree.excl_events): the warm rebuild (partition/rebuild.py)
        # re-VERIFIES exactly these (node, delta) certificates against
        # a revised oracle and inherits the survivors down the tree --
        # +inf rows are whole-simplex emptiness certificates (they mask
        # descendant point cells and close pending commutations for
        # free), finite rows are the simplex lower bounds descendant
        # certifications passed with (re-solved lazily at the SAME
        # node, shared by every descendant leaf).  Re-DISCOVERING
        # either costs a joint QP per (leaf, pending commutation), the
        # dominant sweep cost on hybrid problems.  -inf stalls carry no
        # reusable fact and are not logged.
        ev = self.tree.excl_events
        for n2, fd in fresh.items():
            for d2, v2 in fd.items():
                if v2 == np.inf or np.isfinite(v2):
                    ev.append((int(n2), int(d2), float(v2)))

        n_leaves = n_splits = 0
        store_z = getattr(self.cfg, "store_vertex_z", True)
        # Certificate-margin telemetry (ROADMAP item 4 evidence base):
        # per certified leaf, the eps-budget slack the certificate
        # passed with -- build.cert_margin's p01 is how much headroom
        # a precision change (f32 refine) must fit under.
        cert_h = self.obs.histogram("build.cert_margin") \
            if self.obs.enabled else None
        for n in nodes:
            res = results[n]
            did_split = False
            if res.status == "certified":
                self.tree.set_leaf(n, LeafData(
                    delta_idx=res.delta_idx,
                    vertex_inputs=res.vertex_inputs,
                    vertex_costs=res.vertex_costs,
                    vertex_z=res.vertex_z if store_z else None))
                n_leaves += 1
                if cert_h is not None:
                    m = certify.cert_margin(
                        res.gap, sds[n].Vstar,
                        self.cfg.eps_a, self.cfg.eps_r)
                    if m is not None:
                        cert_h.observe(m)
            elif res.status == "infeasible":
                pass  # leaf with no data: outside the feasible region
            else:  # split
                # Boundary closure (round-3 verdict item 4): a
                # mixed-feasibility split can NEVER certify -- the hybrid
                # feasible set's boundary crosses R, and every descendant
                # straddling it inherits the problem.  At depth >=
                # semi_explicit_boundary_depth, close it as a
                # semi-explicit leaf instead: the stored commutation is
                # certified feasible on the converged-vertex hull
                # (convexity), and the online path solves the fixed-delta
                # QP at the query point (SemiExplicitController), which
                # establishes feasibility per query.
                sb = self.cfg.semi_explicit_boundary_depth
                if (sb is not None and res.mixed_feasibility
                        and self.tree.depth[n] >= sb):
                    sd = sds[n]
                    d = certify.boundary_candidate(sd)
                    if d is not None:
                        u, V, z = certify.boundary_payload(sd, d)
                        self.tree.set_leaf(n, LeafData(
                            delta_idx=d, vertex_inputs=u, vertex_costs=V,
                            vertex_z=z if store_z else None,
                            certified=False, semi_explicit=True))
                        self.n_semi_explicit += 1
                        n_leaves += 1
                        self._inherit.pop(n, None)
                        self._release(n)
                        pipe.on_commit(n, split=False)
                        continue
                if self.tree.depth[n] >= self.cfg.max_depth:
                    # Depth cap: accept the best available candidate as an
                    # UNcertified best-effort leaf, flag it in stats.
                    self.n_uncertified += 1
                    sd = sds[n]
                    if self.recorder is not None:
                        try:  # diagnostics must never break the build
                            self._capture_uncertified(n, sd, res)
                        except Exception:  # tpulint: disable=silent-except
                            pass
                    d = certify.best_feasible_candidate(sd)
                    if d is not None:
                        self.tree.set_leaf(n, LeafData(
                            delta_idx=d, vertex_inputs=sd.u0[:, d, :],
                            vertex_costs=sd.V[:, d],
                            vertex_z=(sd.z[:, d, :] if store_z else None),
                            certified=False))
                    self._inherit.pop(n, None)
                    self._release(n)
                    pipe.on_commit(n, split=False)
                    continue
                left, right, i, j, _ = geometry.bisect(self.tree.vertices[n])
                li, ri = self.tree.split(n, left, right, (i, j))
                did_split = True
                # The children inherit the parent's certificate gap as
                # their split-prediction hint (speculative dispatch).
                pipe.note_children(li, ri, float(res.gap))
                self.frontier.append(li)
                self.frontier.append(ri)
                # Children first: shared parent/child vertices must never
                # transiently hit refcount 0 (a release-first order would
                # evict + re-solve them).
                self._retain(li)
                self._retain(ri)
                if use_inh:
                    # Children inherit ancestor facts, overridden by this
                    # step's exact results (tighter: computed on n's own R).
                    # -inf (stalled solve, no usable bound) is never stored.
                    child_inh = {**self._inherit.get(n, {}),
                                 **{d: v for d, v in fresh[n].items()
                                    if v != -np.inf}}
                    if child_inh:
                        self._inherit[li] = dict(child_inh)
                        self._inherit[ri] = child_inh
                n_splits += 1
            self._inherit.pop(n, None)
            self._release(n)
            # Settle this cell's speculation: a non-split drops its
            # staged child-midpoint rows before they can reach the
            # cache (mis-speculation = waste, never a changed tree).
            pipe.on_commit(n, split=did_split)

        t_work_end = time.perf_counter()
        # In-build wall-stall probe: how long since the previous
        # step's records went out -- the silent window an external
        # obs_watch tail would have measured on the stream.  A wedged-
        # then-recovered solve (device hang, injected fault) shows up
        # HERE, not in the inter-step gap: the step that contained it
        # ran longer than the stall budget with nothing emitted.
        # Checked before this step's own records are emitted, so the
        # health.stall event lands in the stream at the position the
        # silence ended -- and the auto-profile trigger riding on a
        # critical verdict (cfg.auto_profile) fires without an
        # external watcher.
        if self._health is not None and self._last_step_end is not None:
            self._health.check_stall(t_work_end - self._last_step_end)
        self.steps += 1
        step_s = t_work_end - t_step
        # Per-step critical-path wall breakdown (fleet telemetry):
        # fill (lookahead plan+dispatch, pipeline-measured), plan (the
        # authoritative re-plan), wait (everything the oracle layer
        # charged to _oracle_s after fill -- blocking waits, residual
        # dispatches, stage-2 calls, speculation dispatch), certify
        # (the remaining host wall of the gather/certify/commit
        # block), other (prologue + the residual; clamped at 0 against
        # timer noise).  The five sum to step_s by construction, so
        # the per-step fractions sum to 1.
        cp_fill = min(pipe.last_fill_wall, t_fill_end - t_step)
        cp_plan = t_plan_end - t_fill_end
        cp_wait = self._oracle_s - oracle_s_fill
        cp_certify = max(0.0, (t_work_end - t_plan_end) - cp_wait)
        cp_other = max(0.0, step_s - cp_fill - cp_plan - cp_wait
                       - cp_certify)
        self._cp["fill"] += cp_fill
        self._cp["plan"] += cp_plan
        self._cp["wait"] += cp_wait
        self._cp["certify"] += cp_certify
        self._cp["other"] += cp_other
        self._cp_step_s += step_s
        regions = self.tree.n_regions()
        # Fraction of the step spent blocked on oracle device programs
        # -- the JSONL device-utilization proxy (SURVEY.md section 6.5;
        # exact per-op device time lives in the --profile trace).
        device_frac = round(self._oracle_s / max(step_s, 1e-9), 3)
        self.device_frac_ema = (0.7 * self.device_frac_ema
                                + 0.3 * device_frac)
        self.log.emit(step=self.steps, frontier=len(self.frontier),
                      batch=B, leaves=n_leaves, splits=n_splits,
                      regions=regions,
                      solves=self.oracle.n_solves,
                      cached_vertices=len(self.cache),
                      step_s=round(step_s, 4),
                      oracle_s=round(self._oracle_s, 4),
                      device_frac=device_frac)
        o = self.obs
        if o.enabled:
            m = o.metrics
            m.counter("build.steps").inc()
            m.counter("build.leaves").inc(n_leaves)
            m.counter("build.splits").inc(n_splits)
            m.counter("build.oracle_solves").inc(
                self.oracle.n_solves - self._prev_solves)
            self._prev_solves = self.oracle.n_solves
            # build.regions doubles as the converged-leaf backlog:
            # certified leaves accumulate in the tree until the
            # bounded-memory export (PR 1) drains them post-build.
            m.gauge("build.frontier").set(len(self.frontier))
            m.gauge("build.regions").set(regions)
            m.gauge("build.device_frac").set(device_frac)
            # THIS SESSION's throughput (regions certified here over
            # session wall): a resumed campaign must not divide prior
            # sessions' regions by this session's clock.  The
            # cumulative figure lives in stats_dict/build.done.
            wall = time.perf_counter() - self._obs_t0
            m.gauge("build.regions_per_s").set(
                (regions - self._obs_regions0) / max(wall, 1e-9))
            m.histogram("build.step_s").observe(step_s)
            m.histogram("build.oracle_wait_s").observe(self._oracle_s)
            # Pipeline occupancy + speculation/dedup economy: cumulative
            # gauges, cheap to recompute per step; scripts/obs_report.py
            # renders them next to device_frac (the device-busy vs
            # host-busy occupancy split).
            m.gauge("build.pipeline_fill").set(
                pipe.planned_in_flight / pipe.depth if pipe.depth
                else 0.0)
            m.gauge("build.pipeline_fill_frac").set(pipe.fill_frac())
            m.gauge("build.dedup_saved").set(pipe.dedup_saved)
            m.gauge("build.spec_hit_rate").set(pipe.spec_hit_rate())
            m.gauge("build.spec_waste_frac").set(
                pipe.spec_waste_frac(self.oracle.n_point_solves))
            # Cumulative critical-path attribution: seconds per
            # segment plus run-mean fractions of step wall (the
            # occupancy decomposition obs_report renders and the
            # bench row records; docs/observability.md "Fleet
            # telemetry").
            denom = max(self._cp_step_s, 1e-9)
            for seg in ("fill", "plan", "wait", "certify", "other"):
                m.gauge(f"build.cp_{seg}_s").set(self._cp[seg])
                m.gauge(f"build.cp_{seg}_frac").set(
                    self._cp[seg] / denom)
            if pipe.async_on:
                m.gauge("build.cp_overlap_s").set(pipe.overlap_wait_s)
            rec = o.event("build.step", step=self.steps, regions=regions,
                          frontier=len(self.frontier), batch=B,
                          leaves=n_leaves, splits=n_splits,
                          step_s=round(step_s, 6),
                          device_frac=device_frac,
                          pipeline=pipe.in_flight,
                          cp_fill_s=round(cp_fill, 6),
                          cp_plan_s=round(cp_plan, 6),
                          cp_wait_s=round(cp_wait, 6),
                          cp_certify_s=round(cp_certify, 6),
                          cp_other_s=round(cp_other, 6))
            if self._health is not None:
                # In-stream watchdog (cfg.health_rules): rolling rules
                # over the step events, plus a periodic metrics
                # snapshot so rate rules (rescue storm, warm-start
                # collapse) see counter deltas mid-build.  health.*
                # events land in the SAME stream via the monitor's
                # sink.
                self._health.feed(rec)
                every = int(self._health.rules["metrics_every_steps"])
                if every > 0 and self.steps % every == 0:
                    self._health.feed(o.flush_metrics())
        if self._rc_guard is not None:
            self._guard_step(B)
        if self._auto_prof is not None:
            self._poll_auto_profile()
        self._last_step_end = time.perf_counter()

    def _poll_auto_profile(self) -> None:
        """Advance an open auto-capture one step; open one when the
        in-build health verdict turned CRITICAL since the last poll
        (obs/profiling.py AutoProfiler; cfg.auto_profile)."""
        ap = self._auto_prof
        ap.on_step(self.obs)
        if self._health is None or ap.active \
                or ap.n_captures >= ap.max_captures:
            return
        evs = self._health.events
        while self._auto_prof_seen_events < len(evs):
            ev = evs[self._auto_prof_seen_events]
            self._auto_prof_seen_events += 1
            if ev.get("severity") == "critical":
                ap.trigger(ev.get("name", "critical"),
                           detail={"msg": ev.get("msg"),
                                   "value": ev.get("value"),
                                   "threshold": ev.get("threshold")},
                           obs=self.obs, step=self.steps)
                break

    def trigger_auto_profile(self, reason: str) -> int:
        """External capture trigger (scripts/long_build.py's
        health-halt path: capture the evidence BEFORE halting).
        Returns how many more frontier steps the caller should run to
        fill the capture window; 0 when auto-profiling is not armed,
        already capturing, or the per-run budget is spent."""
        ap = self._auto_prof
        if ap is None:
            return 0
        if ap.trigger(reason, obs=self.obs, step=self.steps):
            return ap.steps
        return 0

    # -- full run ----------------------------------------------------------

    def run(self) -> PartitionResult:
        t0 = time.perf_counter()
        budget = self.cfg.time_budget_s
        profiling = False
        if self.cfg.profile_path:
            # SURVEY.md section 6.1: jax.profiler trace of the first
            # profile_steps frontier steps (device utilization and
            # f64-emulation hotspots are visible only at this level).
            import jax

            jax.profiler.start_trace(self.cfg.profile_path)
            profiling = True
            self.log.emit(profiling=True, trace_dir=self.cfg.profile_path)
        try:
            try:
                while self.frontier and self.steps < self.cfg.max_steps:
                    if (budget is not None
                            and time.perf_counter() - t0 >= budget):
                        self.log.emit(time_budget_hit=True,
                                      budget_s=budget)
                        break
                    self.step()
                    if profiling and self.steps >= self.cfg.profile_steps:
                        import jax

                        jax.profiler.stop_trace()
                        profiling = False
                    if (self.cfg.checkpoint_every
                            and self.steps % self.cfg.checkpoint_every == 0
                            and self.cfg.checkpoint_path):
                        self.save_checkpoint(self.cfg.checkpoint_path)
            finally:
                if profiling:
                    import jax

                    jax.profiler.stop_trace()
            # Drop whatever the lookahead still has in flight (budget or
            # max_steps stop): the claims were never popped from the
            # frontier, so truncation stats stay exact, and unwaited
            # speculation settles into the waste counters before the
            # stats snapshot below.
            self._pipe.cancel()
            wall = time.perf_counter() - t0
            if self._shard is not None:
                # Sharded epilogue (partition/shard.py): serve peer
                # requests until every shard drains, then merge the
                # shard trees -- every process merges identically, so
                # callers see one global result on all shards.
                res = self._shard.finalize(self, wall)
                brief = {k: v for k, v in res.stats.items()
                         if k != "per_shard"}
                self.log.emit(done=True, **brief)
                self.obs.event("build.done", **brief)
                return res
            stats = self.stats_dict(wall)
            self.log.emit(done=True, **stats)
            self.obs.event("build.done", **stats)
            return PartitionResult(self.tree, self.roots, stats)
        finally:
            self.finish_obs()

    def finish_obs(self) -> None:
        """Final metrics snapshot (+ close when the engine built the
        handle from cfg).  Runs in run()'s outer finally so a crashed
        build still ships its histograms -- the snapshot matters MOST
        for the run that died; external step-loop drivers (long_build)
        own their handle's lifecycle and close it themselves."""
        if self._auto_prof is not None:
            # Close a capture the run ended inside (frontier drained
            # or halted mid-window); the summary bundle still lands.
            self._auto_prof.finish(self.obs)
        if self.obs.enabled:
            self.obs.flush_metrics()
            if self._owns_obs:
                self.obs.close(snapshot=False)

    def stats_dict(self, wall: float) -> dict:
        """The run-summary statistics dict for the build so far.

        Factored out of run() so external drivers (scripts/long_build.py
        runs its own step loop to support pause/resume around TPU capture
        windows) report the IDENTICAL schema."""
        stats = {
            "regions": self.tree.n_regions(),
            "tree_nodes": len(self.tree),
            "max_depth": self.tree.max_depth(),
            "steps": self.steps,
            "oracle_solves": self.oracle.n_solves,
            # Solve mix: stage-2 joint simplex QPs dominated round-2's
            # builds (82% of solves); bound inheritance exists to flip
            # that, and `inherited_skips` counts the solves it avoided.
            "point_solves": self.oracle.n_point_solves,
            "simplex_solves": self.oracle.n_simplex_solves,
            "rescue_solves": self.oracle.n_rescue_solves,
            "inherited_skips": self.n_inherited_skips,
            "uncertified": self.n_uncertified,
            # Semi-explicit boundary leaves (mixed vertex feasibility
            # closed via cfg.semi_explicit_boundary_depth): their volume
            # counts as covered-but-not-eps-certified; post.analysis
            # reports the certified/semi-explicit split.
            "semi_explicit": self.n_semi_explicit,
            # Non-empty frontier here means the run hit max_steps: the
            # remaining simplices are UNCOVERED holes, not a complete
            # partition -- callers must check this.
            "truncated": len(self.frontier) > 0,
            "frontier_left": len(self.frontier),
            "wall_s": wall,
            "regions_per_s": self.tree.n_regions() / max(wall, 1e-9),
            # Memory figure for the bounded-cache design (SURVEY.md
            # section 6.4/VERDICT r1 item 6): high-water mark of live
            # vertex rows, plus total unique vertex solves (the
            # work-sharing metric the cache exists for).
            "unique_vertex_solves": self.n_unique_solves,
            # (vertex, commutation) point QPs skipped because the
            # commutation was Farkas-excluded on an ancestor simplex
            # (cfg.mask_point_solves).
            "masked_point_skips": self.n_point_skips,
            # Steps whose point solves were dispatched during an
            # EARLIER step's host work (the bounded build pipeline;
            # the legacy key name is kept for BENCH/driver consumers).
            "prefetched_steps": self._pipe.n_pipelined_steps,
            "pipelined_steps": self._pipe.n_pipelined_steps,
            "pipeline_depth": self._pipe.depth,
            # Mean lookahead occupancy (in-flight claims / depth); 1.0
            # = the pipeline stayed full every step.
            "pipeline_fill_frac": round(self._pipe.fill_frac(), 4),
            # (vertex, delta) device solves avoided by coalescing
            # duplicate in-flight requests across the window (the old
            # prefetch re-solved these across batch boundaries).
            # Counted at fill time, once per skipped re-dispatch; a
            # serve-time route miss on a counted cell (donor drift,
            # rare) re-solves it anyway, so the figure can overstate by
            # those cells.
            "dedup_saved": self._pipe.dedup_saved,
            # Speculative child dispatch economy: consumed vs dropped
            # speculative point-QP cells, the derived precision, and
            # the waste as a fraction of all point-QP cells the device
            # ran (waited solves + dropped-unwaited speculation).
            "spec_hits": self._pipe.spec_hits,
            "spec_waste": self._pipe.spec_waste,
            "spec_hit_rate": round(self._pipe.spec_hit_rate(), 4),
            "spec_waste_frac": round(
                self._pipe.spec_waste_frac(self.oracle.n_point_solves),
                4),
            "device_failures": self.n_device_failures,
            # Asynchronous host-certify economy (cfg.async_certify):
            # device-wait seconds the background waiter absorbed while
            # the host certified -- the overlap win the serialized
            # cp_wait_frac no longer contains.
            "async_certify": bool(getattr(self._pipe, "async_on",
                                          False)),
            "cp_overlap_s": round(
                getattr(self._pipe, "overlap_wait_s", 0.0), 3),
            # Checkpoint wall (the one critical-path segment outside
            # the step loop); the per-segment step-wall fractions are
            # appended below when any step ran.
            "cp_checkpoint_s": round(self._cp["checkpoint"], 3),
            # Poison-cell quarantine (faults/policy.py): cells whose
            # every recovery attempt failed and that were closed with
            # synthesized no-information results.  0 on any healthy
            # run; the chaos acceptance config requires 0 too (every
            # injected fault must be RECOVERED, not given up on).
            "quarantined_cells": self.n_quarantined_cells,
            "device_degraded": bool(self._degraded),
            "cache_peak_vertices": self.cache.peak_vertices,
            "cache_peak_mb": round(self.cache.peak_bytes / 2**20, 2),
            "cache_live_vertices": len(self.cache),
        }
        # Critical-path attribution (ISSUE 13): run-mean fraction of
        # step wall per segment -- they sum to ~1 by construction (the
        # per-step residual is clamped at 0 against timer noise).
        # bench.py lifts these into the capture row.
        if self._cp_step_s > 0:
            for seg in ("fill", "plan", "wait", "certify", "other"):
                stats[f"cp_{seg}_frac"] = round(
                    self._cp[seg] / self._cp_step_s, 4)
        return stats

    # -- checkpoint / resume (SURVEY.md section 6.4) -----------------------

    def save_checkpoint(self, path: str) -> None:
        t_ck = time.perf_counter()
        if self._shard is not None:
            # Each shard owns ITS OWN frontier state: per-shard
            # checkpoint generations, suffixed like the per-process
            # obs streams (resume re-derives the same suffix).
            path = f"{path}.p{self._shard.shard}"
        try:
            self._save_checkpoint(path, t_ck)
        finally:
            # A slow checkpoint is not a stall: re-arm the in-build
            # wall-stall probe so the next step's gap measures real
            # silence, not the serialization we just did on purpose.
            self._last_step_end = time.perf_counter()

    def _save_checkpoint(self, path: str, t_ck: float) -> None:
        # Cancel the in-flight pipeline BEFORE serializing (and before
        # the owner check -- under SPMD every process must cancel
        # identically to stay in lockstep): a snapshot is only ever
        # taken at a quiescent boundary, so a resume can never
        # re-dispatch or double-commit work that was in flight at
        # checkpoint time.  (The old single-slot prefetch serialized
        # with a handle armed and the resume path silently discarded
        # it.)  Claims were never popped from the frontier, so the
        # snapshot loses no nodes; dropped handles were never counted
        # by the oracle, so resumed-equals-straight solve parity holds.
        # Cost: one lookahead's dispatched device work per
        # checkpoint_every steps (~0.1% at long_build's default 1000)
        # -- accepted for the hard quiescence invariant.
        self._pipe.cancel()
        # Under multi-process SPMD every process runs the frontier in
        # lockstep; side effects belong to the owner (process 0) only.
        # A SHARDED frontier is the opposite: every shard's state is
        # distinct and every shard writes its own (suffixed) file.
        from explicit_hybrid_mpc_tpu.parallel import distributed

        if self._shard is None and not distributed.is_frontier_owner():
            return
        snap = {
            "tree": self.tree, "roots": self.roots,
            "frontier": list(self.frontier),
            "cache": self.cache._d, "steps": self.steps,
            "n_uncertified": self.n_uncertified,
            "n_semi_explicit": self.n_semi_explicit,
            "n_unique_solves": self.n_unique_solves,
            "n_solves": self.oracle.n_solves,
            "n_point_solves": self.oracle.n_point_solves,
            "n_simplex_solves": self.oracle.n_simplex_solves,
            "n_rescue_solves": self.oracle.n_rescue_solves,
            # Inherited per-delta bounds are part of frontier state:
            # dropping them on resume would be sound (they are an
            # optimization) but would break resumed-equals-straight
            # solve-count parity.
            "inherit": {n: self._inherit[n] for n in self.frontier
                        if n in self._inherit},
            "n_inherited_skips": self.n_inherited_skips,
            "n_point_skips": self.n_point_skips,
            "cfg": self.cfg,
            # Duplicates the tree's own stamp at the top level so a
            # checkpoint's provenance is inspectable without paying
            # the multi-hundred-MB tree unpickle.
            "provenance": getattr(self.tree, "provenance", None),
        }
        # Two-generation rotation + checksummed atomic write
        # (utils/atomic.py): the current valid checkpoint becomes
        # `.prev` and the new one STREAMS via tmp+fsync+rename behind
        # a content-checksum header (no full-payload byte string in
        # RAM -- the tree is the process's largest object), so a crash
        # at ANY instant leaves at least one loadable generation on
        # disk and at-rest corruption is detected at load
        # (load_checkpoint falls back to `.prev` on a rejected file).
        # A pickling failure mid-stream deletes the tmp and leaves
        # `.prev` as the newest generation -- strictly better than the
        # old in-place pickle.dump, which tore the primary.
        if os.path.exists(path):
            os.replace(path, path + ".prev")
        # Kill-mid-checkpoint injection site: a crash HERE (after
        # rotation, before the write) is the worst-ordered torn
        # checkpoint -- only `.prev` survives, which is exactly what
        # the generation fallback exists for (chaos schedule 3).
        faults_lib.fire("checkpoint.write", label=os.path.basename(path))
        atomic.atomic_pickle(path, snap)
        # Checkpoint wall into the critical-path ledger, then a
        # metrics snapshot into the stream BEFORE the crash-injection
        # site below: the snapshot is the per-process stream's "work
        # completed through this checkpoint" record, which is what
        # makes fleet counter rollups over a supervised restart chain
        # reconcile EXACTLY (a process os._exit-killed at the
        # checkpoint boundary has already shipped its totals;
        # obs/fleet.py, scripts/fleet_smoke.py).  Doubles as the
        # resumable counter/histogram trajectory long_build used to
        # flush itself.
        self._cp["checkpoint"] += time.perf_counter() - t_ck
        if self.obs.enabled:
            self.obs.gauge("build.cp_checkpoint_s").set(
                self._cp["checkpoint"])
            self.obs.flush_metrics()
        # At-rest corruption site: `corrupt` kinds mangle the landed
        # file so the loader's checksum rejection is exercised.
        faults_lib.fire("checkpoint.written",
                        label=os.path.basename(path), path=path)

    @classmethod
    def resume(cls, snapshot: str | dict, problem, oracle: Oracle,
               log: RunLog | None = None,
               cfg: PartitionConfig | None = None,
               obs: "obs_lib.Obs | None" = None) -> "FrontierEngine":
        """Rebuild an engine from a checkpoint path or an already-loaded
        snapshot dict (checkpoints hold the whole tree + cache; callers
        that inspected the snapshot pass the dict to avoid a second
        multi-hundred-MB unpickle).  `cfg` overrides the snapshot's (the
        CLI uses it to redirect log/checkpoint paths to the new run)."""
        if isinstance(snapshot, dict):
            snap = snapshot
        else:
            snap = load_checkpoint(snapshot)
        eng = cls.__new__(cls)
        eng.problem = problem
        eng.oracle = oracle
        if cfg is None:
            cfg_snap = snap["cfg"]
            # Conservative back-fill for pre-knob snapshots: the
            # two-phase/warm-start class defaults are True, but a
            # resumed old build must keep its original single-phase
            # cold-start semantics mid-build (resumed-equals-straight
            # parity; main.py applies the same back-fill on its path).
            for fld, legacy in (("ipm_two_phase", False),
                                ("ipm_phase1_iters", None),
                                ("warm_start_tree", False),
                                ("ipm_kernel", "xla")):
                if fld not in cfg_snap.__dict__:
                    object.__setattr__(cfg_snap, fld, legacy)
            cfg = cfg_snap
        eng.cfg = cfg
        eng.log = log or RunLog(eng.cfg.log_path, echo=False)
        eng.tree = snap["tree"]
        if getattr(eng.tree, "provenance", None) is None:
            # Pre-stamp snapshot: back-fill from the EFFECTIVE resumed
            # config (the snapshot's solver knobs -- see the cfg merge
            # above), so artifacts exported after this resume carry a
            # stamp going forward.
            from explicit_hybrid_mpc_tpu.partition import (
                provenance as prov)

            eng.tree.provenance = prov.build_stamp(problem, cfg)
        eng.roots = snap["roots"]
        eng.frontier = collections.deque(snap["frontier"])
        eng.cache = VertexCache()
        eng.cache._d = snap["cache"]
        eng.steps = snap["steps"]
        eng.n_uncertified = snap["n_uncertified"]
        eng.n_semi_explicit = snap.get("n_semi_explicit", 0)
        eng.n_unique_solves = snap.get("n_unique_solves", 0)
        eng.n_device_failures = 0
        eng._inherit = dict(snap.get("inherit", {}))
        eng.n_inherited_skips = snap.get("n_inherited_skips", 0)
        eng.n_point_skips = snap.get("n_point_skips", 0)
        eng._full_mask = np.ones(oracle.can.n_delta, dtype=bool)
        # Sharded context BEFORE the pipeline (the pipeline's
        # speculation gate reads it); the snapshot's frontier already
        # holds only this shard's open nodes, so no re-filter.
        eng._shard = None
        if getattr(cfg, "shard_frontier", False):
            from explicit_hybrid_mpc_tpu.partition.shard import (
                ShardContext)

            eng._shard = ShardContext.from_config(eng, cfg)
        # Fresh pipeline: in-flight state is never serialized (the
        # checkpoint cancelled it), so a resumed build starts quiescent
        # and re-plans from the restored frontier.  Pre-pipeline
        # snapshots resolve the new cfg knobs through the dataclass's
        # class-level defaults -- safe, because pipelining/speculation
        # are bit-invisible to the produced tree by construction.
        eng._pipe = BuildPipeline(eng)
        # Cache rows from pre-masking checkpoints lack the solved-delta
        # mask (8th element): every cell in them was actually solved.
        # Rows from pre-warm-start checkpoints lack the duals/slacks
        # (9th/10th): None = no donor data, midpoints of those vertices
        # simply start cold (the cache is a cache -- correctness is
        # unaffected, only the warm-start hit rate).
        for k, row in eng.cache._d.items():
            if len(row) == 7:
                eng.cache._d[k] = (*row, eng._full_mask, None, None)
            elif len(row) == 8:
                eng.cache._d[k] = (*row, None, None)
        eng._fb_oracle = None
        eng._oracle_s = 0.0
        eng._oracle_lock = threading.RLock()
        oracle.n_solves = snap.get("n_solves", 0)
        oracle.n_point_solves = snap.get("n_point_solves", 0)
        oracle.n_simplex_solves = snap.get("n_simplex_solves", 0)
        oracle.n_rescue_solves = snap.get("n_rescue_solves", 0)
        eng.obs = obs if obs is not None else obs_lib.from_config(eng.cfg)
        eng._owns_obs = obs is None
        if (eng.obs.enabled and getattr(oracle, "obs", None) is not None
                and not oracle.obs.enabled):
            oracle.obs = eng.obs
        eng._obs_t0 = time.perf_counter()
        # After the counter/tree restore above, so the first step's
        # solve delta and the regions_per_s gauge count THIS session's
        # work only.
        eng._prev_solves = oracle.n_solves
        eng._obs_regions0 = eng.tree.n_regions()
        eng._init_diagnostics()
        # Rebuild the open-simplex refcounts from the restored frontier and
        # drop cache rows no open simplex references (the snapshot may
        # predate their eviction).
        eng._refcount = collections.Counter()
        eng.device_frac_ema = 0.0
        # node -> vertex cache keys memo (see _keys): populated here for
        # the restored open set, dropped per node in _release.
        eng._node_keys = {}
        for n in eng.frontier:
            eng._retain(n)
        for k in list(eng.cache._d):
            if k not in eng._refcount:
                eng.cache.evict_key(k)
        return eng


def load_checkpoint(path: str, fallback: bool = True) -> dict:
    """Load a build checkpoint with integrity verification and
    previous-generation fallback (docs/robustness.md "Crash-safe
    writes").

    The primary path is verified against its content-checksum trailer
    (legacy stamp-less checkpoints load with a clear conscience --
    pickle-decodability is their only check); a truncated, torn, or
    bit-flipped file is REJECTED with ``atomic.CorruptArtifact`` and,
    when ``fallback`` is on, the ``.prev`` generation rotated aside by
    ``save_checkpoint`` is tried next (with a warning naming both
    files).  Only when no candidate loads does the error propagate --
    listing every file tried and why it was rejected, so the operator
    is never left diagnosing a bare UnpicklingError at 3 a.m."""
    tried: list[str] = []
    cands = [path] + ([path + ".prev"] if fallback else [])
    for p in cands:
        if not os.path.exists(p):
            tried.append(f"{p}: missing")
            continue
        try:
            obj, _checked = atomic.read_checked_pickle(p)
        except atomic.CorruptArtifact as e:
            tried.append(str(e))
            continue
        if not isinstance(obj, dict) or "tree" not in obj:
            tried.append(f"{p}: not a build checkpoint")
            continue
        if p != path:
            warnings.warn(
                f"checkpoint {path} is unusable "
                f"({tried[-1] if tried else 'missing'}); falling back "
                f"to the previous generation {p}", RuntimeWarning,
                stacklevel=2)
        return obj
    raise atomic.CorruptArtifact(
        "no valid checkpoint generation: " + "; ".join(tried))


def make_oracle(problem, cfg: PartitionConfig, mesh=None,
                strict: bool = False) -> Oracle:
    """The oracle choice, shared by build_partition and the CLI: honors
    cfg.backend / precision / IPM schedules, and routes through
    PrunedOracle when cfg.prune_rows is set.  Pruning covers batched
    single-device backends only; strict=True raises where it cannot take
    effect (the CLI surfaces the error), strict=False silently builds
    the plain oracle (the library default)."""
    kw = dict(backend=cfg.backend, mesh=mesh, precision=cfg.precision,
              point_schedule=getattr(cfg, "ipm_point_schedule", None),
              rescue_iter=getattr(cfg, "ipm_rescue_iters", 0),
              # The getattr FALLBACKS (reached only for pre-knob
              # pickled checkpoint cfgs) are conservative False: a
              # resumed old build must keep its original single-phase
              # cold-start semantics mid-build (resumed-equals-straight
              # parity), not silently adopt the new defaults.  Fresh
              # configs carry the dataclass defaults (True).
              two_phase=getattr(cfg, "ipm_two_phase", False),
              phase1_iters=getattr(cfg, "ipm_phase1_iters", None),
              phase1_iters_point=getattr(cfg, "ipm_phase1_iters_point",
                                         None),
              phase1_iters_simplex=getattr(cfg, "ipm_phase1_iters_simplex",
                                           None),
              warm_start=getattr(cfg, "warm_start_tree", False),
              # Pre-tier pickled cfgs (no ipm_kernel field) keep the
              # XLA reference path, like the other conservative
              # fallbacks above.
              ipm_kernel=getattr(cfg, "ipm_kernel", "xla"))
    if getattr(cfg, "prune_rows", False):
        if cfg.backend == "serial" or mesh is not None:
            if strict:
                raise ValueError(
                    "--prune-rows cannot take effect with --mesh or "
                    "--backend serial (pruning covers batched "
                    "single-device backends only)")
        else:
            from explicit_hybrid_mpc_tpu.oracle.prune import PrunedOracle

            return PrunedOracle(problem, **kw)
    return Oracle(problem, **kw)


def build_partition(problem, cfg: PartitionConfig,
                    oracle: Oracle | None = None,
                    obs: "obs_lib.Obs | None" = None) -> PartitionResult:
    """One-call offline build: problem + config -> certified partition.

    cfg.rebuild_from routes through the incremental warm rebuild
    (partition/rebuild.py): the named prior tree/checkpoint is
    transferred, bulk re-certified, and only invalidated leaves are
    re-subdivided -- same result contract, fraction of the solves."""
    if oracle is None:
        oracle = make_oracle(problem, cfg)
    if getattr(cfg, "rebuild_from", None):
        from explicit_hybrid_mpc_tpu.partition.rebuild import warm_rebuild

        return warm_rebuild(
            problem, cfg, cfg.rebuild_from, oracle=oracle, obs=obs,
            log=RunLog(cfg.log_path, echo=False),
            strict_provenance=getattr(cfg, "rebuild_strict_provenance",
                                      False))
    log = RunLog(cfg.log_path, echo=False)
    return FrontierEngine(problem, oracle, cfg, log, obs=obs).run()
