"""Breadth-first frontier partition engine.

This is the TPU-native inversion of the reference's distributed runtime
(SURVEY.md sections 3-4, [M-high]): where the reference runs an MPI task
farm (scheduler rank + workers recursing depth-first with one serial Gurobi
solve at a time), here the open leaves form a HOST-SIDE FRONTIER and each
step issues ONE batched device program covering every unsolved vertex of
every frontier simplex (BASELINE.json north-star: "the simplex-tree
subdivision loop becomes a breadth-first frontier").

Per step:
  1. pop up to cfg.batch_simplices open simplices;
  2. dedupe their vertices against the solve cache (bisection shares
     vertices between siblings/neighbours -- caching preserves the
     reference's work complexity);
  3. one vmapped oracle call for all new vertices x all commutations;
  4. host-side certificates (cheap numpy, certify.py); commutations with no
     converged vertex trigger a second batched device call (exact simplex
     minima / infeasibility exclusion);
  5. converged leaves stream into the Tree; bisected children re-enter the
     frontier.

Termination: frontier empty (all leaves certified / infeasible / depth-
capped).  The frontier + cache + tree snapshot to disk every
cfg.checkpoint_every steps and any run can resume (SURVEY.md section 6.4).
"""

from __future__ import annotations

import collections
import pickle
import time

import numpy as np

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle, VertexSolution
from explicit_hybrid_mpc_tpu.partition import certify, geometry
from explicit_hybrid_mpc_tpu.partition.tree import LeafData, Tree
from explicit_hybrid_mpc_tpu.utils.logging import RunLog


class VertexCache:
    """vertex -> oracle solution row, keyed by rounded coordinates."""

    def __init__(self):
        self._d: dict[bytes, tuple] = {}

    def __contains__(self, v: np.ndarray) -> bool:
        return geometry.vertex_key(v) in self._d

    def get(self, v: np.ndarray) -> tuple:
        return self._d[geometry.vertex_key(v)]

    def put(self, v: np.ndarray, row: tuple) -> None:
        self._d[geometry.vertex_key(v)] = row

    def __len__(self) -> int:
        return len(self._d)


class PartitionResult:
    def __init__(self, tree: Tree, roots: list[int], stats: dict):
        self.tree = tree
        self.roots = roots
        self.stats = stats


class FrontierEngine:
    def __init__(self, problem, oracle: Oracle, cfg: PartitionConfig,
                 log: RunLog | None = None):
        self.problem = problem
        self.oracle = oracle
        self.cfg = cfg
        self.log = log or RunLog(cfg.log_path, echo=False)
        p = problem.n_theta
        self.tree = Tree(p=p, n_u=problem.n_u)
        self.roots = [self.tree.add_root(V) for V in
                      geometry.box_triangulation(
                          problem.theta_lb, problem.theta_ub,
                          getattr(problem, "root_splits", None))]
        self.frontier: collections.deque[int] = collections.deque(self.roots)
        self.cache = VertexCache()
        self.steps = 0
        self.n_uncertified = 0

    # -- vertex solves -----------------------------------------------------

    def _solve_missing(self, nodes: list[int]) -> None:
        missing: list[np.ndarray] = []
        seen: set[bytes] = set()
        for n in nodes:
            for v in self.tree.vertices[n]:
                k = geometry.vertex_key(v)
                if k not in seen and v not in self.cache:
                    seen.add(k)
                    missing.append(v)
        if not missing:
            return
        thetas = np.stack(missing)
        sol = self.oracle.solve_vertices(thetas)
        for i, v in enumerate(missing):
            self.cache.put(v, (sol.V[i], sol.conv[i], sol.grad[i],
                               sol.u0[i], sol.z[i], sol.Vstar[i],
                               sol.dstar[i]))

    def _vertex_data(self, node: int) -> certify.SimplexVertexData:
        verts = self.tree.vertices[node]
        rows = [self.cache.get(v) for v in verts]
        return certify.SimplexVertexData(
            verts=verts,
            V=np.stack([r[0] for r in rows]),
            conv=np.stack([r[1] for r in rows]),
            grad=np.stack([r[2] for r in rows]),
            u0=np.stack([r[3] for r in rows]),
            z=np.stack([r[4] for r in rows]),
            Vstar=np.array([r[5] for r in rows]),
            dstar=np.array([r[6] for r in rows]),
        )

    # -- one frontier step -------------------------------------------------

    def step(self) -> None:
        B = min(len(self.frontier), self.cfg.batch_simplices)
        nodes = [self.frontier.popleft() for _ in range(B)]
        self._solve_missing(nodes)

        results: dict[int, certify.CertificateResult] = {}
        stage2: list[tuple[int, int]] = []  # (node, delta')
        sds: dict[int, certify.SimplexVertexData] = {}
        infeas_candidates: list[int] = []
        for n in nodes:
            sd = self._vertex_data(n)
            sds[n] = sd
            if self.cfg.algorithm == "feasible":
                res = certify.certify_feasible(sd)
            else:
                res = certify.certify_suboptimal_stage1(
                    sd, self.cfg.eps_a, self.cfg.eps_r)
            results[n] = res
            if res.status == "pending":
                stage2.extend((n, int(d)) for d in res.pending_deltas)
            elif res.status == "infeasible":
                infeas_candidates.append(n)

        if infeas_candidates:
            # All vertices infeasible does NOT imply the simplex is (the
            # hybrid feasible set is a union over commutations, not
            # convex): require positive phase-1 evidence that EVERY
            # commutation is infeasible on the whole simplex; otherwise
            # split to hunt for the interior feasible pocket.
            nd = self.oracle.can.n_delta
            reqs = [(n, d) for n in infeas_candidates for d in range(nd)]
            Ms = np.stack([geometry.barycentric_matrix(self.tree.vertices[n])
                           for n, _ in reqs])
            ds = np.array([d for _, d in reqs], dtype=np.int64)
            _t, _feas, infeas_cert = self.oracle.simplex_feasibility(Ms, ds)
            empty_on_R = collections.defaultdict(lambda: True)
            for (n, _), ok in zip(reqs, infeas_cert):
                empty_on_R[n] &= bool(ok)
            for n in infeas_candidates:
                if not empty_on_R[n]:
                    results[n] = certify.CertificateResult(status="split")
                # else keep 'infeasible': certified empty on R

        if stage2:
            Ms = np.stack([geometry.barycentric_matrix(self.tree.vertices[n])
                           for n, _ in stage2])
            ds = np.array([d for _, d in stage2], dtype=np.int64)
            Vmin, _feas = self.oracle.solve_simplex_min(Ms, ds)
            per_node: dict[int, dict[int, float]] = collections.defaultdict(dict)
            for (n, d), vm in zip(stage2, Vmin):
                per_node[n][d] = float(vm)
            for n, vm in per_node.items():
                results[n] = certify.certify_suboptimal_stage2(
                    sds[n], results[n], vm, self.cfg.eps_a, self.cfg.eps_r)

        n_leaves = n_splits = 0
        for n in nodes:
            res = results[n]
            if res.status == "certified":
                self.tree.set_leaf(n, LeafData(
                    delta_idx=res.delta_idx,
                    vertex_inputs=res.vertex_inputs,
                    vertex_costs=res.vertex_costs,
                    vertex_z=res.vertex_z))
                n_leaves += 1
            elif res.status == "infeasible":
                pass  # leaf with no data: outside the feasible region
            else:  # split
                if self.tree.depth[n] >= self.cfg.max_depth:
                    # Depth cap: accept the best available candidate as an
                    # UNcertified best-effort leaf, flag it in stats.
                    self.n_uncertified += 1
                    sd = sds[n]
                    d = certify.best_feasible_candidate(sd)
                    if d is not None:
                        self.tree.set_leaf(n, LeafData(
                            delta_idx=d, vertex_inputs=sd.u0[:, d, :],
                            vertex_costs=sd.V[:, d],
                            vertex_z=sd.z[:, d, :]))
                    continue
                left, right, i, j, _ = geometry.bisect(self.tree.vertices[n])
                li, ri = self.tree.split(n, left, right, (i, j))
                self.frontier.append(li)
                self.frontier.append(ri)
                n_splits += 1

        self.steps += 1
        self.log.emit(step=self.steps, frontier=len(self.frontier),
                      batch=B, leaves=n_leaves, splits=n_splits,
                      regions=self.tree.n_regions(),
                      solves=self.oracle.n_solves,
                      cached_vertices=len(self.cache))

    # -- full run ----------------------------------------------------------

    def run(self) -> PartitionResult:
        t0 = time.perf_counter()
        while self.frontier and self.steps < self.cfg.max_steps:
            self.step()
            if (self.cfg.checkpoint_every
                    and self.steps % self.cfg.checkpoint_every == 0
                    and self.cfg.checkpoint_path):
                self.save_checkpoint(self.cfg.checkpoint_path)
        wall = time.perf_counter() - t0
        stats = {
            "regions": self.tree.n_regions(),
            "tree_nodes": len(self.tree),
            "max_depth": self.tree.max_depth(),
            "steps": self.steps,
            "oracle_solves": self.oracle.n_solves,
            "uncertified": self.n_uncertified,
            # Non-empty frontier here means the run hit max_steps: the
            # remaining simplices are UNCOVERED holes, not a complete
            # partition -- callers must check this.
            "truncated": len(self.frontier) > 0,
            "frontier_left": len(self.frontier),
            "wall_s": wall,
            "regions_per_s": self.tree.n_regions() / max(wall, 1e-9),
        }
        self.log.emit(done=True, **stats)
        return PartitionResult(self.tree, self.roots, stats)

    # -- checkpoint / resume (SURVEY.md section 6.4) -----------------------

    def save_checkpoint(self, path: str) -> None:
        with open(path, "wb") as f:
            pickle.dump({
                "tree": self.tree, "roots": self.roots,
                "frontier": list(self.frontier),
                "cache": self.cache._d, "steps": self.steps,
                "n_uncertified": self.n_uncertified,
                "n_solves": self.oracle.n_solves,
                "cfg": self.cfg,
            }, f, protocol=pickle.HIGHEST_PROTOCOL)

    @classmethod
    def resume(cls, path: str, problem, oracle: Oracle,
               log: RunLog | None = None) -> "FrontierEngine":
        with open(path, "rb") as f:
            snap = pickle.load(f)
        eng = cls.__new__(cls)
        eng.problem = problem
        eng.oracle = oracle
        eng.cfg = snap["cfg"]
        eng.log = log or RunLog(eng.cfg.log_path, echo=False)
        eng.tree = snap["tree"]
        eng.roots = snap["roots"]
        eng.frontier = collections.deque(snap["frontier"])
        eng.cache = VertexCache()
        eng.cache._d = snap["cache"]
        eng.steps = snap["steps"]
        eng.n_uncertified = snap["n_uncertified"]
        oracle.n_solves = snap.get("n_solves", 0)
        return eng


def build_partition(problem, cfg: PartitionConfig,
                    oracle: Oracle | None = None) -> PartitionResult:
    """One-call offline build: problem + config -> certified partition."""
    oracle = oracle or Oracle(problem, backend=cfg.backend,
                              precision=cfg.precision)
    log = RunLog(cfg.log_path, echo=False)
    return FrontierEngine(problem, oracle, cfg, log).run()
