"""Simplex-wide eps-suboptimality and feasibility certificates.

The certificate decides, for a leaf-candidate simplex R = conv{v_0..v_p} and
a candidate commutation delta, whether the barycentric-interpolated control
law with commutation delta is certified feasible and eps-suboptimal over ALL
of R (SURVEY.md section 1 step 2b, [P]; section 8 "hard parts" item 5).

Mathematical basis (each bound is sound; derivations in docs/certificates.md):

U  (upper bound on the implemented cost):  V_delta is convex in theta, so the
   affine interpolation of vertex values over-approximates it on R:
   V_delta(theta) <= U(theta) := sum_i lam_i(theta) V_delta(v_i).

L  (lower bound on the optimal cost V* = min_delta' V_delta'):
   for every commutation delta' and any vertex v_i where the fixed-delta'
   QP converged, the envelope-theorem tangent
       l_{delta',i}(theta) = V_delta'(v_i) + g_delta'(v_i)'(theta - v_i)
   under-approximates the convex V_delta' GLOBALLY (off its feasible set
   V_delta' = +inf, so the bound holds trivially).  Hence
       V*(theta) >= min_delta' max_i l_{delta',i}(theta).
   For a delta' converged at NO vertex, the engine asks the oracle for the
   certified lower bound on min_{theta in R} V_delta'(theta) (an elastic
   joint QP over
   (z, theta)), a constant valid lower bound on R -- or a proof that delta'
   is infeasible on all of R, excluding it from the min.

Gap (all evaluated at vertices only -- affine functions on a simplex attain
their extrema at vertices):
   max_R [U - L] <= max_delta' min_i max_j [U(v_j) - l_{delta',i}(v_j)].
The certificate passes when this gap is <= eps_a (absolute) or
<= eps_r * min_j |V*(v_j)| (relative), matching the reference's eps_a/eps_r
pair (SURVEY.md section 1, [NS] "absolute (eps_a) or relative (eps_r)
suboptimality test").

Feasibility over R is inherited from the vertices: the feasible set of the
fixed-delta problem is convex in theta (projection of a polyhedron), so
delta feasible at every vertex implies feasible on conv{v_i} = R.
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass
class SimplexVertexData:
    """Oracle results at the p+1 vertices of one simplex (host numpy)."""

    verts: np.ndarray   # (p+1, p)
    V: np.ndarray       # (p+1, nd) +inf where not converged
    conv: np.ndarray    # (p+1, nd) bool
    grad: np.ndarray    # (p+1, nd, p)
    u0: np.ndarray      # (p+1, nd, n_u)
    z: np.ndarray       # (p+1, nd, nz)
    Vstar: np.ndarray   # (p+1,)
    dstar: np.ndarray   # (p+1,)


@dataclasses.dataclass
class CertificateResult:
    status: str                 # 'certified' | 'split' | 'infeasible' | 'pending'
    delta_idx: int = -1
    vertex_inputs: np.ndarray | None = None
    vertex_costs: np.ndarray | None = None
    vertex_z: np.ndarray | None = None
    gap: float = np.inf
    # Commutations needing a stage-2 simplex-min solve (converged nowhere).
    pending_deltas: np.ndarray | None = None
    # True on a 'split' caused by MIXED vertex feasibility (the hybrid
    # feasible set's boundary crosses R): no whole-simplex certificate can
    # ever close such a cell, so the frontier may instead close it as a
    # semi-explicit boundary leaf (cfg.semi_explicit_boundary_depth).
    mixed_feasibility: bool = False
    # Internal: stage-1 partial gaps, completed by stage 2.
    _stage1_gap: np.ndarray | None = None
    _candidates: np.ndarray | None = None


def cell_snapshot(sd: SimplexVertexData) -> dict[str, np.ndarray]:
    """Canonical array packaging of one simplex's certification inputs
    -- THE serialization repro bundles use for cell-level anomalies
    (uncertified depth-capped leaves, obs/recorder.py).  Everything the
    certificate read is here: replaying the vertex solves against
    ``cell_verts`` and re-running the stage-1 certificate over this
    snapshot reproduces the certify/split decision exactly."""
    return {"cell_verts": np.asarray(sd.verts),
            "obs_V": np.asarray(sd.V),
            "obs_conv": np.asarray(sd.conv, dtype=bool),
            "obs_grad": np.asarray(sd.grad),
            "obs_Vstar": np.asarray(sd.Vstar),
            "obs_dstar": np.asarray(sd.dstar, dtype=np.int64)}


def candidate_set(sd: SimplexVertexData) -> np.ndarray:
    """Vertex-optimal commutations, deterministic ascending order
    (SURVEY.md section 4.1: candidate delta from vertex solutions)."""
    ds = sd.dstar[sd.dstar >= 0]
    return np.unique(ds)


def best_feasible_candidate(sd: SimplexVertexData) -> int | None:
    """Lowest-total-vertex-cost commutation among vertex-optimal candidates
    that converged at EVERY vertex; None if there is none.  Deterministic
    (ascending candidate order, argmin takes the first minimum) -- shared by
    the feasibility-variant leaf rule and the depth-cap best-effort leaf so
    backend parity cannot diverge between them."""
    cands = candidate_set(sd)
    cands = cands[np.all(sd.conv[:, cands], axis=0)]
    if cands.size == 0:
        return None
    tot = np.array([np.sum(sd.V[:, int(d)]) for d in cands])
    return int(cands[int(np.argmin(tot))])


def boundary_candidate(sd: SimplexVertexData) -> int | None:
    """Commutation stored by a semi-explicit BOUNDARY leaf (mixed vertex
    feasibility; round-3 verdict item 4).

    Chooses the commutation converged at the MOST vertices (maximizing
    the convex-hull sub-region where offline vertex feasibility +
    convexity already guarantee the online fixed-delta QP succeeds);
    ties break to the lowest mean cost over converged vertices, then the
    lowest index.  Deterministic, so backend/tree parity is preserved.
    None when no commutation converged at any vertex.
    """
    n_conv = sd.conv.sum(axis=0)
    if n_conv.max(initial=0) == 0:
        return None
    cand = np.where(n_conv == n_conv.max())[0]
    means = np.array([float(np.mean(sd.V[sd.conv[:, d], d]))
                      for d in cand])
    return int(cand[int(np.argmin(means))])


def boundary_payload(sd: SimplexVertexData, d: int
                     ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Finite leaf payload (u0, V, z) for a semi-explicit boundary leaf.

    Vertices where commutation d did not converge hold +inf costs and
    garbage inputs; they are filled deterministically (inputs/z with the
    mean over converged vertices, costs with the converged max) so the
    exported table stays finite.  The fills only feed the online FALLBACK
    interpolation -- the boundary leaf's primary online path is the
    fixed-delta QP (sim.SemiExplicitController), which never reads them
    when it converges.
    """
    conv = sd.conv[:, d]
    u = sd.u0[:, d, :].copy()
    z = sd.z[:, d, :].copy()
    V = sd.V[:, d].copy()
    u[~conv] = u[conv].mean(axis=0)
    z[~conv] = z[conv].mean(axis=0)
    V[~conv] = V[conv].max()
    return u, V, z


def tangent_gaps(sd: SimplexVertexData, U: np.ndarray) -> np.ndarray:
    """gap_{delta'} = min_i max_j [U_j - l_{delta',i}(v_j)] for all delta'.

    Returns (nd,); NaN where delta' converged at no vertex (stage 2 needed).
    U is (p+1,) -- the candidate's vertex costs.
    """
    # tangents[i, j, d] = V[i, d] + grad[i, d] . (v_j - v_i)
    dv = sd.verts[None, :, :] - sd.verts[:, None, :]      # (p+1, p+1, p)
    # Unconverged cells hold V=+inf with garbage grad (possibly inf/nan,
    # e.g. masked-skip fabrications): inf arithmetic raises 'invalid
    # value' warnings, yet every such lane is overwritten by the conv
    # mask below.
    with np.errstate(invalid="ignore"):
        t = sd.V[:, None, :] + np.einsum("ijk,idk->ijd", dv, sd.grad)
        slack = U[None, :, None] - t                      # (i, j, d)
        worst = np.max(slack, axis=1)                     # (i, d) max over j
    worst = np.where(sd.conv, worst, np.inf)              # only valid tangents
    gap = np.min(worst, axis=0)                           # (d,) min over i
    none_conv = ~np.any(sd.conv, axis=0)
    return np.where(none_conv, np.nan, gap)


def _passes(gap: float, Vstar_verts: np.ndarray, eps_a: float,
            eps_r: float) -> bool:
    if eps_a > 0 and gap <= eps_a:
        return True
    if eps_r > 0 and gap <= eps_r * float(np.min(np.abs(Vstar_verts))):
        return True
    return False


def cert_margin(gap: float, Vstar_verts: np.ndarray, eps_a: float,
                eps_r: float) -> float | None:
    """Certificate slack: effective eps budget minus the certified gap
    (>= 0 whenever ``_passes`` held).  The budget is the LARGEST
    enabled bound -- passing under either eps_a or eps_r means the
    slack against the looser one is what a precision change must not
    consume.  None when no budget is enabled or the gap is not finite
    (a -inf stage-1 gap means the candidate dominates outright; there
    is no meaningful scalar slack to histogram).

    Feeds ``build.cert_margin`` (frontier.py) -- the evidence base for
    ROADMAP item 4's "f32 iterative refinement suffices": if the p01
    margin dwarfs the f32 round-off on V, a lower-precision refine
    cannot flip a certificate."""
    budget = -np.inf
    if eps_a > 0:
        budget = eps_a
    if eps_r > 0:
        budget = max(budget,
                     eps_r * float(np.min(np.abs(Vstar_verts))))
    margin = budget - gap
    if not np.isfinite(margin):
        return None
    return float(margin)


def certify_suboptimal_stage1(sd: SimplexVertexData, eps_a: float,
                              eps_r: float) -> CertificateResult:
    """Vertex-data-only certification attempt.

    Outcomes: 'infeasible' (no commutation valid at any vertex),
    'certified', 'split' (a candidate exists but its gap from complete
    stage-1 information already exceeds eps), or 'pending' (gap depends on
    commutations with no converged vertex -> stage-2 simplex-min solves).
    """
    feas_vertex = sd.dstar >= 0
    if not np.any(feas_vertex):
        return CertificateResult(status="infeasible")
    if not np.all(feas_vertex):
        # Mixed feasibility: the feasible/infeasible boundary crosses R.
        return CertificateResult(status="split", mixed_feasibility=True)

    cands = candidate_set(sd)
    # Candidates must be feasible (converged) at every vertex to define U.
    cands = cands[np.all(sd.conv[:, cands], axis=0)]
    if cands.size == 0:
        return CertificateResult(status="split")

    nd = sd.V.shape[1]
    pending = np.zeros(nd, dtype=bool)
    best = None  # (gap, delta, U)
    stage1 = np.full((len(cands), nd), np.nan)
    for ci, d in enumerate(cands):
        U = sd.V[:, int(d)]
        gaps = tangent_gaps(sd, U)
        stage1[ci] = gaps
        nan = np.isnan(gaps)
        pending |= nan
        g = np.max(np.where(nan, -np.inf, gaps))
        if not np.any(nan):
            if best is None or g < best[0]:
                best = (float(g), int(d), U)
    if not np.any(pending):
        if best is not None and _passes(best[0], sd.Vstar, eps_a, eps_r):
            d = best[1]
            return CertificateResult(
                status="certified", delta_idx=d,
                vertex_inputs=sd.u0[:, d, :], vertex_costs=sd.V[:, d],
                vertex_z=sd.z[:, d, :], gap=best[0])
        return CertificateResult(status="split",
                                 gap=best[0] if best else np.inf)
    return CertificateResult(status="pending",
                             pending_deltas=np.where(pending)[0],
                             _stage1_gap=stage1, _candidates=cands)


def certify_stage1_batch(verts: np.ndarray, V: np.ndarray,
                         conv: np.ndarray, grad: np.ndarray,
                         Vstar: np.ndarray, dstar: np.ndarray,
                         eps_a: float, eps_r: float
                         ) -> list[CertificateResult]:
    """Vectorized certify_suboptimal_stage1 over a batch of B simplices.

    Shapes: verts (B, m, p), V/conv (B, m, nd), grad (B, m, nd, p),
    Vstar/dstar (B, m).  Decision-identical to the scalar function node
    by node (tests/test_partition.py asserts it on random batches and
    end-to-end); it exists because the scalar path's per-node Python
    loops (a tangent einsum per (node, candidate)) dominated host-side
    certification time in steady-state profiles.

    Memory note: the slack tensor is (B, C, m, m, nd) where C is the
    batch's max candidate count -- candidates are the few vertex-optimal
    commutations (C << nd), which keeps the tensor a few MB at the
    shipping batch sizes rather than the (B, nd, m, m, nd) a dense
    formulation would need.
    """
    B, m, nd = V.shape
    results: list[CertificateResult | None] = [None] * B
    feas_vertex = dstar >= 0                          # (B, m)
    feas_any = feas_vertex.any(axis=1)
    feas_all = feas_vertex.all(axis=1)
    for b in np.where(~feas_any)[0]:
        results[b] = CertificateResult(status="infeasible")
    for b in np.where(feas_any & ~feas_all)[0]:
        results[b] = CertificateResult(status="split",
                                       mixed_feasibility=True)
    todo = np.where(feas_all)[0]
    if todo.size == 0:
        return results

    # Candidate sets: vertex-optimal commutations converged at EVERY
    # vertex, in ascending order per node (matches candidate_set +
    # the conv filter in the scalar path).
    dmask = np.zeros((B, nd), dtype=bool)
    np.put_along_axis(dmask, np.maximum(dstar, 0),
                      feas_vertex, axis=1)            # d in dstar set
    cand_mask = dmask & conv.all(axis=1)              # (B, nd)
    n_c = cand_mask[todo].sum(axis=1)
    for b in todo[n_c == 0]:
        results[b] = CertificateResult(status="split")
    todo = todo[n_c > 0]
    if todo.size == 0:
        return results
    C = int(cand_mask[todo].sum(axis=1).max())
    # Padded candidate index list (B', C), -1 = empty slot.
    cand_idx = np.full((todo.size, C), -1, dtype=np.int64)
    for r, b in enumerate(todo):                      # cheap: B' rows
        ds = np.where(cand_mask[b])[0]
        cand_idx[r, :ds.size] = ds
    slot = cand_idx >= 0                              # (B', C)
    safe_idx = np.maximum(cand_idx, 0)

    vb = verts[todo]                                  # (B', m, p)
    Vb, convb, gradb = V[todo], conv[todo], grad[todo]
    # tangents[b, i, j, d] = V[b,i,d] + grad[b,i,d,:].(v_j - v_i)
    dv = vb[:, None, :, :] - vb[:, :, None, :]        # (B', i, j, p)
    with np.errstate(invalid="ignore"):
        t = Vb[:, :, None, :] + np.einsum("bijk,bidk->bijd", dv, gradb)
        # U[b, c, j] = V[b, j, cand c]
        U = np.take_along_axis(
            Vb, safe_idx[:, None, :], axis=2).transpose(0, 2, 1)
        slack = U[:, :, None, :, None] - t[:, None, :, :, :]
        worst = np.max(slack, axis=3)                 # (B', C, i, d)
    worst = np.where(convb[:, None, :, :], worst, np.inf)
    gaps = np.min(worst, axis=2)                      # (B', C, d)
    none_conv = ~convb.any(axis=1)                    # (B', d)
    gaps = np.where(none_conv[:, None, :], np.nan, gaps)

    pending = none_conv.any(axis=1)                   # (B',)
    # Nodes with pending deltas: hand stage-2 the per-candidate partial
    # gaps exactly as the scalar path does.
    for r in np.where(pending)[0]:
        b = todo[r]
        cands = cand_idx[r][slot[r]]
        results[b] = CertificateResult(
            status="pending", pending_deltas=np.where(none_conv[r])[0],
            _stage1_gap=gaps[r][slot[r]], _candidates=cands)
    # Complete nodes: best candidate by max-over-deltas gap (first
    # minimum among slots = ascending candidate order, matching the
    # scalar path's strict-< update).
    done = np.where(~pending)[0]
    if done.size:
        g = np.max(gaps[done], axis=2)                # (D, C)
        g = np.where(slot[done], g, np.inf)
        ci = np.argmin(g, axis=1)
        gbest = g[np.arange(done.size), ci]
        for k, r in enumerate(done):
            b = todo[r]
            gk = float(gbest[k])
            d = int(cand_idx[r, ci[k]])
            if _passes(gk, Vstar[b], eps_a, eps_r):
                results[b] = CertificateResult(
                    status="certified", delta_idx=d,
                    vertex_inputs=None, vertex_costs=V[b, :, d],
                    vertex_z=None, gap=gk)
            else:
                results[b] = CertificateResult(status="split", gap=gk)
    return results


def certify_suboptimal_stage2(sd: SimplexVertexData, res: CertificateResult,
                              Vmin: dict[int, float], eps_a: float,
                              eps_r: float) -> CertificateResult:
    """Complete a 'pending' certification with stage-2 simplex minima.

    Vmin maps pending delta' -> certified lower bound on V_delta' over R
    (exact when the elastic slack is zero; +inf if delta'
    infeasible on all of R; -inf if the joint solve failed, blocking
    certification conservatively).
    """
    best = None
    for ci, d in enumerate(res._candidates):
        gaps = res._stage1_gap[ci]
        U = sd.V[:, int(d)]
        g = -np.inf
        for dp in range(gaps.size):
            if np.isnan(gaps[dp]):
                lo = Vmin[dp]
                gd = -np.inf if lo == np.inf else float(np.max(U) - lo)
            else:
                gd = gaps[dp]
            g = max(g, gd)
        if best is None or g < best[0]:
            best = (float(g), int(d))
    if best is not None and _passes(best[0], sd.Vstar, eps_a, eps_r):
        d = best[1]
        return CertificateResult(
            status="certified", delta_idx=d, vertex_inputs=sd.u0[:, d, :],
            vertex_costs=sd.V[:, d], vertex_z=sd.z[:, d, :], gap=best[0])
    return CertificateResult(status="split", gap=best[0] if best else np.inf)


def recertify_stored_stage1(sd: SimplexVertexData, delta_idx: int,
                            eps_a: float, eps_r: float
                            ) -> CertificateResult:
    """Re-certification of a leaf's ALREADY-STORED commutation against
    fresh oracle data (the warm-rebuild keep-check, partition/rebuild).

    Unlike certify_suboptimal_stage1 this fixes the candidate to the
    leaf's stored ``delta_idx`` -- the question is not "can some law
    certify here" but "does the law this leaf already serves still
    carry its eps-certificate".  The bound mathematics is identical
    (same U from the stored delta's vertex costs, same tangent lower
    envelope over every commutation), so a pass is exactly as sound as
    the cold build's certificate; the stored delta need not be
    vertex-optimal under the revised problem for the pass to be valid
    (any delta converged at every vertex defines a valid U).

    Outcomes: 'certified' (keep the leaf untouched), 'split'
    (invalidated -- re-open into the frontier), or 'pending' with
    ``pending_deltas`` (stage-2 simplex bounds needed; complete via
    certify_suboptimal_stage2, which accepts this result's
    single-candidate ``_candidates``/``_stage1_gap`` directly)."""
    d = int(delta_idx)
    if d < 0 or not bool(np.all(sd.conv[:, d])):
        # Stored law no longer converges at every vertex: U is not a
        # valid upper bound anywhere on R -- certificate gone.
        return CertificateResult(status="split")
    U = sd.V[:, d]
    gaps = tangent_gaps(sd, U)
    nan = np.isnan(gaps)
    if np.any(nan):
        return CertificateResult(
            status="pending", pending_deltas=np.where(nan)[0],
            _stage1_gap=gaps[None], _candidates=np.asarray([d]))
    g = float(np.max(gaps))
    if _passes(g, sd.Vstar, eps_a, eps_r):
        return CertificateResult(
            status="certified", delta_idx=d, vertex_inputs=sd.u0[:, d, :],
            vertex_costs=sd.V[:, d], vertex_z=sd.z[:, d, :], gap=g)
    return CertificateResult(status="split", gap=g)


def recertify_stored_stage2(stage1_gaps: np.ndarray, U_max: float,
                            Vstar: np.ndarray, Vmin: dict,
                            eps_a: float, eps_r: float
                            ) -> tuple[bool, float]:
    """Complete a recertify_stored_stage1 'pending' verdict with
    stage-2 lower bounds; returns (passes, gap).

    Same bound algebra as certify_suboptimal_stage2 restricted to the
    single stored candidate: a NaN stage-1 gap (delta' converged at no
    vertex) is replaced by ``U_max - Vmin[dp]`` (+inf Vmin = certified
    exclusion contributes -inf; -inf Vmin = stalled solve contributes
    +inf, conservatively blocking the keep).  ``Vmin`` entries may be
    ANCESTOR-simplex bounds (warm rebuild lifts stage-2 solves up the
    tree): a lower bound on a superset is a lower bound on the leaf,
    so a PASS is sound with loose bounds -- a FAIL is inconclusive and
    the caller re-solves exactly, mirroring the frontier's round A/B."""
    g = -np.inf
    for dp in range(stage1_gaps.size):
        if np.isnan(stage1_gaps[dp]):
            lo = Vmin[dp]
            gd = -np.inf if lo == np.inf else float(U_max - lo)
        else:
            gd = float(stage1_gaps[dp])
        g = max(g, gd)
    return _passes(g, Vstar, eps_a, eps_r), g


def recertify_infeasible(sd: SimplexVertexData) -> str:
    """Vertex-level re-check of a closed INFEASIBLE leaf (warm rebuild):
    'split' when any vertex became feasible under the revised problem
    (the emptiness proof is void -- re-open), 'pending' otherwise (all
    vertices still infeasible; the whole-simplex Farkas certificates
    must be re-established per commutation, exactly as the cold build's
    infeasible path does)."""
    return "pending" if not np.any(sd.dstar >= 0) else "split"


def certify_feasible(sd: SimplexVertexData) -> CertificateResult:
    """Feasibility-only ('feasible'/ECC) certification: a commutation
    feasible at every vertex is feasible on all of R (convexity); the leaf
    stores it and the online stage solves a small fixed-delta QP
    (semi-explicit, SURVEY.md section 1 variant 'ecc' [P])."""
    feas_vertex = sd.dstar >= 0
    if not np.any(feas_vertex):
        return CertificateResult(status="infeasible")
    if not np.all(feas_vertex):
        return CertificateResult(status="split", mixed_feasibility=True)
    d = best_feasible_candidate(sd)
    if d is None:
        return CertificateResult(status="split")
    return CertificateResult(status="certified", delta_idx=d,
                             vertex_inputs=sd.u0[:, d, :],
                             vertex_costs=sd.V[:, d],
                             vertex_z=sd.z[:, d, :], gap=0.0)
