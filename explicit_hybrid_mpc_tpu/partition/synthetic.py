"""Vectorized synthetic partition trees (export / serving scale tests).

Building a million-leaf tree through the real engine takes hours of
oracle solves; the export and serving layers, though, only care about
the TREE -- its geometry, hyperplanes, and leaf payloads.  This module
grows a balanced longest-edge-bisection tree with a synthetic linear
control law ONE LEVEL AT A TIME, each level as a handful of vectorized
numpy passes over every leaf at once (~2 s for 2^20 leaves, vs minutes
through per-node Tree.split calls), writing the columnar storage
directly.

Fidelity contract (tests/test_export_scale.py pins it on a small tree):
the result is bit-identical to the same tree built through
geometry.bisect + Tree.split + Tree.set_leaf -- same edge selection
(the relative-margin longest-edge tie-break of geometry.longest_edge,
vectorized), same midpoint arithmetic, same split-time hyperplanes --
so anything proven on a synthetic tree transfers to engine-built ones.
"""

from __future__ import annotations

import numpy as np

from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.tree import (_F_CERTIFIED, _F_DATA,
                                                    Tree)


def _longest_edges(V: np.ndarray) -> np.ndarray:
    """(K, 2) longest-edge (i, j) per simplex: geometry.longest_edge's
    sequential relative-margin scan, vectorized over the batch (the
    pair loop is over the (p+1)p/2 index pairs, not the K simplices)."""
    K, m, _ = V.shape
    D = V[:, :, None, :] - V[:, None, :, :]
    d2 = np.einsum("kijp,kijp->kij", D, D)
    best_d = np.full(K, -1.0)
    best = np.zeros((K, 2), dtype=np.int64)
    for i in range(m):
        for j in range(i + 1, m):
            d = d2[:, i, j]
            upd = d > best_d * (1.0 + 1e-12)
            best_d[upd] = d[upd]
            best[upd] = (i, j)
    return best


def leaf_payload(V: np.ndarray, n_u: int
                 ) -> tuple[np.ndarray, np.ndarray]:
    """Synthetic vertex payloads for leaf vertex matrices V (K, m, p):
    a fixed linear law u(theta) = A theta (exactly reproduced by
    barycentric interpolation, so evaluator cross-checks stay exact)
    and cost V(theta) = sum(theta).  Returns (U (K, m, n_u), c (K, m))."""
    p = V.shape[2]
    A = (np.arange(n_u)[:, None] + 1.0) * (np.arange(p)[None, :] + 1.0)
    return np.einsum("kmp,up->kmu", V, A), V.sum(axis=2)


def build_synthetic_tree(p: int = 2, depth: int = 10, n_u: int = 1,
                         lb=None, ub=None) -> tuple[Tree, list[int]]:
    """Balanced depth-`depth` bisection tree over the [lb, ub] box
    (default unit box): n_roots * 2^depth leaves, every leaf carrying a
    synthetic certified payload, split-time hyperplanes live.  Returns
    (tree, roots) matching build_partition's result shape."""
    lb = np.full(p, 0.0) if lb is None else np.asarray(lb, float)
    ub = np.full(p, 1.0) if ub is None else np.asarray(ub, float)
    roots_V = geometry.box_triangulation(lb, ub)
    R = roots_V.shape[0]
    m = p + 1
    tree = Tree(p=p, n_u=n_u)
    n_total = R * (2 ** (depth + 1) - 1)
    tree._grow(n_total)
    tree._vertices[:R] = roots_V
    tree._depth[:R] = 0
    tree._n = R
    ids = np.arange(R, dtype=np.int64)
    V = roots_V
    for d in range(depth):
        K = ids.size
        ij = _longest_edges(V)
        ar = np.arange(K)
        w, c = geometry.split_hyperplanes(V, ij)
        mid = 0.5 * (V[ar, ij[:, 0]] + V[ar, ij[:, 1]])
        left = V.copy()
        left[ar, ij[:, 1]] = mid
        right = V.copy()
        right[ar, ij[:, 0]] = mid
        # Children interleave left/right per parent, in parent order --
        # the same id layout a Tree.split loop produces.
        n0 = tree._n
        kids = np.empty((2 * K, m, p))
        kids[0::2] = left
        kids[1::2] = right
        tree._vertices[n0:n0 + 2 * K] = kids
        tree._parent[n0:n0 + 2 * K] = np.repeat(ids, 2).astype(np.int32)
        tree._depth[n0:n0 + 2 * K] = d + 1
        li = n0 + 2 * ar
        tree._children[ids, 0] = li.astype(np.int32)
        tree._children[ids, 1] = (li + 1).astype(np.int32)
        tree._split_edge[ids] = ij
        tree._normal[ids] = w
        tree._offset[ids] = c
        tree._n = n0 + 2 * K
        ids = np.arange(n0, n0 + 2 * K, dtype=np.int64)
        V = np.empty((2 * K, m, p))
        V[0::2] = left
        V[1::2] = right
    tree._max_depth = depth
    # Leaf payloads, written columnar in one pass (a per-leaf set_leaf
    # loop is minutes at 10^6 leaves).
    K = ids.size
    U, costs = leaf_payload(V, n_u)
    tree._grow_payload(K)
    tree._pl_delta[:K] = 0
    tree._pl_inputs[:K] = U
    tree._pl_costs[:K] = costs
    tree._leaf_slot[ids] = np.arange(K, dtype=np.int32)
    tree._leaf_flags[ids] = _F_DATA | _F_CERTIFIED
    tree._n_slots = K
    tree._n_regions = K
    return tree, list(range(R))
