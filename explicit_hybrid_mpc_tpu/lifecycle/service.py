"""The continuous rebuild daemon: revisions in, live controllers out.

``RebuildService`` closes the loop ROADMAP item 4 names: it watches a
``RevisionSource`` (revision.py), schedules warm rebuilds
(partition/rebuild.py) under a wall-clock SLA, publishes each
generation as a DELTA-compressed artifact (delta.py, full-artifact
fallback), and hot-swaps it into a ``serve.ControllerRegistry``
(two-epoch handoff, docs/serving.md) while traffic flows.  The
headline observable is END-TO-END STALENESS: revision observed ->
rebuilt controller live, measured per generation and rolled into
``lifecycle.staleness_p50_s`` / ``_p99_s`` gauges.

Scheduling semantics (docs/lifecycle.md):

- **Coalescing**: at most ONE revision per controller is ever queued;
  a newer revision of the same controller SUPERSEDES a queued older
  one (``lifecycle.revisions_superseded``) -- rebuilding against a
  stale intermediate revision would add a whole generation of
  staleness for a tree nobody wants.  The superseding revision keeps
  the OLDER observation time: the operator's staleness clock started
  when the plant first drifted away from the serving tree, not when
  the latest refinement of that drift was measured.
- **Priority**: workers claim the queued revision with the LEAST SLA
  headroom (oldest ``t_observed`` first) across controllers.
- **Bounded concurrency**: ``max_concurrent`` worker threads (default
  1 -- rebuilds are device-bound and two builds sharing one
  accelerator serialize anyway); a controller is never rebuilt by two
  workers at once.
- **SLA**: ``sla_s`` is a staleness budget, not a deadline scheduler:
  a generation that goes live past it emits ``health.staleness``
  (warn, adopted by any HealthMonitor / obs_watch) and counts
  ``lifecycle.sla_misses``.

Each generation chains the PREVIOUS generation's ``PartitionResult``
straight into ``warm_rebuild`` (no disk round-trip -- Tree.clone) and
appends a row to ``service.generations``: reuse_frac, ledger size,
staleness, delta-vs-full bytes.  The ledger-pruning claim from PR 10
(chained rebuilds stay bounded) is benchmarked over this exact loop
(``bench.py --drift-walk``) and pinned by tests/test_lifecycle.py.

Failure containment: a failed rebuild/publish (solver error,
provenance rejection, injected fault) leaves the PRIOR generation
serving and the prior result as the next chain link; the failure is
counted + evented and the daemon keeps running.  ``InjectedCrash``
(faults/plan.py) is deliberately NOT contained -- it must unwind like
the SIGKILL it stands for (the chaos drill asserts the old version
keeps serving).  Injection sites: ``lifecycle.revision`` (worker
picks up a revision) and ``lifecycle.publish_delta`` (between the
delta landing on disk and the swap).
"""

from __future__ import annotations

import dataclasses
import os
import threading
import time
from typing import Optional

import numpy as np

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.faults import injector as faults_inj
from explicit_hybrid_mpc_tpu.faults.plan import InjectedCrash
from explicit_hybrid_mpc_tpu.lifecycle import delta as delta_mod
from explicit_hybrid_mpc_tpu.lifecycle.revision import (Revision,
                                                        RevisionSource)


@dataclasses.dataclass(frozen=True)
class LifecycleConfig:
    """Daemon knobs (distinct from PartitionConfig on purpose: these
    are SERVICE-scoped -- none of them can change a solved value, only
    when rebuilds run and how artifacts ship)."""

    #: Root directory for published artifacts:
    #: <root>/<controller>/<version>[.delta].
    artifacts_root: str = "artifacts/lifecycle"
    #: Staleness budget in wall seconds (revision observed -> new
    #: controller live); breaches emit health.staleness + count
    #: lifecycle.sla_misses.  <= 0 disables the alarm.
    sla_s: float = 600.0
    #: Revision-source poll cadence (the scheduler loop's idle sleep).
    poll_s: float = 0.05
    #: Rebuild worker threads (see module docstring).
    max_concurrent: int = 1
    #: Publish delta artifacts when a committed base exists (full
    #: fallback is automatic and counted).
    delta_publish: bool = True
    #: Refuse priors without a provenance stamp (rebuild strictness).
    strict_provenance: bool = False
    #: Re-anchor with a FULL artifact every K generations (0 = only
    #: when the delta path falls back).  Bounds the delta chain a
    #: cold-started replica must walk.
    full_every: int = 0
    #: Root of the serving fleet's demand snapshots
    #: (<demand_dir>/<controller>/demand.{npz,json}, obs/demand.py):
    #: when set, each warm rebuild loads the controller's latest
    #: committed snapshot, maps its hot leaf rows to tree node ids
    #: through the prior artifact's node_id.npy, and passes the
    #: result to ``warm_rebuild(priority=...)`` so live-traffic
    #: leaves re-certify first.  Best-effort: a missing/torn/stale
    #: snapshot degrades to the default node ordering.
    demand_dir: Optional[str] = None
    #: Attach an SloTracker (obs/slo.py) over the lifecycle metric
    #: family: per-generation SLA-miss ratio + rolling staleness p99
    #: as durable error budgets, ticked from the watch loop.  Needs
    #: an enabled obs handle to do anything.
    slo: bool = False
    #: Compliance goal for the lifecycle objectives.
    slo_goal: float = 0.999
    #: Retention-ring slot width (seconds) for the lifecycle budgets.
    slo_interval_s: float = 60.0
    #: Durable budget state directory (None = in-memory only).
    slo_dir: Optional[str] = None

    def __post_init__(self) -> None:
        if self.poll_s <= 0:
            raise ValueError("poll_s must be > 0")
        if self.max_concurrent < 1:
            raise ValueError("max_concurrent must be >= 1")
        if self.full_every < 0:
            raise ValueError("full_every must be >= 0 (0 = delta "
                             "whenever a base exists)")
        if not 0.0 < self.slo_goal < 1.0:
            raise ValueError("slo_goal must be in (0, 1)")
        if self.slo_interval_s <= 0:
            raise ValueError("slo_interval_s must be > 0")


class _ControllerState:
    """Per-controller chain state (owned by the service lock)."""

    __slots__ = ("prior", "prior_dir", "prior_version", "generation",
                 "in_flight", "queued")

    def __init__(self):
        self.prior = None          # last PartitionResult (chain link)
        self.prior_dir = None      # last FULL artifact dir (delta base)
        self.prior_version = None
        self.generation = 0
        self.in_flight = False
        self.queued: Optional[Revision] = None


class RebuildService:
    """The daemon (see module docstring).

    ``registry`` may be None (publish-to-disk only -- no serving
    fleet on this host); with a registry every generation hot-swaps
    under the controller's name.  ``prior`` seeds controller chains:
    a dict {controller: PartitionResult | path} or a single value for
    the default controller; revisions for a controller with no prior
    run a COLD build for generation 0.
    """

    def __init__(self, source: RevisionSource, build_cfg: PartitionConfig,
                 cfg: LifecycleConfig | None = None, registry=None,
                 prior=None, obs: "obs_lib.Obs | None" = None,
                 arena=None):
        self.source = source
        self.build_cfg = build_cfg
        self.cfg = cfg or LifecycleConfig()
        self.registry = registry
        #: Optional serve.DeviceArena: each published generation also
        #: hot-swaps into the device-resident arena (delta generations
        #: via the O(changed) publish_delta path).
        self.arena = arena
        self.obs = obs if obs is not None else obs_lib.NOOP
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        # obs Counters are single-producer by contract (obs/metrics.py)
        # and the watcher + max_concurrent workers all update the
        # lifecycle.* family: serialize metric writes.
        self._ms_lock = threading.Lock()
        self._ctl: dict[str, _ControllerState] = {}
        self._closed = False
        self._started = False
        self._worker_error: Optional[BaseException] = None
        self._staleness: list[float] = []
        #: One row per completed generation, in completion order.
        self.generations: list[dict] = []
        self.n_failures = 0
        if isinstance(prior, dict):
            for name, p in prior.items():
                self._seed_prior(name, p)
        elif prior is not None:
            self._seed_prior("default", p=prior)
        self._ms = None
        if self.obs.enabled:
            m = self.obs.metrics
            self._ms = {
                "seen": m.counter("lifecycle.revisions_seen"),
                "superseded": m.counter("lifecycle.revisions_superseded"),
                "rebuilds": m.counter("lifecycle.rebuilds"),
                "failures": m.counter("lifecycle.rebuild_failures"),
                "pub_delta": m.counter("lifecycle.publishes_delta"),
                "pub_full": m.counter("lifecycle.publishes_full"),
                "fallbacks": m.counter("lifecycle.delta_fallbacks"),
                "sla": m.counter("lifecycle.sla_misses"),
                "stale_h": m.histogram("lifecycle.staleness_s"),
                "p50": m.gauge("lifecycle.staleness_p50_s"),
                "p99": m.gauge("lifecycle.staleness_p99_s"),
                "reuse": m.gauge("lifecycle.last_reuse_frac"),
                "gen": m.gauge("lifecycle.generation"),
                "dfrac": m.gauge("lifecycle.delta_bytes_frac"),
                "ledger": m.gauge("lifecycle.excl_events"),
                "depth": m.gauge("lifecycle.queue_depth"),
            }
        # Durable staleness error budget (obs/slo.py), ticked from the
        # watch loop at a bounded cadence; None when off -- the hub
        # pattern the schedulers use.
        self.slo = None
        if self.cfg.slo and self.obs.enabled:
            from explicit_hybrid_mpc_tpu.obs import slo as slo_mod

            self.slo = slo_mod.SloTracker(
                slo_mod.lifecycle_slo_specs(self.cfg.sla_s,
                                            goal=self.cfg.slo_goal),
                interval_s=self.cfg.slo_interval_s, obs=self.obs,
                state_dir=self.cfg.slo_dir, identity="lifecycle")
        # Host forensics (obs/reqtrace.py), previously serve/bench
        # only: GC pauses and watcher sleep overshoot are attributed
        # to the HOST, so a GC-stalled rebuild worker stops blaming
        # the rebuild.
        self._gc_rec = None
        self._host_trace = None
        if self.obs.enabled:
            from explicit_hybrid_mpc_tpu.obs import reqtrace as rt_mod

            self._gc_rec = rt_mod.GcPauseRecorder(obs=self.obs)
            self._host_trace = rt_mod.ReqTrace(mode="on", obs=self.obs)
        # Inherit an env/cfg fault plan exactly like the frontier
        # engine does (the chaos surface for subprocess daemons).
        faults_inj.install_from_config(build_cfg, obs=self.obs)
        self._watcher = threading.Thread(
            target=self._watch_loop, name="lifecycle-watch", daemon=True)
        self._workers = [
            threading.Thread(target=self._work_loop,
                             name=f"lifecycle-worker-{i}", daemon=True)
            for i in range(self.cfg.max_concurrent)]

    def _seed_prior(self, name: str, p) -> None:
        st = self._ctl.setdefault(name, _ControllerState())
        st.prior = p

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "RebuildService":
        if self._started:
            return self
        self._started = True
        if self._gc_rec is not None:
            self._gc_rec.start()
        self._watcher.start()
        for w in self._workers:
            w.start()
        return self

    def close(self, timeout: Optional[float] = 60.0) -> None:
        """Stop watching, let in-flight rebuilds finish, join."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._started:
            self._watcher.join(timeout)
            for w in self._workers:
                w.join(timeout)
        self.source.close()
        if self._gc_rec is not None:
            self._gc_rec.stop()
        if self.obs.enabled:
            rec = self.obs.flush_metrics()
            # Final budget fold + durable commit before the stream
            # closes, so a supervised restart resumes the budget the
            # daemon actually earned.
            if self.slo is not None and rec is not None:
                self.slo.tick(rec)
        if self.slo is not None:
            self.slo.flush()

    def __enter__(self) -> "RebuildService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    def wait_idle(self, timeout: float = 600.0,
                  target_generations: Optional[int] = None) -> bool:
        """Block until the queue is drained and no rebuild is in
        flight (or `target_generations` rows exist); False on
        timeout.  Surfaces a worker-killing error (InjectedCrash in
        the chaos drills) instead of spinning on a dead pool.

        With a target AND at least one contained failure, a
        persistently-idle daemon returns False after a short idle
        debounce instead of burning the whole timeout: the
        liveness-gated drift drivers count failures toward their
        emission gate, so a failed generation makes the target
        unreachable and only the failure count says so.  (The
        debounce, not bare idleness, is what keeps the brief
        between-generations gap of a gated walk from reading as
        exhaustion.)"""
        deadline = time.perf_counter() + timeout
        debounce = max(1.0, 5 * self.cfg.poll_s)
        idle_since: Optional[float] = None
        while time.perf_counter() < deadline:
            with self._lock:
                if self._worker_error is not None:
                    return False
                done = len(self.generations)
                failures = self.n_failures
                idle = not any(st.queued or st.in_flight
                               for st in self._ctl.values())
            if target_generations is not None:
                if done >= target_generations:
                    return True
                if idle and failures:
                    now = time.perf_counter()
                    if idle_since is None:
                        idle_since = now
                    elif now - idle_since >= debounce:
                        return False
                else:
                    idle_since = None
            elif idle:
                return True
            time.sleep(min(0.02, self.cfg.poll_s))
        return False

    @property
    def worker_error(self) -> Optional[BaseException]:
        return self._worker_error

    # -- watcher: source -> coalesced queue --------------------------------

    #: Watch-loop slo tick cadence (wall seconds): the budget fold is
    #: per-interval anyway, so ticking every poll (20 Hz default)
    #: would only burn snapshot walks.
    _SLO_TICK_S = 2.0

    def _watch_loop(self) -> None:
        last_tick = time.perf_counter()
        while True:
            with self._lock:
                if self._closed:
                    return
            try:
                revs = self.source.poll()
            except Exception as e:  # tpulint: disable=silent-except -- a flaky source must not kill the daemon; counted below
                revs = []
                self._count_failure(None, f"source poll failed: {e}")
            for rev in revs:
                self._enqueue(rev)
            if self.slo is not None:
                now = time.perf_counter()
                if now - last_tick >= self._SLO_TICK_S:
                    last_tick = now
                    rec = self.obs.flush_metrics()
                    if rec is not None:
                        self.slo.tick(rec)
            t_sleep = time.perf_counter()
            time.sleep(self.cfg.poll_s)
            if self._host_trace is not None:
                # Sleep-overshoot stall probe (the scheduler flush-loop
                # idiom): waking far past poll_s is host interference
                # -- GC, preemption -- not rebuild work, and it lands
                # in serve.host.stall_us instead of the staleness row.
                over = time.perf_counter() - t_sleep - self.cfg.poll_s
                if over > 0:
                    self._host_trace.note_stall(int(over * 1e9))

    def _enqueue(self, rev: Revision) -> None:
        with self._cond:
            st = self._ctl.setdefault(rev.controller, _ControllerState())
            old = st.queued
            if old is not None:
                # Coalesce: the newer revision supersedes, but keeps
                # the OLDER observation time (staleness is measured
                # from when the plant first drifted off the serving
                # tree, not from the latest refinement).
                rev = dataclasses.replace(rev,
                                          t_observed=old.t_observed)
            st.queued = rev
            depth = sum(1 for s in self._ctl.values() if s.queued)
            self._cond.notify()
        if self._ms:
            with self._ms_lock:
                self._ms["seen"].inc()
                self._ms["depth"].set(depth)
                if old is not None:
                    self._ms["superseded"].inc()
        self.obs.event("lifecycle.revision", controller=rev.controller,
                       seq=rev.seq, problem=rev.problem,
                       eps_a=rev.eps_a, note=rev.note,
                       superseded_seq=old.seq if old else None)

    # -- workers: claim -> rebuild -> publish -> swap ----------------------

    def _claim(self) -> Optional[tuple[str, Revision]]:
        """Least-SLA-headroom queued revision of an idle controller;
        blocks until one exists or the service closes."""
        with self._cond:
            while True:
                best, best_t = None, None
                for name, st in self._ctl.items():
                    if st.queued is not None and not st.in_flight:
                        t = st.queued.t_observed
                        if best_t is None or t < best_t:
                            best, best_t = name, t
                if best is not None:
                    st = self._ctl[best]
                    rev = st.queued
                    st.queued = None
                    st.in_flight = True
                    if self._ms:
                        with self._ms_lock:
                            self._ms["depth"].set(
                                sum(1 for s in self._ctl.values()
                                    if s.queued))
                    return best, rev
                if self._closed:
                    return None
                self._cond.wait(timeout=self.cfg.poll_s)

    def _work_loop(self) -> None:
        while True:
            claimed = self._claim()
            if claimed is None:
                return
            name, rev = claimed
            try:
                self._handle(name, rev)
            except InjectedCrash:
                # The SIGKILL stand-in: no containment layer may
                # swallow it -- record for wait_idle and unwind.
                with self._lock:
                    self._worker_error = InjectedCrash(
                        f"worker crashed on {name}#{rev.seq}")
                raise
            except Exception as e:  # noqa: BLE001 -- containment: prior generation keeps serving
                self._count_failure(rev, str(e))
            finally:
                with self._cond:
                    self._ctl[name].in_flight = False
                    self._cond.notify_all()

    def _count_failure(self, rev: Optional[Revision], msg: str) -> None:
        with self._lock:
            self.n_failures += 1
        if self._ms:
            with self._ms_lock:
                self._ms["failures"].inc()
        self.obs.event(
            "lifecycle.rebuild_failed", severity="warn",
            controller=rev.controller if rev else None,
            seq=rev.seq if rev else None, msg=msg)

    def _handle(self, name: str, rev: Revision) -> None:
        from explicit_hybrid_mpc_tpu.partition.frontier import (
            build_partition, make_oracle)
        from explicit_hybrid_mpc_tpu.partition.rebuild import warm_rebuild
        from explicit_hybrid_mpc_tpu.problems.registry import make

        faults_inj.fire("lifecycle.revision",
                        label=f"{name}#{rev.seq}")
        t0 = time.perf_counter()
        with self._lock:
            st = self._ctl[name]
            prior = st.prior
            prior_dir = st.prior_dir
            gen = st.generation
        problem = make(rev.problem, **dict(rev.problem_args))
        cfg2 = dataclasses.replace(
            self.build_cfg, problem=rev.problem,
            problem_args=rev.problem_args, eps_a=rev.eps_a,
            eps_r=rev.eps_r)
        oracle = make_oracle(problem, cfg2)
        if prior is None:
            res = build_partition(problem, cfg2, oracle=oracle,
                                  obs=self.obs)
            reuse = None
        else:
            priority = self._demand_priority(name, prior_dir)
            res = warm_rebuild(
                problem, cfg2, prior, oracle=oracle, obs=self.obs,
                strict_provenance=self.cfg.strict_provenance,
                priority=priority)
            reuse = res.stats.get("rebuild_reuse_frac")
            if priority:
                self.obs.event(
                    "lifecycle.demand_priority", controller=name,
                    seq=rev.seq, hot_nodes=len(priority),
                    hinted=res.stats.get("rebuild_priority_hint"))
        rebuild_s = time.perf_counter() - t0
        row = self._publish(name, rev, res, gen)
        staleness = time.perf_counter() - rev.t_observed
        row.update(
            controller=name, seq=rev.seq, generation=gen,
            reuse_frac=reuse, rebuild_wall_s=round(rebuild_s, 3),
            staleness_s=round(staleness, 3),
            excl_events=len(res.tree.excl_events),
            subdivision_solves=res.stats.get("subdivision_solves"),
            recert_solves=res.stats.get("recert_solves"),
            regions=res.stats.get("regions"), note=rev.note)
        with self._lock:
            st.prior = res
            st.generation = gen + 1
            self._staleness.append(staleness)
            stale = np.asarray(self._staleness)
            self.generations.append(row)
        p50 = float(np.percentile(stale, 50))
        p99 = float(np.percentile(stale, 99))
        if self._ms:
            with self._ms_lock:
                self._ms["rebuilds"].inc()
                self._ms["stale_h"].observe(staleness)
                self._ms["p50"].set(p50)
                self._ms["p99"].set(p99)
                if reuse is not None:
                    self._ms["reuse"].set(reuse)
                self._ms["gen"].set(gen + 1)
                self._ms["ledger"].set(len(res.tree.excl_events))
        self.obs.event("lifecycle.rebuilt", controller=name,
                       seq=rev.seq, generation=gen,
                       reuse_frac=reuse,
                       staleness_s=round(staleness, 3),
                       published=row.get("published"),
                       version=row.get("version"),
                       delta_bytes=row.get("delta_bytes"),
                       full_bytes=row.get("full_bytes"))
        if 0 < self.cfg.sla_s < staleness:
            if self._ms:
                with self._ms_lock:
                    self._ms["sla"].inc()
            # health.* event: adopted by any HealthMonitor fed this
            # stream (obs/health.py), so obs_watch exits nonzero.
            self.obs.event(
                "health.staleness", severity="warn",
                value=round(staleness, 3), threshold=self.cfg.sla_s,
                controller=name,
                msg=f"generation {gen} of {name!r} went live "
                    f"{staleness:.1f}s after its revision was "
                    f"observed (SLA {self.cfg.sla_s:g}s): the rebuild "
                    "pipeline is not keeping up with plant drift")

    def _demand_priority(self, name: str,
                         prior_dir: Optional[str]
                         ) -> Optional[dict[int, float]]:
        """{node id: hits} hint from the controller's latest committed
        demand snapshot (cfg.demand_dir), mapped through the PRIOR
        artifact's node_id.npy -- the table the serving leaf rows
        index.  Best-effort by contract: no snapshot dir, a torn
        snapshot, or a missing prior artifact all return None (the
        rebuild proceeds in default node order)."""
        if self.cfg.demand_dir is None or prior_dir is None:
            return None
        from explicit_hybrid_mpc_tpu.obs import demand as demand_mod
        try:
            snap = demand_mod.load_demand(
                os.path.join(self.cfg.demand_dir, name))
            node_id = np.load(os.path.join(prior_dir, "node_id.npy"))
            pr = demand_mod.priority_from_snapshot(snap, node_id)
        except Exception as e:  # tpulint: disable=silent-except -- hint is best-effort; evented below
            self.obs.event("lifecycle.demand_priority_skipped",
                           controller=name, msg=repr(e))
            return None
        return pr or None

    def _publish(self, name: str, rev: Revision, res, gen: int) -> dict:
        """Delta-compressed publish + hot swap; returns the byte
        accounting row.  The delta path: write the delta dir, fire the
        crash site, APPLY it against the base (the replica sync path,
        exercised live), and swap the APPLIED directory in -- so what
        serves is provably what a delta-syncing replica would load."""
        from explicit_hybrid_mpc_tpu.serve import registry as reg_mod

        stamp = getattr(res.tree, "provenance", None)
        version = f"g{gen:04d}"
        if stamp is not None:
            version += f"-{stamp['problem_hash'][:8]}"
        root = os.path.join(self.cfg.artifacts_root, name)
        full_dir = os.path.join(root, version)
        with self._lock:
            st = self._ctl[name]
            base_dir, base_version = st.prior_dir, st.prior_version
        force_full = (self.cfg.full_every > 0
                      and gen % self.cfg.full_every == 0)
        published = "full"
        delta_bytes = None
        if (self.cfg.delta_publish and base_dir is not None
                and not force_full):
            delta_dir = full_dir + ".delta"
            try:
                dstats = delta_mod.write_delta_artifact(
                    res.tree, res.roots, delta_dir, base_dir,
                    base_version=base_version, provenance=stamp)
                # THE crash window: delta on disk, swap not yet run.
                faults_inj.fire("lifecycle.publish_delta",
                                label=f"{name}:{version}")
                delta_mod.apply_delta(delta_dir, base_dir, full_dir)
                published = "delta"
                delta_bytes = dstats["delta_bytes"]
            except delta_mod.DeltaMismatch as e:
                if self._ms:
                    with self._ms_lock:
                        self._ms["fallbacks"].inc()
                self.obs.event("lifecycle.delta_fallback",
                               controller=name, version=version,
                               msg=str(e))
        if published == "full":
            reg_mod.save_artifacts(res.tree, res.roots, full_dir,
                                   provenance=stamp)
        full_bytes = delta_mod.delta_size_bytes(full_dir)
        if self.registry is not None:
            self.registry.load_artifacts(name, version, full_dir,
                                         expect_provenance=stamp)
        if self.arena is not None:
            # Device-resident fleet path: delta generations swap in
            # O(changed) (kept columns device-gathered from the
            # resident base extent); anything the arena cannot delta
            # against (first generation, non-resident base) loads full.
            try:
                if published == "delta":
                    self.arena.publish_delta(
                        name, version, full_dir + ".delta", base_dir)
                else:
                    self.arena.publish_from_artifacts(
                        name, version, full_dir)
            except delta_mod.DeltaMismatch:
                self.arena.publish_from_artifacts(name, version,
                                                  full_dir)
        with self._lock:
            st.prior_dir = full_dir
            st.prior_version = version
        if self._ms:
            with self._ms_lock:
                self._ms["pub_delta" if published == "delta"
                         else "pub_full"].inc()
                if delta_bytes is not None and full_bytes:
                    self._ms["dfrac"].set(delta_bytes / full_bytes)
        self.obs.event("lifecycle.published", controller=name,
                       version=version, published=published,
                       delta_bytes=delta_bytes, full_bytes=full_bytes,
                       dir=full_dir)
        return {"version": version, "published": published,
                "delta_bytes": delta_bytes, "full_bytes": full_bytes,
                "artifact_dir": full_dir}

    # -- reporting ---------------------------------------------------------

    def summary(self) -> dict:
        """Aggregate lifecycle report (the CLI/bench surface)."""
        with self._lock:
            gens = list(self.generations)
            stale = list(self._staleness)
            failures = self.n_failures
        reuse = [g["reuse_frac"] for g in gens
                 if g.get("reuse_frac") is not None]
        # Monotone-reported decay: the running MIN of per-generation
        # reuse -- by construction non-increasing, so a report reader
        # sees the worst decay so far, never a lucky generation
        # masking an earlier collapse.
        decay = list(np.minimum.accumulate(reuse)) if reuse else []
        deltas = [g for g in gens if g.get("published") == "delta"]
        dfracs = [g["delta_bytes"] / g["full_bytes"] for g in deltas
                  if g.get("delta_bytes") and g.get("full_bytes")]
        slo = self.slo.summary() if self.slo is not None else None
        return {
            "slo": slo,
            "generations": len(gens),
            "failures": failures,
            "staleness_p50_s": (round(float(np.percentile(stale, 50)), 3)
                                if stale else None),
            "staleness_p99_s": (round(float(np.percentile(stale, 99)), 3)
                                if stale else None),
            "reuse_fracs": [round(float(r), 4) for r in reuse],
            "reuse_decay": [round(float(r), 4) for r in decay],
            "excl_events": [g["excl_events"] for g in gens],
            "delta_publishes": len(deltas),
            "full_publishes": sum(1 for g in gens
                                  if g.get("published") == "full"),
            "delta_bytes_frac": (round(float(np.mean(dfracs)), 4)
                                 if dfracs else None),
        }
