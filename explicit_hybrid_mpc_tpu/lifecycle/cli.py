"""CLI for the continuous rebuild daemon: ``main.py serve-rebuild``.

Runs a RebuildService against either the simulated plant-drift driver
(``--drift``, the default: a seeded bounded walk on one constructor
argument -- the demo/bench surface) or an external JSONL revision
stream (``--source FILE``: one revision dict per line, the
integration surface for a real sys-id pipeline).  Prints a JSON
summary (generations, staleness p50/p99, reuse decay, delta byte
ratio) and exits nonzero on any rebuild failure.

    python -m explicit_hybrid_mpc_tpu.main serve-rebuild \\
        -e double_integrator --problem-arg N=3 \\
        --problem-arg theta_box=1.5 -a 0.2 --backend cpu \\
        --revisions 3 --artifacts-root /tmp/lc --obs jsonl

scripts/rebuild_service.py is the standalone wrapper.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="explicit_hybrid_mpc_tpu serve-rebuild",
        description="continuous rebuild daemon: plant-drift watch -> "
                    "SLA-scheduled warm rebuild -> delta publish -> "
                    "hot swap (docs/lifecycle.md)")
    p.add_argument("-e", "--example", required=True,
                   help="benchmark problem name (problems/registry.py)")
    p.add_argument("--problem-arg", action="append", default=[],
                   metavar="K=V", help="problem constructor overrides")
    p.add_argument("-a", "--eps-a", type=float, default=1e-2)
    p.add_argument("-r", "--eps-r", type=float, default=0.0)
    p.add_argument("--backend", choices=("tpu", "cpu", "serial"),
                   default="cpu")
    p.add_argument("--batch", type=int, default=256)
    p.add_argument("--controller", default="default",
                   help="registry controller name rebuilt generations "
                        "publish under")
    p.add_argument("--artifacts-root", required=True, metavar="DIR",
                   help="published artifact root "
                        "(<DIR>/<controller>/<version>[.delta])")
    p.add_argument("--source", metavar="FILE.jsonl", default=None,
                   help="external JSONL revision stream (default: the "
                        "simulated drift driver)")
    p.add_argument("--drift-arg", default="u_max", metavar="ARG",
                   help="constructor argument the drift walk perturbs "
                        "(default u_max; never the theta box)")
    p.add_argument("--drift-frac", type=float, default=0.02,
                   help="per-revision drift step fraction (default "
                        "0.02; the walk is clamped to +-20%%)")
    p.add_argument("--eps-frac", type=float, default=0.0,
                   help="per-revision eps_a walk step fraction "
                        "(default 0: eps fixed)")
    p.add_argument("--revisions", type=int, default=3, metavar="K",
                   help="drift revisions to emit before exiting "
                        "(default 3; 0 = run until --duration)")
    p.add_argument("--period", type=float, default=0.0, metavar="S",
                   help="min seconds between drift revisions")
    p.add_argument("--probe-T", type=int, default=0, metavar="T",
                   help="open-loop divergence probe horizon (sim/"
                        "simulator.py) recorded with each revision; "
                        "0 skips the probe")
    p.add_argument("--sla", type=float, default=600.0, metavar="S",
                   help="staleness budget (health.staleness past it)")
    p.add_argument("--prior", metavar="TREE.pkl", default=None,
                   help="seed the controller chain from a prior tree/"
                        "checkpoint (default: generation 0 builds "
                        "cold)")
    p.add_argument("--no-delta", action="store_true",
                   help="always publish full artifacts")
    p.add_argument("--full-every", type=int, default=0, metavar="K",
                   help="re-anchor with a full artifact every K "
                        "generations (bounds replica delta chains)")
    p.add_argument("--no-serve", action="store_true",
                   help="publish to disk only (no in-process registry "
                        "hot swap)")
    p.add_argument("--duration", type=float, default=None, metavar="S",
                   help="wall budget; default: exit once the drift "
                        "source is exhausted and the queue drains")
    p.add_argument("--obs", choices=("off", "jsonl", "full"),
                   default="off")
    p.add_argument("--obs-path", metavar="FILE", default=None,
                   help="obs stream path (default <artifacts-root>/"
                        "lifecycle.obs.jsonl)")
    p.add_argument("--fault-plan", metavar="PLAN.json", default=None,
                   help="deterministic fault injection (chaos only)")
    p.add_argument("--json", dest="json_out", default=None,
                   help="also write the summary JSON here")
    return p


def serve_rebuild_main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if not args.source and args.revisions <= 0 and args.duration is None:
        raise SystemExit(
            "serve-rebuild: an unbounded drift walk (--revisions 0) "
            "needs --duration S (otherwise the daemon would rebuild "
            "for an arbitrary hour and exit)")
    if args.backend in ("cpu", "serial"):
        # Platform pin before any device query (verify SKILL.md
        # gotcha: env JAX_PLATFORMS alone is overridden by the
        # accelerator plugin's own config.update).
        import jax

        jax.config.update("jax_platforms", "cpu")

    import os

    from explicit_hybrid_mpc_tpu import obs as obs_lib
    from explicit_hybrid_mpc_tpu.config import PartitionConfig
    from explicit_hybrid_mpc_tpu.lifecycle.revision import (
        DriftSource, FileRevisionSource)
    from explicit_hybrid_mpc_tpu.lifecycle.service import (
        LifecycleConfig, RebuildService)
    from explicit_hybrid_mpc_tpu.main import _parse_problem_args

    problem_args = tuple(sorted(
        _parse_problem_args(args.problem_arg).items()))
    # The default obs stream lives under the artifacts root: it must
    # exist before the sink opens (the daemon itself creates only the
    # per-version subdirectories).
    os.makedirs(args.artifacts_root, exist_ok=True)
    build_cfg = PartitionConfig(
        problem=args.example, problem_args=problem_args,
        eps_a=args.eps_a, eps_r=args.eps_r, backend=args.backend,
        batch_simplices=args.batch, obs=args.obs,
        obs_path=(args.obs_path
                  or os.path.join(args.artifacts_root,
                                  "lifecycle.obs.jsonl")
                  if args.obs != "off" else None),
        fault_plan=args.fault_plan)
    if args.source:
        source = FileRevisionSource(args.source,
                                    controller=args.controller)
    else:
        source = DriftSource(
            args.example, problem_args=problem_args,
            controller=args.controller, eps_a=args.eps_a,
            eps_r=args.eps_r, drift_arg=args.drift_arg,
            drift_frac=args.drift_frac, eps_frac=args.eps_frac,
            n_revisions=args.revisions or None, period_s=args.period,
            probe_T=args.probe_T)
    lc_cfg = LifecycleConfig(
        artifacts_root=args.artifacts_root, sla_s=args.sla,
        delta_publish=not args.no_delta, full_every=args.full_every)
    obs = obs_lib.from_config(build_cfg)
    registry = None
    if not args.no_serve:
        from explicit_hybrid_mpc_tpu.serve.registry import (
            ControllerRegistry)

        registry = ControllerRegistry(obs=obs)
    # Seed under the controller the revisions actually arrive for --
    # a bare value would land on the literal name "default" and a
    # --controller di run would silently cold-build generation 0.
    prior = {args.controller: args.prior} if args.prior else None
    svc = RebuildService(source, build_cfg, cfg=lc_cfg,
                         registry=registry, prior=prior, obs=obs)
    if not args.source:
        # Drift mode paces itself on liveness: revision k+1 is
        # emitted once generation k is live (or failed), so
        # `--revisions K` predictably yields K generations instead of
        # the daemon coalescing a faster-than-rebuild walk down to a
        # couple (coalescing still governs FileRevisionSource storms
        # -- that source reflects an EXTERNAL clock).
        source.gate = (lambda: len(svc.generations) + svc.n_failures
                       >= source.n_emitted)
    svc.start()
    import time

    try:
        if args.duration is not None:
            deadline = time.time() + args.duration
            while time.time() < deadline:
                time.sleep(min(0.2, args.duration))
                if svc.worker_error is not None:
                    break
        else:
            # Run the source dry, then drain the queue.  File mode
            # without --duration drains whatever the file holds now.
            t_end = time.time() + 3600.0
            while time.time() < t_end and svc.worker_error is None:
                exhausted = (source.exhausted()
                             if hasattr(source, "exhausted") else True)
                if exhausted and svc.wait_idle(timeout=30.0):
                    break
                time.sleep(0.2)
    finally:
        svc.close()
    summary = svc.summary()
    summary["controller"] = args.controller
    print(json.dumps(summary))
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump({"summary": summary,
                       "generations": svc.generations}, f, indent=2)
    if svc.worker_error is not None:
        print(f"serve-rebuild: worker crashed: {svc.worker_error}",
              file=sys.stderr)
        return 2
    return 1 if summary["failures"] else 0
