"""Delta-compressed serving artifacts: ship O(changed), not O(tree).

A warm rebuild keeps most prior leaves BIT-IDENTICAL (partition/
rebuild.py: kept payloads are never rewritten, kept node ids never
move), so successive generations' serving artifacts share most of
their bytes -- yet ``save_artifacts`` ships the full table every
time, and a replica fleet syncing N copies of an O(tree) artifact per
revision pays the tree size on every swap.  The delta format carries
only what changed, with the base pinned by provenance:

Leaf table (the byte-dominant part): the new table's rows are keyed by
``node_id`` (stable across a rebuild -- invalidated leaves become
internal nodes and their replacement leaves get NEW ids, so a kept row
has the same id and the same bytes).  The delta stores

- ``src_idx.npy``: (L_new,) int64 -- for each new row, the base-table
  row it is copied from verbatim, or -1 for a fresh row;
- ``fresh_<field>.npy``: the fresh rows only, in new-row order.

Descent arrays (online/descent.py): keyed by tree node index (node
ids only ever APPEND across a rebuild).  The delta stores the changed
prefix rows (invalidated leaves that gained children) + the appended
tail; ``leaf_row`` is not shipped at all -- it is a permutation of the
new leaf order and is recomputed exactly at apply time, and the root
arrays come from the base (root geometry transfer is a warm-rebuild
precondition).

``delta_meta.json`` is the delta's COMMIT MARKER (written atomically
LAST, utils/atomic.py): it pins the base (provenance stamp +
n_leaves + the base's own file checksums) and records content sha256s
of every RECONSTRUCTED array, so ``apply_delta`` can prove the applied
artifact is bitwise what the publisher exported -- a wrong base, a
torn delta, or bit rot all fail loudly (``DeltaMismatch`` /
``CorruptArtifact``) instead of serving a franken-table.  Applying
writes a directory byte-compatible with ``save_artifacts``'s layout
(fields + descent.npz first, meta.json commit marker last), so
``ControllerRegistry.load_artifacts`` consumes it unchanged.

When no valid base exists (first generation, provenance drift, legacy
base) the caller falls back to a FULL artifact -- the daemon
(service.py) counts those as ``lifecycle.delta_fallbacks``.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

import numpy as np

DELTA_KIND = "ehm-delta-v1"
DELTA_META = "delta_meta.json"

#: Leaf-table fields, publisher order (online/export.py layout).
_LEAF_FIELDS = ("bary_M", "U", "V", "delta", "node_id")
#: Descent arrays delta-compressed on the node axis (the rest of the
#: npz -- root_bary/root_node -- transfers from the base, and leaf_row
#: is recomputed).
_DESC_FIELDS = ("children", "normal", "offset")


class DeltaMismatch(ValueError):
    """The delta does not apply to this base (wrong generation /
    provenance drift / shape disagreement): sync the full artifact."""


def _sha(a: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(a).tobytes()).hexdigest()


def _read_meta(dir_path: str, name: str) -> Optional[dict]:
    from explicit_hybrid_mpc_tpu.utils import atomic

    p = os.path.join(dir_path, name)
    try:
        with open(p) as f:
            return json.load(f)
    except OSError:
        return None
    except json.JSONDecodeError as e:
        raise atomic.CorruptArtifact(
            f"{p}: unreadable ({e}) -- the commit marker is torn; "
            "re-publish or fall back to the full artifact") from e


def delta_size_bytes(dir_path: str) -> int:
    """Total on-disk bytes of an artifact/delta directory (the
    replica-sync cost the delta format exists to shrink)."""
    total = 0
    for name in os.listdir(dir_path):
        p = os.path.join(dir_path, name)
        if os.path.isfile(p):
            total += os.path.getsize(p)
    return total


def write_delta_artifact(tree, roots, delta_dir: str, base_dir: str,
                         base_version: Optional[str] = None,
                         provenance: Optional[dict] = None) -> dict:
    """Export `tree` as a DELTA against the published artifact at
    `base_dir`.  Returns stats (n_kept/n_fresh/delta row counts +
    byte accounting).  Raises DeltaMismatch when the base cannot host
    a delta (row-key drift, shape change, missing/legacy meta) -- the
    caller then publishes a full artifact instead."""
    from explicit_hybrid_mpc_tpu.online import descent as descent_mod
    from explicit_hybrid_mpc_tpu.online import export as export_mod
    from explicit_hybrid_mpc_tpu.utils import atomic

    if provenance is None:
        provenance = getattr(tree, "provenance", None)
    base_meta = _read_meta(base_dir, "meta.json")
    if base_meta is None or "n_leaves" not in base_meta:
        raise DeltaMismatch(
            f"{base_dir}: no committed meta.json -- a legacy or "
            "uncommitted base cannot anchor a delta")
    base_table = export_mod.load_leaf_table(base_dir, mmap=True)
    base_desc = np.load(os.path.join(base_dir, "descent.npz"))
    try:
        table = export_mod.export_leaves(tree)
        dt = descent_mod.export_descent(tree, roots, table, stage=False)
        n_base = int(base_table.n_leaves)
        if (table.bary_M.shape[1:] != base_table.bary_M.shape[1:]
                or table.U.shape[1:] != base_table.U.shape[1:]):
            raise DeltaMismatch(
                "leaf-row shapes differ from the base (p or n_u "
                "changed): nothing transfers, publish full")
        root_bary = np.asarray(dt.root_bary)
        if not np.array_equal(root_bary, base_desc["root_bary"]) or \
                not np.array_equal(np.asarray(dt.root_node),
                                   base_desc["root_node"]):
            raise DeltaMismatch(
                "root triangulation differs from the base: the box "
                "changed -- a cold-build event, publish full")

        # -- leaf rows: match by node_id, keep only byte-equal rows ----
        base_ids = np.asarray(base_table.node_id, dtype=np.int64)
        new_ids = np.asarray(table.node_id, dtype=np.int64)
        # Exported ids are converged_leaf_ids(): ascending by contract
        # (searchsorted below depends on it; a hand-assembled base that
        # violates it cannot anchor a delta).
        if base_ids.size > 1 and np.any(np.diff(base_ids) <= 0):
            raise DeltaMismatch(
                f"{base_dir}: base node_id rows are not ascending -- "
                "not an export-layout artifact, publish full")
        pos = np.searchsorted(base_ids, new_ids)
        pos_c = np.clip(pos, 0, n_base - 1)
        found = base_ids[pos_c] == new_ids
        same = found.copy()
        for k in ("bary_M", "U", "V", "delta"):
            a = np.asarray(getattr(table, k))
            b = np.asarray(getattr(base_table, k))[pos_c]
            eq = a == b
            if eq.ndim > 1:
                eq = eq.reshape(eq.shape[0], -1).all(axis=1)
            same &= eq
        src_idx = np.where(same, pos_c, -1).astype(np.int64)
        fresh = src_idx < 0

        # -- descent rows: changed prefix + appended tail --------------
        children = np.asarray(dt.children)
        normal = np.asarray(dt.normal)
        offset = np.asarray(dt.offset)
        nb_nodes = int(base_desc["children"].shape[0])
        if children.shape[0] < nb_nodes:
            raise DeltaMismatch(
                "new tree has fewer nodes than the base: not a "
                "descendant generation, publish full")
        changed = np.zeros(nb_nodes, dtype=bool)
        changed |= (children[:nb_nodes]
                    != base_desc["children"]).any(axis=1)
        changed |= (normal[:nb_nodes]
                    != base_desc["normal"]).any(axis=1)
        changed |= offset[:nb_nodes] != base_desc["offset"]
        changed_idx = np.nonzero(changed)[0].astype(np.int64)

        os.makedirs(delta_dir, exist_ok=True)
        # A re-published delta dir must not keep a stale marker over
        # half-rewritten fields (export.invalidate_meta discipline).
        try:
            os.unlink(os.path.join(delta_dir, DELTA_META))
        except FileNotFoundError:
            pass
        np.save(os.path.join(delta_dir, "src_idx.npy"), src_idx)
        for k in _LEAF_FIELDS:
            np.save(os.path.join(delta_dir, f"fresh_{k}.npy"),
                    np.asarray(getattr(table, k))[fresh])
        np.save(os.path.join(delta_dir, "desc_changed_idx.npy"),
                changed_idx)
        np.save(os.path.join(delta_dir, "desc_changed_children.npy"),
                children[changed_idx])
        np.save(os.path.join(delta_dir, "desc_changed_normal.npy"),
                normal[changed_idx])
        np.save(os.path.join(delta_dir, "desc_changed_offset.npy"),
                offset[changed_idx])
        np.save(os.path.join(delta_dir, "desc_tail_children.npy"),
                children[nb_nodes:])
        np.save(os.path.join(delta_dir, "desc_tail_normal.npy"),
                normal[nb_nodes:])
        np.save(os.path.join(delta_dir, "desc_tail_offset.npy"),
                offset[nb_nodes:])

        meta = {
            "kind": DELTA_KIND,
            "base_version": base_version,
            "base_n_leaves": n_base,
            "base_n_nodes": nb_nodes,
            "base_provenance": base_meta.get("provenance"),
            "base_checksums": base_meta.get("checksums"),
            "n_leaves": int(table.n_leaves),
            "p": int(table.bary_M.shape[1] - 1),
            "n_u": int(table.U.shape[2]),
            "max_depth": int(dt.max_depth),
            "provenance": provenance,
            # Content hashes of the FULL reconstructed arrays: apply
            # proves bitwise identity with what the publisher held.
            "array_sha": {
                **{k: _sha(np.asarray(getattr(table, k)))
                   for k in _LEAF_FIELDS},
                "children": _sha(children), "normal": _sha(normal),
                "offset": _sha(offset),
            },
            "n_fresh": int(fresh.sum()),
            "n_kept": int((~fresh).sum()),
            "n_desc_changed": int(changed_idx.size),
        }
        atomic.atomic_write_json(os.path.join(delta_dir, DELTA_META),
                                 meta)
        return {"n_fresh": meta["n_fresh"], "n_kept": meta["n_kept"],
                "n_desc_changed": meta["n_desc_changed"],
                "delta_bytes": delta_size_bytes(delta_dir)}
    finally:
        base_desc.close()


def _validate_delta_base(delta_dir: str, base_dir: str) -> dict:
    """Shared front-half validation for delta consumers: the delta is
    committed, its kind is known, and the base at `base_dir` is the
    generation it was built against (row count + provenance stamp).
    Returns the delta meta."""
    from explicit_hybrid_mpc_tpu.utils import atomic

    meta = _read_meta(delta_dir, DELTA_META)
    if meta is None:
        raise atomic.CorruptArtifact(
            f"{delta_dir}: no {DELTA_META} -- the delta was never "
            "committed (torn publish); re-sync")
    if meta.get("kind") != DELTA_KIND:
        raise DeltaMismatch(
            f"{delta_dir}: unknown delta kind {meta.get('kind')!r}")
    base_meta = _read_meta(base_dir, "meta.json")
    if base_meta is None:
        raise DeltaMismatch(
            f"{base_dir}: base carries no committed meta.json; delta "
            "cannot be validated against it")
    if int(base_meta.get("n_leaves", -1)) != int(meta["base_n_leaves"]):
        raise DeltaMismatch(
            f"base at {base_dir} has {base_meta.get('n_leaves')} "
            f"leaves but the delta was built against "
            f"{meta['base_n_leaves']}: wrong base generation")
    from explicit_hybrid_mpc_tpu.partition import provenance as prov

    if prov.diff_stamps(base_meta.get("provenance"),
                        meta.get("base_provenance")):
        raise DeltaMismatch(
            f"base at {base_dir} carries a different provenance stamp "
            "than the delta's recorded base: wrong base generation "
            "(sync the full artifact)")
    return meta


def load_delta_plan(delta_dir: str, base_dir: str) -> dict:
    """Load the LEAF-ROW plan of a committed delta for device-resident
    consumers (serve/arena.py): which new rows are verbatim copies of
    base rows (gatherable in place on device) and the fresh rows' f64
    payloads (the only host->device upload a hot swap needs).

    Runs the same base validation as ``apply_delta`` (commit marker,
    kind, base generation by row count + provenance) but loads ONLY the
    O(changed) delta files -- neither the base table nor the descent
    arrays are touched, because the arena's fused kernel locates by
    brute leaf-tile streaming, not tree descent.  The bitwise proof
    (content sha256 of the full reconstructed arrays) needs the base
    rows and therefore lives on the ``apply_delta`` disk path; the
    arena's equivalent guarantee is structural -- kept columns are
    device-gathered from the already-resident base extent, and the
    f64->f32 column pack is elementwise, so delta-apply into the arena
    is bitwise a full re-pack of the reconstructed table (tests pin
    this).

    Returns ``{"meta", "n_leaves", "base_n_leaves", "base_version",
    "src_idx", "fresh": {field: rows}}`` with fresh rows aligned to
    ``np.flatnonzero(src_idx < 0)``.
    """
    from explicit_hybrid_mpc_tpu.utils import atomic

    meta = _validate_delta_base(delta_dir, base_dir)
    p = os.path.join(delta_dir, "src_idx.npy")
    try:
        src_idx = np.load(p)
    except (OSError, ValueError, EOFError) as e:
        raise atomic.CorruptArtifact(
            f"{p}: unreadable delta field ({e}); re-sync") from e
    L = int(meta["n_leaves"])
    if src_idx.shape[0] != L:
        raise atomic.CorruptArtifact(
            f"{delta_dir}: src_idx holds {src_idx.shape[0]} rows but "
            f"the marker committed {L}: torn delta")
    n_fresh = int((src_idx < 0).sum())
    fresh = {}
    for k in ("bary_M", "U", "V", "node_id"):
        fp = os.path.join(delta_dir, f"fresh_{k}.npy")
        try:
            rows = np.load(fp)
        except (OSError, ValueError, EOFError) as e:
            raise atomic.CorruptArtifact(
                f"{fp}: unreadable delta field ({e}); re-sync") from e
        if rows.shape[0] != n_fresh:
            raise atomic.CorruptArtifact(
                f"{fp}: {rows.shape[0]} fresh rows but src_idx marks "
                f"{n_fresh}: torn delta")
        fresh[k] = rows
    return {"meta": meta, "n_leaves": L,
            "base_n_leaves": int(meta["base_n_leaves"]),
            "base_version": meta.get("base_version"),
            "src_idx": np.asarray(src_idx, dtype=np.int64),
            "fresh": fresh}


def apply_delta(delta_dir: str, base_dir: str, out_dir: str,
                verify_base_checksums: bool = False) -> dict:
    """Reconstruct the FULL serving artifact at `out_dir` from a delta
    + its base.  Returns the delta meta.  The result is bitwise the
    publisher's table (content sha256s enforced; DeltaMismatch on a
    wrong base, CorruptArtifact on a torn delta or hash miss) and
    loads through ``ControllerRegistry.load_artifacts`` like any full
    artifact.  ``verify_base_checksums`` additionally re-hashes the
    base's field files against ITS meta (a full read -- deploy-time
    paranoia)."""
    from explicit_hybrid_mpc_tpu.online import descent as descent_mod
    from explicit_hybrid_mpc_tpu.online import export as export_mod
    from explicit_hybrid_mpc_tpu.online.descent import DescentTable
    from explicit_hybrid_mpc_tpu.utils import atomic

    meta = _validate_delta_base(delta_dir, base_dir)
    base_table = export_mod.load_leaf_table(
        base_dir, mmap=True, verify_checksum=verify_base_checksums)

    def _load(name: str) -> np.ndarray:
        p = os.path.join(delta_dir, name + ".npy")
        try:
            return np.load(p)
        except (OSError, ValueError, EOFError) as e:
            raise atomic.CorruptArtifact(
                f"{p}: unreadable delta field ({e}); re-sync") from e

    src_idx = _load("src_idx")
    L = int(meta["n_leaves"])
    if src_idx.shape[0] != L:
        raise atomic.CorruptArtifact(
            f"{delta_dir}: src_idx holds {src_idx.shape[0]} rows but "
            f"the marker committed {L}: torn delta")
    fresh = src_idx < 0
    kept = ~fresh
    fields = {}
    for k in _LEAF_FIELDS:
        fresh_rows = _load(f"fresh_{k}")
        base_arr = np.asarray(getattr(base_table, k))
        out = np.empty((L,) + base_arr.shape[1:], dtype=base_arr.dtype)
        out[kept] = base_arr[src_idx[kept]]
        out[fresh] = fresh_rows
        want = meta["array_sha"][k]
        if _sha(out) != want:
            raise atomic.CorruptArtifact(
                f"{delta_dir}: reconstructed {k} hashes "
                f"{_sha(out)[:12]}.. but the delta committed "
                f"{want[:12]}..: base or delta corrupted; sync the "
                "full artifact")
        fields[k] = out

    # -- descent reconstruction -------------------------------------------
    base_desc = np.load(os.path.join(base_dir, "descent.npz"))
    try:
        nb = int(meta["base_n_nodes"])
        if int(base_desc["children"].shape[0]) != nb:
            raise DeltaMismatch(
                f"base descent at {base_dir} has "
                f"{base_desc['children'].shape[0]} nodes, delta "
                f"expected {nb}: wrong base generation")
        idx = _load("desc_changed_idx")
        desc = {}
        for k in _DESC_FIELDS:
            arr = np.concatenate(
                [np.asarray(base_desc[k]), _load(f"desc_tail_{k}")],
                axis=0)
            arr[idx] = _load(f"desc_changed_{k}")
            want = meta["array_sha"][k]
            if _sha(arr) != want:
                raise atomic.CorruptArtifact(
                    f"{delta_dir}: reconstructed descent {k} does not "
                    "hash to the delta's commitment: base or delta "
                    "corrupted; sync the full artifact")
            desc[k] = arr
        # leaf_row is a pure function of the new leaf order
        # (online/descent.export_descent): recompute, never ship.
        leaf_row = np.full(desc["children"].shape[0], -1,
                           dtype=np.int32)
        leaf_row[fields["node_id"]] = np.arange(L, dtype=np.int32)
        dt = DescentTable(
            root_bary=np.asarray(base_desc["root_bary"]),
            root_node=np.asarray(base_desc["root_node"]),
            children=desc["children"], normal=desc["normal"],
            offset=desc["offset"], leaf_row=leaf_row,
            max_depth=int(meta["max_depth"]))
    finally:
        base_desc.close()

    # -- write the full artifact (save_artifacts layout + commit order) ----
    os.makedirs(out_dir, exist_ok=True)
    export_mod.invalidate_meta(out_dir)
    for k in _LEAF_FIELDS:
        np.save(os.path.join(out_dir, f"{k}.npy"), fields[k])
    descent_mod.save_descent(dt, os.path.join(out_dir, "descent.npz"))
    export_mod.commit_leaf_table(out_dir, L, int(meta["p"]),
                                 int(meta["n_u"]), meta.get("provenance"))
    return meta
