"""Problem-revision streams: what the rebuild daemon watches.

A *revision* is one observed change of the controlled plant (or of the
certification targets) that invalidates the currently-serving tree:
new dynamics parameters, a tightened eps.  Revisions are value
objects (JSON round-trippable, so a file can carry them between
processes); the daemon measures END-TO-END staleness from the moment a
revision is OBSERVED (``t_observed``, stamped by the source on the
monotonic clock) to the moment the rebuilt controller is live.

Sources:

- ``DriftSource``: the simulated plant-drift driver.  A seeded,
  bounded random walk perturbs one numeric constructor argument of a
  registered problem (``problems/registry.py``) -- the stand-in for a
  system-identification pipeline re-estimating plant parameters -- and
  optionally *verifies the drift is observable* by rolling the nominal
  and drifted plants open-loop through the closed-loop simulator
  (``sim/simulator.py``; ``plant_divergence``) and only emitting a
  revision once the trajectories diverge past a threshold.  The walk
  deliberately never touches ``theta_box``/bounds: the parameter box
  is the partition's root geometry, and a box change is a COLD-build
  event (partition/rebuild.RebuildError), not a warm revision.
- ``FileRevisionSource``: tails a JSONL file of revision records --
  the test/integration surface, and how an external watcher (a real
  sys-id job) feeds the daemon.  Tolerates a torn final line (the
  writer may still be appending).

Both implement the two-method ``RevisionSource`` protocol: ``poll()``
returns newly-observed revisions (non-blocking), ``close()`` releases
resources.  Sources never block the daemon's scheduler loop.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Revision:
    """One observed problem revision.

    ``problem_args`` uses the PartitionConfig convention: a sorted
    tuple of (key, value) pairs, drop-in for ``cfg.problem_args``.
    ``t_observed`` is on ``time.perf_counter()``'s clock -- staleness
    is measured against it, so it must never be a wall-clock stamp
    from another process (a file source re-stamps at read time: the
    daemon can only be held accountable for latency it can see)."""

    controller: str
    problem: str
    problem_args: tuple
    eps_a: float
    eps_r: float = 0.0
    seq: int = 0
    t_observed: float = 0.0
    note: str = ""

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["problem_args"] = [list(kv) for kv in self.problem_args]
        d.pop("t_observed")  # clock-local; re-stamped by the reader
        return d

    @classmethod
    def from_dict(cls, d: dict, controller: str = "default",
                  seq: int = 0) -> "Revision":
        args = d.get("problem_args") or ()
        if isinstance(args, dict):
            args = args.items()
        return cls(
            controller=str(d.get("controller", controller)),
            problem=d["problem"],
            problem_args=tuple(sorted((str(k), v) for k, v in args)),
            eps_a=float(d.get("eps_a", 1e-2)),
            eps_r=float(d.get("eps_r", 0.0)),
            seq=int(d.get("seq", seq)),
            t_observed=time.perf_counter(),
            note=str(d.get("note", "")))


class RevisionSource:
    """Protocol base: poll() -> newly observed revisions; close()."""

    def poll(self) -> list[Revision]:
        raise NotImplementedError

    def close(self) -> None:
        pass


class FileRevisionSource(RevisionSource):
    """JSONL revision stream: one revision dict per line, observed in
    file order as lines COMPLETE (a torn final line -- a writer still
    appending -- is retried on the next poll, never half-parsed).
    Each record needs at least ``problem``; see Revision.from_dict."""

    def __init__(self, path: str, controller: str = "default"):
        self.path = path
        self.controller = controller
        self._offset = 0
        self._seq = 0

    def poll(self) -> list[Revision]:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size <= self._offset:
            return []
        with open(self.path, "rb") as f:
            f.seek(self._offset)
            buf = f.read()
        out: list[Revision] = []
        consumed = 0
        for line in buf.splitlines(keepends=True):
            if not line.endswith(b"\n"):
                break  # torn tail: the writer is mid-append
            consumed += len(line)
            s = line.strip()
            if not s:
                continue
            try:
                d = json.loads(s)
            except json.JSONDecodeError:
                continue  # garbage line: skip, never wedge the stream
            self._seq += 1
            out.append(Revision.from_dict(d, controller=self.controller,
                                          seq=self._seq))
        self._offset += consumed
        return out


class _ProbeController:
    """Zero-input probe controller for the open-loop divergence roll
    (sim.simulate needs a theta -> (u, StepInfo) callable)."""

    def __init__(self, n_u: int):
        self._u = np.zeros(n_u)

    def __call__(self, theta):
        from explicit_hybrid_mpc_tpu.sim.simulator import StepInfo

        return self._u, StepInfo(eval_s=0.0, inside=True,
                                 cost_pred=float("nan"))


def plant_divergence(nominal, drifted, T: int = 20,
                     theta0: Optional[np.ndarray] = None) -> float:
    """Max state divergence of the drifted plant vs the nominal model
    over a T-step open-loop roll from a corner of the certified box --
    the drift-watch observable (a plant that tracks its model produces
    0.0; the DriftSource gates revision emission on it).  Runs through
    the closed-loop simulator's plant-rolling path (sim/simulator.py)
    with a zero-input probe controller."""
    from explicit_hybrid_mpc_tpu.sim import simulator

    if theta0 is None:
        theta0 = 0.8 * np.asarray(nominal.theta_ub, dtype=np.float64)
    ctrl = _ProbeController(nominal.n_u)
    a = simulator.simulate(nominal, ctrl, theta0, T)
    b = simulator.simulate(drifted, ctrl, theta0, T)
    return float(np.max(np.abs(a.states - b.states)))


class DriftSource(RevisionSource):
    """Simulated plant drift: a bounded random walk on one numeric
    constructor argument of a registered problem.

    Every ``period_s`` the walk advances one step; the drifted problem
    is instantiated through ``problems.registry.make`` and (when
    ``min_divergence`` > 0) its open-loop trajectory is compared
    against the nominal plant's (``plant_divergence``) -- a revision
    is emitted only once the drift is actually OBSERVABLE, so a
    dormant plant does not trigger rebuild churn.  ``eps_frac`` adds
    an independent walk on eps_a (certification-target drift).

    The walk is bounded to ``+-max_drift_frac`` around the base value:
    warm rebuild reuse decays with revision distance, and an unbounded
    walk would quietly turn every rebuild cold.  Deterministic under
    ``seed`` (the bench/test surface)."""

    def __init__(self, problem: str, problem_args: tuple = (),
                 controller: str = "default",
                 eps_a: float = 1e-2, eps_r: float = 0.0,
                 drift_arg: str = "u_max", drift_frac: float = 0.02,
                 max_drift_frac: float = 0.2, eps_frac: float = 0.0,
                 n_revisions: Optional[int] = 3, period_s: float = 0.0,
                 seed: int = 0, probe_T: int = 0,
                 min_divergence: float = 0.0):
        from explicit_hybrid_mpc_tpu.problems.registry import make

        if drift_frac < 0 or max_drift_frac <= 0:
            raise ValueError("drift_frac must be >= 0 and "
                             "max_drift_frac > 0")
        self.problem = problem
        self.controller = controller
        self.eps_a, self.eps_r = float(eps_a), float(eps_r)
        self.drift_arg = drift_arg
        self.drift_frac = float(drift_frac)
        self.max_drift_frac = float(max_drift_frac)
        self.eps_frac = float(eps_frac)
        self.n_revisions = n_revisions
        self.period_s = float(period_s)
        self.probe_T = int(probe_T)
        self.min_divergence = float(min_divergence)
        self._base_args = dict(problem_args)
        self._nominal = make(problem, **self._base_args)
        if drift_arg in ("theta_box", "theta_lb", "theta_ub"):
            raise ValueError(
                "the parameter box is the partition's root "
                "geometry: drifting it is a cold-build event, "
                "not a warm revision (pick a dynamics argument)")
        base = self._base_args.get(drift_arg,
                                   getattr(self._nominal, drift_arg, None))
        if base is None or not isinstance(base, (int, float)):
            raise ValueError(
                f"problem {problem!r} has no numeric constructor "
                f"argument {drift_arg!r} to drift")
        self._base_value = float(base)
        self._rng = np.random.default_rng(seed)
        self._frac = 0.0       # accumulated drift fraction of base
        self._eps_frac_state = 0.0
        self._seq = 0
        self._t_last = -float("inf")
        #: Optional emission gate: poll() emits nothing while it
        #: returns False.  The K-generation drives (bench.py
        #: --drift-walk, scripts/drift_smoke.py) gate revision k+1 on
        #: generation k being LIVE, so daemon-side coalescing -- the
        #: right behavior under a revision storm -- cannot shrink a
        #: fixed-K walk (a fast walk against a slow rebuild would
        #: otherwise supersede most of its revisions).
        self.gate = None

    @property
    def n_emitted(self) -> int:
        return self._seq

    def exhausted(self) -> bool:
        return (self.n_revisions is not None
                and self._seq >= self.n_revisions)

    def _advance(self) -> tuple[float, float]:
        # Bounded multiplicative random walk: each step moves the
        # accumulated drift fraction by up to +-drift_frac, clamped to
        # the max excursion (an unbounded walk would quietly turn
        # every warm rebuild cold).
        self._frac = float(np.clip(
            self._frac + self.drift_frac * self._rng.uniform(-1.0, 1.0),
            -self.max_drift_frac, self.max_drift_frac))
        val = self._base_value * (1.0 + self._frac)
        eps = self.eps_a
        if self.eps_frac > 0:
            self._eps_frac_state = float(np.clip(
                self._eps_frac_state
                + self.eps_frac * self._rng.uniform(-1.0, 1.0),
                -0.5, 0.5))
            eps = self.eps_a * (1.0 + self._eps_frac_state)
        return val, float(eps)

    def poll(self) -> list[Revision]:
        if self.exhausted():
            return []
        if self.gate is not None and not self.gate():
            return []
        now = time.perf_counter()
        if now - self._t_last < self.period_s:
            return []
        from explicit_hybrid_mpc_tpu.problems.registry import make

        val, eps = self._advance()
        args = dict(self._base_args)
        args[self.drift_arg] = val
        note = f"{self.drift_arg}={val:.6g}"
        if self.probe_T > 0 or self.min_divergence > 0:
            drifted = make(self.problem, **args)
            div = plant_divergence(self._nominal, drifted,
                                   T=max(self.probe_T, 1))
            note += f" divergence={div:.3g}"
            if div < self.min_divergence:
                # Drift not yet observable: keep walking silently.
                self._t_last = now
                return []
        self._t_last = now
        self._seq += 1
        return [Revision(
            controller=self.controller, problem=self.problem,
            problem_args=tuple(sorted(args.items())),
            eps_a=eps, eps_r=self.eps_r, seq=self._seq,
            t_observed=now, note=note)]
