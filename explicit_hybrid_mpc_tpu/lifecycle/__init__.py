"""Continuous rebuild lifecycle: plant-drift watch -> SLA-scheduled
warm rebuild -> delta-compressed publish -> fleet hot-swap.

The offline tree is a certificate for ONE problem revision; the moment
the plant drifts, the production story needs a loop nobody has to run
by hand.  This package chains the existing subsystems into that loop:

- ``revision.py``: the revision stream -- a ``RevisionSource``
  abstraction with a simulated plant-drift driver (``DriftSource``,
  built on ``sim/simulator.py`` + ``problems/registry.py``) and a
  JSONL file source for tests/external watchers;
- ``service.py``: the supervised daemon (``RebuildService``) that
  schedules warm rebuilds (partition/rebuild.py) under a wall-clock
  SLA with priority + coalescing, publishes each generation, and
  hot-swaps it into a ``serve.ControllerRegistry`` while traffic
  flows;
- ``delta.py``: delta-compressed serving artifacts -- only the
  invalidated/new leaf rows plus a base-version provenance pointer,
  applied server-side so replicas sync in O(changed), not O(tree);
- ``cli.py``: the ``main.py serve-rebuild`` surface
  (scripts/rebuild_service.py is the standalone wrapper).

docs/lifecycle.md is the prose spec (revision sources, SLA semantics,
delta format, staleness metric definitions).
"""

from explicit_hybrid_mpc_tpu.lifecycle.delta import (  # noqa: F401
    DeltaMismatch, apply_delta, delta_size_bytes, write_delta_artifact)
from explicit_hybrid_mpc_tpu.lifecycle.revision import (  # noqa: F401
    DriftSource, FileRevisionSource, Revision, RevisionSource,
    plant_divergence)
from explicit_hybrid_mpc_tpu.lifecycle.service import (  # noqa: F401
    LifecycleConfig, RebuildService)
