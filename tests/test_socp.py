"""SOC-constrained QP solver (oracle/socp.py).

Correctness strategy (no SOCP reference solver exists in this image):
1. QP limit: with zero cones, socp_solve must match ipm.qp_solve.
2. Linear encoding: a 2-dim SOC (s0 >= |s1|) is EXACTLY two linear rows;
   random problems with 2-dim cones must match the pure-QP encoding.
3. KKT self-certification: for convex problems, a point satisfying
   stationarity + primal/dual cone feasibility + complementarity to
   tolerance IS optimal -- the returned residuals + explicit dual-cone
   checks certify optimality without an external solver.
"""

import numpy as np
import pytest

import jax.numpy as jnp
from jax import vmap

from explicit_hybrid_mpc_tpu.oracle import ipm
from explicit_hybrid_mpc_tpu.oracle.socp import socp_solve


def _rand_qp(rng, nz=6, nl=8):
    B = rng.normal(size=(nz, nz))
    Q = B @ B.T + nz * np.eye(nz)
    q = rng.normal(size=nz)
    Al = rng.normal(size=(nl, nz))
    bl = np.abs(rng.normal(size=nl)) + 0.5
    return map(jnp.asarray, (Q, q, Al, bl))


def _no_cones(nz, m=3):
    return jnp.zeros((0, m, nz)), jnp.zeros((0, m))


def test_qp_limit_matches_ipm():
    rng = np.random.default_rng(0)
    for _ in range(5):
        Q, q, Al, bl = _rand_qp(rng)
        Ac, bc = _no_cones(6)
        a = socp_solve(Q, q, Al, bl, Ac, bc)
        b = ipm.qp_solve(Q, q, Al, bl)
        assert bool(a.converged) and bool(b.converged)
        np.testing.assert_allclose(a.obj, b.obj, rtol=1e-7, atol=1e-9)
        np.testing.assert_allclose(a.z, b.z, rtol=1e-6, atol=1e-8)


def test_dim2_cone_equals_linear_rows():
    """SOC_2 = {s0 >= |s1|} is exactly two linear rows: with
    s = bc - Ac z,  s0 -+ s1 >= 0  <=>  (Ac0 -+ Ac1) z <= bc0 -+ bc1."""
    rng = np.random.default_rng(1)
    for _ in range(5):
        Q, q, Al, bl = _rand_qp(rng)
        K = 2
        Ac = rng.normal(size=(K, 2, 6)) * 0.7
        # bc chosen so z=0 is strictly cone-interior: s = bc, s0 > |s1|.
        bc = np.stack([np.abs(rng.normal(size=K)) + 2.0,
                       rng.normal(size=K) * 0.3], axis=1)
        sol = socp_solve(Q, q, Al, bl, jnp.asarray(Ac), jnp.asarray(bc))
        # Linear encoding: s0 - s1 >= 0 -> (Ac0 - Ac1) z <= bc0 - bc1
        #                  s0 + s1 >= 0 -> (Ac0 + Ac1) z <= bc0 + bc1
        rows = np.concatenate([Ac[:, 0] - Ac[:, 1], Ac[:, 0] + Ac[:, 1]])
        rhs = np.concatenate([bc[:, 0] - bc[:, 1], bc[:, 0] + bc[:, 1]])
        Al2 = jnp.concatenate([Al, jnp.asarray(rows)])
        bl2 = jnp.concatenate([bl, jnp.asarray(rhs)])
        ref = ipm.qp_solve(Q, q, Al2, bl2)
        assert bool(sol.converged) and bool(ref.converged)
        np.testing.assert_allclose(sol.obj, ref.obj, rtol=1e-6, atol=1e-8)
        np.testing.assert_allclose(sol.z, ref.z, rtol=1e-5, atol=1e-7)


def test_active_cone_kkt_certificate():
    """3-dim cones tightened until active; returned solutions must be
    certified KKT points: residuals small, primal in the cone.  A small
    minority of randomly-degenerate instances may honestly report
    unconverged (fixed iterations, no line search) -- those must NOT
    claim convergence, and at least 7/8 must fully converge."""
    rng = np.random.default_rng(2)
    n_active = n_conv = 0
    for _ in range(8):
        Q, q, Al, bl = _rand_qp(rng)
        K = 3
        Ac = rng.normal(size=(K, 3, 6)) * 0.8
        bc = np.stack([np.abs(rng.normal(size=K)) * 0.5 + 0.2,
                       rng.normal(size=K) * 0.2,
                       rng.normal(size=K) * 0.2], axis=1)
        sol = socp_solve(Q, q, Al, bl, jnp.asarray(Ac), jnp.asarray(bc),
                         n_iter=60)
        if not bool(sol.converged):
            continue
        n_conv += 1
        z = np.asarray(sol.z)
        s = bc - Ac @ z
        margin = s[:, 0] - np.linalg.norm(s[:, 1:], axis=1)
        assert np.all(margin >= -1e-6)      # primal cone feasibility
        n_active += int(np.sum(margin < 1e-4))
    assert n_conv >= 7, f"only {n_conv}/8 converged"
    assert n_active > 0, "no converged instance had an active cone"


def test_infeasible_cone_flagged():
    """Contradictory cones (s0 forced negative) must not report
    converged-feasible."""
    rng = np.random.default_rng(3)
    Q, q, Al, bl = _rand_qp(rng)
    nz = 6
    # cone needs e'z <= -1 AND linear row e'z >= 1 (via -e'z <= -1).
    Ac = np.zeros((1, 3, nz))
    Ac[0, 0, 0] = 1.0
    bc = np.array([[-1.0, 0.0, 0.0]])
    Al2 = jnp.concatenate([Al, -jnp.eye(nz)[:1]])
    bl2 = jnp.concatenate([bl, jnp.asarray([-1.0])])
    sol = socp_solve(Q, q, Al2, bl2, jnp.asarray(Ac), jnp.asarray(bc))
    assert not bool(sol.converged)
    assert not bool(sol.feasible)


def test_vmap_batching():
    rng = np.random.default_rng(4)
    Qs, qs, Als, bls, Acs, bcs = [], [], [], [], [], []
    for _ in range(8):
        Q, q, Al, bl = _rand_qp(rng)
        Qs.append(Q), qs.append(q), Als.append(Al), bls.append(bl)
        Ac = rng.normal(size=(2, 3, 6)) * 0.5
        bc = np.stack([np.abs(rng.normal(size=2)) + 1.0,
                       rng.normal(size=2) * 0.3,
                       rng.normal(size=2) * 0.3], axis=1)
        Acs.append(jnp.asarray(Ac)), bcs.append(jnp.asarray(bc))
    stack = lambda xs: jnp.stack(xs)  # noqa: E731
    batched = vmap(socp_solve)(stack(Qs), stack(qs), stack(Als),
                               stack(bls), stack(Acs), stack(bcs))
    for i in range(8):
        single = socp_solve(Qs[i], qs[i], Als[i], bls[i], Acs[i], bcs[i])
        np.testing.assert_allclose(batched.obj[i], single.obj,
                                   rtol=1e-9, atol=1e-12)
        assert bool(batched.converged[i]) == bool(single.converged)
