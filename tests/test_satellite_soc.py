"""SOC satellite config (problems/satellite_soc.py + oracle/soc_point.py).

The cone constraint ||(u_wx, u_wy)|| <= r is sandwiched by boxes:
box(r) contains ball(r) contains box(r/sqrt(2)), so the SOC optimal cost
lies between the two box-variant QP costs -- an external-solver-free
correctness check of the whole SOC path on a real MPC problem.
"""

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.oracle.soc_point import SOCPointOracle
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def soc_problem():
    return make("satellite_soc", N=3)


@pytest.fixture(scope="module")
def points(soc_problem):
    rng = np.random.default_rng(11)
    return rng.uniform(soc_problem.theta_lb, soc_problem.theta_ub,
                       size=(4, soc_problem.n_theta))


def test_axes1_rejected():
    with pytest.raises(ValueError, match="axes=3"):
        make("satellite_soc", axes=1)


def test_cost_sandwiched_by_boxes(soc_problem, points):
    r = soc_problem.soc_radius
    outer = Oracle(make("satellite", N=3, u_w_max=r), backend="cpu")
    inner = Oracle(make("satellite", N=3, u_w_max=r / np.sqrt(2)),
                   backend="cpu")
    soc = SOCPointOracle(soc_problem)
    V_o = outer.solve_vertices(points).Vstar
    V_i = inner.solve_vertices(points).Vstar
    _, _, _, V_s, dstar = soc.solve_vertices(points)
    assert np.all(dstar >= 0), "SOC MICP must be feasible on the box"
    tol = 1e-6 * np.maximum(1.0, np.abs(V_s))
    # NOTE the inner-box bound only holds for the transverse channels
    # the cone couples; the z-wheel keeps the full box in ALL variants
    # only if u_w_max matches -- the inner problem shrank all three, so
    # it is a valid UPPER bound a fortiori.
    assert np.all(V_o.astype(float) <= V_s + tol), (V_o, V_s)
    assert np.all(V_s <= V_i.astype(float) + tol), (V_s, V_i)


def test_cone_binds_somewhere(soc_problem, points):
    """On wheel-heavy maneuvers the optimizer pushes the transverse
    torque to the envelope: some step's cone margin ~ 0."""
    soc = SOCPointOracle(soc_problem)
    V, conv, u0, Vstar, dstar = soc.solve_vertices(points)
    Ac, bc = soc_problem.soc_cones()
    can = soc_problem.canonical
    import jax.numpy as jnp
    from explicit_hybrid_mpc_tpu.oracle.socp import socp_solve

    min_margin = np.inf
    for p in range(len(points)):
        d = int(dstar[p])
        q = can.f[d] + can.F[d] @ points[p]
        b = can.w[d] + can.S[d] @ points[p]
        sol = socp_solve(jnp.asarray(can.H[d]), jnp.asarray(q),
                         jnp.asarray(can.G[d]), jnp.asarray(b),
                         jnp.asarray(Ac), jnp.asarray(bc), n_iter=60)
        s = bc - Ac @ np.asarray(sol.z)
        margin = s[:, 0] - np.linalg.norm(s[:, 1:], axis=1)
        min_margin = min(min_margin, margin.min())
    assert min_margin < 1e-3, (
        f"cone never binds (min margin {min_margin}); the config is not "
        "exercising the SOC path")


def test_online_fixed_delta_closed_loop(soc_problem, points):
    """Semi-explicit style deployment: fixed-commutation SOCP at each
    step drives the plant without constraint violation."""
    soc = SOCPointOracle(soc_problem)
    _, _, _, _, dstar = soc.solve_vertices(points[:1])
    d = int(dstar[0])
    x = soc_problem.state_of_theta(points[0])
    r = soc_problem.soc_radius
    for _ in range(4):
        th = soc_problem.theta_of_state(x)
        th = np.clip(th, soc_problem.theta_lb, soc_problem.theta_ub)
        u0, V, conv, _z = soc.solve_fixed(th[None], np.array([d]))
        assert bool(conv[0]), "online fixed-delta SOCP must converge"
        u = u0[0]
        assert np.linalg.norm(u[:2]) <= r * (1 + 1e-6), (
            "applied transverse wheel torque violates the cone")
        x = soc_problem.plant_step(x, u)
        assert np.all(np.isfinite(x))


# -- r5: certified SOC partitions (oracle/soc_oracle.py) --------------------

def test_soc_oracle_vertex_solution_matches_point_oracle():
    """SOCOracle's point grid must agree with the proven SOCPointOracle
    on values and commutation choice, while adding certificate-grade
    gradients and the strict conv flag the partition engine needs."""
    import numpy as np

    from explicit_hybrid_mpc_tpu.oracle.soc_oracle import SOCOracle
    from explicit_hybrid_mpc_tpu.oracle.soc_point import SOCPointOracle
    from explicit_hybrid_mpc_tpu.problems.registry import make

    prob = make("satellite_soc", N=3)
    o1 = SOCOracle(prob, backend="cpu")
    o2 = SOCPointOracle(prob)
    rng = np.random.default_rng(0)
    ths = rng.uniform(prob.theta_lb, prob.theta_ub,
                      size=(4, prob.n_theta))
    s1 = o1.solve_vertices(ths)
    V2, _usable2, _u02, _Vstar2, dstar2 = o2.solve_vertices(ths)
    m = s1.conv
    assert m.mean() > 0.9, "tangent rescue regressed strict convergence"
    np.testing.assert_allclose(s1.V[m], V2[m], rtol=1e-6, atol=1e-8)
    np.testing.assert_array_equal(s1.dstar, dstar2)


def test_soc_envelope_gradients_match_finite_differences():
    import numpy as np

    from explicit_hybrid_mpc_tpu.oracle.soc_oracle import SOCOracle
    from explicit_hybrid_mpc_tpu.problems.registry import make

    prob = make("satellite_soc", N=3)
    o = SOCOracle(prob, backend="cpu")
    rng = np.random.default_rng(3)
    th = rng.uniform(0.5 * prob.theta_lb, 0.5 * prob.theta_ub)
    sol = o.solve_vertices(th[None])
    d = int(sol.dstar[0])
    assert d >= 0 and sol.conv[0, d]
    g = sol.grad[0, d]
    eps = 1e-5
    for ax in range(prob.n_theta):
        e = np.zeros(prob.n_theta)
        e[ax] = eps
        Vp = o.solve_vertices((th + e)[None]).V[0, d]
        Vm = o.solve_vertices((th - e)[None]).V[0, d]
        fd = (Vp - Vm) / (2 * eps)
        assert abs(fd - g[ax]) / (1 + abs(fd)) < 1e-5, (ax, fd, g[ax])


def test_soc_partition_certifies_slice():
    """End-to-end eps-certified partition over an SOC problem: the full
    QP/SOCP MICP class (SURVEY.md section 1 [P]; r4 verdict missing #3).
    Joint stage-2/Farkas queries run on the LINEAR RELAXATION (sound
    lower bounds; soc_oracle.py docstring)."""
    from explicit_hybrid_mpc_tpu.config import PartitionConfig
    from explicit_hybrid_mpc_tpu.oracle.soc_oracle import SOCOracle
    from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
    from explicit_hybrid_mpc_tpu.problems.registry import make

    prob = make("satellite_soc", N=3, h_box=0.15, omega_box=0.015)
    cfg = PartitionConfig(problem="satellite_soc", eps_a=4.0, eps_r=0.5,
                          backend="cpu", batch_simplices=64, max_depth=16,
                          max_steps=4000, semi_explicit_boundary_depth=8,
                          time_budget_s=300)
    res = build_partition(prob, cfg,
                          oracle=SOCOracle(prob, backend="cpu"))
    assert res.stats["regions"] > 50
    assert res.stats["uncertified"] == 0


def test_soc_oracle_rejects_serial_and_mesh():
    import pytest as _pytest

    from explicit_hybrid_mpc_tpu.oracle.soc_oracle import SOCOracle
    from explicit_hybrid_mpc_tpu.problems.registry import make

    prob = make("satellite_soc", N=3)
    with _pytest.raises(ValueError, match="single-device"):
        SOCOracle(prob, backend="serial")
    with _pytest.raises(ValueError, match="rescue_iter"):
        SOCOracle(prob, backend="cpu", rescue_iter=30)
    with _pytest.raises(NotImplementedError, match="QP-scope"):
        SOCOracle(prob, backend="cpu").point_feasibility(
            prob.theta_lb[None], [0])


def test_soc_cpu_twin_mirrors_solver_settings(soc_problem):
    """ADVICE r5: the device-failure fallback twin must carry the SAME
    solver semantics as the main oracle -- n_iter drives the LP
    joint-bound programs, and a twin with the default schedule would
    break the bit-compatibility contract of Oracle.cpu_twin."""
    from explicit_hybrid_mpc_tpu.oracle.soc_oracle import SOCOracle

    o = SOCOracle(soc_problem, soc_n_iter=41, backend="cpu", n_iter=22,
                  points_cap=64)
    twin = o.cpu_twin(soc_problem)
    assert isinstance(twin, SOCOracle)
    assert twin._soc_n_iter == o._soc_n_iter == 41
    assert twin.n_iter + twin.n_f32 == o.n_iter + o.n_f32 == 22
    assert twin.precision == o.precision
    assert twin.points_cap == o.points_cap == 64
    assert twin.backend == "cpu"
