"""SOC satellite config (problems/satellite_soc.py + oracle/soc_point.py).

The cone constraint ||(u_wx, u_wy)|| <= r is sandwiched by boxes:
box(r) contains ball(r) contains box(r/sqrt(2)), so the SOC optimal cost
lies between the two box-variant QP costs -- an external-solver-free
correctness check of the whole SOC path on a real MPC problem.
"""

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.oracle.soc_point import SOCPointOracle
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def soc_problem():
    return make("satellite_soc", N=3)


@pytest.fixture(scope="module")
def points(soc_problem):
    rng = np.random.default_rng(11)
    return rng.uniform(soc_problem.theta_lb, soc_problem.theta_ub,
                       size=(4, soc_problem.n_theta))


def test_axes1_rejected():
    with pytest.raises(ValueError, match="axes=3"):
        make("satellite_soc", axes=1)


def test_cost_sandwiched_by_boxes(soc_problem, points):
    r = soc_problem.soc_radius
    outer = Oracle(make("satellite", N=3, u_w_max=r), backend="cpu")
    inner = Oracle(make("satellite", N=3, u_w_max=r / np.sqrt(2)),
                   backend="cpu")
    soc = SOCPointOracle(soc_problem)
    V_o = outer.solve_vertices(points).Vstar
    V_i = inner.solve_vertices(points).Vstar
    _, _, _, V_s, dstar = soc.solve_vertices(points)
    assert np.all(dstar >= 0), "SOC MICP must be feasible on the box"
    tol = 1e-6 * np.maximum(1.0, np.abs(V_s))
    # NOTE the inner-box bound only holds for the transverse channels
    # the cone couples; the z-wheel keeps the full box in ALL variants
    # only if u_w_max matches -- the inner problem shrank all three, so
    # it is a valid UPPER bound a fortiori.
    assert np.all(V_o.astype(float) <= V_s + tol), (V_o, V_s)
    assert np.all(V_s <= V_i.astype(float) + tol), (V_s, V_i)


def test_cone_binds_somewhere(soc_problem, points):
    """On wheel-heavy maneuvers the optimizer pushes the transverse
    torque to the envelope: some step's cone margin ~ 0."""
    soc = SOCPointOracle(soc_problem)
    V, conv, u0, Vstar, dstar = soc.solve_vertices(points)
    Ac, bc = soc_problem.soc_cones()
    can = soc_problem.canonical
    import jax.numpy as jnp
    from explicit_hybrid_mpc_tpu.oracle.socp import socp_solve

    min_margin = np.inf
    for p in range(len(points)):
        d = int(dstar[p])
        q = can.f[d] + can.F[d] @ points[p]
        b = can.w[d] + can.S[d] @ points[p]
        sol = socp_solve(jnp.asarray(can.H[d]), jnp.asarray(q),
                         jnp.asarray(can.G[d]), jnp.asarray(b),
                         jnp.asarray(Ac), jnp.asarray(bc), n_iter=60)
        s = bc - Ac @ np.asarray(sol.z)
        margin = s[:, 0] - np.linalg.norm(s[:, 1:], axis=1)
        min_margin = min(min_margin, margin.min())
    assert min_margin < 1e-3, (
        f"cone never binds (min margin {min_margin}); the config is not "
        "exercising the SOC path")


def test_online_fixed_delta_closed_loop(soc_problem, points):
    """Semi-explicit style deployment: fixed-commutation SOCP at each
    step drives the plant without constraint violation."""
    soc = SOCPointOracle(soc_problem)
    _, _, _, _, dstar = soc.solve_vertices(points[:1])
    d = int(dstar[0])
    x = soc_problem.state_of_theta(points[0])
    r = soc_problem.soc_radius
    for _ in range(4):
        th = soc_problem.theta_of_state(x)
        th = np.clip(th, soc_problem.theta_lb, soc_problem.theta_ub)
        u0, V, conv, _z = soc.solve_fixed(th[None], np.array([d]))
        assert bool(conv[0]), "online fixed-delta SOCP must converge"
        u = u0[0]
        assert np.linalg.norm(u[:2]) <= r * (1 + 1e-6), (
            "applied transverse wheel torque violates the cone")
        x = soc_problem.plant_step(x, u)
        assert np.all(np.isfinite(x))
