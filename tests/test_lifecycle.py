"""Continuous rebuild lifecycle (explicit_hybrid_mpc_tpu/lifecycle/).

Contract tests for ISSUE 15: revision sources (drift walk + JSONL
tail), delta-compressed artifacts (bitwise-identical apply, loud
rejection of wrong bases / corruption), the live daemon (end-to-end
revision -> warm rebuild -> delta publish -> hot swap under traffic,
coalescing, failure containment, crash-mid-publish), the K-generation
ledger-pruning walk (the PR-10 bounded-chain claim), and the obs /
health / gate wiring.
"""

import dataclasses
import importlib.util
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.faults import injector as faults_inj
from explicit_hybrid_mpc_tpu.faults.plan import FaultPlan, FaultSpec
from explicit_hybrid_mpc_tpu.lifecycle import (DeltaMismatch, DriftSource,
                                               FileRevisionSource,
                                               LifecycleConfig,
                                               RebuildService, Revision,
                                               RevisionSource, apply_delta,
                                               delta_size_bytes,
                                               plant_divergence,
                                               write_delta_artifact)
from explicit_hybrid_mpc_tpu.obs import Obs
from explicit_hybrid_mpc_tpu.obs.health import HealthMonitor
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.partition.rebuild import warm_rebuild
from explicit_hybrid_mpc_tpu.problems.registry import make
from explicit_hybrid_mpc_tpu.serve.registry import ControllerRegistry
from explicit_hybrid_mpc_tpu.utils.atomic import CorruptArtifact

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

DI_ARGS = (("N", 3), ("theta_box", 1.5))


@pytest.fixture(scope="module")
def di_problem():
    return make("double_integrator", **dict(DI_ARGS))


@pytest.fixture(scope="module")
def di_cfg():
    return PartitionConfig(problem="double_integrator",
                           problem_args=DI_ARGS, eps_a=0.3,
                           backend="cpu", batch_simplices=128)


@pytest.fixture(scope="module")
def prior(di_problem, di_cfg):
    return build_partition(di_problem, di_cfg)


@pytest.fixture(scope="module")
def base_dir(prior, tmp_path_factory):
    """The prior generation's FULL serving artifact (delta base)."""
    from explicit_hybrid_mpc_tpu.serve.registry import save_artifacts

    d = str(tmp_path_factory.mktemp("lc") / "base")
    save_artifacts(prior.tree, prior.roots, d)
    return d


@pytest.fixture(scope="module")
def revised(di_cfg, prior):
    """A plant-drifted warm rebuild chained on `prior` IN MEMORY (the
    daemon's hot-loop shape: PartitionResult prior, no pickle)."""
    prob2 = make("double_integrator", **dict(DI_ARGS), u_max=0.95)
    cfg2 = dataclasses.replace(
        di_cfg, problem_args=DI_ARGS + (("u_max", 0.95),))
    return warm_rebuild(prob2, cfg2, prior)


class ListSource(RevisionSource):
    """Test source: hands out a scripted revision list, then dries up."""

    def __init__(self, revisions):
        self._revs = list(revisions)

    def poll(self):
        out, self._revs = self._revs, []
        return out


class StagedSource(RevisionSource):
    """Test source releasing revision batches behind ready-gates, so
    enqueue-vs-claim interleavings are deterministic."""

    def __init__(self, stages):
        self._stages = list(stages)  # [(ready_fn, [revisions])]

    def poll(self):
        if self._stages and self._stages[0][0]():
            return self._stages.pop(0)[1]
        return []


def _rev(seq, controller="di", eps=0.3, extra=(), problem_args=DI_ARGS):
    return Revision(controller=controller, problem="double_integrator",
                    problem_args=tuple(sorted(problem_args + extra)),
                    eps_a=eps, seq=seq, t_observed=time.perf_counter())


# -- chained-prior ergonomics (satellite 1) --------------------------------


def test_warm_rebuild_accepts_partition_result(di_problem, di_cfg,
                                               prior):
    res = warm_rebuild(di_problem, di_cfg, prior)
    assert res.stats["rebuild_prior_source"] == "result"
    assert res.stats["rebuild_reuse_frac"] == 1.0
    assert res.stats["subdivision_solves"] == 0


def test_tree_clone_matches_pickle_roundtrip(prior):
    import pickle

    a = prior.tree.clone()
    b = pickle.loads(pickle.dumps(prior.tree))
    sa, sb = a.__getstate__(), b.__getstate__()
    assert set(sa) == set(sb)
    for k in sa:
        va, vb = sa[k], sb[k]
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            assert np.array_equal(va, vb), k
        else:
            assert va == vb, k
    # Deep copy: mutating the clone leaves the original untouched.
    a.excl_events.append((0, 0, np.inf))
    assert len(prior.tree.excl_events) == len(b.excl_events)


# -- revision sources ------------------------------------------------------


def test_file_revision_source_tails_and_tolerates_torn_tail(tmp_path):
    p = str(tmp_path / "revs.jsonl")
    full = json.dumps({"problem": "double_integrator",
                       "problem_args": {"N": 3}, "eps_a": 0.25})
    with open(p, "w") as f:
        f.write(full + "\n")
        f.write(full + "\n")
        f.write('{"problem": "double_int')  # torn: writer mid-append
    src = FileRevisionSource(p, controller="di")
    revs = src.poll()
    assert [r.seq for r in revs] == [1, 2]
    assert revs[0].controller == "di"
    assert revs[0].problem_args == (("N", 3),)
    assert revs[0].eps_a == 0.25
    assert src.poll() == []  # torn tail not consumed
    with open(p, "a") as f:
        f.write('egrator"}\n')
    revs = src.poll()
    assert len(revs) == 1 and revs[0].seq == 3
    assert revs[0].problem == "double_integrator"


def test_drift_source_deterministic_bounded_and_exhausts():
    kw = dict(problem_args=DI_ARGS, eps_a=0.3, drift_arg="u_max",
              drift_frac=0.05, max_drift_frac=0.2, n_revisions=4,
              seed=3)
    a = DriftSource("double_integrator", **kw)
    b = DriftSource("double_integrator", **kw)
    ra = [r for _ in range(10) for r in a.poll()]
    rb = [r for _ in range(10) for r in b.poll()]
    assert len(ra) == 4 and a.exhausted()
    assert [r.problem_args for r in ra] == [r.problem_args for r in rb]
    for r in ra:
        u = dict(r.problem_args)["u_max"]
        assert abs(u - 1.0) <= 0.2 + 1e-12  # bounded walk
    assert len({r.problem_args for r in ra}) > 1  # it does drift


def test_drift_source_refuses_box_drift_and_unknown_arg():
    with pytest.raises(ValueError, match="root geometry"):
        DriftSource("double_integrator", drift_arg="theta_box")
    with pytest.raises(ValueError, match="no numeric"):
        DriftSource("double_integrator", drift_arg="nonsense")


def test_plant_divergence_observable(di_problem):
    same = make("double_integrator", **dict(DI_ARGS))
    assert plant_divergence(di_problem, same, T=10) == 0.0
    drifted = make("double_integrator", **dict(DI_ARGS), dt=0.3)
    assert plant_divergence(di_problem, drifted, T=10) > 0.0


def test_drift_source_gates_on_divergence():
    # u_max is a CONSTRAINT parameter: the open-loop probe sees zero
    # divergence, so a min_divergence gate must suppress emission.
    src = DriftSource("double_integrator", problem_args=DI_ARGS,
                      drift_arg="u_max", drift_frac=0.05,
                      n_revisions=3, probe_T=5, min_divergence=1e-9)
    assert [r for _ in range(5) for r in src.poll()] == []
    # dt drifts the dynamics: observable, so revisions flow.
    src2 = DriftSource("double_integrator", problem_args=DI_ARGS,
                       drift_arg="dt", drift_frac=0.05,
                       n_revisions=2, probe_T=5, min_divergence=1e-9)
    revs = [r for _ in range(5) for r in src2.poll()]
    assert len(revs) == 2
    assert all("divergence" in r.note for r in revs)


# -- delta artifacts -------------------------------------------------------


def test_delta_apply_bitwise_identical_to_full(revised, base_dir,
                                               tmp_path):
    from explicit_hybrid_mpc_tpu.online import descent as descent_mod
    from explicit_hybrid_mpc_tpu.online import export as export_mod
    from explicit_hybrid_mpc_tpu.serve.registry import save_artifacts

    delta_dir = str(tmp_path / "v1.delta")
    stats = write_delta_artifact(revised.tree, revised.roots, delta_dir,
                                 base_dir, base_version="v0")
    assert stats["n_kept"] > 0
    out_dir = str(tmp_path / "v1")
    meta = apply_delta(delta_dir, base_dir, out_dir)
    assert meta["kind"] == "ehm-delta-v1"
    full_dir = str(tmp_path / "v1full")
    save_artifacts(revised.tree, revised.roots, full_dir)
    ta = export_mod.load_leaf_table(out_dir)
    tb = export_mod.load_leaf_table(full_dir)
    for k in ("bary_M", "U", "V", "delta", "node_id"):
        assert np.array_equal(np.asarray(getattr(ta, k)),
                              np.asarray(getattr(tb, k))), k
    da = descent_mod.load_descent(os.path.join(out_dir, "descent.npz"))
    db = descent_mod.load_descent(os.path.join(full_dir, "descent.npz"))
    for k in ("root_bary", "root_node", "children", "normal", "offset",
              "leaf_row"):
        assert np.array_equal(np.asarray(getattr(da, k)),
                              np.asarray(getattr(db, k))), k
    assert da.max_depth == db.max_depth
    # The point of the format: the delta ships a fraction of the tree.
    assert stats["delta_bytes"] < 0.5 * delta_size_bytes(full_dir)
    # The applied dir is a first-class artifact: registry-loadable
    # with provenance enforcement.
    reg = ControllerRegistry()
    reg.load_artifacts("di", "v1", out_dir,
                       expect_provenance=revised.tree.provenance,
                       strict=True)


def test_delta_rejects_wrong_base(revised, base_dir, tmp_path, prior):
    from explicit_hybrid_mpc_tpu.serve.registry import save_artifacts

    delta_dir = str(tmp_path / "d.delta")
    write_delta_artifact(revised.tree, revised.roots, delta_dir,
                         base_dir, base_version="v0")
    # A DIFFERENT base generation (the revised tree's own full
    # artifact): provenance stamp differs from the recorded base.
    wrong = str(tmp_path / "wrong_base")
    save_artifacts(revised.tree, revised.roots, wrong)
    with pytest.raises(DeltaMismatch, match="provenance|generation"):
        apply_delta(delta_dir, wrong, str(tmp_path / "out"))


def test_delta_write_needs_committed_base(revised, tmp_path):
    bare = str(tmp_path / "bare")
    os.makedirs(bare)
    with pytest.raises(DeltaMismatch, match="meta.json"):
        write_delta_artifact(revised.tree, revised.roots,
                             str(tmp_path / "d.delta"), bare)


def test_delta_apply_detects_corruption(revised, base_dir, tmp_path):
    delta_dir = str(tmp_path / "d.delta")
    write_delta_artifact(revised.tree, revised.roots, delta_dir,
                         base_dir, base_version="v0")
    # Flip one byte of a fresh leaf row: the content-sha commitment
    # must refuse to serve the franken-table.
    p = os.path.join(delta_dir, "fresh_U.npy")
    with open(p, "r+b") as f:
        f.seek(-1, os.SEEK_END)
        b = f.read(1)
        f.seek(-1, os.SEEK_END)
        f.write(bytes([b[0] ^ 0x40]))
    with pytest.raises(CorruptArtifact, match="hash|corrupted"):
        apply_delta(delta_dir, base_dir, str(tmp_path / "out"))
    # A delta with no commit marker is torn, not legacy.
    os.unlink(os.path.join(delta_dir, "delta_meta.json"))
    with pytest.raises(CorruptArtifact, match="never .*committed"):
        apply_delta(delta_dir, base_dir, str(tmp_path / "out2"))


# -- the live daemon -------------------------------------------------------


def test_service_e2e_swap_under_load(di_cfg, prior, tmp_path):
    """THE acceptance demo: the daemon observes revisions, warm-
    rebuilds, publishes deltas, and the registry hot-swaps while a
    scheduler serves traffic -- 0 dropped, 0 torn (every result
    bitwise equals a fresh load of its version's artifact)."""
    from explicit_hybrid_mpc_tpu.serve.scheduler import RequestScheduler

    obs = Obs("jsonl", path=str(tmp_path / "lc.obs.jsonl"))
    reg = ControllerRegistry(obs=obs)
    src = DriftSource("double_integrator", problem_args=DI_ARGS,
                      controller="di", eps_a=0.3, drift_arg="u_max",
                      drift_frac=0.05, n_revisions=2, seed=5)
    svc = RebuildService(
        src, di_cfg,
        cfg=LifecycleConfig(artifacts_root=str(tmp_path / "art"),
                            sla_s=300.0),
        registry=reg, prior={"di": prior}, obs=obs)
    src.gate = (lambda: len(svc.generations) + svc.n_failures
                >= src.n_emitted)
    svc.start()
    assert svc.wait_idle(timeout=300, target_generations=1)

    sched = RequestScheduler(reg, "di", max_batch=32, obs=obs)
    served, dropped = [], []
    stop = threading.Event()
    rng = np.random.default_rng(0)

    def load():
        while not stop.is_set():
            thetas = rng.uniform(-1.4, 1.4, size=(4, 2))
            try:
                served.extend(
                    zip(thetas, sched.submit_batch(thetas).result(30)))
            except Exception as e:  # noqa: BLE001 -- a drop IS the verdict
                dropped.append(e)

    t = threading.Thread(target=load, daemon=True)
    t.start()
    assert svc.wait_idle(timeout=300, target_generations=2)
    time.sleep(0.1)
    stop.set()
    t.join(30)
    sched.close()
    svc.close()
    obs.close()

    assert not dropped
    assert served
    assert svc.n_failures == 0
    assert len(svc.generations) == 2
    # Generation 0 seeded from a prior result publishes FULL (no base
    # on disk yet); generation 1 ships a delta.
    assert svc.generations[0]["published"] == "full"
    assert svc.generations[1]["published"] == "delta"
    assert reg.active_version("di") == svc.generations[1]["version"]
    for g in svc.generations:
        assert g["staleness_s"] > 0
        assert g["reuse_frac"] is not None  # every generation was warm
    # Torn audit: every served value bitwise vs its version's artifact.
    dirs = {g["version"]: g["artifact_dir"] for g in svc.generations}
    by_ver = {}
    for th, r in served:
        by_ver.setdefault(r.version, []).append((th, r))
    assert set(by_ver) <= set(dirs)
    for ver, rows in by_ver.items():
        ref_reg = ControllerRegistry()
        ref_reg.load_artifacts("ref", ver, dirs[ver])
        with ref_reg.lease("ref") as v:
            ref = v.server.evaluate(np.stack([th for th, _ in rows]))
        for j, (_th, r) in enumerate(rows):
            if r.fallback is None:
                assert np.array_equal(r.u, np.asarray(ref.u[j]))
    # The stream carries the lifecycle block obs_report renders.
    from explicit_hybrid_mpc_tpu.obs.sink import load_jsonl

    recs = load_jsonl(str(tmp_path / "lc.obs.jsonl"))
    snaps = [r for r in recs if r.get("kind") == "metrics"]
    c = snaps[-1]["counters"]
    assert c["lifecycle.rebuilds"] == 2
    assert c["lifecycle.publishes_delta"] == 1
    assert c["lifecycle.sla_misses"] == 0
    assert snaps[-1]["gauges"]["lifecycle.staleness_p99_s"] > 0


def test_service_coalesces_revision_storm(di_cfg, prior, tmp_path):
    obs = Obs("jsonl")
    revs = [_rev(1, extra=(("u_max", 0.99),)),
            _rev(2, extra=(("u_max", 0.98),)),
            _rev(3, extra=(("u_max", 0.97),))]
    holder: list = []
    src = StagedSource([
        (lambda: True, [revs[0]]),
        # The storm lands only once rev 1 is IN FLIGHT, so exactly
        # rev 2 sits queued for rev 3 to supersede.
        (lambda: holder[0]._ctl["di"].in_flight, [revs[1], revs[2]]),
    ])
    svc = RebuildService(
        src, di_cfg,
        cfg=LifecycleConfig(artifacts_root=str(tmp_path / "art")),
        prior={"di": prior}, obs=obs)
    holder.append(svc)
    with svc:
        assert svc.wait_idle(timeout=300, target_generations=2)
        assert svc.wait_idle(timeout=60)
    assert svc.n_failures == 0
    # rev 1 claimed immediately; rev 3 superseded rev 2 in the queue.
    assert len(svc.generations) == 2
    assert [g["seq"] for g in svc.generations] == [1, 3]
    snap = obs.metrics.snapshot()["counters"]
    assert snap["lifecycle.revisions_seen"] == 3
    assert snap["lifecycle.revisions_superseded"] == 1


def _wait_for(cond, timeout: float = 300.0) -> bool:
    deadline = time.perf_counter() + timeout
    while time.perf_counter() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return False


def test_service_contains_failures_and_keeps_serving(di_cfg, prior,
                                                     tmp_path):
    reg = ControllerRegistry()
    # rev 1 is fine; rev 2's box change cannot warm-transfer
    # (RebuildError) -- the daemon must count it, keep the old
    # version serving, and still process rev 3.
    ok1 = _rev(1, extra=(("u_max", 0.99),))
    bad = _rev(2, problem_args=(("N", 3), ("theta_box", 2.0)))
    good = _rev(3, extra=(("u_max", 0.98),))
    svc_box: list = []
    src = StagedSource([
        (lambda: True, [ok1]),
        (lambda: len(svc_box[0].generations) >= 1, [bad]),
        (lambda: svc_box[0].n_failures >= 1, [good]),
    ])
    svc = RebuildService(
        src, di_cfg,
        cfg=LifecycleConfig(artifacts_root=str(tmp_path / "art")),
        registry=reg, prior={"di": prior})
    svc_box.append(svc)
    with svc:
        assert svc.wait_idle(timeout=300, target_generations=1)
        v1 = reg.active_version("di")
        assert _wait_for(lambda: svc.n_failures == 1)
        assert reg.active_version("di") == v1  # old version serving
        assert svc.wait_idle(timeout=300, target_generations=2)
    assert svc.n_failures == 1
    assert len(svc.generations) == 2
    assert reg.active_version("di") == svc.generations[-1]["version"]


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_service_publish_crash_leaves_old_version_serving(
        di_cfg, prior, tmp_path):
    """Satellite 2 (in-process half of the chaos drill): an injected
    crash BETWEEN the delta write and the swap kills the worker, the
    registry keeps serving the prior generation, and the crashed
    generation's directory never gets a commit marker."""
    reg = ControllerRegistry()
    plan = FaultPlan(faults=(
        FaultSpec(site="lifecycle.publish_delta", kind="crash"),))
    revs = [_rev(1, extra=(("u_max", 0.99),)),
            _rev(2, extra=(("u_max", 0.98),))]
    svc_box: list = []
    src = StagedSource([
        (lambda: True, [revs[0]]),
        (lambda: len(svc_box[0].generations) >= 1, [revs[1]]),
    ])
    with faults_inj.activate(plan) as inj:
        svc = RebuildService(
            src, di_cfg,
            cfg=LifecycleConfig(artifacts_root=str(tmp_path / "art")),
            registry=reg, prior={"di": prior})
        svc_box.append(svc)
        svc.start()
        # gen 0 publishes FULL (site never fires); gen 1's delta
        # publish crashes the worker.
        assert not svc.wait_idle(timeout=300, target_generations=2)
        assert svc.worker_error is not None
        inj.assert_all_fired()
        v0 = svc.generations[0]["version"]
        assert reg.active_version("di") == v0
        # The crashed generation's full dir is absent or uncommitted.
        art = os.path.join(str(tmp_path / "art"), "di")
        for name in os.listdir(art):
            if name.startswith("g0001") and not name.endswith(".delta"):
                assert not os.path.exists(
                    os.path.join(art, name, "meta.json"))
        svc.close(timeout=5)


def test_lifecycle_fault_sites_registered():
    # Plans may script the new sites (validated at spec construction).
    FaultSpec(site="lifecycle.revision", kind="error")
    FaultSpec(site="lifecycle.publish_delta", kind="crash")


# -- K-generation ledger pruning (satellite 3) -----------------------------


def test_k20_drift_walk_ledger_bounded_and_decay_monotone(tmp_path):
    """The PR-10 claim at K=20, previously untested beyond K=1: a
    20-step eps/plant drift walk of chained warm rebuilds keeps the
    stage-2 fact ledger BOUNDED (dead events pruned, duplicates
    collapsed -- no monotone growth), and the service reports the
    reuse decay MONOTONE (running min) consistent with the per-
    generation stats."""
    args = (("N", 2), ("theta_box", (0.25, 0.6)))
    cfg = PartitionConfig(problem="inverted_pendulum",
                          problem_args=args, eps_a=1.0,
                          backend="cpu", batch_simplices=64)
    prior = build_partition(make("inverted_pendulum", **dict(args)), cfg)
    assert len(prior.tree.excl_events) > 0  # hybrid: a real ledger
    src = DriftSource("inverted_pendulum", problem_args=args,
                      controller="pend", eps_a=1.0, drift_arg="a",
                      drift_frac=0.01, eps_frac=0.02, n_revisions=20,
                      seed=2)
    svc = RebuildService(
        src, cfg,
        cfg=LifecycleConfig(artifacts_root=str(tmp_path / "art"),
                            delta_publish=True),
        prior={"pend": prior})
    src.gate = (lambda: len(svc.generations) + svc.n_failures
                >= src.n_emitted)
    with svc:
        assert svc.wait_idle(timeout=600, target_generations=20)
    assert svc.n_failures == 0
    summary = svc.summary()
    assert summary["generations"] == 20
    sizes = summary["excl_events"]
    # Bounded chains: the chained ledger never grows past a small
    # multiple of the nominal build's (pruning drops dead events and
    # collapses duplicates per rebuild; without it the transferred
    # ledger would accrete every generation's fresh facts forever).
    bound = 2 * len(prior.tree.excl_events) + 64
    assert max(sizes) <= bound, (sizes, bound)
    # Monotone-reported decay: non-increasing, consistent with the
    # per-generation reuse fracs, and ending at their running min.
    reuse = summary["reuse_fracs"]
    decay = summary["reuse_decay"]
    assert len(reuse) == 20 and len(decay) == 20
    assert all(d2 <= d1 + 1e-12 for d1, d2 in zip(decay, decay[1:]))
    assert decay == [round(float(m), 4) for m in
                     np.minimum.accumulate(reuse)]
    # The walk did drift: not every generation is a full-reuse no-op.
    assert min(reuse) < 1.0
    # Delta publishing held up across the whole chain.
    assert summary["delta_publishes"] >= 18
    assert summary["delta_bytes_frac"] < 0.8


def test_summary_reports_monotone_decay_without_builds(di_cfg):
    """The decay REPORTING contract alone (no builds): summary's
    reuse_decay is the running min of the per-generation fracs --
    non-increasing by construction, so a lucky late generation can
    never mask an earlier collapse."""
    svc = RebuildService(ListSource([]), di_cfg,
                         cfg=LifecycleConfig(artifacts_root="unused"))
    reuse = [1.0, 0.97, 0.99, 0.91, 0.95, 0.91]
    for i, r in enumerate(reuse):
        svc.generations.append(
            {"generation": i, "reuse_frac": r, "excl_events": 10 + i,
             "published": "delta", "delta_bytes": 10, "full_bytes": 100})
        svc._staleness.append(1.0 + i)
    s = svc.summary()
    assert s["reuse_fracs"] == [round(r, 4) for r in reuse]
    assert s["reuse_decay"] == [1.0, 0.97, 0.97, 0.91, 0.91, 0.91]
    assert all(b <= a for a, b in zip(s["reuse_decay"],
                                      s["reuse_decay"][1:]))
    assert s["staleness_p50_s"] == pytest.approx(3.5)
    assert s["delta_bytes_frac"] == pytest.approx(0.1)


# -- obs / health / report / gate wiring -----------------------------------


def test_health_staleness_rule():
    mon = HealthMonitor({"max_staleness_s": 10.0})
    rec = {"kind": "metrics",
           "counters": {"lifecycle.rebuilds": 3},
           "gauges": {"lifecycle.staleness_p99_s": 45.0}}
    evs = mon.feed(rec)
    assert any(e["name"] == "health.staleness" for e in evs)
    assert mon.worst == "warn"
    # Volume gate: no completed rebuild -> no verdict.
    mon2 = HealthMonitor({"max_staleness_s": 10.0})
    assert mon2.feed({"kind": "metrics", "counters": {},
                      "gauges": {"lifecycle.staleness_p99_s": 45.0}}) \
        == []
    # 0 disables (the default: budgets are deployment-specific).
    mon3 = HealthMonitor()
    assert mon3.feed(rec) == []


def _load_script(name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_obs_report_renders_lifecycle_block(di_cfg, prior, tmp_path):
    obs_report = _load_script("obs_report")
    path = str(tmp_path / "lc.obs.jsonl")
    obs = Obs("jsonl", path=path)
    src = DriftSource("double_integrator", problem_args=DI_ARGS,
                      controller="di", eps_a=0.3, drift_arg="u_max",
                      drift_frac=0.05, n_revisions=1, seed=9)
    svc = RebuildService(
        src, di_cfg,
        cfg=LifecycleConfig(artifacts_root=str(tmp_path / "art"),
                            sla_s=1e-4),  # everything misses: rendered
        prior={"di": prior}, obs=obs)
    with svc:
        assert svc.wait_idle(timeout=300, target_generations=1)
    obs.close()
    from explicit_hybrid_mpc_tpu.obs.sink import load_jsonl

    rep = obs_report.report(load_jsonl(path))
    lc = rep["lifecycle"]
    assert lc["rebuilds"] == 1
    assert lc["staleness_p99_s"] > 0
    assert lc["sla_misses"] == 1
    assert lc["reuse_decay"]
    txt = obs_report.render_text(rep, [], None)
    assert "lifecycle:" in txt and "SLA MISS" in txt
    # The SLA-miss health event lands in the warnings block.
    assert any("health.staleness" in w
               for w in rep.get("warnings", []))
    # Staleness + delta-size regressions diff-flag vs a bench row.
    flags = obs_report.diff_bench(
        rep, {"staleness_p99_s": lc["staleness_p99_s"] / 10})
    assert any("staleness regression" in f for f in flags)
    rep2 = {"lifecycle": {"delta_bytes_frac": 0.5}}
    flags2 = obs_report.diff_bench(rep2, {"delta_bytes_frac": 0.1})
    assert any("delta-artifact size regression" in f for f in flags2)


def test_bench_gate_gates_lifecycle_metrics():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import bench_gate
    finally:
        sys.path.pop(0)
    assert bench_gate.GATED_METRICS["staleness_p99_s"][0] == "lower"
    assert bench_gate.GATED_METRICS["delta_bytes_frac"][0] == "lower"
    row = bench_gate.summarize(
        {"platform": "cpu", "metric": "lifecycle drift-walk",
         "staleness_p99_s": 5.0, "delta_bytes_frac": 0.1,
         "drift_generations": 20, "reuse_decay": [1.0, 0.9]},
        "BENCH_drift_r01.json", mtime=1.0)
    assert row["staleness_p99_s"] == 5.0
    assert row["reuse_decay"] == [1.0, 0.9]
    hist = [{"platform": "cpu", "source": "old.json",
             "staleness_p99_s": 5.0, "delta_bytes_frac": 0.1}]
    flags, _ = bench_gate.gate(
        dict(row, staleness_p99_s=20.0, delta_bytes_frac=0.4), hist)
    assert any("staleness_p99_s" in f for f in flags)
    assert any("delta_bytes_frac" in f for f in flags)


# -- CLI surface -----------------------------------------------------------


def test_serve_rebuild_cli_requires_artifacts_root():
    from explicit_hybrid_mpc_tpu.main import main

    with pytest.raises(SystemExit):
        main(["serve-rebuild", "-e", "double_integrator"])


def test_lifecycle_config_validates():
    with pytest.raises(ValueError, match="poll_s"):
        LifecycleConfig(poll_s=0)
    with pytest.raises(ValueError, match="max_concurrent"):
        LifecycleConfig(max_concurrent=0)
    with pytest.raises(ValueError, match="full_every"):
        LifecycleConfig(full_every=-1)
