"""Fleet telemetry (ISSUE 13): stream identity + clock anchoring,
per-process stream naming, clock-aligned merge, exact counter rollup,
fleet health rules, per-step critical-path attribution, and the
health-triggered bounded auto-profile capture.
"""

import glob
import json
import os
import sys

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.obs import clock, fleet
from explicit_hybrid_mpc_tpu.obs.sink import (SCHEMA_VERSION, JsonlSink,
                                              load_jsonl)
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _script(name):
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


def _write_stream(path, records, version=SCHEMA_VERSION, identity=None):
    """Hand-written stream: schema record, optional identity record,
    then `records` (each a full dict with t/kind/name)."""
    with open(path, "w") as f:
        f.write(json.dumps({"t": 0.0, "kind": "meta", "name": "schema",
                            "version": version}) + "\n")
        if identity is not None:
            f.write(json.dumps({"t": identity.get("t", 0.0),
                                "kind": "meta", "name": "stream",
                                **identity}) + "\n")
        for r in records:
            f.write(json.dumps(r) + "\n")
    return path


# -- identity + clock ------------------------------------------------------

def test_identity_record_and_anchor(tmp_path):
    p = str(tmp_path / "x.obs.jsonl")
    with obs_lib.Obs("jsonl", path=p):
        pass
    recs = load_jsonl(p)
    assert recs[0]["name"] == "schema"
    assert recs[0]["version"] == SCHEMA_VERSION == 2
    ident = recs[1]
    assert ident["kind"] == "meta" and ident["name"] == "stream"
    for k in ("run_id", "host", "pid", "process_index", "process_count",
              "wall_time", "t"):
        assert k in ident, k
    assert ident["pid"] == os.getpid()
    # The anchor maps stream t onto the wall axis consistently.
    off = clock.wall_offset(ident)
    assert off is not None
    assert clock.to_wall(ident, ident["t"]) == pytest.approx(
        ident["wall_time"])


def test_run_id_env_override(monkeypatch):
    monkeypatch.setattr(clock, "_run_id", None)
    monkeypatch.setenv(clock.RUN_ID_ENV, "deadbeef0123")
    assert clock.run_id() == "deadbeef0123"
    monkeypatch.setattr(clock, "_run_id", None)


def test_process_coords():
    from explicit_hybrid_mpc_tpu.parallel import distributed

    coords = distributed.process_coords()
    assert coords["process_index"] == 0
    assert coords["process_count"] == 1
    assert coords["n_local_devices"] >= 1


# -- per-process naming + bare-name resolution -----------------------------

def test_per_process_path_shapes():
    assert fleet.per_process_path("a/b.obs.jsonl", 3, 77) \
        == "a/b.obs.p3-77.jsonl"
    assert fleet.per_process_path("noext", 0, 5) == "noext.p0-5"


def test_bare_name_resolution(tmp_path):
    bare = str(tmp_path / "run.obs.jsonl")
    o = obs_lib.Obs("jsonl", path=bare, per_process=True)
    o.event("tick", i=1)
    o.close()
    assert not os.path.exists(bare)
    assert len(fleet.sibling_streams(bare)) == 1
    # load_jsonl resolves the old bare name to the one sibling.
    recs = load_jsonl(bare)
    assert any(r.get("name") == "tick" for r in recs)
    # A second sibling makes the bare name ambiguous: the reader must
    # refuse to silently pick one shard.
    _write_stream(str(tmp_path / "run.obs.p0-99999.jsonl"), [])
    with pytest.raises(FileNotFoundError, match="fleet"):
        load_jsonl(bare)
    # ...but the fleet loader takes the whole family.
    assert len(fleet.load_fleet(bare)) == 2


# -- clock-aligned merge ---------------------------------------------------

def test_merge_orders_by_wall_anchor(tmp_path):
    """Two streams with skewed anchors: the same stream-local t values
    must interleave by ABSOLUTE time, not by t."""
    a = _write_stream(
        str(tmp_path / "a.jsonl"),
        [{"t": 1.0, "kind": "event", "name": "build.step", "step": 1},
         {"t": 3.0, "kind": "event", "name": "build.step", "step": 2}],
        identity={"t": 0.0, "wall_time": 1000.0, "run_id": "r", "pid": 1,
                  "host": "h", "process_index": 0, "process_count": 2})
    b = _write_stream(
        str(tmp_path / "b.jsonl"),
        [{"t": 1.0, "kind": "event", "name": "build.step", "step": 1},
         {"t": 3.0, "kind": "event", "name": "build.step", "step": 2}],
        identity={"t": 0.0, "wall_time": 1001.0, "run_id": "r", "pid": 2,
                  "host": "h", "process_index": 1, "process_count": 2})
    streams = fleet.load_fleet([a, b])
    merged = fleet.merge_events(streams, kinds=("event",))
    order = [(r["shard"], r["step"]) for r in merged]
    assert order == [("p0:1", 1), ("p1:2", 1), ("p0:1", 2), ("p1:2", 2)]
    assert [r["t_abs"] for r in merged] == [1001.0, 1002.0, 1003.0,
                                            1004.0]


# -- rollup ----------------------------------------------------------------

def test_rollup_counters_sum_bit_exact(tmp_path):
    big = 123_456_789_012_345
    a = _write_stream(
        str(tmp_path / "a.jsonl"),
        [{"t": 1.0, "kind": "metrics", "name": "snapshot",
          "counters": {"oracle.point_solves": big, "build.leaves": 7},
          "gauges": {"build.regions": 7.0},
          "histograms": {"x_s": {"bounds": [1.0, 2.0],
                                 "counts": [1, 2, 3], "count": 6,
                                 "sum": 9.0, "min": 0.5, "max": 4.0}}}],
        identity={"t": 0.0, "wall_time": 10.0, "run_id": "r", "pid": 1,
                  "host": "h", "process_index": 0, "process_count": 2})
    b = _write_stream(
        str(tmp_path / "b.jsonl"),
        [{"t": 1.0, "kind": "metrics", "name": "snapshot",
          "counters": {"oracle.point_solves": 987_654_321,
                       "build.leaves": 5},
          "gauges": {"build.regions": 12.0},
          "histograms": {"x_s": {"bounds": [1.0, 2.0],
                                 "counts": [0, 1, 0], "count": 1,
                                 "sum": 1.5, "min": 1.5, "max": 1.5}}}],
        identity={"t": 0.0, "wall_time": 11.0, "run_id": "r", "pid": 2,
                  "host": "h", "process_index": 1, "process_count": 2})
    roll = fleet.fleet_rollup(fleet.load_fleet([a, b]))
    assert roll["counters"]["oracle.point_solves"] == big + 987_654_321
    assert roll["counters"]["build.leaves"] == 12
    assert roll["regions"] == 12.0  # gauges: max, not sum
    h = roll["histograms"]["x_s"]
    assert h["counts"] == [1, 3, 3] and h["count"] == 7
    assert h["min"] == 0.5 and h["max"] == 4.0
    assert roll["run_ids"] == ["r"]


def test_v1_stream_tolerated_and_strict_issues(tmp_path):
    v1 = _write_stream(str(tmp_path / "v1.jsonl"),
                       [{"t": 1.0, "kind": "event", "name": "build.step",
                         "step": 1, "regions": 5}], version=1)
    v2 = _write_stream(
        str(tmp_path / "v2.jsonl"), [],
        identity={"t": 0.0, "wall_time": 1.0, "run_id": "r", "pid": 2,
                  "host": "h", "process_index": 0, "process_count": 1})
    streams = fleet.load_fleet([v1, v2])
    assert streams[0].identity is None
    assert streams[0].schema_version == 1
    issues = fleet.strict_issues(streams)
    assert any("mixed stream schema versions" in i for i in issues)
    assert any("no stream-identity" in i for i in issues)
    assert fleet.strict_issues([streams[1]]) == []


# -- straggler attribution + fleet rules -----------------------------------

def _progress_stream(tmp_path, name, wall0, rate, n=6, pid=1, idx=0):
    recs = [{"t": float(i), "kind": "event", "name": "build.step",
             "step": i, "regions": int(i * rate)}
            for i in range(1, n + 1)]
    return _write_stream(
        str(tmp_path / name), recs,
        identity={"t": 0.0, "wall_time": wall0, "run_id": "r",
                  "pid": pid, "host": "h", "process_index": idx,
                  "process_count": 2})


def test_straggler_report_concurrent(tmp_path):
    fast = _progress_stream(tmp_path, "fast.jsonl", 100.0, 100.0,
                            pid=1, idx=0)
    slow = _progress_stream(tmp_path, "slow.jsonl", 100.0, 10.0,
                            pid=2, idx=1)
    rep = fleet.straggler_report(fleet.load_fleet([fast, slow]))
    assert rep["concurrent"]
    assert rep["slowest"] == "p1:2" and rep["fastest"] == "p0:1"
    assert rep["straggle_frac"] == pytest.approx(0.9)
    # Sequential sessions (a restart chain) are not stragglers.
    late = _progress_stream(tmp_path, "late.jsonl", 1000.0, 10.0,
                            pid=3, idx=0)
    rep = fleet.straggler_report(fleet.load_fleet([fast, late]))
    assert not rep["concurrent"] and rep["straggle_frac"] is None


def test_shard_labels_deduped_across_hosts(tmp_path):
    """Two containerized replicas both running as pid 1 on different
    hosts must not collapse into one shard row."""
    for i, host in enumerate(("host-a", "host-b")):
        _write_stream(
            str(tmp_path / f"r{i}.jsonl"),
            [{"t": 1.0, "kind": "metrics", "name": "snapshot",
              "counters": {"build.leaves": 1}, "gauges": {},
              "histograms": {}}],
            identity={"t": 0.0, "wall_time": 100.0, "run_id": "r",
                      "pid": 1, "host": host, "process_index": 0,
                      "process_count": 2})
    streams = fleet.load_fleet(str(tmp_path / "r*.jsonl"))
    assert len({s.shard for s in streams}) == 2
    roll = fleet.fleet_rollup(streams)
    assert len(roll["per_shard"]) == 2
    assert roll["counters"]["build.leaves"] == 2


def test_straggler_pairwise_overlap(tmp_path):
    """One sequential restart-chain session among concurrent shards
    must not disable straggler attribution for the whole fleet."""
    fast = _progress_stream(tmp_path, "fast.jsonl", 100.0, 100.0,
                            pid=1, idx=0)
    slow = _progress_stream(tmp_path, "slow.jsonl", 100.0, 10.0,
                            pid=2, idx=1)
    dead = _progress_stream(tmp_path, "dead.jsonl", 1000.0, 50.0,
                            pid=3, idx=2)  # long after the others
    rep = fleet.straggler_report(fleet.load_fleet([fast, slow, dead]))
    assert rep["concurrent"]
    assert rep["slowest"] == "p1:2" and rep["fastest"] == "p0:1"
    assert rep["straggle_frac"] == pytest.approx(0.9)
    assert rep["shards"]["p2:3"]["concurrent"] is False


def test_fleet_monitor_rules(tmp_path):
    fast = _progress_stream(tmp_path, "fast.jsonl", 100.0, 100.0,
                            pid=1, idx=0)
    slow = _progress_stream(tmp_path, "slow.jsonl", 100.0, 10.0,
                            pid=2, idx=1)
    streams = fleet.load_fleet([fast, slow])
    mon = fleet.FleetMonitor()
    for s in streams:
        for r in s.records:
            mon.feed(s.shard, r)
    evs = mon.finalize(streams)
    assert [e["name"] for e in evs] == ["health.shard_straggle"]
    assert mon.worst == "warn" and mon.exit_code == 1
    assert mon.finalize(streams) == []  # fires once
    # Fleet stall: every shard silent past the rule -> critical.
    evs = mon.check_fleet_stall(400.0)
    assert [e["name"] for e in evs] == ["health.fleet_stall"]
    assert mon.exit_code == 2
    # Unknown rule names raise through the shared validator.
    with pytest.raises(ValueError, match="unknown health rule"):
        fleet.FleetMonitor({"bogus": 1.0})


def test_obs_watch_fleet_once(tmp_path):
    obs_watch = _script("obs_watch")
    ok = _progress_stream(tmp_path, "run.obs.p0-1.jsonl", 100.0, 50.0,
                          pid=1, idx=0)
    _write_stream(
        str(tmp_path / "run.obs.p0-2.jsonl"),
        [{"t": 1.0, "kind": "event", "name": "health.quarantine",
          "severity": "critical", "msg": "boom"}],
        identity={"t": 0.0, "wall_time": 100.0, "run_id": "r", "pid": 2,
                  "host": "h", "process_index": 0, "process_count": 2})
    rc = obs_watch.main([str(tmp_path / "run.obs.p*.jsonl"),
                         "--fleet", "--once"])
    assert rc == 2  # the adopted critical event dominates
    rc = obs_watch.main([ok, "--fleet", "--once"])
    assert rc == 0


def test_obs_report_fleet_and_strict(tmp_path, capsys):
    obs_report = _script("obs_report")
    _progress_stream(tmp_path, "f.obs.p0-1.jsonl", 100.0, 50.0,
                     pid=1, idx=0)
    _write_stream(str(tmp_path / "f.obs.p0-2.jsonl"),
                  [{"t": 1.0, "kind": "metrics", "name": "snapshot",
                    "counters": {"build.leaves": 3}, "gauges": {},
                    "histograms": {}}], version=1)
    pat = str(tmp_path / "f.obs.p*.jsonl")
    assert obs_report.main([pat, "--fleet"]) == 0
    out = capsys.readouterr().out
    assert "fleet report: 2 stream(s)" in out
    assert "rollup" in out
    # --strict: the v1 identity-less stream gates the fold.
    assert obs_report.main([pat, "--fleet", "--strict"]) == 1
    out = capsys.readouterr().out
    assert "STRICT" in out


# -- build-integrated coverage ---------------------------------------------

@pytest.fixture(scope="module")
def cp_build(tmp_path_factory):
    """One small DI build with obs + checkpoints: the critical-path
    and checkpoint-snapshot fixtures."""
    d = tmp_path_factory.mktemp("cp")
    path = str(d / "run.obs.jsonl")
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.4, backend="cpu", batch_simplices=64,
                          obs="jsonl", obs_path=path,
                          checkpoint_every=4,
                          checkpoint_path=str(d / "x.ckpt.pkl"))
    res = build_partition(prob, cfg)
    return path, res


def test_critical_path_fractions_sum_to_one(cp_build):
    """ISSUE acceptance: per-step critical-path fractions sum to
    1.0 +- 0.02."""
    path, res = cp_build
    steps = [r for r in load_jsonl(path)
             if r.get("kind") == "event" and r.get("name") == "build.step"]
    assert steps
    for s in steps:
        parts = [s[f"cp_{seg}_s"] for seg in
                 ("fill", "plan", "wait", "certify", "other")]
        assert all(p >= 0 for p in parts)
        assert sum(parts) / s["step_s"] == pytest.approx(1.0, abs=0.02)
    # Cumulative gauges + stats agree and sum to ~1 too.
    fr = {seg: res.stats[f"cp_{seg}_frac"]
          for seg in ("fill", "plan", "wait", "certify", "other")}
    assert sum(fr.values()) == pytest.approx(1.0, abs=0.02)
    assert res.stats["cp_checkpoint_s"] >= 0


def test_checkpoint_flushes_metrics_snapshot(cp_build):
    """Every checkpoint writes a metrics snapshot BEFORE the
    checkpoint.written injection site -- the fleet-reconciliation
    prerequisite (a boundary-killed process has shipped its totals)."""
    path, res = cp_build
    recs = load_jsonl(path)
    snaps = [r for r in recs if r["kind"] == "metrics"]
    n_ckpts = res.stats["steps"] // 4
    assert len(snaps) >= n_ckpts + 1  # per checkpoint + final
    assert snaps[-1]["gauges"]["build.cp_checkpoint_s"] > 0


def test_obs_report_renders_critical_path(cp_build):
    obs_report = _script("obs_report")
    path, _res = cp_build
    rep = obs_report.report(load_jsonl(path))
    cp = rep["critical_path"]
    assert sum(cp[s] for s in ("fill", "plan", "wait", "certify",
                               "other")) == pytest.approx(1.0, abs=0.02)
    assert "checkpoint_s" in cp
    text = obs_report.render_text(rep, [], None)
    assert "critical path:" in text
    assert rep["identity"]["pid"] == os.getpid()


# -- auto-profile (health-triggered bounded capture) -----------------------

def test_auto_profile_on_injected_stall(tmp_path):
    """ISSUE acceptance: an injected hang triggers exactly ONE bounded
    auto-profile capture with a valid summarized bundle, and obs_watch
    exits 2 on the same stream."""
    from explicit_hybrid_mpc_tpu import faults as faults_lib
    from explicit_hybrid_mpc_tpu.faults.plan import FaultPlan

    path = str(tmp_path / "run.obs.jsonl")
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.4, backend="cpu", batch_simplices=64,
                          obs="jsonl", obs_path=path, auto_profile=True,
                          profile_steps=2,
                          recorder_dir=str(tmp_path / "repro"),
                          health_rules=(("stall_s", 0.2),))
    # TWO hangs: the second stall must NOT open a second capture
    # (max_captures=1 -- bounded by design).
    plan = FaultPlan(faults=(
        {"site": "oracle.wait", "kind": "hang", "at": 2, "hang_s": 0.4},
        {"site": "oracle.wait", "kind": "hang", "at": 7, "hang_s": 0.4}))
    with faults_lib.activate(plan):
        res = build_partition(prob, cfg)
    assert res.stats["regions"] > 0
    recs = load_jsonl(path)
    assert any(r.get("name") == "health.stall" for r in recs)
    caps = [r for r in recs if r.get("name") == "profile.capture"]
    assert len(caps) == 1
    bundles = glob.glob(str(tmp_path / "repro" / "auto_profile_*.json"))
    assert len(bundles) == 1
    with open(bundles[0]) as f:
        bundle = json.load(f)
    assert bundle["reason"] == "health.stall"
    assert "error" not in bundle
    summ = bundle["trace_summary"]
    assert summ.get("trace_files", 0) >= 1
    assert isinstance(summ.get("top_ops_ms"), list)
    snaps = [r for r in recs if r["kind"] == "metrics"]
    assert snaps[-1]["counters"]["build.auto_profiles"] == 1
    # The same schedule through the external watcher: exit 2.
    obs_watch = _script("obs_watch")
    rc, _mon = obs_watch.watch(path, once=True)
    assert rc == 2


def test_trigger_auto_profile_external(tmp_path):
    """The long_build halt path: an external driver can open the
    bounded capture and drive it to completion with its own steps."""
    from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                            make_oracle)

    path = str(tmp_path / "run.obs.jsonl")
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.5, backend="cpu", batch_simplices=32,
                          obs="jsonl", obs_path=path, auto_profile=True,
                          profile_steps=2,
                          recorder_dir=str(tmp_path / "repro"))
    eng = FrontierEngine(prob, make_oracle(prob, cfg), cfg)
    eng.step()
    extra = eng.trigger_auto_profile("health_halt:test")
    assert extra == 2
    for _ in range(extra):
        if eng.frontier:
            eng.step()
    eng.finish_obs()
    bundles = glob.glob(str(tmp_path / "repro" / "auto_profile_*.json"))
    assert len(bundles) == 1
    # The budget is spent: a second trigger is refused.
    assert eng.trigger_auto_profile("again") == 0


def test_auto_profile_off_by_default(tmp_path):
    from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                            make_oracle)

    cfg = PartitionConfig(eps_a=0.5, backend="cpu", batch_simplices=32)
    eng = FrontierEngine(make("double_integrator", N=3, theta_box=1.5),
                         make_oracle(make("double_integrator", N=3,
                                          theta_box=1.5), cfg), cfg)
    assert eng._auto_prof is None
    assert eng.trigger_auto_profile("nope") == 0


# -- satellites ------------------------------------------------------------

def test_serve_replica_identity_event():
    import types

    from explicit_hybrid_mpc_tpu.serve.scheduler import RequestScheduler

    o = obs_lib.Obs("jsonl")
    reg = types.SimpleNamespace(param_dim=lambda name: None, lease=None)
    sched = RequestScheduler(reg, "ctl-a", max_batch=8, obs=o)
    try:
        evs = [r for r in o.sink.records
               if r.get("name") == "serve.replica"]
        assert len(evs) == 1
        assert evs[0]["controller"] == "ctl-a"
        assert evs[0]["pid"] == os.getpid()
        assert evs[0]["run_id"] == clock.run_id()
    finally:
        sched.close(timeout=5.0)


def test_bench_gate_row_carries_fleet_keys():
    bench_gate = _script("bench_gate")
    row = bench_gate.summarize(
        {"value": 1.0, "platform": "cpu", "run_id": "abc123",
         "obs_schema_version": 2, "cp_wait_frac": 0.7,
         "cp_checkpoint_s": 0.1}, "BENCH_x.json")
    assert row["run_id"] == "abc123"
    assert row["obs_schema_version"] == 2
    assert row["cp_wait_frac"] == 0.7
    assert row["cp_checkpoint_s"] == 0.1


def test_health_rules_include_fleet_rules():
    from explicit_hybrid_mpc_tpu.obs.health import (DEFAULT_RULES,
                                                    rules_from_pairs)

    assert "max_shard_straggle_frac" in DEFAULT_RULES
    assert "fleet_stall" in DEFAULT_RULES
    assert rules_from_pairs([("fleet_stall", 10.0)])["fleet_stall"] \
        == 10.0
