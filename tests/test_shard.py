"""Sharded frontier (partition/shard.py): ownership hash determinism,
exchange protocol, in-process multi-shard build parity vs the
single-process build, async host-certify parity, merge/compare
helpers.

The multi-shard builds here run N FrontierEngines in N THREADS of one
process over one exchange directory -- the full request/publish/drain
protocol without a jax.distributed rendezvous (the real multi-process
path is exercised by tests/test_distributed.py's worker harness and
the pre-merge scripts: fleet_smoke --sharded, chaos_suite's
sharded_device_failure schedule, bench --multichip)."""

import os
import pickle
import threading

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.partition import shard as shard_lib
from explicit_hybrid_mpc_tpu.partition.shard import (
    ShardExchange, compare_trees_canonical, merge_shard_trees,
    owned_root_indices, shard_owner)

BASE = dict(problem="double_integrator", eps_a=0.5, backend="cpu",
            batch_simplices=32, max_depth=20, speculate=False)


def _problem():
    from explicit_hybrid_mpc_tpu.problems.registry import make

    return make("double_integrator", N=3, theta_box=1.5)


def _oracle(prob):
    from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle

    return Oracle(prob, backend="cpu")


@pytest.fixture(scope="module")
def reference():
    """Single-process build of the shared parity config."""
    from explicit_hybrid_mpc_tpu.partition.frontier import (
        build_partition)

    prob = _problem()
    res = build_partition(prob, PartitionConfig(**BASE),
                          oracle=_oracle(prob))
    return prob, res


def _run_shards(prob, n_shards, wd, cfg_extra=None, timeout_s=180.0):
    """N engines in N threads over one exchange dir; returns
    [(PartitionResult, oracle, engine)] indexed by shard."""
    from explicit_hybrid_mpc_tpu.partition.frontier import (
        FrontierEngine)

    results = [None] * n_shards
    errors = [None] * n_shards

    def run(i):
        try:
            extra = cfg_extra(i) if callable(cfg_extra) \
                else (cfg_extra or {})
            cfg = PartitionConfig(
                **BASE, shard_frontier=True, shard_dir=wd,
                shard_index=i, shard_count=n_shards,
                shard_timeout_s=timeout_s, **extra)
            oracle = _oracle(prob)
            eng = FrontierEngine(prob, oracle, cfg)
            results[i] = (eng.run(), oracle, eng)
        except BaseException as e:  # surfaced by the assert below
            errors[i] = e

    threads = [threading.Thread(target=run, args=(i,), daemon=True)
               for i in range(n_shards)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    assert all(e is None for e in errors), errors
    assert all(r is not None for r in results), "shard thread hung"
    return results


# -- ownership hash ---------------------------------------------------------


def test_shard_owner_partitions_every_cell():
    """Every (vertex, delta) cell maps to EXACTLY one shard for any
    process count -- the cross-host dedup invariant (two shards can
    never both own, hence never both solve, the same program)."""
    rng = np.random.default_rng(0)
    keys = [rng.standard_normal(2).round(9).tobytes()
            for _ in range(512)]
    for n in (1, 2, 4):
        owners = {}
        for k in keys:
            for d in range(8):  # all deltas of a vertex co-owned
                o = shard_owner(k, n)
                assert 0 <= o < n
                assert owners.setdefault((k, d), o) == o
        per_vertex = {k: shard_owner(k, n) for k in keys}
        if n > 1:
            # Non-degenerate spread (512 keys over <= 4 shards).
            assert len(set(per_vertex.values())) == n


def test_shard_owner_deterministic_across_calls():
    k = np.asarray([0.125, -1.5]).tobytes()
    assert all(shard_owner(k, 4) == shard_owner(bytes(k), 4)
               for _ in range(10))
    assert shard_owner(k, 1) == 0


def test_owned_roots_round_robin():
    for n in (1, 2, 3):
        cover = sorted(sum((owned_root_indices(7, s, n)
                            for s in range(n)), []))
        assert cover == list(range(7))  # every root exactly once


def test_shard_cfg_validation():
    with pytest.raises(ValueError):
        PartitionConfig(**BASE, shard_timeout_s=0)
    with pytest.raises(ValueError):
        PartitionConfig(**BASE, shard_index=2, shard_count=2)
    with pytest.raises(ValueError):
        PartitionConfig(**BASE, shard_count=0)


# -- exchange protocol ------------------------------------------------------


def test_exchange_request_publish_roundtrip(tmp_path):
    nd, nt, nu, nz = 4, 2, 1, 3
    a = ShardExchange(str(tmp_path), 0, 2)
    b = ShardExchange(str(tmp_path), 1, 2)
    key = np.asarray([0.5, -0.25]).tobytes()
    theta = np.asarray([0.5, -0.25])
    need = np.asarray([True, False, True, False])
    assert b.request(key, theta, need) == 2
    # Duplicate request for the same cells is suppressed; a widened
    # request posts only the new cells.
    assert b.request(key, theta, need) == 0
    wider = np.asarray([True, True, True, False])
    assert b.request(key, theta, wider) == 1
    reqs = a.read_requests(nd)
    assert len(reqs) == 1
    rk, rtheta, rmask = reqs[0]
    assert rk == key
    np.testing.assert_array_equal(rtheta, theta)
    np.testing.assert_array_equal(rmask, wider)
    # Owner answers: merge a partial row, publish, peer polls it in.
    a.merge_row(key, np.asarray([True, False, True, False]),
                V=np.arange(nd, dtype=float),
                conv=np.asarray([True, False, True, False]),
                grad=np.ones((nd, nt)), u0=np.ones((nd, nu)),
                z=np.ones((nd, nz)))
    assert a.publish([(key, rmask)]) == 1
    assert b.poll() == 1
    row = b.rows[key]
    np.testing.assert_array_equal(
        row["mask"], [True, False, True, False])
    assert row["V"][2] == 2.0 and not np.isfinite(row["V"][1])
    # Second publication covering more cells merges idempotently.
    a.merge_row(key, np.asarray([False, True, False, False]),
                V=np.full(nd, 7.0), conv=np.ones(nd, dtype=bool),
                grad=np.zeros((nd, nt)), u0=np.zeros((nd, nu)),
                z=np.zeros((nd, nz)))
    assert a.publish([(key, wider)]) == 1
    b.poll()
    np.testing.assert_array_equal(
        b.rows[key]["mask"], [True, True, True, False])
    assert b.rows[key]["V"][2] == 2.0  # earlier cells untouched
    assert b.rows[key]["V"][1] == 7.0
    # Fully-published cells are never re-shipped.
    assert a.publish([(key, wider)]) == 0


def test_exchange_recovers_own_publications(tmp_path):
    """Crash/resume: a restarted owner must continue its publication
    sequence (an overwrite would be invisible to peers' basename
    dedup + sequence cursors) and serve re-read requests from the
    recovered store instead of re-solving."""
    nd, nt, nu, nz = 2, 2, 1, 3
    a = ShardExchange(str(tmp_path), 0, 2)
    key = np.asarray([0.25, 0.75]).tobytes()
    full = np.ones(nd, dtype=bool)
    a.merge_row(key, full, V=np.arange(nd, dtype=float),
                conv=np.ones(nd, dtype=bool), grad=np.ones((nd, nt)),
                u0=np.ones((nd, nu)), z=np.ones((nd, nz)))
    assert a.publish([(key, full)]) == 1
    # "Restart": a fresh exchange over the same dir.
    a2 = ShardExchange(str(tmp_path), 0, 2)
    assert a2._pub_seq == 1  # sequence continues, no overwrite
    assert key in a2.rows and a2.rows[key]["mask"].all()
    assert a2.publish([(key, full)]) == 0  # already published


def test_stale_shard_dir_rejected(tmp_path):
    """A reused shard_dir from a DIFFERENT build identity must be
    refused loudly (its recovered rows would be another problem's
    solutions keyed by theta coordinates)."""
    from explicit_hybrid_mpc_tpu.partition.frontier import (
        FrontierEngine)

    prob = _problem()
    cfg1 = PartitionConfig(**BASE, shard_frontier=True,
                           shard_dir=str(tmp_path), shard_index=0,
                           shard_count=2)
    FrontierEngine(prob, _oracle(prob), cfg1)  # claims the dir
    base2 = dict(BASE, eps_a=0.3)
    cfg2 = PartitionConfig(**base2, shard_frontier=True,
                           shard_dir=str(tmp_path), shard_index=1,
                           shard_count=2)
    with pytest.raises(ValueError, match="different build"):
        FrontierEngine(prob, _oracle(prob), cfg2)


# -- sharded build parity ----------------------------------------------------


@pytest.mark.parametrize("n_shards", [2, 4])
def test_sharded_build_matches_single_process(reference, tmp_path,
                                              n_shards):
    """The tentpole acceptance: N-shard build produces a tree
    node-for-node identical to the single-process build (vertices
    bitwise, leaf sets, statuses and commutation choices) with ZERO
    duplicate (vertex, delta) solves -- summed oracle.point_solves
    equals the single-process count exactly.  n_shards=4 on a 2-root
    problem additionally proves idle shards participate in the
    exchange/drain protocol without deadlock."""
    prob, ref = reference
    results = _run_shards(prob, n_shards, str(tmp_path))
    merged0 = results[0][0]
    # Every shard merges the identical global result.
    for res, _o, _e in results[1:]:
        assert compare_trees_canonical(merged0.tree, res.tree,
                                       payloads=True) == []
        assert res.stats["regions"] == merged0.stats["regions"]
    assert compare_trees_canonical(ref.tree, merged0.tree) == []
    assert merged0.stats["regions"] == ref.stats["regions"]
    assert merged0.stats["tree_nodes"] == ref.stats["tree_nodes"]
    assert merged0.stats["max_depth"] == ref.stats["max_depth"]
    # Zero duplicate solves across shards: the summed count is the
    # single-process count, and the engines' raw oracle counters agree
    # with the merged stats (the per-shard stats snapshot after the
    # drain barrier).
    summed = sum(o.n_point_solves for _r, o, _e in results)
    assert summed == ref.stats["point_solves"]
    assert merged0.stats["point_solves"] == ref.stats["point_solves"]
    assert merged0.stats["simplex_solves"] == ref.stats["simplex_solves"]
    assert merged0.stats["uncertified"] == ref.stats["uncertified"] == 0
    # No shard hit the remote-timeout fallback.
    assert merged0.stats["shard_fallback_cells"] == 0
    assert merged0.stats["n_shards"] == n_shards
    assert len(merged0.stats["per_shard"]) == n_shards


def test_sharded_obs_counters_reconcile(reference, tmp_path):
    """Per-shard obs streams (the fleet-telemetry surface): summed
    final-snapshot counters equal the single-process build's."""
    from explicit_hybrid_mpc_tpu.obs import fleet as fleet_lib
    from explicit_hybrid_mpc_tpu.partition.frontier import (
        build_partition)

    prob, _ = reference
    ref_obs = str(tmp_path / "ref.obs.jsonl")
    res = build_partition(
        prob, PartitionConfig(**BASE, obs="jsonl", obs_path=ref_obs),
        oracle=_oracle(prob))
    wd = str(tmp_path / "ex")
    os.makedirs(wd)
    # Distinct per-shard stream paths: both engines share one PROCESS
    # here (threaded harness), so the per-process suffix cannot
    # disambiguate them the way it does for the real launcher.
    results = _run_shards(
        prob, 2, wd,
        cfg_extra=lambda i: {
            "obs": "jsonl",
            "obs_path": str(tmp_path / f"fleet.obs.p{i}.jsonl")})
    for _r, _o, eng in results:
        eng.finish_obs()
    ref_counters = fleet_lib.fleet_rollup(
        fleet_lib.load_fleet([ref_obs]))["counters"]
    roll = fleet_lib.fleet_rollup(
        fleet_lib.load_fleet(str(tmp_path / "fleet.obs.p*.jsonl")))
    assert roll["n_streams"] == 2
    for key in ("oracle.point_solves", "build.leaves", "build.splits"):
        assert roll["counters"].get(key) == ref_counters.get(key), key
    assert res.stats["regions"] == results[0][0].stats["regions"]
    # Sharded rollups carry the per-shard regions SUM alongside the
    # lockstep-max (each shard certifies its own subtree).
    assert roll["regions_sum"] == res.stats["regions"]


def test_sharded_checkpoints_are_per_shard(reference, tmp_path):
    prob, ref = reference
    ck = str(tmp_path / "b.ckpt.pkl")
    results = _run_shards(
        prob, 2, str(tmp_path / "ex"),
        cfg_extra={"checkpoint_every": 2, "checkpoint_path": ck})
    assert results[0][0].stats["regions"] == ref.stats["regions"]
    for i in (0, 1):
        path = f"{ck}.p{i}"
        assert os.path.exists(path), path
        from explicit_hybrid_mpc_tpu.partition.frontier import (
            load_checkpoint)

        snap = load_checkpoint(path)
        assert snap["cfg"].shard_frontier


def test_remote_timeout_falls_back_locally(reference, tmp_path):
    """Liveness: a shard whose peer never answers re-solves remote
    cells locally after shard_timeout_s and still finishes its own
    subtree soundly (loud: shard_fallback_cells > 0)."""
    from explicit_hybrid_mpc_tpu.partition.frontier import (
        FrontierEngine)

    prob, ref = reference
    cfg = PartitionConfig(**BASE, shard_frontier=True,
                          shard_dir=str(tmp_path),
                          shard_index=0, shard_count=2,
                          shard_timeout_s=0.3)
    eng = FrontierEngine(prob, _oracle(prob), cfg)
    while eng.frontier:  # step manually: run() would block in finalize
        eng.step()
    assert eng._shard.fallback_cells > 0
    assert eng.n_uncertified == 0
    # This shard certified exactly its own root's subtree.
    per_shard_regions = eng.tree.n_regions()
    assert 0 < per_shard_regions < ref.stats["regions"]


# -- async host-certify ------------------------------------------------------


def test_async_certify_bit_identical(reference):
    """cfg.async_certify resolves the same device programs earlier:
    the tree is BIT-identical (payloads included) and solve counters
    are unchanged; the overlap ledger records background waits."""
    from explicit_hybrid_mpc_tpu.partition.frontier import (
        build_partition)

    prob, ref = reference
    res = build_partition(
        prob, PartitionConfig(**BASE, async_certify=True),
        oracle=_oracle(prob))
    a, b = ref.tree, res.tree
    assert len(a) == len(b)
    assert np.array_equal(a.vertices, b.vertices)
    ia, ib = a.converged_leaf_ids(), b.converged_leaf_ids()
    assert np.array_equal(ia, ib)
    for xa, xb in zip(a.leaf_payloads(ia), b.leaf_payloads(ib)):
        assert np.array_equal(xa, xb)
    assert res.stats["point_solves"] == ref.stats["point_solves"]
    assert res.stats["async_certify"] is True
    assert res.stats["cp_overlap_s"] >= 0.0
    assert res.stats["regions"] == ref.stats["regions"]


def test_async_certify_absorbs_wait_into_certify_window(reference):
    """The overlap mechanism, made measurable: with a wait-side delay
    injected into the oracle (standing in for real device latency the
    CPU harness lacks), the background waiter must absorb wait wall
    into the certify window (cp_overlap_s > 0) -- and the tree must
    stay BIT-identical to the same build without async certify, since
    the resolved programs are the same ones, earlier."""
    import time as _time

    from explicit_hybrid_mpc_tpu.partition.frontier import (
        FrontierEngine)

    prob, _ = reference
    base = dict(BASE, batch_simplices=8)  # small batches: full-size
    # claims (the pipeline's lookahead unit) occur on most steps.

    def build(async_on: bool):
        oracle = _oracle(prob)
        orig = oracle.wait_vertices

        def slow_wait(handle):
            _time.sleep(0.01)
            return orig(handle)

        oracle.wait_vertices = slow_wait
        cfg = PartitionConfig(**base, async_certify=async_on)
        eng = FrontierEngine(prob, oracle, cfg)
        return eng.run()

    sync = build(False)
    asy = build(True)
    assert asy.stats["cp_overlap_s"] > 0.0, \
        "background waiter never absorbed a wait"
    assert sync.stats["cp_overlap_s"] == 0.0
    a, b = sync.tree, asy.tree
    assert np.array_equal(a.vertices, b.vertices)
    assert np.array_equal(a.converged_leaf_ids(),
                          b.converged_leaf_ids())
    assert asy.stats["point_solves"] == sync.stats["point_solves"]


def test_async_certify_with_sharding(reference, tmp_path):
    """Async certify composes with the sharded frontier (the multichip
    bench configuration): parity bar unchanged."""
    prob, ref = reference
    results = _run_shards(prob, 2, str(tmp_path),
                          cfg_extra={"async_certify": True})
    merged = results[0][0]
    assert compare_trees_canonical(ref.tree, merged.tree) == []
    summed = sum(o.n_point_solves for _r, o, _e in results)
    assert summed == ref.stats["point_solves"]


# -- merge / canonical compare ----------------------------------------------


def test_merge_rejects_diverged_roots(reference):
    prob, ref = reference
    t2 = pickle.loads(pickle.dumps(ref.tree))
    t2._vertices[0, 0, 0] += 1.0
    with pytest.raises(ValueError, match="roots diverge"):
        merge_shard_trees([ref.tree, t2], lambda r: r % 2)


def test_compare_trees_canonical_flags_status_drift(reference):
    prob, ref = reference
    assert compare_trees_canonical(ref.tree, ref.tree,
                                   payloads=True) == []
    t2 = pickle.loads(pickle.dumps(ref.tree))
    leaf = int(t2.converged_leaf_ids()[0])
    t2.clear_leaf(leaf)
    diffs = compare_trees_canonical(ref.tree, t2)
    assert diffs, "cleared leaf must surface as a canonical diff"
