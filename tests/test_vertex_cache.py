"""Direct unit tests for VertexCache eviction + refcount accounting
(ISSUE 3 satellite): `_release` must drop a vertex only when no open
simplex references it, and the peak_vertices/peak_bytes high-water
marks must survive a release-then-reinsert cycle."""

import numpy as np

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                        VertexCache)
from explicit_hybrid_mpc_tpu.problems.registry import make

EPS = 0.5


def _row():
    return (np.zeros(1), np.zeros(1, dtype=bool), np.zeros((1, 2)),
            np.zeros((1, 1)), np.zeros((1, 3)), 0.0, np.int64(0),
            np.ones(1, dtype=bool), None, None)


def test_release_drops_vertex_only_when_unreferenced():
    """The box triangulation's root simplices share vertices: releasing
    ONE root must keep every shared row alive (refcount > 0) and evict
    only that root's exclusive rows; releasing the other root then
    drains the cache and the refcount map completely."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=EPS,
                          backend="cpu", batch_simplices=8)
    eng = FrontierEngine(prob, Oracle(prob, backend="cpu"), cfg)
    assert len(eng.roots) == 2  # 2-D box -> 2 triangles
    n0, n1 = eng.roots
    k0, k1 = set(eng._keys(n0)), set(eng._keys(n1))
    shared = k0 & k1
    assert shared and (k0 - shared)  # diagonal shared, corners exclusive
    for k in k0 | k1:
        eng.cache.put_key(k, _row())
    eng._release(n0)
    for k in shared:
        assert eng.cache.get_key(k) is not None, "shared row evicted early"
        assert eng._refcount[k] == 1
    for k in k0 - shared:
        assert eng.cache.get_key(k) is None, "exclusive row not evicted"
        assert k not in eng._refcount
    # Release-then-reinsert: retaining n0 again must re-count its keys
    # without disturbing n1's.
    eng._retain(n0)
    for k in shared:
        assert eng._refcount[k] == 2
    eng._release(n0)
    eng._release(n1)
    assert len(eng.cache) == 0
    assert eng._refcount == {}


def test_peak_accounting_survives_release_then_reinsert():
    c = VertexCache()
    row = _row()
    for i in range(3):
        c.put_key(bytes([i]), row)
    assert c.peak_vertices == 3
    row_bytes = c._row_bytes
    assert row_bytes > 0
    assert c.peak_bytes == 3 * row_bytes
    # Evict below the high-water mark; reinsert back up to it.
    c.evict_key(b"\x00")
    c.evict_key(b"\x01")
    assert len(c) == 1
    c.put_key(b"\x05", row)
    assert len(c) == 2
    assert c.peak_vertices == 3, "high-water mark must not regress"
    assert c.peak_bytes == 3 * row_bytes
    # A genuinely new high water moves both marks.
    c.put_key(b"\x06", row)
    c.put_key(b"\x07", row)
    assert c.peak_vertices == 4
    assert c.peak_bytes == 4 * row_bytes


def test_evict_missing_key_is_noop():
    c = VertexCache()
    c.put_key(b"a", _row())
    c.evict_key(b"zzz")  # must not raise
    assert len(c) == 1
