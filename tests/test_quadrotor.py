"""Quadrotor obstacle-avoidance benchmark: encoding sanity, avoidance
semantics, oracle-vs-scipy, and a coarse partition over the 4-D slice."""

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make
from tests.qp_ref import fixed_delta_value


@pytest.fixture(scope="module")
def quad():
    return make("quadrotor", N=4)


@pytest.fixture(scope="module")
def oracle(quad):
    return Oracle(quad, backend="cpu")


def test_canonical_shapes(quad):
    can = quad.canonical
    assert can.n_delta == 16
    assert can.deltas.shape == (16, 8)     # 8 one-hot integer mode vars
    assert np.all(can.deltas.sum(axis=1) == 2)   # one face per obstacle
    assert can.nz == 4 * quad.N + 2 * quad.N     # inputs + obstacle slacks
    assert quad.n_theta == 4


def test_root_splits_cover_obstacle_edges(quad):
    assert set(quad.root_splits) == {0, 1}
    assert set(quad.root_splits[0]) == {-2.1, -0.9, 0.9, 2.1}
    assert set(quad.root_splits[1]) == {-0.6, 0.6}


def test_avoidance_rows_bind(oracle, quad):
    """Starting at rest at the origin (left of obstacle 0 at (1.5, 0)),
    'stay right of obstacle 0' pays the heavy soft-avoidance penalty (the
    quad cannot actually cross in one step), so the optimum picks a
    penalty-free side and the side choice separates by orders of
    magnitude in cost."""
    th = np.array([0.0, 0.0, 0.0, 0.0])   # at origin, left of obs 0
    sol = oracle.solve_vertices(th[None])
    deltas = quad.canonical.deltas
    left_of_0 = deltas[:, 0] == 1          # face 0 = (-1, x): stay left
    right_of_0 = deltas[:, 1] == 1         # face 1 = (+1, x): stay right
    assert np.isfinite(sol.Vstar[0])
    assert deltas[sol.dstar[0], 1] == 0    # optimum never squeezes right
    V_left = sol.V[0, left_of_0].min()
    V_right = sol.V[0, right_of_0].min()
    assert V_right > 10.0 * V_left


def test_enumeration_matches_admm_reference(oracle, quad):
    """IPM values vs an independent ADMM QP solver (tests/qp_ref.py;
    SLSQP stalls on the penalty-conditioned slices).  The argmin delta
    must match exactly; other converged deltas are spot-checked."""
    can = quad.canonical
    thetas = np.array([[0.0, 2.0, 0.5, -0.5],
                       [-3.0, -2.0, 0.0, 1.0]])
    sol = oracle.solve_vertices(thetas)
    for k, th in enumerate(thetas):
        d_star = int(sol.dstar[k])
        ref = fixed_delta_value(can, d_star, th)
        assert ref is not None, "ADMM failed on the optimal delta"
        np.testing.assert_allclose(sol.Vstar[k], ref, rtol=1e-6, atol=1e-8)
        # No converged delta may beat the claimed optimum.
        for d in range(0, can.n_delta, 5):
            v = fixed_delta_value(can, d, th, max_iter=20_000)
            if v is not None:
                assert v >= sol.Vstar[k] - 1e-6
                np.testing.assert_allclose(sol.V[k, d], v,
                                           rtol=1e-5, atol=1e-6)


def test_inside_obstacle_penalized(oracle, quad):
    """Deep inside obstacle 0 at rest every side choice pays the slack
    penalty: V* stays finite (soft rows) but dwarfs the free-space cost."""
    th_in = np.array([1.5, 0.0, 0.0, 0.0])
    th_out = np.array([-0.5, 0.0, 0.0, 0.0])
    sol = oracle.solve_vertices(np.stack([th_in, th_out]))
    assert np.all(np.isfinite(sol.Vstar))
    assert sol.Vstar[0] > 10.0 * sol.Vstar[1]


def test_partition_build_coarse():
    """Coarse eps over the 2-D position slice (the 4-D benchmark build is
    bench territory, not a CPU test): must terminate with certified +
    infeasible leaves only (obstacle interiors are certified-infeasible,
    exercising the Farkas path)."""
    quad2 = make("quadrotor", N=3, param="p")
    # eps_a + eps_r combined: near the goal V* -> 0 and a pure relative
    # test needs unbounded depth; the absolute tolerance closes it there.
    cfg = PartitionConfig(problem="quadrotor", eps_a=0.05, eps_r=0.5,
                          backend="cpu", batch_simplices=128,
                          max_steps=800, max_depth=12)
    res = build_partition(quad2, cfg)
    assert res.stats["regions"] > 0
    assert not res.stats["truncated"]
    assert res.stats["uncertified"] == 0
