"""bench.py capture robustness (round-1 postmortem: one backend outage
produced an empty round).  The benchmark must ALWAYS emit a parseable JSON
line with a value, on any platform, inside a bounded wall-clock window."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_cpu_emits_json():
    env = dict(
        os.environ,
        BENCH_PLATFORM="cpu",
        BENCH_PROBLEM="double_integrator",
        BENCH_EPS="0.2",
        BENCH_MAX_STEPS="80",
        BENCH_TIME_BUDGET="60",
        BENCH_DEADLINE="240",
        BENCH_BATCH="64",
        BENCH_POINTS_CAP="64",
    )
    out = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                         text=True, timeout=300, cwd=REPO, env=env)
    assert out.stdout.strip(), f"no stdout; stderr tail: {out.stderr[-800:]}"
    data = json.loads(out.stdout.strip().splitlines()[-1])
    assert out.returncode == 0, f"rc={out.returncode}: {data}"
    assert data["value"] is not None and data["value"] > 0
    assert data["unit"] == "regions/s"
    assert data["platform"] == "cpu"
    assert data["vs_baseline"] is not None
    assert data["regions"] > 0
    # Both serial baselines ship: the flat vmap-amortized estimate and the
    # measured best-first B&B stand-in (round-3 verdict item 8).
    assert data["vs_baseline_bnb"] is not None and data["vs_baseline_bnb"] > 0
    assert data["bnb_qp_per_point"] >= 1
    assert "incumbent pruning" in data["bnb_baseline_definition"]


def test_bench_probe_failure_is_not_fatal():
    """probe_backend must return None (not raise, not hang) when the probe
    subprocess cannot produce a backend."""
    sys.path.insert(0, REPO)
    try:
        import bench
        assert bench.probe_backend(0.001) is None
    finally:
        sys.path.remove(REPO)
