"""bench.py capture robustness (round-1 postmortem: one backend outage
produced an empty round).  The benchmark must ALWAYS emit a parseable JSON
line with a value, on any platform, inside a bounded wall-clock window."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_bench_smoke_cpu_emits_json():
    env = dict(
        os.environ,
        BENCH_PLATFORM="cpu",
        BENCH_PROBLEM="double_integrator",
        BENCH_EPS="0.2",
        BENCH_MAX_STEPS="80",
        BENCH_TIME_BUDGET="60",
        BENCH_DEADLINE="240",
        BENCH_BATCH="64",
        BENCH_POINTS_CAP="64",
        BENCH_LARGE_DEPTH="6",
        BENCH_LARGE_P="3",
        BENCH_SHARDS="4",
    )
    out = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                         text=True, timeout=300, cwd=REPO, env=env)
    assert out.stdout.strip(), f"no stdout; stderr tail: {out.stderr[-800:]}"
    data = json.loads(out.stdout.strip().splitlines()[-1])
    assert out.returncode == 0, f"rc={out.returncode}: {data}"
    assert data["value"] is not None and data["value"] > 0
    assert data["unit"] == "regions/s"
    assert data["platform"] == "cpu"
    assert data["vs_baseline"] is not None
    assert data["regions"] > 0
    # Export-seconds + large-L serving fields (PR 1): regressions in the
    # export/serving path must surface in every BENCH_*.json.
    assert data["export_leaves_s"] >= 0
    assert data["large_l_leaves"] == 6 * 2 ** 6
    assert data["large_l_export_s"] >= 0
    assert data["large_l_flat_us_per_query"] > 0
    assert data["large_l_sharded_us_per_query"] > 0
    # Both serial baselines ship: the flat vmap-amortized estimate and the
    # measured best-first B&B stand-in (round-3 verdict item 8).
    assert data["vs_baseline_bnb"] is not None and data["vs_baseline_bnb"] > 0
    assert data["bnb_qp_per_point"] >= 1
    assert "incumbent pruning" in data["bnb_baseline_definition"]
    # Unified obs metrics block (ISSUE 2): build/oracle/serving signals
    # condensed into every bench JSON so the trajectory carries trend
    # data, not just the headline number.
    mb = data["metrics"]
    assert mb["counters"]["build.steps"] > 0
    assert mb["histograms"]["oracle.point_solve_s"]["p99"] > 0
    assert mb["counters"]["bnb.points"] > 0
    # The large-L section served through the sharded path with the same
    # handle, so serving latencies ride along.
    assert mb["histograms"]["serve.query_s"]["count"] > 0


def test_bench_probe_failure_is_not_fatal():
    """probe_backend must return None (not raise, not hang) when the probe
    subprocess cannot produce a backend."""
    sys.path.insert(0, REPO)
    try:
        import bench
        assert bench.probe_backend(0.001) is None
    finally:
        sys.path.remove(REPO)


def test_probe_skip_vs_failure_classification(monkeypatch):
    """ISSUE 9 satellite: a clean CPU-only host (accelerator probe
    negative, CPU-pinned probe fine -- the shape of every committed
    BENCH_rNN capture on this container) must record
    backend_probe_skipped, NOT backend_probe_failed; the probe detail
    moves to backend_probe_detail so obs_report stops rendering the
    expected configuration as a degraded capture.  A host where even
    the CPU probe dies keeps the genuine-failure fields."""
    sys.path.insert(0, REPO)
    try:
        import bench

        monkeypatch.delenv("BENCH_PLATFORM", raising=False)

        def fail_probe(timeout_s, result=None):
            if result is not None:
                result["backend_probe_error"] = "probe timed out"
            return None

        monkeypatch.setattr(bench, "probe_backend", fail_probe)
        monkeypatch.setattr(bench, "probe_cpu_only", lambda t: True)
        res = {}
        assert bench.choose_backend(
            res, hold_capture_sentinel=False) == "cpu"
        assert res.get("backend_probe_skipped") is True
        assert "backend_probe_failed" not in res
        assert "backend_probe_error" not in res
        assert res.get("backend_probe_detail") == "probe timed out"

        monkeypatch.setattr(bench, "probe_cpu_only", lambda t: False)
        res2 = {}
        assert bench.choose_backend(
            res2, hold_capture_sentinel=False) == "cpu"
        assert res2.get("backend_probe_failed") is True
        assert res2.get("backend_probe_error") == "probe timed out"
        assert "backend_probe_skipped" not in res2

        # obs_report classification: skipped is NOT a warning (the
        # whole point); genuine failures still warn.
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import obs_report
        finally:
            sys.path.remove(os.path.join(REPO, "scripts"))
        assert obs_report.bench_warnings(
            {"backend_probe_skipped": True,
             "backend_probe_detail": "probe timed out"}) == []
        assert obs_report.bench_warnings(res2)
    finally:
        sys.path.remove(REPO)


def test_bench_smoke_carries_host_fields():
    """r4 weak #1: the driver capture silently reported half the real
    throughput while a background campaign ran.  The JSON must carry the
    load/contention fields so a contended capture is self-describing."""
    # Reuses the smoke run's artifact shape via a tiny dedicated run.
    env = dict(
        os.environ,
        BENCH_PLATFORM="cpu",
        BENCH_PROBLEM="double_integrator",
        BENCH_EPS="0.5",
        BENCH_MAX_STEPS="20",
        BENCH_TIME_BUDGET="30",
        BENCH_DEADLINE="180",
        BENCH_BATCH="32",
        BENCH_POINTS_CAP="32",
        BENCH_LARGE_DEPTH="0",  # host-fields test: skip the extras
    )
    out = subprocess.run([sys.executable, "bench.py"], capture_output=True,
                         text=True, timeout=240, cwd=REPO, env=env)
    data = json.loads(out.stdout.strip().splitlines()[-1])
    host = data.get("host")
    assert host and host["cpu_count"] >= 1
    assert "loadavg_end" in host
    # procfs hosts sample the competing share; the flag must be present
    # (True or False), not silently missing.
    if "competing_cpu_frac_mean" in host:
        assert "contended" in host
        assert 0.0 <= host["competing_cpu_frac_mean"] <= 1.0


def test_contention_monitor_sees_competing_load():
    """The monitor must attribute a busy-spinning OTHER process to the
    competing share, not to the bench's own."""
    import time as _t

    import pytest

    sys.path.insert(0, REPO)
    try:
        from bench import ContentionMonitor
        mon = ContentionMonitor(interval_s=0.4)
        if mon._jiffies() is None:
            return  # non-procfs host: monitor degrades to loadavg only
        # Some virtualized hosts expose a FROZEN /proc/stat (all-zero
        # cpu line that never advances); no sampler can measure load
        # there.  The guest-jiffies arithmetic is covered determin-
        # istically via fake readers in tests/test_obs.py.
        j0 = mon._jiffies()
        t0 = _t.time()
        while _t.time() - t0 < 0.3:
            pass  # burn real CPU
        if mon._jiffies()[0] - j0[0] <= 0:
            pytest.skip("frozen /proc/stat: busy jiffies never advance")
        spin = subprocess.Popen(
            [sys.executable, "-c",
             "import time; t=time.time()\n"
             "while time.time()-t < 4: pass"])
        try:
            mon.start()
            _t.sleep(3.0)
            s = mon.summary()
        finally:
            spin.kill()
        assert s.get("competing_cpu_frac_mean", 0) > 0.005, s
    finally:
        sys.path.remove(REPO)


def test_cpu_cache_dir_is_host_fingerprinted():
    """r4 weak #8: XLA:CPU executables reused across machine types risk
    SIGILL.  The CPU cache dir must be keyed by the host fingerprint."""
    sys.path.insert(0, REPO)
    try:
        import jax

        from bench import cpu_cache_dir, host_cpu_fingerprint
        fp = host_cpu_fingerprint()
        assert len(fp) == 12 and fp == host_cpu_fingerprint()  # stable
        d = cpu_cache_dir()
        # Suite processes run a forced-device-count client (conftest
        # sets XLA_FLAGS when absent), whose AOT lowering prefs differ
        # from other counts' -- the key must carry the ACTIVE count.
        import re as _re

        m = _re.search(r"host_platform_device_count=(\d+)",
                       os.environ.get("XLA_FLAGS", ""))
        n = m.group(1) if m else "1"
        assert os.path.basename(d) == f"cpu-{fp}-d{n}"
        # conftest pins the forced-CPU in-process tests to the
        # fingerprinted dir (the env var stays the shared base for
        # accelerator subprocesses).
        assert jax.config.jax_compilation_cache_dir == d
    finally:
        sys.path.remove(REPO)


def test_hold_sentinel_creates_and_releases(tmp_path, monkeypatch):
    sys.path.insert(0, REPO)
    try:
        import bench
        sent = str(tmp_path / ".capture_active")
        monkeypatch.setattr(bench, "SENTINEL", sent)
        stop = bench.hold_sentinel()
        assert os.path.exists(sent)
        stop()
        assert not os.path.exists(sent)
        # Pre-existing sentinel (the watcher's) must survive release.
        open(sent, "w").close()
        bench.hold_sentinel()()
        assert os.path.exists(sent)
    finally:
        sys.path.remove(REPO)


def test_busy_jiffies_excludes_guest_ticks():
    """ADVICE r5: /proc/stat's user field already contains guest ticks;
    busy accounting must subtract guest/guest_nice or VM hosts running
    guests double-count and overstate the competing-CPU share."""
    from bench import ContentionMonitor

    # user nice system idle iowait irq softirq steal guest guest_nice
    full = [100, 10, 50, 900, 30, 5, 5, 10, 40, 2]
    assert ContentionMonitor._busy_jiffies(full) == 100 + 10 + 50 + 5 + 5 + 10
    # Guest ticks excluded exactly once: adding guest load to user (as
    # the kernel does) must not change the busy total beyond the real
    # steal/virtualization fields.
    no_guest = [60, 8, 50, 900, 30, 5, 5, 10]
    assert ContentionMonitor._busy_jiffies(no_guest) == 60 + 8 + 50 + 5 + 5 + 10
    # Short pre-2.6.24 lines (no steal/guest fields) still work.
    assert ContentionMonitor._busy_jiffies([100, 10, 50, 900]) == 160
