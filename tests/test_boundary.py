"""Semi-explicit boundary closure (cfg.semi_explicit_boundary_depth).

Round-3 verdict item 4: a simplex whose vertices have mixed feasibility
straddles the feasible set's boundary and can never pass a whole-simplex
certificate -- the pure 'suboptimal' build splits it to max_depth and
leaves an uncovered hole.  The closure composes the two algorithm
variants: at depth >= semi_explicit_boundary_depth such cells close as
SEMI-EXPLICIT leaves (stored feasible-somewhere commutation + online
fixed-delta QP), so the build drains with volume fully accounted and the
certified / semi-explicit split reported separately.
"""

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.online import export
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.post.analysis import partition_report
from explicit_hybrid_mpc_tpu.problems.registry import make
from explicit_hybrid_mpc_tpu.sim.simulator import SemiExplicitController

# A box large enough that the input-constrained finite-horizon QP is
# infeasible near the corners: the feasible boundary crosses the interior.
_BOX = 3.0


@pytest.fixture(scope="module")
def problem():
    return make("mass_spring", N=4, theta_box=_BOX)


@pytest.fixture(scope="module")
def oracle(problem):
    return Oracle(problem, backend="cpu")


def _cfg(**kw):
    base = dict(problem="mass_spring", eps_a=1.0, eps_r=0.5, backend="cpu",
                batch_simplices=128, max_depth=12, max_steps=4000)
    base.update(kw)
    return PartitionConfig(**base)


@pytest.fixture(scope="module")
def closed_build(problem, oracle):
    # Closure depth sets the boundary-shell resolution: at 8, a cell
    # closing semi-explicit has volume 2^-8 of its root, so the shell
    # stays thin around the feasible boundary instead of swallowing the
    # (largely infeasible) outer box.
    return build_partition(problem, _cfg(semi_explicit_boundary_depth=8),
                           oracle=oracle)


def test_boundary_cells_exist(problem, oracle):
    """Precondition for the whole module: the chosen box actually puts
    the feasible boundary inside Theta (some vertices infeasible)."""
    rng = np.random.default_rng(0)
    pts = rng.uniform(problem.theta_lb, problem.theta_ub, size=(64, 4))
    sol = oracle.solve_vertices(pts)
    feas = sol.dstar >= 0
    assert feas.any() and not feas.all(), (
        f"box {_BOX} gives {feas.sum()}/64 feasible -- pick a box where "
        "the feasible boundary crosses the interior")


def test_closure_drains_with_boundary_covered(closed_build):
    """The frontier drains with every boundary cell closed semi-explicit
    (at this crude eps/depth the INTERIOR may still have depth-cap
    best-effort leaves -- that is the eps-vs-depth tradeoff, not the
    boundary feature; the benchmark-scale run drives it to zero)."""
    stats = closed_build.stats
    assert not stats["truncated"]
    assert stats["semi_explicit"] > 0
    rep = partition_report(closed_build.tree, closed_build.roots)
    assert rep["n_semi_explicit"] == stats["semi_explicit"]
    # Large parts of a 3.0 box are infeasible or (at depth 12) best-
    # effort; the invariant under test is the ACCOUNTING: certified and
    # semi-explicit volume both exist and are reported separately.
    assert rep["volume_certified_frac"] > 0.05
    assert 0.0 < rep["volume_semi_explicit_frac"] < 0.5


def test_no_closure_leaves_holes(problem, oracle, closed_build):
    """The same build WITHOUT the closure burns steps on the boundary
    shell and ends with uncovered volume at the depth cap (mixed cells
    have no all-vertex-feasible candidate, so they become holes)."""
    res = build_partition(problem, _cfg(), oracle=oracle)
    rep_open = partition_report(res.tree, res.roots)
    rep_closed = partition_report(closed_build.tree, closed_build.roots)
    covered_open = (rep_open["volume_certified_frac"]
                    + rep_open["volume_best_effort_frac"])
    covered_closed = (rep_closed["volume_certified_frac"]
                      + rep_closed["volume_best_effort_frac"]
                      + rep_closed["volume_semi_explicit_frac"])
    assert covered_closed > covered_open, (
        "closure must strictly extend guaranteed coverage")
    assert res.stats["semi_explicit"] == 0


def test_semi_explicit_leaves_have_mixed_feasibility(closed_build, oracle):
    """Each semi-explicit leaf straddles the boundary: its stored
    commutation converges at >= 1 vertex but not all (that is the only
    path that creates them)."""
    tree = closed_build.tree
    semi = [i for i in tree.converged_leaves()
            if getattr(tree.leaf_data[i], "semi_explicit", False)]
    assert semi
    for n in semi[:10]:
        sol = oracle.solve_vertices(tree.vertices[n])
        conv = sol.conv[:, tree.leaf_data[n].delta_idx]
        assert conv.any() and not conv.all()


def test_hybrid_online_path(closed_build, problem, oracle):
    """Deployment: certified leaves answer by interpolation (no QP);
    semi-explicit leaves run the online fixed-delta QP, which succeeds on
    the feasible side of the cell (sampled at converged vertices)."""
    table = export.export_leaves(closed_build.tree)
    mask = export.semi_explicit_mask(closed_build.tree, table)
    assert mask.any() and not mask.all()
    ctl = SemiExplicitController(table, oracle, semi_mask=mask)

    tree = closed_build.tree
    cert = [i for i in tree.converged_leaves()
            if not getattr(tree.leaf_data[i], "semi_explicit", False)
            and getattr(tree.leaf_data[i], "certified", True)][0]
    theta_cert = tree.vertices[cert].mean(axis=0)  # interior point
    before = oracle.n_point_solves
    u, info = ctl(theta_cert)
    assert oracle.n_point_solves == before, "certified leaf must not QP"
    assert info.inside

    semi = [i for i in tree.converged_leaves()
            if getattr(tree.leaf_data[i], "semi_explicit", False)][0]
    sol = oracle.solve_vertices(tree.vertices[semi])
    d = tree.leaf_data[semi].delta_idx
    v_ok = int(np.where(sol.conv[:, d])[0][0])
    # STRICTLY inside the cell (a bare vertex is shared with adjacent --
    # possibly certified -- leaves and point location may pick those),
    # biased toward the feasible vertex so the online QP has a solution.
    theta_semi = (0.9 * tree.vertices[semi][v_ok]
                  + 0.1 * tree.vertices[semi].mean(axis=0))
    before = oracle.n_point_solves
    u, info = ctl(theta_semi)
    assert oracle.n_point_solves > before, "semi-explicit leaf must QP"
    assert np.all(np.isfinite(u))
