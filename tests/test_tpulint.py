"""tpulint rule engine + rule pack + CLI gate (analysis/, ISSUE 6).

Fixture snippets per rule (positive, negative, pragma-suppressed),
baseline round-trip, the jit-region index's reachability cases, and the
tier-1 gate itself: the whole package must lint clean against the
committed TPULINT_BASELINE.json -- the same check scripts/tpulint.py
runs pre-merge (docs/static_analysis.md)."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from explicit_hybrid_mpc_tpu.analysis import engine
from explicit_hybrid_mpc_tpu.analysis.rules import all_rules, rules_by_name

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "explicit_hybrid_mpc_tpu")
BASELINE = os.path.join(REPO, "TPULINT_BASELINE.json")


def lint(src: str, rules=None) -> list:
    return engine.lint_source(textwrap.dedent(src), "fixture.py",
                              rules=rules, rel="fixture.py")


def rule_ids(findings) -> set:
    return {f.rule for f in findings}


# -- host-sync-in-jit ------------------------------------------------------

_HOST_SYNC_POS = """
    import jax, numpy as np

    @jax.jit
    def kernel(x):
        s = float(x.sum())          # host cast
        a = np.asarray(x)           # np transfer
        v = x.item()                # blocking read
        if jnp.any(x > 0):          # traced branch
            s = s + 1
        return s + a.sum() + v
"""


def test_host_sync_positive():
    found = lint(_HOST_SYNC_POS)
    msgs = [f for f in found if f.rule == "host-sync-in-jit"]
    assert len(msgs) == 4, found
    assert all(f.severity == "error" for f in msgs)


def test_host_sync_positive_pallas_kernel_body():
    """A Pallas kernel body is a traced (then Mosaic-lowered) region:
    functions passed to pl.pallas_call index as jit regions, so the
    host-sync rule covers them (oracle/pallas_ipm.py,
    online/pallas_eval.py)."""
    found = lint("""
        import jax
        from jax.experimental import pallas as pl

        def _kernel(x_ref, o_ref):
            v = float(x_ref[0])     # host cast inside the kernel
            o_ref[:] = x_ref[:] + v

        def launch(x):
            return pl.pallas_call(
                _kernel, out_shape=x)(x)
    """)
    assert "host-sync-in-jit" in rule_ids(found)


def test_host_sync_negative_pallas_host_helper():
    # The same cast in a plain host helper of the same module: clean.
    found = lint("""
        from jax.experimental import pallas as pl

        def _kernel(x_ref, o_ref):
            o_ref[:] = x_ref[:]

        def host_stage(x):
            scale = float(x.sum())
            return pl.pallas_call(_kernel, out_shape=x)(x), scale
    """)
    assert "host-sync-in-jit" not in rule_ids(found)


def test_host_sync_negative_host_code_free():
    # The SAME calls outside any jit region are plain numpy: clean.
    found = lint("""
        import numpy as np

        def host(x):
            if np.any(x > 0):
                return float(x.sum()) + np.asarray(x).item()
            return 0.0
    """)
    assert "host-sync-in-jit" not in rule_ids(found)


def test_host_sync_negative_static_python_in_jit():
    # Static Python control flow inside a jitted fn is fine (the
    # kernels' n_f32 > 0 / warm_start is None patterns).
    found = lint("""
        import jax

        @jax.jit
        def kernel(x, n=3):
            if n > 0:
                x = x * n
            return x
    """)
    assert "host-sync-in-jit" not in rule_ids(found)


def test_host_sync_positive_fused_arena_kernel_shape():
    """Seeded violation shaped like the PR-16 fused arena kernel
    (online/pallas_eval._fused_kernel): a multi-ref grid kernel with
    VMEM scratch operands and pl.program_id tile logic, seeded with ONE
    host cast in the kernel body.  Pins that the host-sync rule keeps
    indexing pallas_call bodies at this arity/shape -- the fused
    descent->eval->clamp kernel is exactly the region where a stray
    host sync would stall every mixed-tenant batch."""
    found = lint("""
        import jax.numpy as jnp
        from jax.experimental import pallas as pl

        def _fused(th_ref, lb_ref, ub_ref, ext_ref, bary_ref, u_ref,
                   v_ref, val_ref, idx_ref, u_out_ref, cost_ref,
                   clamp_ref, best_val, best_idx, best_u, best_cost):
            lt = pl.program_id(1)
            thc = jnp.clip(th_ref[:], lb_ref[:], ub_ref[:])
            scale = float(thc.max())        # seeded host cast
            best_val[:] = best_val[:] * jnp.float32(scale)
            val_ref[:] = best_val[:]

        def launch(th, lb, ub, ext, bary, u, v, grid, shapes, scratch):
            return pl.pallas_call(
                _fused, grid=grid, out_shape=shapes,
                scratch_shapes=scratch)(th, lb, ub, ext, bary, u, v)
    """)
    msgs = [f for f in found if f.rule == "host-sync-in-jit"]
    assert len(msgs) == 1 and msgs[0].severity == "error", found


def test_host_sync_pragma_line():
    found = lint("""
        import jax

        @jax.jit
        def kernel(x):
            return float(x)  # tpulint: disable=host-sync-in-jit -- probe
    """)
    assert "host-sync-in-jit" not in rule_ids(found)


def test_host_sync_transitive_helper():
    # A helper CALLED from a jitted lambda is traced too.
    found = lint("""
        import jax

        def helper(x):
            return float(x)

        solve = jax.jit(lambda x: helper(x))
    """)
    assert "host-sync-in-jit" in rule_ids(found)


def test_jit_index_partial_and_fori_loop():
    # @functools.partial(jax.jit, ...) decoration and lax.fori_loop
    # body position both mark their functions.
    found = lint("""
        import functools, jax

        @functools.partial(jax.jit, static_argnums=0)
        def kernel(n, x):
            return float(x)

        def body(i, c):
            return c + float(i)

        def run(x):
            return jax.lax.fori_loop(0, 3, body, x)
    """)
    per_line = {f.line for f in found if f.rule == "host-sync-in-jit"}
    assert len(per_line) == 2, found


# -- recompile-hazard ------------------------------------------------------

def test_recompile_jit_in_function_positive_and_ctor_exempt():
    found = lint("""
        import jax

        def per_call(x):
            fn = jax.jit(lambda y: y * 2)   # fresh compile per call
            return fn(x)

        class Oracle:
            def __init__(self):
                self._fn = jax.jit(lambda y: y * 2)  # once per object
    """)
    hits = [f for f in found if f.rule == "recompile-hazard"]
    assert len(hits) == 1 and hits[0].line == 5, found


def test_recompile_cached_builder_exempt():
    found = lint("""
        import functools, jax

        @functools.lru_cache(maxsize=8)
        def solver(n):
            return jax.jit(lambda y: y * n)
    """)
    assert "recompile-hazard" not in rule_ids(found)


def test_recompile_loop_closure_positive():
    found = lint("""
        import jax

        def sweep(xs):
            out = []
            for scale in xs:
                fn = jax.jit(lambda y: y * scale)  # retrace per scale
                out.append(fn(scale))
            return out
    """)
    hits = [f for f in found if f.rule == "recompile-hazard"
            and "closes over" in f.msg]
    assert hits, found


def test_recompile_bucket_literal():
    found = lint("""
        def plan():
            pad = 100            # non-pow-2 bucket
            good_pad = 128       # pow-2: fine
            return pad + good_pad
    """)
    hits = [f for f in found if f.rule == "recompile-hazard"]
    assert len(hits) == 1 and "100" in hits[0].msg, found


def test_recompile_bucket_keyword():
    found = lint("""
        def run(solve):
            return solve(points_cap=1000)
    """)
    assert "recompile-hazard" in rule_ids(found)
    assert "recompile-hazard" not in rule_ids(lint("""
        def run(solve):
            return solve(points_cap=1024)
    """))


# -- dtype-discipline ------------------------------------------------------

def test_dtype_builtin_casts():
    found = lint("""
        import numpy as np

        def f(x):
            a = x.astype(float)            # width-ambiguous
            b = np.zeros(3, dtype=int)     # width-ambiguous
            c = np.zeros(3, dtype=bool)    # bool: exempt
            d = x.astype(np.float64)       # named: fine
            return a, b, c, d
    """)
    hits = [f for f in found if f.rule == "dtype-discipline"]
    assert len(hits) == 2, found


def test_dtype_x32_module_tag():
    tagged = """
        # tpulint: x32-module
        import jax.numpy as jnp
        import numpy as np

        def kernel(x):
            return x * np.float64(2.0)
    """
    found = lint(tagged)
    assert "dtype-discipline" in rule_ids(found)
    # Same code without the tag: f64 literals are policy here.
    untagged = "\n".join(l for l in textwrap.dedent(tagged).splitlines()
                         if "x32-module" not in l)
    assert "dtype-discipline" not in rule_ids(
        engine.lint_source(untagged, "fixture.py", rel="fixture.py"))


# -- obs-in-hot-loop -------------------------------------------------------

def test_obs_in_hot_loop_positive_negative():
    found = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(x, obs):
            obs.event("bad", v=1)          # emission in trace
            y = jnp.log(x)                 # array math: fine
            return y.at[0].set(0.0)        # .set is jnp, not a gauge
    """)
    hits = [f for f in found if f.rule == "obs-in-hot-loop"]
    assert len(hits) == 1 and hits[0].line == 7, found


def test_obs_emission_on_host_is_fine():
    found = lint("""
        def step(self):
            self.obs.event("build.step", n=1)
            self.log.emit(step=1)
    """)
    assert "obs-in-hot-loop" not in rule_ids(found)


# -- silent-except ---------------------------------------------------------

def test_silent_except_positive_negative_pragma():
    found = lint("""
        def risky(solve, x):
            try:
                return solve(x)
            except Exception:
                pass
    """)
    assert "silent-except" in rule_ids(found)
    # Typed + handled: clean.
    found = lint("""
        def risky(solve, x, log):
            try:
                return solve(x)
            except RuntimeError as e:
                log(e)
                return None
    """)
    assert "silent-except" not in rule_ids(found)
    # Pragma'd with justification: suppressed.
    found = lint("""
        def risky(dump, x):
            try:
                dump(x)
            except Exception:  # tpulint: disable=silent-except -- diag
                pass
    """)
    assert "silent-except" not in rule_ids(found)


# -- engine mechanics ------------------------------------------------------

def test_file_level_pragma_suppresses_whole_file():
    found = lint("""
        # tpulint: disable=silent-except
        def a(x):
            try:
                x()
            except Exception:
                pass

        def b(x):
            try:
                x()
            except Exception:
                pass
    """)
    assert "silent-except" not in rule_ids(found)


def test_parse_error_is_a_finding_not_a_crash():
    found = lint("def broken(:\n")
    assert [f.rule for f in found] == ["parse-error"]


def test_baseline_round_trip(tmp_path):
    src = """
        def risky(solve, x):
            try:
                return solve(x)
            except Exception:
                pass
    """
    findings = lint(src)
    assert findings
    bp = tmp_path / "baseline.json"
    bp.write_text(json.dumps(engine.baseline_payload(findings)))
    baseline = engine.load_baseline(str(bp))
    new, old = engine.split_baselined(findings, baseline)
    assert not new and len(old) == len(findings)
    # A SECOND occurrence of the same key is new (multiset semantics)...
    twice = findings + findings
    new, old = engine.split_baselined(twice, baseline)
    assert len(new) == len(findings) and len(old) == len(findings)
    # ...and baseline matching survives a line shift (content-keyed).
    shifted = lint("\n\n\n" + src)
    new, _ = engine.split_baselined(shifted, baseline)
    assert not new


def test_baseline_version_mismatch_raises(tmp_path):
    bp = tmp_path / "baseline.json"
    bp.write_text(json.dumps({"version": 999, "findings": []}))
    with pytest.raises(ValueError, match="version"):
        engine.load_baseline(str(bp))


def test_rule_registry_names_unique():
    rules = all_rules()
    names = [r.name for r in rules]
    assert len(set(names)) == len(names) == 5
    assert set(rules_by_name()) == {
        "host-sync-in-jit", "recompile-hazard", "dtype-discipline",
        "obs-in-hot-loop", "silent-except"}


# -- the tier-1 gate -------------------------------------------------------

def test_package_lints_clean_against_baseline():
    """The pre-merge invariant: zero non-baselined findings over the
    whole package.  A red run here means either fix the new violation
    or (for a justified intentional pattern) add an inline pragma with
    its reason -- NOT a baseline bump; the committed baseline stays the
    legacy-debt ledger only (docs/static_analysis.md)."""
    findings = engine.lint_paths([PACKAGE], root=REPO)
    baseline = engine.load_baseline(BASELINE)
    new, _ = engine.split_baselined(findings, baseline)
    assert not new, "new tpulint findings:\n" + "\n".join(
        f.render() for f in new)


def test_cli_gates_seeded_violation_and_passes_package(tmp_path):
    """scripts/tpulint.py exit contract: 1 on a seeded violation in a
    fixture file, 0 on the package at HEAD with the committed
    baseline."""
    bad = tmp_path / "seeded.py"
    bad.write_text(textwrap.dedent("""
        import jax

        @jax.jit
        def kernel(x):
            return float(x)
    """))
    script = os.path.join(REPO, "scripts", "tpulint.py")
    r = subprocess.run([sys.executable, script, str(bad)],
                       capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1, r.stdout + r.stderr
    assert "host-sync-in-jit" in r.stdout
    r = subprocess.run([sys.executable, script],
                       capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr


def test_cli_refuses_repo_baseline_update_from_restricted_run(tmp_path):
    """--update-baseline on the REPO baseline with explicit paths (or
    --rules) would drop every other baselined entry; the CLI refuses
    (exit 2).  Scoped updates against an explicit --baseline file stay
    allowed (next test)."""
    seed = tmp_path / "s.py"
    seed.write_text("x = 1\n")
    script = os.path.join(REPO, "scripts", "tpulint.py")
    r = subprocess.run(
        [sys.executable, script, str(seed), "--update-baseline"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 2 and "refusing" in r.stderr
    r = subprocess.run(
        [sys.executable, script, "--rules", "silent-except",
         "--update-baseline"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 2 and "refusing" in r.stderr
    # The committed baseline survived untouched.
    with open(BASELINE) as fh:
        assert json.load(fh)["findings"] == []


def test_cli_update_baseline_round_trip(tmp_path):
    bad = tmp_path / "seeded.py"
    bad.write_text("def f(x):\n    try:\n        x()\n"
                   "    except Exception:\n        pass\n")
    script = os.path.join(REPO, "scripts", "tpulint.py")
    bp = tmp_path / "b.json"
    r = subprocess.run(
        [sys.executable, script, str(bad), "--baseline", str(bp),
         "--update-baseline"], capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, script, str(bad), "--baseline", str(bp)],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "baselined" in r.stdout
