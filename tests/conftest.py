"""Test environment: CPU platform with 8 virtual devices.

Mesh/sharding logic is tested without a TPU via XLA's host-platform device
splitting (SURVEY.md section 5: "multi-device tests via jax CPU-device
simulation").

Platform forcing note: this container's axon TPU plugin registers itself at
interpreter start (sitecustomize) and calls
``jax.config.update("jax_platforms", "axon,cpu")``, which OVERRIDES the
``JAX_PLATFORMS`` environment variable.  Setting the env var alone silently
runs "CPU" tests on the tunnelled TPU chip; the only reliable override is a
second ``jax.config.update`` after importing jax, before any backend
initialization.
"""

import os

# Must be in the environment before the CPU client is created.
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent compile cache dir (harmless no-op on the CPU backend in
# this jax build -- it only writes for accelerator backends; the env var
# mainly reaches the capture-script smoke tests' subprocesses so a
# chip-up capture session shares warm compiles).
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 ".jax_cache"))
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_platform():
    """Guard against the axon plugin silently re-grabbing the tests."""
    assert jax.default_backend() == "cpu", (
        f"tests must run on CPU, got {jax.default_backend()}")
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {len(jax.devices())}")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)
