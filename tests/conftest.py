"""Test environment: CPU platform with 8 virtual devices.

Mesh/sharding logic is tested without a TPU via XLA's host-platform device
splitting (SURVEY.md section 5: "multi-device tests via jax CPU-device
simulation").  Must run before jax is imported anywhere.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)
