"""Test environment: CPU platform with 8 virtual devices.

Mesh/sharding logic is tested without a TPU via XLA's host-platform device
splitting (SURVEY.md section 5: "multi-device tests via jax CPU-device
simulation").

Platform forcing note: this container's axon TPU plugin registers itself at
interpreter start (sitecustomize) and calls
``jax.config.update("jax_platforms", "axon,cpu")``, which OVERRIDES the
``JAX_PLATFORMS`` environment variable.  Setting the env var alone silently
runs "CPU" tests on the tunnelled TPU chip; the only reliable override is a
second ``jax.config.update`` after importing jax, before any backend
initialization.
"""

import os

# Must be in the environment before the CPU client is created.
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent compile cache.  The ENV VAR stays the shared base dir --
# capture-script subprocesses inherit it and bench.choose_backend
# re-keys it per backend (TPU children must keep sharing the watcher's
# warm tunnel compiles).  These in-process tests are forced-CPU, and
# XLA:CPU executables are host-feature-specific (cross-host reuse is a
# SIGILL risk XLA warns about), so the IN-PROCESS jax config points at
# the bench.cpu_cache_dir() fingerprinted subdirectory instead.
import sys as _sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in _sys.path:
    _sys.path.insert(0, _REPO)
from bench import CACHE_DIR, cpu_cache_dir  # noqa: E402

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", CACHE_DIR)
os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
# Smoke benches spawned by the suite must not append their throwaway
# rows to the committed bench trajectory (empty string disables the
# bench.py history hook; scripts/bench_gate.py).
os.environ.setdefault("BENCH_HISTORY", "")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", cpu_cache_dir())

import numpy as np  # noqa: E402
import pytest  # noqa: E402


# -- fast/slow test tiers (round-3 verdict item 10) ------------------------
# `pytest -m fast` is the <2-minute iteration tier; the full suite stays
# the merge gate.  Tier membership is curated HERE, from measured
# durations (--durations=0), not guessed per-file: everything below is
# either a whole module whose shared fixture is itself expensive, or an
# individual test measured >= ~4 s single-threaded.  Re-measure when
# adding heavy tests.
_SLOW = (
    "test_boundary.py::",
    "test_socp.py::",
    "test_satellite_soc.py::",
    "test_capture_scripts.py::",
    "test_cli.py::",
    "test_distributed.py::",
    "test_export_scale.py::test_million_leaf_export_bounded_rss_and_wall",
    "test_post.py::",
    "test_sim.py::",
    "test_bench.py::test_bench_smoke_cpu_emits_json",
    "test_bench.py::test_bench_smoke_carries_host_fields",
    "test_bench.py::test_contention_monitor_sees_competing_load",
    "test_bnb.py::test_root_bounds_are_lower_bounds",
    "test_bnb.py::test_bnb_matches_enumeration",
    "test_bnb.py::test_pruning_happens",
    "test_inverted_pendulum.py::test_partition_build_certifies",
    "test_obs_schema.py::test_obs_off_overhead_under_one_percent",
    "test_ipm.py::test_random_qp_matches_scipy",
    "test_ipm.py::test_mixed_precision_matches_f64",
    "test_online.py::test_descent_hybrid_partition",
    "test_oracle.py::test_rescue_recovers_short_point_schedule",
    "test_oracle.py::test_simplex_chunking_matches_single_call",
    "test_oracle.py::test_stage2_orders_agree_on_hybrid",
    "test_oracle.py::test_solve_pairs_matches_dense_grid",
    "test_oracle.py::test_vertex_solutions_consistent",
    "test_parallel.py::test_sharded_matches_dense",
    "test_parallel.py::test_delta_padding_mesh",
    "test_parallel.py::test_oracle_mesh_backend_parity",
    "test_partition.py::test_prefetch_parity",
    "test_partition.py::test_inherited_bounds_parity_and_savings",
    "test_partition.py::test_masked_point_solves_tree_parity_and_savings",
    "test_partition.py::test_batched_stage1_matches_scalar",
    "test_partition.py::test_device_failure_falls_back_to_cpu",
    "test_partition.py::test_serial_vs_batched_region_parity",
    "test_partition.py::test_vertex_cache_shares_work_and_bounds_memory",
    "test_partition.py::test_checkpoint_resume",
    "test_lifecycle.py::test_k20_drift_walk_ledger_bounded_and_decay_monotone",
    "test_problems.py::test_prestab_condense_is_exact_substitution",
    "test_quadrotor.py::test_partition_build_coarse",
    "test_quadrotor.py::test_enumeration_matches_admm_reference",
)


def pytest_collection_modifyitems(config, items):
    for item in items:
        name = item.nodeid.rsplit("tests/", 1)[-1]
        if any(name.startswith(s) for s in _SLOW):
            item.add_marker(pytest.mark.slow)
        else:
            item.add_marker(pytest.mark.fast)


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_platform():
    """Guard against the axon plugin silently re-grabbing the tests."""
    assert jax.default_backend() == "cpu", (
        f"tests must run on CPU, got {jax.default_backend()}")
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {len(jax.devices())}")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(1234)
