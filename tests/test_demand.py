"""Demand telemetry (obs/demand.py, ISSUE 17): count-min sketch
accuracy under an adversarial key stream, decay aging, reservoir
determinism, off-mode cost, snapshot commit/torn-load semantics, the
online suboptimality sampler's health gate, the per-controller
fallback oracle budget (two-tenant starvation regression), and the
warm-rebuild priority hint (hot leaves first, final tree
bit-identical)."""

import dataclasses
import json
import os
import shutil
import time

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.config import PartitionConfig, ServeConfig
from explicit_hybrid_mpc_tpu.obs import demand as demand_mod
from explicit_hybrid_mpc_tpu.obs.demand import (CM_DEPTH, DemandHub,
                                                DemandSnapshot,
                                                ExceedHist, LeafSketch,
                                                Reservoir,
                                                SuboptSampler,
                                                hub_from_serve_config,
                                                load_demand,
                                                priority_from_snapshot,
                                                top_decile_frac)
from explicit_hybrid_mpc_tpu.obs.health import HealthMonitor
from explicit_hybrid_mpc_tpu.utils import atomic


class _Clock:
    """Injectable monotonic clock: decay/cadence under test control."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# -- LeafSketch --------------------------------------------------------------


def test_leafsketch_exact_mode_matches_truth():
    clk = _Clock()
    sk = LeafSketch(max_leaves=1024, decay_halflife_s=300.0, clock=clk)
    rng = np.random.default_rng(0)
    truth: dict[int, float] = {}
    for _ in range(20):
        batch = rng.integers(0, 100, size=64)
        sk.update(batch)
        for k in batch.tolist():
            truth[k] = truth.get(k, 0.0) + 1.0
    assert sk.mode == "exact"
    for k, v in truth.items():
        assert sk.estimate(k) == v
    ids, hits = sk.items()
    assert ids.size == len(truth)
    # hits-descending, id-ascending on ties.
    assert all(hits[i] >= hits[i + 1] for i in range(hits.size - 1))
    assert sk.total == pytest.approx(sum(truth.values()))


def test_leafsketch_countmin_adversarial_never_underestimates():
    """Spill to count-min under a heavy-tailed stream over far more
    distinct keys than max_leaves; pin the documented guarantees:
    estimates NEVER underestimate, the 2N/width overestimate bound
    holds for all but ~2^-CM_DEPTH of keys, and the true hottest key
    stays at the top of the heavy-hitter candidates."""
    clk = _Clock()
    sk = LeafSketch(max_leaves=32, decay_halflife_s=300.0, seed=3,
                    clock=clk)
    rng = np.random.default_rng(7)
    n_keys = 400
    # Zipf-ish popularity over an adversarially wide key space (keys
    # scattered across the int range so hash behavior is exercised).
    keys = rng.integers(0, 2 ** 40, size=n_keys)
    w = 1.0 / np.arange(1, n_keys + 1) ** 1.2
    w /= w.sum()
    truth: dict[int, float] = {}
    for _ in range(40):
        batch = rng.choice(keys, size=128, p=w)
        sk.update(batch)
        for k in batch.tolist():
            truth[k] = truth.get(k, 0.0) + 1.0
    assert sk.mode == "countmin"
    total = sum(truth.values())
    assert sk.total == pytest.approx(total)
    bound = 2.0 * total / sk.width
    n_over = 0
    for k, v in truth.items():
        est = sk.estimate(k)
        assert est >= v - 1e-9, f"count-min underestimated key {k}"
        if est > v + bound:
            n_over += 1
    # Markov bound per key: P(err > 2N/w) <= 2^-CM_DEPTH.  Allow 2x
    # slack over the expectation (the stream is fixed-seed, so this is
    # a deterministic regression pin, not a flaky statistical test).
    assert n_over <= 2 * len(truth) * 2.0 ** -CM_DEPTH
    # The genuinely hot head stays identifiable through the sketch.
    hottest = max(truth, key=truth.get)
    top_ids = [k for k, _h in sk.top(5)]
    assert hottest in top_ids


def test_leafsketch_decay_ages_old_traffic():
    clk = _Clock()
    sk = LeafSketch(max_leaves=64, decay_halflife_s=10.0, clock=clk)
    sk.update(np.full(100, 1))
    clk.t = 10.0  # one half-life
    sk.update(np.full(60, 2))
    assert sk.estimate(1) == pytest.approx(50.0)
    assert sk.estimate(2) == pytest.approx(60.0)
    # Recency wins: leaf 2 carried less raw traffic but leads now.
    ids, _hits = sk.items()
    assert ids[0] == 2
    # Many half-lives out, the old key is noise; totals decay too.
    clk.t = 210.0
    assert sk.estimate(1) < 1e-3
    assert sk.total == pytest.approx(110.0 * 0.5 ** 20, abs=1e-3)


def test_top_decile_frac_shapes():
    assert top_decile_frac(np.empty(0)) is None
    assert top_decile_frac(np.array([5.0])) == 1.0
    # 20 leaves, uniform: top-2 of 20 carry 10%.
    assert top_decile_frac(np.full(20, 3.0)) == pytest.approx(0.1)
    # One dominant leaf out of 10: near 1.
    hits = np.r_[1000.0, np.full(9, 1.0)]
    assert top_decile_frac(hits) > 0.99


# -- Reservoir / ExceedHist --------------------------------------------------


def test_reservoir_seeded_determinism_and_bound():
    rng = np.random.default_rng(11)
    stream = rng.uniform(-1, 1, size=(300, 3))
    r1, r2 = Reservoir(k=16, seed=5), Reservoir(k=16, seed=5)
    for lo in range(0, 300, 32):
        r1.add(stream[lo:lo + 32])
        r2.add(stream[lo:lo + 32])
    assert r1.n_seen == r2.n_seen == 300
    assert r1.sample().shape == (16, 3)
    np.testing.assert_array_equal(r1.sample(), r2.sample())
    # A different seed sees the same stream but keeps a different
    # sample (the rng IS the sampling decision).
    r3 = Reservoir(k=16, seed=6)
    r3.add(stream)
    assert not np.array_equal(r1.sample(), r3.sample())
    # Every kept row really came from the stream.
    seen = {tuple(row) for row in stream}
    assert all(tuple(row) in seen for row in r1.sample())


def test_exceed_hist_attributes_dimensions():
    h = ExceedHist(3)
    lb, ub = np.zeros(3), np.ones(3)
    th = np.array([[1.5, 0.5, 0.5],    # above dim 0
                   [2.0, 0.5, -0.2],   # above dim 0, below dim 2
                   [0.5, 0.5, 0.5]])   # inside
    h.update(th, lb, ub)
    assert h.hi.tolist() == [2, 0, 0]
    assert h.lo.tolist() == [0, 0, 1]
    assert h.hot_dims() == [0, 2]


# -- off-mode cost -----------------------------------------------------------


def test_demand_off_mode_is_noop_and_under_one_percent():
    """mode='off' must cost a single attribute test per batch: no
    state, no snapshot, and per-record time under 1% of what one
    serving micro-batch costs to evaluate (the scheduler calls record
    once per batch, so this bounds the serve-path overhead)."""
    from explicit_hybrid_mpc_tpu.online import descent, export, sharded
    from explicit_hybrid_mpc_tpu.partition.synthetic import \
        build_synthetic_tree

    tree, roots = build_synthetic_tree(p=2, depth=6, n_u=2)
    table = export.export_leaves(tree)
    dt = descent.export_descent(tree, roots, table, stage=False)
    srv = sharded.shard_descent(dt, table, n_shards=2)
    rng = np.random.default_rng(2)
    thetas = rng.uniform(0, 1, size=(32, 2))
    srv.evaluate(thetas)  # warm the compiled path
    reps = 20
    t0 = time.perf_counter()
    for _ in range(reps):
        srv.evaluate(thetas)
    batch_s = (time.perf_counter() - t0) / reps

    hub = DemandHub()  # defaults: mode='off'
    assert not hub.enabled
    leaf = np.arange(32)
    served = np.ones(32, dtype=bool)
    costs = np.zeros(32)
    n = 2000
    t0 = time.perf_counter()
    for _ in range(n):
        hub.record("c", thetas, leaf, None, served, costs)
    per_record_s = (time.perf_counter() - t0) / n
    assert per_record_s < 0.01 * batch_s, (
        f"off-mode record cost {per_record_s * 1e6:.2f}us vs batch "
        f"{batch_s * 1e6:.1f}us")
    # No state leaked, no snapshot produced.
    assert hub._ctl == {}
    assert hub.snapshot() == {}
    hub.close()


# -- hub capture + snapshot commit -------------------------------------------


def _fill_hub(hub: DemandHub, name: str = "c") -> None:
    """One deterministic capture mix: hot leaves 3/7, some fallback
    rows outside the unit box on dim 0, one in-box hole."""
    rng = np.random.default_rng(4)
    box = (np.zeros(2), np.ones(2))
    for _ in range(8):
        thetas = rng.uniform(0, 1, size=(16, 2))
        leaf = np.r_[np.full(10, 3), np.full(4, 7),
                     rng.integers(0, 50, size=2)]
        hub.record(name, thetas, leaf, None,
                   np.ones(16, dtype=bool), np.zeros(16), box=box,
                   n_leaves=64)
    bad_th = np.array([[1.7, 0.5], [2.1, 0.4], [0.5, 0.5]])
    tags = ["clamp", "clamp", "oracle"]
    hub.record(name, bad_th, np.array([-1, -1, -1]), tags,
               np.array([False, False, False]), np.zeros(3), box=box)


def test_hub_snapshot_roundtrip_and_priority_mapping(tmp_path):
    clk = _Clock()
    o = obs_lib.Obs("jsonl")
    hub = DemandHub(mode="on", max_leaves=256, reservoir_k=8,
                    snapshot_dir=str(tmp_path), obs=o, clock=clk)
    _fill_hub(hub)
    metas = hub.snapshot()
    hub.close(snapshot=False)
    meta = metas["c"]
    assert meta["schema"] == demand_mod.SNAPSHOT_SCHEMA
    assert meta["sketch"]["mode"] == "exact"
    assert meta["leaves_observed"] >= 2
    assert meta["n_leaves_hint"] == 64
    assert meta["hot"][0][0] == 3  # hottest leaf leads
    assert meta["fallback"]["outside_seen"] == 2
    assert meta["fallback"]["hole_seen"] == 1
    assert meta["fallback"]["exceed_dims"] == [0]
    # The committed artifact round-trips strict (sha-verified).
    snap = load_demand(str(tmp_path / "c"))
    assert snap.meta["npz_sha256"] == meta["npz_sha256"]
    assert snap.leaf_ids[0] == 3
    assert snap.top_decile_frac == pytest.approx(
        meta["top_decile_frac"])
    assert snap.res_outside.shape[0] == 2
    assert snap.exceed_hi[0] == 2
    # demand.snapshot event carries the render/report fields.
    evs = [r for r in o.sink.records
           if r.get("name") == "demand.snapshot"]
    assert evs and evs[-1]["controller"] == "c"
    for key in ("leaves_observed", "top_decile_frac", "hot",
                "exceed_dims", "subopt_p50", "subopt_p99",
                "subopt_samples", "subopt_offered"):
        assert key in evs[-1]
    o.close()
    # Rebuild priority hint: rows map through the artifact's
    # node_id table; rows outside it are dropped (best-effort).
    node_id = np.arange(100, 150)  # leaf row r -> tree node 100 + r
    pr = priority_from_snapshot(snap, node_id)
    assert pr[103] == pytest.approx(float(snap.leaf_hits[0]))
    assert all(100 <= n < 150 for n in pr)
    tiny = priority_from_snapshot(snap, np.arange(2))  # rows dropped
    assert set(tiny) <= {0, 1}


def test_torn_snapshot_never_loads(tmp_path):
    clk = _Clock()
    hub = DemandHub(mode="on", snapshot_dir=str(tmp_path), clock=clk)
    _fill_hub(hub)
    hub.snapshot()
    hub.close(snapshot=False)
    good = tmp_path / "c"
    assert load_demand(str(good)).leaf_ids.size  # baseline loads

    # (a) npz landed, commit marker never did: refused.
    torn_a = tmp_path / "torn_a"
    shutil.copytree(good, torn_a)
    os.remove(torn_a / "demand.json")
    with pytest.raises(atomic.CorruptArtifact, match="never committed"):
        load_demand(str(torn_a))

    # (b) npz truncated/bit-flipped under a stale committed marker.
    torn_b = tmp_path / "torn_b"
    shutil.copytree(good, torn_b)
    with open(torn_b / "demand.npz", "r+b") as f:
        f.truncate(max(8, os.path.getsize(torn_b / "demand.npz") // 2))
    with pytest.raises(atomic.CorruptArtifact, match="sha256 mismatch"):
        load_demand(str(torn_b))

    # (c) unknown schema major: refused before any array is trusted.
    torn_c = tmp_path / "torn_c"
    shutil.copytree(good, torn_c)
    with open(torn_c / "demand.json") as f:
        meta = json.load(f)
    meta["schema"] = "demand-v999"
    with open(torn_c / "demand.json", "w") as f:
        json.dump(meta, f)
    with pytest.raises(atomic.CorruptArtifact, match="unknown demand"):
        load_demand(str(torn_c))

    with pytest.raises(FileNotFoundError):
        load_demand(str(tmp_path / "never_written"))


# -- online suboptimality sampling -------------------------------------------


class _GapOracle:
    """solve_vertices stand-in: V* = 0 for every theta, so the folded
    suboptimality equals the served cost exactly."""

    def __init__(self):
        self.n_calls = 0

    def solve_vertices(self, thetas):
        from types import SimpleNamespace

        self.n_calls += thetas.shape[0]
        K = thetas.shape[0]
        return SimpleNamespace(Vstar=np.zeros(K),
                               dstar=np.zeros(K, dtype=np.int64))


def test_subopt_sampler_stride_and_budget():
    s = SuboptSampler(frac=0.25, max_pending=4)
    thetas = np.arange(16, dtype=np.float64).reshape(8, 2)
    s.offer(thetas, np.arange(8.0), np.ones(8, dtype=bool))
    assert s.n_offered == 2  # stride 4 over 8 served rows
    s.offer(thetas, np.arange(8.0), np.ones(8, dtype=bool))
    s.offer(thetas, np.arange(8.0), np.ones(8, dtype=bool))
    # 6 offered total, pending capped at 4: overflow counted, never
    # queued (the budget is the contract).
    assert s.n_offered == 6
    assert s.n_dropped == 2
    th, v = s.take_pending()
    assert th.shape == (4, 2) and v.shape == (4,)
    assert len(s._pending_theta) == 0


def test_hub_subopt_gauges_and_health_gate():
    """The full online-subopt loop: deterministic stride sample ->
    host-oracle re-solve -> p50/p99 gauges -> volume-gated
    health.subopt event, both from the hub itself and from the
    external max_subopt HealthMonitor rule over the same gauges."""
    clk = _Clock()
    o = obs_lib.Obs("jsonl")
    oracle = _GapOracle()
    hub = DemandHub(mode="on", subopt_frac=1.0, subopt_eps=0.01,
                    oracle=oracle, obs=o, clock=clk)
    thetas = np.random.default_rng(5).uniform(0, 1, size=(16, 2))
    costs = np.full(16, 0.05)  # every served answer 0.05 suboptimal

    # Below the volume gate: no alarm yet, gauges already live.
    hub.record("c", thetas, np.zeros(16), None,
               np.ones(16, dtype=bool), costs)
    hub.drain_for_test()
    g = o.metrics.snapshot()["gauges"]
    assert g["serve.ctl.c.subopt_p50"] == pytest.approx(0.05)
    assert g["serve.ctl.c.subopt_p99"] == pytest.approx(0.05)
    assert not [r for r in o.sink.records
                if r.get("name") == "health.subopt"]

    # Over the gate (>= SUBOPT_MIN_SAMPLES): exactly one warn event
    # (the refire cooldown holds under a frozen clock).
    hub.record("c", thetas, np.zeros(16), None,
               np.ones(16, dtype=bool), costs)
    hub.drain_for_test()
    hub.drain_for_test()
    evs = [r for r in o.sink.records if r.get("name") == "health.subopt"]
    assert len(evs) == 1
    assert evs[0]["severity"] == "warn"
    assert evs[0]["controller"] == "c"
    assert evs[0]["value"] == pytest.approx(0.05)
    assert oracle.n_calls == 32
    assert hub.subopt_p99("c") == pytest.approx(0.05)

    # External tailer's view: the max_subopt metrics rule re-derives
    # the same verdict from the gauges (volume-gated on its own
    # subopt_samples counter).
    mon = HealthMonitor({"max_subopt": 0.01})
    fired = mon.feed(o.flush_metrics())
    assert [e["name"] for e in fired] == ["health.subopt"]
    hub.close(snapshot=False)
    o.close()


def test_hub_subopt_clamps_knife_edge_negative_gaps():
    """Served cost an ulp BELOW V* (interpolation knife edge) must
    fold as 0, not negative: the SLO is an upper bound."""
    from types import SimpleNamespace

    class _HighOracle:
        def solve_vertices(self, thetas):
            K = thetas.shape[0]
            return SimpleNamespace(Vstar=np.full(K, 1.0),
                                   dstar=np.zeros(K, dtype=np.int64))

    clk = _Clock()
    hub = DemandHub(mode="on", subopt_frac=1.0, oracle=_HighOracle(),
                    clock=clk)
    thetas = np.zeros((8, 2))
    hub.record("c", thetas, np.zeros(8), None, np.ones(8, dtype=bool),
               np.full(8, 1.0 - 1e-12))
    hub.drain_for_test()
    p50, p99 = hub._ctl["c"].subopt.quantiles()
    assert p50 == 0.0 and p99 == 0.0
    hub.close(snapshot=False)


def test_hub_from_serve_config():
    assert hub_from_serve_config(ServeConfig()) is None
    cfg = ServeConfig(demand="on", demand_max_leaves=77,
                      demand_decay_s=12.5, demand_reservoir=9,
                      demand_subopt_frac=0.25, demand_subopt_eps=0.3)
    hub = hub_from_serve_config(cfg)
    assert hub is not None and hub.enabled
    assert hub.max_leaves == 77
    assert hub.decay_halflife_s == 12.5
    assert hub.reservoir_k == 9
    assert hub.subopt_frac == 0.25
    assert hub.subopt_eps == 0.3
    hub.close()


# -- per-controller fallback oracle budget (two-tenant regression) -----------


def test_fallback_oracle_budget_scoped_per_controller():
    """Regression: the oracle re-solve budget is earned per controller
    NAME.  A hole-heavy tenant must not spend the allowance another
    tenant's (mostly-certified) volume earned -- under the old
    instance-global counters, tenant A below drains the shared pool
    and B's occasional holes go unserved."""
    from explicit_hybrid_mpc_tpu.online import descent, export, sharded
    from explicit_hybrid_mpc_tpu.partition import geometry
    from explicit_hybrid_mpc_tpu.partition.tree import LeafData, Tree
    from explicit_hybrid_mpc_tpu.serve import FallbackPolicy

    t = Tree(p=1, n_u=1)
    r = t.add_root(np.array([[0.0], [1.0]]))
    left, right, i, j, _ = geometry.bisect(t.vertices[r])
    li, _ri = t.split(r, left, right, (i, j))
    t.set_leaf(li, LeafData(delta_idx=0, vertex_inputs=np.ones((2, 1)),
                            vertex_costs=np.zeros(2)))
    table = export.export_leaves(t)
    dt = descent.export_descent(t, [r], table, stage=False)
    srv = sharded.shard_descent(dt, table, n_shards=2, granularity=1)

    class _Oracle(_GapOracle):
        def solve_vertices(self, thetas):
            from types import SimpleNamespace

            self.n_calls += thetas.shape[0]
            K = thetas.shape[0]
            return SimpleNamespace(dstar=np.zeros(K, dtype=np.int64),
                                   u0=np.ones((K, 1, 1)),
                                   Vstar=thetas.sum(axis=1))

    fb = FallbackPolicy(np.zeros(1), np.ones(1), oracle=_Oracle(),
                        max_oracle_frac=0.1)
    rng = np.random.default_rng(6)

    # Tenant B first: 100 certified (in-box, payload-carrying) rows.
    # B's volume earns B -- and only B -- oracle allowance.
    th_b = rng.uniform(0.01, 0.49, size=(100, 1))
    _res, tags = fb.apply(th_b, srv.evaluate(th_b), srv,
                          controller="B")
    assert tags == [None] * 100

    # Tenant A: a pure hole storm.  Its OWN 20 requests earn 2 oracle
    # re-solves; the rest degrade to unserved.  (Globally-scoped, A
    # would have claimed 0.1 * 120 = 12 here.)
    th_a = rng.uniform(0.51, 0.99, size=(20, 1))
    _res, tags_a = fb.apply(th_a, srv.evaluate(th_a), srv,
                            controller="A")
    assert tags_a.count("oracle") == 2
    assert tags_a.count("unserved") == 18
    assert fb.oracle_spent("A") == 2

    # B comes back with 10 rows, half of them holes: B's accumulated
    # 110-request volume covers all 5 -- A's storm starved nothing.
    th_b2 = np.r_[rng.uniform(0.01, 0.49, size=(5, 1)),
                  rng.uniform(0.51, 0.99, size=(5, 1))]
    _res, tags_b2 = fb.apply(th_b2, srv.evaluate(th_b2), srv,
                             controller="B")
    assert tags_b2[:5] == [None] * 5
    assert tags_b2[5:] == ["oracle"] * 5
    assert fb.oracle_spent("B") == 5
    # Summary totals still aggregate across controllers.
    assert fb.n_seen == 130
    assert fb.n_oracle == 7


# -- warm_rebuild priority hint ----------------------------------------------


@pytest.fixture(scope="module")
def depth_capped_prior():
    """A depth-capped build whose best-effort leaves warm_rebuild
    conservatively invalidates: they re-enter the frontier but CANNOT
    split (the cap holds), pinning the no-split reorder case the
    priority-hint contract promises bit-identity for."""
    from explicit_hybrid_mpc_tpu.partition.frontier import \
        build_partition
    from explicit_hybrid_mpc_tpu.problems.registry import make

    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=0.3,
                          backend="cpu", batch_simplices=128,
                          max_depth=6)
    return prob, cfg, build_partition(prob, cfg)


def test_warm_rebuild_priority_hot_first_and_bit_identical(
        depth_capped_prior):
    from explicit_hybrid_mpc_tpu.online import export
    from explicit_hybrid_mpc_tpu.partition.rebuild import warm_rebuild

    prob, cfg, prior = depth_capped_prior
    ra = warm_rebuild(prob, cfg, prior)
    assert ra.stats["rebuild_leaves_invalidated"] > 0
    assert ra.stats["rebuild_priority_hint"] == 0
    order_a = ra.stats["rebuild_priority_order"]
    assert order_a == sorted(order_a)  # default: node order

    # Hint two of the invalidated nodes hot (weights descending).
    hot = [order_a[-1], order_a[3]]
    rb = warm_rebuild(prob, cfg, prior,
                      priority={hot[0]: 100.0, hot[1]: 40.0})
    assert rb.stats["rebuild_priority_hint"] == 2
    order_b = rb.stats["rebuild_priority_order"]
    # Hot leaves enter the frontier first, weight-descending; the
    # unhinted rest follow in node order (weight-0 ties).
    assert order_b[:2] == hot
    rest = [n for n in order_a if n not in hot]
    assert order_b[2:] == rest[:len(order_b) - 2]

    # The hint is an ORDERING only: same leaves, no splits, and the
    # final tree is identical node for node -- structure arrays
    # bitwise, payload content per node (slot numbering is processing
    # order, so compare through the indirection), ledger as a fact
    # set, and the exported serving artifact bitwise.
    assert len(ra.tree) == len(rb.tree) == len(prior.tree)
    assert (rb.stats["rebuild_leaves_invalidated"]
            == ra.stats["rebuild_leaves_invalidated"])
    sa, sb = ra.tree.__getstate__(), rb.tree.__getstate__()
    for key in ("children", "parent", "depth", "leaf_flags", "normal",
                "offset", "split_edge", "n", "n_regions"):
        va, vb = sa[key], sb[key]
        assert np.array_equal(va, vb), f"tree field {key} diverged"
    assert (set(map(tuple, sa["excl_events"] or []))
            == set(map(tuple, sb["excl_events"] or [])))
    ta = export.export_leaves(ra.tree)
    tb = export.export_leaves(rb.tree)
    names = ([f.name for f in dataclasses.fields(ta)]
             if dataclasses.is_dataclass(ta) else list(ta._fields))
    for name in names:
        va, vb = getattr(ta, name), getattr(tb, name)
        same = (np.array_equal(va, vb)
                if isinstance(va, np.ndarray) else va == vb)
        assert same, f"exported leaf table field {name} diverged"
