"""Serve request tracing (obs/reqtrace.py, ISSUE 19): the per-ticket
stamp-vector fold (phase histograms summing to request wall BY
CONSTRUCTION, for both schedulers), the lock-free slowest-K exemplar
ring under concurrent submitters across a hot swap, the off-mode
byte-for-byte no-op + on-mode overhead A/B, the rolling-window max-age
cut behind the p99 gauge (a stale window must stop firing
``serve_p99_us``), the volume-gated ``max_queue_frac`` ->
``health.serve_queue`` rule, and the host-interference forensics
(``GcPauseRecorder`` gc-pause capture, ``note_stall`` rate limiting).
"""

import gc
import threading
import time

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.config import ServeConfig
from explicit_hybrid_mpc_tpu.obs import reqtrace
from explicit_hybrid_mpc_tpu.obs.health import HealthMonitor
from explicit_hybrid_mpc_tpu.obs.reqtrace import (GcPauseRecorder, ReqTrace,
                                                  _Ring,
                                                  trace_from_serve_config)
from explicit_hybrid_mpc_tpu.online import descent, export, sharded
from explicit_hybrid_mpc_tpu.partition.synthetic import build_synthetic_tree
from explicit_hybrid_mpc_tpu.serve import (ArenaScheduler, ControllerRegistry,
                                           DeviceArena, FallbackPolicy,
                                           RequestScheduler)


def _server(obs=None, scale=1.0, depth=6):
    tree, roots = build_synthetic_tree(p=2, depth=depth, n_u=2)
    if scale != 1.0:
        tree._pl_inputs[:] *= scale
        tree._pl_costs[:] *= scale
    table = export.export_leaves(tree)
    dt = descent.export_descent(tree, roots, table, stage=False)
    return sharded.shard_descent(dt, table, n_shards=2, obs=obs)


def _synthetic_table(rng, L=24, p=2, n_u=2):
    """Disjoint unit-grid simplices (test_arena idiom)."""
    from explicit_hybrid_mpc_tpu.partition import geometry

    base = np.vstack([np.zeros(p), np.eye(p)])
    side = int(np.ceil(np.sqrt(L)))
    bary, U, V = [], [], []
    for i in range(L):
        off = np.array([i % side, i // side], dtype=float)[:p]
        verts = 0.8 * base + off + 0.1 * rng.uniform(size=p)
        bary.append(geometry.barycentric_matrix(verts))
        U.append(rng.normal(size=(p + 1, n_u)))
        V.append(np.abs(rng.normal(size=p + 1)))
    return export.LeafTable(
        bary_M=np.stack(bary), U=np.stack(U), V=np.stack(V),
        delta=np.zeros(L, dtype=np.int64),
        node_id=np.arange(L, dtype=np.int64))


_BOX = (np.zeros(2), np.full(2, 8.0))

_STAMP_ORDER = ("enqueue", "seal", "lease", "put", "launch_return",
                "fallback_end", "reply")


def _phase_hists(o, ctl):
    pre = f"serve.ctl.{ctl}.phase."
    return {k[len(pre):-3]: h
            for k, h in o.metrics.snapshot()["histograms"].items()
            if k.startswith(pre)}


def _assert_phases_sum_to_wall(ph, n_expected):
    assert set(reqtrace.PHASES) | {"wall"} == set(ph)
    n = ph["wall"]["count"]
    assert n == n_expected
    wall_mean = ph["wall"]["sum"] / n
    phase_sum = sum(ph[p]["sum"] / ph[p]["count"] for p in reqtrace.PHASES)
    # Arithmetic identity (reply is computed as the remainder), so the
    # tolerance covers float summation order only -- not sampling.
    assert abs(phase_sum - wall_mean) <= 1e-6 * wall_mean
    assert all(ph[p]["count"] == n for p in reqtrace.PHASES)


# -- phase-sum == wall invariant, both schedulers ---------------------------


def test_request_scheduler_phase_sum_equals_wall(rng):
    o = obs_lib.Obs("jsonl")
    srv = _server(obs=o)
    reg = ControllerRegistry(obs=o)
    reg.publish("c", "v1", srv)
    tr = ReqTrace(mode="on", obs=o)
    with RequestScheduler(reg, "c", max_batch=16, max_wait_us=1000.0,
                          obs=o, trace=tr) as sched:
        tickets = [sched.submit(th)
                   for th in rng.uniform(0, 1, size=(120, 2))]
        for t in tickets:
            assert t.result(30.0)[0].ok
    _assert_phases_sum_to_wall(_phase_hists(o, "c"), 120)
    # queue_frac gauge minted and sane.
    qf = o.metrics.snapshot()["gauges"]["serve.ctl.c.queue_frac"]
    assert 0.0 <= qf <= 1.0
    assert tr.queue_frac("c") == pytest.approx(qf)


def test_arena_scheduler_phase_sum_equals_wall(rng):
    o = obs_lib.Obs("jsonl")
    arena = DeviceArena(p=2, n_u=2, capacity_cols=256, obs=o)
    arena.publish("a", "v1", _synthetic_table(rng), *_BOX)
    arena.publish("b", "v1", _synthetic_table(rng), *_BOX)
    fb = FallbackPolicy(*_BOX, obs=o)
    tr = ReqTrace(mode="on", obs=o)
    with ArenaScheduler(arena, max_batch=16, max_wait_us=2000.0,
                        fallback=fb, obs=o, trace=tr) as sched:
        names = ["a", "b"]
        tickets = [sched.submit(names[i % 2], th) for i, th
                   in enumerate(rng.uniform(0, 8, size=(60, 2)))]
        for t in tickets:
            t.result(30.0)
    for ctl in ("a", "b"):
        _assert_phases_sum_to_wall(_phase_hists(o, ctl), 30)


# -- exemplar ring ----------------------------------------------------------


def test_exemplar_ring_race_across_hot_swap(rng):
    """Six concurrent submitters racing a registry hot swap: every
    exemplar must bind to a COMMITTED version (v1 or v2, never a torn
    or in-flight label), carry a monotone stamp vector, and the ring
    must stay bounded at K."""
    o = obs_lib.Obs("jsonl")
    reg = ControllerRegistry(obs=o)
    reg.publish("c", "v1", _server(obs=o))
    tr = ReqTrace(mode="on", exemplar_k=8, obs=o)
    stop = threading.Event()
    errors: list = []

    with RequestScheduler(reg, "c", max_batch=16, max_wait_us=500.0,
                          obs=o, trace=tr) as sched:

        def submitter(seed):
            r = np.random.default_rng(seed)
            try:
                while not stop.is_set():
                    sched.submit(r.uniform(0, 1, 2)).result(30.0)
            except Exception as e:  # pragma: no cover - fail loud
                errors.append(repr(e))

        threads = [threading.Thread(target=submitter, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.25)
        reg.publish("c", "v2", _server(obs=o, scale=2.0))
        time.sleep(0.25)
        stop.set()
        for t in threads:
            t.join()
        ex = tr.exemplars("c")

    assert not errors
    assert 1 <= len(ex) <= 8
    # Slowest-first ordering.
    walls = [e["wall_us"] for e in ex]
    assert walls == sorted(walls, reverse=True)
    for e in ex:
        assert e["version"] in ("v1", "v2")
        st = e["stamps_us"]
        vals = [st[k] for k in _STAMP_ORDER]
        assert all(b >= a - 1e-6 for a, b in zip(vals, vals[1:]))
        assert st["reply"] == pytest.approx(e["wall_us"], abs=1e-3)
        assert e["rows"] >= 1 and 0.0 < e["batch_fill"] <= 1.0


def test_ring_keeps_k_slowest_within_window():
    ring = _Ring(k=3, window_s=10.0)
    for i, w in enumerate([5.0, 1.0, 9.0, 3.0, 7.0]):
        ring.offer(float(i), w, {"wall_us": w})
    assert [e["wall_us"] for e in ring.snapshot()] == [9.0, 7.0, 5.0]
    # Entries older than the window are evicted on the next offer.
    ring.offer(100.0, 0.5, {"wall_us": 0.5})
    assert [e["wall_us"] for e in ring.snapshot()] == [0.5]


def test_flush_emits_exemplar_digest_events():
    o = obs_lib.Obs("jsonl")
    tr = ReqTrace(mode="on", obs=o)
    base = time.perf_counter_ns()
    tr.fold("c", seal=base + 2_000, lease=base + 3_000,
            eval0=base + 4_000, eval1=base + 6_000, fb_end=base + 6_500,
            done=base + 9_000, rows=[((base, base + 500), 2, None)],
            fill=0.5, version="v1", extent=64)
    tr.flush()
    evs = [r for r in o.sink.records
           if r.get("name") == "serve.trace.exemplars"]
    assert len(evs) == 1
    assert evs[0]["controller"] == "c" and evs[0]["n"] == 1
    assert evs[0]["slowest"][0]["version"] == "v1"


def test_fold_drops_batch_sealed_before_attach():
    """A batch collected while tracing was detached has no seal stamp
    (the serve_bench A/B flips the hub live); fold must drop it rather
    than emit a garbage decomposition."""
    o = obs_lib.Obs("jsonl")
    tr = ReqTrace(mode="on", obs=o)
    base = time.perf_counter_ns()
    tr.fold("c", seal=0, lease=base, eval0=base, eval1=base,
            fb_end=base, done=base, rows=[((base, base), 1, None)],
            fill=1.0)
    assert not _phase_hists(o, "c")
    assert tr.queue_frac("c") is None


# -- off mode ---------------------------------------------------------------


def test_off_mode_is_byte_for_byte_noop(rng):
    """mode='off' (and a missing hub) must leave the serve path with
    zero trace work: the scheduler drops the hub at construction, no
    ticket carries stamps, and no phase metric is ever minted."""
    o = obs_lib.Obs("jsonl")
    reg = ControllerRegistry(obs=o)
    reg.publish("c", "v1", _server(obs=o))
    with RequestScheduler(reg, "c", max_batch=8, max_wait_us=500.0,
                          obs=o, trace=ReqTrace(mode="off")) as sched:
        assert sched.trace is None  # dropped at construction
        tickets = [sched.submit(th)
                   for th in rng.uniform(0, 1, size=(20, 2))]
        for t in tickets:
            assert t.result(30.0)[0].ok
        assert all(t.t_ns is None for t in tickets)
    snap = o.metrics.snapshot()
    assert not any(".phase." in k for k in snap["histograms"])
    assert not any(k.endswith(".queue_frac") for k in snap["gauges"])


def test_trace_overhead_ab(rng):
    """Interleaved off/on windows through one live scheduler; min-p99
    per arm (minimum is the noise-robust statistic for a lower-bounded
    latency).  This is the CI backstop at a loose bound -- the strict
    <=1% gate runs in scripts/serve_bench.py over seconds-long windows
    (main() exits nonzero past trace_overhead_frac 0.01), where the
    arms are long enough for 1% to clear scheduler jitter."""
    o = obs_lib.Obs("jsonl")
    reg = ControllerRegistry(obs=o)
    reg.publish("c", "v1", _server(obs=o))
    tr = ReqTrace(mode="on", obs=o)
    thetas = rng.uniform(0, 1, size=(50, 2))

    with RequestScheduler(reg, "c", max_batch=16, max_wait_us=1000.0,
                          obs=o, trace=tr) as sched:

        def window():
            # Open-loop pacing below capacity, like serve_bench: the
            # worker folds while idle between arrivals, so the A/B
            # measures steady-state overhead, not burst serialization.
            tks = []
            for th in thetas:
                tks.append(sched.submit(th))
                time.sleep(0.0015)
            return [t.result(30.0)[0].latency_s for t in tks]

        window()  # warm both code paths (bucket compiles)
        p99_off, p99_on = [], []
        # GC off for the measured windows: late in the suite a gen2
        # pass costs more than a whole 75 ms window, and the on arm's
        # extra allocations draw it in preferentially -- that is GC
        # accounting, not trace overhead.  The GC-inclusive gate is
        # serve_bench's gc_pause_frac.
        gc.collect()
        gc.disable()
        try:
            for _ in range(5):
                sched.trace = None
                p99_off.append(
                    np.percentile(np.asarray(window()) * 1e6, 99))
                sched.trace = tr
                p99_on.append(
                    np.percentile(np.asarray(window()) * 1e6, 99))
        finally:
            gc.enable()

    # Per-arm p99 FLOORS across the interleaved pairs (the serve_bench
    # trace_overhead methodology): a GC pass or scheduler hiccup lands
    # in one window's tail but cannot poison the min, where a pooled
    # per-arm p99 inherits the single worst window.
    p_off = float(min(p99_off))
    p_on = float(min(p99_on))
    overhead = (p_on - p_off) / p_off
    assert overhead <= 0.15


# -- rolling-window max-age cut (satellite: stale p99) ----------------------


def test_prune_stale_unit():
    from explicit_hybrid_mpc_tpu.serve import scheduler as sched_mod
    from collections import deque

    now = 1000.0
    old = now - sched_mod._ROLL_MAX_AGE_S - 1.0
    lat = deque([(old, 9.9), (old, 9.9), (now - 1.0, 0.001)])
    fb = deque([(old, 1), (now - 1.0, 0)])
    sched_mod._prune_stale(lat, fb, now)
    assert list(lat) == [(now - 1.0, 0.001)]
    assert list(fb) == [(now - 1.0, 0)]


def test_stale_window_stops_firing_serve_p99(rng):
    """Latency samples older than the max-age cut must fall out of the
    rolling p99 gauge: a burst of old slow requests cannot keep firing
    ``health.serve_p99_us`` forever, while the SAME samples with fresh
    timestamps must fire it (the rule still works)."""
    o = obs_lib.Obs("jsonl")
    reg = ControllerRegistry(obs=o)
    reg.publish("c", "v1", _server(obs=o))
    rules = {"serve_p99_us": 1e6, "min_solves_for_rates": 1.0}
    with RequestScheduler(reg, "c", max_batch=16, max_wait_us=500.0,
                          obs=o) as sched:
        # A stale burst of 5 s latencies, older than _ROLL_MAX_AGE_S.
        sched._lat_roll.extend([(time.perf_counter() - 120.0, 5.0)] * 200)
        for t in [sched.submit(th)
                  for th in rng.uniform(0, 1, size=(30, 2))]:
            t.result(30.0)
        p99 = o.metrics.snapshot()["gauges"]["serve.ctl.c.p99_us"]
        assert p99 < 1e6  # the 5e6 us stale burst was pruned
        snap = o.metrics.snapshot()
        mon = HealthMonitor(rules=rules)
        mon.feed({"kind": "metrics", "counters": snap["counters"],
                  "gauges": snap["gauges"]})
        assert not any(e["name"] == "health.serve_p99_us"
                       for e in mon.events)

        # Control: the same burst with FRESH timestamps dominates the
        # window and the rule fires.
        sched._lat_roll.extend([(time.perf_counter(), 5.0)] * 200)
        for t in [sched.submit(th)
                  for th in rng.uniform(0, 1, size=(30, 2))]:
            t.result(30.0)
        assert o.metrics.snapshot()["gauges"]["serve.ctl.c.p99_us"] > 1e6
        snap = o.metrics.snapshot()
        mon = HealthMonitor(rules=rules)
        mon.feed({"kind": "metrics", "counters": snap["counters"],
                  "gauges": snap["gauges"]})
        assert any(e["name"] == "health.serve_p99_us"
                   for e in mon.events)


# -- queue-dominated health rule --------------------------------------------


def test_max_queue_frac_rule_fires_volume_gated():
    gauges = {"serve.ctl.c.queue_frac": 0.62}
    # Below the volume gate: silent.
    mon = HealthMonitor(rules={"max_queue_frac": 0.5})
    mon.feed({"kind": "metrics",
              "counters": {"serve.ctl.c.requests": 10.0},
              "gauges": gauges})
    assert not mon.events
    # Past the gate: warn, keyed per controller, nonzero exit.
    mon.feed({"kind": "metrics",
              "counters": {"serve.ctl.c.requests": 5000.0},
              "gauges": gauges})
    evs = [e for e in mon.events if e["name"] == "health.serve_queue"]
    assert len(evs) == 1
    assert evs[0]["severity"] == "warn"
    assert "queue" in evs[0]["msg"]
    assert mon.exit_code != 0
    # Default rules keep the rule off (opt-in like serve_p99_us).
    mon2 = HealthMonitor()
    mon2.feed({"kind": "metrics",
               "counters": {"serve.ctl.c.requests": 5000.0},
               "gauges": gauges})
    assert not mon2.events


def test_queue_frac_rule_end_to_end(rng):
    """A long batching window on single-row submits is queue-dominated
    by construction; the gauge the scheduler publishes must trip the
    rule through a real metrics snapshot."""
    o = obs_lib.Obs("jsonl")
    reg = ControllerRegistry(obs=o)
    reg.publish("c", "v1", _server(obs=o))
    # Short trace window so the warmup round (whose wall is dominated
    # by the first-batch JIT compile, ~100x a warm eval) ages OUT of
    # the queue_frac roll before the measured round.
    tr = ReqTrace(mode="on", window_s=0.5, obs=o)
    with RequestScheduler(reg, "c", max_batch=64, max_wait_us=20000.0,
                          obs=o, trace=tr) as sched:
        for t in [sched.submit(th)
                  for th in rng.uniform(0, 1, size=(40, 2))]:
            t.result(30.0)  # warmup: compiles the bucket
        time.sleep(0.6)
        for t in [sched.submit(th)
                  for th in rng.uniform(0, 1, size=(40, 2))]:
            t.result(30.0)
    snap = o.metrics.snapshot()
    mon = HealthMonitor(rules={"max_queue_frac": 0.2,
                               "min_solves_for_rates": 1.0})
    mon.feed({"kind": "metrics", "counters": snap["counters"],
              "gauges": snap["gauges"]})
    assert any(e["name"] == "health.serve_queue" for e in mon.events)


# -- host forensics ---------------------------------------------------------


def test_gc_pause_recorder_captures_forced_collect():
    o = obs_lib.Obs("jsonl")
    with GcPauseRecorder(obs=o) as rec:
        junk = []
        for _ in range(1000):
            a, b = [], []
            a.append(b)
            b.append(a)
            junk.append(a)
        del junk
        gc.collect()
    assert rec.pauses and all(p > 0 for p in rec.pauses)
    assert rec.total_pause_s() == pytest.approx(sum(rec.pauses) / 1e6)
    evs = [r for r in o.sink.records
           if r.get("name") == "serve.host.gc_pause_us"]
    assert evs and evs[-1]["pause_us"] > 0
    h = o.metrics.snapshot()["histograms"]["serve.host.gc_pause_us"]
    assert h["count"] == len(rec.pauses)
    # Stop is idempotent and the hook is really gone.
    rec.stop()
    n = len(rec.pauses)
    gc.collect()
    assert len(rec.pauses) == n


def test_note_stall_histogram_always_event_rate_limited():
    class _Clock:
        t = 0.0

        def __call__(self):
            return self.t

    clk = _Clock()
    o = obs_lib.Obs("jsonl")
    tr = ReqTrace(mode="on", obs=o, clock=clk)

    def stall_events():
        return [r for r in o.sink.records
                if r.get("name") == "serve.host.stall_us"]

    tr.note_stall(500_000)  # 500 us: below the event floor
    assert not stall_events()
    tr.note_stall(2_000_000)  # 2 ms: evented
    assert len(stall_events()) == 1
    tr.note_stall(3_000_000)  # same second: rate-limited
    assert len(stall_events()) == 1
    clk.t += 1.5
    tr.note_stall(3_000_000)
    assert len(stall_events()) == 2
    h = o.metrics.snapshot()["histograms"]["serve.host.stall_us"]
    assert h["count"] == 4  # the histogram always observes


# -- config plumbing --------------------------------------------------------


def test_trace_from_serve_config():
    assert trace_from_serve_config(ServeConfig()) is None
    tr = trace_from_serve_config(
        ServeConfig(tracing="on", trace_exemplar_k=4, trace_window_s=5.0))
    assert tr is not None and tr.enabled
    assert tr.exemplar_k == 4 and tr.window_s == 5.0

    class _Legacy:  # config pickled before the knobs existed
        pass

    assert trace_from_serve_config(_Legacy()) is None


def test_trace_validation():
    with pytest.raises(ValueError, match="tracing mode"):
        ReqTrace(mode="sometimes")
    with pytest.raises(ValueError, match="exemplar_k"):
        ReqTrace(mode="on", exemplar_k=0)
    with pytest.raises(ValueError, match="window_s"):
        ReqTrace(mode="on", window_s=0.0)
    with pytest.raises(ValueError, match="tracing mode"):
        ServeConfig(tracing="verbose")
    with pytest.raises(ValueError, match="trace_exemplar_k"):
        ServeConfig(tracing="on", trace_exemplar_k=0)
    with pytest.raises(ValueError, match="trace_window_s"):
        ServeConfig(tracing="on", trace_window_s=-1.0)
