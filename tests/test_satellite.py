"""Satellite desaturation benchmark: canonicalization, min-impulse hybrid
structure, physics invariants, oracle-vs-scipy, and a 1-axis partition."""

import numpy as np
import pytest
from scipy.optimize import minimize

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def sat1():
    return make("satellite", axes=1, N=3)


@pytest.fixture(scope="module")
def oracle1(sat1):
    return Oracle(sat1, backend="cpu")


def _scipy_fixed_delta(can, d, theta):
    H, f, F = can.H[d], can.f[d], can.F[d]
    G, w, S = can.G[d], can.w[d], can.S[d]
    q = f + F @ theta
    b = w + S @ theta
    res = minimize(
        lambda z: 0.5 * z @ H @ z + q @ z, np.zeros(can.nz),
        jac=lambda z: H @ z + q, method="SLSQP",
        constraints=[{"type": "ineq", "fun": lambda z: b - G @ z,
                      "jac": lambda z: -G}],
        options={"maxiter": 400, "ftol": 1e-12})
    if not res.success:
        return None
    return (res.fun + 0.5 * theta @ can.Y[d] @ theta
            + can.pvec[d] @ theta + can.cconst[d])


def test_canonical_shapes():
    sat = make("satellite", N=2)
    can = sat.canonical
    assert can.n_delta == 27
    assert can.deltas.shape == (27, 3)
    assert can.nz == 2 * 6        # N * (3 wheels + 3 magnitudes)
    assert sat.n_theta == 6


def test_off_thrusters_park_at_zero(oracle1, sat1):
    """All-off commutation: magnitude channel must sit at exactly 0 and the
    applied thruster torque must vanish (u_selector zeroes the channel)."""
    can = sat1.canonical
    d_off = int(np.where((can.deltas == 0).all(axis=1))[0][0])
    sol = oracle1.solve_vertices(np.array([[0.05, 0.3]]))
    z = sol.z[0, d_off]
    mags = z.reshape(sat1.N, 2)[:, 1]      # magnitude channel per step
    assert np.all(np.abs(mags) < 1e-6)
    u0 = sol.u0[0, d_off]
    assert abs(u0[1]) < 1e-6               # applied thruster torque


def test_min_impulse_bound_enforced(oracle1, sat1):
    """Firing commutations must apply at least u_min of torque at every
    step -- the defining min-impulse constraint."""
    can = sat1.canonical
    d_pos = int(np.where((can.deltas == 1).all(axis=1))[0][0])
    sol = oracle1.solve_vertices(np.array([[0.0, -1.0]]))
    assert sol.conv[0, d_pos]
    mags = sol.z[0, d_pos].reshape(sat1.N, 2)[:, 1]
    assert np.all(mags >= sat1.u_min - 1e-7)


def test_desaturation_needs_thrusters(oracle1, sat1):
    """Wheels conserve total momentum J*omega + h: with wheels only (all
    thrusters off) the optimal cost at large |h| must exceed a firing
    commutation's -- the physics that makes the problem hybrid."""
    can = sat1.canonical
    d_off = int(np.where((can.deltas == 0).all(axis=1))[0][0])
    sol = oracle1.solve_vertices(np.array([[0.0, 1.1]]))
    # Saturated wheels: best commutation fires the thruster (negative
    # torque to dump positive momentum).
    assert sol.dstar[0] != d_off
    assert can.deltas[sol.dstar[0], 0] == -1
    # Near the origin the min-impulse cost is not worth it: stay off.
    sol0 = oracle1.solve_vertices(np.array([[0.0, 0.02]]))
    assert sol0.dstar[0] == d_off


def test_enumeration_matches_scipy(oracle1, sat1, rng):
    can = sat1.canonical
    thetas = rng.uniform(sat1.theta_lb, sat1.theta_ub, size=(3, 2))
    sol = oracle1.solve_vertices(thetas)
    for k, th in enumerate(thetas):
        vals = [_scipy_fixed_delta(can, d, th) for d in range(can.n_delta)]
        vals = [v for v in vals if v is not None]
        assert vals
        np.testing.assert_allclose(sol.Vstar[k], min(vals),
                                   rtol=1e-5, atol=1e-7)


def test_full_3axis_oracle_point(rng):
    """27-commutation 6-state grid solve at a few points: finite optimum,
    correct argmin structure (spot-check against scipy on the argmin)."""
    sat = make("satellite", N=2)
    o = Oracle(sat, backend="cpu")
    thetas = rng.uniform(sat.theta_lb, sat.theta_ub, size=(2, 6))
    sol = o.solve_vertices(thetas)
    assert np.all(np.isfinite(sol.Vstar))
    can = sat.canonical
    for k in range(2):
        d = int(sol.dstar[k])
        ref = _scipy_fixed_delta(can, d, thetas[k])
        assert ref is not None
        np.testing.assert_allclose(sol.V[k, d], ref, rtol=1e-5, atol=1e-6)


def test_partition_build_1axis(sat1):
    cfg = PartitionConfig(problem="satellite", eps_a=2.0, backend="cpu",
                          batch_simplices=64, max_steps=600)
    res = build_partition(sat1, cfg)
    assert res.stats["regions"] > 0
    assert not res.stats["truncated"]
    assert res.stats["uncertified"] == 0
