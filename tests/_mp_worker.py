"""Worker script for the 2-process jax.distributed localhost test.

Each process initializes jax.distributed over localhost CPU devices,
builds the same small double-integrator partition with the oracle's
vertex-grid solves sharded over the GLOBAL (batch) mesh, and prints one
JSON line with its view of the result.  The parent test asserts all
processes agree with each other and with a single-process build --
proving the frontier's multi-process staging path (SURVEY.md section 6.8)
end to end without a cluster.

Usage: python tests/_mp_worker.py PORT PROCESS_ID NUM_PROCESSES [MODE]

MODE 'build' (default) runs the lockstep mesh build above; MODE
'stage_permuted' instead checks `distributed.stage_batch` on a mesh
built from an INTERLEAVED global device list -- each process's rows
are then non-contiguous, `local_contiguous_block` must reject the
fast path, and the callback fallback must still stage every shard's
exact rows (the PR-14 contiguity satellite).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
mode = sys.argv[4] if len(sys.argv) > 4 else "build"

import re  # noqa: E402

# Force exactly 4 virtual devices per process, replacing any count the
# parent environment (e.g. the pytest conftest's 8) may have set.
flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    flags + " --xla_force_host_platform_device_count=4").strip()

# Re-key the persistent compile cache for THIS process's client shape
# (d4): the inherited env var points at the parent suite's dir, and
# XLA:CPU AOT results are host- and device-count-specific (bench.
# cpu_cache_dir rationale).
from bench import cpu_cache_dir  # noqa: E402

os.environ["JAX_COMPILATION_CACHE_DIR"] = cpu_cache_dir()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=nproc, process_id=pid)
assert jax.process_count() == nproc
assert len(jax.devices()) == 4 * nproc, jax.devices()

from explicit_hybrid_mpc_tpu.config import PartitionConfig  # noqa: E402
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle  # noqa: E402
from explicit_hybrid_mpc_tpu.parallel import (distributed,  # noqa: E402
                                              make_mesh)
from explicit_hybrid_mpc_tpu.partition.frontier import (  # noqa: E402
    build_partition)
from explicit_hybrid_mpc_tpu.problems.registry import make  # noqa: E402

if mode == "stage_permuted":
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    # Interleave the two processes' devices on the batch axis: local
    # rows are then non-contiguous and the fast
    # make_array_from_process_local_data path is INVALID.
    devs = sorted(jax.devices(), key=lambda d: (d.id % 4, d.process_index))
    mesh = make_mesh((4 * nproc, 1), devices=devs)
    sharding = NamedSharding(mesh, P("batch"))
    x = np.arange(16 * nproc * 3, dtype=np.float64).reshape(-1, 3)
    idx_map = sharding.addressable_devices_indices_map(x.shape)
    block = distributed.local_contiguous_block(idx_map, x.shape)
    arr = distributed.stage_batch(sharding, x)
    ok = True
    for shard in arr.addressable_shards:
        want = x[shard.index]
        ok &= bool(np.array_equal(np.asarray(shard.data), want))
    print(json.dumps({"pid": pid, "mode": mode, "ok": ok,
                      "contiguous_block": block,
                      "n_local_shards": len(idx_map)}), flush=True)
    sys.exit(0)

prob = make("double_integrator", N=3, theta_box=1.5)
mesh = make_mesh((4 * nproc, 1))  # batch axis over ALL processes' devices
oracle = Oracle(prob, backend="cpu", mesh=mesh)
cfg = PartitionConfig(problem="double_integrator", eps_a=0.5,
                      backend="cpu", batch_simplices=32, max_depth=20)
res = build_partition(prob, cfg, oracle=oracle)
print(json.dumps({
    "pid": pid,
    "owner": distributed.is_frontier_owner(),
    "regions": res.stats["regions"],
    "tree_nodes": res.stats["tree_nodes"],
    "max_depth": res.stats["max_depth"],
    "oracle_solves": res.stats["oracle_solves"],
}), flush=True)
