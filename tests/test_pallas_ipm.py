"""Fused Pallas IPM micro-kernel (oracle/pallas_ipm.py) vs the XLA
reference path, in interpret mode on CPU (on TPU the same kernel
compiles via Mosaic for the f32 leg).

The parity contract (docs/perf.md "IPM kernel"): converged/feasible
masks bitwise-equal across tiers on every program family, iterates to
tight tolerance, `schedule_iters` accounting exact under the kernel
tier, and a full tier-1 build tree-identical.  The XLA path is the
semantic reference; these tests are what lets the pallas tier ship as
a dispatch tier instead of a fork of the solver.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import explicit_hybrid_mpc_tpu  # noqa: F401  (enables x64)
from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle import ipm, pallas_ipm
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make


def _qp_batch(rng, K=21, nz=8, nc=20, infeasible_every=3):
    """Random strictly-feasible QPs with a sprinkling of infeasible
    instances (contradictory row pair) so the not-converged /
    not-feasible classification path is exercised too."""
    Qs, qs, As, bs = [], [], [], []
    for i in range(K):
        W = rng.normal(size=(nz, nz))
        Qs.append(W @ W.T + np.eye(nz))
        qs.append(rng.normal(size=nz))
        A = rng.normal(size=(nc, nz))
        b = np.abs(rng.normal(size=nc)) + 0.5
        if infeasible_every and i % infeasible_every == 0:
            A[0] = -A[1]           # A1 z <= b1 and A1 z >= b1 + 1:
            b[0] = -b[1] - 1.0     # contradictory pair, empty set
        As.append(A)
        bs.append(b)
    return tuple(jnp.asarray(np.stack(x)) for x in (Qs, qs, As, bs))


def _solve(tier, Qs, qs, As, bs, **kw):
    return jax.jit(jax.vmap(functools.partial(
        ipm.qp_solve, kernel=tier, **kw)))(Qs, qs, As, bs)


def test_point_family_mask_and_iterate_parity():
    rng = np.random.default_rng(11)
    Qs, qs, As, bs = _qp_batch(rng)
    for kw in (dict(n_iter=20), dict(n_iter=8, n_f32=15)):
        ref = _solve("xla", Qs, qs, As, bs, **kw)
        pal = _solve("pallas", Qs, qs, As, bs, **kw)
        assert bool((ref.converged == pal.converged).all()), kw
        assert bool((ref.feasible == pal.feasible).all()), kw
        conv = np.asarray(ref.converged)
        # Iterates to tight tolerance on the converged population (the
        # diverging iterates of infeasible QPs are unstable by nature).
        np.testing.assert_allclose(np.asarray(pal.z)[conv],
                                   np.asarray(ref.z)[conv], atol=1e-9)
        np.testing.assert_allclose(np.asarray(pal.obj)[conv],
                                   np.asarray(ref.obj)[conv],
                                   rtol=1e-9, atol=1e-9)
        assert ref.converged.any() and not ref.converged.all()


def test_warm_start_gate_parity():
    """The merit-gated warm path runs OUTSIDE the legs (shared code):
    warm_ok decisions and warm-started results must agree across
    tiers."""
    rng = np.random.default_rng(5)
    Qs, qs, As, bs = _qp_batch(rng, K=13)
    base = _solve("xla", Qs, qs, As, bs, n_iter=20)
    warm = (base.z, base.s, base.lam,
            jnp.asarray(np.arange(13) % 2 == 0))  # half the donors valid

    def wsolve(tier):
        return jax.jit(jax.vmap(
            lambda Q, q, A, b, z, s, lam, h: ipm.qp_solve(
                Q, q, A, b, n_iter=6, warm_start=(z, s, lam, h),
                kernel=tier)))(Qs, qs, As, bs, *warm)

    ref, pal = wsolve("xla"), wsolve("pallas")
    assert bool((ref.warm_ok == pal.warm_ok).all())
    assert bool((ref.converged == pal.converged).all())
    assert ref.warm_ok.any()


def test_unbatched_call_uses_reference_body():
    """The custom_vmap fallback: an unbatched qp_solve (the serial
    baseline's program shape) is the XLA body bit-for-bit even under
    kernel='pallas'."""
    rng = np.random.default_rng(2)
    Qs, qs, As, bs = _qp_batch(rng, K=1, infeasible_every=0)
    ref = jax.jit(functools.partial(ipm.qp_solve, kernel="xla"))(
        Qs[0], qs[0], As[0], bs[0])
    pal = jax.jit(functools.partial(ipm.qp_solve, kernel="pallas"))(
        Qs[0], qs[0], As[0], bs[0])
    assert np.array_equal(np.asarray(ref.z), np.asarray(pal.z))
    assert bool(ref.converged) == bool(pal.converged)


def test_solve_tiles_padding_and_tile_pick():
    # Non-multiple batch sizes pad with benign identity QPs and slice
    # them back off; small batches shrink the tile instead of padding
    # 4x; the VMEM guard caps the tile for big shapes.
    rng = np.random.default_rng(3)
    Qs, qs, As, bs = _qp_batch(rng, K=11, infeasible_every=0)
    z = jnp.zeros((11, 8))
    s = jnp.ones((11, 20))
    lam = jnp.ones((11, 20))
    out = pallas_ipm.solve_tiles(Qs, qs, As, bs, z, s, lam, n_iter=5)
    assert out[0].shape == (11, 8)
    assert all(bool(jnp.all(jnp.isfinite(o))) for o in out)
    assert pallas_ipm._pick_tile(2, 8, 20, 8) == 2
    assert pallas_ipm._pick_tile(100, 8, 20, 8) == pallas_ipm.TILE
    # A shape whose 8-wide working set exceeds the budget shrinks...
    mid = pallas_ipm._pick_tile(64, 48, 128, 8)
    assert 1 <= mid < pallas_ipm.TILE
    assert pallas_ipm.tile_vmem_bytes(mid, 48, 128,
                                      8) <= pallas_ipm.VMEM_BUDGET
    # ...down to the 1-QP floor for shapes that can never fit.
    assert pallas_ipm._pick_tile(64, 96, 512, 8) == 1


def test_resolve_tier_and_forced_xla():
    assert pallas_ipm.resolve_kernel_tier("auto") == "xla"  # CPU host
    assert pallas_ipm.resolve_kernel_tier("pallas") == "pallas"
    with pytest.raises(ValueError, match="ipm_kernel"):
        pallas_ipm.resolve_kernel_tier("mosaic")
    with pytest.raises(ValueError, match="ipm_kernel"):
        PartitionConfig(problem="double_integrator",
                        ipm_kernel="mosaic")
    prob = make("double_integrator", N=3, theta_box=1.5)
    assert Oracle(prob, backend="serial",
                  ipm_kernel="pallas").ipm_kernel == "xla"
    assert Oracle(prob, backend="cpu").ipm_kernel == "xla"  # auto/CPU


@pytest.fixture(scope="module")
def di_problem():
    return make("double_integrator", N=3, theta_box=1.5)


@pytest.fixture(scope="module")
def tier_oracles(di_problem):
    """Warm-capable two-phase oracles on both tiers (the shipping
    configuration of the tier-1 build)."""
    mk = lambda tier: Oracle(di_problem, backend="cpu", two_phase=True,  # noqa: E731
                             warm_start=True, ipm_kernel=tier)
    return mk("xla"), mk("pallas")


def test_oracle_vertex_masks_and_exact_accounting(di_problem,
                                                  tier_oracles):
    """Two-phase cohort flow through the kernel tier: conv/feas masks
    and the d* reduction bitwise-equal, and the host iteration ledger
    (the exactness contract behind oracle.ipm_iters /
    wasted_iter_frac) IDENTICAL across tiers -- cohort survivor sets
    included."""
    ox, op = tier_oracles
    rng = np.random.default_rng(17)
    thetas = rng.uniform(di_problem.theta_lb, di_problem.theta_ub,
                         size=(23, di_problem.n_theta))
    sx = ox.solve_vertices(thetas)
    sp = op.solve_vertices(thetas)
    assert np.array_equal(sx.conv, sp.conv)
    assert np.array_equal(sx.feas, sp.feas)
    assert np.array_equal(sx.dstar, sp.dstar)
    fin = np.isfinite(sx.V)
    np.testing.assert_allclose(sp.V[fin], sx.V[fin], rtol=1e-9,
                               atol=1e-9)
    assert ox.stat_snapshot() == op.stat_snapshot()
    assert op.n_iters_f64 > 0


def test_oracle_simplex_and_farkas_parity(di_problem, tier_oracles):
    """Elastic-simplex-min (two-phase cohort) and the sound
    Farkas/phase-1 program: encoding classes, feasibility witnesses,
    and infeasibility certificates bitwise-equal across tiers."""
    ox, op = tier_oracles
    rng = np.random.default_rng(23)
    Ms = np.stack([geometry.barycentric_matrix(
        rng.uniform(di_problem.theta_lb, di_problem.theta_ub,
                    size=(di_problem.n_theta + 1, di_problem.n_theta)))
        for _ in range(9)])
    ds = rng.integers(0, di_problem.canonical.n_delta, size=9)
    vx, fx = ox.solve_simplex_min(Ms, ds)
    vp, fp = op.solve_simplex_min(Ms, ds)

    def cls(v):
        return np.where(np.isposinf(v), 1, np.where(np.isneginf(v),
                                                    -1, 0))

    assert np.array_equal(cls(vx), cls(vp))
    assert np.array_equal(fx, fp)
    both = np.isfinite(vx) & np.isfinite(vp)
    np.testing.assert_allclose(vp[both], vx[both], rtol=1e-8, atol=1e-8)
    tx, feasx, infx = ox.simplex_feasibility(Ms, ds)
    tp, feasp, infp = op.simplex_feasibility(Ms, ds)
    assert np.array_equal(feasx, feasp)
    assert np.array_equal(infx, infp)
    np.testing.assert_allclose(tp, tx, atol=1e-10)


def test_solve_mask_kernel_tier():
    """The bare-kernel replay probe (scripts/replay_solve.py
    --kernel-only --kernel-tier) agrees across tiers."""
    rng = np.random.default_rng(29)
    Qs, qs, As, bs = _qp_batch(rng, K=10)
    cx, fx, rx = ipm.solve_mask(Qs, qs, As, bs, n_iter=15)
    cp, fp, rp = ipm.solve_mask(Qs, qs, As, bs, n_iter=15,
                                kernel="pallas")
    assert np.array_equal(cx, cp) and np.array_equal(fx, fp)
    fin = np.isfinite(rx) & np.isfinite(rp)
    np.testing.assert_allclose(rp[fin], rx[fin], rtol=1e-6, atol=1e-12)


def _tree_signature(res):
    """Node-for-node structural identity (same contract as
    tests/test_pipeline.py): vertex matrices bitwise, leaf
    commutations and certification statuses, region/node counts."""
    tree = res.tree
    leaves = tree.converged_leaves()
    return (res.stats["regions"], res.stats["tree_nodes"],
            res.stats["uncertified"], res.stats["semi_explicit"],
            tuple(tree.vertices[n].tobytes() for n in range(len(tree))),
            tuple(tree.leaf_data[n].delta_idx for n in leaves),
            tuple(bool(tree.leaf_data[n].certified) for n in leaves))


def test_full_build_tree_identical_across_tiers(di_problem):
    """Acceptance: a full tier-1 build with ipm_kernel='pallas'
    (interpret) produces the IDENTICAL tree to 'xla' -- every program
    family, the cohort compaction, warm-start donors, and the
    certificates all flow through the kernel tier."""
    def build(tier):
        cfg = PartitionConfig(problem="double_integrator", eps_a=0.5,
                              backend="cpu", batch_simplices=64,
                              max_depth=20, ipm_kernel=tier)
        return build_partition(di_problem, cfg)

    rx, rp = build("xla"), build("pallas")
    assert rx.stats["regions"] > 50
    assert _tree_signature(rx) == _tree_signature(rp)


def test_obs_kernel_gauge_and_tile_histogram(di_problem):
    from explicit_hybrid_mpc_tpu import obs as obs_lib

    rng = np.random.default_rng(31)
    thetas = rng.uniform(di_problem.theta_lb, di_problem.theta_ub,
                         size=(5, di_problem.n_theta))
    for tier, want in (("pallas", 1.0), ("xla", 0.0)):
        obs = obs_lib.Obs("jsonl")
        o = Oracle(di_problem, backend="cpu", ipm_kernel=tier, obs=obs)
        o.solve_vertices(thetas)
        summ = obs.metrics.summary()
        assert summ["gauges"]["oracle.ipm_kernel"] == want
        hist = summ.get("histograms", {}).get("oracle.ipm_kernel_tile_s")
        if tier == "pallas":
            assert hist is not None and hist["count"] > 0
        else:
            assert hist is None
