"""Incremental warm rebuild (partition/rebuild.py) + provenance stamps.

Contract tests for ISSUE 10: an unchanged problem rebuilds
node-for-node bit-identical with ZERO subdivision solves (the
re-certification sweep is the only oracle traffic); an eps-tightened
rebuild reaches the cold build's certification verdicts; stale or
unstamped priors are rejected where the caller asked for strictness;
mid-rebuild checkpoints resume through the existing path; and the
reuse counters land in the obs schema + health rules.
"""

import dataclasses
import glob
import importlib.util
import os
import pickle
import sys

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.obs import Obs
from explicit_hybrid_mpc_tpu.obs.health import HealthMonitor
from explicit_hybrid_mpc_tpu.partition import provenance as prov
from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                        build_partition,
                                                        make_oracle)
from explicit_hybrid_mpc_tpu.partition.rebuild import (RebuildError,
                                                       publish_rebuild,
                                                       warm_rebuild)
from explicit_hybrid_mpc_tpu.problems.registry import make

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tree_states_equal(ta, tb, ignore=("provenance",)) -> bool:
    a, b = ta.__getstate__(), tb.__getstate__()
    if set(a) != set(b):
        return False
    for k in a:
        if k in ignore:
            continue
        va, vb = a[k], b[k]
        if isinstance(va, np.ndarray) or isinstance(vb, np.ndarray):
            if not np.array_equal(va, vb):
                return False
        elif va != vb:
            return False
    return True


@pytest.fixture(scope="module")
def di_problem():
    return make("double_integrator", N=3, theta_box=1.5)


@pytest.fixture(scope="module")
def di_cfg():
    return PartitionConfig(problem="double_integrator", eps_a=0.3,
                           backend="cpu", batch_simplices=128)


@pytest.fixture(scope="module")
def prior(di_problem, di_cfg):
    """The prior build every rebuild test transfers from."""
    return build_partition(di_problem, di_cfg)


@pytest.fixture(scope="module")
def tight_cfg(di_cfg):
    return dataclasses.replace(di_cfg, eps_a=0.15)


@pytest.fixture(scope="module")
def tight_rebuild(di_problem, tight_cfg, prior):
    return warm_rebuild(di_problem, tight_cfg, prior.tree)


# -- the acceptance case ---------------------------------------------------


def test_unchanged_rebuild_bit_identical_zero_subdivision(
        di_problem, di_cfg, prior, tmp_path):
    path = str(tmp_path / "prior.tree.pkl")
    prior.tree.save(path)
    res = warm_rebuild(di_problem, di_cfg, path)
    st = res.stats
    assert st["subdivision_solves"] == 0
    assert st["rebuild_leaves_invalidated"] == 0
    assert st["rebuild_reuse_frac"] == 1.0
    assert st["recert_solves"] > 0  # the sweep DID re-prove everything
    assert st["regions"] == prior.stats["regions"]
    assert _tree_states_equal(prior.tree, res.tree)
    # The new tree is re-stamped with the (identical) revision's stamp.
    assert res.tree.provenance is not None
    assert prov.diff_stamps(res.tree.provenance,
                            prior.tree.provenance) == []


def test_unchanged_rebuild_via_build_partition_route(di_problem, di_cfg,
                                                     prior, tmp_path):
    path = str(tmp_path / "prior.tree.pkl")
    prior.tree.save(path)
    cfg = dataclasses.replace(di_cfg, rebuild_from=path)
    res = build_partition(di_problem, cfg)
    assert res.stats["rebuild_reuse_frac"] == 1.0
    assert _tree_states_equal(prior.tree, res.tree)


def test_eps_tightened_rebuild_matches_cold_verdicts(
        di_problem, tight_cfg, prior, tight_rebuild):
    cold = build_partition(di_problem, tight_cfg)
    st = tight_rebuild.stats
    # Equal certification: both fully eps-certified, no truncation.
    assert st["uncertified"] == 0 and cold.stats["uncertified"] == 0
    assert not st["truncated"] and not cold.stats["truncated"]
    assert 0.0 < st["rebuild_reuse_frac"] < 1.0
    assert st["rebuild_leaves_invalidated"] > 0
    assert st["subdivision_solves"] > 0
    assert st["provenance_changed"] == ["eps_a: 0.3 != 0.15"]
    # Certification-verdict parity on a theta sweep: every sampled
    # point lands in a leaf of the same kind (certified payload /
    # infeasible hole) in the cold and rebuilt trees.
    rng = np.random.default_rng(0)
    qs = rng.uniform(di_problem.theta_lb, di_problem.theta_ub,
                     size=(400, di_problem.n_theta))
    for q in qs:
        la = cold.tree.locate(q, cold.roots)
        lb = tight_rebuild.tree.locate(q, tight_rebuild.roots)
        da = cold.tree.leaf_data[la] if la >= 0 else None
        db = tight_rebuild.tree.leaf_data[lb] if lb >= 0 else None
        assert (da is None) == (db is None)
        if da is not None:
            assert da.certified == db.certified


def test_rebuild_checkpoint_donors_are_consumed(di_problem, di_cfg,
                                                tmp_path):
    """A CHECKPOINT prior donates its VertexCache duals as warm starts
    (the sweep's pair path); the produced tree still matches."""
    ckpt = str(tmp_path / "prior.ckpt.pkl")
    cfg = dataclasses.replace(di_cfg, checkpoint_every=3,
                              checkpoint_path=ckpt, max_steps=6)
    res = build_partition(di_problem, cfg)  # truncated, ckpt written
    assert os.path.exists(ckpt)
    full_cfg = dataclasses.replace(cfg, checkpoint_every=0,
                                   checkpoint_path=None, max_steps=10_000)
    reb = warm_rebuild(di_problem, full_cfg, ckpt)
    # The mid-build checkpoint's open frontier nodes carry no
    # certificates: the sweep re-opens them (any feasible vertex fails
    # the emptiness re-check) and the frontier completes the build.
    # Its VertexCache rows, though, were offered as warm-start donors.
    assert reb.stats["uncertified"] == 0
    assert not reb.stats["truncated"]
    assert reb.stats["rebuild_leaves_total"] > 0
    assert reb.stats["warm_donor_vertices"] > 0
    assert reb.stats["regions"] > 0


def test_resume_mid_rebuild_reaches_the_same_tree(
        di_problem, tight_cfg, prior, tight_rebuild, tmp_path):
    ckpt = str(tmp_path / "rebuild.ckpt.pkl")
    cfg = dataclasses.replace(tight_cfg, checkpoint_every=1,
                              checkpoint_path=ckpt, max_steps=1)
    partial = warm_rebuild(di_problem, cfg, prior.tree)
    assert partial.stats["truncated"]
    assert os.path.exists(ckpt)
    oracle = make_oracle(di_problem, tight_cfg)
    eng = FrontierEngine.resume(ckpt, di_problem, oracle,
                                cfg=dataclasses.replace(
                                    tight_cfg, max_steps=10_000))
    res = eng.run()
    assert res.stats["uncertified"] == 0
    assert _tree_states_equal(res.tree, tight_rebuild.tree)


# -- rejection / provenance ------------------------------------------------


def test_incompatible_prior_rejected(prior):
    other = make("double_integrator", N=3, theta_box=2.0)
    cfg = PartitionConfig(problem="double_integrator", eps_a=0.3,
                          backend="cpu")
    with pytest.raises(RebuildError, match="root triangulation"):
        warm_rebuild(other, cfg, prior.tree)


def test_strict_provenance_rejects_unstamped_prior(di_problem, di_cfg,
                                                   prior):
    legacy = pickle.loads(pickle.dumps(prior.tree))
    legacy.provenance = None
    with pytest.raises(prov.ProvenanceMismatch, match="no provenance"):
        warm_rebuild(di_problem, di_cfg, legacy,
                     strict_provenance=True)
    # Default shims: the rebuild proceeds and records the shim.
    res = warm_rebuild(di_problem, di_cfg, legacy)
    assert any("no provenance" in d
               for d in res.stats["provenance_changed"])
    assert res.stats["rebuild_reuse_frac"] == 1.0


def test_artifact_loaders_check_stamps(prior, tmp_path):
    from explicit_hybrid_mpc_tpu.online import export
    from explicit_hybrid_mpc_tpu.serve.registry import save_artifacts

    d = str(tmp_path / "art")
    save_artifacts(prior.tree, prior.roots, d)
    stamp = export.load_table_provenance(d)
    assert stamp is not None
    assert stamp["problem_hash"] == \
        prior.tree.provenance["problem_hash"]
    # Matching expectation: silent.
    export.load_leaf_table(d, expect_provenance=prior.tree.provenance)
    stale = dict(prior.tree.provenance, eps_a=99.0,
                 problem_hash="deadbeefdeadbeef")
    with pytest.warns(prov.ProvenanceWarning, match="mismatch"):
        export.load_leaf_table(d, expect_provenance=stale)
    with pytest.raises(prov.ProvenanceMismatch):
        export.load_leaf_table(d, expect_provenance=stale, strict=True)


def test_legacy_stampless_table_shims(prior, tmp_path):
    from explicit_hybrid_mpc_tpu.online import export

    d = str(tmp_path / "legacy")
    table = export.export_leaves(prior.tree)
    export.save_leaf_table(table, d)  # no provenance passed
    assert export.load_table_provenance(d) is None
    # Expectation against an unstamped table: warns, loads, NEVER
    # raises even under strict (nothing to compare).
    with pytest.warns(prov.ProvenanceWarning, match="no provenance"):
        t2 = export.load_leaf_table(
            d, expect_provenance=prior.tree.provenance, strict=True)
    assert t2.n_leaves == table.n_leaves


def test_checkpoint_carries_stamp(di_problem, di_cfg, tmp_path):
    ckpt = str(tmp_path / "c.pkl")
    cfg = dataclasses.replace(di_cfg, checkpoint_every=2,
                              checkpoint_path=ckpt, max_steps=4)
    build_partition(di_problem, cfg)
    # Checkpoints carry the PR-12 content-checksum header: read through
    # the verifying loader, not bare pickle.load.
    from explicit_hybrid_mpc_tpu.partition.frontier import load_checkpoint

    snap = load_checkpoint(ckpt)
    assert snap["provenance"]["problem_hash"] == \
        prov.problem_hash(di_problem)
    assert snap["tree"].provenance is not None


# -- publish path ----------------------------------------------------------


def test_publish_rebuild_hot_swaps_registry(di_problem, di_cfg, prior,
                                            tmp_path):
    from explicit_hybrid_mpc_tpu.serve.registry import ControllerRegistry

    reg = ControllerRegistry()
    d1 = str(tmp_path / "v1")
    v1 = publish_rebuild(prior, d1, registry=reg, name="di")
    assert reg.active_version("di") == v1
    res = warm_rebuild(di_problem, dataclasses.replace(di_cfg, eps_a=0.25),
                       prior.tree)
    d2 = str(tmp_path / "v2")
    v2 = publish_rebuild(res, d2, registry=reg, name="di")
    assert v2 != v1
    assert reg.active_version("di") == v2
    with reg.lease("di") as ver:
        assert ver.version == v2


# -- obs / health / gate wiring --------------------------------------------


def test_rebuild_obs_counters_land_in_schema(di_problem, tight_cfg,
                                             prior):
    o = Obs("jsonl")
    res = warm_rebuild(di_problem, tight_cfg, prior.tree, obs=o)
    snap = o.metrics.snapshot()
    c, g = snap["counters"], snap["gauges"]
    st = res.stats
    assert c["rebuild.leaves_recertified"] == \
        st["rebuild_leaves_recertified"]
    assert c["rebuild.leaves_reused"] == st["rebuild_leaves_reused"]
    assert c["rebuild.leaves_invalidated"] == \
        st["rebuild_leaves_invalidated"]
    assert c["rebuild.recert_solves"] == st["recert_solves"]
    assert g["rebuild.reuse_frac"] == pytest.approx(
        st["rebuild_reuse_frac"], abs=1e-4)
    events = [r for r in o.sink.records
              if r.get("kind") == "event"
              and r.get("name") == "rebuild.sweep"]
    assert len(events) == 1
    o.close()


def test_obs_report_renders_rebuild_block(di_problem, tight_cfg, prior,
                                          tmp_path):
    spec = importlib.util.spec_from_file_location(
        "obs_report", os.path.join(REPO, "scripts", "obs_report.py"))
    obs_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(obs_report)
    path = str(tmp_path / "r.obs.jsonl")
    o = Obs("jsonl", path=path)
    warm_rebuild(di_problem, tight_cfg, prior.tree, obs=o)
    o.close()
    from explicit_hybrid_mpc_tpu.obs.sink import load_jsonl

    rep = obs_report.report(load_jsonl(path))
    assert "rebuild" in rep
    assert rep["rebuild"]["reuse_frac"] > 0
    txt = obs_report.render_text(rep, [], None)
    assert "rebuild:" in txt
    # diff_bench flags a reuse collapse vs a bench row.
    flags = obs_report.diff_bench(
        rep, {"rebuild_reuse_frac": rep["rebuild"]["reuse_frac"] * 4})
    assert any("rebuild reuse regression" in f for f in flags)


def test_health_rebuild_reuse_collapse_rule():
    mon = HealthMonitor({"min_rebuild_reuse": 0.5,
                         "min_rebuild_leaves": 10})
    rec = {"kind": "metrics",
           "counters": {"rebuild.leaves_reused": 2,
                        "rebuild.leaves_invalidated": 98},
           "gauges": {"rebuild.reuse_frac": 0.02}}
    evs = mon.feed(rec)
    assert any(e["name"] == "health.rebuild_reuse_collapse"
               for e in evs)
    assert mon.worst == "warn"
    # Volume gate (its OWN leaf-count floor, not the solve-count
    # knob): a tiny rebuild never fires.
    mon2 = HealthMonitor({"min_rebuild_reuse": 0.5,
                          "min_rebuild_leaves": 1000})
    assert mon2.feed(rec) == []
    # 0 disables.
    mon3 = HealthMonitor({"min_rebuild_reuse": 0.0,
                          "min_rebuild_leaves": 10})
    assert mon3.feed(rec) == []


def test_bench_gate_gates_rebuild_metrics():
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import bench_gate
    finally:
        sys.path.pop(0)
    assert bench_gate.GATED_METRICS["rebuild_reuse_frac"][0] == "higher"
    assert bench_gate.GATED_METRICS["rebuild_speedup"][0] == "higher"
    row = bench_gate.summarize(
        {"platform": "cpu", "metric": "warm-rebuild",
         "rebuild_reuse_frac": 0.8, "rebuild_speedup": 2.0,
         "recert_solves": 123}, "BENCH_rebuild_r01.json", mtime=1.0)
    assert row["rebuild_reuse_frac"] == 0.8
    assert row["recert_solves"] == 123
    hist = [{"platform": "cpu", "source": "old.json",
             "rebuild_reuse_frac": 0.9, "rebuild_speedup": 2.0}]
    flags, _info = bench_gate.gate(
        dict(row, rebuild_reuse_frac=0.2, rebuild_speedup=0.5), hist)
    assert any("rebuild_reuse_frac" in f for f in flags)
    assert any("rebuild_speedup" in f for f in flags)


def test_dead_ledger_events_pruned_from_rebuilt_tree(di_problem, di_cfg,
                                                     prior):
    """A stale exclusion event that fails re-verification must NOT ride
    into the rebuilt tree's ledger (it would be re-checked -- and fail
    -- on every future chained rebuild)."""
    doctored = pickle.loads(pickle.dumps(prior.tree))
    root = doctored.roots()[0]
    # The root simplex is feasible for delta 0, so this bogus emptiness
    # certificate cannot re-verify.
    doctored.excl_events.append((int(root), 0, np.inf))
    res = warm_rebuild(di_problem, di_cfg, doctored)
    assert (int(root), 0, np.inf) not in [
        (a, d, v) for a, d, v in res.tree.excl_events]
    # The doctored event changed nothing else: full reuse still holds.
    assert res.stats["rebuild_reuse_frac"] == 1.0
    assert res.stats["rebuild_excl_events"] == \
        len(set((a, d) for a, d, _v in doctored.excl_events))


# -- recorder / replay -----------------------------------------------------


def test_invalidated_leaf_recert_bundle_replays(di_problem, tight_cfg,
                                                prior, tmp_path):
    rec_dir = str(tmp_path / "repro")
    cfg = dataclasses.replace(tight_cfg, obs_recorder=True,
                              recorder_dir=rec_dir)
    warm_rebuild(di_problem, cfg, prior.tree)
    bundles = sorted(glob.glob(
        os.path.join(rec_dir, "*recert_invalidated*.npz")))
    assert bundles, "eps-tightened rebuild must dump recert bundles"
    spec = importlib.util.spec_from_file_location(
        "replay_solve", os.path.join(REPO, "scripts", "replay_solve.py"))
    replay_solve = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(replay_solve)
    rep = replay_solve.replay_bundle(bundles[0])
    assert rep["kind"] == "recert"
    assert rep["snapshot_verdict"] != "certified"
    assert rep["ok"]


# -- CLI surface -----------------------------------------------------------


def test_rebuild_subcommand_requires_from():
    from explicit_hybrid_mpc_tpu.main import main

    with pytest.raises(SystemExit, match="--from"):
        main(["rebuild", "-e", "double_integrator"])


def test_rebuild_and_resume_exclusive():
    from explicit_hybrid_mpc_tpu.main import main

    with pytest.raises(SystemExit, match="exclusive"):
        main(["-e", "double_integrator", "--rebuild-from", "x.pkl",
              "--resume", "y.pkl"])
