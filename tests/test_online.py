import jax.numpy as jnp
import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.online import descent, evaluator, export
from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def built():
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=0.5,
                          backend="cpu", batch_simplices=64, max_depth=20)
    res = build_partition(prob, cfg)
    table = export.export_leaves(res.tree)
    return prob, res, table


def test_export_shapes(built):
    prob, res, table = built
    L = table.n_leaves
    assert L == res.stats["regions"]
    assert table.bary_M.shape == (L, 3, 3)
    assert table.U.shape == (L, 3, 1)


def test_device_eval_matches_tree_descent(built, rng):
    prob, res, table = built
    dev = evaluator.stage(table)
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub, size=(64, 2))
    out = evaluator.evaluate(dev, jnp.asarray(thetas))
    assert bool(np.all(np.asarray(out.inside)))
    for k, th in enumerate(thetas):
        n = res.tree.locate(th, res.roots)
        lam = geometry.barycentric(res.tree.vertices[n], th)
        u_ref = res.tree.leaf_data[n].vertex_inputs.T @ lam
        # Shared facets can give two containing leaves; compare values, not
        # leaf ids.
        np.testing.assert_allclose(np.asarray(out.u[k]), u_ref, atol=1e-6)
        u_np = evaluator.evaluate_np(table, th)
        np.testing.assert_allclose(u_np, u_ref, atol=1e-6)


def test_outside_flagged(built):
    prob, res, table = built
    dev = evaluator.stage(table)
    out = evaluator.evaluate(dev, jnp.asarray([[10.0, 10.0]]))
    assert not bool(out.inside[0])


def test_descent_matches_brute_force(built, rng):
    """The O(depth) device descent must agree with the O(L) brute-force
    evaluator: located simplex contains the query and the interpolated
    law matches (shared facets may differ in leaf id, never in value)."""
    prob, res, table = built
    dev = evaluator.stage(table)
    dt = descent.export_descent(res.tree, res.roots, table)
    assert dt.max_depth == res.tree.max_depth()
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub, size=(128, 2))
    brute = evaluator.evaluate(dev, jnp.asarray(thetas))
    desc = descent.evaluate_descent(dt, dev, jnp.asarray(thetas))
    assert bool(np.all(np.asarray(desc.inside)))
    np.testing.assert_allclose(np.asarray(desc.u), np.asarray(brute.u),
                               atol=1e-8)
    np.testing.assert_allclose(np.asarray(desc.cost),
                               np.asarray(brute.cost), atol=1e-8)
    # The located simplex geometrically contains each query.
    rows, nodes = descent.locate_descent(dt, jnp.asarray(thetas))
    for k, th in enumerate(thetas):
        assert geometry.contains(res.tree.vertices[int(nodes[k])], th,
                                 tol=1e-9)


def test_descent_outside_flagged(built):
    prob, res, table = built
    dev = evaluator.stage(table)
    dt = descent.export_descent(res.tree, res.roots, table)
    out = descent.evaluate_descent(dt, dev, jnp.asarray([[10.0, 10.0]]))
    assert not bool(out.inside[0])


def test_descent_hybrid_partition(rng):
    """Descent on a pendulum partition (pre-split roots, hybrid deltas):
    values must match brute force everywhere inside."""
    prob = make("inverted_pendulum", N=3)
    cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                          backend="cpu", batch_simplices=64, max_steps=400)
    res = build_partition(prob, cfg)
    table = export.export_leaves(res.tree)
    dev = evaluator.stage(table)
    dt = descent.export_descent(res.tree, res.roots, table)
    # North-star problem parity: split-time hyperplane arrays must be
    # bit-identical to the batched-SVD export on the pendulum too
    # (pre-split roots, hybrid deltas).
    dt_svd = descent.export_descent(res.tree, res.roots, table,
                                    force_batched=True)
    np.testing.assert_array_equal(np.asarray(dt.normal),
                                  np.asarray(dt_svd.normal))
    np.testing.assert_array_equal(np.asarray(dt.offset),
                                  np.asarray(dt_svd.offset))
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub, size=(64, 2))
    brute = evaluator.evaluate(dev, jnp.asarray(thetas))
    desc = descent.evaluate_descent(dt, dev, jnp.asarray(thetas))
    ok = np.asarray(brute.inside) & np.asarray(desc.inside)
    assert ok.mean() > 0.9  # infeasible margins may be flagged by either
    np.testing.assert_allclose(np.asarray(desc.u)[ok],
                               np.asarray(brute.u)[ok], atol=1e-8)


def test_controller_is_continuous_across_facets(built, rng):
    """PWA law from barycentric interpolation is continuous: evaluate at
    points straddling internal facets."""
    prob, res, table = built
    dev = evaluator.stage(table)
    for _ in range(10):
        th = rng.uniform(prob.theta_lb * 0.9, prob.theta_ub * 0.9)
        eps_step = 1e-7 * rng.normal(size=2)
        pair = jnp.asarray(np.stack([th, th + eps_step]))
        out = evaluator.evaluate(dev, pair)
        assert abs(float(out.u[0, 0]) - float(out.u[1, 0])) < 1e-4


def test_split_time_hyperplanes_match_batched_svd(built):
    """Tentpole parity: a build with split-time hyperplanes (the
    default) must export a DescentTable BIT-IDENTICAL to the batched
    post-hoc SVD pass it amortizes away."""
    prob, res, table = built
    assert res.tree.split_hyperplanes_available()
    dt_fast = descent.export_descent(res.tree, res.roots, table)
    dt_slow = descent.export_descent(res.tree, res.roots, table,
                                     force_batched=True)
    assert dt_fast.max_depth == dt_slow.max_depth
    for name in ("root_bary", "root_node", "children", "normal",
                 "offset", "leaf_row"):
        np.testing.assert_array_equal(
            np.asarray(getattr(dt_fast, name)),
            np.asarray(getattr(dt_slow, name)), err_msg=name)


def test_split_hyperplanes_survive_pickle(built, tmp_path):
    """Serialized trees keep their split-time hyperplane columns (a
    resumed campaign must not silently fall back to the slow export),
    and loaded-tree exports stay bit-identical to the live tree's."""
    from explicit_hybrid_mpc_tpu.partition.tree import Tree

    prob, res, table = built
    path = str(tmp_path / "t.pkl")
    res.tree.save(path)
    loaded = Tree.load(path)
    assert loaded.split_hyperplanes_available()
    np.testing.assert_array_equal(loaded.split_normals,
                                  res.tree.split_normals)
    np.testing.assert_array_equal(loaded.split_offsets,
                                  res.tree.split_offsets)


def test_chunked_export_matches_in_ram(built, tmp_path):
    """Streamed memmap export == in-RAM export bit-for-bit, at a chunk
    size that forces many partial chunks; load_leaf_table round-trips
    both mmap'd and copied."""
    prob, res, table = built
    d = str(tmp_path / "leaves")
    written = export.write_leaf_table(res.tree, d, chunk=37)
    for mmap in (True, False):
        loaded = export.load_leaf_table(d, mmap=mmap)
        for k in export._LEAF_FIELDS:
            np.testing.assert_array_equal(getattr(table, k),
                                          getattr(loaded, k), err_msg=k)
    assert written.n_leaves == table.n_leaves
    # A memmap-backed table serves the evaluator unchanged.
    dev = evaluator.stage(export.load_leaf_table(d))
    out = evaluator.evaluate(dev, jnp.asarray([[0.1, -0.2]]))
    ref = evaluator.evaluate(evaluator.stage(table),
                             jnp.asarray([[0.1, -0.2]]))
    np.testing.assert_array_equal(np.asarray(out.u), np.asarray(ref.u))


def test_descent_table_save_load(built, tmp_path):
    """save_descent/load_descent round-trip: with the leaf-table files,
    the online stage deploys from flat arrays alone -- no pickled
    Tree."""
    import os

    prob, res, table = built
    dt = descent.export_descent(res.tree, res.roots, table)
    path = os.path.join(str(tmp_path), "dt.npz")
    descent.save_descent(dt, path)
    dt2 = descent.load_descent(path)
    assert dt2.max_depth == dt.max_depth
    for name in ("root_bary", "root_node", "children", "normal",
                 "offset", "leaf_row"):
        np.testing.assert_array_equal(np.asarray(getattr(dt, name)),
                                      np.asarray(getattr(dt2, name)),
                                      err_msg=name)
    dev = evaluator.stage(table)
    qs = jnp.asarray([[0.3, 0.4], [-0.5, 0.2]])
    a = descent.evaluate_descent(dt, dev, qs)
    b = descent.evaluate_descent(dt2, dev, qs)
    np.testing.assert_array_equal(np.asarray(a.u), np.asarray(b.u))


def test_tree_roots_survive_pickle(built, tmp_path):
    """Tree.roots() recovers the build's root list from a loaded pickle,
    so export_descent / partition_report work without the live
    PartitionResult (docs/guide.md deployment path)."""
    from explicit_hybrid_mpc_tpu.partition.tree import Tree

    prob, res, table = built
    path = str(tmp_path / "t.pkl")
    res.tree.save(path)
    loaded = Tree.load(path)
    assert loaded.roots() == res.roots
    dt = descent.export_descent(loaded, loaded.roots(), table)
    assert dt.leaf_row.shape[0] == len(loaded)
