"""ISSUE 4 diagnostics subsystem: flight-recorder repro bundles +
standalone replay, the streaming health watchdog, and the bench
regression gate.

The tier-1 acceptance flow lives here: a fault-injected (forced-
divergence, via a genuinely-too-short IPM schedule) oracle under
obs='jsonl' must produce a repro bundle during a tiny build,
scripts/replay_solve.py must round-trip it bit-for-bit,
scripts/obs_watch.py must raise health.stall on a frozen stream, and
scripts/bench_gate.py must flag a synthetic >=10% regions/sec
regression.
"""

import io
import json
import os
import sys

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.obs.health import (DEFAULT_RULES,
                                                HealthMonitor,
                                                rules_from_pairs)
from explicit_hybrid_mpc_tpu.obs.recorder import (BUNDLE_VERSION,
                                                  FlightRecorder,
                                                  load_bundle)
from explicit_hybrid_mpc_tpu.obs.sink import load_jsonl
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")


def _script(name):
    sys.path.insert(0, SCRIPTS)
    try:
        return __import__(name)
    finally:
        sys.path.pop(0)


@pytest.fixture(scope="module")
def prob():
    return make("double_integrator", N=3, theta_box=1.5)


# -- flight recorder + replay ----------------------------------------------

def _short_schedule_anomaly(prob, tmp_path, n_points=24):
    """Fault injection: a 2-iteration f64 schedule cannot converge any
    QP, so every feasible cell ends feasible-but-unconverged -- the
    diverged-straggler class the recorder captures."""
    rec = FlightRecorder(str(tmp_path / "bundles"))
    orc = Oracle(prob, backend="cpu", n_iter=2)
    orc.recorder = rec
    rng = np.random.default_rng(0)
    th = rng.uniform(prob.theta_lb, prob.theta_ub,
                     size=(n_points, prob.n_theta))
    ds = rng.integers(0, prob.canonical.n_delta, size=n_points)
    V, conv, *_ = orc.solve_pairs(th, ds)
    return rec, conv


def test_divergence_bundle_replays_bit_for_bit(prob, tmp_path):
    rec, conv = _short_schedule_anomaly(prob, tmp_path)
    assert not conv.any()  # the fault injection really diverges
    assert rec.bundles, "no repro bundle produced"
    meta, arrays = load_bundle(rec.bundles[0])
    assert meta["bundle_version"] == BUNDLE_VERSION
    assert meta["kind"] == "pairs"
    assert meta["trigger"] == "diverged_cells"
    assert meta["oracle"]["n_iter"] == 2
    # Everything replay needs is in the bundle: canonical matrices,
    # query, observed masks.
    for k in ("can_H", "can_G", "thetas", "delta_idx", "obs_conv",
              "obs_feas", "obs_V"):
        assert k in arrays, k

    replay_solve = _script("replay_solve")
    rep = replay_solve.replay_bundle(rec.bundles[0])
    assert rep["ok"]
    assert rep["conv_match"] and rep["conv_mismatches"] == 0
    assert rep["V_bitwise"]  # same platform, same kernel: bit-for-bit
    # CLI contract: exit 0 on a reproduced mask.
    assert replay_solve.main([rec.bundles[0]]) == 0


def test_replay_kernel_only_probe(prob, tmp_path):
    rec, _conv = _short_schedule_anomaly(prob, tmp_path)
    replay_solve = _script("replay_solve")
    rep = replay_solve.replay_bundle(rec.bundles[0], kernel_only=True)
    assert rep["kernel_only"] and rep["ok"]
    # The bare kernel under the same 2-iteration schedule agrees with
    # the pipeline's observed mask (no cohort/rescue stages existed to
    # diverge from).
    assert rep["kernel_vs_obs_conv_match"]


def test_recorder_ring_and_bundle_cap(tmp_path):
    rec = FlightRecorder(str(tmp_path / "b"), capacity=4, max_bundles=1)
    for i in range(8):
        rec.note({"kind": "event", "name": f"e{i}"})
    assert len(rec.ring) == 4  # bounded ring keeps the newest
    p1 = rec.dump("t", {"x": np.zeros(2)}, {"kind": "pairs"})
    p2 = rec.dump("t", {"x": np.zeros(2)}, {"kind": "pairs"})
    assert p1 is not None and p2 is None
    assert rec.n_dropped == 1
    meta, _arrays = load_bundle(p1)
    # The ring rides in the bundle: the obs records leading up to the
    # anomaly are part of the repro context.
    assert [r["name"] for r in meta["ring"]] == ["e4", "e5", "e6", "e7"]


def test_fault_injected_build_emits_bundle_and_replays(prob, tmp_path):
    """The CI acceptance flow: tiny build, forced-divergence oracle,
    obs='jsonl' -> a bundle exists, the stream records it, and replay
    round-trips it."""
    stream = str(tmp_path / "run.obs.jsonl")
    bdir = str(tmp_path / "repro")
    cfg = PartitionConfig(eps_a=0.3, backend="cpu", batch_simplices=32,
                          max_steps=40, max_depth=3, obs="jsonl",
                          obs_path=stream, obs_recorder=True,
                          recorder_dir=bdir)
    oracle = Oracle(prob, backend="cpu", n_iter=2)  # forced divergence
    res = build_partition(prob, cfg, oracle=oracle)
    assert res.stats["uncertified"] > 0  # nothing can certify at iters=2

    bundles = sorted(os.listdir(bdir))
    assert bundles, "fault-injected build produced no repro bundle"
    recs = load_jsonl(stream)
    ev = [r for r in recs if r.get("name") == "recorder.bundle"]
    assert ev, "no recorder.bundle event in the obs stream"
    snaps = [r for r in recs if r["kind"] == "metrics"]
    assert snaps[-1]["counters"]["recorder.bundles"] == len(ev)

    replay_solve = _script("replay_solve")
    # Replay every distinct kind produced (at least the uncertified-
    # leaf cell bundles fire under this fault injection).
    kinds = set()
    for b in bundles:
        rep = replay_solve.replay_bundle(os.path.join(bdir, b))
        kinds.add(rep["kind"])
        assert rep["ok"], rep
        if rep["kind"] == "cell":
            # The snapshot's own stage-1 decision must reproduce: the
            # cell was depth-capped, so it cannot certify.
            assert rep["snapshot_stage1_status"] != "certified"
    assert "cell" in kinds


def test_recorder_off_by_default(prob):
    cfg = PartitionConfig(eps_a=0.5, backend="cpu", batch_simplices=32)
    from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                            make_oracle)

    eng = FrontierEngine(prob, make_oracle(prob, cfg), cfg)
    assert eng.recorder is None and eng._health is None
    assert eng.oracle.recorder is None


# -- health watchdog -------------------------------------------------------

def _metrics_rec(t=1.0, counters=None, gauges=None):
    return {"t": t, "kind": "metrics", "name": "snapshot",
            "counters": counters or {}, "gauges": gauges or {}}


def test_health_rescue_storm_fires_on_counter_delta():
    mon = HealthMonitor({"min_solves_for_rates": 100})
    assert mon.feed(_metrics_rec(1.0, {"oracle.point_solves": 0,
                                       "oracle.rescue_solves": 0})) == []
    evs = mon.feed(_metrics_rec(
        2.0, {"oracle.point_solves": 1000,
              "oracle.rescue_solves": 400}))
    assert [e["name"] for e in evs] == ["health.rescue_storm"]
    assert mon.worst == "critical" and mon.exit_code == 2


def test_health_divergence_storm_and_warmstart_collapse():
    mon = HealthMonitor({"min_solves_for_rates": 100})
    evs = mon.feed(_metrics_rec(
        1.0, {"oracle.point_solves": 5000},
        {"oracle.phase2_survivor_frac": 0.99,
         "oracle.warmstart_accept_rate": 0.001,
         "oracle.warm_attempts": 5000}))
    names = {e["name"] for e in evs}
    assert names == {"health.divergence_storm",
                     "health.warmstart_collapse"}
    assert mon.worst == "critical"


def test_health_warmstart_rule_needs_attempts():
    """Accept rate 0.0 with zero attempts means warm-starts are OFF,
    not collapsed: no event."""
    mon = HealthMonitor({"min_solves_for_rates": 100})
    evs = mon.feed(_metrics_rec(
        1.0, {"oracle.point_solves": 5000},
        {"oracle.warmstart_accept_rate": 0.0,
         "oracle.warm_attempts": 0}))
    assert evs == []


def test_health_shard_imbalance_and_contention_warn():
    mon = HealthMonitor()
    evs = mon.feed(_metrics_rec(
        1.0, gauges={"serve.shard_imbalance": 20.0,
                     "host.competing_cpu_frac_mean": 0.5}))
    assert {e["name"] for e in evs} == {"health.shard_imbalance",
                                       "health.host_contended"}
    assert mon.worst == "warn" and mon.exit_code == 1


def test_health_throughput_floor_and_refire_cooldown():
    mon = HealthMonitor({"min_regions_per_s": 100.0, "window_steps": 3},
                        refire_after=1000)
    evs = []
    for k in range(6):
        evs += mon.feed({"t": float(k), "kind": "event",
                         "name": "build.step", "regions": 10 * k})
    assert [e["name"] for e in evs] == ["health.throughput_low"]
    # Cooldown: the rule keeps triggering but emits one event.
    assert len(mon.events) == 1


def test_health_cooldown_refires_on_persistent_condition():
    """A persistent condition re-notifies once per refire_after fed
    records -- the cooldown must not be refreshed by suppressed
    triggers (that would silence the rest of the episode)."""
    mon = HealthMonitor({"max_shard_imbalance": 1.5}, refire_after=3)
    for k in range(7):
        mon.feed(_metrics_rec(float(k),
                              gauges={"serve.shard_imbalance": 9.0}))
    # Events at feeds 0, 3, 6 (cooldown 3, ticked once per feed).
    assert len(mon.events) == 3


def test_health_device_failure_rule():
    mon = HealthMonitor({"max_device_failures": 0})
    evs = mon.feed({"t": 1.0, "kind": "event", "name": "runlog",
                    "device_failure": "XlaRuntimeError('dead tunnel')",
                    "query": "solve_vertices"})
    assert [e["name"] for e in evs] == ["health.device_failures"]


def test_health_rules_validated():
    with pytest.raises(ValueError, match="unknown health rule"):
        rules_from_pairs([("bogus_rule", 1.0)])
    with pytest.raises(ValueError, match="unknown health rule"):
        PartitionConfig(health_rules=(("bogus_rule", 1.0),))
    assert rules_from_pairs([("stall_s", 5.0)])["stall_s"] == 5.0
    assert set(rules_from_pairs({})) == set(DEFAULT_RULES)


def test_health_events_land_in_sink():
    from explicit_hybrid_mpc_tpu import obs as obs_lib

    o = obs_lib.Obs("jsonl")
    mon = HealthMonitor({"max_shard_imbalance": 1.5}, sink=o.sink)
    mon.feed(_metrics_rec(1.0, gauges={"serve.shard_imbalance": 3.0}))
    recs = [r for r in o.sink.records
            if r["name"] == "health.shard_imbalance"]
    assert len(recs) == 1 and recs[0]["severity"] == "warn"


def test_engine_in_stream_health(prob, tmp_path):
    """cfg.health_rules + obs: the engine itself feeds the monitor and
    health.* events land in the build's own stream."""
    stream = str(tmp_path / "h.obs.jsonl")
    cfg = PartitionConfig(
        eps_a=0.5, backend="cpu", batch_simplices=32, obs="jsonl",
        obs_path=stream,
        # Impossible throughput floor over a tiny window: fires on any
        # real build, proving the in-stream wiring end to end.
        health_rules=(("min_regions_per_s", 1e9),
                      ("window_steps", 3),
                      ("metrics_every_steps", 2)))
    build_partition(prob, cfg)
    recs = load_jsonl(stream)
    assert any(r.get("name") == "health.throughput_low" for r in recs)
    # The periodic in-build snapshots are in the stream too (beyond the
    # single close-time snapshot).
    assert sum(r["kind"] == "metrics" for r in recs) >= 2


def test_engine_feeds_device_failures_to_health(prob):
    """The device_failure RunLog records go to the legacy stream the
    monitor never reads; the engine must feed them directly or the
    max_device_failures rule can never fire in-build."""
    from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                            make_oracle)

    cfg = PartitionConfig(eps_a=0.5, backend="cpu", batch_simplices=32,
                          obs="jsonl",
                          health_rules=(("max_device_failures", 0),))
    eng = FrontierEngine(prob, make_oracle(prob, cfg), cfg)
    assert eng._health is not None
    eng._health_device_failure(RuntimeError("dead tunnel"))
    assert [e["name"] for e in eng._health.events] == \
        ["health.device_failures"]


def test_obs_watch_stall_on_frozen_stream(tmp_path):
    """Acceptance: a stream that stops growing raises health.stall and
    the watcher exits critical."""
    obs_watch = _script("obs_watch")
    path = str(tmp_path / "frozen.obs.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"t": 0.0, "kind": "meta", "name": "schema",
                            "version": 1}) + "\n")
        f.write(json.dumps({"t": 1.0, "kind": "event",
                            "name": "build.step", "step": 1,
                            "regions": 10}) + "\n")
    out = io.StringIO()
    rc, mon = obs_watch.watch(path, rules={"stall_s": 0.3},
                              interval=0.05, max_wall=5.0, out=out)
    assert rc == 2
    assert any(e["name"] == "health.stall" for e in mon.events)
    emitted = [json.loads(ln) for ln in
               out.getvalue().strip().splitlines()]
    assert emitted and emitted[-1]["name"] == "health.stall"


def test_obs_watch_once_mode_healthy(tmp_path):
    obs_watch = _script("obs_watch")
    path = str(tmp_path / "ok.obs.jsonl")
    with open(path, "w") as f:
        for k in range(3):
            f.write(json.dumps({"t": float(k), "kind": "event",
                                "name": "build.step", "step": k,
                                "regions": 10 * k}) + "\n")
    rc, mon = obs_watch.watch(path, once=True, out=io.StringIO())
    assert rc == 0 and mon.worst == "ok" and mon.n_records == 3


def test_obs_watch_cli_once(tmp_path, capsys):
    obs_watch = _script("obs_watch")
    path = str(tmp_path / "bad.obs.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(_metrics_rec(
            1.0, gauges={"serve.shard_imbalance": 99.0})) + "\n")
    rc = obs_watch.main([path, "--once"])
    assert rc == 1  # warn-level verdict
    out = capsys.readouterr().out
    assert "health.shard_imbalance" in out


# -- obs_report warnings + --strict (ISSUE 4 satellites) -------------------

def _mini_stream(tmp_path, gauges=None):
    from explicit_hybrid_mpc_tpu.obs.sink import JsonlSink

    path = str(tmp_path / "mini.obs.jsonl")
    with JsonlSink(path, schema_meta=True) as s:
        s.emit("event", "build.step", step=1, regions=100,
               frontier=0, device_frac=0.5)
        s.emit("metrics", "snapshot", counters={}, histograms={},
               gauges=gauges or {})
    return path


def test_obs_report_renders_contention_and_probe_warnings(tmp_path,
                                                          capsys):
    obs_report = _script("obs_report")
    stream = _mini_stream(tmp_path, gauges={
        "host.contended": 1.0,
        "host.competing_cpu_frac_mean": 0.42,
        "host.competing_cpu_frac_max": 0.9})
    bench_path = str(tmp_path / "BENCH_x.json")
    with open(bench_path, "w") as f:
        json.dump({"value": 1.0,
                   "backend_probe_error": "probe timed out after 180s",
                   "host": {"contended": True,
                            "competing_cpu_frac_mean": 0.3}}, f)
    rc = obs_report.main([stream, "--bench", bench_path])
    out = capsys.readouterr().out
    assert rc == 0  # no regression flags, warnings alone never gate
    assert "WARNING" in out
    assert "CONTENDED" in out
    assert "probe timed out" in out


def test_obs_report_strict_exits_nonzero_on_flags(tmp_path, capsys):
    obs_report = _script("obs_report")
    stream = _mini_stream(tmp_path)
    fast_bench = str(tmp_path / "BENCH_fast.json")
    with open(fast_bench, "w") as f:
        json.dump({"value": 1e9}, f)  # absurdly fast bench -> regression
    assert obs_report.main([stream, "--bench", fast_bench]) == 0
    rc = obs_report.main([stream, "--bench", fast_bench, "--strict"])
    assert rc == 1
    assert "REGRESSION" in capsys.readouterr().out


def test_obs_report_surfaces_health_events_and_bundles(tmp_path, capsys):
    from explicit_hybrid_mpc_tpu.obs.sink import JsonlSink

    obs_report = _script("obs_report")
    path = str(tmp_path / "h.obs.jsonl")
    with JsonlSink(path, schema_meta=True) as s:
        s.emit("event", "health.divergence_storm", severity="critical",
               value=0.99, threshold=0.95, msg="storm")
        s.emit("metrics", "snapshot",
               counters={"recorder.bundles": 2}, gauges={},
               histograms={})
    rc = obs_report.main([path])
    out = capsys.readouterr().out
    assert rc == 0
    assert "health.divergence_storm" in out
    assert "2 repro bundle(s)" in out


def test_obs_report_drift_flags_compiled_shape_growth(tmp_path, capsys):
    """--drift (ISSUE 6): oracle.compiled_shapes growth between two
    streams is a recompile regression; shrinkage is not."""
    from explicit_hybrid_mpc_tpu.obs.sink import JsonlSink

    obs_report = _script("obs_report")

    def stream(name, shapes):
        path = str(tmp_path / name)
        with JsonlSink(path, schema_meta=True) as s:
            s.emit("metrics", "snapshot", counters={}, histograms={},
                   gauges={"oracle.compiled_shapes": float(shapes)})
        return path

    old = stream("old.obs.jsonl", 40)
    grown = stream("grown.obs.jsonl", 52)
    no_bench = ["--bench", str(tmp_path / "missing.json")]
    rc = obs_report.main([grown, "--drift", old] + no_bench)
    out = capsys.readouterr().out
    assert rc == 0  # advisory without --strict
    assert "compiled-shape growth" in out and "52" in out
    rc = obs_report.main([grown, "--drift", old, "--strict"] + no_bench)
    capsys.readouterr()
    assert rc == 1
    # Fewer shapes than before: directional, not a regression.
    rc = obs_report.main([old, "--drift", grown, "--strict"] + no_bench)
    out = capsys.readouterr().out
    assert rc == 0 and "compiled-shape drift" in out


# -- bench regression gate -------------------------------------------------

def _bench(value, platform="cpu", **kw):
    return {"value": value, "platform": platform, "unit": "regions/s",
            **kw}


def test_bench_gate_flags_synthetic_regression(tmp_path):
    bench_gate = _script("bench_gate")
    hist = str(tmp_path / "BENCH_HISTORY.jsonl")
    for i, v in enumerate([98.0, 101.0, 100.0]):
        assert bench_gate.append_history(
            _bench(v), f"BENCH_r{i:02d}.json", path=hist,
            mtime=float(i)) is not None
    history = bench_gate.load_history(hist)
    assert len(history) == 3

    # >=10% regions/sec drop: flagged (the acceptance threshold).
    cand = bench_gate.summarize(_bench(85.0), "BENCH_new.json")
    flags, _info = bench_gate.gate(cand, history)
    assert any("value" in f and "REGRESSION" in f for f in flags)
    # Within tolerance: clean.
    cand = bench_gate.summarize(_bench(95.0), "BENCH_new.json")
    flags, info = bench_gate.gate(cand, history)
    assert flags == [] and any(line.startswith("ok value") for line in info)
    # A faster run is never a regression.
    flags, _ = bench_gate.gate(
        bench_gate.summarize(_bench(140.0), "BENCH_new.json"), history)
    assert flags == []


def test_bench_gate_iteration_economy_and_latency_directions(tmp_path):
    bench_gate = _script("bench_gate")
    hist = str(tmp_path / "h.jsonl")
    for i in range(3):
        bench_gate.append_history(
            _bench(100.0, wasted_iter_frac=0.27,
                   warmstart_accept_rate=0.5, online_us_per_query=1.0),
            f"BENCH_r{i:02d}.json", path=hist, mtime=float(i))
    history = bench_gate.load_history(hist)
    cand = bench_gate.summarize(
        _bench(100.0, wasted_iter_frac=0.10,       # lower = worse
               warmstart_accept_rate=0.1,          # lower = worse
               online_us_per_query=2.0),           # higher = worse
        "BENCH_new.json")
    flags, _ = bench_gate.gate(cand, history)
    flagged = {f.split()[1].rstrip(":") for f in flags}
    assert flagged == {"wasted_iter_frac", "warmstart_accept_rate",
                       "online_us_per_query"}


def test_bench_gate_skips_contended_and_foreign_platform(tmp_path):
    bench_gate = _script("bench_gate")
    hist = str(tmp_path / "h.jsonl")
    bench_gate.append_history(_bench(500.0, platform="tpu"),
                              "BENCH_tpu.json", path=hist, mtime=0.0)
    bench_gate.append_history(
        _bench(100.0, host={"contended": True}), "BENCH_bad.json",
        path=hist, mtime=1.0)
    history = bench_gate.load_history(hist)
    # Only a TPU row and a contended CPU row: no comparable base for a
    # clean CPU candidate -> vacuous pass, explained.
    flags, info = bench_gate.gate(
        bench_gate.summarize(_bench(10.0), "BENCH_new.json"), history)
    assert flags == [] and any("no comparable history" in s for s in info)
    # A contended CANDIDATE gates nothing either.
    flags, info = bench_gate.gate(
        bench_gate.summarize(_bench(10.0, host={"contended": True}),
                             "BENCH_new.json"), history)
    assert flags == [] and any("CONTENDED" in s for s in info)


def test_bench_gate_candidate_never_in_its_own_base(tmp_path):
    """EVERY history row sharing the candidate's source is excluded
    (bench.py appends a row for the capture before the gate runs; a
    candidate compared against itself would wash out any regression)."""
    bench_gate = _script("bench_gate")
    hist = str(tmp_path / "h.jsonl")
    for i in range(3):
        bench_gate.append_history(_bench(100.0), f"BENCH_r{i:02d}.json",
                                  path=hist, mtime=float(i))
    # The candidate's own row, appended by bench.py with a slightly
    # different mtime key than the gate would compute.
    bench_gate.append_history(_bench(80.0), "BENCH_new.json",
                              path=hist, mtime=99.0)
    cand = bench_gate.summarize(_bench(80.0), "BENCH_new.json",
                                mtime=99.5)
    flags, _ = bench_gate.gate(cand, bench_gate.load_history(hist))
    # 20% below the 100-mean window: flagged despite its own row
    # sitting in the history under the same source name.
    assert any(f.startswith("REGRESSION value") for f in flags)


def test_bench_gate_skips_valueless_captures(tmp_path):
    """A failed capture (driver wrapper with parsed: null, or a result
    with neither value nor error) must not become a clean all-null
    history row."""
    bench_gate = _script("bench_gate")
    hist = str(tmp_path / "h.jsonl")
    assert bench_gate.append_history({"rc": 1, "parsed": None,
                                      "tail": "boom"},
                                     "BENCH_broken.json", path=hist) is None
    assert bench_gate.append_history(
        {"value": None, "platform": "cpu"}, "BENCH_void.json",
        path=hist) is None
    # Errored captures ARE recorded (the error field documents them and
    # the gate's comparable filter excludes them).
    assert bench_gate.append_history(
        {"value": None, "error": "RuntimeError('x')"},
        "BENCH_err.json", path=hist) is not None
    assert len(bench_gate.load_history(hist)) == 1


def test_recorder_dir_implies_recorder(prob, tmp_path):
    """Naming a bundle directory activates the recorder at the config
    layer too, not just through the CLI flag pair."""
    from explicit_hybrid_mpc_tpu.partition.frontier import (FrontierEngine,
                                                            make_oracle)

    cfg = PartitionConfig(eps_a=0.5, backend="cpu", batch_simplices=32,
                          recorder_dir=str(tmp_path / "b"))
    eng = FrontierEngine(prob, make_oracle(prob, cfg), cfg)
    assert eng.recorder is not None
    assert eng.recorder.out_dir == str(tmp_path / "b")


def test_bench_gate_roll_and_cli(tmp_path):
    bench_gate = _script("bench_gate")
    repo = tmp_path / "repo"
    repo.mkdir()
    for i, v in enumerate([100.0, 102.0]):
        with open(repo / f"BENCH_r{i:02d}.json", "w") as f:
            json.dump(_bench(v), f)
        os.utime(repo / f"BENCH_r{i:02d}.json", (i + 1, i + 1))
    hist = str(repo / "BENCH_HISTORY.jsonl")
    added = bench_gate.roll_history(str(repo), hist)
    assert [r["source"] for r in added] == ["BENCH_r00.json",
                                           "BENCH_r01.json"]
    assert bench_gate.roll_history(str(repo), hist) == []  # idempotent

    cand = repo / "BENCH_new.json"
    with open(cand, "w") as f:
        json.dump(_bench(80.0), f)  # 20% down vs the 101 mean
    rc = bench_gate.main([str(cand), "--history", hist])
    assert rc == 1
    with open(cand, "w") as f:
        json.dump(_bench(99.0), f)
    assert bench_gate.main([str(cand), "--history", hist]) == 0
