"""Inverted-pendulum hybrid benchmark: canonicalization, oracle
enumeration soundness, PWA continuity at the wall, and a partition build.
"""

import numpy as np
import pytest
from scipy.optimize import minimize

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def pend():
    return make("inverted_pendulum", N=3)  # 8 commutations: fast tests


@pytest.fixture(scope="module")
def oracle(pend):
    return Oracle(pend, backend="cpu")


def _scipy_fixed_delta(can, d, theta):
    """Ground-truth fixed-commutation solve via scipy SLSQP."""
    H, f, F = can.H[d], can.f[d], can.F[d]
    G, w, S = can.G[d], can.w[d], can.S[d]
    q = f + F @ theta
    b = w + S @ theta
    res = minimize(
        lambda z: 0.5 * z @ H @ z + q @ z, np.zeros(can.nz),
        jac=lambda z: H @ z + q, method="SLSQP",
        constraints=[{"type": "ineq", "fun": lambda z: b - G @ z,
                      "jac": lambda z: -G}],
        options={"maxiter": 300, "ftol": 1e-12})
    if not res.success:
        return None
    theta_cost = (0.5 * theta @ can.Y[d] @ theta + can.pvec[d] @ theta
                  + can.cconst[d])
    return res.fun + theta_cost


def test_canonical_shapes(pend):
    can = pend.canonical
    assert can.n_delta == 8
    assert can.nz == 3
    assert can.deltas.shape == (8, 3)
    # Commutation 0 = all-free; its mode rows force th_k <= 0.
    assert np.all(np.linalg.eigvalsh(can.H.reshape(-1, 3, 3)) > 0)


def test_mode_membership_excludes_wrong_side(oracle):
    """Deep in the free region, every delta starting with mode 1 must be
    infeasible (its theta_con row demands th >= 0)."""
    sol = oracle.solve_vertices(np.array([[-0.3, 0.0]]))
    deltas = oracle.can.deltas
    first_mode = deltas[:, 0]
    assert not np.any(sol.conv[0, first_mode == 1] &
                      np.isfinite(sol.V[0, first_mode == 1]))
    assert np.isfinite(sol.Vstar[0])
    assert first_mode[sol.dstar[0]] == 0


def test_enumeration_matches_scipy(oracle, pend, rng):
    """V* = min over scipy-solved fixed-delta QPs at sample points."""
    can = pend.canonical
    thetas = rng.uniform(pend.theta_lb, pend.theta_ub, size=(4, 2))
    sol = oracle.solve_vertices(thetas)
    for k, th in enumerate(thetas):
        vals = [_scipy_fixed_delta(can, d, th) for d in range(can.n_delta)]
        vals = [v for v in vals if v is not None]
        assert vals, "scipy found no feasible commutation"
        ref = min(vals)
        assert np.isfinite(sol.Vstar[k])
        np.testing.assert_allclose(sol.Vstar[k], ref, rtol=1e-5, atol=1e-7)


def test_value_continuity_at_wall(oracle):
    """The PWA field is continuous at th = 0, so V* must be too."""
    eps = 1e-6
    for w in (-0.5, 0.0, 0.5):
        pair = np.array([[-eps, w], [eps, w]])
        sol = oracle.solve_vertices(pair)
        assert np.all(np.isfinite(sol.Vstar))
        np.testing.assert_allclose(sol.Vstar[0], sol.Vstar[1],
                                   rtol=1e-4, atol=1e-6)


def test_partition_build_certifies(pend):
    cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                          backend="cpu", batch_simplices=64, max_steps=400)
    res = build_partition(pend, cfg)
    assert res.stats["regions"] > 0
    assert not res.stats["truncated"]
    assert res.stats["uncertified"] == 0
