"""Constraint pruning with KKT-verified fallback (oracle/prune.py).

Correctness contract: PrunedOracle is EXACT -- verified instances
satisfy the full problem's KKT system, violators re-solve on the full
program -- so values, gradients, first moves, and the produced
partition must match the plain Oracle's.
"""

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.oracle.oracle import Oracle
from explicit_hybrid_mpc_tpu.oracle.prune import PrunedOracle
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def quad():
    # The BENCHMARK config (BASELINE.md row 5b: N=10, nz=60, nc=360 on
    # the 10% pv sub-box) -- the regime the verdict's 5x ask is about;
    # smaller horizons have too few rows for pruning ratios to mean
    # anything, and on the FULL box the obstacle rows are live so the
    # slack vars legitimately stay.
    return make("quadrotor", pos_box=0.4, vel_box=0.2)


@pytest.fixture(scope="module")
def full(quad):
    return Oracle(quad, backend="cpu")


@pytest.fixture(scope="module")
def pruned(quad):
    return PrunedOracle(quad, backend="cpu")


@pytest.fixture(scope="module")
def points(quad):
    rng = np.random.default_rng(3)
    return rng.uniform(quad.theta_lb, quad.theta_ub,
                       size=(12, quad.n_theta))


def test_rows_actually_pruned(pruned):
    kept = pruned.row_keep.sum(axis=1)
    assert kept.max() < pruned.can.nc / 2, (
        f"pruning kept {kept.max()}/{pruned.can.nc} rows -- no win")
    # Slack vars drop for commutations whose chosen obstacle faces agree
    # with the sub-box (soft rows inactive); wrong-face commutations pay
    # the penalty with ACTIVE slacks and legitimately keep theirs.
    assert pruned.var_keep.sum(axis=1).min() < pruned.can.nz


def test_vertex_grid_matches_full(full, pruned, points):
    a = full.solve_vertices(points)
    b = pruned.solve_vertices(points)
    np.testing.assert_array_equal(a.dstar, b.dstar)
    np.testing.assert_allclose(b.Vstar, a.Vstar, rtol=1e-6, atol=1e-8)
    m = a.conv & b.conv
    np.testing.assert_allclose(b.V[m], a.V[m], rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(b.grad[m], a.grad[m], rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(b.u0[m], a.u0[m], rtol=1e-5, atol=1e-7)


def test_pairs_match_full(full, pruned, points, quad):
    nd = quad.canonical.n_delta
    ds = (np.arange(len(points)) % nd).astype(np.int64)
    Va, conva, grada, u0a, _za = full.solve_pairs(points, ds)
    Vb, convb, gradb, u0b, zb = pruned.solve_pairs(points, ds)
    m = conva & convb
    assert m.any()
    np.testing.assert_allclose(Vb[m], Va[m], rtol=1e-6, atol=1e-8)
    np.testing.assert_allclose(gradb[m], grada[m], rtol=1e-4, atol=1e-6)
    assert zb.shape[-1] == quad.canonical.nz  # full-width primal out


def test_all_dropped_still_exact(quad, full, points):
    """margin < 0 drops EVERY row: the reduced solve is unconstrained,
    verification fails everywhere, and the fallback must still produce
    the full answers (the stress case for the fallback path)."""
    harsh = PrunedOracle(quad, backend="cpu", margin=-1.0)
    a = full.solve_vertices(points[:4])
    b = harsh.solve_vertices(points[:4])
    assert harsh.n_prune_fallbacks > 0
    np.testing.assert_array_equal(a.dstar, b.dstar)
    np.testing.assert_allclose(b.Vstar, a.Vstar, rtol=1e-6, atol=1e-8)


def test_partition_parity_with_pruning():
    """The pruned build must produce the plain build's partition."""
    quad2 = make("quadrotor", N=3, param="p")
    cfg = PartitionConfig(problem="quadrotor", eps_a=0.05, eps_r=0.5,
                          backend="cpu", batch_simplices=128,
                          max_steps=800, max_depth=12)
    plain = build_partition(quad2, cfg)
    pruned = build_partition(
        quad2, PartitionConfig(**{**cfg.__dict__, "prune_rows": True}))
    assert pruned.stats["regions"] == plain.stats["regions"]
    assert pruned.stats["tree_nodes"] == plain.stats["tree_nodes"]
    assert not pruned.stats["truncated"]
    assert pruned.stats["uncertified"] == 0


def test_serial_backend_rejected(quad):
    with pytest.raises(ValueError, match="batched single-device"):
        PrunedOracle(quad, backend="serial")


def test_stalled_gate_directions():
    """ADVICE r4 (low): stalled (~feas & ~conv) reduced cells must not be
    trusted as infeasible-on-full unchecked.  The reduced phase-1 gate
    (_stalled_need_resolve) must always demand a re-solve for cells that
    are actually feasible (the sound direction), while certifying
    decisively infeasible cells without a full re-solve (the win).

    margin=1e9 keeps every row, making reduced == full: the gate is only
    ever invoked on cells that stalled on the REDUCED problem, so the
    certify-infeasible direction needs the infeasibility-carrying rows
    present in the reduced set (a default-margin oracle may DROP exactly
    those rows -- such cells then converge reduced-feasible and are
    caught by the dropped-row violation check instead)."""
    ms = make("mass_spring", N=4, theta_box=3.0)
    po = PrunedOracle(ms, backend="cpu", margin=1e9)
    rng = np.random.default_rng(7)
    # Interior points are feasible; near-corner points violate the
    # input-constrained horizon QP decisively (test_boundary's box).
    inner = rng.uniform(-0.5, 0.5, size=(12, ms.n_theta))
    sgn = rng.choice([-1.0, 1.0], size=(16, ms.n_theta))
    corners = sgn * rng.uniform(2.7, 3.0, size=(16, ms.n_theta))
    full = Oracle(ms, backend="cpu")
    sol_in = full.solve_vertices(inner)
    sol_co = full.solve_vertices(corners)
    ok = (sol_in.conv & sol_in.feas)[:, 0]
    bad = (~sol_co.feas & ~sol_co.conv)[:, 0]
    assert ok.any() and bad.any(), "box must straddle feasibility"
    d0 = np.zeros(int(ok.sum()), dtype=np.int64)
    need = po._stalled_need_resolve(inner[ok], d0)
    assert need.all(), "gate certified a FEASIBLE cell infeasible"
    d0 = np.zeros(int(bad.sum()), dtype=np.int64)
    need_i = po._stalled_need_resolve(corners[bad], d0)
    assert not need_i.all(), "gate never certifies -- pruning win erased"
