"""Bounded-memory export at cluster scale (slow) + synthetic-tree
builder fidelity (fast).

The slow test is the acceptance check for the streaming export: a
>= 1M-leaf synthetic tree exports through write_leaf_table with peak
ADDITIONAL RSS bounded well under the O(L) table size, inside a wall
ceiling -- the regression guard for the 94.8 GB-peak in-RAM export at
the 9.8M-leaf satellite (commit 0ff2285)."""

import resource
import time

import numpy as np

from explicit_hybrid_mpc_tpu.online import descent, export
from explicit_hybrid_mpc_tpu.partition import geometry
from explicit_hybrid_mpc_tpu.partition.synthetic import (
    build_synthetic_tree, leaf_payload)
from explicit_hybrid_mpc_tpu.partition.tree import LeafData, Tree


def test_synthetic_tree_matches_split_loop():
    """Vectorized builder fidelity: bit-identical to the same tree grown
    through geometry.bisect + Tree.split + Tree.set_leaf, including the
    split-time hyperplane columns -- so scale results on synthetic
    trees transfer to engine-built ones."""
    p, depth, n_u = 2, 5, 2
    t_vec, roots = build_synthetic_tree(p=p, depth=depth, n_u=n_u)
    t_loop = Tree(p=p, n_u=n_u)
    frontier = [t_loop.add_root(V) for V in
                geometry.box_triangulation(np.zeros(p), np.ones(p))]
    assert frontier == roots
    for _ in range(depth):
        nxt = []
        for n in frontier:
            left, right, i, j, _ = geometry.bisect(t_loop.vertices[n])
            nxt.extend(t_loop.split(n, left, right, (i, j)))
        frontier = nxt
    for n in frontier:
        U, c = leaf_payload(t_loop.vertices[n][None], n_u)
        t_loop.set_leaf(n, LeafData(delta_idx=0, vertex_inputs=U[0],
                                    vertex_costs=c[0]))
    assert len(t_vec) == len(t_loop)
    assert t_vec.max_depth() == t_loop.max_depth() == depth
    np.testing.assert_array_equal(t_vec.vertices, t_loop.vertices)
    np.testing.assert_array_equal(t_vec.children, t_loop.children)
    np.testing.assert_array_equal(t_vec.parent, t_loop.parent)
    np.testing.assert_array_equal(t_vec.split_edge, t_loop.split_edge)
    np.testing.assert_array_equal(t_vec.split_normals,
                                  t_loop.split_normals)
    np.testing.assert_array_equal(t_vec.split_offsets,
                                  t_loop.split_offsets)
    ids = t_vec.converged_leaf_ids()
    np.testing.assert_array_equal(ids, t_loop.converged_leaf_ids())
    for a, b in zip(t_vec.leaf_payloads(ids), t_loop.leaf_payloads(ids)):
        np.testing.assert_array_equal(a, b)


def test_split_rejects_perturbed_inherited_rows():
    """Tree.split must reject children whose midpoints are right but
    whose inherited rows differ from the parent's (ADVICE r5: such a
    caller would silently corrupt _rederive_vertices on load)."""
    import pytest

    t = Tree(p=2, n_u=1)
    V = np.array([[0.0, 0.0], [1.0, 0.0], [0.0, 1.0]])
    r = t.add_root(V)
    left, right, i, j, _ = geometry.bisect(V)
    bad = left.copy()
    keep = next(k for k in range(3) if k not in (i, j))
    bad[keep] += 1e-9
    with pytest.raises(ValueError, match="inherit"):
        t.split(r, bad, right, (i, j))
    # The untouched bisection still splits fine.
    li, ri = t.split(r, left, right, (i, j))
    assert (li, ri) == (1, 2)


def test_million_leaf_export_bounded_rss_and_wall():
    """Slow acceptance check: chunked memmap export of a >= 1M-leaf
    tree costs O(chunk) additional RSS (<= 2 GB asserted, measured
    ~10 MB) and finishes inside a generous wall ceiling; the streamed
    table is spot-check-identical to direct payload reads, and the
    split-time descent export is available in seconds, not minutes."""
    tree, roots = build_synthetic_tree(p=2, depth=19)  # 1,048,576 leaves
    assert tree.n_regions() >= 1_000_000
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        t0 = time.perf_counter()
        export.write_leaf_table(tree, td)
        wall = time.perf_counter() - t0
        rss1 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KiB on linux; additional peak must stay <= 2 GB
        # (the full bary_M alone is ~72 MB at p=2 -- the bound has
        # headroom ONLY if export never materializes O(L) transients).
        assert (rss1 - rss0) <= 2 * 1024 * 1024, (rss0, rss1)
        assert wall < 120.0, wall
        table = export.load_leaf_table(td)
        assert table.n_leaves == tree.n_regions()
        ids = tree.converged_leaf_ids()
        for k in (0, table.n_leaves // 2, table.n_leaves - 1):
            np.testing.assert_array_equal(
                table.bary_M[k],
                geometry.barycentric_matrix(tree.vertices[ids[k]]))
        t0 = time.perf_counter()
        dt = descent.export_descent(tree, roots, table, stage=False)
        assert time.perf_counter() - t0 < 30.0
        assert np.asarray(dt.leaf_row).max() == table.n_leaves - 1
