"""Post-processing: partition stats invariants, runlog reports, figures."""

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.post import (load_runlog, partition_report,
                                          runtime_report)
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def built(tmp_path_factory):
    log = str(tmp_path_factory.mktemp("post") / "run.jsonl")
    prob = make("inverted_pendulum", N=3)
    cfg = PartitionConfig(problem="inverted_pendulum", eps_a=0.5,
                          backend="cpu", batch_simplices=64,
                          max_steps=400, log_path=log)
    res = build_partition(prob, cfg)
    return prob, res, log


def test_partition_report_invariants(built):
    prob, res, _ = built
    rep = partition_report(res.tree, res.roots)
    assert rep["n_regions"] == res.stats["regions"]
    assert rep["n_nodes"] == len(res.tree)
    # Certified volume fraction: complete non-truncated hybrid build may
    # keep infeasible cells, but coverage must be substantial and <= 1.
    assert 0.5 < rep["volume_certified_frac"] <= 1.0 + 1e-9
    assert rep["depth_max"] == res.stats["max_depth"]
    assert sum(rep["depth_hist"]) == rep["n_regions"]
    # Both PWA modes appear among leaf commutations.
    assert len(rep["regions_per_delta"]) >= 2


def test_volume_exactly_tiles_for_pure_qp():
    """Single-commutation problem: every leaf certifies, so certified
    volume == root volume exactly."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.3, backend="cpu", batch_simplices=64)
    res = build_partition(prob, cfg)
    rep = partition_report(res.tree, res.roots)
    np.testing.assert_allclose(rep["volume_certified_frac"], 1.0,
                               rtol=1e-9)


def test_runtime_report(built):
    _, res, log = built
    recs = load_runlog(log)
    rep = runtime_report(recs)
    assert rep["n_steps"] == res.stats["steps"]
    assert rep["regions_final"] == res.stats["regions"]
    assert rep["regions_per_s_overall"] > 0
    assert rep["final_stats"]["regions"] == res.stats["regions"]


def test_figures_render(built, tmp_path):
    prob, res, log = built
    from explicit_hybrid_mpc_tpu.post import figures

    f1 = figures.plot_partition_2d(res.tree,
                                   save=str(tmp_path / "part.png"))
    assert (tmp_path / "part.png").stat().st_size > 0
    f2 = figures.plot_runtime(load_runlog(log),
                              save=str(tmp_path / "rt.png"))
    assert (tmp_path / "rt.png").stat().st_size > 0

    from explicit_hybrid_mpc_tpu.online import export
    from explicit_hybrid_mpc_tpu.sim import simulator

    table = export.export_leaves(res.tree)
    sim = simulator.simulate(prob, simulator.ExplicitController(table),
                             np.array([0.3, 0.5]), T=10)
    f3 = figures.plot_closed_loop({"explicit": sim},
                                  save=str(tmp_path / "cl.png"))
    assert (tmp_path / "cl.png").stat().st_size > 0
    import matplotlib.pyplot as plt
    plt.close("all")
