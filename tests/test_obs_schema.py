"""End-to-end obs schema smoke (ISSUE 2 CI satellite): a short
double-integrator build + sharded serving must emit a schema-valid
JSONL stream that scripts/obs_report.py can render, and the obs=off
hook cost must stay under 1% of build wall (overhead test, slow tier).
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from explicit_hybrid_mpc_tpu import obs as obs_lib
from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.obs.sink import SCHEMA_VERSION, load_jsonl
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def obs_stream(tmp_path_factory):
    """One short build + 10k sharded queries, streamed to JSONL."""
    path = str(tmp_path_factory.mktemp("obs") / "run.obs.jsonl")
    o = obs_lib.Obs("jsonl", path=path)
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.3, backend="cpu", batch_simplices=64)
    res = build_partition(prob, cfg, obs=o)
    assert res.stats["regions"] > 0

    from explicit_hybrid_mpc_tpu.online import descent, export, sharded

    table = export.export_leaves(res.tree)
    dt = descent.export_descent(res.tree, res.roots, table, stage=False,
                                obs=o)
    srv = sharded.shard_descent(dt, table, n_shards=4, obs=o)
    rng = np.random.default_rng(0)
    for _ in range(10):
        out = srv.evaluate(
            rng.uniform(-1.5, 1.5, size=(1000, prob.n_theta)))
        assert out.u.shape == (1000, prob.n_u)
    o.close()
    return path, res


def test_stream_parses_and_every_record_has_envelope(obs_stream):
    path, _res = obs_stream
    recs = load_jsonl(path)
    assert len(recs) > 10
    for r in recs:
        assert "t" in r and "kind" in r and "name" in r, r
        assert r["t"] >= 0.0
        assert r["kind"] in ("meta", "span", "event", "metrics")
    assert recs[0] == {"t": recs[0]["t"], "kind": "meta",
                      "name": "schema", "version": SCHEMA_VERSION}


def test_histogram_bucket_counts_sum_to_total(obs_stream):
    path, _res = obs_stream
    recs = load_jsonl(path)
    snaps = [r for r in recs if r["kind"] == "metrics"]
    assert snaps, "no metrics snapshot in the stream"
    hists = snaps[-1]["histograms"]
    assert hists, "no histograms recorded"
    for name, h in hists.items():
        assert len(h["counts"]) == len(h["bounds"]) + 1, name
        assert sum(h["counts"]) == h["count"], name


def test_all_three_layers_recorded(obs_stream):
    """Build, oracle, and serving must all land in ONE registry."""
    path, res = obs_stream
    snap = [r for r in load_jsonl(path) if r["kind"] == "metrics"][-1]
    c, g, h = snap["counters"], snap["gauges"], snap["histograms"]
    # build layer
    assert c["build.steps"] == res.stats["steps"]
    assert c["build.leaves"] == res.stats["regions"]
    assert c["build.oracle_solves"] == res.stats["oracle_solves"]
    assert g["build.regions"] == res.stats["regions"]
    assert h["build.step_s"]["count"] == res.stats["steps"]
    # oracle layer (wired through the engine automatically)
    assert c["oracle.point_solves"] == res.stats["point_solves"]
    assert c["oracle.ipm_iters"] > 0
    assert h["oracle.point_solve_s"]["count"] > 0
    # serving layer
    assert c["serve.queries"] == 10_000
    assert g["serve.shards"] == 4
    assert g["serve.shard_imbalance"] >= 1.0
    shard_hists = [k for k in h
                   if k.startswith("serve.shard") and k.endswith(".query_s")]
    assert len(shard_hists) >= 2  # queries spread over shards
    assert sum(h[k]["count"] for k in shard_hists) == 10_000


def test_obs_report_renders_headline_signals(obs_stream):
    path, res = obs_stream
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    rep = obs_report.report(load_jsonl(path))
    assert rep["schema_version"] == SCHEMA_VERSION
    # regions/sec
    assert rep["build"]["regions"] == res.stats["regions"]
    assert rep["build"]["regions_per_s"] > 0
    # oracle solve-time p50/p99
    pt = rep["oracle"]["point_solve_s"]
    assert 0 < pt["p50"] <= pt["p99"]
    # per-shard query-latency p50/p99
    assert rep["serve"]["shards"]
    for row in rep["serve"]["shards"].values():
        assert 0 < row["p50"] <= row["p99"]
    # The text renderer covers every section without raising.
    text = obs_report.render_text(rep, [], None)
    assert "regions/s" in text and "shard" in text

    # Bench diff: a much-faster bench flags a regression; a slower one
    # (or equal) does not.
    flags = obs_report.diff_bench(rep, {"value": 1e9})
    assert any("regions/s regression" in f for f in flags)
    assert obs_report.diff_bench(
        rep, {"value": rep["build"]["regions_per_s"] * 0.5}) == []
    # Histogram p99 diff against a bench metrics block.
    fake_bench = {"metrics": {"histograms": {
        "oracle.point_solve_s": {"p99": pt["p99"] / 100}}}}
    flags = obs_report.diff_bench(rep, fake_bench)
    assert any("oracle.point_solve_s p99" in f for f in flags)


def test_obs_off_build_emits_nothing(tmp_path):
    """Default cfg: the engine runs on the shared NOOP handle and the
    oracle stays unwired."""
    from explicit_hybrid_mpc_tpu.partition.frontier import FrontierEngine
    from explicit_hybrid_mpc_tpu.partition.frontier import make_oracle

    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.5, backend="cpu", batch_simplices=32)
    oracle = make_oracle(prob, cfg)
    eng = FrontierEngine(prob, oracle, cfg)
    assert eng.obs is obs_lib.NOOP
    assert oracle.obs is obs_lib.NOOP
    eng.run()


def test_obs_off_overhead_under_one_percent():
    """ISSUE acceptance: with obs=off, flagship-build wall within 1% of
    baseline.  Measured structurally: the complete per-step set of
    disabled hooks (the only code obs=off adds to a build step) must
    cost <1% of the measured mean step time, so the end-to-end wall
    difference is bounded below measurement noise."""
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(eps_a=0.5, backend="cpu", batch_simplices=64)
    res = build_partition(prob, cfg)
    mean_step_s = res.stats["wall_s"] / max(1, res.stats["steps"])

    o = obs_lib.NOOP
    reps = 2000
    t0 = time.perf_counter()
    for _ in range(reps):
        # The hooks one frontier step executes when obs is off
        # (step-end metrics block + dispatch/wait spans + oracle batch).
        with o.span("build.dispatch"):
            pass
        with o.span("build.wait_vertices"):
            pass
        for name in ("build.steps", "build.leaves", "build.splits",
                     "build.oracle_solves"):
            o.counter(name).inc()
        for name in ("build.frontier", "build.regions",
                     "build.device_frac", "build.regions_per_s"):
            o.gauge(name).set(1.0)
        o.histogram("build.step_s").observe(0.1)
        o.histogram("build.oracle_wait_s").observe(0.1)
        o.event("build.step", step=1)
    per_step = (time.perf_counter() - t0) / reps
    assert per_step < 0.01 * mean_step_s, (
        f"obs=off hooks cost {per_step * 1e6:.1f}us/step vs mean step "
        f"{mean_step_s * 1e3:.1f}ms -- over the 1% budget")


def test_bench_metrics_block_shape():
    """bench.py writes registry.summary() as the JSON `metrics` block;
    pin its shape here (the slow bench smoke asserts it end-to-end)."""
    o = obs_lib.Obs("jsonl")
    o.counter("build.steps").inc(5)
    o.histogram("oracle.point_solve_s").observe(1e-4, n=100)
    block = o.metrics.summary()
    json.dumps(block)
    assert block["counters"]["build.steps"] == 5
    row = block["histograms"]["oracle.point_solve_s"]
    assert row["count"] == 100
    assert row["p50"] > 0 and row["p99"] >= row["p50"]
