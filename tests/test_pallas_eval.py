"""Pallas point-location kernel vs the pure-JAX evaluator (interpret mode:
the kernel is exercised on CPU; on TPU the same code compiles via Mosaic)."""

import jax.numpy as jnp
import numpy as np
import pytest

from explicit_hybrid_mpc_tpu.config import PartitionConfig
from explicit_hybrid_mpc_tpu.online import evaluator, export, pallas_eval
from explicit_hybrid_mpc_tpu.partition.frontier import build_partition
from explicit_hybrid_mpc_tpu.problems.registry import make


@pytest.fixture(scope="module")
def built():
    prob = make("double_integrator", N=3, theta_box=1.5)
    cfg = PartitionConfig(problem="double_integrator", eps_a=0.5,
                          backend="cpu", batch_simplices=64, max_depth=20)
    res = build_partition(prob, cfg)
    table = export.export_leaves(res.tree)
    return prob, res, table


def test_stage_pallas_padding(built):
    _, _, table = built
    pt = pallas_eval.stage_pallas(table)
    PV, K, Lpad = pt.bary_T.shape
    assert pt.n_leaves == table.n_leaves
    assert Lpad % 128 == 0 and Lpad >= table.n_leaves
    assert PV >= table.bary_M.shape[1] and K % 8 == 0


def test_locate_matches_reference(built, rng):
    prob, _, table = built
    pt = pallas_eval.stage_pallas(table)
    dev = evaluator.stage(table)
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub,
                         size=(200, prob.n_theta))
    ref = evaluator.evaluate(dev, jnp.asarray(thetas))
    leaf, score = pallas_eval.locate(pt, jnp.asarray(thetas), interpret=True)
    # f32 location may pick the twin leaf at a shared facet; the
    # interpolated VALUES must agree, the ids mostly do.
    same = np.asarray(leaf) == np.asarray(ref.leaf)
    assert same.mean() > 0.95
    out = pallas_eval.evaluate(pt, dev, jnp.asarray(thetas), interpret=True)
    np.testing.assert_allclose(np.asarray(out.u), np.asarray(ref.u),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(out.cost), np.asarray(ref.cost),
                               rtol=1e-4, atol=1e-4)
    assert bool(np.all(np.asarray(out.inside)))


def test_locate_outside(built):
    prob, _, table = built
    pt = pallas_eval.stage_pallas(table)
    dev = evaluator.stage(table)
    out = pallas_eval.evaluate(
        pt, dev, jnp.asarray([[10.0, 10.0]]), interpret=True)
    assert not bool(out.inside[0])


def test_locate_many_query_tiles(built, rng):
    """Queries spanning several 128-row tiles (exercises the query grid)."""
    prob, _, table = built
    pt = pallas_eval.stage_pallas(table)
    dev = evaluator.stage(table)
    thetas = rng.uniform(prob.theta_lb, prob.theta_ub,
                         size=(300, prob.n_theta))
    ref = evaluator.evaluate(dev, jnp.asarray(thetas))
    out = pallas_eval.evaluate(pt, dev, jnp.asarray(thetas), interpret=True)
    np.testing.assert_allclose(np.asarray(out.u), np.asarray(ref.u),
                               atol=1e-4)
